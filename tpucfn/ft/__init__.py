"""tpucfn.ft — the fleet fault-tolerance plane (ISSUE 4 + ISSUE 7).

Heartbeat failure detection (``heartbeat``), recovery policies with
budgets and backoff (``policy``), the gang coordinator that executes
them over the launcher's process table (``coordinator``), the
deterministic chaos harness that proves the whole loop works
(``chaos``), and the graceful-degradation protocol — preemption
notices + drain files (``preempt``), elastic N-1 shrink,
checkpoint-corruption retry, straggler eviction guard (ISSUE 7).
"""

from tpucfn.ft.chaos import (  # noqa: F401
    ChaosEngine,
    ChaosEvent,
    ChaosSpec,
    ChaosTarget,
    ControlPlaneChaosTarget,
    corrupt_latest_checkpoint,
)
from tpucfn.ft.coordinator import GangCoordinator  # noqa: F401
from tpucfn.ft.journal import (  # noqa: F401
    JOURNAL_KINDS,
    AdoptedProcess,
    CoordinatorState,
    JournalError,
    JournalWriter,
    crash_point,
    journal_path,
    replay_journal,
)
from tpucfn.ft.heartbeat import (  # noqa: F401
    FleetView,
    HeartbeatMonitor,
    HeartbeatWriter,
    HostState,
    HostVerdict,
    MonitorConfig,
    heartbeat_path,
    read_heartbeats,
)
from tpucfn.ft.policy import (  # noqa: F401
    CKPT_BLACKLIST_ENV,
    RESTORE_FAILED_RC,
    Action,
    Decision,
    Failure,
    FailureKind,
    GangRestart,
    RecoveryPolicy,
    RestartBudget,
    SoloRestart,
    StragglerGuard,
    format_ckpt_blacklist,
    parse_ckpt_blacklist,
    policy_from_name,
)
from tpucfn.ft.preempt import (  # noqa: F401
    PreemptNotice,
    consume_notice,
    drain_requested,
    request_drain,
    write_notice,
)
