#!/usr/bin/env python
"""Continuous-batching serving benchmark (tpucfn.serve).

Drives a synthetic mixed prefill/decode workload — Zipf-ish spread of
prompt lengths, Poisson-ish arrival jitter is deliberately OMITTED
(open-loop arrivals would measure the queue, not the engine; every
request is submitted up front so the scheduler stays saturated) —
through the full Server → scheduler → engine path and prints ONE JSON
line in the standard BENCH row schema:

    {"metric": "serve_tokens_per_sec", "value": N,
     "unit": "generated tokens/sec", "vs_baseline": 0.0, "detail": {...}}

``vs_baseline`` is 0.0: the reference repo was a training-only harness
with no serving number to compare against (detail.baseline_note says
so).  ``detail`` carries TTFT p50/p95, per-request latency, decode-slot
utilization, KV occupancy/preemptions, and the compile-count-relevant
knobs (buckets, max_batch), so rows are comparable across runs.

Meaningful throughput needs the real chip; on CPU this is a correctness
and scheduling-overhead bench.

Usage: python benches/serve_bench.py [--preset tiny --requests 32 ...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", choices=["tiny", "llama3-1b", "llama3-8b"],
                   default="tiny")
    p.add_argument("--requests", type=int, default=32)
    p.add_argument("--prompt-len-lo", type=int, default=8)
    p.add_argument("--prompt-len-hi", type=int, default=96)
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--cache-len", type=int, default=256)
    p.add_argument("--num-blocks", type=int, default=512)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    import jax
    import numpy as np

    from tpucfn.serve import Server
    from tpucfn.serve.engine import demo_llama_engine

    print(f"# backend={jax.default_backend()} preset={args.preset} "
          f"requests={args.requests}", file=sys.stderr)
    cfg, engine = demo_llama_engine(args.preset, seed=args.seed,
                                    max_batch=args.max_batch,
                                    cache_len=args.cache_len)
    server = Server(engine, num_blocks=args.num_blocks,
                    block_size=args.block_size)

    rs = np.random.RandomState(args.seed)
    prompts = [rs.randint(0, cfg.vocab_size,
                          rs.randint(args.prompt_len_lo,
                                     args.prompt_len_hi + 1)).tolist()
               for _ in range(args.requests)]

    # Warm the compile caches outside the timed window (one decode
    # program + every prefill bucket this workload will hit), mirroring
    # bench.py's warmup-exclusion rule for training steps.  Same server
    # (jit caches are per engine instance); metrics are reset after.
    from tpucfn.serve import ServingMetrics
    from tpucfn.serve.scheduler import prefill_bucket

    for b in sorted({prefill_bucket(len(q), args.cache_len)
                     for q in prompts}):
        server.submit([1] * min(b, args.cache_len - 2), max_new_tokens=2)
    server.run_until_idle()
    server.metrics = ServingMetrics()

    t0 = time.perf_counter()
    reqs = [server.submit(q, max_new_tokens=args.max_new) for q in prompts]
    server.run_until_idle()
    wall = time.perf_counter() - t0

    failed = [r for r in reqs if r.error is not None]
    snap = server.metrics.snapshot()
    generated = snap["generated_tokens"]
    row = {
        "metric": "serve_tokens_per_sec",
        "value": round(generated / wall, 3),
        "unit": "generated tokens/sec",
        "vs_baseline": 0.0,
        "detail": {
            "baseline_note": "reference harness was training-only; no "
                             "published serving number exists",
            "backend": jax.default_backend(),
            "preset": args.preset,
            "requests": args.requests,
            "failed": len(failed),
            "wall_s": round(wall, 3),
            "max_batch": args.max_batch,
            "cache_len": args.cache_len,
            "block_size": args.block_size,
            "num_blocks": args.num_blocks,
            "max_new": args.max_new,
            "ttft_s": snap["ttft_s"],
            "request_latency_s": snap["request_latency_s"],
            "preemptions": snap["preemptions"],
            "kv_blocks_high_water": server.kv.allocator.high_water,
            "kv_blocks_leaked": server.kv.allocator.num_used,
            # The full ServingMetrics snapshot rides on every row so a
            # perf regression carries its own latency decomposition
            # (queue depth, occupancy, token counts) instead of just the
            # headline number (ISSUE 2 satellite).
            "serving_metrics": snap,
        },
    }
    print(json.dumps(row))
    return 0 if not failed and server.kv.allocator.num_used == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
