"""ServeEngine + Server end-to-end (tpucfn.serve): greedy decode parity
against models/generate.py, LoRA-merged serving, continuous batching
across ragged prompt lengths, admission control (429/400), deadlines,
and the zero-KV-leak acceptance invariant through the real engine.

Compile-budget note: the engine's jit caches live per instance, so the
module shares ONE 8-slot engine (slots are fully overwritten by each
prefill — cross-test state cannot leak) and batches the generate()
references by prompt length."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpucfn.models.generate import generate
from tpucfn.models.llama import Llama, LlamaConfig
from tpucfn.serve import AdmissionError, DeadlineExceeded, ServeEngine, Server


@pytest.fixture(scope="module")
def tiny():
    cfg = dataclasses.replace(LlamaConfig.tiny(), max_seq=64)
    params = Llama(cfg).init(jax.random.key(2),
                             jnp.zeros((2, 8), jnp.int32))["params"]
    return cfg, params


@pytest.fixture(scope="module")
def eng8(tiny):
    cfg, params = tiny
    return ServeEngine.from_llama(cfg, params, max_batch=8, cache_len=64)


def _ref_tokens(cfg, params, prompts, max_new):
    """Greedy references for same-length prompts, batched into ONE
    generate() call (one compile per (len, max_new) shape)."""
    assert len({len(p) for p in prompts}) == 1
    out = generate(cfg, params, jnp.asarray(prompts, jnp.int32),
                   max_new_tokens=max_new)
    return [list(np.asarray(out[i, len(prompts[i]):]))
            for i in range(len(prompts))]


def test_engine_greedy_parity_single(tiny, eng8):
    cfg, params = tiny
    prompt = [5, 9, 2, 77, 31]
    tok = eng8.prefill(slot=1, prefix=prompt, bucket=16)
    toks = [tok]
    for _ in range(5):
        toks.append(eng8.decode({1: toks[-1]})[1])
    assert toks == _ref_tokens(cfg, params, [prompt], 5 + 1)[0]


def test_engine_parity_interleaved_ragged_slots(tiny, eng8):
    """Two sequences of different lengths admitted at different times
    into one decode batch: each must match its own single-sequence
    greedy reference — the per-slot cache-index correctness proof."""
    cfg, params = tiny
    rs = np.random.RandomState(3)
    p_a = rs.randint(0, cfg.vocab_size, 11).tolist()
    p_b = rs.randint(0, cfg.vocab_size, 4).tolist()

    a = [eng8.prefill(slot=0, prefix=p_a, bucket=16)]
    a.append(eng8.decode({0: a[-1]})[0])          # a decodes alone first
    b = [eng8.prefill(slot=2, prefix=p_b, bucket=16)]
    for _ in range(4):                            # then both, interleaved
        out = eng8.decode({0: a[-1], 2: b[-1]})
        a.append(out[0])
        b.append(out[2])
    assert a == _ref_tokens(cfg, params, [p_a], 6)[0]
    assert b == _ref_tokens(cfg, params, [p_b], 5)[0]


def test_engine_slot_reuse_after_retire(tiny, eng8):
    """A freed slot's stale cache must not bleed into its next tenant:
    the prefill scatter overwrites the whole row (incl. cache_index)."""
    cfg, params = tiny
    first = [eng8.prefill(slot=3, prefix=[9, 8, 7, 6, 5], bucket=16)]
    for _ in range(5):
        first.append(eng8.decode({3: first[-1]})[3])
    second = [eng8.prefill(slot=3, prefix=[1, 2, 3, 4, 5], bucket=16)]
    for _ in range(5):
        second.append(eng8.decode({3: second[-1]})[3])
    refs = _ref_tokens(cfg, params, [[9, 8, 7, 6, 5], [1, 2, 3, 4, 5]], 6)
    assert first == refs[0]
    assert second == refs[1]


def test_engine_lora_parity(tiny):
    """Serving a LoRA adapter == serving the merged weights: the engine
    merges once at construction (train/lora.py), so greedy output must
    equal generate() over lora_materialize'd params."""
    from tpucfn.train.lora import lora_init, lora_materialize

    cfg, params = tiny
    adapters = lora_init(params, jax.random.key(5), rank=2)
    # Zero-init B makes the merge a no-op; perturb to get a REAL delta.
    adapters = jax.tree.map(
        lambda a: a + 0.05 * jax.random.normal(jax.random.key(6), a.shape,
                                               a.dtype), adapters)
    merged = lora_materialize(params, adapters)
    prompt = [3, 1, 4, 1, 5]
    eng = ServeEngine.from_llama(cfg, params, max_batch=1, cache_len=64,
                                 lora_adapters=adapters)
    toks = [eng.prefill(slot=0, prefix=prompt, bucket=16)]
    for _ in range(3):
        toks.append(eng.decode({0: toks[-1]})[0])
    assert toks == _ref_tokens(cfg, merged, [prompt], 4)[0]


def test_server_e2e_concurrent_requests_zero_leaks(tiny, eng8):
    """The acceptance run: >= 8 concurrent synthetic requests of ragged
    lengths through submit -> scheduler -> engine; every completion is
    token-identical to the single-sequence greedy reference and the
    allocator's free count returns to the initial pool."""
    cfg, params = tiny
    rs = np.random.RandomState(0)
    lengths = [3, 5, 8, 10, 12]
    prompts = [rs.randint(0, cfg.vocab_size, lengths[i % 5]).tolist()
               for i in range(10)]
    server = Server(eng8, num_blocks=48, block_size=8)
    reqs = [server.submit(p, max_new_tokens=4) for p in prompts]
    server.run_until_idle()
    refs = {}
    for n in lengths:
        same = [p for p in prompts if len(p) == n]
        refs.update(zip(map(tuple, same),
                        _ref_tokens(cfg, params, same, 4)))
    for p, r in zip(prompts, reqs):
        assert r.result(timeout=0) == refs[tuple(p)]
    assert server.kv.allocator.num_free == 48
    assert server.kv.allocator.num_used == 0
    snap = server.metrics.snapshot()
    assert snap["completed"] == 10
    assert snap["generated_tokens"] == 40
    assert snap["ttft_s"]["count"] == 10
    assert snap["kv_cache_occupancy"] == 0.0


def test_server_preemption_preserves_greedy_output(tiny, eng8):
    """A block pool the admitted batch outgrows forces evictions; the
    recompute path must still produce reference-identical tokens."""
    cfg, params = tiny
    rs = np.random.RandomState(1)
    prompts = [rs.randint(0, cfg.vocab_size, 5).tolist() for _ in range(3)]
    # 5-token prompts at block_size 2 = 3 blocks each: all three admit
    # into 9 blocks with ZERO slack, but 6 new tokens each need 5 blocks
    # per sequence -> the first decode reservations must evict.
    server = Server(eng8, num_blocks=9, block_size=2)
    reqs = [server.submit(p, max_new_tokens=6) for p in prompts]
    server.run_until_idle()
    refs = _ref_tokens(cfg, params, prompts, 6)
    for r, ref in zip(reqs, refs):
        assert r.result(timeout=0) == ref
    assert server.metrics.snapshot()["preemptions"] > 0
    assert server.kv.allocator.num_free == 9


def test_server_backpressure_429(tiny, eng8):
    cfg, params = tiny
    server = Server(eng8, num_blocks=16, block_size=8, max_queued_tokens=20)
    server.submit([1, 2, 3, 4], max_new_tokens=8)  # 12 outstanding
    with pytest.raises(AdmissionError, match="queue full") as ei:
        server.submit([1, 2, 3, 4], max_new_tokens=8)  # would be 24 > 20
    assert ei.value.status == 429
    server.run_until_idle()
    # Completion returns the budget: the same submit now passes.
    server.submit([1, 2, 3, 4], max_new_tokens=8)
    server.run_until_idle()
    assert server.metrics.snapshot()["rejected"] == 1


def test_server_rejects_oversized_400(tiny, eng8):
    cfg, params = tiny
    server = Server(eng8, num_blocks=4, block_size=8)
    with pytest.raises(AdmissionError, match="capacity") as ei:
        server.submit(list(range(1, 62)), max_new_tokens=8)  # > cache_len
    assert ei.value.status == 400
    with pytest.raises(AdmissionError, match="capacity") as ei2:
        server.submit([1] * 30, max_new_tokens=4)  # 33 KV entries > 32-slot pool
    assert ei2.value.status == 400
    with pytest.raises(AdmissionError, match="max_new_tokens"):
        server.submit([1, 2], max_new_tokens=0)


def test_server_deadline_timeout(tiny, eng8):
    cfg, params = tiny
    server = Server(eng8, num_blocks=16, block_size=8)
    dead = server.submit([1, 2, 3, 4, 5], max_new_tokens=4, deadline_s=-1.0)
    live = server.submit([1, 2, 3, 4, 5], max_new_tokens=4)
    server.run_until_idle()
    with pytest.raises(DeadlineExceeded):
        dead.result(timeout=0)
    assert live.result(timeout=0) == _ref_tokens(
        cfg, params, [[1, 2, 3, 4, 5]], 4)[0]
    snap = server.metrics.snapshot()
    assert snap["expired"] == 1 and snap["completed"] == 1
    assert server.kv.allocator.num_used == 0


def test_server_threaded_mode(tiny, eng8):
    """The background-thread posture: submits from the caller thread,
    completion via the request event, clean stop."""
    cfg, params = tiny
    server = Server(eng8, num_blocks=32, block_size=8)
    server.start()
    try:
        reqs = [server.submit([7, 11, i + 1], max_new_tokens=3)
                for i in range(6)]
        outs = [r.result(timeout=120) for r in reqs]
    finally:
        server.stop()
    refs = _ref_tokens(cfg, params, [[7, 11, i + 1] for i in range(6)], 3)
    assert outs == refs
    assert server.kv.allocator.num_used == 0


def test_cli_serve_smoke(tmp_path, capsys):
    """`tpucfn serve --synthetic` end to end through the CLI surface."""
    import json

    from tpucfn.cli.main import main

    rc = main(["serve", "--preset", "tiny", "--synthetic", "3",
               "--prompt-len", "3:6", "--max-new", "4",
               "--max-batch", "2", "--cache-len", "64",
               "--num-blocks", "16", "--block-size", "8"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    snap = json.loads(out[-1])
    assert snap["completed"] == 3
    assert snap["generated_tokens"] == 12


# ---- ISSUE 3: prefix caching + batched prefill through the real engine --

def test_engine_copy_prefix_then_suffix_prefill_parity(tiny, eng8):
    """copy_prefix + a start-offset suffix prefill must equal one full
    prefill: the copied KV plus recomputed suffix is the same cache a
    scratch prefill builds."""
    cfg, params = tiny
    rs = np.random.RandomState(7)
    prompt = rs.randint(0, cfg.vocab_size, 12).tolist()
    # Backer: slot 5 prefills the full prompt.
    a = [eng8.prefill(slot=5, prefix=prompt, bucket=16)]
    # Hit: slot 6 copies the first 8 tokens, prefills only the last 4.
    eng8.copy_prefix(5, 6, 8)
    b = [eng8.prefill(slot=6, prefix=prompt[8:], bucket=16, start=8)]
    for _ in range(4):
        out = eng8.decode({5: a[-1], 6: b[-1]})
        a.append(out[5])
        b.append(out[6])
    ref = _ref_tokens(cfg, params, [prompt], 5)[0]
    assert a == ref
    assert b == ref


def test_engine_prefill_batch_matches_singles(tiny, eng8):
    """One vmapped width-K call == K single calls: per-lane buckets,
    starts, and sampling positions are lane-local."""
    cfg, params = tiny
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, cfg.vocab_size, n).tolist()
               for n in (5, 9, 13)]
    toks = eng8.prefill_batch(
        [(0, prompts[0], 0, 0.0), (1, prompts[1], 0, 0.0),
         (2, prompts[2], 0, 0.0)], bucket=16)
    outs = {s: [toks[s]] for s in (0, 1, 2)}
    for _ in range(3):
        nxt = eng8.decode({s: outs[s][-1] for s in outs})
        for s in outs:
            outs[s].append(nxt[s])
    for slot, p in zip((0, 1, 2), prompts):
        assert outs[slot] == _ref_tokens(cfg, params, [p], 4)[0]


def test_server_prefix_cache_parity_and_hits(tiny, eng8):
    """The satellite pin: greedy outputs with the prefix cache ON are
    token-identical to models/generate.py, and the shared system prompt
    actually hits (fewer prefilled tokens than total prompt tokens)."""
    cfg, params = tiny
    rs = np.random.RandomState(13)
    system = rs.randint(0, cfg.vocab_size, 16).tolist()
    prompts = [system + rs.randint(0, cfg.vocab_size, 3 + i % 4).tolist()
               for i in range(12)]
    server = Server(eng8, num_blocks=48, block_size=8)
    reqs = [server.submit(p, max_new_tokens=4) for p in prompts]
    server.run_until_idle()
    by_len = {}
    for p in prompts:
        by_len.setdefault(len(p), []).append(p)
    refs = {}
    for same in by_len.values():
        refs.update(zip(map(tuple, same), _ref_tokens(cfg, params, same, 4)))
    for p, r in zip(prompts, reqs):
        assert r.result(timeout=0) == refs[tuple(p)]
    snap = server.metrics.snapshot()
    assert snap["prefix_hit_requests"] > 0
    assert snap["prefix_hit_tokens"] > 0
    assert snap["prefilled_tokens"] < snap["prompt_tokens"]
    assert snap["prefill_calls"] < len(prompts)  # batching collapsed calls
    assert snap["prefill_batch_size"]["count"] == snap["prefill_calls"]
    assert server.kv.allocator.num_used == 0


def test_server_acceptance_mix_zero_leaks(tiny, eng8):
    """ISSUE 3 acceptance: an end-to-end run mixing shared-prefix hits,
    misses, preemptions (tight pool), and deadline expiries ends with
    num_used == 0."""
    cfg, params = tiny
    rs = np.random.RandomState(17)
    system = rs.randint(0, cfg.vocab_size, 8).tolist()
    # Tight pool: 12 blocks x 4 = 48 token slots for up to 8 concurrent
    # sequences -> decode reservations must preempt.
    server = Server(eng8, num_blocks=12, block_size=4)
    reqs = []
    for i in range(10):
        shared = i % 2 == 0
        p = (system if shared else
             rs.randint(0, cfg.vocab_size, 8).tolist()) \
            + rs.randint(0, cfg.vocab_size, 1 + i % 3).tolist()
        reqs.append(server.submit(
            p, max_new_tokens=4,
            deadline_s=(-1.0 if i in (3, 7) else None)))
    server.run_until_idle()
    snap = server.metrics.snapshot()
    assert snap["expired"] == 2
    assert snap["completed"] == 8
    assert snap["preemptions"] > 0
    assert snap["prefix_hit_requests"] > 0
    for r in reqs:
        if r.error is None:
            p = r.prompt
            assert r.result(timeout=0) == _ref_tokens(cfg, params, [p], 4)[0]
    assert server.kv.allocator.num_used == 0
    assert server.kv.allocator.num_free == 12


def test_engine_compile_counts_stay_bucketed(tiny):
    """The compile-budget contract: a workload spanning two prefill
    buckets with prefix hits and batched prefills compiles exactly
    len(buckets) prefill programs + 1 decode + 1 copy_prefix."""
    cfg, params = tiny
    eng = ServeEngine.from_llama(cfg, params, max_batch=4, cache_len=64,
                                 prefill_width=3)
    rs = np.random.RandomState(19)
    system = rs.randint(0, cfg.vocab_size, 8).tolist()
    server = Server(eng, num_blocks=32, block_size=4)
    prompts = [system + rs.randint(0, cfg.vocab_size, 2 + i % 3).tolist()
               for i in range(8)]
    prompts.append(rs.randint(0, cfg.vocab_size, 20).tolist())  # bucket 32
    reqs = [server.submit(p, max_new_tokens=3) for p in prompts]
    server.run_until_idle()
    assert all(r.error is None for r in reqs)
    snap = server.metrics.snapshot()
    assert snap["prefix_hit_requests"] > 0   # copy_prefix really ran
    counts = eng.compile_counts()
    assert counts == {"prefill": 2, "decode": 1, "copy_prefix": 1}, counts
    assert server.kv.allocator.num_used == 0
