"""Serving frontend: thread-safe request queue, admission control, and
the loop that binds queue → scheduler → engine.

Admission control is LAYERED, and each layer rejects for a different
reason with a different status:

* 429 (:class:`AdmissionError`, ``status=429``) — backpressure: the sum
  of OUTSTANDING tokens (prompt + budgeted new tokens of every request
  submitted but not yet completed) would exceed ``max_queued_tokens``.
  Outstanding, not merely queued: a frontend that only counts its own
  queue believes itself empty while the scheduler drowns.
* 400 (``status=400``) — the request can never run on this engine
  (empty prompt, prompt + max_new over the cache capacity, or more KV
  blocks than the whole pool): rejecting at submit beats starving at
  the head of the queue.
* 503-equivalent deadline expiry — a request whose deadline passes
  while queued or running is completed with :class:`DeadlineExceeded`;
  capacity goes back to live traffic instead of computing answers
  nobody is waiting for.

Metrics ride on ``tpucfn.obs`` primitives registered in a
``MetricRegistry`` (Counter/Gauge/Summary/Histogram): TTFT, generated
tokens/sec, queue depth, KV-cache occupancy, preemptions, rejections —
``ServingMetrics.snapshot()`` is the one dict the CLI, the bench, and
tests all read, and the registry is the scrape surface the per-host
``/metrics`` endpoint exposes (tpucfn/obs/server.py).  A ``Tracer``
(tpucfn/obs/trace.py) records the request lifecycle as spans —
request_submitted → queue_wait → prefill → decode_round* →
request_done (plus preemption events) — so TTFT decomposes into
queue-wait vs prefill vs scheduling per request, reconstructable from
the trace JSONL alone (``tpucfn obs`` renders the breakdown table).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from tpucfn.obs.metrics import Summary
from tpucfn.obs.registry import MetricRegistry
from tpucfn.obs.trace import Tracer
from tpucfn.serve.engine import ServeEngine
from tpucfn.serve.kvcache import KVCacheManager
from tpucfn.serve.scheduler import (
    ContinuousBatchingScheduler,
    PrefillWork,
    Sequence,
    SequenceState,
)


# Canonical terminal vocabulary of ServeRequest.status (ISSUE 10): the
# router, the benches, and tests branch on these strings, so they live
# in ONE tuple the `vocab-drift` rule of `tpucfn check` enforces — a
# literal outside this set anywhere in the package is a finding.
# "pending" is the non-terminal initial state; everything else is
# settled exactly when `done` fires (see ServeRequest).
REQUEST_STATUSES = ("pending", "ok", "expired", "replica_failed",
                    "retried", "rejected", "cancelled")


class AdmissionError(RuntimeError):
    """Request refused at submit time.  ``status`` follows HTTP
    semantics: 429 = retry later (backpressure), 400 = never valid on
    this engine, 503 = this replica is unavailable (draining or failed)
    — retry ELSEWHERE, which is exactly what the replica router does."""

    def __init__(self, msg: str, *, status: int = 429):
        super().__init__(msg)
        self.status = status


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it finished."""


class ReplicaFailed(RuntimeError):
    """5xx-equivalent: the replica (engine or serve loop) died under the
    request.  Structurally distinct from :class:`DeadlineExceeded` on
    purpose (ISSUE 9): a router retries a replica failure on a healthy
    replica with the remaining deadline budget, while an expired
    deadline is terminal — nobody is waiting anymore."""


class Requeued(ReplicaFailed):
    """The replica handed this request back without finishing it
    (drain / queue eviction); the router resubmits it elsewhere.  The
    replica-level handle's terminal ``status`` is ``"retried"``."""


class Cancelled(RuntimeError):
    """The request was cancelled (a hedge that lost the race)."""


class ServeRequest:
    """Caller-facing handle: block on :meth:`result` (or poll
    :attr:`done`).  Timing fields are filled by the serve loop —
    ``t_first_token - t_submit`` is the TTFT the metrics record.

    ``status`` is the terminal outcome, settled exactly when ``done``
    sets (ISSUE 9 satellite): ``"ok"`` / ``"expired"`` (deadline) /
    ``"replica_failed"`` (engine or replica death) / ``"retried"`` (the
    replica handed it back for resubmission elsewhere) / ``"rejected"``
    (admission) / ``"cancelled"`` (hedge loser) — so routers and tests
    branch on structure instead of string-matching error messages.
    ``on_done`` is an optional single-shot callback invoked after the
    terminal state is visible (the router's completion hook)."""

    def __init__(self, req_id: int, prompt: list[int], max_new_tokens: int,
                 temperature: float, deadline: float | None):
        self.req_id = req_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.deadline = deadline
        self.tokens: list[int] | None = None
        self.error: BaseException | None = None
        self.status = "pending"
        self.on_done = None
        self.t_submit = time.monotonic()
        self.t_first_token: float | None = None
        self.t_done: float | None = None
        self.done = threading.Event()

    def result(self, timeout: float | None = None) -> list[int]:
        """Generated tokens (prompt excluded); raises the request's
        error (DeadlineExceeded, ValueError...) if it failed."""
        if not self.done.wait(timeout):
            raise TimeoutError(f"request {self.req_id} still in flight")
        if self.error is not None:
            raise self.error
        assert self.tokens is not None
        return self.tokens


class ServingMetrics:
    """The serving dashboard in one object, owned by a
    :class:`~tpucfn.obs.registry.MetricRegistry` so ``GET /metrics``
    exposes every serving series in Prometheus text format alongside
    whatever else the process registered (training metrics, supervisor
    counters).  Default is a private registry (test/bench isolation);
    the CLI passes its role-labelled registry so the per-host obs
    endpoint covers serving too.

    ``request_latency_s`` is kept as an (unregistered) Summary for the
    exact-percentile ``snapshot()`` dict; the registered cross-host-
    aggregatable form is the ``serve_request_latency_seconds``
    Histogram — both observe every completion.
    """

    def __init__(self, registry: MetricRegistry | None = None):
        r = self.registry = (registry if registry is not None
                             else MetricRegistry())
        self.ttft_s = r.summary(
            "serve_ttft_seconds", "time to first generated token")
        self.request_latency_s = Summary("request_latency_s")
        self.request_latency_hist = r.histogram(
            "serve_request_latency_seconds",
            "end-to-end request latency (submit to done)")
        self.generated_tokens = r.counter(
            "serve_generated_tokens_total", "tokens sampled (rate = tokens/sec)")
        self.prompt_tokens = r.counter(
            "serve_prompt_tokens_total", "prompt tokens accepted at submit")
        self.completed = r.counter(
            "serve_completed_requests_total", "requests finished successfully")
        self.rejected = r.counter(
            "serve_rejected_requests_total", "requests refused (429/400)")
        self.expired = r.counter(
            "serve_expired_requests_total", "requests past their deadline")
        self.replica_failed = r.counter(
            "serve_replica_failed_requests_total",
            "requests completed with a replica/engine failure "
            "(5xx-equivalent; counted separately from deadline expiry)")
        self.preemptions = r.counter(
            "serve_preemptions_total", "KV-pressure evictions")
        self.prefill_calls = r.counter(
            "serve_prefill_calls_total",
            "jitted prefill programs dispatched (batched: one per batch)")
        self.prefilled_tokens = r.counter(
            "serve_prefilled_tokens_total",
            "real (unpadded) tokens run through prefill — suffix only on "
            "prefix-cache hits")
        self.prefix_hit_requests = r.counter(
            "serve_prefix_hit_requests_total",
            "prefills that reused cached prefix blocks")
        self.prefix_hit_tokens = r.counter(
            "serve_prefix_hit_tokens_total",
            "prompt tokens served by copy_prefix instead of prefill")
        self.prefill_batch_size = r.summary(
            "serve_prefill_batch_size", "sequences per prefill call")
        self.slo_shed = r.counter(
            "serve_slo_shed_total",
            "requests shed at submit because the SLO burn rate was "
            "sustained above 1 (--slo-shed)")
        # Decode-round economics (ISSUE 14): tokens / slot_steps is the
        # per-slot tokens-per-target-step — exactly 1.0 for plain
        # decode, acceptance-driven above 1 with --spec-draft.
        self.decode_rounds = r.counter(
            "serve_decode_rounds_total",
            "decode rounds dispatched (one target verify or decode "
            "program each)")
        self.decode_slot_steps = r.counter(
            "serve_decode_slot_steps_total",
            "active slot-steps across decode rounds (one per running "
            "slot per round)")
        self.decode_tokens = r.counter(
            "serve_decode_tokens_total",
            "tokens emitted by decode rounds (excludes prefill's first "
            "tokens)")
        self.spec_rounds = r.counter(
            "serve_spec_rounds_total",
            "decode rounds that ran propose-verify (speculation on and "
            "proposing; off/probe-idle rounds excluded)")
        self.spec_proposed = r.counter(
            "serve_spec_proposed_total",
            "draft tokens proposed to greedy slots")
        self.spec_accepted = r.counter(
            "serve_spec_accepted_total",
            "proposed draft tokens the target verified and emitted")
        self.queue_depth = r.gauge(
            "serve_queue_depth", "requests waiting (frontend + scheduler)")
        self.running = r.gauge(
            "serve_running_sequences", "sequences in decode slots")
        self.cache_occupancy = r.gauge(
            "serve_kv_cache_occupancy", "fraction of KV blocks in use")
        self._t0 = time.monotonic()

    def snapshot(self) -> dict:
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        return {
            "elapsed_s": round(elapsed, 3),
            "completed": self.completed.value,
            "rejected": self.rejected.value,
            "expired": self.expired.value,
            "replica_failed": self.replica_failed.value,
            "preemptions": self.preemptions.value,
            "prompt_tokens": self.prompt_tokens.value,
            "generated_tokens": self.generated_tokens.value,
            "tokens_per_sec": self.generated_tokens.value / elapsed,
            "ttft_s": self.ttft_s.snapshot(),
            "request_latency_s": self.request_latency_s.snapshot(),
            "prefill_calls": self.prefill_calls.value,
            "prefilled_tokens": self.prefilled_tokens.value,
            "prefix_hit_requests": self.prefix_hit_requests.value,
            "prefix_hit_tokens": self.prefix_hit_tokens.value,
            "prefill_batch_size": self.prefill_batch_size.snapshot(),
            "decode_rounds": self.decode_rounds.value,
            "decode_slot_steps": self.decode_slot_steps.value,
            "decode_tokens": self.decode_tokens.value,
            "tokens_per_target_step": (
                self.decode_tokens.value / self.decode_slot_steps.value
                if self.decode_slot_steps.value else None),
            "spec_rounds": self.spec_rounds.value,
            "spec_proposed": self.spec_proposed.value,
            "spec_accepted": self.spec_accepted.value,
            "spec_acceptance_rate": (
                self.spec_accepted.value / self.spec_proposed.value
                if self.spec_proposed.value else None),
            "slo_shed": self.slo_shed.value,
            "queue_depth": self.queue_depth.value,
            "running_sequences": self.running.value,
            "kv_cache_occupancy": self.cache_occupancy.value,
        }


class SLOTracker:
    """TTFT/TPOT service-level objectives with a rolling-window burn
    rate, exported as ``serve_slo_*`` (ISSUE 5).

    Two latency objectives — time-to-first-token and time-per-output-
    token — each with a target and one shared ``objective`` (the
    fraction of requests that must meet it, e.g. 0.99).  Every finished
    request is scored against both; the **burn rate** is the classic
    SRE ratio

        (violation fraction in the rolling window) / (1 − objective)

    — 1.0 means the error budget is being consumed exactly as fast as
    it refills; >1 sustained means the SLO will be missed.  An expired
    (deadline-exceeded) request counts as a violation of both
    objectives: the caller got no usable answer, whatever the partial
    timings say.

    ``clock`` is injectable so burn-rate windows are pinned by
    fake-clock tests.
    """

    def __init__(self, registry: MetricRegistry | None = None, *,
                 ttft_slo_s: float = 0.5, tpot_slo_s: float = 0.05,
                 objective: float = 0.99, window_s: float = 60.0,
                 clock=time.monotonic):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        r = registry if registry is not None else MetricRegistry()
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.objective = objective
        self.window_s = window_s
        self.clock = clock
        self._lock = threading.Lock()
        self._window: deque[tuple[float, bool, bool]] = deque()
        # Running violation counts for the CURRENT window, maintained
        # incrementally by record()/_evict(): the burn gauges and the
        # shed check run per scrape / per submit, and re-summing a
        # 60s-of-traffic deque under the lock each time would make
        # admission cost grow linearly with throughput — worst exactly
        # under the overload shedding exists for.
        self._win_ttft_bad = 0
        self._win_tpot_bad = 0
        self.requests = r.counter(
            "serve_slo_requests_total", "requests scored against the SLOs")
        self.ttft_violations = r.counter(
            "serve_slo_ttft_violations_total",
            "requests whose TTFT missed the target")
        self.tpot_violations = r.counter(
            "serve_slo_tpot_violations_total",
            "requests whose per-output-token time missed the target")
        # computed_gauge rebinds the read callback on re-registration,
        # so a process that rebuilds a Server against the shared
        # default_registry() gets the LIVE tracker's window backing the
        # series — the counters stay shared and cumulative either way.
        self.ttft_burn = r.computed_gauge(
            "serve_slo_ttft_burn_rate", self._ttft_burn_now,
            "TTFT violation rate in the rolling window / error budget")
        self.tpot_burn = r.computed_gauge(
            "serve_slo_tpot_burn_rate", self._tpot_burn_now,
            "TPOT violation rate in the rolling window / error budget")
        self.window_requests = r.computed_gauge(
            "serve_slo_window_requests", lambda: self._window_stats()[0],
            "requests in the rolling window")
        # Targets as gauges so a scrape is self-describing: a burn rate
        # without its objective is not actionable.
        r.gauge("serve_slo_ttft_target_s",
                "TTFT objective target").set(ttft_slo_s)
        r.gauge("serve_slo_tpot_target_s",
                "TPOT objective target").set(tpot_slo_s)
        r.gauge("serve_slo_objective",
                "fraction of requests that must meet each target").set(
            objective)

    def record(self, ttft_s: float | None, tpot_s: float | None) -> None:
        """Score one finished request; ``None`` means the quantity was
        never achieved (no first token before expiry) and is a
        violation by definition."""
        ttft_ok = ttft_s is not None and ttft_s <= self.ttft_slo_s
        tpot_ok = tpot_s is not None and tpot_s <= self.tpot_slo_s
        now = self.clock()
        self.requests.add()
        if not ttft_ok:
            self.ttft_violations.add()
        if not tpot_ok:
            self.tpot_violations.add()
        with self._lock:
            self._window.append((now, ttft_ok, tpot_ok))
            if not ttft_ok:
                self._win_ttft_bad += 1
            if not tpot_ok:
                self._win_tpot_bad += 1
            self._evict(now)

    def _evict(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._window and self._window[0][0] < cutoff:
            _, ttft_ok, tpot_ok = self._window.popleft()
            if not ttft_ok:
                self._win_ttft_bad -= 1
            if not tpot_ok:
                self._win_tpot_bad -= 1

    def _window_stats(self) -> tuple[int, int, int]:
        """``(requests, ttft_violations, tpot_violations)`` in the
        rolling window AS OF NOW — evicts first, so idle time decays the
        window between requests (the computed gauges read this).
        O(evictions), not O(window): the counts are maintained
        incrementally by record()/_evict()."""
        with self._lock:
            self._evict(self.clock())
            return len(self._window), self._win_ttft_bad, \
                self._win_tpot_bad

    def _burn(self, bad: int, n: int) -> float:
        """Burn rate = window violation rate / error budget.  The ONE
        definition behind both the computed gauges and snapshot() — the
        /metrics series and serve_bench's BENCH row must never
        disagree."""
        return bad / n / (1.0 - self.objective) if n else 0.0

    def should_shed(self, min_window: int = 8) -> bool:
        """Shed-load verdict for the frontend's admission path (ISSUE 6
        satellite): True when EITHER burn rate is above 1 over the
        rolling window — the error budget is being consumed faster than
        it refills, so rejecting now beats breaching the SLO later.
        ``min_window`` scored requests are required before any verdict:
        one bad request over an empty window is noise, and the window
        itself is what makes the burn "sustained"."""
        n, ttft_bad, tpot_bad = self._window_stats()
        if n < min_window:
            return False
        return (self._burn(ttft_bad, n) > 1.0
                or self._burn(tpot_bad, n) > 1.0)

    def _ttft_burn_now(self) -> float:
        n, ttft_bad, _ = self._window_stats()
        return self._burn(ttft_bad, n)

    def _tpot_burn_now(self) -> float:
        n, _, tpot_bad = self._window_stats()
        return self._burn(tpot_bad, n)

    def snapshot(self) -> dict:
        """The ``serve_slo_*`` block serve_bench's BENCH row carries."""
        n, ttft_bad, tpot_bad = self._window_stats()
        return {
            "ttft_target_s": self.ttft_slo_s,
            "tpot_target_s": self.tpot_slo_s,
            "objective": self.objective,
            "window_s": self.window_s,
            "requests": self.requests.value,
            "window_requests": n,
            "ttft": {"violations_total": self.ttft_violations.value,
                     "window_violations": ttft_bad,
                     "burn_rate": self._burn(ttft_bad, n)},
            "tpot": {"violations_total": self.tpot_violations.value,
                     "window_violations": tpot_bad,
                     "burn_rate": self._burn(tpot_bad, n)},
        }


class Server:
    """One engine + one scheduler + the frontend queue.

    Two driving modes sharing one step function: :meth:`run_until_idle`
    (synchronous — CLI, benches, deterministic tests) and
    :meth:`start`/:meth:`stop` (a background thread that sleeps on a
    condition until work arrives — the long-lived serving posture).
    """

    def __init__(self, engine: ServeEngine, *, num_blocks: int = 256,
                 block_size: int = 16, max_queued_tokens: int = 1 << 16,
                 eos_id: int | None = None,
                 registry: MetricRegistry | None = None,
                 tracer: Tracer | None = None,
                 prefix_cache: bool = True,
                 max_prefill_batch: int | None = None,
                 ttft_slo_s: float = 0.5, tpot_slo_s: float = 0.05,
                 slo_objective: float = 0.99, slo_window_s: float = 60.0,
                 slo_shed: bool = False, shed_min_window: int = 8,
                 shed_probe_every: int = 10,
                 flight=None, heartbeat=None,
                 clock=time.monotonic):
        """``slo_shed`` arms SLO-aware early shedding: submit() rejects
        with 429 while the rolling-window burn rate is sustained above 1
        (``SLOTracker.should_shed``), shedding load BEFORE the SLO is
        breached instead of after; sheds are counted in
        ``serve_slo_shed_total``.  Every ``shed_probe_every``-th request
        is admitted anyway as a PROBE: shed requests are never scored,
        so without fresh scores the window would freeze and a transient
        blip would 429 everything for the full window — probes that
        complete healthily decay the burn and end the shed episode as
        soon as the engine actually recovers.  ``flight`` is a
        :class:`~tpucfn.obs.flight.FlightRecorder` receiving queue
        depth / batch occupancy / scheduler-decision samples (ISSUE 6).
        ``heartbeat`` is a :class:`~tpucfn.ft.heartbeat.HeartbeatWriter`
        beaten FROM the serve loop itself (ISSUE 9): a frozen or wedged
        loop stops beating, which is what lets the ft classifier (and
        the replica router's health check) tell a stuck replica from an
        idle one — a daemon-thread writer would keep beating through a
        freeze.  ``clock`` (monotonic) is injectable for drain/freeze
        timing tests."""
        self.engine = engine
        # Both ISSUE-3 fast paths are duck-typed off the engine so fakes
        # (and any decode-protocol engine without the batched entry
        # points) degrade to the classic one-prefill-per-call behavior.
        # Prefix hits need BOTH entry points: the hit executes as
        # copy_prefix + a start-offset prefill_batch call, so an engine
        # with only one of them must run fully cache-off.
        self._can_copy_prefix = (hasattr(engine, "copy_prefix")
                                 and hasattr(engine, "prefill_batch"))
        k = (max_prefill_batch if max_prefill_batch is not None
             else getattr(engine, "prefill_width", 1))
        if not hasattr(engine, "prefill_batch"):
            k = 1
        k = max(1, min(k, getattr(engine, "prefill_width", k)))
        self.kv = KVCacheManager(
            num_blocks, block_size,
            prefix_cache=prefix_cache and self._can_copy_prefix)
        self.flight = flight
        self.scheduler = ContinuousBatchingScheduler(
            self.kv, max_batch=engine.max_batch,
            cache_len=engine.cache_len, eos_id=eos_id,
            max_prefill_batch=k, flight=flight)
        self.metrics = ServingMetrics(registry)
        if getattr(engine, "spec_enabled", False):
            # Live acceptance-rate observability (ISSUE 14): the same
            # windowed rate the k-controller acts on, scrapeable — a
            # burn-rate dashboard next to a falling acceptance rate is
            # the whole speculative-decode story in two series.
            ctl = engine.controller
            self.metrics.registry.computed_gauge(
                "serve_spec_acceptance_rate", ctl.acceptance_rate,
                "windowed draft-token acceptance rate (the k-controller's "
                "shrink/grow signal)")
            self.metrics.registry.computed_gauge(
                "serve_spec_k", lambda: float(ctl.k),
                "current proposal depth k (0 = speculation off, probing)")
        self.slo = SLOTracker(self.metrics.registry, ttft_slo_s=ttft_slo_s,
                              tpot_slo_s=tpot_slo_s,
                              objective=slo_objective,
                              window_s=slo_window_s)
        self.slo_shed_enabled = slo_shed
        self.shed_min_window = shed_min_window
        self.shed_probe_every = max(2, shed_probe_every)
        self._shed_seen = 0  # requests arriving during a shed episode
        self.tracer = tracer if tracer is not None else Tracer(None)
        self.max_queued_tokens = max_queued_tokens
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._incoming: deque[ServeRequest] = deque()
        self._outstanding_tokens = 0
        self._by_seq: dict[int, ServeRequest] = {}
        self._next_id = 0
        self._thread: threading.Thread | None = None
        self._stopping = False
        # Resilience state (ISSUE 9): drain/failure/chaos, all consumed
        # at step boundaries ON the serve thread so no second thread
        # ever mutates the scheduler.
        self.heartbeat = heartbeat
        self.clock = clock
        self._last_beat = float("-inf")
        self._draining = False
        self._drain_deadline: float | None = None
        self._failed: BaseException | None = None
        self._injected_failure: BaseException | None = None
        self._frozen_until = 0.0
        self._slow_until = 0.0
        self._slow_delay = 0.0
        self._cancel_req: set[int] = set()
        self._evict_waiting = False

    @property
    def failed(self) -> BaseException | None:
        """The exception that killed this replica's serve loop, or None
        while it is healthy — the router's liveness probe."""
        return self._failed

    # -- submit path (any thread) ------------------------------------------
    def submit(self, prompt: list[int], *, max_new_tokens: int,
               temperature: float = 0.0,
               deadline_s: float | None = None,
               on_done=None) -> ServeRequest:
        """``on_done(req)`` — optional single-shot completion callback,
        attached BEFORE the request is queued so a fast serve thread can
        never complete the request in the submit/attach gap (the race
        the router's retry path would otherwise lose)."""
        with self._lock:
            if self._failed is not None:
                self.metrics.rejected.add()
                raise AdmissionError(
                    f"replica failed: {self._failed}", status=503)
            if self._draining:
                self.metrics.rejected.add()
                raise AdmissionError(
                    "replica draining: admission closed", status=503)
        budget = len(prompt) + max_new_tokens
        if not prompt or max_new_tokens < 1:
            self.metrics.rejected.add()
            raise AdmissionError(
                f"empty prompt or max_new_tokens {max_new_tokens} < 1",
                status=400)
        if budget > self.engine.cache_len \
                or not self.kv.fits_at_all(budget - 1):
            self.metrics.rejected.add()
            raise AdmissionError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} exceeds "
                f"engine capacity (cache_len {self.engine.cache_len}, "
                f"{self.kv.allocator.num_blocks} KV blocks)", status=400)
        if self.slo_shed_enabled and self.slo.should_shed(
                self.shed_min_window):
            # SLO-aware early shedding (ISSUE 6 satellite): the burn
            # rate says the error budget is being consumed faster than
            # it refills — reject NOW so in-flight traffic recovers,
            # instead of admitting work that will breach the SLO.
            # Every Nth arrival is admitted as a probe (see __init__):
            # its completion score is the recovery signal that ends the
            # episode.
            with self._lock:  # submit() is any-thread: cadence must not race
                self._shed_seen += 1
                probe = self._shed_seen % self.shed_probe_every == 0
            if not probe:
                self.metrics.rejected.add()
                self.metrics.slo_shed.add()
                raise AdmissionError(
                    "shedding load: SLO burn rate sustained above 1 over "
                    f"the rolling {self.slo.window_s:g}s window (back off "
                    "and retry)", status=429)
        elif self.slo_shed_enabled:  # healthy again: reset the cadence
            with self._lock:
                self._shed_seen = 0
        with self._lock:
            # Re-checked HERE, in the same lock acquisition that
            # enqueues: the gate at the top is a fast path, but fail()/
            # drain() can land between it and this block, and a request
            # appended after _fail_all drained the queue would never be
            # processed — its on_done would never fire and the caller
            # would wait forever.
            if self._failed is not None:
                self.metrics.rejected.add()
                raise AdmissionError(
                    f"replica failed: {self._failed}", status=503)
            if self._draining:
                self.metrics.rejected.add()
                raise AdmissionError(
                    "replica draining: admission closed", status=503)
            if self._outstanding_tokens + budget > self.max_queued_tokens:
                self.metrics.rejected.add()
                raise AdmissionError(
                    f"queue full: {self._outstanding_tokens} outstanding "
                    f"tokens + {budget} > {self.max_queued_tokens} "
                    "(back off and retry)", status=429)
            self._outstanding_tokens += budget
            req = ServeRequest(
                self._next_id, list(prompt), max_new_tokens, temperature,
                None if deadline_s is None
                else time.monotonic() + deadline_s)
            req.on_done = on_done
            self._next_id += 1
            self._incoming.append(req)
            self._work.notify()
        self.metrics.prompt_tokens.add(len(prompt))
        self.metrics.queue_depth.set(len(self._incoming)
                                     + self.scheduler.num_waiting)
        if self.tracer.enabled:
            self.tracer.event("request_submitted", trace_id=req.req_id,
                              prompt_tokens=len(prompt),
                              max_new=max_new_tokens)
        return req

    # -- completion --------------------------------------------------------
    def _complete(self, req: ServeRequest, *, tokens=None, error=None,
                  partial_generated: int = 0):
        """``partial_generated``: tokens produced before a failure
        (deadline expiry mid-decode) — the trace must not report an
        expired request that generated 30 tokens as zero-output work."""
        req.t_done = time.monotonic()
        req.tokens, req.error = tokens, error
        with self._lock:
            self._outstanding_tokens -= len(req.prompt) + req.max_new_tokens
        ttft = (None if req.t_first_token is None
                else req.t_first_token - req.t_submit)
        if error is None:
            req.status = "ok"
            self.metrics.completed.add()
            self.metrics.request_latency_s.observe(req.t_done - req.t_submit)
            self.metrics.request_latency_hist.observe(req.t_done - req.t_submit)
            # TPOT over the decode tail (first token excluded — that one
            # is the TTFT's business); single-token answers have no tail
            # and score a perfect 0.
            tail = len(tokens) - 1 if tokens else 0
            tpot = ((req.t_done - req.t_first_token) / tail if tail > 0
                    else 0.0)
            self.slo.record(ttft, tpot)
        elif isinstance(error, DeadlineExceeded):
            req.status = "expired"
            self.metrics.expired.add()
            # an expired request violates both objectives by definition —
            # the caller got no usable answer (None scores as violation;
            # results aren't streamed, so a mid-flight first token never
            # reached anyone).
            self.slo.record(None, None)
        elif isinstance(error, Requeued):
            # Handed back for resubmission elsewhere (drain): not a
            # failure of this replica and not scored — the retry's
            # eventual completion is what the fleet experienced.
            req.status = "retried"
        elif isinstance(error, ReplicaFailed):
            # Counted separately from expiry on purpose (ISSUE 9): a
            # dead replica is an availability event the router retries;
            # an expired deadline is a latency event nobody can retry.
            req.status = "replica_failed"
            self.metrics.replica_failed.add()
        elif isinstance(error, Cancelled):
            req.status = "cancelled"
        else:
            req.status = "rejected"
            self.metrics.rejected.add()
        if self.tracer.enabled:
            self.tracer.event(
                "request_done", trace_id=req.req_id, outcome=req.status,
                latency_s=req.t_done - req.t_submit,
                ttft_s=(None if req.t_first_token is None
                        else req.t_first_token - req.t_submit),
                generated=len(tokens) if tokens is not None
                else partial_generated)
        req.done.set()
        cb, req.on_done = req.on_done, None
        if cb is not None:
            try:
                cb(req)
            except Exception:  # noqa: BLE001 — a router-callback bug
                pass  # must not take the serve loop down with it

    # -- the step function (one scheduler decision + one engine call) ------
    def _ingest(self) -> None:
        with self._lock:
            batch = list(self._incoming)
            self._incoming.clear()
        for req in batch:
            seq = Sequence(
                seq_id=req.req_id, prompt=req.prompt,
                max_new_tokens=req.max_new_tokens,
                temperature=req.temperature, deadline=req.deadline,
                arrival=req.t_submit)
            self._by_seq[req.req_id] = req
            try:
                self.scheduler.add(seq)
            except ValueError as e:
                # add() re-checks feasibility because Server config and
                # direct-scheduler users can disagree; surface as 400.
                self._by_seq.pop(req.req_id)
                self._complete(req, error=AdmissionError(str(e), status=400))

    def step(self) -> bool:
        """One iteration: ingest, expire deadlines, run one prefill or
        one decode round, record results.  Returns False when idle.

        Raises :class:`ReplicaFailed` when a failure was injected
        (:meth:`fail`) — the driving loops route that through
        :meth:`_fail_all` so every in-flight request completes with a
        structured error instead of hanging forever."""
        self._maybe_beat()
        self._pause_if_frozen()
        with self._lock:
            inj, self._injected_failure = self._injected_failure, None
            slow = (self._slow_delay
                    if self.clock() < self._slow_until else 0.0)
        if inj is not None:
            raise inj
        if slow > 0.0:
            time.sleep(slow)
        if (self._drain_deadline is not None
                and self.clock() > self._drain_deadline):
            # Bounded drain: the grace window closed with work still in
            # flight — fail the leftovers loudly (the router requeues
            # them; a bare `tpucfn serve` reports them) instead of
            # decoding past the preemption that motivated the drain.
            self._fail_all(ReplicaFailed("drain grace expired with work "
                                         "in flight"))
            return False
        self._process_cancels()
        self._ingest()
        preempt0 = self.kv.evictions
        for seq in self.scheduler.expire():
            req = self._by_seq.pop(seq.seq_id)
            self._complete(req, error=DeadlineExceeded(
                f"deadline passed after {len(seq.generated)}"
                f"/{seq.max_new_tokens} tokens"),
                partial_generated=len(seq.generated))
        work = self.scheduler.next_work()
        if work is None:
            self._refresh_gauges()
            return False
        if isinstance(work, PrefillWork):
            # The prefill's sampled token is ALWAYS new output: for a
            # fresh sequence it's token 1; for a preempted one, the
            # recomputed prefix already contains everything previously
            # emitted, so the last position's logits predict the next
            # unseen token.
            items = work.items
            t_pf0 = time.monotonic()
            if hasattr(self.engine, "prefill_batch") and (
                    len(items) > 1 or items[0].cached_len):
                # Prefix hits plant the shared run first; then ONE
                # bucketed program prefills every item's suffix.
                for it in items:
                    # src == slot is the zero-copy hit: the sequence was
                    # landed on the retired slot that already holds its
                    # prefix, so there is nothing to move.
                    if it.cached_len and it.src_slot != it.slot:
                        self.engine.copy_prefix(it.src_slot, it.slot,
                                                it.cached_len)
                toks = self.engine.prefill_batch(
                    [(it.slot, it.seq.prefix[it.cached_len:], it.cached_len,
                      it.seq.temperature) for it in items], work.bucket)
            else:
                it = items[0]
                toks = {it.slot: self.engine.prefill(
                    it.slot, it.seq.prefix, work.bucket,
                    it.seq.temperature)}
            t_pf1 = time.monotonic()
            self.metrics.prefill_calls.add()
            self.metrics.prefill_batch_size.observe(len(items))
            if self.flight is not None:
                self.flight.record(
                    "sched", work="prefill", batch=len(items),
                    bucket=work.bucket, dur_s=round(t_pf1 - t_pf0, 6),
                    cached=sum(1 for it in items if it.cached_len))
            for it in items:
                req = self._by_seq[it.seq.seq_id]
                first = req.t_first_token is None
                self.metrics.prefilled_tokens.add(
                    len(it.seq.prefix) - it.cached_len)
                if it.cached_len:
                    self.metrics.prefix_hit_requests.add()
                    self.metrics.prefix_hit_tokens.add(it.cached_len)
                if self.tracer.enabled:
                    if first:
                        # The span whose start nobody observed from the
                        # serve loop: submit happened on the caller's
                        # thread, so it is recorded retroactively from
                        # t_submit.  queue_wait + prefill sums to the
                        # measured TTFT by construction.
                        self.tracer.record("queue_wait", start=req.t_submit,
                                           end=t_pf0, trace_id=req.req_id)
                    self.tracer.record("prefill", start=t_pf0, end=t_pf1,
                                       trace_id=req.req_id, slot=it.slot,
                                       bucket=work.bucket,
                                       prefix_len=len(it.seq.prefix),
                                       cached_len=it.cached_len,
                                       batch=len(items),
                                       resumed=not first)
                if first:  # preempted reruns keep the first
                    req.t_first_token = t_pf1
                    self.metrics.ttft_s.observe(
                        req.t_first_token - req.t_submit)
                self.metrics.generated_tokens.add()
                self._finish(
                    self.scheduler.record_prefill(it.slot, toks[it.slot]))
        elif getattr(self.engine, "spec_enabled", False):
            # Propose-verify round (ISSUE 14): the draft proposes k
            # tokens per slot, ONE target dispatch verifies k+1
            # positions, and the scheduler records the accepted run —
            # truncating on EOS/max_new or a dry pool, after which
            # commit_round repairs both caches to what actually landed.
            t_dec0 = time.monotonic()
            outs, st = self.engine.run_round(work.slots)
            work.proposed = outs
            recorded: dict[int, int] = {}
            emitted = 0
            for slot, toks in outs.items():
                seq = work.slots[slot]
                fin, n = self.scheduler.record_decode_tokens(slot, toks)
                recorded[slot] = len(seq.prompt) + len(seq.generated) - 1
                emitted += n
                self.metrics.generated_tokens.add(n)
                self.metrics.decode_tokens.add(n)
                self._finish(fin)
            self.engine.commit_round(recorded)
            t_dec1 = time.monotonic()
            self.metrics.decode_rounds.add()
            self.metrics.decode_slot_steps.add(len(work.slots))
            if st.mode == "spec":
                self.metrics.spec_rounds.add()
                self.metrics.spec_proposed.add(st.proposed)
                self.metrics.spec_accepted.add(st.accepted)
            if self.flight is not None:
                self.flight.record(
                    "sched", work="decode", batch=len(work.slots),
                    dur_s=round(t_dec1 - t_dec0, 6), spec=st.mode,
                    emitted=emitted, proposed=st.proposed,
                    accepted=st.accepted)
            if self.tracer.enabled:
                seqs = sorted(s.seq_id for s in work.slots.values())
                self.tracer.record("decode_round", start=t_dec0,
                                   end=t_dec1, batch=len(work.slots),
                                   seqs=seqs)
                if st.mode == "spec":
                    # The round's TTFT/TPOT attribution splits into its
                    # draft and verify halves; the request breakdown
                    # (obs.aggregate) sums both per request, so the SLO
                    # burn math sees where the per-token time went.
                    self.tracer.record(
                        "spec_propose", start=st.t_propose0,
                        end=st.t_propose1, batch=len(work.slots),
                        seqs=seqs, width=st.width, proposed=st.proposed,
                        resyncs=st.resyncs)
                    self.tracer.record(
                        "spec_verify", start=st.t_verify0,
                        end=st.t_verify1, batch=len(work.slots),
                        seqs=seqs, width=st.width, accepted=st.accepted,
                        emitted=emitted)
        else:
            t_dec0 = time.monotonic()
            out = self.engine.decode(
                {slot: seq.last_token for slot, seq in work.slots.items()})
            if self.flight is not None:
                self.flight.record(
                    "sched", work="decode", batch=len(work.slots),
                    dur_s=round(time.monotonic() - t_dec0, 6))
            if self.tracer.enabled:
                self.tracer.record(
                    "decode_round", start=t_dec0, end=time.monotonic(),
                    batch=len(work.slots),
                    seqs=sorted(s.seq_id for s in work.slots.values()))
            self.metrics.decode_rounds.add()
            self.metrics.decode_slot_steps.add(len(work.slots))
            for slot, tok in out.items():
                self.metrics.generated_tokens.add()
                self.metrics.decode_tokens.add()
                self._finish(self.scheduler.record_decode(slot, tok))
        evicted = self.kv.evictions - preempt0
        if evicted and self.tracer.enabled:
            self.tracer.event("preemption", count=evicted)
        self.metrics.preemptions.add(evicted)
        self._refresh_gauges()
        return True

    def _finish(self, seq) -> None:
        if seq is not None and seq.state is SequenceState.FINISHED:
            req = self._by_seq.pop(seq.seq_id)
            self._complete(req, tokens=list(seq.generated))

    def _refresh_gauges(self) -> None:
        queue = len(self._incoming) + self.scheduler.num_waiting
        running = self.scheduler.num_running
        occupancy = self.kv.occupancy()
        self.metrics.queue_depth.set(queue)
        self.metrics.running.set(running)
        self.metrics.cache_occupancy.set(occupancy)
        if self.flight is not None:
            # One ring sample per serve iteration: queue depth + batch
            # occupancy are exactly the "what was the engine doing in
            # its final seconds" series a postmortem reads (ISSUE 6).
            self.flight.record("serve", queue=queue, running=running,
                               occupancy=round(occupancy, 4))

    # -- resilience plumbing (ISSUE 9) -------------------------------------
    def _maybe_beat(self) -> None:
        """One heartbeat per writer interval, FROM the serve loop (see
        ``heartbeat`` in ``__init__``) — a frozen loop stops beating."""
        hb = self.heartbeat
        if hb is None:
            return
        now = self.clock()
        if now - self._last_beat >= hb.interval_s:
            self._last_beat = now
            hb.beat()

    def _pause_if_frozen(self) -> None:
        """Chaos ``freeze_replica``: block the serve loop (no steps, no
        beats) until the freeze lapses — or a kill/stop arrives, which
        must still win against a frozen replica."""
        while True:
            with self._lock:
                if self._injected_failure is not None or self._stopping:
                    return
                remaining = self._frozen_until - self.clock()
            if remaining <= 0:
                return
            time.sleep(min(0.005, remaining))

    def _process_cancels(self) -> None:
        """Apply cancel/evict requests at the step boundary — the serve
        thread is the only scheduler mutator, so cross-thread ``cancel``
        /``evict_queued`` calls just leave a note here."""
        with self._lock:
            ids, self._cancel_req = self._cancel_req, set()
            evict, self._evict_waiting = self._evict_waiting, False
        for rid in sorted(ids):
            self._cancel_one(rid)
        if evict:
            self._evict_waiting_now()

    def _cancel_one(self, rid: int) -> None:
        with self._lock:
            queued = next((r for r in self._incoming if r.req_id == rid),
                          None)
            if queued is not None:
                self._incoming.remove(queued)
        if queued is not None:
            self._complete(queued, error=Cancelled("cancelled before start"))
            return
        if rid in self._by_seq:
            seq = self.scheduler.cancel(rid)
            if seq is not None:
                req = self._by_seq.pop(rid)
                self._complete(req, error=Cancelled(
                    f"cancelled after {len(seq.generated)}"
                    f"/{seq.max_new_tokens} tokens"),
                    partial_generated=len(seq.generated))

    def _evict_waiting_now(self) -> None:
        """Hand every not-yet-started sequence back to the caller with
        ``Requeued`` (terminal status ``retried``): a draining replica's
        queue belongs on a healthy replica, not behind this one's last
        decodes.  Running sequences are untouched — they get the drain
        grace window."""
        with self._lock:
            batch = list(self._incoming)
            self._incoming.clear()
        for seq in list(self.scheduler.waiting):
            self.scheduler.cancel(seq.seq_id)
            req = self._by_seq.pop(seq.seq_id, None)
            if req is not None:
                batch.append(req)
        for req in batch:
            self._complete(req, error=Requeued(
                "replica draining: requeued to another replica"))

    def cancel(self, req_id: int) -> None:
        """Request cancellation (hedge-loser path): takes effect at the
        next step boundary on the serve thread; the handle completes
        with :class:`Cancelled` (status ``"cancelled"``).  Unknown or
        already-finished ids are a no-op."""
        with self._lock:
            self._cancel_req.add(req_id)
            self._work.notify()

    def evict_queued(self) -> None:
        """Hand all queued-not-started work back (each completes with
        :class:`Requeued`, status ``"retried"``) at the next step
        boundary — the router's drain calls this before waiting out the
        in-flight grace."""
        with self._lock:
            self._evict_waiting = True
            self._work.notify()

    def fail(self, exc: BaseException | None = None) -> None:
        """Kill this replica (chaos ``kill_replica``, or the router
        acting on a DEAD health verdict): every in-flight and queued
        request completes with :class:`ReplicaFailed`, admission closes
        (503), and the serve thread exits.  Idempotent."""
        exc = exc if exc is not None else ReplicaFailed("replica killed")
        if not isinstance(exc, ReplicaFailed):
            exc = ReplicaFailed(repr(exc))
        with self._lock:
            if self._failed is not None:
                return
            if self._thread is not None:
                # the serve thread consumes the injection at its next
                # step boundary (and the freeze-pause loop checks it, so
                # a kill still beats a frozen replica)
                self._injected_failure = exc
                self._work.notify()
                return
        self._fail_all(exc)

    def _fail_all(self, exc: ReplicaFailed) -> None:
        """Terminal: mark the replica failed and complete everything in
        flight with the failure.  Scheduler state is abandoned, not
        repaired — a failed replica never runs another step."""
        with self._lock:
            if self._failed is not None:
                return
            self._failed = exc
            batch = list(self._incoming)
            self._incoming.clear()
        abandon = getattr(self.engine, "abandon_round", None)
        if abandon is not None:
            # A spec round killed between propose-verify and commit
            # must not wedge the engine pair's next incarnation
            # (ISSUE 14); the relaunch re-prefills every slot anyway.
            abandon()
        reqs = batch + [self._by_seq.pop(k) for k in list(self._by_seq)]
        self.scheduler.waiting.clear()
        self.scheduler.running.clear()
        for req in reqs:
            self._complete(req, error=exc)

    def freeze(self, duration_s: float | None = None) -> None:
        """Chaos ``freeze_replica``: the serve loop (and its heartbeat)
        stalls for ``duration_s`` (None = until :meth:`unfreeze`)."""
        with self._lock:
            self._frozen_until = (float("inf") if duration_s is None
                                  else self.clock() + duration_s)

    def unfreeze(self) -> None:
        with self._lock:
            self._frozen_until = 0.0

    def slow(self, delay_s: float, duration_s: float | None = None) -> None:
        """Chaos ``slow_replica``: every step pays an extra ``delay_s``
        for ``duration_s`` (None = until ``slow(0)``)."""
        with self._lock:
            self._slow_delay = float(delay_s)
            self._slow_until = (float("inf") if duration_s is None
                                else self.clock() + duration_s)

    def outstanding(self) -> int:
        """Requests submitted but not yet terminal."""
        with self._lock:
            return len(self._incoming) + len(self._by_seq)

    def wait_stopped(self, timeout: float | None = None) -> bool:
        """Wait for the serve thread to exit (it ends on its own after
        :meth:`fail` or :meth:`stop`); True when no thread is running.
        The router joins a killed incarnation here before relaunching —
        two serve loops driving one engine race its donated cache
        buffers."""
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def drain(self, grace_s: float = 30.0, *, wait: bool = True) -> bool:
        """Graceful shutdown (ISSUE 9 satellite): close admission (503)
        and run the work already accepted to completion, bounded by
        ``grace_s`` — a preempted serve host finishes its decodes
        instead of abandoning them the way ``stop()`` did.  Work still
        unfinished when the grace closes completes with
        :class:`ReplicaFailed` (the router requeues it).

        ``wait=False`` only arms the drain (admission off + deadline)
        and returns — the signal-handler form: the already-running loop
        enforces the bound.  Returns True when everything finished
        inside the grace."""
        if not wait:
            # Signal-handler form: the handler may have interrupted a
            # frame ON THIS THREAD that already holds self._lock (the
            # serve loop's step(), or submit()), and self._lock is not
            # reentrant — acquiring it here would deadlock the process
            # at the exact moment it is trying to die gracefully.
            # Plain attribute stores are GIL-atomic and the running
            # loop reads them at its next step boundary.
            self._draining = True
            if self._drain_deadline is None:
                self._drain_deadline = self.clock() + grace_s
            return len(self._incoming) + len(self._by_seq) == 0
        with self._lock:
            self._draining = True
            if self._drain_deadline is None:
                self._drain_deadline = self.clock() + grace_s
            deadline = self._drain_deadline
            self._work.notify()
        clean = True
        if self._thread is None:
            while True:
                if self.clock() > deadline:
                    if self.outstanding():
                        self._fail_all(ReplicaFailed(
                            "drain grace expired with work in flight"))
                        clean = False
                    break
                try:
                    if not self.step():
                        break
                except ReplicaFailed as e:
                    self._fail_all(e)
                    clean = False
                    break
                except Exception as e:  # noqa: BLE001 — engine died mid-drain
                    self._fail_all(ReplicaFailed(f"serve loop failed: {e!r}"))
                    clean = False
                    break
        else:
            while self.outstanding() and self.clock() <= deadline:
                time.sleep(0.005)
            thread = self._thread
            self.stop(timeout=max(grace_s, 1.0))
            if thread is not None and thread.is_alive():
                # wedged (e.g. frozen) — leave the leftovers to fail()
                # /the router; completing them here would race the loop
                return False
            if self.outstanding():
                self._fail_all(ReplicaFailed(
                    "drain grace expired with work in flight"))
                clean = False
        # _failed catches every force-fail path, including the serve
        # thread running step()'s own drain-deadline branch just before
        # exiting (the threaded join then sees outstanding()==0 and a
        # dead thread — which is NOT a clean drain).
        return clean and self.outstanding() == 0 and self._failed is None

    # -- driving modes -----------------------------------------------------
    def run_until_idle(self) -> None:
        while True:
            try:
                if not self.step():
                    return
            except ReplicaFailed as e:
                self._fail_all(e)
                return
            except Exception as e:  # noqa: BLE001 — engine/scheduler died
                wrapped = ReplicaFailed(f"serve loop failed: {e!r}")
                self._fail_all(wrapped)
                raise wrapped from e

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._stopping = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpucfn-serve")
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        with self._lock:
            self._stopping = True
            self._work.notify()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _run(self) -> None:
        while True:
            try:
                progressed = self.step()
            except ReplicaFailed as e:
                self._fail_all(e)
                return
            except Exception as e:  # noqa: BLE001 — engine/scheduler died
                # The old behavior silently killed this thread and left
                # every in-flight request hanging forever; a replica
                # failure must complete them with a structured error the
                # router can retry (ISSUE 9).
                self._fail_all(ReplicaFailed(f"serve loop failed: {e!r}"))
                return
            if not progressed:
                with self._lock:
                    if self._stopping:
                        return
                    if not self._incoming and not self.scheduler.has_work():
                        # Truly idle: no queued or running sequences means
                        # no pending deadlines either (_by_seq drains with
                        # the scheduler), so sleep until submit()/stop()
                        # notifies — with a heartbeat attached, wake once
                        # per interval so liveness keeps flowing while
                        # idle (idle is not dead).
                        self._work.wait(
                            None if self.heartbeat is None
                            else self.heartbeat.interval_s)
