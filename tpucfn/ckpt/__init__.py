from tpucfn.ckpt.manager import (  # noqa: F401
    CheckpointManager,
    rewrap_prng_keys,
    split_prng_keys,
    split_prng_keys_abstract,
)
