"""CLI surface of the ft plane: `tpucfn launch --ft` runs the gang
coordinator with heartbeat fan-out, and `tpucfn ft status` renders the
fleet view + recovery metrics from the supervisor's on-disk snapshot."""

import json
import sys

from tpucfn.cli.main import main


def _cli(tmp_path, *argv):
    return main(["--state-dir", str(tmp_path / "state"), *argv])


# Beats once via stdlib (no tpucfn import: fast interpreter startup),
# fails the first gang attempt, succeeds the second.
WORKER = """
import json, os, pathlib, sys, time
d = os.environ['TPUCFN_FT_DIR']; h = int(os.environ['TPUCFN_HOST_ID'])
os.makedirs(d, exist_ok=True)
with open(f'{d}/hb-host{h:03d}.jsonl', 'a') as f:
    f.write(json.dumps({'host_id': h, 'pid': os.getpid(), 'step': 5,
                        't': time.time(), 'seq': 1}) + '\\n')
storage = pathlib.Path(os.environ['TPUCFN_STORAGE'])
storage.mkdir(parents=True, exist_ok=True)
flag = storage / f'ran_once_{h}'  # per-host: no cross-host flag races
if flag.exists():
    sys.exit(0)
flag.write_text('x')
sys.exit(3 if h == 0 else 0)
"""


def test_launch_ft_then_status_json(tmp_path, capsys):
    assert _cli(tmp_path, "create-stack", "--name", "drill",
                "--accelerator", "v4-16") == 0
    rc = _cli(tmp_path, "launch", "--name", "drill", "--ft",
              "--ft-restart-budget", "1", "--ft-backoff", "0",
              "--ft-heartbeat-interval", "0.2", "--",
              sys.executable, "-c", WORKER)
    assert rc == 0
    capsys.readouterr()

    assert _cli(tmp_path, "ft", "status", "--name", "drill", "--json") == 0
    report = json.loads(capsys.readouterr().out)
    # acceptance: ft_* metrics visible in `tpucfn ft status --json`
    m = report["metrics"]
    assert m["ft_restarts_total"] == 1
    assert m["ft_failures_detected_total"] >= 1
    assert m["ft_mttr_seconds"]["count"] == 1
    assert report["policy"] == "gang"
    assert report["budget"] == {"max_restarts": 1, "used": 1}
    assert {h["host"] for h in report["hosts"]} == {0, 1}
    kinds = [e["kind"] for e in report["events"]]
    assert "detect" in kinds and "recovered" in kinds and "done" in kinds

    # human rendering mentions the fleet + restart counters
    assert _cli(tmp_path, "ft", "status", "--name", "drill") == 0
    out = capsys.readouterr().out
    assert "ft fleet view" in out and "restarts=1" in out


def test_ft_status_without_target_errors(tmp_path, capsys):
    assert _cli(tmp_path, "ft", "status") == 2
    assert "ft status needs" in capsys.readouterr().err


def test_ft_status_missing_dir_errors(tmp_path, capsys):
    assert _cli(tmp_path, "ft", "status", "--dir",
                str(tmp_path / "nope")) == 1
    assert "no ft dir" in capsys.readouterr().err


def test_launch_without_ft_has_no_ft_dir(tmp_path, capsys):
    assert _cli(tmp_path, "create-stack", "--name", "plain",
                "--accelerator", "cpu-8") == 0
    code = ("import os, sys; "
            "sys.exit(1 if 'TPUCFN_FT_DIR' in os.environ else 0)")
    assert _cli(tmp_path, "launch", "--name", "plain", "--",
                sys.executable, "-c", code) == 0


def test_supervise_requires_ft(tmp_path, capsys):
    """--supervise without --ft must refuse loudly: the journal and
    adoption live under the ft dir (ISSUE 12)."""
    assert _cli(tmp_path, "create-stack", "--name", "sup",
                "--accelerator", "v4-16") == 0
    rc = _cli(tmp_path, "launch", "--name", "sup", "--supervise", "--",
              sys.executable, "-c", "pass")
    assert rc == 2
    assert "--supervise needs --ft" in capsys.readouterr().err


def test_launch_ft_journals_and_no_adopt_flag(tmp_path, capsys):
    """A --ft launch writes the run journal; a second run over the same
    ft dir with --no-adopt starts fresh (the first run's journal is
    rotated aside, not adopted)."""
    from tpucfn.ft import replay_journal
    from tpucfn.ft.journal import journal_path

    assert _cli(tmp_path, "create-stack", "--name", "jrn",
                "--accelerator", "v4-16") == 0
    assert _cli(tmp_path, "launch", "--name", "jrn", "--ft", "--",
                sys.executable, "-c", "pass") == 0
    ft_dir = tmp_path / "state" / "clusters" / "jrn" / "ft"
    st, _, _ = replay_journal(journal_path(ft_dir))
    assert st.started and st.done_rc == 0
    capsys.readouterr()
    assert _cli(tmp_path, "launch", "--name", "jrn", "--ft", "--no-adopt",
                "--", sys.executable, "-c", "pass") == 0
    assert (ft_dir / "journal" / "journal-prev.jsonl").is_file()
    st2, _, _ = replay_journal(journal_path(ft_dir))
    assert st2.done_rc == 0 and st2.adoptions == 0


def test_adopt_and_no_adopt_are_mutually_exclusive(tmp_path, capsys):
    """--adopt --no-adopt on one command line is a usage error, not a
    silent resolution in --adopt's favor (an alias that already carried
    --adopt must not adopt a stale fleet when the operator appends
    --no-adopt asking for a fresh launch)."""
    import pytest

    with pytest.raises(SystemExit) as e:
        _cli(tmp_path, "launch", "--name", "x", "--ft", "--adopt",
             "--no-adopt", "--", sys.executable, "-c", "pass")
    assert e.value.code == 2
    assert "not allowed with" in capsys.readouterr().err
