"""Deterministic chaos harness (tpucfn.ft.chaos): spec parsing, seeded
replay, firing semantics against a recording target, the
FakeControlPlane target, and checkpoint corruption."""

import json
import random

import pytest

from tpucfn.ft import (
    ChaosEngine,
    ChaosEvent,
    ChaosSpec,
    ChaosTarget,
    ControlPlaneChaosTarget,
    corrupt_latest_checkpoint,
)
from tpucfn.provision.control_plane import FakeControlPlane
from tpucfn.spec import ClusterSpec


class Recorder(ChaosTarget):
    def __init__(self, n=4):
        self.n = n
        self.calls = []

    def num_hosts(self):
        return self.n

    def kill_host(self, host_id):
        self.calls.append(("kill", host_id))

    def hang_host(self, host_id):
        self.calls.append(("hang", host_id))

    def resume_host(self, host_id):
        self.calls.append(("resume", host_id))

    def delay_heartbeats(self, host_id, duration_s):
        self.calls.append(("delay", host_id, duration_s))

    def preempt_notice(self, host_id, lead_s):
        self.calls.append(("preempt", host_id, lead_s))

    def lose_host(self, host_id):
        self.calls.append(("lose", host_id))

    def corrupt_latest_checkpoint(self, rng, step=None):
        self.calls.append(("corrupt", step))


def test_spec_json_roundtrip_and_validation():
    spec = ChaosSpec(events=(
        ChaosEvent(action="kill", at_s=1.5, host=2),
        ChaosEvent(action="hang", at_step=100, duration_s=3.0),
        ChaosEvent(action="corrupt_ckpt", at_s=9.0),
    ), seed=42)
    again = ChaosSpec.from_json(json.dumps(spec.to_json()))
    assert again == spec
    with pytest.raises(ValueError):
        ChaosEvent(action="explode", at_s=1.0)
    with pytest.raises(ValueError):
        ChaosEvent(action="kill")  # no trigger at all


def test_engine_fires_on_elapsed_and_step_triggers():
    t = Recorder()
    spec = ChaosSpec(events=(
        ChaosEvent(action="kill", at_s=2.0, host=1),
        ChaosEvent(action="delay_heartbeats", at_step=50, host=0,
                   duration_s=4.0),
    ))
    eng = ChaosEngine(spec, t)
    assert eng.tick(0.5, fleet_step=10) == [] and not t.calls
    eng.tick(2.1, fleet_step=20)
    assert t.calls == [("kill", 1)]
    assert not eng.done()
    eng.tick(2.2, fleet_step=50)  # step trigger independent of time
    assert t.calls[-1] == ("delay", 0, 4.0)
    assert eng.done()
    assert [f.event.action for f in eng.fired] == ["kill",
                                                   "delay_heartbeats"]


def test_engine_hang_schedules_resume_after_duration():
    t = Recorder()
    eng = ChaosEngine(ChaosSpec(events=(
        ChaosEvent(action="hang", at_s=1.0, host=2, duration_s=2.0),)), t)
    eng.tick(1.0)
    assert t.calls == [("hang", 2)] and not eng.done()
    eng.tick(2.5)
    assert t.calls == [("hang", 2)]  # not yet
    eng.tick(3.0)
    assert t.calls == [("hang", 2), ("resume", 2)]
    assert eng.done()


def test_unpinned_victim_comes_from_seeded_rng():
    spec = ChaosSpec(events=tuple(
        ChaosEvent(action="kill", at_s=float(i)) for i in range(6)), seed=9)
    t1, t2 = Recorder(4), Recorder(4)
    ChaosEngine(ChaosSpec.from_json(spec.to_json()), t1).tick(100.0)
    ChaosEngine(spec, t2).tick(100.0)
    assert t1.calls == t2.calls  # same seed → same victims
    ref = random.Random(9)
    assert [c[1] for c in t1.calls] == [ref.randrange(4) for _ in range(6)]


def test_control_plane_target_kills_fake_host():
    cp = FakeControlPlane(steps_to_provision=1)
    cp.create(ClusterSpec(name="chaos", accelerator="v4-16"))
    cp.tick()
    target = ControlPlaneChaosTarget(cp, "chaos")
    assert target.num_hosts() == 2
    eng = ChaosEngine(ChaosSpec(events=(
        ChaosEvent(action="kill", at_s=0.5, host=1),)), target)
    eng.tick(1.0)
    rec = cp.describe("chaos")
    assert not rec.hosts[1].healthy and rec.hosts[0].healthy
    assert ("chaos", "host1-died") in cp.events


def test_corrupt_latest_checkpoint_targets_latest_step(tmp_path):
    d = tmp_path / "ckpt"
    for step in (5, 10):
        sub = d / str(step) / "default"
        sub.mkdir(parents=True)
        (sub / "data.bin").write_bytes(b"A" * 4096)
        (d / str(step) / "_METADATA").write_text("{}")
    victim = corrupt_latest_checkpoint(d, random.Random(0))
    assert victim is not None and victim.parts[-3] == "10"
    blob = victim.read_bytes()
    assert blob != b"A" * 4096 and len(blob) == 256  # garbage + truncate
    # step 5 untouched
    assert (d / "5" / "default" / "data.bin").read_bytes() == b"A" * 4096
    # replayed RNG produces identical garbage (determinism)
    for p in d.rglob("data.bin"):
        p.write_bytes(b"A" * 4096)
    assert corrupt_latest_checkpoint(d, random.Random(0)).read_bytes() == blob


def test_corrupt_latest_checkpoint_empty_dirs(tmp_path):
    assert corrupt_latest_checkpoint(tmp_path / "nope", random.Random(0)) is None
    (tmp_path / "ckpt").mkdir()
    assert corrupt_latest_checkpoint(tmp_path / "ckpt", random.Random(0)) is None


# -- graceful-degradation ops (ISSUE 7) ------------------------------------


def test_engine_fires_preempt_notice_and_lose_host():
    """The two new ops: preempt_notice carries its lead seconds via
    duration_s; lose_host fires like a kill but through the dedicated
    target hook (kill AND refuse re-acquire).  Both replay seeded."""
    spec = ChaosSpec(events=(
        ChaosEvent(action="preempt_notice", at_s=1.0, host=2,
                   duration_s=30.0),
        ChaosEvent(action="lose_host", at_step=50, host=1),
    ), seed=3)
    again = ChaosSpec.from_json(json.dumps(spec.to_json()))
    assert again == spec  # roundtrip incl. the new actions
    t = Recorder()
    eng = ChaosEngine(spec, t)
    eng.tick(1.5, fleet_step=10)
    assert t.calls == [("preempt", 2, 30.0)]
    eng.tick(1.6, fleet_step=50)
    assert t.calls[-1] == ("lose", 1)
    assert eng.done()
    # unpinned victims draw from the seeded rng, same as kill
    t1, t2 = Recorder(4), Recorder(4)
    unpinned = ChaosSpec(events=(
        ChaosEvent(action="lose_host", at_s=0.5),), seed=11)
    ChaosEngine(unpinned, t1).tick(1.0)
    ChaosEngine(ChaosSpec.from_json(unpinned.to_json()), t2).tick(1.0)
    assert t1.calls == t2.calls


def test_corrupt_ckpt_targets_a_specific_step(tmp_path):
    """``corrupt_ckpt`` with a step field hits exactly that finalized
    step (the deterministic drill needs to corrupt the checkpoint the
    retry path will blacklist), and a missing target is a no-op."""
    d = tmp_path / "ckpt"
    for step in (5, 10):
        sub = d / str(step) / "default"
        sub.mkdir(parents=True)
        (sub / "data.bin").write_bytes(b"A" * 4096)
    victim = corrupt_latest_checkpoint(d, random.Random(0), step=5)
    assert victim is not None and victim.parts[-3] == "5"
    assert (d / "10" / "default" / "data.bin").read_bytes() == b"A" * 4096
    assert corrupt_latest_checkpoint(d, random.Random(0), step=99) is None
    # engine path: the event's step reaches the target
    t = Recorder()
    eng = ChaosEngine(ChaosSpec(events=(
        ChaosEvent(action="corrupt_ckpt", at_s=1.0, step=20),)), t)
    eng.tick(1.0)
    assert t.calls == [("corrupt", 20)]


def test_serve_ops_roundtrip_and_dispatch():
    """ISSUE 9: the serve-tier ops (kill/freeze/slow replica) ride the
    same spec/engine machinery — `host` addresses the replica index on
    serve targets, `delay_s` carries slow_replica's injected latency."""

    class ServeRecorder(ChaosTarget):
        def __init__(self, n=2):
            self.n = n
            self.calls = []

        def num_hosts(self):
            return self.n

        def kill_replica(self, replica):
            self.calls.append(("kill_replica", replica))

        def freeze_replica(self, replica, duration_s):
            self.calls.append(("freeze_replica", replica, duration_s))

        def slow_replica(self, replica, delay_s, duration_s):
            self.calls.append(("slow_replica", replica, delay_s,
                               duration_s))

    spec = ChaosSpec(events=(
        ChaosEvent(action="kill_replica", at_s=1.0, host=0),
        ChaosEvent(action="freeze_replica", at_s=2.0, host=1,
                   duration_s=5.0),
        ChaosEvent(action="slow_replica", at_s=3.0, host=0,
                   delay_s=0.05, duration_s=4.0),
    ), seed=7)
    again = ChaosSpec.from_json(json.dumps(spec.to_json()))
    assert again == spec  # roundtrip incl. delay_s
    t = ServeRecorder()
    eng = ChaosEngine(spec, t)
    eng.tick(3.5)
    assert t.calls == [("kill_replica", 0),
                       ("freeze_replica", 1, 5.0),
                       ("slow_replica", 0, 0.05, 4.0)]
    assert eng.done()
    # an unpinned victim still draws from the seeded rng
    t1, t2 = ServeRecorder(), ServeRecorder()
    unpinned = ChaosSpec(events=(
        ChaosEvent(action="kill_replica", at_s=0.5),), seed=13)
    ChaosEngine(unpinned, t1).tick(1.0)
    ChaosEngine(ChaosSpec.from_json(unpinned.to_json()), t2).tick(1.0)
    assert t1.calls == t2.calls


def test_serve_ops_default_to_not_implemented():
    base = ChaosTarget()
    for call in (lambda: base.kill_replica(0),
                 lambda: base.freeze_replica(0, 1.0),
                 lambda: base.slow_replica(0, 0.1, 1.0)):
        with pytest.raises(NotImplementedError):
            call()


def test_kill_coordinator_op_roundtrip_dispatch_and_fire_hook():
    """ISSUE 12: kill_coordinator rides the same spec machinery, never
    draws an RNG victim (hostless — later unpinned events must resolve
    the same victims with or without it), and the on_fire hook runs
    BEFORE dispatch (the write-ahead contract: a kill_coordinator must
    be journaled before it kills the journaler)."""

    class CoordRecorder(ChaosTarget):
        def __init__(self):
            self.calls = []

        def num_hosts(self):
            return 2

        def kill_host(self, host_id):
            self.calls.append(("kill", host_id))

        def kill_coordinator(self):
            self.calls.append(("kill_coordinator",))

    spec = ChaosSpec(events=(
        ChaosEvent(action="kill_coordinator", at_s=1.0),
        ChaosEvent(action="kill", at_s=2.0),
    ), seed=3)
    assert ChaosSpec.from_json(json.dumps(spec.to_json())) == spec
    fired_hook = []
    t = CoordRecorder()
    eng = ChaosEngine(
        spec, t, on_fire=lambda i, ev, host: fired_hook.append(
            (i, ev.action, host, list(t.calls))))
    eng.tick(2.5)
    assert t.calls[0] == ("kill_coordinator",)
    # the hook saw each firing BEFORE its action ran
    assert fired_hook[0][:3] == (0, "kill_coordinator", None)
    assert fired_hook[0][3] == []  # no calls yet at hook time
    assert fired_hook[1][1] == "kill"
    # the unpinned kill drew the same victim a no-kill_coordinator spec
    # would (hostless actions never consume the seeded RNG)
    t2 = CoordRecorder()
    ChaosEngine(ChaosSpec(events=(ChaosEvent(action="kill", at_s=2.0),),
                          seed=3), t2).tick(2.5)
    assert t.calls[1] == t2.calls[0]
    with pytest.raises(NotImplementedError):
        ChaosTarget().kill_coordinator()


def test_skip_fired_drops_already_fired_events():
    """An adopting coordinator replays chaos_fired journal records into
    skip_fired: those spec indices must not re-fire (a kill_coordinator
    would otherwise kill every incarnation forever)."""

    class R(ChaosTarget):
        def __init__(self):
            self.calls = []

        def num_hosts(self):
            return 2

        def kill_host(self, host_id):
            self.calls.append(("kill", host_id))

        def kill_coordinator(self):
            self.calls.append(("kill_coordinator",))

    spec = ChaosSpec(events=(
        ChaosEvent(action="kill_coordinator", at_s=0.5),
        ChaosEvent(action="kill", at_s=1.0, host=1),
    ))
    t = R()
    eng = ChaosEngine(spec, t)
    eng.skip_fired({0})  # index 0 fired in a previous incarnation
    eng.tick(2.0)
    assert t.calls == [("kill", 1)]
    assert eng.done()
