"""Deterministic gray-failure injection: a TCP proxy that misbehaves
on schedule (ISSUE 15 tentpole, the injection half).

``ChaosEngine`` could always kill a process; until now it could not
make a *network* lie — a peer that is up but slow, stalled, trickling,
half-open, or gone in one direction only, which is how production TPU
fleets actually fail.  :class:`ChaosProxy` sits in front of any fleet
plane's port (input service, compile-artifact service) and forwards
traffic verbatim until a fault fires:

======================  =====================================================
fault kind              observable behavior
======================  =====================================================
``latency``             every forwarded chunk waits ``delay_s`` first
``throttle``            forwarding is rate-limited to ``rate_bps`` (a tiny
                        rate IS the trickle: bytes keep flowing, per-chunk
                        socket timeouts keep resetting, only an end-to-end
                        deadline notices)
``stall``               forwarding stops mid-stream, both sockets held OPEN
                        (the half-alive peer: no FIN, no RST, no bytes)
``partition``           one direction's bytes are silently dropped, the
                        other keeps flowing (asymmetric reachability)
``tear``                ``after_bytes`` more bytes are forwarded, then both
                        sides are closed — a frame torn mid-payload
``rst``                 connections are closed with SO_LINGER(0): the peer
                        sees ECONNRESET now, not a quiet FIN
======================  =====================================================

Determinism (the chaos plane's standing rule since ISSUE 4): every
unpinned choice — today only a ``tear``'s unspecified ``after_bytes``
— draws from a ``random.Random`` seeded by the schedule, faults fire
in schedule order off one injectable clock, and the resolved firing
timeline lands in :attr:`ChaosProxy.fired` for drills to assert on.
Same seed, same schedule ⇒ same fault timeline, bit for bit.

Two driving modes: a standalone seeded schedule (``tpucfn chaos proxy
--spec``), or slaved to a :class:`~tpucfn.ft.chaos.ChaosEngine` via
:meth:`ChaosProxy.inject` — the coordinator's ``net_*`` chaos ACTIONS
land here, so launch-level chaos specs schedule network faults exactly
like kills.

jax-free, stdlib only.
"""

from __future__ import annotations

import dataclasses
import json
import random
import socket
import struct
import threading
import time
from typing import Callable

NET_FAULT_KINDS = ("latency", "throttle", "stall", "partition", "tear",
                   "rst", "clear")

_DIRECTIONS = ("up", "down", "both")  # up: client->upstream

# Forwarding chunk; small enough that throttle/stall/tear act at
# sub-frame granularity (a torn frame is the point of `tear`).
_CHUNK = 16 * 1024
# Poll cadence for the pump loops and the fault scheduler — bounds how
# stale a fault decision can be, not any user-visible latency.
_POLL_S = 0.05


@dataclasses.dataclass(frozen=True)
class NetFault:
    """One scheduled network fault.  ``at_s`` is seconds since the
    proxy started (schedule mode; ignored under ``inject()``).
    ``duration_s`` bounds latency/throttle/stall/partition windows
    (0 = until cleared).  ``after_bytes`` arms ``tear``/``stall`` only
    after that many MORE bytes were forwarded in the fault's direction
    — the mid-stream precision the drills need (handshakes pass, the
    payload tears); ``None`` on a ``tear`` draws from the seeded RNG.
    ``clear`` lifts every active fault (scheduled recovery)."""

    kind: str
    at_s: float = 0.0
    duration_s: float = 0.0
    delay_s: float = 0.0       # latency
    rate_bps: float = 0.0      # throttle
    direction: str = "both"
    after_bytes: int | None = None  # tear / stall arming offset

    def __post_init__(self):
        if self.kind not in NET_FAULT_KINDS:
            raise ValueError(
                f"unknown net fault {self.kind!r}; one of {NET_FAULT_KINDS}")
        if self.direction not in _DIRECTIONS:
            raise ValueError(
                f"bad direction {self.direction!r}; one of {_DIRECTIONS}")
        if self.kind == "throttle" and self.rate_bps <= 0:
            raise ValueError("throttle needs rate_bps > 0")
        if self.kind == "latency" and self.delay_s <= 0:
            raise ValueError("latency needs delay_s > 0")

    def to_json(self) -> dict:
        out = {"kind": self.kind, "at_s": self.at_s}
        if self.duration_s:
            out["duration_s"] = self.duration_s
        if self.delay_s:
            out["delay_s"] = self.delay_s
        if self.rate_bps:
            out["rate_bps"] = self.rate_bps
        if self.direction != "both":
            out["direction"] = self.direction
        if self.after_bytes is not None:
            out["after_bytes"] = self.after_bytes
        return out


@dataclasses.dataclass(frozen=True)
class NetFaultSchedule:
    faults: tuple[NetFault, ...]
    seed: int = 0

    @classmethod
    def from_json(cls, obj: str | dict) -> "NetFaultSchedule":
        if isinstance(obj, str):
            obj = json.loads(obj)
        return cls(faults=tuple(NetFault(**f) for f in obj.get("faults", ())),
                   seed=int(obj.get("seed", 0)))

    def to_json(self) -> dict:
        return {"seed": self.seed,
                "faults": [f.to_json() for f in self.faults]}


class _FaultState:
    """The proxy-wide active-fault picture the pump threads consult.
    All mutation under one lock; reads snapshot the fields they need
    (a pump must never sleep holding it)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.latency_s = 0.0
        self.latency_until: float | None = None   # None = inactive
        self.rate_bps = 0.0
        self.rate_until: float | None = None
        self.stall_until: float | None = None     # inf = until cleared
        self.stall_dir = "both"
        self.stall_after: int | None = None       # arm at this fwd-bytes mark
        self.partition_until: float | None = None
        self.partition_dir = "both"
        # tear is ONE-SHOT: cut at this forwarded-bytes mark, then the
        # state self-clears (a fired tear must not kill every later
        # connection at birth)
        self.tear_at: int | None = None
        self.tear_dir = "both"

    def clear(self):
        with self.lock:
            self.latency_until = None
            self.rate_until = None
            self.stall_until = None
            self.stall_after = None
            self.partition_until = None
            self.tear_at = None


class ChaosProxy:
    """A misbehaving-on-schedule TCP forwarder in front of one
    upstream ``host:port``.  Start it, point clients at
    :attr:`address`, and inject gray failures — from the seeded
    schedule, or programmatically via :meth:`inject` (the
    :class:`~tpucfn.ft.chaos.ChaosEngine` path)."""

    def __init__(self, upstream: str, *, host: str = "127.0.0.1",
                 port: int = 0, schedule: NetFaultSchedule | None = None,
                 registry=None,
                 connect_timeout_s: float = 5.0,
                 io_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        up_host, _, up_port = upstream.rpartition(":")
        self.upstream = (up_host or "127.0.0.1", int(up_port))
        self._bind_host = host
        self._bind_port = port
        self.schedule = schedule
        self.rng = random.Random(schedule.seed if schedule is not None else 0)
        self.connect_timeout_s = connect_timeout_s
        self.io_timeout_s = io_timeout_s
        self.clock = clock
        self.state = _FaultState()
        self._fwd_bytes = {"up": 0, "down": 0}  # forwarded, under state.lock
        self.fired: list[dict] = []  # resolved fault timeline (audit trail)
        self._pending = list(schedule.faults) if schedule is not None else []
        self._conns: list["_Conn"] = []
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._t0: float | None = None
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        if registry is not None:
            self.conns_c = registry.counter(
                "net_proxy_connections_total", "connections proxied")
            self.fired_c = registry.counter(
                "net_proxy_faults_fired_total", "scheduled faults fired")
            self.bytes_c = registry.counter(
                "net_proxy_forwarded_bytes_total", "bytes forwarded")
            self.dropped_c = registry.counter(
                "net_proxy_dropped_bytes_total",
                "bytes dropped by a one-way partition")
        else:
            from tpucfn.obs.metrics import Counter

            # private instruments (non-fleet use falls back to bare
            # counters; names still registry-shaped for the audit dict)
            self.conns_c = Counter()
            self.fired_c = Counter()
            self.bytes_c = Counter()
            self.dropped_c = Counter()

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> int:
        if self._sock is None:
            raise RuntimeError("proxy not started")
        return self._sock.getsockname()[1]

    @property
    def address(self) -> str:
        return f"{self._bind_host}:{self.port}"

    def start(self) -> "ChaosProxy":
        if self._sock is not None:
            return self
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._bind_host, self._bind_port))
        s.listen(32)
        # Polling accept (the PR 11 lesson: close() does not wake a
        # blocked accept on Linux).
        s.settimeout(0.25)
        self._sock = s
        self._t0 = self.clock()
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="tpucfn-chaosproxy-accept")
        t.start()
        self._threads.append(t)
        if self._pending:
            ts = threading.Thread(target=self._schedule_loop, daemon=True,
                                  name="tpucfn-chaosproxy-sched")
            ts.start()
            self._threads.append(ts)
        return self

    def close(self) -> None:
        self._closed.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- fault surface -----------------------------------------------------

    def inject(self, kind: str, *, duration_s: float = 0.0,
               delay_s: float = 0.0, rate_bps: float = 0.0,
               direction: str = "both",
               after_bytes: int | None = None) -> dict:
        """Apply one fault NOW — the ChaosEngine-slaved form (the
        ``net_*`` chaos ACTIONS land here); schedule-mode firings go
        through the same path so the two modes cannot drift."""
        fault = NetFault(kind=kind, duration_s=duration_s, delay_s=delay_s,
                         rate_bps=rate_bps, direction=direction,
                         after_bytes=after_bytes)
        return self._apply(fault)

    def clear(self) -> None:
        """Lift every active fault (pass-through resumes)."""
        self.state.clear()

    def _apply(self, f: NetFault) -> dict:
        st = self.state
        now = self.clock()
        until = (now + f.duration_s) if f.duration_s > 0 else float("inf")
        resolved: dict = {"kind": f.kind, "direction": f.direction,
                          "elapsed_s": round(now - (self._t0 or now), 4)}
        with st.lock:
            if f.kind == "latency":
                st.latency_s = f.delay_s
                st.latency_until = until
                resolved["delay_s"] = f.delay_s
            elif f.kind == "throttle":
                st.rate_bps = f.rate_bps
                st.rate_until = until
                resolved["rate_bps"] = f.rate_bps
            elif f.kind == "stall":
                st.stall_until = until
                st.stall_dir = f.direction
                if f.after_bytes is not None:
                    st.stall_after = (self._fwd(f.direction)
                                      + int(f.after_bytes))
                    resolved["after_bytes"] = int(f.after_bytes)
                else:
                    st.stall_after = None
            elif f.kind == "partition":
                st.partition_until = until
                st.partition_dir = f.direction
            elif f.kind == "tear":
                n = f.after_bytes if f.after_bytes is not None \
                    else self.rng.randrange(1, 64)
                st.tear_at = self._fwd(f.direction) + int(n)
                st.tear_dir = f.direction
                resolved["after_bytes"] = int(n)
            elif f.kind == "rst":
                pass  # one-shot: applied to live connections below
            elif f.kind == "clear":
                pass  # handled below, outside the lock
        if f.kind == "clear":
            st.clear()
        if f.kind == "rst":
            self._rst_all()
        self.fired_c.add()
        self.fired.append(resolved)
        return resolved

    def _fwd(self, direction: str) -> int:
        # caller holds state.lock
        if direction == "up":
            return self._fwd_bytes["up"]
        if direction == "down":
            return self._fwd_bytes["down"]
        return self._fwd_bytes["up"] + self._fwd_bytes["down"]

    def _rst_all(self) -> None:
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.reset()

    # -- loops -------------------------------------------------------------

    def _schedule_loop(self) -> None:
        assert self._t0 is not None
        while not self._closed.is_set() and self._pending:
            elapsed = self.clock() - self._t0
            due = [f for f in self._pending if elapsed >= f.at_s]
            if due:
                self._pending = [f for f in self._pending
                                 if elapsed < f.at_s]
                # schedule order: seeded draws must resolve identically
                # run to run
                for f in sorted(due, key=lambda f: f.at_s):
                    self._apply(f)
            time.sleep(_POLL_S / 2)

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(self.io_timeout_s)
            self.conns_c.add()
            with self._lock:
                self._conns = [c for c in self._conns if not c.dead]
                self._conns.append(_Conn(self, conn))


class _Conn:
    """One proxied connection: two pump threads (client→upstream and
    upstream→client) consulting the shared fault state per chunk."""

    def __init__(self, proxy: ChaosProxy, client: socket.socket):
        self.proxy = proxy
        self.client = client
        self.dead = False
        self._lock = threading.Lock()
        self.up: socket.socket | None = None
        try:
            up = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            up.settimeout(proxy.connect_timeout_s)
            up.connect(proxy.upstream)
            up.settimeout(proxy.io_timeout_s)
            self.up = up
        except OSError:
            self.close()
            return
        for src, dst, direction in ((client, up, "up"), (up, client, "down")):
            threading.Thread(
                target=self._pump, args=(src, dst, direction),
                daemon=True, name=f"tpucfn-chaosproxy-{direction}").start()

    def close(self) -> None:
        with self._lock:
            self.dead = True
        for s in (self.client, self.up):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def reset(self) -> None:
        """Close with SO_LINGER(0): the client (and upstream) see an
        RST — ECONNRESET — instead of a graceful FIN."""
        for s in (self.client, self.up):
            if s is not None:
                try:
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                 struct.pack("ii", 1, 0))
                except OSError:
                    pass
        self.close()

    # -- the per-chunk fault gauntlet --------------------------------------

    def _pump(self, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        proxy = self.proxy
        try:
            while not self.dead and not proxy._closed.is_set():
                # A stall must also stop READING: the upstream's own
                # sendall then backpressures exactly like a real wedged
                # peer (bytes neither drained nor acked away).
                if self._stalled(direction):
                    time.sleep(_POLL_S)
                    continue
                src.settimeout(_POLL_S)
                try:
                    data = src.recv(_CHUNK)
                except socket.timeout:
                    continue
                except OSError:
                    return
                if not data:
                    # half-close: forward the FIN, keep the other
                    # direction pumping
                    try:
                        dst.shutdown(socket.SHUT_WR)
                    except OSError:
                        pass
                    return
                if not self._forward(dst, direction, data):
                    self.close()  # broken pipe or a fired tear: all done
                    return
        finally:
            if self.dead or proxy._closed.is_set():
                self.close()

    def _stalled(self, direction: str) -> bool:
        st = self.proxy.state
        now = self.proxy.clock()
        with st.lock:
            if st.stall_until is None or now >= st.stall_until:
                return False
            if st.stall_dir not in (direction, "both"):
                return False
            if st.stall_after is not None \
                    and self.proxy._fwd(st.stall_dir) < st.stall_after:
                return False  # not armed yet: the marker bytes still flow
            return True

    def _forward(self, dst: socket.socket, direction: str,
                 data: bytes) -> bool:
        """Apply latency / throttle / partition / tear to one chunk,
        then forward.  False ends the pump (tear fired, or peer gone)."""
        proxy = self.proxy
        st = proxy.state
        # A pump blocked in recv when the stall fired still lands here
        # with a chunk in hand: hold it (connection open, nothing
        # forwarded) until the stall lifts — without this gate the
        # first post-stall chunk slips through.
        while self._stalled(direction) and not self.dead \
                and not proxy._closed.is_set():
            time.sleep(_POLL_S)
        now = proxy.clock()
        with st.lock:
            delay = st.latency_s if (st.latency_until is not None
                                     and now < st.latency_until) else 0.0
            rate = st.rate_bps if (st.rate_until is not None
                                   and now < st.rate_until) else 0.0
            partitioned = (st.partition_until is not None
                           and now < st.partition_until
                           and st.partition_dir in (direction, "both"))
            tear_at = st.tear_at if (st.tear_at is not None
                                     and st.tear_dir in (direction, "both")) \
                else None
            fwd = proxy._fwd(st.tear_dir) if tear_at is not None else 0
        if partitioned:
            proxy.dropped_c.add(len(data))
            with st.lock:
                # dropped bytes still count as "consumed" for tear/stall
                # arming: the schedule is in wire bytes, not luck
                proxy._fwd_bytes[direction] += len(data)
            return True
        if delay > 0:
            self._nap(delay)
        budget = None
        if tear_at is not None:
            budget = max(0, tear_at - fwd)
            data = data[:budget]
        view = memoryview(data)
        off = 0
        while off < len(view):
            if self.dead or proxy._closed.is_set():
                # an unbounded stall must not outlive the proxy: without
                # this check a pump holding a mid-chunk remainder spins
                # here forever after close() (close does not join pumps)
                return False
            if self._stalled(direction):
                # a stall armed mid-chunk (after_bytes landed inside
                # this chunk): hold the remainder, connection open
                time.sleep(_POLL_S)
                continue
            n = len(view) - off
            if rate > 0:
                # trickle: at most rate * tick bytes per tick, so the
                # receiver sees a continuous dribble (each chunk resets
                # a naive per-chunk timeout — the hole deadlines close)
                n = min(n, max(1, int(rate * _POLL_S)))
            with st.lock:
                if (st.stall_until is not None
                        and proxy.clock() < st.stall_until
                        and st.stall_dir in (direction, "both")
                        and st.stall_after is not None):
                    # a byte-armed stall must never be overshot by a
                    # large chunk: cap the slice at the threshold, so
                    # the next iteration's gate holds exactly there
                    gap = st.stall_after - proxy._fwd(st.stall_dir)
                    if gap <= 0:
                        continue  # armed: the gate above takes over
                    n = min(n, gap)
            try:
                dst.settimeout(proxy.io_timeout_s)
                sent = dst.send(view[off:off + n])
            except OSError:
                return False
            off += sent
            proxy.bytes_c.add(sent)
            with st.lock:
                proxy._fwd_bytes[direction] += sent
            if rate > 0 and off < len(view):
                self._nap(_POLL_S)
        if budget is not None:
            with st.lock:
                done = (st.tear_at is not None
                        and proxy._fwd(st.tear_dir) >= st.tear_at)
                if done:
                    st.tear_at = None  # one-shot: later connections live
            if done:
                self.close()  # torn frame, then a plain close
                return False
        return True

    def _nap(self, seconds: float) -> None:
        """Sleep in poll-sized slices so close() is honored promptly."""
        end = self.proxy.clock() + seconds
        while not self.dead and not self.proxy._closed.is_set():
            rem = end - self.proxy.clock()
            if rem <= 0:
                return
            time.sleep(min(_POLL_S, rem))
