"""Real-data path (SURVEY.md §2.1 "S3 data staging", §7.4 item 4):
Store staging, dataset conversion, and encoded-image decode — the
convert → publish → stage → decode → train chain the reference ran as
im2rec → s3 cp → s3 sync → DataIter."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tpucfn.data import (
    CliObjectStore,
    LocalStore,
    ShardedDataset,
    convert_cifar_binary,
    convert_image_tree,
    decode_image,
    decode_transform,
    encode_jpeg,
    stage,
    stage_url,
    store_for_url,
    upload_shards,
)
from tpucfn.data.images import center_crop_resize

REPO = Path(__file__).resolve().parent.parent


# ---- Store ---------------------------------------------------------------


def test_local_store_roundtrip_and_stage(tmp_path):
    store = LocalStore(tmp_path / "bucket")
    store.write_bytes("ds/a-00000-of-00002.tpurec", b"alpha")
    store.write_bytes("ds/a-00001-of-00002.tpurec", b"beta")
    store.write_bytes("ds/readme.txt", b"not a shard")
    assert store.list("ds/") == [
        "ds/a-00000-of-00002.tpurec", "ds/a-00001-of-00002.tpurec",
        "ds/readme.txt",
    ]
    assert store.read_bytes("ds/a-00000-of-00002.tpurec") == b"alpha"
    assert store.size("ds/a-00001-of-00002.tpurec") == 4

    cache = tmp_path / "cache"
    paths = stage(store, "ds/", cache)
    assert [p.name for p in paths] == [
        "a-00000-of-00002.tpurec", "a-00001-of-00002.tpurec"]
    assert (cache / "a-00000-of-00002.tpurec").read_bytes() == b"alpha"

    # idempotent: second stage re-uses matching-size local files
    mtimes = {p: p.stat().st_mtime_ns for p in paths}
    paths2 = stage(store, "ds/", cache)
    assert {p: p.stat().st_mtime_ns for p in paths2} == mtimes


def test_local_store_rejects_escaping_keys(tmp_path):
    store = LocalStore(tmp_path)
    with pytest.raises(ValueError):
        store.read_bytes("../../etc/passwd")


def test_store_for_url_dispatch(tmp_path):
    s, prefix = store_for_url(str(tmp_path))
    assert isinstance(s, LocalStore) and prefix == ""
    s, prefix = store_for_url(f"file://{tmp_path}")
    assert isinstance(s, LocalStore)
    s, prefix = store_for_url("gs://bucket/datasets/imagenet")
    assert isinstance(s, CliObjectStore) and prefix == "datasets/imagenet"
    assert s.base_url == "gs://bucket"
    s, prefix = store_for_url("s3://bucket/ds")
    assert s.scheme == "s3" and prefix == "ds"


class ReplayRunner:
    """Record-replay CLI runner: asserts argv against recorded fixtures
    and performs the local side effect (zero-egress CI, full argv
    coverage — SURVEY.md §4 'fake backend' stance)."""

    def __init__(self, objects: dict[str, bytes]):
        self.objects = objects  # key -> bytes, as the bucket would hold
        self.calls: list[list[str]] = []

    def __call__(self, argv):
        self.calls.append(list(argv))
        if argv[:2] == ["gsutil", "ls"]:
            pat = argv[2]
            base = pat[: pat.index("**")] if "**" in pat else pat
            bucket = pat.split("://", 1)[1].split("/", 1)[0]
            urls = [f"gs://{bucket}/{k}" for k in sorted(self.objects)]
            return "".join(u + "\n" for u in urls if u.startswith(base))
        if argv[:2] == ["gsutil", "stat"]:
            key = argv[2].split("://", 1)[1].split("/", 1)[1]
            if key in self.objects:
                return f"    Content-Length:   {len(self.objects[key])}\n"
            raise subprocess.CalledProcessError(1, argv, stderr="NotFound")
        if argv[:2] == ["gsutil", "cp"]:
            src, dest = argv[2], argv[3]
            if dest.startswith("gs://"):  # upload
                k = dest.split("://", 1)[1].split("/", 1)[1]
                self.objects[k] = Path(src).read_bytes()
                return ""
            key = src.split("://", 1)[1].split("/", 1)[1]  # download
            if key in self.objects:
                Path(dest).write_bytes(self.objects[key])
                return ""
            raise subprocess.CalledProcessError(1, argv, stderr="NotFound")
        raise AssertionError(f"unexpected argv {argv}")


def test_cli_object_store_gs_replay(tmp_path):
    runner = ReplayRunner({
        "ds/x-00000-of-00001.tpurec": b"shardbytes",
        "ds/class_map.json": b"{}",
    })
    store = CliObjectStore("gs://bkt", runner=runner)
    assert store.list("ds/") == ["ds/class_map.json", "ds/x-00000-of-00001.tpurec"]
    assert store.read_bytes("ds/x-00000-of-00001.tpurec") == b"shardbytes"
    store.write_bytes("ds/new.txt", b"pushed")
    assert runner.objects["ds/new.txt"] == b"pushed"

    cache = tmp_path / "cache"
    paths = stage(store, "ds/", cache)
    assert [p.name for p in paths] == ["x-00000-of-00001.tpurec"]
    # the recorded argv surface is exactly the documented CLI commands
    assert all(c[0] == "gsutil" for c in runner.calls)


# ---- images --------------------------------------------------------------


def test_jpeg_roundtrip_and_decode_transform():
    rs = np.random.RandomState(0)
    # smooth gradient, not noise — noise is JPEG's pathological case
    yy, xx = np.mgrid[0:48, 0:64]
    img = np.stack([yy * 5 % 256, xx * 4 % 256, (yy + xx) * 2 % 256],
                   axis=-1).astype(np.uint8)
    enc = encode_jpeg(img, quality=95)
    dec = decode_image(enc)
    assert dec.shape == (48, 64, 3) and dec.dtype == np.uint8
    assert np.mean(np.abs(dec.astype(int) - img.astype(int))) < 20  # lossy

    t = decode_transform()
    ex = {"image": np.frombuffer(enc, dtype=np.uint8), "label": np.int32(3)}
    out = t(ex, rs)
    assert out["image"].shape == (48, 64, 3)
    # decoded examples pass through untouched
    again = t(out, rs)
    assert again["image"] is out["image"]


def test_center_crop_resize_geometry():
    rs = np.random.RandomState(0)
    for h, w in [(100, 160), (160, 100), (32, 32)]:
        img = np.zeros((h, w, 3), np.uint8)
        out = center_crop_resize(64)({"image": img}, rs)["image"]
        assert out.shape == (64, 64, 3)


# ---- converters ----------------------------------------------------------


def _make_image_tree(root: Path, classes=("cat", "dog"), per_class=6, seed=0):
    rs = np.random.RandomState(seed)
    for c in classes:
        (root / c).mkdir(parents=True)
        for i in range(per_class):
            img = rs.randint(0, 255, (40 + i, 50, 3), dtype=np.uint8)
            (root / c / f"{i}.jpg").write_bytes(encode_jpeg(img))


def test_convert_image_tree_and_read_back(tmp_path):
    src = tmp_path / "tree"
    _make_image_tree(src)
    out = tmp_path / "shards"
    paths = convert_image_tree(src, out, num_shards=2)
    assert len(paths) == 2
    class_map = json.loads((out / "class_map.json").read_text())
    assert class_map == {"cat": 0, "dog": 1}

    ds = ShardedDataset(paths, batch_size_per_process=4, shuffle=False,
                        process_index=0, process_count=1,
                        transform=__import__("tpucfn.data.transforms", fromlist=["Compose"]).Compose(
                            [decode_transform(), center_crop_resize(32)]))
    batch = next(ds.epoch(0))
    assert batch["image"].shape == (4, 32, 32, 3)
    assert set(np.unique(batch["label"])) <= {0, 1}


def _make_cifar_binary(root: Path, n=20, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, n, dtype=np.uint8)
    pixels = rs.randint(0, 255, (n, 3072), dtype=np.uint8)
    recs = np.concatenate([labels[:, None], pixels], axis=1)
    root.mkdir(parents=True, exist_ok=True)
    (root / "data_batch_1.bin").write_bytes(recs[: n // 2].tobytes())
    (root / "data_batch_2.bin").write_bytes(recs[n // 2:].tobytes())
    return labels, pixels


def test_convert_cifar_binary(tmp_path):
    labels, pixels = _make_cifar_binary(tmp_path / "cifar")
    out = tmp_path / "shards"
    paths = convert_cifar_binary(tmp_path / "cifar", out, num_shards=2)
    ds = ShardedDataset(paths, batch_size_per_process=20, shuffle=False,
                        process_index=0, process_count=1)
    batch = next(ds.epoch(0))
    assert batch["image"].shape == (20, 32, 32, 3)
    assert batch["image"].dtype == np.uint8
    # round-robin sharding interleaves, so compare as multisets
    assert sorted(batch["label"].tolist()) == sorted(labels.tolist())
    # CHW->HWC transpose correctness for one record
    i = int(np.where(labels == batch["label"][0])[0][0])
    expect = pixels[i].reshape(3, 32, 32).transpose(1, 2, 0)
    assert np.array_equal(batch["image"][0], expect)


def test_recordio_roundtrip_and_convert(tmp_path):
    """MXNet RecordIO: pack → .rec write/read round-trip → convert to
    shards → stream + decode through the normal path (the reference
    user's existing im2rec datasets port directly)."""
    from tpucfn.data.recordio import (
        convert_recordio,
        pack_image_record,
        read_recordio,
        unpack_image_record,
        write_recordio,
    )

    rs = np.random.RandomState(0)
    imgs = [encode_jpeg(rs.randint(0, 255, (32 + i, 32, 3), dtype=np.uint8))
            for i in range(7)]  # odd lengths exercise the 4-byte padding
    labels = rs.randint(0, 5, 7)
    rec = tmp_path / "train.rec"
    write_recordio(rec, (pack_image_record(int(l), d, rec_id=i)
                         for i, (l, d) in enumerate(zip(labels, imgs))))

    got = [unpack_image_record(p) for p in read_recordio(rec)]
    assert [int(lv[0]) for lv, _ in got] == labels.tolist()
    assert [d for _, d in got] == imgs

    # multi-label records keep the full vector
    multi = pack_image_record([1.0, 2.5, -3.0], imgs[0])
    lv, d = unpack_image_record(multi)
    assert lv.tolist() == [1.0, 2.5, -3.0] and d == imgs[0]

    out = tmp_path / "shards"
    paths = convert_recordio(rec, out, num_shards=2)
    from tpucfn.data.transforms import Compose

    ds = ShardedDataset(paths, batch_size_per_process=7, shuffle=False,
                        drop_remainder=False,
                        process_index=0, process_count=1,
                        transform=Compose([decode_transform(),
                                           center_crop_resize(32)]))
    batch = next(ds.epoch(0))
    assert batch["image"].shape == (7, 32, 32, 3)
    assert sorted(batch["label"].tolist()) == sorted(labels.tolist())

    # converting a multi-label .rec refuses loudly instead of silently
    # truncating the label vector
    multirec = tmp_path / "multi.rec"
    write_recordio(multirec, iter([multi]))
    with pytest.raises(NotImplementedError, match="single integer class"):
        convert_recordio(multirec, tmp_path / "shards2", num_shards=1)


def test_recordio_rejects_bad_magic(tmp_path):
    from tpucfn.data.recordio import read_recordio

    (tmp_path / "bad.rec").write_bytes(b"\x00" * 16)
    with pytest.raises(ValueError, match="magic"):
        list(read_recordio(tmp_path / "bad.rec"))


def test_recordio_rejects_truncated_padding(tmp_path):
    """A file cut inside the final record's zero-padding (payload intact)
    is corrupt and must fail as loudly as a cut inside the payload
    (ADVICE r4)."""
    from tpucfn.data.recordio import read_recordio, write_recordio

    rec = tmp_path / "t.rec"
    write_recordio(rec, iter([b"abcde"]))  # 5 bytes -> 3 bytes padding
    whole = rec.read_bytes()
    assert whole[-3:] == b"\x00\x00\x00"
    rec.write_bytes(whole[:-2])  # payload complete, padding truncated
    with pytest.raises(ValueError, match="truncated payload"):
        list(read_recordio(rec))


def test_convert_cifar_rejects_corrupt(tmp_path):
    (tmp_path / "data_batch_1.bin").write_bytes(b"x" * 1000)  # not a multiple
    with pytest.raises(ValueError, match="corrupt"):
        list(__import__("tpucfn.data.convert", fromlist=["iter_cifar_binary"])
             .iter_cifar_binary(tmp_path))


def test_publish_stage_roundtrip(tmp_path):
    """convert → publish to store → stage_url → identical bytes."""
    src = tmp_path / "tree"
    _make_image_tree(src, per_class=3)
    shards = convert_image_tree(src, tmp_path / "out", num_shards=1)
    store = LocalStore(tmp_path / "bucket")
    upload_shards(shards, store, "datasets/minitree")
    staged = stage_url(f"file://{tmp_path}/bucket/datasets/minitree",
                       tmp_path / "cache")
    assert len(staged) == 1
    assert staged[0].read_bytes() == shards[0].read_bytes()


# ---- end-to-end: imagenet example on a real (converted) dataset ----------


def test_imagenet_example_trains_from_converted_tree(tmp_path):
    src = tmp_path / "tree"
    _make_image_tree(src, classes=("a", "b"), per_class=16)
    convert_image_tree(src, tmp_path / "shards", num_shards=4)

    from tpucfn.utils.env import scrub_accelerator_env

    env = scrub_accelerator_env(os.environ, n_devices=8)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([
        sys.executable, str(REPO / "examples" / "imagenet_resnet50.py"),
        "--run-dir", str(tmp_path / "run"),
        "--data-url", str(tmp_path / "shards"),
        "--network", "resnet18", "--image-size", "32", "--num-classes", "2",
        "--batch-size", "16", "--steps", "3", "--ckpt-every", "100",
        "--log-every", "1", "--augment",
    ], env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "final: step=3" in r.stdout
    # staged cache exists and holds the shards
    assert sorted((tmp_path / "run" / "data-cache").glob("*.tpurec"))


# ---- streaming dataset + owner-slice staging -----------------------------


def test_streaming_matches_cached_multiset(tmp_path):
    """cache_in_memory=False yields the same examples per epoch as the
    cached path (different order), in constant memory."""
    from tpucfn.data import write_dataset_shards

    exs = [{"image": np.full((4, 4, 3), i, np.uint8), "label": np.int32(i)}
           for i in range(37)]
    paths = write_dataset_shards(iter(exs), tmp_path, num_shards=3)
    kw = dict(batch_size_per_process=5, seed=3, process_index=0,
              process_count=1)
    cached = ShardedDataset(paths, **kw)
    streamed = ShardedDataset(paths, cache_in_memory=False, shuffle_buffer=8,
                              **kw)
    assert len(cached) == len(streamed) == 37 // 5

    def labels(ds):
        out = []
        for b in ds.epoch(0):
            out.extend(b["label"].tolist())
        return out

    lc, ls = labels(cached), labels(streamed)
    assert len(lc) == len(ls) == 35
    # same length; both shuffled draws from the same 37 examples
    assert set(ls) <= set(range(37))
    # deterministic: same seed/epoch reproduces the stream
    assert labels(streamed) == ls
    # epoch 1 differs (shuffle is epoch-keyed)
    ls1 = []
    for b in streamed.epoch(1):
        ls1.extend(b["label"].tolist())
    assert ls1 != ls


def test_streaming_no_shuffle_preserves_order(tmp_path):
    from tpucfn.data import write_dataset_shards

    exs = [{"x": np.int32(i)} for i in range(10)]
    paths = write_dataset_shards(iter(exs), tmp_path, num_shards=1)
    ds = ShardedDataset(paths, batch_size_per_process=5, shuffle=False,
                        cache_in_memory=False, process_index=0, process_count=1)
    got = [x for b in ds.epoch(0) for x in b["x"].tolist()]
    assert got == list(range(10))


def test_stage_owner_slice_downloads_only_owned(tmp_path):
    store = LocalStore(tmp_path / "bucket")
    for i in range(4):
        store.write_bytes(f"ds/s-{i:05d}-of-00004.tpurec", bytes([i]) * 10)
    cache = tmp_path / "cache"
    paths = stage(store, "ds", cache, owner_slice=(1, 2))
    # full sorted list returned, but only shards 1 and 3 fetched
    assert [p.name for p in paths] == [
        f"s-{i:05d}-of-00004.tpurec" for i in range(4)]
    assert [p.exists() for p in paths] == [False, True, False, True]


def test_stage_preserves_subdirs(tmp_path):
    store = LocalStore(tmp_path / "bucket")
    store.write_bytes("ds/train/x-00000-of-00001.tpurec", b"train")
    store.write_bytes("ds/val/x-00000-of-00001.tpurec", b"val")
    paths = stage(store, "ds", tmp_path / "cache")
    assert len(paths) == len(set(paths)) == 2
    assert (tmp_path / "cache" / "train" / "x-00000-of-00001.tpurec").read_bytes() == b"train"
    assert (tmp_path / "cache" / "val" / "x-00000-of-00001.tpurec").read_bytes() == b"val"


def test_local_store_sibling_root_escape_rejected(tmp_path):
    (tmp_path / "store-evil").mkdir()
    (tmp_path / "store-evil" / "x").write_text("secret")
    store = LocalStore(tmp_path / "store")
    with pytest.raises(ValueError):
        store.read_bytes("../store-evil/x")


def test_local_store_list_sibling_prefix_excluded(tmp_path):
    store = LocalStore(tmp_path)
    store.write_bytes("imagenet/a.tpurec", b"x")
    store.write_bytes("imagenet2012/b.tpurec", b"y")
    assert store.list("imagenet") == ["imagenet/a.tpurec"]
    paths = stage(store, "imagenet", tmp_path / "cache")
    assert [p.name for p in paths] == ["a.tpurec"]


def test_local_store_upload_onto_itself_is_noop(tmp_path):
    store = LocalStore(tmp_path)
    f = tmp_path / "x.tpurec"
    f.write_bytes(b"data")
    store.upload(f, "x.tpurec")  # same file: must not raise
    assert f.read_bytes() == b"data"
