"""Flash-attention block-size autotuner + persisted best-config table.

VERDICT r2 item 3: block sizes were a fixed 128/128 with env overrides
and no way to learn better ones. This module adds the missing piece:

* :func:`tune` — eagerly times candidate (block_q, block_k) pairs for a
  given (S, D, dtype, causal) ON THE CURRENT BACKEND (fwd + bwd, real
  executions — must run outside jit) and persists the winner.
* :func:`lookup` — consulted by ``flash_attention``'s wrapper at trace
  time (pure dict read): explicit ``block_q/block_k`` args win, then
  ``TPUCFN_FLASH_BLOCK_Q/_K`` env overrides, then this table, then the
  128/128 default.

The table is keyed by (device_kind, causal, S-bucket, D, dtype) where
the S bucket is the next power of two — one tuning run covers the
nearby shape family. Cache file: ``~/.tpucfn/flash_tune.json``
(``TPUCFN_FLASH_TUNE_CACHE`` overrides; delete it to re-tune).

The reference delegated this entirely to cuDNN's internal heuristics
(SURVEY.md §2.2 CUDA/cuDNN row); on TPU the block shape is ours to
pick, and the best pick is device-generation- and shape-dependent.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

_MEM_CACHE: dict[str, tuple[int, int]] | None = None

DEFAULT_CANDIDATES = ((128, 128), (128, 256), (256, 128), (256, 256),
                      (128, 512), (512, 128), (256, 512), (512, 256))


def _cache_path() -> Path:
    return Path(os.environ.get(
        "TPUCFN_FLASH_TUNE_CACHE",
        os.path.expanduser("~/.tpucfn/flash_tune.json")))


def _bucket(s: int) -> int:
    b = 128
    while b < s:
        b *= 2
    return b


def _key(device_kind: str, causal: bool, s: int, d: int, dtype) -> str:
    import numpy as np

    return "|".join([device_kind, "causal" if causal else "full",
                     str(_bucket(s)), str(d), str(np.dtype(dtype))])


def _read_table(path: Path) -> dict[str, tuple]:
    """Entries are [block_q, block_k] (legacy) or [block_q, block_k,
    speedup] where speedup is the MEASURED fwd+bwd dense/flash time
    ratio at tune time (None/absent = never measured against dense)."""
    try:
        raw = json.loads(path.read_text())
        return {k: tuple(v) for k, v in raw.items()}
    except (OSError, ValueError):
        return {}


def _load() -> dict[str, tuple[int, int]]:
    """User cache layered over the packaged table: tunes shipped with the
    repo (flash_tune_builtin.json — measured on real chips, see PARITY
    round-3 status) seed the defaults; a user's own ``tune`` runs
    override them per key.  The user cache file stores only the user's
    own tunes (``_save`` never writes builtin entries into it, so a
    package update can improve unpinned keys)."""
    global _MEM_CACHE
    if _MEM_CACHE is None:
        table = _read_table(Path(__file__).parent / "flash_tune_builtin.json")
        for k, v in _read_table(_cache_path()).items():
            # A legacy (pre-speedup) user entry must not erase a builtin
            # measured ratio it agrees with on blocks — that would flip
            # a measured-winning family back to the no-evidence dense
            # rule for exactly the users who tuned.
            old = table.get(k)
            if (len(v) < 3 and old is not None and len(old) >= 3
                    and tuple(old[:2]) == tuple(v[:2])):
                v = tuple(v[:2]) + (old[2],)
            table[k] = v
        _MEM_CACHE = table
    return _MEM_CACHE


def _save(cache: dict[str, tuple[int, int]]) -> None:
    p = _cache_path()
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp")
    tmp.write_text(json.dumps({k: list(v) for k, v in cache.items()},
                              indent=1, sort_keys=True))
    os.replace(tmp, p)


def _entry(s: int, d: int, dtype, causal: bool) -> tuple | None:
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — backend not initialized yet
        return None
    return _load().get(_key(kind, causal, s, d, dtype))


def kind_has_entries(device_kind: str) -> bool:
    """Whether the merged table (builtin + user cache) has ANY entry for
    this device kind — the discoverability probe behind
    ``kernels.auto``'s one-time untuned-device warning: a kind with zero
    entries runs dense everywhere below ``untuned_flash_min_s`` and the
    operator should know why."""
    prefix = device_kind + "|"
    return any(k.startswith(prefix) for k in _load())


def lookup(s: int, d: int, dtype, causal: bool) -> tuple[int, int] | None:
    """Best known (block_q, block_k) for this shape family on the
    current device, or None. Trace-time safe (no device work)."""
    e = _entry(s, d, dtype, causal)
    return None if e is None else tuple(e[:2])


def lookup_speedup(s: int, d: int, dtype, causal: bool) -> float | None:
    """MEASURED fwd+bwd speedup of tuned flash over XLA dense for this
    shape family on the current device — the evidence
    ``kernels.auto``'s dispatch consults (VERDICT r4 #5). None when the
    family was never tuned against dense (incl. legacy 2-entry rows)."""
    e = _entry(s, d, dtype, causal)
    if e is None or len(e) < 3 or e[2] is None:
        return None
    return float(e[2])


def tune(
    s: int,
    d: int = 128,
    *,
    heads: int = 8,
    kv_heads: int = 8,
    batch: int = 1,
    dtype=None,
    causal: bool = True,
    candidates=DEFAULT_CANDIDATES,
    iters: int = 5,
    include_bwd: bool = True,
    persist: bool = True,
) -> dict:
    """Time each candidate block pair eagerly; persist + return results.

    Returns {"best": (bq, bk), "rows": [{blocks, fwd_ms, bwd_ms, total_ms
    | error}], "key": cache_key}. Call OUTSIDE jit, on the device you
    intend to run on (CPU runs interpret mode — only useful for testing
    the mechanism, not for real numbers).
    """
    import jax
    import jax.numpy as jnp

    from tpucfn.kernels.flash_attention import SUBLANES, flash_attention

    dtype = dtype or jnp.bfloat16
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (batch, s, heads, d), dtype)
    k = jax.random.normal(kk, (batch, s, kv_heads, d), dtype)
    v = jax.random.normal(kv, (batch, s, kv_heads, d), dtype)

    def timed(fn, *args):
        jax.block_until_ready(fn(*args))  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    rows = []
    for bq, bk in candidates:
        if bq % SUBLANES or bk % SUBLANES or bq > s or bk > s:
            continue
        row = {"blocks": (bq, bk)}
        try:
            fwd = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                q, k, v, causal=causal, block_q=bq, block_k=bk))
            row["fwd_ms"] = round(timed(fwd, q, k, v), 3)
            total = row["fwd_ms"]
            if include_bwd:
                bwd = jax.jit(jax.grad(
                    lambda q, k, v, bq=bq, bk=bk: jnp.sum(flash_attention(
                        q, k, v, causal=causal, block_q=bq, block_k=bk
                    ).astype(jnp.float32) ** 2), argnums=(0, 1, 2)))
                row["bwd_ms"] = round(timed(bwd, q, k, v), 3)
                total += row["bwd_ms"]
            row["total_ms"] = round(total, 3)
        except Exception as e:  # noqa: BLE001 — e.g. VMEM overflow at 512
            row["error"] = repr(e)[:200]
        rows.append(row)

    ok = [r for r in rows if "total_ms" in r]
    if not ok:
        raise RuntimeError(f"no flash block candidate ran for S={s}, D={d}: "
                           f"{[r.get('error') for r in rows]}")
    best_row = min(ok, key=lambda r: r["total_ms"])
    best = best_row["blocks"]

    # Time XLA dense at the same shape: the dispatch policy needs the
    # dense/flash ratio, not just the best blocks (VERDICT r4 #5 — a
    # tuned-but-losing family must fall back to dense). Dense OOM at
    # long S is an answer too: speedup None = "dense not runnable",
    # which the untuned-length rule in kernels.auto resolves.
    speedup = None
    dense_ms = None
    if include_bwd:
        from tpucfn.ops.attention import dot_product_attention

        try:
            dfwd = jax.jit(lambda q, k, v: dot_product_attention(
                q, k, v, causal=causal))
            dense_f = timed(dfwd, q, k, v)
            dbwd = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(dot_product_attention(
                    q, k, v, causal=causal).astype(jnp.float32) ** 2),
                argnums=(0, 1, 2)))
            dense_ms = round(dense_f + timed(dbwd, q, k, v), 3)
            speedup = round(dense_ms / best_row["total_ms"], 3)
        except Exception as e:  # noqa: BLE001 — dense OOM at long S
            dense_ms = f"error: {repr(e)[:160]}"

    key = _key(jax.devices()[0].device_kind, causal, s, d, dtype)
    if persist:
        global _MEM_CACHE
        user = _read_table(_cache_path())
        if speedup is None:
            # A speedup-less re-tune (fwd-only, or dense errored) must
            # not erase a previously MEASURED ratio it agrees with on
            # blocks — same preservation rule as the builtin merge.
            old = user.get(key)
            if (old is not None and len(old) >= 3 and old[2] is not None
                    and tuple(old[:2]) == tuple(best)):
                speedup = old[2]
        user[key] = tuple(best) + ((speedup,) if speedup is not None else ())
        _save(user)
        _MEM_CACHE = None  # re-merge (builtin + user) on next lookup
    return {"best": tuple(best), "rows": rows, "key": key,
            "dense_total_ms": dense_ms, "speedup_vs_dense": speedup}
