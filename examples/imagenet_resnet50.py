#!/usr/bin/env python
"""ImageNet ResNet-50 data-parallel training (BASELINE config 2; the
reference's headline example, SURVEY.md §2.1 "Example: ImageNet ResNet-50").

    reference:  launch.py -n $DEEPLEARNING_WORKERS_COUNT -H $HOSTFILE \
                   python train_imagenet.py --network resnet --kv-store dist_sync
    tpucfn:     tpucfn launch examples/imagenet_resnet50.py -- --batch-size 1024

DP via psum over ICI (XLA-inserted); --fsdp N shards params/optimizer.
Data: real ImageNet stages through the identical tpurecord path — here the
synthetic generator stands in (zero-egress build env; BASELINE.md caveat).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import (  # noqa: E402
    add_cluster_args,
    build_example_mesh,
    per_process_batch,
    run_train_loop,
    stage_synthetic,
)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_cluster_args(p)
    p.add_argument("--network", default="resnet50", choices=["resnet50", "resnet18"])
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-examples", type=int, default=512,
                   help="synthetic-data size (ignored with --data-url)")
    p.add_argument("--data-url", default="",
                   help="real dataset: tpurecord shards of encoded images "
                        "(tpucfn convert-dataset) at a gs://, s3://, "
                        "file:// URL or local dir; staged to --run-dir "
                        "then decoded on the host input path")
    p.add_argument("--num-classes", type=int, default=1000,
                   help="label cardinality (set to the real dataset's "
                        "class count with --data-url)")
    p.add_argument("--label-smoothing", type=float, default=0.1)
    p.add_argument("--loader-workers", type=int, default=0,
                   help="decode/augment parallelism: N>0 threads "
                        "(in-process; per-example seeds stay "
                        "deterministic) or N<0 spawn processes (|N| "
                        "workers via MultiProcessLoader — the answer "
                        "when one decode core cannot feed the chips)")
    p.add_argument("--augment", action="store_true",
                   help="inception-style random-resized-crop + mirror")
    args = p.parse_args()

    from tpucfn.launch import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp
    import optax

    from tpucfn.data import ShardedDataset
    from tpucfn.models import ResNet, ResNetConfig
    from tpucfn.parallel import dense_rules
    from tpucfn.train import Trainer

    run_dir = Path(args.run_dir)
    if args.data_url:
        # The reference's "aws s3 sync s3://bucket /efs" staging step
        # (SURVEY.md §2.1 S3 row): sync shards down once, train from the
        # local cache; shards hold encoded images, decoded on the host.
        # Each process fetches only the shards it will read (owner_slice).
        from tpucfn.data import stage_url

        shards = stage_url(args.data_url, run_dir / "data-cache",
                           owner_slice=(jax.process_index(),
                                        jax.process_count()))
    else:
        shards = stage_synthetic(
            "imagenet", run_dir / "data", n=args.num_examples,
            num_shards=max(8, jax.process_count()), seed=args.seed,
            image_size=args.image_size,
        )

    mesh = build_example_mesh(args)
    cfg = {"resnet50": ResNetConfig.resnet50, "resnet18": ResNetConfig.resnet18}[
        args.network
    ](num_classes=args.num_classes)
    model = ResNet(cfg)
    sample = jnp.zeros((1, args.image_size, args.image_size, 3))

    def init_fn(rng):
        v = model.init(rng, sample, train=True)
        return v["params"], {"batch_stats": v["batch_stats"]}

    def loss_fn(params, mstate, batch, rng):
        logits, upd = model.apply(
            {"params": params, **mstate}, batch["image"], train=True,
            mutable=["batch_stats"],
        )
        labels = optax.smooth_labels(
            jax.nn.one_hot(batch["label"], cfg.num_classes), args.label_smoothing
        )
        loss = optax.softmax_cross_entropy(logits, labels).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, ({"accuracy": acc}, dict(upd))

    # The standard 76%-top-1 recipe: SGD + momentum, cosine decay, warmup.
    steps_total = args.steps or 1000
    tx = optax.chain(
        optax.add_decayed_weights(1e-4),
        optax.sgd(
            optax.warmup_cosine_decay_schedule(
                0.0, args.lr, min(200, steps_total // 10), steps_total
            ),
            momentum=0.9, nesterov=True,
        ),
    )
    trainer = Trainer(mesh, dense_rules(fsdp=args.fsdp > 1), loss_fn, tx, init_fn)
    transform = None
    if args.data_url:
        # Encoded shards vary in size: decode, fix geometry (augment for
        # training, center-crop otherwise) so batches stack, then
        # normalize 0-255 pixels with the standard channel stats.
        from tpucfn.data import center_crop_resize, decode_transform
        from tpucfn.data.transforms import (
            IMAGENET_MEAN,
            IMAGENET_STD,
            Compose,
            normalize,
            random_flip,
            random_resized_crop,
        )

        geom = ([random_resized_crop(args.image_size), random_flip()]
                if args.augment else [center_crop_resize(args.image_size)])
        transform = Compose([decode_transform(), *geom,
                             normalize(IMAGENET_MEAN, IMAGENET_STD)])
    elif args.augment:
        from tpucfn.data.transforms import Compose, random_flip, random_resized_crop

        transform = Compose([random_resized_crop(args.image_size), random_flip()])
    # Real datasets stream (constant host RAM); synthetic smoke data is
    # small enough to cache decoded.
    if args.loader_workers < 0:
        from tpucfn.data import MultiProcessLoader

        ds = MultiProcessLoader(
            shards, num_workers=-args.loader_workers,
            batch_size_per_process=per_process_batch(args),
            seed=args.seed, transform=transform,
            cache_in_memory=not args.data_url)
    else:
        ds = ShardedDataset(shards,
                            batch_size_per_process=per_process_batch(args),
                            seed=args.seed, transform=transform,
                            cache_in_memory=not args.data_url,
                            num_workers=args.loader_workers)
    run_train_loop(trainer, ds, mesh, args, items_per_step=args.batch_size)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
