from tpucfn.bootstrap.contract import (  # noqa: F401
    COORDINATOR_PORT,
    EnvContract,
    converge,
    shrink_contract,
)
