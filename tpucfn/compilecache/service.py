"""Fleet distribution plane for compiled XLA artifacts.

A jax-free artifact server (run by host 0, an input-role host, or the
``tpucfn launch --compile-cache`` coordinator process) plus the client
trainers/serve replicas consult before compiling.  Reuses the PR 11
input-plane framing (:mod:`tpucfn.data.service` — length-prefixed
frames over TCP) under its own magic, with the same design rules:

* **handshake validates identity** — a client whose device_kind or jax
  version disagrees with the fleet's is refused loudly (an executable
  serialized for v5e under jax X must never be deserialized on
  different hardware or a different compiler); the server pins the
  fleet identity from its flags or from the first client.
* **single-flight on a cold fleet** — ``claim`` hands exactly one
  client the right to compile a key; everyone else polls ``get`` until
  the publish lands (or their wait budget expires and they compile
  locally — correctness never waits on the network).
* **every transport failure degrades to local compile** — a dead
  server, a refused handshake, or a fetch torn mid-transfer costs
  startup latency, never correctness: the client falls back to
  compiling the exact same lowered program, so the run trajectory is
  bit-identical (pinned by test).

:class:`CompileCacheClient` is the jax-free orchestration of
local-store / fleet-fetch / single-flight-compile — compile and
(de)serialize are injected callables, which is what lets the
cold-fleet stampede tests race N clients with a counting fake compiler
and no jax in the process.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from pathlib import Path
from typing import Callable, Sequence

from tpucfn.data.service import (
    ServiceError,
    recv_frame,
    recv_frame_ctx,
    send_frame,
)
from tpucfn.net.deadline import (
    Deadline,
    DeadlineExceeded,
    NetMetrics,
    RetryPolicy,
)
from tpucfn.compilecache.store import (
    ArtifactStore,
    CacheCorrupt,
    CacheMismatch,
    valid_key,
)

# -- env contract (fanned out by the launcher, ISSUE 13) --------------------

COMPILE_CACHE_ADDRS_ENV = "TPUCFN_COMPILE_CACHE_ADDRS"  # comma host:port
COMPILE_CACHE_DIR_ENV = "TPUCFN_COMPILE_CACHE_DIR"      # local store dir
DEFAULT_COMPILE_CACHE_PORT = 7741


def cache_addrs_from_env(env: dict | None = None) -> list[str]:
    import os

    e = os.environ if env is None else env
    raw = (e.get(COMPILE_CACHE_ADDRS_ENV) or "").strip()
    return [a for a in (s.strip() for s in raw.split(",")) if a]


# -- wire protocol ----------------------------------------------------------

CC_MAGIC = b"TPCC"  # tpucfn compile cache
# v2 (ISSUE 20): the shared frame header (see data.service._HEADER)
# grew three u64 trace-context fields — (trace_id, span_id, origin),
# all-zero = none.  The client injects its compile_fetch span context
# into the op frame; the server's artifact_serve span records it as
# its remote parent, which is what lets the merged fleet timeline draw
# the trainer-step -> artifact-fetch edge.
CC_PROTOCOL_VERSION = 2

# frame kinds (1 byte); HELLO/ERROR mirror the input plane's roles
CC_HELLO = b"H"    # client -> server: JSON identity handshake
CC_OK = b"O"       # server -> client: JSON ack (handshake / put / stats)
CC_ERROR = b"X"    # server -> client: utf-8 reason, connection is dead
CC_GET = b"G"      # client -> server: utf-8 key
CC_HIT = b"A"      # server -> client: meta+payload blob (see _pack_entry)
CC_MISS = b"N"     # server -> client: JSON {"claimed": bool}
CC_CLAIM = b"C"    # client -> server: utf-8 key (single-flight request)
CC_GRANTED = b"R"  # server -> client: this client owns the compile
CC_BUSY = b"B"     # server -> client: someone else is compiling it
CC_PUT = b"U"      # client -> server: meta+payload blob
CC_STAT = b"S"     # client -> server: empty; answered with CC_OK stats
CC_RELEASE = b"L"  # client -> server: utf-8 key (claim owner gives up)


def _pack_entry(meta: dict, payload: bytes) -> bytes:
    head = json.dumps(meta).encode()
    return struct.pack("<I", len(head)) + head + payload


def _unpack_entry(blob: bytes | bytearray) -> tuple[dict, bytes]:
    if len(blob) < 4:
        raise ServiceError("torn artifact blob (no meta length)")
    head_len, = struct.unpack_from("<I", blob, 0)
    if 4 + head_len > len(blob):
        raise ServiceError("torn artifact blob (truncated meta)")
    try:
        meta = json.loads(bytes(blob[4:4 + head_len]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ServiceError(f"undecodable artifact meta: {e}") from None
    if not isinstance(meta, dict):
        raise ServiceError("artifact meta is not an object")
    return meta, bytes(blob[4 + head_len:])


# -- the server -------------------------------------------------------------

class ArtifactServer:
    """Serves one :class:`ArtifactStore` to the fleet.

    jax-free: the coordinator or an input-role host runs it.  One
    thread per connection (connections are one-op and short-lived);
    claims are in-memory with an expiry so a claimer that died mid-
    compile frees the key for the next cold client.
    """

    def __init__(self, store_dir: str | Path, *, host: str = "0.0.0.0",
                 port: int = 0, device_kind: str | None = None,
                 jax_version: str | None = None,
                 claim_ttl_s: float = 600.0,
                 send_deadline_s: float = 60.0,
                 registry=None,
                 tracer=None,
                 clock: Callable[[], float] = time.monotonic):
        self.store = ArtifactStore(store_dir)
        self._bind_host = host
        self._bind_port = port
        # Fleet identity: from flags when given, else pinned to the
        # first client's handshake — after that, a disagreeing client
        # is refused (heterogeneous fleets need one server per kind).
        self.device_kind = device_kind
        self.jax_version = jax_version
        self.claim_ttl_s = claim_ttl_s
        # End-to-end bound on serving one response frame (ISSUE 15): an
        # artifact payload is tens of MB, and a stalled/trickling client
        # would otherwise pin this connection's thread for as long as
        # per-chunk timeouts keep resetting.
        self.send_deadline_s = float(send_deadline_s)
        # Fleet timeline (ISSUE 20): one ``artifact_serve`` span per op,
        # remote-parented on the requesting client's span context from
        # the op frame header (its compile_fetch span).
        self.tracer = tracer
        self.clock = clock
        self._claims: dict[str, float] = {}  # key -> expiry
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._closed = threading.Event()
        if registry is None:
            from tpucfn.obs.registry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        self.gets_c = registry.counter(
            "compilecache_gets_total", "artifact GET requests served")
        self.hits_c = registry.counter(
            "compilecache_hits_total", "artifact GETs answered with a hit")
        self.puts_c = registry.counter(
            "compilecache_publishes_total", "artifacts published by clients")
        self.claims_c = registry.counter(
            "compilecache_claims_granted_total",
            "single-flight compile claims granted")
        self.refusals_c = registry.counter(
            "compilecache_handshake_refusals_total",
            "connections refused at the identity handshake")
        self.send_stalls_c = registry.counter(
            "compilecache_send_stalls_total",
            "responses dropped because the send deadline expired "
            "(stalled/trickling client)")
        self.bytes_c = registry.counter(
            "compilecache_served_bytes_total", "artifact payload bytes served")
        registry.computed_gauge(
            "compilecache_entries", lambda: float(len(self.store.keys())),
            "artifacts resident in the server's store")

    # -- lifecycle ---------------------------------------------------------

    @property
    def port(self) -> int:
        if self._sock is None:
            raise RuntimeError("server not started")
        return self._sock.getsockname()[1]

    @property
    def address(self) -> str:
        host = self._bind_host if self._bind_host not in ("", "0.0.0.0") \
            else "127.0.0.1"
        return f"{host}:{self.port}"

    def start(self) -> "ArtifactServer":
        if self._sock is not None:
            return self
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self._bind_host, self._bind_port))
        s.listen(64)
        # Polling accept, same reason as InputService: close() from
        # another thread does not reliably wake a blocked accept().
        s.settimeout(0.25)
        self._sock = s
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name="tpucfn-compilecache-accept")
        self._accept_thread.start()
        return self

    def close(self) -> None:
        self._closed.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    def _accept_loop(self) -> None:
        assert self._sock is not None
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(30.0)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name="tpucfn-compilecache-conn").start()

    # -- per-connection protocol -------------------------------------------

    def _validate_hello(self, hello: dict) -> str | None:
        if hello.get("v") != CC_PROTOCOL_VERSION:
            return (f"protocol version {hello.get('v')} != "
                    f"{CC_PROTOCOL_VERSION}")
        dk = hello.get("device_kind") or None
        jv = hello.get("jax_version") or None
        with self._lock:
            if self.device_kind is None and dk:
                self.device_kind = dk  # first client pins the fleet
            if self.jax_version is None and jv:
                self.jax_version = jv
            if dk and self.device_kind and dk != self.device_kind:
                return (f"device_kind {dk!r} != fleet {self.device_kind!r} "
                        "— an executable for one cannot run on the other")
            if jv and self.jax_version and jv != self.jax_version:
                return (f"jax version {jv} != fleet {self.jax_version} — "
                        "serialized executables do not cross versions")
        return None

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            kind, payload = recv_frame(conn, magic=CC_MAGIC)
            if kind != CC_HELLO:
                self._send(conn, CC_ERROR, b"expected HELLO")
                return
            try:
                hello = json.loads(bytes(payload).decode())
            except (UnicodeDecodeError, json.JSONDecodeError):
                self._send(conn, CC_ERROR, b"undecodable HELLO")
                return
            refusal = self._validate_hello(hello)
            if refusal:
                self.refusals_c.add()
                self._send(conn, CC_ERROR, refusal.encode())
                return
            self._send(conn, CC_OK,
                       json.dumps({"v": CC_PROTOCOL_VERSION}).encode())
            kind, payload, ctx = recv_frame_ctx(conn, magic=CC_MAGIC)
            t_op = time.monotonic()
            key = None
            if kind == CC_GET:
                key = bytes(payload).decode()
                self._op_get(conn, key)
            elif kind == CC_CLAIM:
                key = bytes(payload).decode()
                self._op_claim(conn, key)
            elif kind == CC_PUT:
                self._op_put(conn, payload)
            elif kind == CC_RELEASE:
                key = bytes(payload).decode()
                self._op_release(conn, key)
            elif kind == CC_STAT:
                self._send(conn, CC_OK, json.dumps({
                    "entries": len(self.store.keys()),
                    "claims": len(self._live_claims()),
                    "device_kind": self.device_kind,
                    "jax_version": self.jax_version,
                }).encode())
            else:
                self._send(conn, CC_ERROR,
                           f"unknown op {kind!r}".encode())
            if self.tracer is not None and self.tracer.enabled:
                # trace_id adopts the client's (the trainer step that
                # triggered the fetch) so the server-side work lands in
                # that step's tree on the merged timeline.
                self.tracer.record(
                    "artifact_serve", start=t_op, end=time.monotonic(),
                    trace_id=(ctx[0] if ctx and ctx[0] else None),
                    remote_parent=ctx, op=kind.decode(errors="replace"),
                    **({"key": key} if key else {}))
        except DeadlineExceeded:
            # a response outlived its send deadline: the client is
            # stalled or trickling — drop the connection (it is one-op;
            # nothing to salvage) and count the gray failure
            self.send_stalls_c.add()
        except (OSError, ServiceError):
            pass  # client vanished / torn frame: nothing to answer
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _send(self, conn: socket.socket, kind: bytes,
              payload: bytes) -> None:
        """One response frame under its own end-to-end deadline — a
        multi-MB artifact to a stalled client fails inside the bound
        instead of pinning this connection thread per-chunk-forever.
        0 disables the bound (the sibling-knob convention) instead of
        minting an already-expired deadline."""
        send_frame(conn, kind, payload, magic=CC_MAGIC,
                   deadline=(Deadline(self.send_deadline_s,
                                      label="compilecache send")
                             if self.send_deadline_s > 0 else None))

    def _live_claims(self) -> dict[str, float]:
        now = self.clock()
        with self._lock:
            self._claims = {k: t for k, t in self._claims.items() if t > now}
            return dict(self._claims)

    def _op_get(self, conn: socket.socket, key: str) -> None:
        self.gets_c.add()
        if not valid_key(key):
            self._send(conn, CC_ERROR, f"invalid key {key!r}".encode())
            return
        try:
            got = self.store.get(key)
        except (CacheCorrupt, CacheMismatch) as e:
            # quarantined server-side; the client sees a miss and
            # compiles — the corrupt artifact is never served.
            self._send(conn, CC_MISS,
                       json.dumps({"claimed": False,
                                   "corrupt": str(e)}).encode())
            return
        if got is None:
            claimed = key in self._live_claims()
            self._send(conn, CC_MISS,
                       json.dumps({"claimed": claimed}).encode())
            return
        payload, meta = got
        self.hits_c.add()
        self.bytes_c.add(len(payload))
        self._send(conn, CC_HIT, _pack_entry(meta, payload))

    def _op_claim(self, conn: socket.socket, key: str) -> None:
        if not valid_key(key):
            self._send(conn, CC_ERROR, f"invalid key {key!r}".encode())
            return
        if self.store.has(key):
            # published while the client was dialing: answer as a GET —
            # but a corrupt entry (get() quarantines it) means the key
            # is COLD, not served: fall through and grant the claim, or
            # the claimer would get a CC_MISS it cannot interpret and
            # the cold fleet would stampede-compile the key.
            try:
                got = self.store.get(key)
            except (CacheCorrupt, CacheMismatch):
                got = None
            if got is not None:
                payload, meta = got
                # counted as a served GET too: a hit answered through
                # CLAIM must keep hits_total <= gets_total (ratio
                # dashboards read the pair)
                self.gets_c.add()
                self.hits_c.add()
                self.bytes_c.add(len(payload))
                self._send(conn, CC_HIT, _pack_entry(meta, payload))
                return
        now = self.clock()
        with self._lock:
            expiry = self._claims.get(key, 0.0)
            if expiry > now:
                self._send(conn, CC_BUSY, b"")
                return
            self._claims[key] = now + self.claim_ttl_s
        self.claims_c.add()
        self._send(conn, CC_GRANTED, b"")

    def _op_release(self, conn: socket.socket, key: str) -> None:
        """A granted claimer whose compile (or publish) failed gives
        the key back so the cold fleet's waiters stop polling for a
        publish that will never come — without this, a single failed
        compile on the claim owner holds every peer until claim_ttl_s."""
        if not valid_key(key):
            self._send(conn, CC_ERROR, f"invalid key {key!r}".encode())
            return
        with self._lock:
            self._claims.pop(key, None)
        self._send(conn, CC_OK, json.dumps({"released": key}).encode())

    def _op_put(self, conn: socket.socket, blob) -> None:
        try:
            meta, payload = _unpack_entry(blob)
        except ServiceError as e:
            self._send(conn, CC_ERROR, str(e).encode())
            return
        key = str(meta.get("key") or "")
        if not valid_key(key):
            self._send(conn, CC_ERROR, f"invalid key {key!r}".encode())
            return
        self.store.put(key, payload, meta)
        with self._lock:
            self._claims.pop(key, None)
        self.puts_c.add()
        self._send(conn, CC_OK, json.dumps({"stored": key}).encode())


# -- the client -------------------------------------------------------------

class ArtifactClient:
    """One-op-per-connection client of :class:`ArtifactServer`.

    Every method raises :class:`~tpucfn.data.service.ServiceError` on
    any transport/protocol failure — :class:`CompileCacheClient` turns
    that into failover across addrs and then local compilation."""

    def __init__(self, addr: str, *, device_kind: str = "",
                 jax_version: str = "", connect_timeout_s: float = 5.0,
                 recv_timeout_s: float = 60.0,
                 op_deadline_s: float | None = None,
                 net_metrics: NetMetrics | None = None):
        self.addr = addr
        self.device_kind = device_kind
        self.jax_version = jax_version
        self.connect_timeout_s = connect_timeout_s
        self.recv_timeout_s = recv_timeout_s
        # One op = dial + handshake + request + response, end to end
        # (ISSUE 15).  recv_timeout_s alone was per-chunk — a trickling
        # server delivering an artifact a byte per timeout never failed.
        self.op_deadline_s = (float(op_deadline_s) if op_deadline_s
                              else recv_timeout_s)
        self.net_metrics = net_metrics

    def _dial(self, deadline: Deadline) -> socket.socket:
        host, _, port = self.addr.rpartition(":")
        sock = None
        try:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.settimeout(deadline.timeout(cap=self.connect_timeout_s,
                                             what="connect"))
            sock.connect((host or "127.0.0.1", int(port)))
            sock.settimeout(self.recv_timeout_s)
            hello = {"v": CC_PROTOCOL_VERSION,
                     "device_kind": self.device_kind,
                     "jax_version": self.jax_version}
            send_frame(sock, CC_HELLO, json.dumps(hello).encode(),
                       magic=CC_MAGIC, deadline=deadline)
            kind, payload = recv_frame(sock, magic=CC_MAGIC,
                                       deadline=deadline)
            if kind == CC_ERROR:
                raise ServiceError(
                    f"artifact server {self.addr} refused: "
                    f"{bytes(payload).decode(errors='replace')}")
            if kind != CC_OK:
                raise ServiceError(f"unexpected handshake frame {kind!r}")
            return sock
        except (OSError, ValueError) as e:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            if isinstance(e, DeadlineExceeded):
                if self.net_metrics is not None:
                    self.net_metrics.deadline_exceeded_c.add()
                raise ServiceError(
                    f"artifact server {self.addr}: {e}") from None
            raise ServiceError(
                f"connect to artifact server {self.addr}: {e}") from None
        except ServiceError:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise

    def _op(self, kind: bytes, payload: bytes,
            ctx: tuple[int, int, int] | None = None
            ) -> tuple[bytes, bytearray]:
        deadline = Deadline(self.op_deadline_s, label="compilecache op")
        sock = self._dial(deadline)
        try:
            send_frame(sock, kind, payload, magic=CC_MAGIC, ctx=ctx,
                       deadline=deadline)
            resp, body = recv_frame(sock, magic=CC_MAGIC, deadline=deadline)
        except DeadlineExceeded as e:
            # gray peer (stalled mid-response / trickling payload):
            # counted, then degraded exactly like a dead one
            if self.net_metrics is not None:
                self.net_metrics.deadline_exceeded_c.add()
            raise ServiceError(f"artifact op to {self.addr}: {e}") from None
        except OSError as e:
            raise ServiceError(f"artifact op to {self.addr}: {e}") from None
        finally:
            try:
                sock.close()
            except OSError:
                pass
        if resp == CC_ERROR:
            raise ServiceError(
                f"artifact server {self.addr}: "
                f"{bytes(body).decode(errors='replace')}")
        return resp, body

    def get(self, key: str,
            ctx: tuple[int, int, int] | None = None
            ) -> tuple[bytes, dict] | None:
        """``(payload, meta)`` or None on a miss.  ``ctx`` is the
        caller's span context for the op frame header (ISSUE 20) —
        the server's artifact_serve span remote-parents on it.  The
        payload is re-verified against the meta's sha256 HERE — a fetch
        torn mid-transfer (or a lying server) raises, it never
        deserializes."""
        resp, body = self._op(CC_GET, key.encode(), ctx=ctx)
        if resp == CC_MISS:
            return None
        if resp != CC_HIT:
            raise ServiceError(f"unexpected GET response {resp!r}")
        meta, payload = _unpack_entry(body)
        import hashlib

        if hashlib.sha256(payload).hexdigest() != meta.get("sha256"):
            raise ServiceError(
                f"artifact {key} from {self.addr} fails its sha256 — "
                "torn transfer or corrupt server entry; refusing it")
        return payload, meta

    def claim(self, key: str) -> str:
        """``"granted"`` | ``"busy"`` | ``"hit"`` (published while we
        dialed — call :meth:`get`)."""
        resp, _body = self._op(CC_CLAIM, key.encode())
        if resp == CC_GRANTED:
            return "granted"
        if resp == CC_BUSY:
            return "busy"
        if resp == CC_HIT:
            return "hit"
        raise ServiceError(f"unexpected CLAIM response {resp!r}")

    def put(self, key: str, payload: bytes, meta: dict) -> None:
        meta = {**meta, "key": key}
        resp, _body = self._op(CC_PUT, _pack_entry(meta, payload))
        if resp != CC_OK:
            raise ServiceError(f"unexpected PUT response {resp!r}")

    def release(self, key: str) -> None:
        """Give a granted single-flight claim back (compile failed or
        nothing publishable) so waiting peers stop polling."""
        resp, _body = self._op(CC_RELEASE, key.encode())
        if resp != CC_OK:
            raise ServiceError(f"unexpected RELEASE response {resp!r}")

    def stats(self) -> dict:
        resp, body = self._op(CC_STAT, b"")
        if resp != CC_OK:
            raise ServiceError(f"unexpected STAT response {resp!r}")
        return json.loads(bytes(body).decode())


class CompileCacheClient:
    """local store → fleet fetch → single-flight compile → publish.

    jax-free orchestration: ``compile_fn``/``serialize_fn``/
    ``deserialize_fn`` are injected per call, so the jax glue
    (:mod:`tpucfn.compilecache.jit`) and the stampede tests share one
    implementation.  Outcomes (also marked on the attached
    :class:`~tpucfn.obs.profiler.CompileCacheProbe` and counted on the
    registry):

    * ``"store"``   — the local artifact store had it (warm restart on
      the same machine); ledger bucket ``compile_cached``;
    * ``"fetch"``   — a fleet peer's artifact was fetched + installed;
      ledger bucket ``compile_fetched``, with its own
      ``compile_fetch`` trace span;
    * ``"compile"`` — compiled here (and published when possible);
      ledger bucket ``compile``.
    """

    def __init__(self, store: ArtifactStore | None,
                 addrs: Sequence[str] = (), *,
                 device_kind: str = "", jax_version: str = "",
                 registry=None, tracer=None, probe=None,
                 wait_s: float = 600.0, poll_s: float = 0.25,
                 connect_timeout_s: float = 5.0,
                 op_deadline_s: float | None = None,
                 retry: RetryPolicy | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.store = store
        self.addrs = list(addrs)
        self.device_kind = device_kind
        self.jax_version = jax_version
        self.tracer = tracer
        self.probe = probe
        self.wait_s = wait_s
        self.poll_s = poll_s
        self.connect_timeout_s = connect_timeout_s
        self.op_deadline_s = op_deadline_s
        self.clock = clock
        self.sleep = sleep
        self.last_outcome: str | None = None
        if registry is None:
            from tpucfn.obs.registry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        self.net_metrics = NetMetrics(registry, "compilecache")
        # The shared jittered-backoff policy (ISSUE 15) behind both
        # wait-for-the-claim-owner poll loops (fleet and local-store) —
        # poll_s stays the floor so the busy-wait tests' fake clocks
        # keep their cadence, jitter spreads a whole cold fleet's polls.
        self.retry = retry if retry is not None else RetryPolicy(
            base_s=poll_s, multiplier=1.5, max_s=max(poll_s * 8, poll_s),
            jitter=0.25, seed=0, clock=clock, sleep=sleep)
        self.store_hits_c = registry.counter(
            "compilecache_store_hits_total",
            "programs served from the local artifact store")
        self.fetch_hits_c = registry.counter(
            "compilecache_fetch_hits_total",
            "programs fetched from a fleet artifact server")
        self.compiles_c = registry.counter(
            "compilecache_compiles_total",
            "programs compiled locally (cold key, or degraded)")
        self.publish_c = registry.counter(
            "compilecache_client_publishes_total",
            "artifacts published back to the fleet")
        self.corrupt_c = registry.counter(
            "compilecache_corrupt_total",
            "artifacts refused for integrity/version failure")
        self.fetch_failures_c = registry.counter(
            "compilecache_fetch_failures_total",
            "fleet fetch attempts that failed (degraded to local)")

    def _clients(self) -> list[ArtifactClient]:
        return [ArtifactClient(a, device_kind=self.device_kind,
                               jax_version=self.jax_version,
                               connect_timeout_s=self.connect_timeout_s,
                               op_deadline_s=self.op_deadline_s,
                               net_metrics=self.net_metrics)
                for a in self.addrs]

    def _mark(self, outcome: str) -> None:
        self.last_outcome = outcome
        if self.probe is not None:
            try:
                self.probe.mark(outcome)
            except Exception:  # noqa: BLE001 — the probe is best-effort
                pass

    def _try_deserialize(self, key: str, payload: bytes, meta: dict,
                         deserialize_fn):
        """None on failure (counted): a payload that will not
        deserialize is corruption-by-another-name — quarantine locally
        and fall through to compiling."""
        try:
            return deserialize_fn(payload, meta)
        except Exception:  # noqa: BLE001 — degrade to compile, loudly counted
            self.corrupt_c.add()
            if self.store is not None and self.store.has(key):
                self.store.quarantine(key)
            return None

    def get_or_compile(self, key: str, compile_fn, *,
                       serialize_fn=None, deserialize_fn=None,
                       label: str = ""):
        """Returns ``(result, outcome)``.  ``compile_fn()`` must return
        the result; ``serialize_fn(result) -> bytes`` (or None to skip
        publishing); ``deserialize_fn(payload, meta) -> result``.  Any
        artifact-plane failure degrades to ``compile_fn()`` — the
        result is always the same program."""
        deserialize_fn = deserialize_fn or (lambda payload, meta: payload)
        # 1. local artifact store
        if self.store is not None:
            try:
                got = self.store.get(key)
            except (CacheCorrupt, CacheMismatch):
                self.corrupt_c.add()
                got = None
            if got is not None:
                result = self._try_deserialize(key, got[0], got[1],
                                               deserialize_fn)
                if result is not None:
                    self.store_hits_c.add()
                    self._mark("store")
                    return result, "store"
        # 2. fleet fetch / single-flight
        if self.addrs:
            result = self._fleet(key, compile_fn, serialize_fn,
                                 deserialize_fn, label)
            if result is not None:
                return result
        # 3. local-only path (no fleet, or fleet unreachable): local
        # single-flight via the store's claim lock, then compile.
        return self._compile_local(key, compile_fn, serialize_fn,
                                   deserialize_fn, publish=None, label=label)

    # -- fleet path --------------------------------------------------------

    def _fetch(self, clients, key: str, deserialize_fn):
        for c in clients:
            t0 = self.clock()
            # Pre-mint the compile_fetch span id so the op frame can
            # carry it (ISSUE 20): the server's artifact_serve span
            # remote-parents on (origin, sid) and the merged timeline
            # draws the fetch edge.  Failed attempts burn an id each —
            # ids are plentiful, alignment is not.
            sid = (self.tracer.next_span_id()
                   if self.tracer is not None and self.tracer.enabled
                   else None)
            try:
                got = c.get(key, ctx=((0, sid, self.tracer.origin)
                                      if sid is not None else None))
            except ServiceError:
                self.fetch_failures_c.add()
                continue
            if got is None:
                continue
            payload, meta = got
            result = self._try_deserialize(key, payload, meta,
                                           deserialize_fn)
            if result is None:
                continue
            dt = self.clock() - t0
            if self.store is not None:
                try:
                    self.store.put(key, payload, meta)
                except OSError:
                    pass
            if self.tracer is not None:
                self.tracer.record("compile_fetch", start=t0, dur_s=dt,
                                   span_id=sid,
                                   key=key, label=label_or(meta, ""),
                                   addr=c.addr, bytes=len(payload))
            self.fetch_hits_c.add()
            self._mark("fetch")
            return result, "fetch"
        return None

    def _fleet(self, key, compile_fn, serialize_fn, deserialize_fn, label):
        clients = self._clients()
        got = self._fetch(clients, key, deserialize_fn)
        if got is not None:
            return got
        # miss everywhere: try to become the fleet's one compiler
        owner = None
        busy = False
        for c in clients:
            try:
                verdict = c.claim(key)
            except ServiceError:
                self.fetch_failures_c.add()
                continue
            if verdict == "granted":
                owner = c
                break
            if verdict == "hit":
                got = self._fetch([c], key, deserialize_fn)
                if got is not None:
                    return got
            if verdict == "busy":
                busy = True
        if owner is not None:
            return self._compile_local(key, compile_fn, serialize_fn,
                                       deserialize_fn, publish=owner,
                                       label=label)
        if busy:
            # someone else is compiling it: poll until it publishes or
            # the wait budget expires (then compile locally — waiting
            # forever on a peer that may have died is worse than
            # paying the compile).  Each round also re-claims: a
            # claimer whose compile failed RELEASEs (and a dead one
            # expires at claim_ttl_s), and the first waiter to notice
            # becomes the fleet's compiler instead of stalling out its
            # whole wait budget.  The cadence is the shared RetryPolicy
            # (ISSUE 15): jittered backoff, so a cold fleet's waiters
            # do not hammer the server in lockstep.
            deadline = Deadline(self.wait_s, clock=self.clock,
                                label="compile wait")
            for _ in self.retry.attempts(deadline=deadline,
                                         metrics=self.net_metrics,
                                         sleep_first=True):
                got = self._fetch(clients, key, deserialize_fn)
                if got is not None:
                    return got
                for c in clients:
                    try:
                        verdict = c.claim(key)
                    except ServiceError:
                        continue
                    if verdict == "granted":
                        return self._compile_local(
                            key, compile_fn, serialize_fn, deserialize_fn,
                            publish=c, label=label)
                    if verdict == "hit":
                        got = self._fetch([c], key, deserialize_fn)
                        if got is not None:
                            return got
        return None  # fleet could not help: caller compiles locally

    # -- compile-and-publish ----------------------------------------------

    def _compile_local(self, key, compile_fn, serialize_fn,
                       deserialize_fn, *,
                       publish: ArtifactClient | None, label: str):
        claimed = False
        if self.store is not None and publish is None:
            # local single-flight: the bench's "second process on the
            # same machine" and N local ranks sharing one store dir
            claimed = self.store.claim(key)
            if not claimed:
                deadline = Deadline(self.wait_s, clock=self.clock,
                                    label="local claim wait")
                for _ in self.retry.attempts(deadline=deadline,
                                             metrics=self.net_metrics,
                                             sleep_first=True):
                    try:
                        got = self.store.get(key)
                    except (CacheCorrupt, CacheMismatch):
                        self.corrupt_c.add()
                        break
                    if got is not None:
                        # the claim winner published: deserialize it —
                        # through the caller's real deserialize_fn, the
                        # payload bytes are NOT the executable
                        result = self._try_deserialize(
                            key, got[0], got[1], deserialize_fn)
                        if result is not None:
                            self.store_hits_c.add()
                            self._mark("store")
                            return result, "store"
                        break  # its artifact is corrupt: compile here
                    if self.store.claim(key):
                        claimed = True
                        break
        published = False
        try:
            result = compile_fn()
        except BaseException:
            # neither claim may outlive a failed compile: give the
            # fleet claim back NOW so waiting peers re-claim instead of
            # polling out their whole wait budget against a dead
            # publish, and free the local lockfile for the next rank.
            if publish is not None:
                try:
                    publish.release(key)
                except ServiceError:
                    pass
            if claimed and self.store is not None:
                self.store.release(key)
            raise
        try:
            self.compiles_c.add()
            self._mark("compile")
            payload = None
            if serialize_fn is not None:
                try:
                    payload = serialize_fn(result)
                except Exception:  # noqa: BLE001 — publish is best-effort
                    payload = None
            if payload is not None:
                meta = {"key": key, "label": label,
                        "device_kind": self.device_kind,
                        "jax_version": self.jax_version}
                if self.store is not None:
                    try:
                        self.store.put(key, payload, meta)
                    except OSError:
                        pass
                targets = [publish] if publish is not None \
                    else self._clients()
                for c in targets:
                    try:
                        c.put(key, payload, meta)
                        self.publish_c.add()
                        published = True
                        break
                    except ServiceError:
                        self.fetch_failures_c.add()
            return result, "compile"
        finally:
            if publish is not None and not published:
                # compiled fine but nothing publishable landed (backend
                # cannot serialize, or the put failed): same rule —
                # release so the fleet stops waiting on this key.
                try:
                    publish.release(key)
                except ServiceError:
                    pass
            if claimed and self.store is not None:
                self.store.release(key)


def label_or(meta: dict, default: str) -> str:
    v = meta.get("label")
    return v if isinstance(v, str) else default
