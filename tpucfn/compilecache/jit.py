"""jax glue for the artifact cache: fingerprint → fetch-or-compile.

``maybe_warm(jitted, label=...)`` is the one integration point the
trainer and serve engine use: it wraps a ``jax.jit`` callable so the
first call per avals-signature runs

    lower (cheap) → cache key (BEFORE compiling — a hit skips the
    compile entirely) → local store / fleet fetch / single-flight
    compile+publish → AOT executable

and subsequent calls go straight to the compiled executable.  With no
client configured it returns the jitted callable itself — the pinned
byte-identical default.

Serialization uses jax's AOT export surface
(``jax.experimental.serialize_executable.serialize`` /
``deserialize_and_load`` — the PAPERS.md whole-program-AOT direction):
the artifact IS the loaded executable, so a hit pays deserialization,
never XLA.  Any failure anywhere in the warm path permanently falls
back to the plain jitted callable for that wrapper — same program,
bit-identical trajectory, just without the warm start.

TRUST MODEL: jax's AOT surface is pickle-based, so deserializing an
artifact EXECUTES whatever the payload encodes — the sha256 checks
prove integrity (the bytes arrived as published), not authenticity
(who published them).  The artifact plane therefore carries the same
trust boundary as the rest of the launch fan-out (the input plane, the
heartbeat dir, the run storage): server and store dirs must live on
the cluster's private network / filesystem, reachable only by fleet
members.  Do not point ``TPUCFN_COMPILE_CACHE_ADDRS`` at an untrusted
server or ``TPUCFN_COMPILE_CACHE_DIR`` at a world-writable path on a
shared machine.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Callable

from tpucfn.compilecache.service import (
    CompileCacheClient,
    cache_addrs_from_env,
    COMPILE_CACHE_DIR_ENV,
)
from tpucfn.compilecache.store import ArtifactStore, cache_key


# -- process-default client -------------------------------------------------

_default_client: CompileCacheClient | None = None
_default_lock = threading.Lock()


def set_default_client(client: CompileCacheClient | None) -> None:
    global _default_client
    with _default_lock:
        _default_client = client


def get_default_client() -> CompileCacheClient | None:
    return _default_client


def runtime_identity() -> tuple[str, str]:
    """(device_kind, jax_version) of this process — two of the key
    components, and the handshake identity."""
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no backend yet: identity is versions
        kind = "unknown"
    import jaxlib

    return kind, f"{jax.__version__}/{getattr(jaxlib, '__version__', '?')}"


def configure_client_from_env(*, tracer=None, registry=None, probe=None,
                              env=None) -> CompileCacheClient | None:
    """Install the process-default client per the launcher fan-out.
    ``TPUCFN_COMPILE_CACHE_ADDRS`` and/or ``TPUCFN_COMPILE_CACHE_DIR``
    unset → None, nothing installed, ``maybe_warm`` stays an identity
    function (byte-identical behavior, pinned)."""
    import os

    e = os.environ if env is None else env
    addrs = cache_addrs_from_env(e)
    store_dir = (e.get(COMPILE_CACHE_DIR_ENV) or "").strip()
    if not addrs and not store_dir:
        return None
    if not store_dir:
        from tpucfn.compilecache.store import default_store_dir

        store_dir = default_store_dir()
    device_kind, jax_version = runtime_identity()
    store = ArtifactStore(store_dir, device_kind=device_kind,
                          jax_version=jax_version)
    client = CompileCacheClient(
        store, addrs, device_kind=device_kind, jax_version=jax_version,
        registry=registry, tracer=tracer, probe=probe)
    set_default_client(client)
    return client


# -- fingerprinting ---------------------------------------------------------

def _config_fingerprint() -> dict:
    """The jax.config flags that change compiled code.  Anything that
    alters lowering shows up in the StableHLO hash already; these are
    the compile-time knobs that do not."""
    import jax

    out = {}
    for flag in ("jax_enable_x64", "jax_default_matmul_precision",
                 "jax_threefry_partitionable", "jax_debug_nans",
                 "jax_disable_jit"):
        try:
            out[flag] = repr(getattr(jax.config, flag))
        except AttributeError:
            continue
    return out


def lowered_fingerprint(lowered, *, label: str = "") -> str:
    """The content-addressed key of one lowered-but-not-compiled
    program.  Computed pre-compile: StableHLO text hash (covers avals,
    shardings, donation, and the computation itself), mesh/backend
    identity, jax + jaxlib versions, and compile-relevant config."""
    hlo = lowered.as_text()
    device_kind, jax_version = runtime_identity()
    import jax

    components = {
        "hlo_sha256": hashlib.sha256(hlo.encode()).hexdigest(),
        "device_kind": device_kind,
        "versions": jax_version,
        "backend": jax.default_backend(),
        "num_devices": jax.device_count(),
        "config": _config_fingerprint(),
        "label": label,
    }
    return cache_key(components)


# -- AOT (de)serialization --------------------------------------------------

def serialize_compiled(compiled) -> bytes | None:
    """One self-describing payload for a ``Compiled`` executable, or
    None when this backend/jax build cannot serialize (the caller then
    simply skips publishing)."""
    import pickle

    from jax.experimental.serialize_executable import serialize

    payload, in_tree, out_tree = serialize(compiled)
    return pickle.dumps({"v": 1, "exe": payload,
                         "in_tree": in_tree, "out_tree": out_tree})


def deserialize_compiled(payload: bytes, meta: dict):
    import pickle

    from jax.experimental.serialize_executable import deserialize_and_load

    obj = pickle.loads(payload)
    if not isinstance(obj, dict) or obj.get("v") != 1:
        raise ValueError("unknown compile-cache payload format")
    return deserialize_and_load(obj["exe"], obj["in_tree"],
                                obj["out_tree"])


# -- the wrapper ------------------------------------------------------------

def _avals_signature(args: tuple, kwargs: dict) -> tuple:
    """Hashable (shape, dtype) tree signature of one call — what keys
    the per-wrapper executable memo (bucketed serve prefills get one
    entry per bucket, the trainer exactly one)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    return (treedef,
            tuple((getattr(x, "shape", None),
                   str(getattr(x, "dtype", type(x).__name__)))
                  for x in leaves))


class WarmJit:
    """Callable wrapper over one ``jax.jit`` result that routes each
    new avals-signature through the artifact cache.  Thread-safe; any
    warm-path failure disables the wrapper (plain jit from then on) —
    degradation is always to the exact same program."""

    def __init__(self, jitted, client: CompileCacheClient, *,
                 label: str = ""):
        self._jit = jitted
        self.client = client
        self.label = label
        self._compiled: dict[tuple, Any] = {}
        # Steady-state fast path: while exactly ONE shape bucket exists
        # (the trainer's every-step case), dispatch straight to its
        # executable — the per-call tree_flatten signature walk is paid
        # only while buckets are still being discovered.  An AOT
        # executable validates input avals BEFORE running (donation
        # included), raising TypeError on a new bucket, which routes
        # back through the slow path.
        self._fast: Any = None
        self._lock = threading.Lock()
        self._disabled = False

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def _cache_size(self) -> int:
        """Resolved-executable count, the duck-type the
        ``jit_cache_programs`` gauge reads (obs.metrics ``jit_sources``):
        warm buckets live in ``_compiled``, plus whatever the underlying
        jit compiled itself on the degraded path."""
        try:
            n = int(self._jit._cache_size())
        except Exception:  # noqa: BLE001 — gauge is best-effort
            n = 0
        return n + len(self._compiled)

    def _warm(self, args, kwargs):
        lowered = self._jit.lower(*args, **kwargs)
        key = lowered_fingerprint(lowered, label=self.label)
        result, _outcome = self.client.get_or_compile(
            key,
            lambda: lowered.compile(),
            serialize_fn=_serialize_or_none,
            deserialize_fn=deserialize_compiled,
            label=self.label)
        return result

    def __call__(self, *args, **kwargs):
        if self._disabled:
            return self._jit(*args, **kwargs)
        fast = self._fast
        if fast is not None:
            try:
                return fast(*args, **kwargs)
            except TypeError:
                # different avals than the known bucket: this wrapper is
                # multi-bucket (or the caller erred) — drop the fast
                # path for good, the signature walk handles both.
                self._fast = None
        try:
            sig = _avals_signature(args, kwargs)
        except Exception:  # noqa: BLE001 — unhashable call shape
            self._disabled = True
            return self._jit(*args, **kwargs)
        compiled = self._compiled.get(sig)
        if compiled is None:
            with self._lock:
                compiled = self._compiled.get(sig)
                if compiled is None:
                    try:
                        compiled = self._warm(args, kwargs)
                    except Exception:  # noqa: BLE001 — degrade, bit-identical
                        self._disabled = True
                        return self._jit(*args, **kwargs)
                    self._compiled[sig] = compiled
                self._fast = (compiled if len(self._compiled) == 1
                              else None)
        return compiled(*args, **kwargs)


def _serialize_or_none(compiled) -> bytes | None:
    try:
        return serialize_compiled(compiled)
    except Exception:  # noqa: BLE001 — backend cannot serialize: no publish
        return None


def maybe_warm(jitted, *, label: str = "",
               client: CompileCacheClient | None = None):
    """The one integration point: wrap ``jitted`` in the artifact-cache
    warm path when a client is configured, return it UNCHANGED when not
    (``TPUCFN_COMPILE_CACHE_ADDRS``/``_DIR`` absent ⇒ byte-identical
    behavior — pinned by test_compilecache)."""
    c = client if client is not None else get_default_client()
    if c is None:
        return jitted
    return WarmJit(jitted, c, label=label)
