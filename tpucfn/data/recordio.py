"""MXNet RecordIO — read (and write, for round-trips) the reference's
on-disk dataset format.

The reference's entire data story was RecordIO: ``im2rec`` packed images
into ``.rec`` files which were staged from S3 and read by MXNet's
``ImageRecordIter`` (SURVEY.md §2.1 "S3 data staging", §3.2). A
reference user switching to tpucfn brings those ``.rec`` files along;
``tpucfn convert-dataset --kind recordio`` re-packs them into tpurecord
shards once, after which the normal streaming/decode path applies.

Format (MXNet ``src/io/recordio``-compatible, reimplemented from the
published format constants — no MXNet code consulted):

* stream of records, each: ``uint32 magic = 0xced7230a``, ``uint32
  lrec`` (upper 3 bits: continuation flag, lower 29: payload length),
  ``payload``, zero-padding to a 4-byte boundary.
* image payloads (``im2rec``/``mx.recordio.pack``) start with IRHeader:
  ``uint32 flag; float32 label; uint64 id; uint64 id2`` (little-endian,
  24 bytes). ``flag > 0`` means the scalar label is replaced by ``flag``
  float32 label values following the header. The rest is the encoded
  (usually JPEG) image, passed through untouched — decode stays on the
  training host exactly like the image-tree path.

Multi-part records (continuation flag != 0, used by MXNet for >512MB
payloads) are refused loudly rather than silently mis-parsed.
"""

from __future__ import annotations

import struct
from pathlib import Path
from typing import Iterator

import numpy as np

from tpucfn.data.records import write_dataset_shards

_MAGIC = 0xCED7230A
_LEN_MASK = (1 << 29) - 1
_HDR = struct.Struct("<II")  # magic, lrec
_IRHEADER = struct.Struct("<IfQQ")  # flag, label, id, id2


def read_recordio(path: str | Path) -> Iterator[bytes]:
    """Yield each record's raw payload from a ``.rec`` file, streaming —
    im2rec datasets are routinely single multi-GB files, so memory stays
    at one record."""
    with Path(path).open("rb") as f:
        off = 0
        while True:
            hdr = f.read(_HDR.size)
            if not hdr:
                return
            if len(hdr) < _HDR.size:
                raise ValueError(f"{path}: truncated record header at {off}")
            magic, lrec = _HDR.unpack(hdr)
            if magic != _MAGIC:
                raise ValueError(
                    f"{path}: bad magic {magic:#x} at offset {off} — not "
                    "MXNet RecordIO (or corrupt)")
            cflag, length = lrec >> 29, lrec & _LEN_MASK
            if cflag:
                raise NotImplementedError(
                    f"{path}: multi-part record (continuation flag {cflag}) "
                    f"at offset {off} — payloads over 2^29 bytes are not "
                    "supported")
            pad = -length % 4
            body = f.read(length + pad)
            if len(body) < length + pad:
                # Covers truncation inside the payload AND inside the
                # trailing zero-padding — a file cut mid-padding is just
                # as corrupt and must fail as loudly (ADVICE r4).
                raise ValueError(f"{path}: truncated payload at {off}")
            yield body[:length]
            off += _HDR.size + length + pad


def write_recordio(path: str | Path, payloads: Iterator[bytes]) -> None:
    """Write payloads as a ``.rec`` file (round-trip/testing aid and a
    migration escape hatch back toward MXNet tooling)."""
    with Path(path).open("wb") as f:
        for p in payloads:
            if len(p) > _LEN_MASK:
                raise NotImplementedError(
                    f"payload of {len(p)} bytes exceeds the single-part "
                    "limit (2^29 - 1)")
            f.write(_HDR.pack(_MAGIC, len(p)))
            f.write(p)
            f.write(b"\x00" * (-len(p) % 4))


def pack_image_record(label: float | list[float], data: bytes,
                      rec_id: int = 0) -> bytes:
    """IRHeader + encoded image bytes (the ``mx.recordio.pack`` layout)."""
    labels = np.atleast_1d(np.asarray(label, np.float32))
    if labels.size == 1:
        return _IRHEADER.pack(0, float(labels[0]), rec_id, 0) + data
    return (_IRHEADER.pack(labels.size, 0.0, rec_id, 0)
            + labels.tobytes() + data)


def unpack_image_record(payload: bytes) -> tuple[np.ndarray, bytes]:
    """(label vector float32, encoded image bytes) from an image record."""
    if len(payload) < _IRHEADER.size:
        raise ValueError(f"record of {len(payload)} bytes is shorter than "
                         "an IRHeader")
    flag, label, _id, _id2 = _IRHEADER.unpack_from(payload, 0)
    off = _IRHEADER.size
    if flag:
        labels = np.frombuffer(payload, np.float32, count=flag, offset=off)
        off += 4 * flag
    else:
        labels = np.asarray([label], np.float32)
    return labels, payload[off:]


def iter_recordio_images(src: str | Path) -> Iterator[dict]:
    """Examples ({"image": encoded bytes, "label": int32}) from one
    ``.rec`` file or a directory of them — the same example schema as
    :func:`convert.iter_image_tree`, so the downstream decode/augment
    path is shared."""
    src = Path(src)
    files = sorted(src.glob("*.rec")) if src.is_dir() else [src]
    if not files:
        raise FileNotFoundError(f"no .rec files under {src}")
    for f in files:
        for i, payload in enumerate(read_recordio(f)):
            labels, data = unpack_image_record(payload)
            if labels.size != 1 or labels[0] != int(labels[0]):
                # Multi-label / float-label records exist (detection
                # boxes, soft labels); silently keeping labels[0] would
                # produce wrong training data. Refuse loudly — the
                # pack/unpack API handles these for custom pipelines.
                raise NotImplementedError(
                    f"{f} record {i}: label vector {labels.tolist()} is "
                    "not a single integer class — convert-dataset "
                    "--kind recordio only maps classification records; "
                    "use read_recordio/unpack_image_record directly for "
                    "custom label schemas")
            yield {
                "image": np.frombuffer(data, dtype=np.uint8),
                "label": np.int32(labels[0]),
            }


def convert_recordio(
    src: str | Path, out_dir: str | Path, *, num_shards: int,
    prefix: str = "data",
) -> list[Path]:
    """``.rec`` file(s) → tpurecord shards of encoded images."""
    return write_dataset_shards(iter_recordio_images(src), Path(out_dir),
                                num_shards=num_shards, prefix=prefix)
