"""Control-plane race test (SURVEY.md §5 race-detection row): concurrent
CLI-style invocations against the same state file must never tear the
JSON or lose clusters."""

import json
import threading

from tpucfn.provision import FakeControlPlane, Provisioner
from tpucfn.spec import ClusterSpec


def test_concurrent_creates_do_not_corrupt_state(tmp_path):
    state = str(tmp_path / "cp.json")
    n_threads = 8
    errs = []

    def worker(i):
        try:
            cp = FakeControlPlane(steps_to_provision=1, state_file=state)
            prov = Provisioner(cp)
            prov.create(ClusterSpec(name=f"c-{i}", accelerator="v4-16"))
        except Exception as e:  # noqa: BLE001
            errs.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs

    # every concurrent create survives (mutations are read-modify-write
    # transactions under one flock — no lost updates), each as a
    # fully-formed record
    raw = json.loads((tmp_path / "cp.json").read_text())
    assert set(raw["clusters"]) == {f"c-{i}" for i in range(n_threads)}
    for rec in raw["clusters"].values():
        assert rec["state"] in {"ACTIVE", "QUEUED", "PROVISIONING"}
        ClusterSpec.from_json(rec["spec"])  # parse round-trip

    # a fresh reader sees a coherent world
    cp = FakeControlPlane(state_file=state)
    for name in raw["clusters"]:
        cp.describe(name)


def test_reader_never_sees_torn_write(tmp_path):
    state = str(tmp_path / "cp.json")
    cp = FakeControlPlane(steps_to_provision=1, state_file=state)
    Provisioner(cp).create(ClusterSpec(name="base", accelerator="v4-16"))
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        while not stop.is_set():
            c = FakeControlPlane(steps_to_provision=1, state_file=state)
            try:
                Provisioner(c).create(ClusterSpec(name=f"w-{i}", accelerator="cpu-8"))
            except ValueError:  # name collision after reload — fine
                pass
            i += 1

    def reader():
        while not stop.is_set():
            try:
                c = FakeControlPlane(state_file=state)
                c.describe("base")
            except Exception as e:  # noqa: BLE001
                errs.append(e)

    ts = [threading.Thread(target=writer), threading.Thread(target=reader)]
    for t in ts:
        t.start()
    import time

    time.sleep(1.0)
    stop.set()
    for t in ts:
        t.join()
    assert not errs, errs[:3]
