"""End-to-end straggler-eviction drill (ISSUE 7 acceptance): the
STRAGGLER→SOLO_RESTART row is on by default but gated by the
StragglerGuard — a host that flaps (brief lag episodes that recover)
under the flap budget is never evicted, while sustained lag past the
hysteresis window earns a targeted solo restart and the run finishes
clean.

Stdlib-only workers (no jax import) so the drill measures the
eviction plane, not interpreter+XLA startup.  Own slow-marked file on
purpose: stacked multi-second drills flake on this container (see
runs/tier1_durations.txt discipline).
"""

import json
import os
import sys
import time
from pathlib import Path

import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.ft import (
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
    StragglerGuard,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.obs import MetricRegistry

pytestmark = pytest.mark.slow


def _contract(tmp_path, n=2) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


# Host 0 beats an advancing step and exits once `done` appears (or after
# the cap).  Host 1's behavior comes from FT_STRAG_MODE:
#   lag  — beat step=1 forever (sustained straggle; a relaunch beats
#          caught-up, writes `done`, exits 0)
#   flap — two brief lag episodes (shorter than the hysteresis), each
#          followed by catching up to host 0's step, then run caught-up
#          until `done`-time; never evicted, exits 0
WORKER = r"""
import json, os, pathlib, sys, time
d = os.environ['TPUCFN_FT_DIR']; h = int(os.environ['TPUCFN_HOST_ID'])
mode = os.environ['FT_STRAG_MODE']
os.makedirs(d, exist_ok=True)
fd = pathlib.Path(os.environ['FLAG_DIR'])
seq = 0
def beat(step):
    global seq
    seq += 1
    with open(f'{d}/hb-host{h:03d}.jsonl', 'a') as f:
        f.write(json.dumps({'host_id': h, 'pid': os.getpid(),
                            'step': step, 't': time.time(),
                            'seq': seq}) + '\n')
def h0_step():
    try:
        lines = open(f'{d}/hb-host000.jsonl').read().splitlines()
        return json.loads(lines[-1])['step']
    except Exception:
        return 1
if h == 0:
    t_end = time.time() + 20
    i = 0
    while time.time() < t_end:
        i += 1
        beat(100 + i)
        if (fd / 'done').exists():
            sys.exit(0)
        time.sleep(0.05)
    sys.exit(1)
# -- host 1 --
if (fd / 'second_1').exists():
    beat(h0_step())          # relaunched: caught up
    (fd / 'done').write_text('x')
    sys.exit(0)
fd.joinpath('second_1').write_text('x')
if mode == 'lag':
    t_end = time.time() + 20
    while time.time() < t_end:
        beat(1)
        time.sleep(0.05)
    sys.exit(1)
# flap mode: two sub-hysteresis lag episodes, recovery in between,
# then a caught-up tail; host 1 itself ends the run (it was never
# evicted, so no relaunch exists to do it)
for cycle in range(2):
    t_end = time.time() + 0.35
    while time.time() < t_end:
        beat(1)
        time.sleep(0.05)
    t_end = time.time() + 0.6
    while time.time() < t_end:
        beat(h0_step())
        time.sleep(0.05)
t_end = time.time() + 0.3
while time.time() < t_end:
    beat(h0_step())
    time.sleep(0.05)
(fd / 'done').write_text('x')
sys.exit(0)
"""


def _run(tmp_path, mode):
    ft_dir = tmp_path / "ft"
    os.environ["FLAG_DIR"] = str(tmp_path)
    os.environ["FT_STRAG_MODE"] = mode
    try:
        registry = MetricRegistry()
        launcher = Launcher(_contract(tmp_path), LocalTransport(),
                            ft_dir=str(ft_dir), ft_heartbeat_s=0.05)
        coord = GangCoordinator(
            launcher, [sys.executable, "-c", WORKER],
            policy=GangRestart(RestartBudget(2)),
            monitor=HeartbeatMonitor(
                ft_dir, expected_hosts=2,
                config=MonitorConfig(interval_s=0.05,
                                     startup_grace_s=10.0,
                                     straggler_step_lag=20)),
            registry=registry, ft_dir=ft_dir, poll_interval=0.01,
            term_grace_s=0.5,
            straggler_guard=StragglerGuard(hysteresis_s=0.8,
                                           flap_budget=3))
        t0 = time.monotonic()
        rc = coord.run()
        wall = time.monotonic() - t0
    finally:
        del os.environ["FLAG_DIR"], os.environ["FT_STRAG_MODE"]
    events = [json.loads(s) for s in
              (ft_dir / "events.jsonl").read_text().splitlines()]
    return rc, wall, registry.varz()["metrics"], events


def test_sustained_lag_past_hysteresis_is_evicted(tmp_path):
    """In `done`-gated mode, only the eviction lets the run finish: the
    straggler's relaunch is what writes `done` — rc 0 proves the
    eviction happened AND the solo restart rejoined the gang."""
    rc, wall, m, events = _run(tmp_path, "lag")
    assert rc == 0
    assert wall < 15
    assert m["ft_straggler_evictions_total"] == 1
    assert m["ft_solo_restarts_total"] == 1
    assert m["ft_gang_restarts_total"] == 0
    detect = next(e for e in events if e["kind"] == "detect")
    assert detect["failures"][0]["kind"] == "straggler"
    assert detect["failures"][0]["host"] == 1
    decide = next(e for e in events if e["kind"] == "decide")
    assert decide["action"] == "solo_restart" and decide["hosts"] == [1]
    solo = next(e for e in events if e["kind"] == "solo_launch")
    assert solo["host"] == 1


def test_flap_under_budget_is_never_evicted(tmp_path):
    """Two brief lag episodes (0.35s each, hysteresis 0.8s, budget 3):
    flaps are tolerated, nothing restarts, both hosts exit clean."""
    rc, wall, m, events = _run(tmp_path, "flap")
    assert rc == 0
    assert m["ft_straggler_evictions_total"] == 0
    assert m["ft_solo_restarts_total"] == 0
    assert m["ft_restarts_total"] == 0
    assert not any(e["kind"] == "detect" for e in events), \
        "a flap under the budget must not even open an incident"
