"""Synthetic dataset generators.

The reference staged real CIFAR-10/ImageNet from S3 (SURVEY.md §2.1); this
zero-egress build environment cannot download them, so convergence smoke
tests and benchmarks run on deterministic synthetic data with the same
shapes/dtypes/label cardinality. The staging path (``write_dataset_shards``
→ ``ShardedDataset``) is identical to what a real dataset would use — only
the bytes differ; point ``write_dataset_shards`` at a real decoder to stage
the real thing.

The synthetic task is *learnable* (class-conditional means) so loss curves
actually discriminate working training from broken training.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def _class_conditional_images(
    n: int, hw: int, classes: int, seed: int
) -> Iterator[dict[str, np.ndarray]]:
    rs = np.random.RandomState(seed)
    # Fixed per-class mean patterns; examples are mean + noise.
    protos = rs.randn(classes, hw, hw, 3).astype(np.float32)
    for _ in range(n):
        y = int(rs.randint(classes))
        x = protos[y] * 0.5 + rs.randn(hw, hw, 3).astype(np.float32) * 0.5
        yield {"image": x.astype(np.float32), "label": np.int32(y)}


def synthetic_cifar10(n: int = 1024, seed: int = 0) -> Iterator[dict[str, np.ndarray]]:
    """CIFAR-10-shaped (32×32×3, 10 classes) learnable synthetic stream."""
    return _class_conditional_images(n, 32, 10, seed)


def synthetic_imagenet(
    n: int = 256, seed: int = 0, image_size: int = 224, classes: int = 1000
) -> Iterator[dict[str, np.ndarray]]:
    """ImageNet-shaped (224×224×3, 1000 classes) synthetic stream."""
    return _class_conditional_images(n, image_size, classes, seed)
