import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpucfn import collectives as col


def _shmap(mesh, fn, in_specs, out_specs):
    # check_vma=False: several collectives (all_gather) produce values that
    # are replicated in fact but conservatively marked varying by the VMA
    # inference; the tests assert the numerics instead.
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    )


def test_psum_over_data(mesh_dp8):
    f = _shmap(mesh_dp8, lambda x: col.psum(x, "data"), P("data"), P())
    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(out, np.full((1,), 28.0))


def test_pmean_matches_manual(mesh_dp8):
    f = _shmap(mesh_dp8, lambda x: col.pmean(x, "data"), P("data"), P())
    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(out, [3.5])


def test_all_gather_tiled(mesh_dp8):
    def fn(x):
        g = col.all_gather(x, "data")
        return g * 0 + g  # shape check happens via out_specs

    f = _shmap(mesh_dp8, fn, P("data"), P())
    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(out, np.arange(8.0))


def test_reduce_scatter_is_psum_shard(mesh_dp8):
    x = jnp.tile(jnp.arange(8.0)[None], (8, 1))  # each shard holds arange(8)

    def fn(xs):  # xs: (1, 8) per shard
        return col.reduce_scatter(xs[0], "data")  # -> (1,) per shard

    f = _shmap(mesh_dp8, fn, P("data", None), P("data"))
    out = f(x)
    np.testing.assert_allclose(out, np.arange(8.0) * 8)


def test_ring_permute_rotates(mesh_dp8):
    f = _shmap(mesh_dp8, lambda x: col.ring_permute(x, "data"), P("data"), P("data"))
    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_ring_permute_negative_shift(mesh_dp8):
    f = _shmap(
        mesh_dp8, lambda x: col.ring_permute(x, "data", shift=-1), P("data"), P("data")
    )
    out = f(jnp.arange(8.0))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), -1))


def test_all_to_all_transposes_shard_axis(mesh_dp8):
    # Each shard starts with a (8, 2) slab; all_to_all over split_axis=0
    # redistributes so shard i holds row i of every source shard.
    x = jnp.arange(8 * 8 * 2, dtype=jnp.float32).reshape(8, 8, 2)

    def fn(xs):  # (1, 8, 2) per shard
        return col.all_to_all(xs, "data", split_axis=1, concat_axis=0)

    f = _shmap(mesh_dp8, fn, P("data"), P("data"))
    out = f(x)
    # shard i's output stacks chunk i of every source shard j: out[i, j] = x[j, i]
    ref = np.transpose(np.asarray(x), (1, 0, 2)).reshape(64, 1, 2)
    np.testing.assert_allclose(out, ref)


def test_axis_index_size(mesh_dp8):
    def fn(x):
        return x * 0 + col.axis_index("data") + col.axis_size("data") * 10

    f = _shmap(mesh_dp8, fn, P("data"), P("data"))
    np.testing.assert_allclose(f(jnp.zeros(8)), np.arange(8) + 80)
