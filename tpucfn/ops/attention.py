"""Attention numerics — the reference implementation every kernel is
tested against.

The reference never owned attention math (it launched MXNet/TF scripts);
BASELINE configs 3-4 (BERT, Llama) make it the hot op here. This module is
the straightforward XLA path: one batched matmul pair the MXU loves, fp32
softmax for bf16 stability. The Pallas flash/ring kernels in
:mod:`tpucfn.kernels` must match it to tolerance (SURVEY.md §7.4 item 3).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA: expand KV heads to match query heads. (B, S, Hkv, D) -> (B, S, Hkv*n_rep, D)."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d
    )


NEG_INF = -1e30  # finite mask value: keeps max/exp nan-free for empty rows


def dot_product_attention_with_lse(
    q: jax.Array,  # (B, Sq, Hq, D)
    k: jax.Array,  # (B, Sk, Hkv, D)
    v: jax.Array,  # (B, Sk, Hkv, D)
    *,
    causal: bool = True,
    mask: jax.Array | None = None,  # broadcastable to (B, Hq, Sq, Sk); True = attend
    q_offset: int | jax.Array = 0,  # global position of q[0] (ring/SP shards)
    k_offset: int | jax.Array = 0,
) -> tuple[jax.Array, jax.Array]:
    """Returns (out (B,Sq,Hq,D), lse (B,Sq,Hq)). Softmax in fp32.

    The log-sum-exp output is what lets ring attention merge per-hop
    partial results exactly (online-softmax combining); rows that attend
    to nothing yield out = 0 and lse = NEG_INF.
    """
    orig_dtype = q.dtype
    hq, hkv = q.shape[2], k.shape[2]
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    k = _repeat_kv(k, hq // hkv)
    v = _repeat_kv(v, hq // hkv)

    scale = q.shape[-1] ** -0.5
    # (B, H, Sq, Sk)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale

    if causal:
        sq, sk = q.shape[1], k.shape[1]
        qpos = jnp.arange(sq)[:, None] + q_offset
        kpos = jnp.arange(sk)[None, :] + k_offset
        logits = jnp.where((qpos >= kpos)[None, None], logits, NEG_INF)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)

    m = jnp.max(logits, axis=-1)  # (B, H, Sq); NEG_INF for empty rows
    probs = jnp.where(logits > NEG_INF / 2, jnp.exp(logits - m[..., None]), 0.0)
    l = jnp.sum(probs, axis=-1)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    out = out / jnp.maximum(l, 1.0e-30).transpose(0, 2, 1)[..., None]
    out = jnp.where((l > 0).transpose(0, 2, 1)[..., None], out, 0.0)
    return out.astype(orig_dtype), lse.transpose(0, 2, 1)  # lse -> (B, Sq, Hq)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    mask: jax.Array | None = None,
    q_offset: int | jax.Array = 0,
    k_offset: int | jax.Array = 0,
) -> jax.Array:
    """Returns (B, Sq, Hq, D); see :func:`dot_product_attention_with_lse`."""
    out, _ = dot_product_attention_with_lse(
        q, k, v, causal=causal, mask=mask, q_offset=q_offset, k_offset=k_offset
    )
    return out
