"""Deterministic toy trainer for the disaggregated-input e2e drill
(ISSUE 11).

Numpy-only (no jax, no checkpoints — the input plane is orthogonal to
both): consumes its batch stream through
``service_or_local_batches`` — the service client with failover and
degrade-to-local when ``TPUCFN_INPUT_ADDRS`` is fanned out, the plain
local loader otherwise — and folds every batch into an exactly
deterministic trajectory (``w ← 0.9·w + mean(batch.x)``) appended to a
per-host JSONL.  Two runs agree bit-for-bit iff they consumed the same
batch sequence, which is the drill's whole point: killing the input
host mid-run must not change the numbers, only the ``data_wait``
goodput bucket.

The LOCAL dataset carries a per-example sleep 'decode' while the
service streams pre-decoded batches — the input-bound shape from the
bench record, in miniature.
"""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from tpucfn.data.pipeline import ShardedDataset  # noqa: E402
from tpucfn.data.service import service_or_local_batches  # noqa: E402
from tpucfn.ft import HeartbeatWriter  # noqa: E402
from tpucfn.obs.goodput import GoodputLedger  # noqa: E402


class _SleepDecode:
    """Value-preserving synthetic decode cost (consumes no RNG, so the
    served stream — which skips it — stays bit-identical)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def __call__(self, ex, rs):
        if self.seconds > 0:
            time.sleep(self.seconds)
        return ex


def main() -> int:
    host = int(os.environ.get("TPUCFN_HOST_ID", "0"))
    trainers = int(os.environ["TPUCFN_WORKERS_COUNT"])
    run_dir = Path(os.environ["INPUT_E2E_RUN_DIR"])
    shards_dir = Path(os.environ["INPUT_E2E_SHARDS"])
    batch = int(os.environ.get("INPUT_E2E_BATCH", "8"))
    seed = int(os.environ.get("INPUT_E2E_SEED", "0"))
    epochs = int(os.environ.get("INPUT_E2E_EPOCHS", "1"))
    step_sleep = float(os.environ.get("INPUT_E2E_STEP_SLEEP", "0.05"))
    decode_sleep = float(os.environ.get("INPUT_E2E_DECODE_SLEEP", "0.004"))
    ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()

    hb = None
    if ft_dir:
        hb = HeartbeatWriter(
            ft_dir, host_id=host, role="trainer",
            interval_s=float(
                os.environ.get("TPUCFN_FT_HEARTBEAT_S", "0.2") or 0.2)
        ).start()
    ledger = GoodputLedger(run_dir / "goodput", host_id=host,
                           role="trainer")
    run_dir.mkdir(parents=True, exist_ok=True)
    mode = {"used_service": False, "degraded": False, "reason": ""}

    def on_degrade(reason: str) -> None:
        mode["degraded"] = True
        mode["reason"] = reason
        # wall-clock stamp: the gray-failure drill rc-gates the
        # fault-injection -> degradation detection latency against it
        mode["degraded_ts"] = time.time()
        print(f"degraded to local loading: {reason}", flush=True)

    ds = ShardedDataset(
        sorted(shards_dir.glob("*.tpurec")),
        batch_size_per_process=batch, seed=seed,
        process_index=host, process_count=trainers,
        transform=_SleepDecode(decode_sleep))
    mode["used_service"] = bool(
        (os.environ.get("TPUCFN_INPUT_ADDRS") or "").strip())
    stream = service_or_local_batches(ds, num_epochs=epochs,
                                      on_degrade=on_degrade)
    losses = run_dir / f"losses-host{host:03d}.jsonl"
    w = 10.0
    step = 0
    try:
        with open(losses, "a") as f:
            while True:
                t0_wait = time.monotonic()
                b = next(stream, None)
                t_wait = time.monotonic() - t0_wait
                if b is None:
                    break
                step += 1
                if t_wait >= 1e-4:
                    ledger.account("data_wait", t_wait, step=step)
                t0_step = time.monotonic()
                w = 0.9 * w + float(np.mean(b["x"]))
                f.write(json.dumps({"step": step, "w": w}) + "\n")
                f.flush()
                if hb is not None:
                    hb.update_step(step)
                time.sleep(step_sleep)
                ledger.account("step", time.monotonic() - t0_step,
                               step=step)
    finally:
        close_stream = getattr(stream, "close", None)
        if close_stream is not None:
            close_stream()
        (run_dir / f"mode-host{host:03d}.json").write_text(
            json.dumps({**mode, "steps": step}))
        if hb is not None:
            hb.stop()
        ledger.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
