"""Auto dense↔flash dispatch + block autotuner (VERDICT r2 item 3).

CPU CI note: the dispatch policy requires a TPU backend, so these tests
monkeypatch the backend probe and run the kernel in interpret mode —
the policy logic and the numerics equivalence are what is under test.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpucfn.kernels import auto as auto_mod
from tpucfn.kernels import flash_autotune
from tpucfn.kernels.flash_attention import _choose_blocks
from tpucfn.models.llama import Llama, LlamaConfig
from tpucfn.ops.attention import dot_product_attention


def test_policy_is_dense_on_cpu():
    assert not auto_mod.should_use_flash(1 << 20)


def test_policy_threshold(monkeypatch):
    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "512")
    assert auto_mod.should_use_flash(512)
    assert not auto_mod.should_use_flash(511)
    assert not auto_mod.should_use_flash(4096, causal=False)
    assert not auto_mod.should_use_flash(4096, mask=jnp.ones((1, 1, 4, 4)))


def test_llama_auto_dispatch_matches_dense(monkeypatch):
    """attention_fn=None + forced-TPU policy: the flash path (interpret)
    must reproduce the dense default exactly (fwd and grads)."""
    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "16")
    monkeypatch.setenv("TPUCFN_FLASH_UNTUNED_MIN_S", "16")

    cfg = LlamaConfig.tiny()
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 32)),
                       jnp.int32)
    auto_model = Llama(cfg)                                  # None = auto
    dense_model = Llama(cfg, attention_fn=dot_product_attention)
    params = dense_model.init(jax.random.key(0), toks)["params"]

    out_auto = auto_model.apply({"params": params}, toks)
    out_dense = dense_model.apply({"params": params}, toks)
    np.testing.assert_allclose(np.asarray(out_auto), np.asarray(out_dense),
                               atol=2e-4)

    g_auto = jax.grad(lambda p: jnp.sum(
        auto_model.apply({"params": p}, toks) ** 2))(params)
    g_dense = jax.grad(lambda p: jnp.sum(
        dense_model.apply({"params": p}, toks) ** 2))(params)
    np.testing.assert_allclose(
        np.asarray(g_auto["layers"]["attn"]["q_proj"]["kernel"]),
        np.asarray(g_dense["layers"]["attn"]["q_proj"]["kernel"]), atol=5e-4)


def test_untuned_device_kind_warns_once(tmp_path, monkeypatch):
    """A TPU device kind with ZERO flash-tune table entries gets a
    one-time warning when a shape lands in the silent dense-fallback
    zone [flash_threshold, untuned_flash_min_s) — the round-4 UNet
    regression class made discoverable (ADVICE r5)."""
    import warnings as _warnings

    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "32")
    monkeypatch.setenv("TPUCFN_FLASH_UNTUNED_MIN_S", "4096")
    monkeypatch.setenv("TPUCFN_FLASH_TUNE_CACHE", str(tmp_path / "t.json"))
    # Empty merged table: pretend the builtin table doesn't exist either.
    monkeypatch.setattr(flash_autotune, "_MEM_CACHE", {})
    monkeypatch.setattr(auto_mod, "_warned_untuned_kinds", set())

    with pytest.warns(UserWarning, match="no flash-tune table entries"):
        assert not auto_mod.should_use_flash(64, d=64, dtype=jnp.bfloat16)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")  # a second warning would raise
        assert not auto_mod.should_use_flash(64, d=64, dtype=jnp.bfloat16)
    # Past the untuned boundary the zone doesn't apply: flash, no warning.
    assert auto_mod.should_use_flash(8192, d=64, dtype=jnp.bfloat16)


def test_tuned_device_kind_does_not_warn(monkeypatch):
    """Any entry for the CURRENT device kind silences the zero-entry
    warning even when the specific family being asked about is untuned
    (per-family silence is normal operation, not a config gap)."""
    import warnings as _warnings

    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "32")
    monkeypatch.setenv("TPUCFN_FLASH_UNTUNED_MIN_S", "4096")
    kind = jax.devices()[0].device_kind
    monkeypatch.setattr(
        flash_autotune, "_MEM_CACHE",
        {f"{kind}|causal|128|128|bfloat16": (128, 128, 1.5)})
    monkeypatch.setattr(auto_mod, "_warned_untuned_kinds", set())
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        assert not auto_mod.should_use_flash(64, d=64, dtype=jnp.bfloat16)


def test_llama_auto_stays_dense_below_threshold(monkeypatch):
    """Below the threshold the resolved fn must be the dense op (no
    kernel involvement at all) — checked via the policy function."""
    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "1024")
    assert not auto_mod.should_use_flash(32)
    # and the static-zero dispatcher takes the dense branch
    q = jnp.zeros((1, 32, 2, 16))
    out = auto_mod.auto_attention_static_zero(q, q, q, causal=True)
    ref = dot_product_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))


def test_ring_auto_hops(monkeypatch):
    """hop_attention='auto' with the policy forced on: ring result still
    equals full attention (flash hops), and with the policy off it
    equals the dense-hop path (trivially the same numbers)."""
    from tpucfn.kernels import make_ring_attention
    from tpucfn.mesh import MeshSpec, build_mesh

    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "8")
    monkeypatch.setenv("TPUCFN_FLASH_UNTUNED_MIN_S", "8")

    mesh = build_mesh(MeshSpec(context=4, data=2))
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(2, 64, 4, 16), jnp.float32)
    k = jnp.asarray(rs.randn(2, 64, 2, 16), jnp.float32)
    v = jnp.asarray(rs.randn(2, 64, 2, 16), jnp.float32)

    att = make_ring_attention(mesh)  # hop_attention defaults to "auto"
    out = att(q, k, v, causal=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    # policy off (threshold above S_loc): auto must resolve to dense
    # hops — assert the flash kernel is genuinely NOT invoked (output
    # comparison alone can't tell, both paths agree to tolerance).
    import sys

    # NB: `import tpucfn.kernels.flash_attention` binds the FUNCTION
    # (kernels/__init__ re-exports shadow the submodule attribute);
    # go through sys.modules for the module object.
    fa = sys.modules["tpucfn.kernels.flash_attention"]

    def boom(*a, **k):
        raise AssertionError("flash path taken despite policy off")

    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "4096")
    monkeypatch.setattr(fa, "flash_attention_with_lse", boom)
    out_dense = make_ring_attention(mesh)(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(ref),
                               atol=2e-4)


def test_autotuner_tune_lookup_and_block_choice(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUCFN_FLASH_TUNE_CACHE", str(tmp_path / "tune.json"))
    monkeypatch.setattr(flash_autotune, "_MEM_CACHE", None)

    res = flash_autotune.tune(
        128, 32, heads=2, kv_heads=2, dtype=jnp.float32,
        candidates=((16, 16), (32, 32)), iters=1, include_bwd=False)
    assert res["best"] in ((16, 16), (32, 32))
    assert all("total_ms" in r or "error" in r for r in res["rows"])

    # persisted + visible to lookup and to the kernel's block chooser
    monkeypatch.setattr(flash_autotune, "_MEM_CACHE", None)  # force re-read
    assert flash_autotune.lookup(128, 32, jnp.float32, True) == res["best"]
    assert flash_autotune.lookup(100, 32, jnp.float32, True) == res["best"], \
        "S buckets to the next power of two"
    assert _choose_blocks(128, 32, jnp.float32, True) == res["best"]
    assert _choose_blocks(128, 64, jnp.float32, True) == (128, 128), \
        "different D must not hit the cached entry"

    # env override beats the tuned table
    monkeypatch.setenv("TPUCFN_FLASH_BLOCK_Q", "64")
    assert _choose_blocks(128, 32, jnp.float32, True) == (64, 128)

    raw = json.loads((tmp_path / "tune.json").read_text())
    assert list(raw.values())[0] == list(res["best"])


def test_builtin_tune_table_layering(tmp_path, monkeypatch):
    """The packaged flash_tune_builtin.json seeds defaults; a user's own
    cache overrides per key."""
    from tpucfn.kernels import flash_autotune as fa

    monkeypatch.setenv("TPUCFN_FLASH_TUNE_CACHE", str(tmp_path / "user.json"))
    monkeypatch.setattr(fa, "_MEM_CACHE", None)
    table = fa._load()
    key = "TPU v5 lite|causal|8192|128|bfloat16"
    # blocks measured on chip round 3; speedup vs dense recorded round 5
    assert table[key][:2] == (256, 512)
    assert table[key][2] == 15.11

    (tmp_path / "user.json").write_text(json.dumps({key: [128, 128]}))
    monkeypatch.setattr(fa, "_MEM_CACHE", None)
    assert fa._load()[key] == (128, 128)
    # ...and the builtin speedup is honestly dropped (different blocks,
    # the old measurement doesn't apply)
    assert fa.lookup_speedup(8192, 128, jnp.bfloat16, True) is None \
        or fa._load()[key][2:] == ()

    # A LEGACY user entry agreeing with the builtin blocks keeps the
    # builtin measured speedup (must not flip a measured-winning family
    # back to the no-evidence rule).
    (tmp_path / "user.json").write_text(json.dumps({key: [256, 512]}))
    monkeypatch.setattr(fa, "_MEM_CACHE", None)
    assert fa._load()[key] == (256, 512, 15.11)


def test_full_attention_auto_dispatch_policy(monkeypatch):
    """Non-causal dispatch: flash only when BOTH sides clear the
    threshold (spatial self-attention yes, 77-key cross attention no)."""
    import jax.numpy as jnp

    calls = []
    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "2048")
    # this test pins the BOTH-SIDES-LONG rule; drop the untuned-family
    # guard out of the way (tested separately below)
    monkeypatch.setenv("TPUCFN_FLASH_UNTUNED_MIN_S", "2048")

    import importlib

    # the package re-exports flash_attention (the function) over the
    # submodule attribute — resolve the module through importlib
    fa = importlib.import_module("tpucfn.kernels.flash_attention")

    def spy_flash(q, k, v, **kw):
        calls.append(("flash", q.shape[1], k.shape[1]))
        return jnp.zeros(q.shape, q.dtype)

    dense_mod = importlib.import_module("tpucfn.ops.attention")

    def spy_dense(q, k, v, **kw):
        calls.append(("dense", q.shape[1], k.shape[1]))
        return jnp.zeros(q.shape, q.dtype)

    monkeypatch.setattr(fa, "flash_attention", spy_flash)
    monkeypatch.setattr(dense_mod, "dot_product_attention", spy_dense)

    q4k = jnp.zeros((1, 4096, 8, 40))
    ctx = jnp.zeros((1, 77, 8, 40))
    q1k = jnp.zeros((1, 1024, 8, 40))
    auto_mod.full_attention_auto(q4k, q4k, q4k)       # long self -> flash
    auto_mod.full_attention_auto(q4k, ctx, ctx)       # 77-key cross -> dense
    auto_mod.full_attention_auto(q1k, q1k, q1k)       # short self -> dense
    assert calls == [("flash", 4096, 4096), ("dense", 4096, 77),
                     ("dense", 1024, 1024)]


def test_dispatch_consults_measured_speedup(tmp_path, monkeypatch):
    """VERDICT r4 #5: dispatch is measurement-backed per (S, D, dtype)
    family — tuned-and-losing falls back to dense, tuned-and-winning
    takes flash, never-measured takes flash only past the untuned
    threshold (the round-4 D=40 UNet regression guard)."""
    monkeypatch.setattr(auto_mod, "_backend", lambda: "tpu")
    monkeypatch.setenv("TPUCFN_FLASH_MIN_S", "1024")
    monkeypatch.setenv("TPUCFN_FLASH_TUNE_CACHE", str(tmp_path / "t.json"))
    monkeypatch.setattr(flash_autotune, "_MEM_CACHE", None)
    kind = jax.devices()[0].device_kind
    (tmp_path / "t.json").write_text(json.dumps({
        f"{kind}|causal|2048|64|float32": [128, 128, 0.9],   # losing
        f"{kind}|causal|4096|64|float32": [256, 256, 1.8],   # winning
        f"{kind}|full|4096|64|float32": [128, 128, 0.95],    # losing
    }))
    assert not auto_mod.should_use_flash(2048, d=64, dtype=jnp.float32)
    assert auto_mod.should_use_flash(4096, d=64, dtype=jnp.float32)
    assert not auto_mod.should_use_flash_full(4096, 4096, d=64,
                                              dtype=jnp.float32)
    # untuned family: dense below the untuned threshold, flash above
    assert not auto_mod.should_use_flash(4096, d=40, dtype=jnp.float32)
    assert auto_mod.should_use_flash(8192, d=40, dtype=jnp.float32)
    assert not auto_mod.should_use_flash_full(4096, 4096, d=40,
                                              dtype=jnp.float32)
    # d-less legacy callers keep the pure length rule
    assert auto_mod.should_use_flash(2048)


def test_tune_records_dense_speedup(tmp_path, monkeypatch):
    """tune() with include_bwd measures XLA dense at the same shape and
    persists the ratio; lookup_speedup surfaces it to the dispatch."""
    monkeypatch.setenv("TPUCFN_FLASH_TUNE_CACHE", str(tmp_path / "tune.json"))
    monkeypatch.setattr(flash_autotune, "_MEM_CACHE", None)
    res = flash_autotune.tune(128, 32, heads=2, kv_heads=2,
                              dtype=jnp.float32, candidates=((32, 32),),
                              iters=1)
    assert res["speedup_vs_dense"] is not None
    monkeypatch.setattr(flash_autotune, "_MEM_CACHE", None)
    assert (flash_autotune.lookup_speedup(128, 32, jnp.float32, True)
            == res["speedup_vs_dense"])
    # blocks lookup still works on the 3-field entry
    assert flash_autotune.lookup(128, 32, jnp.float32, True) == (32, 32)
