"""Recovery policies: what to do about a detected failure, and at what
cost.

The decision layer between detection (ft/heartbeat.py, process exit
codes) and action (ft/coordinator.py).  Three pieces:

* :class:`RestartBudget` — how many recoveries a run is allowed, and the
  exponential-backoff-with-jitter delay before each one.  Jitter comes
  from a ``random.Random`` the caller seeds (no wall-clock randomness:
  the same seed replays the same delays, which is what makes the chaos
  harness deterministic).
* A **decision table** — failure class → action, overridable per policy
  (the per-failure-class table from ISSUE 4: a crash is not a hang is
  not a straggler — and, since ISSUE 7, a preemption notice is not a
  failure at all).
* :class:`GangRestart` / :class:`SoloRestart` — the two recovery shapes
  for a TPU gang.  A TPU slice runs one SPMD program, so the safe
  default is gang restart: kill all, relaunch all, resume from the
  latest checkpoint.  Solo restart (restart only the dead host into the
  same gang) is the cheaper path for harnesses whose ranks are loosely
  coupled (data-parallel CPU rigs, serving fleets) — it falls back to a
  gang restart when multiple hosts fail at once.
* :class:`StragglerGuard` — the hysteresis window + per-host flap
  budget that makes the STRAGGLER→SOLO_RESTART row safe to have on by
  default (ISSUE 7): a brief lag episode that recovers before the
  window elapses is a *flap*, tolerated up to the budget; sustained lag
  past the window — or a chronic flapper over budget — is evicted.
"""

from __future__ import annotations

import dataclasses
import enum
import random
import time
from typing import Callable, Iterable

# -- graceful-degradation contract (ISSUE 7) -------------------------------
#
# These live here (the ft plane's jax-free decision layer) because both
# sides of each contract need them and only one side may import jax:
# the ckpt manager / trainer (jax side) and the GangCoordinator +
# stdlib-only chaos workers (must stay importable without jax).

# Exit code a rank uses when an EXISTING checkpoint failed to restore
# (corruption, truncation).  Distinguishable from a generic crash so the
# coordinator can retry from the previous finalized step instead of
# crash-looping the same corrupt artifact into give_up.
RESTORE_FAILED_RC = 77

# Env var fanned out by the coordinator on a checkpoint-corruption retry:
# comma-separated step numbers the relaunched ranks' CheckpointManager
# must treat as nonexistent for latest-step/restore selection.
CKPT_BLACKLIST_ENV = "TPUCFN_CKPT_BLACKLIST"


def format_ckpt_blacklist(steps: Iterable[int]) -> str:
    return ",".join(str(s) for s in sorted(set(int(s) for s in steps)))


def parse_ckpt_blacklist(value: str | None) -> frozenset[int]:
    """Tolerant parse of the env value — a garbled entry is skipped, not
    raised on (a wrong blacklist must degrade to a smaller blacklist,
    never to a crashed resume path)."""
    out = set()
    for part in (value or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.add(int(part))
        except ValueError:
            continue
    return frozenset(out)


class FailureKind(enum.Enum):
    CLEAN_EXIT = "clean_exit"  # rc == 0 — not a failure; never burns budget
    CRASH = "crash"            # process exited nonzero (or was killed)
    HANG = "hang"              # process alive but heartbeats went DEAD
    STRAGGLER = "straggler"    # alive, beating, but step-lagging the fleet
    PREEMPT = "preempt"        # advance notice: host will be taken away


class Action(enum.Enum):
    NONE = "none"
    SOLO_RESTART = "solo_restart"
    GANG_RESTART = "gang_restart"
    # Proactive drain (ISSUE 7): force-save through the ckpt layer, stop
    # the gang cleanly, relaunch as a PLANNED restart — zero lost work,
    # no budget consumed.
    DRAIN_RESTART = "drain_restart"
    GIVE_UP = "give_up"


@dataclasses.dataclass(frozen=True)
class Failure:
    host_id: int
    kind: FailureKind
    rc: int | None = None      # exit code for CRASH/CLEAN_EXIT
    step: int | None = None    # last heartbeat step, when known
    detail: str = ""
    lead_s: float | None = None  # PREEMPT only: advance-notice seconds


@dataclasses.dataclass(frozen=True)
class Decision:
    action: Action
    hosts: tuple[int, ...] = ()  # SOLO_RESTART victims; empty = whole gang
    delay_s: float = 0.0
    reason: str = ""
    # True for restarts the fleet chose to make (preemption drain):
    # they burn no budget and must not read as downtime regressions.
    planned: bool = False


# action each failure class earns by default; CLEAN_EXIT is observe-only.
# STRAGGLER→SOLO_RESTART is on by default since ISSUE 7 — safe because
# the coordinator routes straggler verdicts through a StragglerGuard
# (hysteresis + flap budget) before they ever reach decide().
# PREEMPT→DRAIN_RESTART turns an advance notice into a proactive drain.
DEFAULT_DECISION_TABLE: dict[FailureKind, Action] = {
    FailureKind.CLEAN_EXIT: Action.NONE,
    FailureKind.CRASH: Action.GANG_RESTART,
    FailureKind.HANG: Action.GANG_RESTART,
    FailureKind.STRAGGLER: Action.SOLO_RESTART,
    FailureKind.PREEMPT: Action.DRAIN_RESTART,
}


class RestartBudget:
    """``max_restarts`` recoveries, exponential backoff + jitter between.

    Delay before restart ``k`` (0-based over *consumed* restarts)::

        min(backoff_s * multiplier**k, max_backoff_s) * (1 + U(-j, +j))

    ``backoff_s=0`` disables delays entirely (the unit-test path).  The
    budget is only consumed for actual recoveries — a clean exit after
    prior restarts must not burn a slot (ISSUE 4 satellite: exit-cause
    accounting).
    """

    def __init__(self, max_restarts: int, *, backoff_s: float = 0.0,
                 multiplier: float = 2.0, max_backoff_s: float = 60.0,
                 jitter: float = 0.1, rng: random.Random | None = None):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if not 0.0 <= jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.max_restarts = max_restarts
        self.backoff_s = float(backoff_s)
        self.multiplier = float(multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self.rng = rng if rng is not None else random.Random(0)
        self.used = 0

    @property
    def remaining(self) -> int:
        return max(0, self.max_restarts - self.used)

    def next_delay(self) -> float:
        """The delay the *next* restart would wait (no state change)."""
        if self.backoff_s <= 0.0:
            return 0.0
        base = min(self.backoff_s * self.multiplier ** self.used,
                   self.max_backoff_s)
        if self.jitter:
            base *= 1.0 + self.rng.uniform(-self.jitter, self.jitter)
        return base

    def consume(self) -> bool:
        """Take one restart slot; False when the budget is exhausted."""
        if self.used >= self.max_restarts:
            return False
        self.used += 1
        return True


class RecoveryPolicy:
    """decide(failures) → Decision; owns the budget and the table."""

    name = "base"

    def __init__(self, budget: RestartBudget,
                 table: dict[FailureKind, Action] | None = None):
        self.budget = budget
        self.table = dict(DEFAULT_DECISION_TABLE)
        if table:
            self.table.update(table)

    def _restart_shape(self, actionable: list[Failure]) -> Action:
        raise NotImplementedError

    def decide(self, failures: list[Failure]) -> Decision:
        acts = {id(f): self.table.get(f.kind, Action.NONE) for f in failures}
        drains = [f for f in failures
                  if acts[id(f)] is Action.DRAIN_RESTART]
        actionable = [f for f in failures
                      if acts[id(f)] not in (Action.NONE,
                                             Action.DRAIN_RESTART)]
        if not actionable:
            if drains:
                # A preemption notice with no real failure alongside it is
                # a PLANNED restart: decided before the budget/give-up
                # check on purpose — an exhausted budget must not turn an
                # orderly drain into a give_up, and the drain never
                # consumes a slot (ISSUE 7 budget semantics).
                hosts = tuple(sorted(f.host_id for f in drains))
                return Decision(
                    Action.DRAIN_RESTART, hosts=hosts, planned=True,
                    reason=f"preemption notice for host(s) {hosts}: "
                           "proactive drain + planned restart "
                           "(budget untouched)")
            kinds = ",".join(sorted({f.kind.value for f in failures})) or "none"
            return Decision(Action.NONE, reason=f"table: no action for {kinds}")
        # A real failure arriving with a notice wins: the restart it earns
        # relaunches the preempted host anyway (or shrinks past it).
        if all(acts[id(f)] is Action.SOLO_RESTART for f in actionable):
            # Every actionable failure's table row names SOLO_RESTART
            # (the straggler-eviction row): eviction is inherently
            # targeted, so the per-kind action pins the shape instead of
            # the policy class — a GangRestart fleet still evicts one
            # straggler solo rather than bouncing the whole gang.
            shape = Action.SOLO_RESTART
        else:
            shape = self._restart_shape(actionable)
        # Delay is drawn before consume so it reflects the restart being
        # paid for (restart k waits multiplier**k), and only when the
        # budget actually has a slot (a drawn-then-refused delay would
        # desync the seeded jitter stream between runs that exhaust at
        # different points).
        if self.budget.remaining == 0:
            if all(f.kind is FailureKind.STRAGGLER for f in actionable):
                # An eviction is an optimization, not a rescue: a gang
                # whose only problem is a slow-but-progressing host must
                # never be killed over it.  Out of budget, stragglers
                # degrade to observe-only instead of give_up.
                return Decision(
                    Action.NONE,
                    reason="straggler eviction skipped: restart budget "
                           "exhausted (observe-only)")
            return Decision(
                Action.GIVE_UP,
                reason=f"restart budget exhausted "
                       f"({self.budget.max_restarts} used)")
        delay = self.budget.next_delay()
        self.budget.consume()
        hosts = tuple(sorted(f.host_id for f in actionable))
        if shape is Action.SOLO_RESTART:
            return Decision(Action.SOLO_RESTART, hosts=hosts, delay_s=delay,
                            reason=f"solo restart of host(s) {hosts} "
                                   f"({self.budget.used}/"
                                   f"{self.budget.max_restarts})")
        return Decision(Action.GANG_RESTART, delay_s=delay,
                        reason=f"gang restart for host(s) {hosts} "
                               f"({self.budget.used}/"
                               f"{self.budget.max_restarts})")


class GangRestart(RecoveryPolicy):
    """Kill all, relaunch all, resume from the latest checkpoint — the
    only safe shape when the ranks form one SPMD program (a TPU slice's
    collectives wedge the moment one participant is gone)."""

    name = "gang"

    def _restart_shape(self, actionable: list[Failure]) -> Action:
        return Action.GANG_RESTART


class SoloRestart(RecoveryPolicy):
    """Restart only the dead host back into the same gang (same host_id,
    same env: obs port, heartbeat file).  Correct only for loosely
    coupled ranks; multiple simultaneous failures escalate to a gang
    restart (correlated death usually means the gang state is gone)."""

    name = "solo"

    def _restart_shape(self, actionable: list[Failure]) -> Action:
        if len(actionable) == 1:
            return Action.SOLO_RESTART
        return Action.GANG_RESTART


class StragglerGuard:
    """Hysteresis + flap budget in front of the STRAGGLER→SOLO_RESTART
    row (ISSUE 7): decides when a lag verdict is allowed to become an
    eviction.

    Per host, a contiguous run of straggler observations is an
    *episode*.  :meth:`observe` returns True (fire the eviction) exactly
    once per episode, when either

    * the episode has lasted ``hysteresis_s`` — sustained lag, or
    * the episode STARTS with the host already over its flap budget —
      a chronic flapper whose brief episodes keep dodging the window.

    An episode that ends (the host returns to LIVE) before firing is a
    *flap* and consumes one unit of the budget; the hysteresis window
    re-arms on every return to LIVE.  All timing comes from the
    injectable ``clock`` so every threshold is pinned with fakes.

    The caller only reports LIVE/STRAGGLER transitions — a SUSPECT host
    (stale beat) freezes the episode rather than ending it, so don't
    call :meth:`observe` for it.  :meth:`reset` forgets a host entirely
    (call it when the host is relaunched: a fresh incarnation starts
    with a fresh budget).
    """

    def __init__(self, *, hysteresis_s: float = 30.0, flap_budget: int = 3,
                 clock: Callable[[], float] = time.monotonic):
        if hysteresis_s < 0:
            raise ValueError(f"hysteresis_s must be >= 0, got {hysteresis_s}")
        if flap_budget < 0:
            raise ValueError(f"flap_budget must be >= 0, got {flap_budget}")
        self.hysteresis_s = float(hysteresis_s)
        self.flap_budget = int(flap_budget)
        self.clock = clock
        self._since: dict[int, float] = {}   # host → episode start
        self._fired: set[int] = set()        # episode already evicted
        self.flaps: dict[int, int] = {}      # host → consumed flap budget

    def observe(self, host_id: int, straggling: bool,
                now: float | None = None) -> bool:
        now = self.clock() if now is None else now
        if not straggling:
            if host_id in self._since and host_id not in self._fired:
                # episode ended before the window elapsed: a flap
                self.flaps[host_id] = self.flaps.get(host_id, 0) + 1
            self._since.pop(host_id, None)
            self._fired.discard(host_id)
            return False
        if host_id in self._fired:
            return False  # once per episode; the restart resets us
        if host_id not in self._since:
            self._since[host_id] = now
            if self.flaps.get(host_id, 0) >= self.flap_budget:
                self._fired.add(host_id)
                return True  # over-budget flapper: no more grace
            return False
        if now - self._since[host_id] >= self.hysteresis_s:
            self._fired.add(host_id)
            return True
        return False

    def reset(self, host_id: int) -> None:
        self._since.pop(host_id, None)
        self._fired.discard(host_id)
        self.flaps.pop(host_id, None)

    def reset_all(self) -> None:
        self._since.clear()
        self._fired.clear()
        self.flaps.clear()


POLICIES = {GangRestart.name: GangRestart, SoloRestart.name: SoloRestart}


def policy_from_name(name: str, budget: RestartBudget,
                     table: dict[FailureKind, Action] | None = None
                     ) -> RecoveryPolicy:
    try:
        cls = POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown ft policy {name!r}; choose from {sorted(POLICIES)}"
        ) from None
    return cls(budget, table)
