"""Metric registry + Prometheus text exposition.

Every instrument (Counter/Gauge/Summary from ``obs.metrics`` plus the
bucketed :class:`Histogram` below) lives under one per-process
:class:`MetricRegistry` so a single scrape surface can expose them all —
the fleet story the JSONL files alone cannot tell (ISSUE 2: dashboards
and scrapers, not tailing 64 JSONL files).  The registry renders the
`Prometheus text exposition format
<https://prometheus.io/docs/instrumenting/exposition_formats/>`_
(``# HELP`` / ``# TYPE`` headers, ``_bucket{le=...}`` cumulative
histograms, ``{quantile=...}`` summaries) and a JSON ``/varz`` snapshot;
``obs.server`` serves both over per-host HTTP.
"""

from __future__ import annotations

import bisect
import math
import re
import threading

from tpucfn.obs.metrics import (Counter, ComputedGauge, Gauge, Summary,
                                nearest_rank)

# Latency-flavored defaults (seconds): sub-ms to tens of seconds, the
# span of a TTFT or a training step on real hardware.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_OK = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class Histogram:
    """Bucketed distribution with exact count/sum — the Prometheus
    histogram: fixed upper bounds chosen up front, O(#buckets) memory
    forever, mergeable across hosts by plain addition (which is what the
    ``tpucfn obs`` aggregator does).  Complements :class:`Summary`:
    summaries give exact recent percentiles per host but cannot be
    aggregated across the fleet; histograms can."""

    def __init__(self, name: str = "",
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        bs = tuple(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(f"bucket bounds must strictly increase: {bs}")
        if math.isinf(bs[-1]):
            bs = bs[:-1]  # +Inf is implicit (the overflow bucket)
        self.name = name
        self.bounds = bs
        self.count = 0
        self.sum = 0.0
        self._counts = [0] * (len(bs) + 1)  # last = overflow (+Inf)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)  # le semantics: v <= bound
        with self._lock:
            self.count += 1
            self.sum += v
            self._counts[i] += 1

    def read(self) -> tuple[list[tuple[float, int]], int, float]:
        """``((upper_bound, cumulative_count) pairs with +Inf last,
        count, sum)`` — all read under ONE lock acquisition so the
        Prometheus invariant ``_count == _bucket{le="+Inf"}`` holds even
        while another thread observes (a scrape that copied buckets,
        then read count separately, could expose count > +Inf)."""
        with self._lock:
            counts = list(self._counts)
            count, total = self.count, self.sum
        out, running = [], 0
        for b, c in zip(self.bounds, counts):
            running += c
            out.append((b, running))
        out.append((math.inf, running + counts[-1]))
        return out, count, total

    def cumulative(self) -> list[tuple[float, int]]:
        """The ``_bucket{le=...}`` series alone (see :meth:`read`)."""
        return self.read()[0]

    def snapshot(self) -> dict:
        cum, count, total = self.read()
        return {"count": count, "sum": total,
                "buckets": {("+Inf" if math.isinf(b) else repr(b)): c
                            for b, c in cum}}


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(float(v))


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\"")
                     .replace("\n", r"\n"))
        for k, v in labels.items())
    return "{%s}" % body


class MetricRegistry:
    """Name → instrument, with get-or-create constructors and one
    exposition surface.

    ``labels`` are constant labels stamped on every exposed series —
    per-host identity (``host``, ``role``) lives here, so fleet scrapes
    can tell 64 hosts' series apart without 64 metric names.  Each
    registry is independent; :func:`default_registry` is the per-process
    shared one that the trainer, the serving frontend, and the HTTP
    endpoint all meet at (pass an explicit registry for isolation, as
    tests and benches do).
    """

    def __init__(self, labels: dict[str, str] | None = None):
        for k in (labels or {}):
            if not _LABEL_OK.match(k):
                raise ValueError(f"invalid label name {k!r}")
        self.labels = dict(labels or {})
        self._metrics: dict[str, object] = {}
        self._help: dict[str, str] = {}
        self._lock = threading.Lock()

    # -- registration ------------------------------------------------------
    def register(self, name: str, metric, help: str = ""):
        """Adopt an existing instrument under ``name`` (the path by which
        ``ServingMetrics`` publishes its already-constructed dashboard).
        Re-registering the same name requires the same object — two
        owners silently splitting one series is the bug this raises on."""
        name = sanitize_metric_name(name)
        with self._lock:
            prev = self._metrics.get(name)
            if prev is not None and prev is not metric:
                raise ValueError(
                    f"metric {name!r} already registered to a different "
                    f"{type(prev).__name__}")
            self._metrics[name] = metric
            if help:
                self._help[name] = help
        return metric

    def _get_or_create(self, name: str, cls, help: str, factory):
        name = sanitize_metric_name(name)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} is a {type(m).__name__}, "
                        f"not a {cls.__name__}")
                return m
            m = factory(name)
            self._metrics[name] = m
            if help:
                self._help[name] = help
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help, Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help, Gauge)

    def computed_gauge(self, name: str, fn, help: str = "") -> ComputedGauge:
        """Gauge whose value is ``fn()`` at read time.  Get-or-create
        like every other instrument, but the callback is rebound on
        every call: when a component is rebuilt against a shared
        registry (a new ``Server`` on ``default_registry()``), the LIVE
        object's state must back the series, not the dead one's."""
        g = self._get_or_create(name, ComputedGauge, help,
                                lambda n: ComputedGauge(n, fn))
        g._fn = fn
        return g

    def summary(self, name: str, help: str = "", *, keep: int = 4096) -> Summary:
        s = self._get_or_create(name, Summary, help,
                                lambda n: Summary(n, keep=keep))
        if s._keep != keep:
            # Same no-silent-splitting stance as register(): returning an
            # instrument whose reservoir differs from what the caller
            # asked for would misconfigure their percentiles invisibly.
            raise ValueError(
                f"summary {name!r} exists with keep={s._keep}, "
                f"requested keep={keep}")
        return s

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        h = self._get_or_create(name, Histogram, help,
                                lambda n: Histogram(n, buckets=buckets))
        want = tuple(float(b) for b in buckets)
        if want and math.isinf(want[-1]):
            want = want[:-1]
        if h.bounds != want:
            raise ValueError(
                f"histogram {name!r} exists with bounds {h.bounds}, "
                f"requested {want} — bucket bounds cannot change after "
                "creation")
        return h

    def metrics(self) -> dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    # -- exposition --------------------------------------------------------
    def to_prometheus(self) -> str:
        """The text exposition body ``GET /metrics`` returns."""
        lines: list[str] = []
        for name, m in sorted(self.metrics().items()):
            help_ = self._help.get(name)
            if help_:
                lines.append(f"# HELP {name} {help_}")
            base = _fmt_labels(self.labels)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name}{base} {_fmt_value(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name}{base} {_fmt_value(m.value)}")
            elif isinstance(m, Summary):
                lines.append(f"# TYPE {name} summary")
                count, total, xs = m.read()  # one lock: count/sum coherent
                for p in (50.0, 95.0, 99.0):
                    v = nearest_rank(xs, p)
                    if v is not None:
                        q = {**self.labels, "quantile": repr(p / 100.0)}
                        lines.append(f"{name}{_fmt_labels(q)} {_fmt_value(v)}")
                lines.append(f"{name}_sum{base} {_fmt_value(total)}")
                lines.append(f"{name}_count{base} {_fmt_value(count)}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {name} histogram")
                cum, count, total = m.read()  # one lock: _count == +Inf
                for b, c in cum:
                    le = {**self.labels, "le": _fmt_value(b)}
                    lines.append(
                        f"{name}_bucket{_fmt_labels(le)} {_fmt_value(c)}")
                lines.append(f"{name}_sum{base} {_fmt_value(total)}")
                lines.append(f"{name}_count{base} {_fmt_value(count)}")
            else:  # pragma: no cover - register() accepts any instrument
                continue
        return "\n".join(lines) + "\n"

    def varz(self) -> dict:
        """JSON-able snapshot of every instrument — the ``/varz`` body
        and the per-host dict the aggregator merges."""
        out: dict[str, object] = {}
        for name, m in sorted(self.metrics().items()):
            if isinstance(m, (Counter, Gauge)):
                out[name] = m.value
            elif isinstance(m, (Summary, Histogram)):
                out[name] = m.snapshot()
        return {"labels": dict(self.labels), "metrics": out}


def sanitize_metric_name(name: str) -> str:
    """Coerce ``name`` into a legal Prometheus metric name (invalid
    chars → ``_``; leading digit gets a ``_`` prefix)."""
    if _NAME_OK.match(name):
        return name
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


_default_lock = threading.Lock()
_default: MetricRegistry | None = None


def default_registry() -> MetricRegistry:
    """The per-process shared registry (created on first use).  Hosts
    stamp their identity on it lazily via :func:`set_default_labels`
    once the cluster contract is known."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricRegistry()
        return _default


def set_default_labels(**labels: str) -> MetricRegistry:
    reg = default_registry()
    reg.labels.update({k: str(v) for k, v in labels.items()})
    return reg
