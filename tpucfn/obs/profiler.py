"""Profiling hooks.

The reference exposed no profiling story at all (delegated to nvprof/
framework profilers, undocumented — SURVEY.md §5). tpucfn makes a step-
range trace a launcher flag: traces capture XLA op timelines *and* ICI
collective overlap, viewable in TensorBoard/XProf.
"""

from __future__ import annotations

import contextlib
from pathlib import Path

import jax


def start_profiler_server(port: int = 9012) -> None:
    """Start the per-host profiler server so XProf/TensorBoard can attach
    a live capture to any host in the fleet (the launcher calls this when
    ``--profile-server`` is set)."""
    jax.profiler.start_server(port)


@contextlib.contextmanager
def profile_steps(log_dir: str | Path, *, enabled: bool = True):
    """Trace everything inside the context into ``log_dir`` (one trace per
    host). Use around a small steady-state step range, not the whole run —
    the first steps are compilation."""
    if not enabled:
        yield
        return
    d = Path(log_dir)
    d.mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(d))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
