"""Continuous-batching decode scheduler.

The serving throughput lever on TPU is the SCHEDULER, not the kernel
(PAPERS.md: the Gemma-on-TPU serving writeup and the Podracer
architectures both win at the batching layer): keep a fixed-shape decode
batch full by admitting new prefills the moment slots and KV blocks free
up, and retire finished sequences in place instead of draining the whole
batch (the static-batch failure mode, where one long request holds B-1
finished slots hostage).

Shape discipline (the TPU-specific part): every jitted engine entry
point runs at a FIXED shape — decode always at ``max_batch`` slots, and
each prefill padded to a power-of-two length bucket capped at the cache
capacity (the same next-pow2 family rule as
``kernels/flash_autotune._bucket``), so steady state compiles
``len(buckets) + 1`` programs total and never again.  Admission control
(queue caps, deadlines, 429s) lives one layer up in
``serve/frontend.py``; this module decides only WHAT RUNS NEXT.

Preemption: when the block pool runs dry mid-decode, the youngest
running sequence is evicted (blocks freed, sequence re-queued at the
front of the waiting line) and later recomputed from its full prefix —
prompt plus everything it had generated.  Greedy decode makes the
recompute token-identical; sampled requests resume from a fresh rng fold
(documented, not hidden).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from collections import deque

from tpucfn.serve.kvcache import KVCacheManager, OutOfBlocksError

# Smallest prefill bucket: below this, padding waste beats recompiles.
MIN_PREFILL_BUCKET = 16


def prefill_bucket(n: int, cache_len: int,
                   min_bucket: int = MIN_PREFILL_BUCKET) -> int:
    """Padded prefill length for an ``n``-token prefix: next power of two
    from ``min_bucket``, capped at the cache capacity (a bucket longer
    than the cache would trip the decode model's overflow poisoning).
    One compile per bucket — the flash-autotune S-bucket rule applied to
    serving shapes."""
    if n > cache_len:
        raise ValueError(f"prefix of {n} tokens exceeds cache_len {cache_len}")
    b = min_bucket
    while b < n:
        b *= 2
    return min(b, cache_len)


class SequenceState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    EXPIRED = "expired"   # deadline passed before completion


@dataclasses.dataclass
class Sequence:
    """One in-flight generation.  ``prompt`` is immutable; ``generated``
    grows one token per decode step.  After a preemption the re-prefill
    prefix is ``prompt + generated`` (recompute, not cache migration)."""

    seq_id: int
    prompt: list[int]
    max_new_tokens: int
    temperature: float = 0.0
    deadline: float | None = None   # absolute time.monotonic() cutoff
    arrival: float = 0.0
    generated: list[int] = dataclasses.field(default_factory=list)
    state: SequenceState = SequenceState.WAITING
    preemptions: int = 0

    @property
    def prefix(self) -> list[int]:
        return self.prompt + self.generated

    @property
    def last_token(self) -> int:
        return self.generated[-1] if self.generated else self.prompt[-1]

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.generated)


@dataclasses.dataclass
class PrefillWork:
    """Run one bucketed prefill and sample the sequence's first token."""
    seq: Sequence
    slot: int
    bucket: int


@dataclasses.dataclass
class DecodeWork:
    """Run one decode iteration over every running slot."""
    slots: dict[int, Sequence]  # slot -> sequence, all reserved for +1 token


class ContinuousBatchingScheduler:
    """FCFS admission, prefill-priority interleave, preempt-on-full.

    The engine owns ``max_batch`` physical decode slots; this class owns
    which sequence occupies each slot and whether the next engine call is
    a prefill (a slot and the prompt's KV blocks are available — filling
    the batch beats another decode iteration for every queued request's
    TTFT) or a decode iteration over everything running.
    """

    def __init__(self, kv: KVCacheManager, *, max_batch: int, cache_len: int,
                 eos_id: int | None = None,
                 min_bucket: int = MIN_PREFILL_BUCKET):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.kv = kv
        self.max_batch = max_batch
        self.cache_len = cache_len
        self.eos_id = eos_id
        self.min_bucket = min_bucket
        self.waiting: deque[Sequence] = deque()
        self.running: dict[int, Sequence] = {}
        self._free_slots = list(range(max_batch - 1, -1, -1))

    # -- intake ------------------------------------------------------------
    def add(self, seq: Sequence) -> None:
        """Accept a sequence or raise ValueError when it can NEVER run —
        the whole-pool feasibility check that keeps an oversized request
        from starving at the head of the queue forever.  (Queue-depth
        backpressure and deadlines are the frontend's jurisdiction.)"""
        if not seq.prompt:
            raise ValueError("empty prompt")
        if seq.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {seq.max_new_tokens}")
        total = len(seq.prompt) + seq.max_new_tokens
        if total > self.cache_len:
            raise ValueError(
                f"prompt {len(seq.prompt)} + max_new {seq.max_new_tokens} "
                f"exceeds cache_len {self.cache_len}")
        # The last sampled token is never written back, hence total - 1.
        if not self.kv.fits_at_all(total - 1):
            raise ValueError(
                f"request needs {self.kv.blocks_for(total - 1)} KV blocks; "
                f"pool has {self.kv.allocator.num_blocks}")
        seq.state = SequenceState.WAITING
        self.waiting.append(seq)

    # -- deadline sweep ----------------------------------------------------
    def expire(self, now: float | None = None) -> list[Sequence]:
        """Drop every waiting AND running sequence whose deadline has
        passed (a running one frees its slot and blocks — capacity back
        to live traffic immediately).  Returns the casualties; the
        caller completes their requests with a timeout error."""
        now = time.monotonic() if now is None else now
        dead = [s for s in self.waiting
                if s.deadline is not None and now > s.deadline]
        for s in dead:
            self.waiting.remove(s)
            s.state = SequenceState.EXPIRED
        for slot, s in list(self.running.items()):
            if s.deadline is not None and now > s.deadline:
                self._vacate(slot)
                s.state = SequenceState.EXPIRED
                dead.append(s)
        return dead

    # -- the core decision -------------------------------------------------
    def next_work(self) -> PrefillWork | DecodeWork | None:
        """Prefill if a waiting sequence fits (slot + blocks), else one
        decode iteration, else None (idle)."""
        if self._free_slots and self.waiting:
            seq = self.waiting[0]
            if self.kv.can_admit(len(seq.prefix)):
                self.waiting.popleft()
                slot = self._free_slots.pop()
                self.kv.admit(seq.seq_id, len(seq.prefix))
                seq.state = SequenceState.RUNNING
                self.running[slot] = seq
                return PrefillWork(
                    seq, slot,
                    prefill_bucket(len(seq.prefix), self.cache_len,
                                   self.min_bucket))
            # else: blocks are tied up in running sequences; decode below
            # makes progress and will free them (add() guaranteed fit).
        if self.running:
            return DecodeWork(self._reserve_all())
        return None

    def _reserve_all(self) -> dict[int, Sequence]:
        """Reserve the block slot every decode step is about to write
        into (each step caches its INPUT token's K/V — one entry per
        step, last step included), preempting youngest-first whenever
        the pool runs dry.  Oldest sequences reserve first so preemption
        converges: the oldest sequence alone always fits, because add()
        checked the whole pool.  Returns the surviving running map."""
        by_age = sorted(self.running.items(), key=lambda kv_: kv_[1].arrival)
        for slot, seq in by_age:
            if self.running.get(slot) is not seq:
                continue  # preempted by an earlier reservation this round
            while True:
                try:
                    self.kv.reserve_next(seq.seq_id)
                    break
                except OutOfBlocksError:
                    victim_slot, victim = max(
                        self.running.items(),
                        key=lambda kv_: (kv_[1].arrival, kv_[1].seq_id))
                    self.preempt(victim_slot)
                    if victim is seq:
                        break
        return dict(self.running)

    # -- step results ------------------------------------------------------
    def record_prefill(self, slot: int, token: int) -> Sequence | None:
        """First sampled token for a just-prefilled slot.  Returns the
        sequence if it is already finished (max_new=1 or instant EOS)."""
        seq = self.running[slot]
        seq.generated.append(token)
        return self._maybe_retire(slot, token)

    def record_decode(self, slot: int, token: int) -> Sequence | None:
        """One decoded token: charge the cache entry the step wrote (the
        K/V of its INPUT token, covered by this round's reservation),
        append, retire in place when done.  Returns the sequence iff
        finished."""
        seq = self.running[slot]
        self.kv.commit_token(seq.seq_id)
        seq.generated.append(token)
        return self._maybe_retire(slot, token)

    def _maybe_retire(self, slot: int, token: int) -> Sequence | None:
        seq = self.running[slot]
        if (self.eos_id is not None and token == self.eos_id) \
                or len(seq.generated) >= seq.max_new_tokens:
            self._vacate(slot)
            seq.state = SequenceState.FINISHED
            return seq
        return None

    def preempt(self, slot: int) -> Sequence:
        """Evict a running sequence: blocks freed (counted as eviction),
        slot returned, sequence re-queued FIRST so it is recomputed as
        soon as capacity returns (no starvation of preempted work)."""
        seq = self.running[slot]
        self._vacate(slot, evicted=True)
        seq.state = SequenceState.WAITING
        seq.preemptions += 1
        self.waiting.appendleft(seq)
        return seq

    def _vacate(self, slot: int, *, evicted: bool = False) -> None:
        seq = self.running.pop(slot)
        self.kv.release(seq.seq_id, evicted=evicted)
        self._free_slots.append(slot)

    # -- observability -----------------------------------------------------
    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)
