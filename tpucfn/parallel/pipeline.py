"""Pipeline parallelism — GPipe microbatch schedule over the ``pipeline``
mesh axis.

Net-new vs the reference (SURVEY.md §2.3: PP "no" in reference, required
in build). TPU-first formulation: this is SPMD, not a scheduler process —
every stage runs the *same* compiled program; stage identity comes from
``lax.axis_index``. Per tick, each stage applies its layer slice to the
activation it holds and hands the result to its neighbor with a single
``ppermute`` hop (stage boundaries are exactly the outermost-axis neighbor
links, which is why ``pipeline`` is the outermost mesh axis —
tpucfn/mesh/mesh.py).

Two schedules:

* :func:`gpipe` — M + P - 1 forward ticks; reverse-mode AD replays the
  scan backwards, so the activation stash is O(M) (scan mechanics + remat
  inside stage_fn).  Differentiate through it normally.
* :func:`pipeline_1f1b` — one-forward-one-backward: each tick runs a
  forward slot and a backward slot, the head/loss computes on the last
  stage as soon as a microbatch arrives, and cotangents ride the reverse
  ring while later microbatches are still going forward.  The per-stage
  *stage-input* stash is a fixed 2P-1 ring buffer — O(P) in M, which is
  1F1B's point (GPipe+AD stashes O(M) per stage, and that multiplies by
  the layers-per-stage remat boundary).  Two O(M) buffers remain, both
  one-hidden-layer-sized like the batch itself: the pre-embedded inputs
  built outside the region, and the scan-stacked stage-0 input
  cotangents (the embedding backward needs them all).  Fill/drain
  bubble count is the same as GPipe's; see :func:`bubble_fraction`.
  It computes grads itself (manual vjp per slot) rather than being
  transposed by AD.

Both are uniform SPMD: stages compute during bubble ticks too (results
masked) — on SPMD hardware predication saves nothing, uniformity keeps
the program one fused XLA computation.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from tpucfn.mesh import AXIS_PIPELINE

# stage_fn(stage_params, x) -> y, applied by each stage to its microbatch.
StageFn = Callable[[Any, jax.Array], jax.Array]


def gpipe(
    stage_fn: StageFn,
    stage_params: Any,
    microbatches: jax.Array,  # (M, mb, ...) — replicated across the axis
    *,
    axis: str = AXIS_PIPELINE,
    with_aux: bool = False,
):
    """Run ``stage_fn`` as a P-stage pipeline; call inside ``shard_map``.

    ``stage_params`` is this stage's slice (shard the stacked layer dim
    over ``axis``). Returns (M, mb, ...) — the composition of all P stages
    applied to every microbatch, replicated to all stages.

    ``with_aux=True`` changes the stage contract to
    ``stage_fn(params, x) -> (y, aux_scalar)`` (e.g. MoE load-balancing
    losses sown inside the stage) and returns ``(ys, aux)`` where ``aux``
    is the per-microbatch MEAN of the per-stage scalars summed over all
    stages — bubble-tick applications (garbage activations) are masked
    out. The aux accumulator rides the scan carry, so reverse-mode AD
    transposes it like any other carry: gradients of aux flow into stage
    params and activations.

    Activations must keep one shape/dtype through stages (true for
    transformer blocks).
    """
    p = lax.axis_size(axis)
    i = lax.axis_index(axis)
    m = microbatches.shape[0]
    perm = [(j, (j + 1) % p) for j in range(p)]

    # Feed microbatches through the scan as xs (padded with repeats of the
    # last microbatch for the drain ticks) rather than dynamically
    # indexing `microbatches[t]` inside the body: scan's per-tick slicing
    # partitions cleanly, while a data-dependent gather on a batch-sharded
    # operand under a manual pipeline axis trips XLA's SPMD partitioner
    # (spmd_partitioner_util CHECK, observed on CPU XLA 0.9 — and a
    # gather is the wrong op for a static schedule anyway).
    pad = jnp.repeat(microbatches[-1:], p - 1, axis=0)
    injects = jnp.concatenate([microbatches, pad], axis=0)  # (ticks, mb, ...)
    ticks = injects.shape[0]

    def tick(carry, xs):
        # Stage 0 injects this tick's microbatch; other stages consume
        # what arrived from their left neighbor.
        recv, aux_acc, outbuf = carry
        inject, t = xs
        x = jnp.where(i == 0, inject, recv)
        if with_aux:
            y, aux = stage_fn(stage_params, x)
            # Stage i holds microbatch t - i this tick; bubble ticks
            # (fill/drain) compute on garbage and must not contribute.
            m_f = t - i
            valid = (m_f >= 0) & (m_f < m)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        else:
            y = stage_fn(stage_params, x)
        # Microbatch j exits the LAST stage at tick j + p - 1: write it
        # into its slot of the M-sized output buffer. An M-slot carry
        # instead of scan-stacking all M+P-1 tick outputs (the r2 form)
        # drops the fill/drain overstash — every stage still materializes
        # the buffer (uniform SPMD), but it is the batch's own size.
        j = t - (p - 1)
        wmask = ((jnp.arange(m) == j) & (j >= 0) & (j < m) & (i == p - 1))
        outbuf = jnp.where(wmask.reshape((m,) + (1,) * y.ndim), y[None],
                           outbuf)
        send = lax.ppermute(y, axis, perm)
        return (send, aux_acc, outbuf), None

    zero = jnp.zeros_like(microbatches[0])
    carry0 = (zero, jnp.zeros((), jnp.float32),
              jnp.zeros_like(microbatches))
    (_, aux_acc, outbuf), _ = lax.scan(
        tick, carry0, (injects, jnp.arange(ticks)))

    # Non-last stages carried zeros; the psum broadcasts the last
    # stage's finished microbatches to every stage.
    out = lax.psum(outbuf, axis)
    if with_aux:
        return out, lax.psum(aux_acc, axis) / m
    return out


def microbatch(x: jax.Array, num_microbatches: int) -> jax.Array:
    """(B, ...) -> (M, B/M, ...)."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by {num_microbatches} microbatches")
    return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """(M, B/M, ...) -> (B, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def bubble_fraction(num_microbatches: int, num_stages: int,
                    schedule: str = "gpipe", num_virtual: int = 1) -> float:
    """Fraction of stage-ticks wasted in pipeline fill/drain.

    * ``gpipe``: forward-tick accounting, (P-1)/(M+P-1) — same fill/drain
      count as vanilla 1F1B (1F1B's classic win is activation memory,
      O(P) stashed microbatches instead of O(M), not bubble size).
    * ``1f1b``: per-slot accounting over the schedule's actual tick count
      (each tick holds one forward and one backward slot per device).
      Vanilla (V=1): ticks = M + 2(P-1), busy = M per slot →
      2(P-1)/(M+2(P-1)). Interleaved (V>1, the circular flight schedule
      of :func:`pipeline_1f1b` ``num_virtual``): ticks = MV + PV + P - 2,
      busy = MV per slot → (PV+P-2)/(MV+PV+P-2), strictly below the
      vanilla fraction for the same per-device work (V-times-deeper
      stages): V·(M + 2(P-1)) chunk-ticks vs MV + PV + P - 2.

    NOTE on accounting continuity (ADVICE r4): through round 3 the
    ``1f1b`` schedule returned the gpipe forward-tick figure
    (P-1)/(M+P-1); round 4 switched it to per-slot accounting, so 1f1b
    numbers logged by benches/examples before and after are on
    different scales — recompute rather than compare across rounds, and
    compare gpipe↔1f1b only via this function at one version."""
    if num_stages <= 1:
        return 0.0
    m, p, v = num_microbatches, num_stages, num_virtual
    if schedule == "1f1b":
        ticks = m * v + p * v + p - 2
        return (ticks - m * v) / ticks
    return (p - 1) / (m + p - 1)


def interleave_chunks(chunked: Any, num_stages: int, num_virtual: int) -> Any:
    """Execution-order → device-major chunk layout for interleaved 1F1B.

    ``chunked`` leaves have leading dim P·V in EXECUTION order (chunk c
    applies c-th). Chunk c runs on device c mod P, so device i needs the
    non-contiguous set {v·P + i}; reordering to position i·V + v makes
    each device's V chunks contiguous, letting a plain ``P('pipeline')``
    leading-dim sharding hand every device exactly its chunks (local
    leading dim V). :func:`deinterleave_chunks` is the inverse (use it on
    the returned ``dstage_params``)."""
    p, v = num_stages, num_virtual
    idx = jnp.asarray([vv * p + i for i in range(p) for vv in range(v)])
    return jax.tree.map(lambda l: jnp.take(l, idx, axis=0), chunked)


def deinterleave_chunks(stacked: Any, num_stages: int, num_virtual: int) -> Any:
    """Inverse of :func:`interleave_chunks` (device-major → execution
    order)."""
    p, v = num_stages, num_virtual
    idx = jnp.asarray([c % p * v + c // p for c in range(p * v)])
    return jax.tree.map(lambda l: jnp.take(l, idx, axis=0), stacked)


# head_fn(head_params, y, labels) -> scalar loss CONTRIBUTION for one
# microbatch: sum of per-token losses over this (local) shard divided by
# that MICROBATCH's global valid-token count (i.e. the per-microbatch
# mean after psum over any reduce_axes shards). pipeline_1f1b itself
# averages over microbatches (the 1/M scale in its tick loop), so a
# head_fn must NOT divide by the all-microbatch token count — that would
# shrink loss and grads by another factor of M.
HeadFn = Callable[[Any, jax.Array, jax.Array], jax.Array]


def pipeline_1f1b(
    stage_fn: StageFn,
    head_fn: HeadFn,
    stage_params: Any,
    head_params: Any,
    microbatches: jax.Array,  # (M, mb, ...) activations entering stage 0
    labels: jax.Array,        # (M, mb, ...) per-micro loss targets
    *,
    axis: str = AXIS_PIPELINE,
    reduce_axes: tuple[str, ...] = (),
    stage_aux: bool = False,
    head_metrics: bool = False,
    num_virtual: int = 1,
):
    """One-forward-one-backward pipelined loss+grads; call inside
    shard_map (manual over ``axis`` and every ``reduce_axes`` entry).

    ``num_virtual=V > 1`` switches to the INTERLEAVED (virtual-stage /
    circular) schedule: the model is split into P·V chunks, chunk c on
    device c mod P, and each device round-robins its V chunks — the
    Megatron-style bubble lever for small M (see
    :func:`_pipeline_1f1b_interleaved` for the schedule math and the
    changed ``stage_params`` layout contract).

    Returns ``(loss, dstage_params, dhead_params, dmicrobatches)`` where
    the grads are exact for
    ``loss = (1/M) Σ_m head_fn(hp, stages(x_m), l_m)`` — the microbatch
    mean, per the HeadFn contract above (tests assert parity with
    jax.grad of the sequential model).

    ``stage_aux=True`` switches the stage contract to
    ``stage_fn(params, x) -> (y, aux_scalar)``: each stage's aux scalar
    (e.g. its layers' MoE load-balancing losses for that microbatch) is
    added into the loss with the same 1/M microbatch averaging, and its
    gradient flows through the backward slot's vjp (the aux cotangent is
    the constant 1/M), so
    ``loss = (1/M) Σ_m [head_fn(...) + Σ_stages aux(stage, m)]``.

    ``head_metrics=True`` switches the head contract to
    ``head_fn(hp, y, lbl) -> (loss, metrics_dict)`` where each metric
    scalar follows the same per-microbatch-mean convention as the loss
    (e.g. accuracy = correct-count / per-micro token count); the dict is
    accumulated on the last stage, averaged over microbatches, psum'd
    over ``axis`` and ``reduce_axes``, and appended to the return tuple:
    ``(loss, dstage, dhead, dmicro, metrics)``. Metrics are value-only
    (no gradient).

    Timing: stage i forwards micro m at tick m+i (GPipe fill); the last
    stage runs head+backward of micro m in the same tick its forward
    completes, and stage i backwards micro m at tick m + 2(P-1) - i.
    Each stage therefore holds at most 2(P-1-i)+1 stage inputs —
    the fixed (2P-1)-slot stash below, read/written with one-hot masks
    (a data-dependent gather on batch-sharded operands under a manual
    axis trips XLA's SPMD partitioner, and a one-hot select over ≤2P-1
    slots is cheap relative to a stage of transformer layers).

    The backward slot recomputes the stage forward from the stashed
    input (jax.vjp) — the same flops-for-memory trade remat makes.

    ``reduce_axes`` (e.g. the context axis when the sequence is sharded
    into the manual region): param/head grads and the loss are psum'd
    over them; activation cotangents stay sharded.
    """
    if num_virtual > 1:
        return _pipeline_1f1b_interleaved(
            stage_fn, head_fn, stage_params, head_params, microbatches,
            labels, axis=axis, reduce_axes=reduce_axes, stage_aux=stage_aux,
            head_metrics=head_metrics, num_virtual=num_virtual)

    p = lax.axis_size(axis)
    i = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = m + 2 * (p - 1)
    depth = 2 * p - 1
    perm_fwd = [(j, (j + 1) % p) for j in range(p)]
    perm_bwd = [(j, (j - 1) % p) for j in range(p)]
    scale = 1.0 / m

    def run_stage(params, x):
        """Stage forward normalized to (y, aux_scalar)."""
        if stage_aux:
            return stage_fn(params, x)
        return stage_fn(params, x), jnp.zeros((), jnp.float32)

    if head_metrics:
        def scaled_head(hp, y, lbl):
            loss, metrics = head_fn(hp, y, lbl)
            return loss * scale, metrics

        grad_head = jax.value_and_grad(scaled_head, argnums=(0, 1),
                                       has_aux=True)
        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda hp, y, lbl: head_fn(hp, y, lbl)[1],
                           head_params, microbatches[0], labels[0]))
    else:
        def scaled_head(hp, y, lbl):
            return head_fn(hp, y, lbl) * scale

        grad_head = jax.value_and_grad(scaled_head, argnums=(0, 1))
        metrics0 = ()

    # Scan xs: stage-0 injections (padded at the end for drain ticks) and
    # last-stage labels (padded at the front for fill ticks) — static
    # padding instead of in-body dynamic indexing, as in gpipe().
    injects = jnp.concatenate(
        [microbatches, jnp.repeat(microbatches[-1:], ticks - m, axis=0)])
    lbl_pad = jnp.repeat(labels[:1], p - 1, axis=0)
    lbl_tail = jnp.repeat(labels[-1:], ticks - m - (p - 1), axis=0)
    lbls = jnp.concatenate([lbl_pad, labels, lbl_tail])

    zero_act = jnp.zeros_like(microbatches[0])
    stash0 = jnp.zeros((depth,) + microbatches.shape[1:], microbatches.dtype)
    dstage0 = jax.tree.map(jnp.zeros_like, stage_params)
    dhead0 = jax.tree.map(jnp.zeros_like, head_params)

    def slot_mask(slot):
        return (jnp.arange(depth) == slot % depth)

    def tick(carry, xs):
        (fwd_recv, bwd_recv, stash, dstage, dhead, loss_acc, metrics_acc,
         t) = carry
        inject, lbl = xs

        # ---- forward slot: stage i forwards micro m_f = t - i ----------
        m_f = t - i
        fwd_valid = (m_f >= 0) & (m_f < m)
        x_in = jnp.where(i == 0, inject, fwd_recv)
        y, aux = run_stage(stage_params, x_in)
        # Every stage contributes its own aux for its current microbatch
        # (bubble ticks masked); the final psum over `axis` sums stages.
        loss_acc = loss_acc + jnp.where(fwd_valid, aux * scale, 0.0)
        wmask = slot_mask(t)  # (t - i) + i == t: write slot is uniform
        stash = jnp.where(
            wmask.reshape((depth,) + (1,) * x_in.ndim) & fwd_valid,
            x_in[None], stash)

        # Last stage: head + loss for the arriving micro; dy seeds its
        # own backward in this same tick.
        at_head = (i == p - 1) & fwd_valid
        if head_metrics:
            (loss_t, metrics_t), (dhead_t, dy_t) = grad_head(
                head_params, y, lbl)
            metrics_acc = jax.tree.map(
                lambda a, g: a + jnp.where(at_head, g * scale,
                                           jnp.zeros_like(g)),
                metrics_acc, metrics_t)
        else:
            loss_t, (dhead_t, dy_t) = grad_head(head_params, y, lbl)
        loss_acc = loss_acc + jnp.where(at_head, loss_t, 0.0)
        dhead = jax.tree.map(
            lambda a, g: a + jnp.where(at_head, g, jnp.zeros_like(g)),
            dhead, dhead_t)

        # ---- backward slot: stage i backwards micro m_b ----------------
        m_b = t - 2 * (p - 1) + i
        bwd_valid = (m_b >= 0) & (m_b < m)
        rmask = slot_mask(m_b + i)  # stashed at its forward tick m_b + i
        x_b = jnp.sum(
            jnp.where(rmask.reshape((depth,) + (1,) * x_in.ndim), stash, 0.0),
            axis=0).astype(stash.dtype)
        ct_in = jnp.where(i == p - 1, dy_t.astype(bwd_recv.dtype), bwd_recv)
        (_, aux_b), vjp = jax.vjp(run_stage, stage_params, x_b)
        # d loss / d aux is the constant microbatch-mean weight; invalid
        # slots are masked below exactly like the activation path.
        dstage_t, dx = vjp((ct_in.astype(y.dtype),
                            jnp.full_like(aux_b, scale)))
        dstage = jax.tree.map(
            lambda a, g: a + jnp.where(bwd_valid, g, jnp.zeros_like(g)),
            dstage, dstage_t)

        fwd_send = lax.ppermute(y, axis, perm_fwd)
        bwd_send = lax.ppermute(
            jnp.where(bwd_valid, dx, jnp.zeros_like(dx)), axis, perm_bwd)
        new_carry = (fwd_send, bwd_send, stash, dstage, dhead, loss_acc,
                     metrics_acc, t + 1)
        return new_carry, dx

    carry0 = (zero_act, jnp.zeros_like(zero_act), stash0, dstage0, dhead0,
              jnp.zeros((), jnp.float32), metrics0, jnp.zeros((), jnp.int32))
    (_, _, _, dstage, dhead, loss_acc, metrics_acc, _), dxs = lax.scan(
        tick, carry0, (injects, lbls))

    # Stage 0 emitted micro m's input-cotangent at tick m + 2(p-1):
    # a contiguous static slice, broadcast from stage 0 via masked psum.
    dmicro = lax.slice_in_dim(dxs, 2 * (p - 1), 2 * (p - 1) + m, axis=0)
    dmicro = lax.psum(
        jnp.where(i == 0, dmicro, jnp.zeros_like(dmicro)), axis)

    # Loss and head grads live on the last stage; param grads are
    # per-stage (stay sharded over `axis`).
    loss = lax.psum(loss_acc, axis)
    dhead = jax.tree.map(lambda g: lax.psum(g, axis), dhead)
    metrics = jax.tree.map(lambda g: lax.psum(g, axis), metrics_acc)
    for r in reduce_axes:
        loss = lax.psum(loss, r)
        dstage = jax.tree.map(lambda g: lax.psum(g, r), dstage)
        dhead = jax.tree.map(lambda g: lax.psum(g, r), dhead)
        metrics = jax.tree.map(lambda g: lax.psum(g, r), metrics)
    if head_metrics:
        return loss, dstage, dhead, dmicro, metrics
    return loss, dstage, dhead, dmicro


def _pipeline_1f1b_interleaved(
    stage_fn: StageFn,
    head_fn: HeadFn,
    stage_params: Any,
    head_params: Any,
    microbatches: jax.Array,
    labels: jax.Array,
    *,
    axis: str,
    reduce_axes: tuple[str, ...],
    stage_aux: bool,
    head_metrics: bool,
    num_virtual: int,
):
    """Interleaved (virtual-stage) 1F1B — the circular flight schedule.

    The model is P·V chunks; chunk c = v·P + i lives on device i, so
    consecutive chunks sit on consecutive devices and every hop is the
    same uniform ring ``ppermute`` as vanilla 1F1B.  Microbatches go out
    in FLIGHTS of P: micro m = f·P + q is injected at tick f·V·P + q.
    Within a flight, q + v·P covers [0, V·P) bijectively, so each flight
    occupies every device for exactly V·P consecutive ticks with no
    collisions; flights spaced V·P apart make the forward slots DENSE.
    Timing (per micro m = f·P+q, logical stage s = v·P + i):

      forward  of (m, s) on device i at tick  f·VP + q + s
      backward of (m, s) on device i at tick  f·VP + q + 2(VP-1) - s

    Both slot schedules are dense and collision-free (the backward map
    (f,q,v) → f·VP + q - v·P + const is injective for q<P, v<V), giving
    total ticks M·V + P·V + P - 2 for 2·M·V applications per device —
    bubble (PV+P-2)/(MV+PV+P-2), vs vanilla 1F1B's V·(M + 2(P-1))
    chunk-ticks for the same per-device work (:func:`bubble_fraction`).

    Contract changes vs vanilla:

    * ``stage_params`` leaves carry a leading LOCAL dim V — this device's
      chunks v = 0..V-1 (= logical stages v·P + i).  Callers shard a
      global (P·V, ...)-leading stack with ``P(axis)`` after reordering
      it device-major with :func:`interleave_chunks`; the returned
      ``dstage_params`` has the same layout (undo with
      :func:`deinterleave_chunks`).
    * ``M % P == 0`` (whole flights).

    Everything else (HeadFn contract, stage_aux, head_metrics,
    reduce_axes, exact-grad semantics) matches :func:`pipeline_1f1b`.
    """
    p = lax.axis_size(axis)
    i = lax.axis_index(axis)
    v_n = num_virtual
    m = microbatches.shape[0]
    if m % p:
        raise ValueError(
            f"interleaved 1F1B needs M % P == 0, got M={m}, P={p}")
    flights = m // p
    vp = v_n * p
    ticks = m * v_n + vp + p - 2
    depth = 2 * vp - 1
    perm_fwd = [(j, (j + 1) % p) for j in range(p)]
    perm_bwd = [(j, (j - 1) % p) for j in range(p)]
    scale = 1.0 / m

    def run_stage(params, x):
        if stage_aux:
            return stage_fn(params, x)
        return stage_fn(params, x), jnp.zeros((), jnp.float32)

    def chunk_of(params, v):
        return jax.tree.map(
            lambda l: lax.dynamic_index_in_dim(l, v, 0, keepdims=False),
            params)

    if head_metrics:
        def scaled_head(hp, y, lbl):
            loss, metrics = head_fn(hp, y, lbl)
            return loss * scale, metrics

        grad_head = jax.value_and_grad(scaled_head, argnums=(0, 1),
                                       has_aux=True)
        metrics0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype),
            jax.eval_shape(lambda hp, y, lbl: head_fn(hp, y, lbl)[1],
                           head_params, microbatches[0], labels[0]))
    else:
        def scaled_head(hp, y, lbl):
            return head_fn(hp, y, lbl) * scale

        grad_head = jax.value_and_grad(scaled_head, argnums=(0, 1))
        metrics0 = ()

    # Microbatches/labels are indexed IN-BODY from the inverse tick maps
    # (inject at t = f·VP + q, head at t + VP - 1) rather than scattered
    # into (ticks, ...) scan inputs: the scatter form holds ~V extra
    # copies of the full microbatch stack in HBM — a real per-device cost
    # at training activation sizes (ADVICE r4).  Out-of-schedule ticks
    # index a clipped (arbitrary) row; every consumer is masked.
    m_idx = jnp.arange(m)

    zero_act = jnp.zeros_like(microbatches[0])
    stash0 = jnp.zeros((depth,) + microbatches.shape[1:], microbatches.dtype)
    dstage0 = jax.tree.map(jnp.zeros_like, stage_params)
    dhead0 = jax.tree.map(jnp.zeros_like, head_params)
    dmicro0 = jnp.zeros_like(microbatches)

    def slot_mask(slot):
        return (jnp.arange(depth) == slot % depth)

    def tick(carry, _):
        (fwd_recv, bwd_recv, stash, dstage, dhead, dmicro, loss_acc,
         metrics_acc, t) = carry
        # Inverse tick maps (see buffer note above): micro injected at
        # this tick, and the micro whose head fires at this tick (the
        # head tick is the inject tick shifted by VP - 1).
        def micro_at(tt):
            return jnp.clip(
                jnp.floor_divide(tt, vp) * p + jnp.remainder(tt, vp),
                0, m - 1)

        inject = lax.dynamic_index_in_dim(
            microbatches, micro_at(t), 0, keepdims=False)
        lbl = lax.dynamic_index_in_dim(
            labels, micro_at(t - (vp - 1)), 0, keepdims=False)

        # ---- forward slot: device i runs chunk v_f of micro m_f --------
        w_f = t - i
        fwd_valid = (w_f >= 0) & (w_f < m * v_n)
        o_f = jnp.remainder(w_f, vp)
        v_f = jnp.clip(o_f // p, 0, v_n - 1)
        x_in = jnp.where((i == 0) & (v_f == 0), inject, fwd_recv)
        y, aux = run_stage(chunk_of(stage_params, v_f), x_in)
        loss_acc = loss_acc + jnp.where(fwd_valid, aux * scale, 0.0)
        wmask = slot_mask(t)
        stash = jnp.where(
            wmask.reshape((depth,) + (1,) * x_in.ndim) & fwd_valid,
            x_in[None], stash)

        # Head fires when the LAST chunk (v = V-1 on device P-1) emerges.
        at_head = (i == p - 1) & fwd_valid & (v_f == v_n - 1)
        if head_metrics:
            (loss_t, metrics_t), (dhead_t, dy_t) = grad_head(
                head_params, y, lbl)
            metrics_acc = jax.tree.map(
                lambda a, g: a + jnp.where(at_head, g * scale,
                                           jnp.zeros_like(g)),
                metrics_acc, metrics_t)
        else:
            loss_t, (dhead_t, dy_t) = grad_head(head_params, y, lbl)
        loss_acc = loss_acc + jnp.where(at_head, loss_t, 0.0)
        dhead = jax.tree.map(
            lambda a, g: a + jnp.where(at_head, g, jnp.zeros_like(g)),
            dhead, dhead_t)

        # ---- backward slot: invert t = f·VP + q + 2(VP-1) - (v·P + i) --
        w_b = t + i - 2 * (vp - 1)
        z_b = jnp.floor_divide(w_b, p)
        q_b = jnp.remainder(w_b, p)
        v_b = jnp.remainder(-z_b, v_n)
        f_b = jnp.floor_divide(z_b + v_b, v_n)
        bwd_valid = (f_b >= 0) & (f_b < flights)
        v_bc = jnp.clip(v_b, 0, v_n - 1)
        micro_b = f_b * p + q_b
        # Stashed at its forward tick f·VP + q + v·P + i.
        rmask = slot_mask(f_b * vp + q_b + v_b * p + i)
        x_b = jnp.sum(
            jnp.where(rmask.reshape((depth,) + (1,) * x_in.ndim), stash, 0.0),
            axis=0).astype(stash.dtype)
        seed_here = (i == p - 1) & (v_b == v_n - 1)
        ct_in = jnp.where(seed_here, dy_t.astype(bwd_recv.dtype), bwd_recv)
        (_, aux_b), vjp = jax.vjp(
            lambda cp, xx: run_stage(cp, xx),
            chunk_of(stage_params, v_bc), x_b)
        dchunk, dx = vjp((ct_in.astype(y.dtype),
                          jnp.full_like(aux_b, scale)))
        dstage = jax.tree.map(
            lambda acc, g: lax.dynamic_update_index_in_dim(
                acc,
                lax.dynamic_index_in_dim(acc, v_bc, 0, keepdims=False)
                + jnp.where(bwd_valid, g, jnp.zeros_like(g)),
                v_bc, 0),
            dstage, dchunk)
        # Chunk 0's input cotangent on device 0 is d loss / d micro m_b.
        at_entry = (i == 0) & (v_b == 0) & bwd_valid
        mmask = (m_idx == micro_b)
        dmicro = jnp.where(
            (mmask.reshape((m,) + (1,) * dx.ndim) & at_entry),
            dx[None].astype(dmicro.dtype), dmicro)

        fwd_send = lax.ppermute(y, axis, perm_fwd)
        bwd_send = lax.ppermute(
            jnp.where(bwd_valid, dx, jnp.zeros_like(dx)), axis, perm_bwd)
        new_carry = (fwd_send, bwd_send, stash, dstage, dhead, dmicro,
                     loss_acc, metrics_acc, t + 1)
        return new_carry, None

    carry0 = (zero_act, jnp.zeros_like(zero_act), stash0, dstage0, dhead0,
              dmicro0, jnp.zeros((), jnp.float32), metrics0,
              jnp.zeros((), jnp.int32))
    (_, _, _, dstage, dhead, dmicro, loss_acc, metrics_acc, _), _ = lax.scan(
        tick, carry0, None, length=ticks)

    dmicro = lax.psum(
        jnp.where(i == 0, dmicro, jnp.zeros_like(dmicro)), axis)
    loss = lax.psum(loss_acc, axis)
    dhead = jax.tree.map(lambda g: lax.psum(g, axis), dhead)
    metrics = jax.tree.map(lambda g: lax.psum(g, axis), metrics_acc)
    for r in reduce_axes:
        loss = lax.psum(loss, r)
        dstage = jax.tree.map(lambda g: lax.psum(g, r), dstage)
        dhead = jax.tree.map(lambda g: lax.psum(g, r), dhead)
        metrics = jax.tree.map(lambda g: lax.psum(g, r), metrics)
    if head_metrics:
        return loss, dstage, dhead, dmicro, metrics
    return loss, dstage, dhead, dmicro
