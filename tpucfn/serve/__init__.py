"""tpucfn.serve — continuous-batching inference.

The serving counterpart of the training stack (ROADMAP north star:
"serves heavy traffic"), layered bottom-up:

* :mod:`tpucfn.serve.kvcache` — paged KV-block accounting (free-list
  allocator, per-sequence block tables, eviction bookkeeping).
* :mod:`tpucfn.serve.scheduler` — continuous batching: bucketed prefill
  admission, in-place retirement, preempt-on-full.
* :mod:`tpucfn.serve.engine` — the jitted prefill/decode steps over a
  slot-resident, donated cache (vmapped per-slot cache indices).
* :mod:`tpucfn.serve.frontend` — thread-safe queue, 429/400 admission
  control, deadlines, and the obs.metrics serving dashboard.
* :mod:`tpucfn.serve.router` — the resilient tier (ISSUE 9): N replica
  Servers behind health-driven failover, deadline-budgeted retry,
  hedging, and graceful drain.
* :mod:`tpucfn.serve.spec` — speculative decoding (ISSUE 14): a draft
  ``ServeEngine`` at the same slot layout proposes, the target verifies
  k+1 positions per dispatch, greedy output stays bit-identical, and an
  acceptance-driven controller bounds the worst case.

CLI: ``tpucfn serve`` (see ``tpucfn/cli/main.py``); bench:
``benches/serve_bench.py``.
"""

from tpucfn.serve.engine import ServeEngine  # noqa: F401
from tpucfn.serve.frontend import (  # noqa: F401
    AdmissionError,
    Cancelled,
    DeadlineExceeded,
    ReplicaFailed,
    Requeued,
    Server,
    ServeRequest,
    ServingMetrics,
    SLOTracker,
)
from tpucfn.serve.router import (  # noqa: F401
    CircuitBreaker,
    ReplicaRouter,
    RouterRequest,
)
from tpucfn.serve.kvcache import (  # noqa: F401
    AdmitResult,
    BlockAllocator,
    BlockTable,
    KVCacheManager,
    OutOfBlocksError,
    PrefixMatch,
)
from tpucfn.serve.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    DecodeWork,
    PrefillItem,
    PrefillWork,
    Sequence,
    prefill_bucket,
)
from tpucfn.serve.spec import (  # noqa: F401
    SpecDecoder,
    SpecKController,
)
