"""Gang supervision: detect → decide → act → recovered.

The :class:`GangCoordinator` owns the launcher's process table and runs
the whole fault-tolerance loop in one place (ISSUE 4 tentpole):

* **detect** — polls every rank's exit code each ``poll_interval`` and,
  when a :class:`~tpucfn.ft.heartbeat.HeartbeatMonitor` is attached,
  consumes its verdicts (a DEAD heartbeat on a live process is a HANG;
  process exit codes are CRASH / CLEAN_EXIT).
* **decide** — hands the failure set to the
  :class:`~tpucfn.ft.policy.RecoveryPolicy` (gang vs solo restart,
  budget + backoff, per-failure-class table).
* **act** — SIGTERM→SIGKILL escalation through
  :meth:`~tpucfn.launch.launcher.Launcher.stop_all`, then relaunch:
  the whole gang (resume happens in the job via its CheckpointManager —
  ``Trainer.init_or_resume``) or just the dead host with its original
  ``host_env`` (same host_id, obs port, heartbeat file).
* **record** — every incident becomes ``ft_*`` registry metrics (MTTR
  included), one line each in ``<ft_dir>/events.jsonl``, a trace span,
  and a refreshed ``<ft_dir>/supervisor.json`` snapshot that ``tpucfn
  ft status`` renders.

``launch.run_with_restarts`` is a thin shim over this class (gang
policy, no monitor), preserving its signature and its ``supervisor_*``
metric names.

The coordinator is also a :class:`~tpucfn.ft.chaos.ChaosTarget`: a
:class:`~tpucfn.ft.chaos.ChaosSpec` passed in is replayed against the
real subprocess table (SIGKILL / SIGSTOP / heartbeat delay / checkpoint
corruption) on the same supervision clock, which is what makes the
end-to-end recovery drill deterministic.
"""

from __future__ import annotations

import json
import os
import signal
import time
from pathlib import Path
from typing import Callable, Sequence

from tpucfn.ft.chaos import ChaosEngine, ChaosSpec, ChaosTarget, \
    corrupt_latest_checkpoint
from tpucfn.ft.heartbeat import HeartbeatMonitor, HostState
from tpucfn.ft.policy import (
    Action,
    Decision,
    Failure,
    FailureKind,
    GangRestart,
    RecoveryPolicy,
    RestartBudget,
)


class GangCoordinator(ChaosTarget):
    def __init__(
        self,
        launcher,
        argv: Sequence[str],
        *,
        policy: RecoveryPolicy | None = None,
        monitor: HeartbeatMonitor | None = None,
        ft_dir: str | Path | None = None,
        registry=None,
        tracer=None,
        poll_interval: float = 0.05,
        term_grace_s: float = 5.0,
        chaos: ChaosSpec | ChaosEngine | None = None,
        kill_host_after: tuple[int, float] | None = None,
        ckpt_dir: str | Path | None = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        capture_flight: bool = True,
        flight_timeout_s: float = 2.0,
    ):
        self.launcher = launcher
        self.argv = list(argv)
        self.policy = policy if policy is not None else GangRestart(
            RestartBudget(0))
        self.monitor = monitor
        self.ft_dir = Path(ft_dir) if ft_dir is not None else None
        self.tracer = tracer
        self.poll_interval = poll_interval
        self.term_grace_s = term_grace_s
        self.kill_host_after = kill_host_after
        self.ckpt_dir = Path(ckpt_dir) if ckpt_dir is not None else None
        self.clock = clock
        self.sleep = sleep
        self.capture_flight = capture_flight
        self.flight_timeout_s = flight_timeout_s

        if registry is None:
            # Throwaway registry: identical flow, nothing exported —
            # keeps the loop free of per-metric None guards.
            from tpucfn.obs.registry import MetricRegistry

            registry = MetricRegistry()
        self.registry = registry
        r = registry
        # supervisor_* names predate the ft plane (obs PR) and stay for
        # dashboard compatibility; ft_* is the recovery-plane surface.
        self.attempts_c = r.counter(
            "supervisor_launch_attempts_total",
            "gang launches (incl. the first)")
        self.restarts_c = r.counter(
            "supervisor_restarts_total", "relaunches after a failure")
        self.failures_c = r.counter(
            "supervisor_failures_total",
            "gang-level failures observed (clean exits excluded)")
        self.hosts_g = r.gauge(
            "supervisor_gang_hosts", "hosts in the launched gang")
        self.rc_g = r.gauge(
            "supervisor_last_exit_code", "exit code of the last finished gang")
        self.ft_failures_c = r.counter(
            "ft_failures_detected_total",
            "host failures detected (crash + hang)")
        self.ft_restarts_c = r.counter(
            "ft_restarts_total", "recovery restarts executed (gang + solo)")
        self.ft_gang_restarts_c = r.counter(
            "ft_gang_restarts_total", "whole-gang restarts")
        self.ft_solo_restarts_c = r.counter(
            "ft_solo_restarts_total", "single-host restarts into a live gang")
        self.ft_incidents_c = r.counter(
            "ft_incidents_total", "detect→decide→act cycles")
        self.ft_give_ups_c = r.counter(
            "ft_give_ups_total", "incidents abandoned (budget exhausted)")
        self.ft_mttr_s = r.summary(
            "ft_mttr_seconds", "detect → relaunch-complete recovery time")
        self.ft_hosts_live_g = r.gauge(
            "ft_hosts_live", "hosts LIVE per the heartbeat monitor")
        self.ft_stragglers_g = r.gauge(
            "ft_stragglers", "hosts flagged STRAGGLER by step lag")

        hosts = self.launcher.contract.hosts()[
            : self.launcher.contract.workers_count]
        self.host_ids = list(range(len(hosts)))
        self._procs: dict[int, object] = {}  # host_id → live Popen
        self._finished: dict[int, int] = {}  # host_id → clean rc (0)
        self._incident = 0
        # Per-host post-(re)launch window during which monitor verdicts
        # for that host are ignored — a fleet-wide window would let one
        # solo restart blind hang detection for every other host.
        self._blind_until: dict[int, float] = {}
        self._next_observe = 0.0  # monitor read throttle (see _detect)
        self._last_fleet_step: int | None = None
        self._reported_stragglers: set[int] = set()
        # HANG/DEAD verdicts the policy already declined to act on
        # (observe-only tables): suppressed until the host beats again,
        # or the detect loop would re-open the same incident every tick.
        self._suppressed_hangs: set[int] = set()
        if isinstance(chaos, ChaosSpec):
            chaos = ChaosEngine(chaos, self)
        self.chaos = chaos
        if (self.chaos is not None and self.monitor is None
                and any(e.at_step is not None and e.at_s is None
                        for e in self.chaos.spec.events)):
            # Fleet step comes from heartbeat observations; without a
            # monitor an at_step-only event would silently never fire
            # and the drill would pass vacuously.
            raise ValueError(
                "chaos events with only an at_step trigger need a "
                "HeartbeatMonitor attached (fleet step comes from "
                "heartbeats)")
        if self.ft_dir is not None:
            self.ft_dir.mkdir(parents=True, exist_ok=True)

    # -- ChaosTarget ------------------------------------------------------

    def num_hosts(self) -> int:
        return len(self.host_ids)

    def kill_host(self, host_id: int) -> None:
        p = self._procs.get(host_id)
        if p is not None and p.poll() is None:
            p.kill()

    def hang_host(self, host_id: int) -> None:
        p = self._procs.get(host_id)
        if p is not None and p.poll() is None:
            os.kill(p.pid, signal.SIGSTOP)

    def resume_host(self, host_id: int) -> None:
        p = self._procs.get(host_id)
        if p is not None and p.poll() is None:
            os.kill(p.pid, signal.SIGCONT)

    def delay_heartbeats(self, host_id: int, duration_s: float) -> None:
        if self.monitor is None:
            raise ValueError(
                "chaos delay_heartbeats needs a HeartbeatMonitor attached")
        self.monitor.inject_heartbeat_delay(
            host_id, extra_age_s=duration_s, duration_s=duration_s)

    def corrupt_latest_checkpoint(self, rng) -> None:
        if self.ckpt_dir is None:
            raise ValueError(
                "chaos corrupt_ckpt fired but GangCoordinator has no "
                "ckpt_dir configured")
        victim = corrupt_latest_checkpoint(self.ckpt_dir, rng)
        self._event("chaos_ckpt_corrupted",
                    path=None if victim is None else str(victim))

    # -- flight capture (ISSUE 6) -----------------------------------------

    def _capture_flight(self, incident: int, failed: set[int]) -> None:
        """Pull every surviving host's flight-recorder ring over its obs
        endpoint BEFORE the gang is stopped — the dead host's last
        seconds are in its own signal/atexit dump, but the survivors'
        rings live only in memory and the restart is about to erase
        them.  Best-effort and CONCURRENT with one shared deadline:
        MTTR includes this call by design (forensics are part of
        incident handling), so its cost must be ~``flight_timeout_s``
        total, not per survivor — a 32-host gang with several
        unreachable endpoints must not serialize 2s timeouts while the
        doomed gang keeps executing steps that will be rewound."""
        base = getattr(self.launcher, "obs_base_port", None)
        if not base or self.ft_dir is None or not self.capture_flight:
            return
        import concurrent.futures
        import urllib.request

        from tpucfn.obs.flight import incident_flight_path, write_flight_dump

        hosts = self.launcher.contract.hosts()[
            : self.launcher.contract.workers_count]
        targets = [(h, hosts[h].rsplit(":", 1)[0])
                   for h, p in sorted(self._procs.items())
                   if h not in failed and p.poll() is None]
        if not targets:
            return

        def fetch(host_id: int, addr: str):
            url = f"http://{addr}:{base + 1 + host_id}/flightrecorder"
            with urllib.request.urlopen(
                    url, timeout=self.flight_timeout_s) as r:
                return json.loads(r.read().decode())

        out_dir = self.ft_dir / "flight"
        captured, errors = [], 0
        # One worker PER survivor, not a smaller pool: with a capped
        # pool, >=cap hung endpoints (plausibly the incident itself)
        # would hold every worker for the whole deadline and the
        # healthy hosts' queued fetches would never start — losing the
        # captures for exactly the hosts that could answer.
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(targets),
            thread_name_prefix="flight-capture")
        try:
            futs = {pool.submit(fetch, h, addr): h for h, addr in targets}
            done, pending = concurrent.futures.wait(
                futs, timeout=self.flight_timeout_s + 0.5)
            errors += len(pending)
            for f in done:
                host_id = futs[f]
                try:
                    body = f.result()
                except Exception:  # noqa: BLE001 — best-effort
                    errors += 1
                    continue
                if not isinstance(body, dict):
                    errors += 1
                    continue
                out_dir.mkdir(parents=True, exist_ok=True)
                write_flight_dump(
                    incident_flight_path(out_dir, incident, host_id), body)
                captured.append(host_id)
        finally:
            # don't block recovery on stragglers: per-request socket
            # timeouts bound the leaked workers' lifetimes anyway
            pool.shutdown(wait=False)
        captured.sort()
        if captured or errors:
            self._event("flight_capture", incident=incident,
                        hosts=captured, errors=errors)

    # -- event / snapshot plumbing ---------------------------------------

    def _event(self, kind: str, **fields) -> None:
        if self.ft_dir is None:
            return
        rec = {"ts": time.time(), "kind": kind, **fields}
        with open(self.ft_dir / "events.jsonl", "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._write_snapshot()

    def _write_snapshot(self) -> None:
        if self.ft_dir is None:
            return
        hb = None
        if self.monitor is not None:
            hb = self.monitor.config.interval_s
        snap = {
            "updated_ts": time.time(),
            "pid": os.getpid(),
            "argv": self.argv,
            "gang_hosts": len(self.host_ids),
            "policy": self.policy.name,
            "budget": {"max_restarts": self.policy.budget.max_restarts,
                       "used": self.policy.budget.used},
            "heartbeat_interval_s": hb,
            **self.registry.varz(),
        }
        tmp = self.ft_dir / "supervisor.json.tmp"
        tmp.write_text(json.dumps(snap, indent=2))
        tmp.replace(self.ft_dir / "supervisor.json")

    # -- supervision loop -------------------------------------------------

    def _launch_gang(self, *, first: bool) -> None:
        inject = self.kill_host_after if first else None
        procs = self.launcher.launch(self.argv, kill_host_after=inject)
        self._procs = dict(zip(self.host_ids, procs))
        self._finished.clear()
        self._reported_stragglers.clear()
        self._suppressed_hangs.clear()
        self.attempts_c.add()
        self.hosts_g.set(len(procs))
        if self.monitor is not None:
            self.monitor.restart_grace()
            for h in self.host_ids:
                self.monitor.activate_host(h)
            blind = self.clock() + self.monitor.config.grace_s
            self._blind_until = {h: blind for h in self.host_ids}
        self._event("launch", first=first, hosts=len(procs),
                    pids=[p.pid for p in procs])

    def _launch_solo(self, host_id: int) -> None:
        # Same host_env as the rank it replaces (host_id, obs port,
        # heartbeat file) — the gang must not notice the substitution.
        self._procs[host_id] = self.launcher.launch_host(self.argv, host_id)
        self._finished.pop(host_id, None)
        self._suppressed_hangs.discard(host_id)
        self._reported_stragglers.discard(host_id)
        if self.monitor is not None:
            self.monitor.activate_host(host_id)
            # Blind only the replaced host: its stale heartbeat must not
            # re-condemn it while it boots, but the REST of the gang
            # keeps full-rate hang detection.
            self._blind_until[host_id] = (self.clock()
                                          + self.monitor.config.grace_s)
        self._event("solo_launch", host=host_id,
                    pid=self._procs[host_id].pid)

    def _straggler_actionable(self) -> bool:
        return self.policy.table.get(
            FailureKind.STRAGGLER, Action.NONE) is not Action.NONE

    def _detect(self, now: float) -> list[Failure]:
        failures: list[Failure] = []
        for host_id, p in list(self._procs.items()):
            rc = p.poll()
            if rc is None:
                continue
            if rc == 0:
                del self._procs[host_id]
                self._finished[host_id] = 0
                if self.monitor is not None:
                    # a finished rank's heartbeat going stale is
                    # retirement, not death — keep /healthz green
                    self.monitor.retire_host(host_id)
                self._event("host_exit", host=host_id, rc=0)
            else:
                failures.append(Failure(host_id, FailureKind.CRASH, rc=rc))
        if (self.monitor is not None and self._procs
                and now >= self._next_observe):
            # Throttle to half the heartbeat interval: heartbeat files
            # change once per interval, so tail-reading every 50ms poll
            # tick is pure redundant I/O (process-exit CRASH detection
            # above still runs at full poll rate).
            self._next_observe = now + self.monitor.config.interval_s / 2.0
            view = self.monitor.observe()
            self._last_fleet_step = view.max_step()
            counts = view.counts()
            self.ft_hosts_live_g.set(counts[HostState.LIVE.value])
            self.ft_stragglers_g.set(counts[HostState.STRAGGLER.value])
            crashed = {f.host_id for f in failures}
            for v in view.hosts:
                if v.host_id not in self._procs or v.host_id in crashed:
                    continue
                if now < self._blind_until.get(v.host_id, 0.0):
                    # Per-host post-(re)launch blind window: a stale
                    # heartbeat from the previous incarnation must not
                    # condemn a rank that is still importing jax.
                    continue
                if v.state is HostState.DEAD:
                    if v.host_id in self._suppressed_hangs:
                        continue  # policy already declined to act
                    failures.append(Failure(v.host_id, FailureKind.HANG,
                                            step=v.step, detail=v.reason))
                else:
                    # the host came back (fresh beat): re-arm reporting
                    self._suppressed_hangs.discard(v.host_id)
                    if v.state is HostState.LIVE:
                        # caught back up: a later straggle is a NEW
                        # episode and must be reported again
                        self._reported_stragglers.discard(v.host_id)
                    if (v.state is HostState.STRAGGLER
                            and self._straggler_actionable()
                            and v.host_id not in self._reported_stragglers):
                        self._reported_stragglers.add(v.host_id)
                        failures.append(
                            Failure(v.host_id, FailureKind.STRAGGLER,
                                    step=v.step, detail=v.reason))
        return failures

    def _stop_hosts(self, host_ids: Sequence[int]) -> None:
        procs = [self._procs[h] for h in host_ids if h in self._procs]
        self.launcher.stop_all(procs, grace_s=self.term_grace_s,
                               poll_interval=self.poll_interval)
        for h in host_ids:
            self._procs.pop(h, None)

    def _failure_rc(self, failures: list[Failure]) -> int:
        for f in failures:
            if f.rc is not None and f.rc != 0:
                return f.rc
        return 1  # hang/straggler incidents have no exit code

    def run(self) -> int:
        """Supervise until the gang finishes cleanly (0), a failure
        exhausts the policy budget (the failing rc), or the policy
        declines to act on a fatal class."""
        try:
            self._launch_gang(first=True)
            start = self.clock()
            while True:
                self.sleep(self.poll_interval)
                now = self.clock()
                if self.chaos is not None and not self.chaos.done():
                    self.chaos.tick(now - start, self._last_fleet_step)
                failures = self._detect(now)
                if not failures:
                    if not self._procs:  # every supervised rank exited
                        rc = next((r for r in self._finished.values() if r),
                                  0)
                        self.rc_g.set(rc)
                        self._event("done", rc=rc)
                        return rc
                    continue
                rc = self._handle_incident(failures)
                if rc is not None:
                    return rc
        finally:
            if self._procs:
                self.launcher.stop_all(list(self._procs.values()),
                                       grace_s=self.term_grace_s,
                                       poll_interval=self.poll_interval)
                self._procs.clear()
            self._write_snapshot()

    def _handle_incident(self, failures: list[Failure]) -> int | None:
        """One detect→decide→act→recovered cycle; returns the run's exit
        code when the incident ends the run, else None."""
        t_detect = self.clock()
        self._incident += 1
        incident = self._incident
        self.ft_incidents_c.add()
        real = [f for f in failures if f.kind in (FailureKind.CRASH,
                                                  FailureKind.HANG)]
        if real:
            self.ft_failures_c.add(len(real))
            self.failures_c.add()
            self.rc_g.set(self._failure_rc(real))
        fail_json = [{"host": f.host_id, "kind": f.kind.value, "rc": f.rc,
                      "step": f.step, "detail": f.detail} for f in failures]
        self._event("detect", incident=incident, failures=fail_json)
        if self.tracer is not None:
            self.tracer.event("ft_detect", trace_id=incident,
                              failures=fail_json)
        if real:
            # Forensics before recovery: the survivors' flight rings are
            # about to be killed with the gang (ISSUE 6 tentpole).
            self._capture_flight(incident, {f.host_id for f in real})
        decision = self.policy.decide(failures)
        self._event("decide", incident=incident,
                    action=decision.action.value,
                    hosts=list(decision.hosts),
                    delay_s=round(decision.delay_s, 3),
                    reason=decision.reason)

        if decision.action is Action.NONE:
            # A table can declare a failure non-actionable (observe-
            # only); the incident must then be closed, not re-detected
            # every poll tick: reap crashed hosts with their rc, and
            # suppress further HANG verdicts until the host beats again.
            for f in failures:
                if f.kind is FailureKind.CRASH and f.host_id in self._procs:
                    del self._procs[f.host_id]
                    self._finished[f.host_id] = f.rc if f.rc else 1
                elif f.kind is FailureKind.HANG:
                    self._suppressed_hangs.add(f.host_id)
            return None
        if decision.action is Action.GIVE_UP:
            rc = self._failure_rc(failures)
            self.ft_give_ups_c.add()
            self._stop_hosts(list(self._procs))
            self.rc_g.set(rc)
            self._event("give_up", incident=incident, rc=rc,
                        reason=decision.reason)
            if self.tracer is not None:
                self.tracer.record("ft_give_up", start=t_detect,
                                   end=self.clock(), trace_id=incident,
                                   rc=rc)
            return rc

        if decision.delay_s > 0:
            self.sleep(decision.delay_s)
        if decision.action is Action.SOLO_RESTART:
            self._stop_hosts(decision.hosts)
            for h in decision.hosts:
                self._launch_solo(h)
            self.ft_solo_restarts_c.add(len(decision.hosts))
            self.ft_restarts_c.add(len(decision.hosts))
            self.restarts_c.add(len(decision.hosts))
        else:  # GANG_RESTART
            self._stop_hosts(list(self._procs))
            self._launch_gang(first=False)
            self.ft_gang_restarts_c.add()
            self.ft_restarts_c.add()
            self.restarts_c.add()
        mttr = self.clock() - t_detect
        self.ft_mttr_s.observe(mttr)
        self._event("recovered", incident=incident,
                    action=decision.action.value, mttr_s=round(mttr, 4))
        # Goodput attribution (ISSUE 5): one ledger row per incident so
        # `tpucfn obs goodput` can name who stole the fleet's seconds.
        # detection_s is the estimated failure→detect latency: a HANG is
        # by construction dead_after_s of silent heartbeats old when the
        # verdict lands; a CRASH is caught within one poll tick.
        detection_s = self.poll_interval
        if self.monitor is not None and any(
                f.kind is FailureKind.HANG for f in failures):
            detection_s = self.monitor.config.dead_s
        self._event("goodput_incident", incident=incident,
                    action=decision.action.value,
                    downtime_s=round(mttr, 4),
                    detection_s=round(detection_s, 4),
                    fleet_step=self._last_fleet_step)
        if self.tracer is not None:
            self.tracer.record("ft_recover", start=t_detect, dur_s=mttr,
                               trace_id=incident,
                               action=decision.action.value,
                               hosts=list(decision.hosts))
        return None
