"""Training state pytree.

The reference's "state" was scattered across processes: weights on ps-lite
servers, optimizer state wherever the server shard lived, step count in each
worker's loop (SURVEY.md §3.2). Here it is one pytree, sharded by the same
rule engine as the params, so checkpointing, resume, and fault recovery all
see a single coherent object.

``model_state`` carries non-differentiated model collections (flax
``batch_stats`` for BatchNorm, etc.). Because the whole step runs as one
GSPMD program over the global batch, BN statistics computed inside it are
*cross-replica by construction* — the sync-BN that needed a dedicated
NCCL/Horovod code path on the reference stack falls out of the sharding.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    model_state: Any  # e.g. {"batch_stats": ...}; {} when unused
    opt_state: optax.OptState
    rng: jax.Array

    @classmethod
    def create(
        cls,
        params: Any,
        tx: optax.GradientTransformation,
        rng: jax.Array,
        model_state: Any = None,
    ) -> "TrainState":
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            model_state={} if model_state is None else model_state,
            opt_state=tx.init(params),
            rng=rng,
        )
