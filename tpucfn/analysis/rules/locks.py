"""blocking-under-lock and lock-order: the deadlock/stall rules.

Incidents encoded (CHANGES.md):

* PR 8 shipped a ``Thread.join`` inside the router lock — the joined
  serve thread's completion callbacks needed that same lock, so the
  join could never finish ("join must happen OUTSIDE the router lock or
  the old thread's completion callbacks deadlock against it").
  ``blocking-under-lock`` flags joins, subprocess calls, socket/HTTP
  round-trips, and long sleeps lexically inside a ``with <lock>:``
  region (one level of same-class/module calls is expanded too).
  Deliberate bounded waits carry a ``# tpucfn: allow[blocking-under-
  lock]`` pragma or a baseline entry — never a silent pass.
* ``lock-order`` builds each module's lock-acquisition graph (lock B
  acquired while A is held, across same-class method calls) and flags
  cycles — including the length-1 cycle of re-acquiring a non-reentrant
  lock you already hold, which is the PR 6 flight-ring shape before the
  RLock fix.
"""

from __future__ import annotations

import ast

from tpucfn.analysis.core import (
    Analysis,
    Finding,
    FuncInfo,
    _const_test,
    _terminates,
    call_consts,
    calls_in,
    sub_suites,
)

BLOCKING_RULE = "blocking-under-lock"
ORDER_RULE = "lock-order"

SLEEP_THRESHOLD_S = 0.05
_SUBPROCESS_CALLS = {"run", "call", "check_call", "check_output", "Popen",
                     "communicate"}
_NET_FUNCS = {"urlopen", "create_connection", "getaddrinfo"}
_NET_MODULES = {"requests", "urllib", "socket", "http"}
# The repo's own join-shaped wrappers: receivers are often unresolvable
# (`old.server.wait_stopped(...)`), so these names flag by themselves —
# Server.wait_stopped IS a thread join (the PR 8 relaunch incident ran
# through exactly this wrapper).
_BLOCKING_WRAPPERS = {
    "wait_stopped": "thread join (wait_stopped)",
    "run_until_idle": "full serve-loop drive (run_until_idle)",
}


def _blocking_desc(call: ast.Call) -> str | None:
    """A human-readable description when ``call`` is a blocking call the
    rule cares about, else None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        recv = f.value
        if f.attr in _BLOCKING_WRAPPERS:
            return _BLOCKING_WRAPPERS[f.attr]
        if f.attr == "join":
            # Thread.join takes at most one (numeric) timeout; str.join
            # takes exactly one iterable.  A constant-string receiver,
            # multiple args, or an iterable-shaped argument is string
            # work; a bare join, a numeric timeout, or a duration-named
            # variable is the thread shape.
            if isinstance(recv, ast.Constant):
                return None
            if len(call.args) > 1:
                return None
            if any(kw.arg == "timeout" for kw in call.keywords):
                return "thread/process join"
            if not call.args and not call.keywords:
                return "thread/process join"
            if len(call.args) == 1:
                a = call.args[0]
                if isinstance(a, ast.Constant) and \
                        isinstance(a.value, (int, float)):
                    return "thread/process join"
                if isinstance(a, ast.Name) and _duration_name(a.id):
                    return "thread/process join"
            return None
        if f.attr == "sleep" and isinstance(recv, ast.Name) \
                and recv.id == "time":
            return _sleep_desc(call)
        if isinstance(recv, ast.Name) and recv.id == "subprocess" \
                and f.attr in _SUBPROCESS_CALLS:
            return f"subprocess.{f.attr}"
        if f.attr in _NET_FUNCS:
            return f"network call .{f.attr}()"
        if isinstance(recv, ast.Name) and recv.id in _NET_MODULES:
            return f"{recv.id}.{f.attr} network call"
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id in _NET_MODULES:
            return f"{recv.value.id}.{recv.attr}.{f.attr} network call"
    elif isinstance(f, ast.Name):
        if f.id == "sleep":
            return _sleep_desc(call)
        if f.id in _NET_FUNCS:
            return f"network call {f.id}()"
        if f.id == "Popen":
            return "subprocess.Popen"
    return None


def _duration_name(name: str) -> bool:
    """Does a variable name read as a duration (``timeout``, ``grace_s``,
    ``RELAUNCH_JOIN_S``)?  A bare ``_s``-substring test flagged ordinary
    ``sep.join(parts_s)`` string work — lowercase names must carry a
    duration word; ALL-CAPS ``*_S`` module constants count."""
    low = name.lower()
    if any(t in low for t in ("timeout", "grace", "deadline")):
        return True
    return name.isupper() and name.endswith("_S")


def _sleep_desc(call: ast.Call) -> str | None:
    """Only constant sleeps at/over the threshold are flagged — a
    bounded 5 ms poll tick under a lock is a deliberate idiom here, and
    a non-constant duration cannot be judged statically."""
    if call.args and isinstance(call.args[0], ast.Constant):
        v = call.args[0].value
        if isinstance(v, (int, float)) and v >= SLEEP_THRESHOLD_S:
            return f"time.sleep({v:g}) >= {SLEEP_THRESHOLD_S:g}s threshold"
    return None


class _Scanner:
    """One traversal serves both rules: walk every function with a
    held-locks stack, emitting blocking findings and acquisition-order
    edges as they appear."""

    def __init__(self, analysis: Analysis):
        self.analysis = analysis
        self.blocking: list[Finding] = []
        # (lock_a, lock_b) -> (mod, line, context) for the module graph
        self.edges: dict[tuple[str, str], tuple] = {}
        self.reacquire: list[Finding] = []
        self._visited: set[tuple] = set()
        # blocking findings dedupe globally by key: a shared helper
        # reached from two modules is ONE defect, and _visited resets
        # per module (the order graph is per-module — a cross-module
        # memo silently dropped edges depending on scan order)
        self._blocking_seen: set[tuple[str, str]] = set()

    def scan_module(self, mod):
        self.edges = {}
        self.reacquire = []
        self._visited = set()
        for qual, info in self.analysis.functions(mod).items():
            if isinstance(info.node, ast.Lambda):
                continue
            self._scan(mod, info, info.node.body, held=(), depth=0)
        return self._cycle_findings(mod)

    def _blocking_finding(self, mod, info, call, desc, held) -> None:
        key = f"{info.qualname}:{held[-1][1]}:{desc}"
        if (mod.rel, key) in self._blocking_seen:
            return
        self._blocking_seen.add((mod.rel, key))
        self.blocking.append(Finding(
            BLOCKING_RULE, mod.rel, call.lineno,
            f"{desc} inside `with {held[-1][1]}:` in {info.qualname} — "
            "callbacks or threads needing that lock can never finish "
            "what this is waiting for; move the wait outside the lock",
            key=key))

    # -- traversal ---------------------------------------------------------

    def _scan(self, mod, info: FuncInfo, body: list[ast.stmt],
              held: tuple, depth: int,
              consts: dict | None = None) -> None:
        consts = consts or {}
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                # same constant-kwarg pruning as the engine's
                # live_statements: descending into drain(wait=False)
                # must analyze only the lock-free arm-only path, not the
                # blocking wait=True body it never reaches
                verdict = _const_test(stmt.test, consts)
                if verdict is True:
                    self._scan(mod, info, stmt.body, held, depth, consts)
                    if _terminates(stmt.body):
                        return
                    continue
                if verdict is False:
                    self._scan(mod, info, stmt.orelse, held, depth, consts)
                    if stmt.orelse and _terminates(stmt.orelse):
                        return
                    continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                # the context expressions themselves evaluate under any
                # OUTER held locks — `with urlopen(url):` inside a lock
                # region is a network call under the lock
                if held:
                    self._check_calls(mod, info, stmt, held, depth,
                                      consts)
                acquired = []
                for item in stmt.items:
                    kind, name = self.analysis.lock_kind(
                        mod, info.class_name, item.context_expr)
                    if kind is None:
                        continue
                    if held:
                        edge = (held[-1][1], name)
                        if edge not in self.edges:
                            self.edges[edge] = (mod, stmt.lineno,
                                                info.qualname)
                    if kind == "lock" and any(h[1] == name for h in held):
                        self.reacquire.append(Finding(
                            ORDER_RULE, mod.rel, stmt.lineno,
                            f"{info.qualname} re-acquires non-reentrant "
                            f"lock {name} it already holds — guaranteed "
                            "self-deadlock on this path",
                            key=f"reacquire:{info.qualname}:{name}"))
                    acquired.append((kind, name))
                self._scan(mod, info, stmt.body, held + tuple(acquired),
                           depth, consts)
                continue
            if held:
                self._check_calls(mod, info, stmt, held, depth, consts)
            # recurse into compound statements with the same held set
            for sub in sub_suites(stmt):
                self._scan(mod, info, sub, held, depth, consts)

    def _check_calls(self, mod, info: FuncInfo, stmt: ast.stmt,
                     held: tuple, depth: int, consts: dict) -> None:
        """Blocking-call check + bounded callee descent for one
        statement's own expressions (held is non-empty)."""
        for call in calls_in(stmt):
            desc = _blocking_desc(call)
            if desc is not None:
                self._blocking_finding(mod, info, call, desc, held)
                continue
            callee = self.analysis.resolve_call(mod, info, call)
            if callee is not None and depth < 2 and \
                    not isinstance(callee.node, ast.Lambda):
                ccon = call_consts(call, callee)
                vkey = (callee.module.rel, callee.qualname,
                        tuple(h[1] for h in held),
                        tuple(sorted(ccon.items())))
                if vkey not in self._visited:
                    self._visited.add(vkey)
                    self._scan(callee.module, callee,
                               callee.node.body, held, depth + 1, ccon)

    # -- cycles ------------------------------------------------------------

    def _cycle_findings(self, mod) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        for (a, b) in self.edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        in_cycle: set[tuple[str, str]] = set()
        for (a, b) in self.edges:
            if a != b and self._reaches(graph, b, a):
                in_cycle.add((a, b))
        out = list(self.reacquire)
        for (a, b) in sorted(in_cycle):
            m, line, context = self.edges[(a, b)]
            out.append(Finding(
                ORDER_RULE, m.rel, line,
                f"lock-order cycle: {context} acquires {b} while holding "
                f"{a}, but elsewhere in this module {a} is acquired "
                f"under {b} — two threads taking the locks in opposite "
                "orders deadlock",
                key=f"cycle:{a}->{b}"))
        return out

    @staticmethod
    def _reaches(graph: dict[str, set[str]], src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(graph.get(n, ()))
        return False


def _scan_all(analysis: Analysis) -> tuple[list[Finding], list[Finding]]:
    """One traversal serves both rules (memoized on the Analysis
    instance — the default run invokes both, and the held-lock
    call-graph walk is the engine's heaviest pass)."""
    cached = getattr(analysis, "_lock_scan", None)
    if cached is not None:
        return cached
    sc = _Scanner(analysis)
    order: list[Finding] = []
    for mod in analysis.modules:
        order.extend(sc.scan_module(mod))
    analysis._lock_scan = (sc.blocking, order)
    return analysis._lock_scan


def check_blocking(analysis: Analysis):
    return _scan_all(analysis)[0]


def check_order(analysis: Analysis):
    return _scan_all(analysis)[1]
