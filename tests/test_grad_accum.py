"""Gradient accumulation: accum=N over batch B must equal one step over
the full batch (same optimizer math, smaller activation peak)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from tpucfn.parallel import ShardingRules, shard_batch
from tpucfn.train import Trainer, TrainerConfig


def _init(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "fc1": {"kernel": jax.random.normal(k1, (4, 16)) * 0.1},
        "fc2": {"kernel": jax.random.normal(k2, (16, 2)) * 0.1},
    }, {}


def _loss(params, mstate, batch, rng):
    h = jnp.tanh(batch["x"] @ params["fc1"]["kernel"])
    pred = h @ params["fc2"]["kernel"]
    loss = jnp.mean((pred - batch["y"]) ** 2)
    return loss, ({"mae": jnp.mean(jnp.abs(pred - batch["y"]))}, mstate)


def _batch():
    rs = np.random.RandomState(0)
    return {"x": rs.randn(32, 4).astype(np.float32),
            "y": rs.randn(32, 2).astype(np.float32)}


def test_accum_matches_full_batch(mesh_dp8):
    rules = ShardingRules(((r".*", P()),))
    results = {}
    for name, accum in [("full", 1), ("accum4", 4)]:
        trainer = Trainer(mesh_dp8, rules, _loss, optax.sgd(0.1), _init,
                          config=TrainerConfig(grad_accum=accum))
        state = trainer.init(jax.random.key(0))
        batch = shard_batch(mesh_dp8, _batch())
        for _ in range(3):
            state, m = trainer.step(state, batch)
        results[name] = (float(m["loss"]),
                         np.asarray(state.params["fc1"]["kernel"]))
    # SGD on mean-of-microbatch-grads == SGD on full-batch grad
    np.testing.assert_allclose(results["full"][0], results["accum4"][0], rtol=1e-5)
    np.testing.assert_allclose(results["full"][1], results["accum4"][1], rtol=1e-5)


def test_accum_metrics_are_means(mesh_dp8):
    rules = ShardingRules(((r".*", P()),))
    t1 = Trainer(mesh_dp8, rules, _loss, optax.sgd(0.0), _init)
    t4 = Trainer(mesh_dp8, rules, _loss, optax.sgd(0.0), _init,
                 config=TrainerConfig(grad_accum=4))
    s1 = t1.init(jax.random.key(0))
    s4 = t4.init(jax.random.key(0))
    b = shard_batch(mesh_dp8, _batch())
    _, m1 = t1.step(s1, b)
    _, m4 = t4.step(s4, b)
    np.testing.assert_allclose(float(m1["mae"]), float(m4["mae"]), rtol=1e-5)
