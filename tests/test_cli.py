"""CLI workflow tests — the reference's README walkthrough as automation:
create-stack → status → env → launch → kill-host → heal → resize → delete.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tpucfn.cli.main import main

REPO = Path(__file__).resolve().parent.parent


def _cli(tmp_path, *argv):
    return main(["--state-dir", str(tmp_path / "state"), *argv])


def test_full_walkthrough(tmp_path, capsys):
    assert _cli(tmp_path, "create-stack", "--name", "demo", "--accelerator", "v4-32") == 0
    out = capsys.readouterr().out
    assert "CREATE_COMPLETE demo" in out
    assert "4 hosts" in out

    assert _cli(tmp_path, "status", "--name", "demo") == 0
    out = capsys.readouterr().out
    assert "ACTIVE" in out and "host3" in out

    assert _cli(tmp_path, "env", "--name", "demo") == 0
    out = capsys.readouterr().out
    assert "export TPUCFN_WORKERS_COUNT='4'" in out
    assert "export DEEPLEARNING_WORKERS_COUNT='4'" in out  # legacy alias

    # launch: each host writes its id into a file
    marker = tmp_path / "marker"
    marker.mkdir()
    rc = _cli(
        tmp_path, "launch", "--name", "demo", "--",
        sys.executable, "-c",
        f"import os,pathlib;pathlib.Path(r'{marker}').joinpath("
        "os.environ['TPUCFN_HOST_ID']).write_text('ok')",
    )
    assert rc == 0
    assert sorted(p.name for p in marker.iterdir()) == ["0", "1", "2", "3"]

    assert _cli(tmp_path, "resize", "--name", "demo", "--accelerator", "v4-64") == 0
    assert "RESIZE_COMPLETE" in capsys.readouterr().out
    _cli(tmp_path, "status", "--name", "demo")
    assert "host7" in capsys.readouterr().out

    assert _cli(tmp_path, "delete", "--name", "demo") == 0
    assert "DELETE_COMPLETE" in capsys.readouterr().out


def test_fault_injection_and_heal(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "ft", "--accelerator", "v4-16")
    capsys.readouterr()
    _cli(tmp_path, "kill-host", "--name", "ft", "--host", "1")
    capsys.readouterr()
    _cli(tmp_path, "status", "--name", "ft")
    assert "DEAD" in capsys.readouterr().out
    assert _cli(tmp_path, "heal", "--name", "ft") == 0
    assert "gen=2" in capsys.readouterr().out
    _cli(tmp_path, "status", "--name", "ft")
    assert "DEAD" not in capsys.readouterr().out


def test_launch_requires_active(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "gone", "--accelerator", "cpu-8")
    _cli(tmp_path, "delete", "--name", "gone")
    capsys.readouterr()
    rc = _cli(tmp_path, "launch", "--name", "gone", "--", "true")
    assert rc == 1
    assert "not ACTIVE" in capsys.readouterr().err


def test_spec_file_create(tmp_path, capsys):
    spec = {"name": "from-file", "accelerator": "v5p-64", "storage_path": "gs://b/x"}
    f = tmp_path / "cluster.json"
    f.write_text(json.dumps(spec))
    assert _cli(tmp_path, "create-stack", "--spec", str(f)) == 0
    out = capsys.readouterr().out
    assert "8 hosts" in out


def test_cli_subprocess_entry(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "tpucfn.cli", "--state-dir", str(tmp_path),
         "create-stack", "--name", "subp", "--accelerator", "cpu-8"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "CREATE_COMPLETE subp" in r.stdout


def test_state_persists_across_invocations(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "persist", "--accelerator", "v4-16")
    capsys.readouterr()
    # fresh control-plane object (new invocation) still sees the cluster
    assert _cli(tmp_path, "status", "--name", "persist") == 0
    assert "ACTIVE" in capsys.readouterr().out
    state_file = tmp_path / "state" / "control_plane.json"
    assert state_file.exists()


def test_unknown_cluster_errors(tmp_path):
    with pytest.raises(KeyError):
        _cli(tmp_path, "status", "--name", "nope")


# -- tpucfn check (ISSUE 10) ------------------------------------------------
# rc/JSON contract pinned so tooling (the builder loop, CI wrappers) can
# consume it: rc 0 clean, rc 1 findings, rc 2 usage error; --json emits
# exactly one JSON object per finding with file/line/rule/fingerprint/
# message keys.

CHECK_BUG_SRC = '''
import threading


class Router:
    def __init__(self):
        self._lock = threading.Lock()

    def relaunch(self, timeout=10.0):
        with self._lock:
            self._thread.join(timeout)
'''


def _check_pkg(tmp_path, src=CHECK_BUG_SRC):
    pkg = tmp_path / "repo" / "pkg"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "router.py").write_text(src)
    return pkg


def test_check_json_one_line_per_finding_rc1(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    rc = _cli(tmp_path, "check", "--json", str(pkg))
    assert rc == 1
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert set(rec) == {"file", "line", "rule", "fingerprint", "message"}
    assert rec["rule"] == "blocking-under-lock"
    assert rec["file"].endswith("pkg/router.py")
    assert isinstance(rec["line"], int) and rec["line"] > 0
    assert isinstance(rec["fingerprint"], str) and len(rec["fingerprint"]) == 16


def test_check_clean_package_rc0(tmp_path, capsys):
    pkg = _check_pkg(tmp_path, "X = 1\n")
    rc = _cli(tmp_path, "check", "--json", str(pkg))
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_check_usage_errors_rc2(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    assert _cli(tmp_path, "check", "--rules", "nosuch", str(pkg)) == 2
    assert "unknown rule" in capsys.readouterr().err
    assert _cli(tmp_path, "check", str(pkg / "missing")) == 2
    capsys.readouterr()
    assert _cli(tmp_path, "check", "--baseline",
                str(tmp_path / "nope.json"), str(pkg)) == 2


def test_check_baseline_suppresses_to_rc0(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    bp = tmp_path / "baseline.json"
    assert _cli(tmp_path, "check", "--baseline", str(bp)) == 2  # missing
    capsys.readouterr()
    # --update-baseline writes it; justify; then the run is clean
    assert _cli(tmp_path, "check", "--update-baseline",
                "--baseline", str(bp), str(pkg)) == 0
    capsys.readouterr()
    body = bp.read_text().replace(
        "TODO: one line on why this finding is deliberately kept",
        "bounded join by design")
    bp.write_text(body)
    rc = _cli(tmp_path, "check", "--json", "--baseline", str(bp), str(pkg))
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_check_rules_filter(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    rc = _cli(tmp_path, "check", "--json", "--rules", "signal-safety",
              str(pkg))
    assert rc == 0  # the join bug is not a signal-safety finding
    assert capsys.readouterr().out.strip() == ""


def test_check_update_baseline_refuses_partial_views(tmp_path, capsys):
    # review fix: rewriting the baseline from a --rules or --diff
    # subset would silently drop every other rule's suppressions
    pkg = _check_pkg(tmp_path)
    bp = tmp_path / "baseline.json"
    rc = _cli(tmp_path, "check", "--update-baseline", "--baseline", str(bp),
              "--rules", "signal-safety", str(pkg))
    assert rc == 2
    assert "--rules" in capsys.readouterr().err
    assert not bp.exists()


# -- chaos plane (ISSUE 15) -------------------------------------------------

def test_chaos_proxy_usage_errors_rc2(tmp_path, capsys):
    # bad upstream format
    assert _cli(tmp_path, "chaos", "proxy", "--upstream", "nocolon") == 2
    assert "HOST:PORT" in capsys.readouterr().err
    # bad spec JSON
    assert _cli(tmp_path, "chaos", "proxy", "--upstream", "127.0.0.1:1",
                "--spec", '{"faults": [{"kind": "flood"}]}') == 2
    assert "bad --spec" in capsys.readouterr().err
    # missing spec file
    assert _cli(tmp_path, "chaos", "proxy", "--upstream", "127.0.0.1:1",
                "--spec", str(tmp_path / "absent.json")) == 2


def test_chaos_proxy_serves_and_prints_stats(tmp_path, capsys):
    """The real CLI path: a proxy fronting a live socket, one proxied
    round trip, scheduled fault fired, stats JSON on exit."""
    import socket as _socket
    import threading
    import time

    srv = _socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    srv.settimeout(5.0)
    up_port = srv.getsockname()[1]
    spec = json.dumps({"seed": 5, "faults": [
        {"kind": "latency", "at_s": 0.0, "delay_s": 0.01,
         "duration_s": 9.0}]})
    result = {}

    def drive():
        # wait for the proxy's address line on stderr is not available
        # in-process; poll-connect to the fixed listen port instead
        for _ in range(100):
            try:
                c = _socket.create_connection(("127.0.0.1", listen),
                                              timeout=1.0)
                break
            except OSError:
                time.sleep(0.05)
        else:
            return
        a, _ = srv.accept()
        c.sendall(b"ping")
        got = a.recv(4)
        a.sendall(got)
        result["echo"] = c.recv(4)
        c.close()
        a.close()

    # an ephemeral free port (bind-0-then-close), never a hardcoded
    # number — any occupant would EADDRINUSE the proxy and fail the
    # test with no product defect
    probe = _socket.socket()
    probe.bind(("127.0.0.1", 0))
    listen = probe.getsockname()[1]
    probe.close()
    t = threading.Thread(target=drive, daemon=True)
    t.start()
    rc = _cli(tmp_path, "chaos", "proxy", "--listen", str(listen),
              "--upstream", f"127.0.0.1:{up_port}", "--spec", spec,
              "--serve-for", "1.5")
    t.join(timeout=10)
    srv.close()
    assert rc == 0
    assert result.get("echo") == b"ping"
    line = capsys.readouterr().out.strip().splitlines()[-1]
    stats = json.loads(line)
    assert stats["connections"] == 1
    assert stats["faults_fired"] == 1
    assert stats["fired"][0]["kind"] == "latency"
    assert stats["forwarded_bytes"] >= 8


def test_launch_chaos_requires_ft_rc2(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "cx", "--accelerator",
         "v4-8")
    capsys.readouterr()
    rc = _cli(tmp_path, "launch", "--name", "cx",
              "--chaos", '{"events": []}', "--",
              sys.executable, "-c", "pass")
    assert rc == 2
    assert "--chaos needs --ft" in capsys.readouterr().err


def test_launch_chaos_bad_spec_and_bad_proxy_rc2(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "cy", "--accelerator",
         "v4-8")
    capsys.readouterr()
    rc = _cli(tmp_path, "launch", "--name", "cy", "--ft",
              "--chaos", '{"events": [{"action": "flood", "at_s": 1}]}',
              "--", sys.executable, "-c", "pass")
    assert rc == 2
    assert "bad --chaos spec" in capsys.readouterr().err
    rc = _cli(tmp_path, "launch", "--name", "cy", "--ft",
              "--chaos", '{"events": []}',
              "--chaos-proxy", "notaport:127.0.0.1:7641",
              "--", sys.executable, "-c", "pass")
    assert rc == 2
    assert "--chaos-proxy wants" in capsys.readouterr().err


def test_launch_chaos_spec_schedules_like_kill(tmp_path, capsys):
    """Acceptance: a net_* op rides `tpucfn launch --chaos` exactly
    like kill — one spec file schedules a kill AND a net fault against
    the launch-owned proxy; the run completes and journals both
    firings."""
    spec = tmp_path / "chaos.json"
    spec.write_text(json.dumps({"seed": 0, "events": [
        {"action": "net_latency", "at_s": 0.2, "delay_s": 0.01,
         "duration_s": 5.0},
        {"action": "kill", "at_s": 0.4, "host": 0},
    ]}))
    _cli(tmp_path, "create-stack", "--name", "cz", "--accelerator",
         "v4-8")
    capsys.readouterr()
    # an idle upstream for the proxy to front (never dialed here; the
    # net_latency lands on the proxy regardless of traffic)
    import socket as _socket

    up = _socket.socket()
    up.bind(("127.0.0.1", 0))
    up.listen(1)
    rc = _cli(tmp_path, "launch", "--name", "cz", "--ft",
              "--restarts", "1", "--ft-startup-grace", "30",
              "--chaos", str(spec),
              "--chaos-proxy", f"0:127.0.0.1:{up.getsockname()[1]}",
              "--", sys.executable, "-c", "import time; time.sleep(1.2)")
    up.close()
    assert rc == 0  # the killed rank was relaunched within budget
    ft_dir = tmp_path / "state" / "clusters" / "cz" / "ft"
    events = [json.loads(s) for s in
              (ft_dir / "events.jsonl").read_text().splitlines() if s]
    kinds = [e["kind"] for e in events]
    assert "chaos_net_fault" in kinds
    net = next(e for e in events if e["kind"] == "chaos_net_fault")
    assert net["fault"] == "latency"
    from tpucfn.ft.journal import journal_path as _jp, replay_journal as _rj

    _st, recs, _ = _rj(_jp(ft_dir))
    fired = [r for r in recs if r["kind"] == "chaos_fired"]
    assert {r["action"] for r in fired} == {"net_latency", "kill"}


def test_launch_chaos_net_events_require_a_proxy_rc2(tmp_path, capsys):
    """Review fix: a net_* event with no --chaos-proxy to land on is a
    usage error at parse time, never a coordinator exception mid-run."""
    _cli(tmp_path, "create-stack", "--name", "cw", "--accelerator",
         "v4-8")
    capsys.readouterr()
    rc = _cli(tmp_path, "launch", "--name", "cw", "--ft",
              "--chaos",
              '{"events": [{"action": "net_stall", "at_s": 1}]}',
              "--", sys.executable, "-c", "pass")
    assert rc == 2
    assert "--chaos-proxy" in capsys.readouterr().err
    # bad net params fail the SPEC PARSE (ChaosEvent validation)
    rc = _cli(tmp_path, "launch", "--name", "cw", "--ft",
              "--chaos",
              '{"events": [{"action": "net_latency", "at_s": 1}]}',
              "--", sys.executable, "-c", "pass")
    assert rc == 2
    assert "bad --chaos spec" in capsys.readouterr().err
