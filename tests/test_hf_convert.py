"""HF Llama import: our model must reproduce the canonical torch
implementation's logits from converted weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from tpucfn.models.hf_convert import from_hf_llama  # noqa: E402
from tpucfn.models.llama import Llama  # noqa: E402


def _tiny_hf_model(tie=False):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=128, max_position_embeddings=512,
        rope_theta=500000.0, rms_norm_eps=1e-5,
        attention_bias=False, mlp_bias=False, tie_word_embeddings=tie)
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(hf_cfg).eval()


def test_hf_llama_logits_parity():
    hf = _tiny_hf_model()
    cfg, params = from_hf_llama(hf, dtype=jnp.float32, remat=False)
    assert cfg.n_kv_heads == 2 and cfg.head_dim == 16

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size, (2, 24)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(toks).long()).logits.numpy()
    out = Llama(cfg).apply({"params": jax.tree.map(jnp.asarray, params)},
                           jnp.asarray(toks))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=2e-4)


def test_hf_llama_tied_embeddings():
    hf = _tiny_hf_model(tie=True)
    cfg, params = from_hf_llama(hf, dtype=jnp.float32, remat=False)
    np.testing.assert_array_equal(
        params["lm_head"]["kernel"],
        params["embed_tokens"]["embedding"].T)
    rs = np.random.RandomState(1)
    toks = rs.randint(0, cfg.vocab_size, (1, 8)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(toks).long()).logits.numpy()
    out = Llama(cfg).apply({"params": jax.tree.map(jnp.asarray, params)},
                           jnp.asarray(toks))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=2e-4)


def _tiny_hf_mixtral(sliding_window=None):
    hf_cfg = transformers.MixtralConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=128, max_position_embeddings=512,
        num_local_experts=4, num_experts_per_tok=2,
        rope_theta=10000.0, rms_norm_eps=1e-5,
        sliding_window=sliding_window, attention_dropout=0.0)
    torch.manual_seed(0)
    return transformers.MixtralForCausalLM(hf_cfg).eval()


def test_hf_mixtral_logits_parity():
    """Mixtral import: our capacity-MoE (exactly-dropless capacity,
    renormalized-top-k gating) must reproduce HF's dropless sparse MoE
    logits — a cross-implementation check of routing + expert SwiGLU on
    top of the attention/RoPE stack."""
    from tpucfn.models.hf_convert import from_hf_mixtral

    hf = _tiny_hf_mixtral()
    cfg, params = from_hf_mixtral(hf, dtype=jnp.float32, remat=False)
    assert cfg.moe is not None and cfg.moe.n_experts == 4
    assert cfg.moe.capacity_factor == 2.0  # E/k: exactly dropless

    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size, (2, 24)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(toks).long()).logits.numpy()
    out, _ = Llama(cfg).apply(
        {"params": jax.tree.map(jnp.asarray, params)}, jnp.asarray(toks),
        mutable=["losses", "metrics"])
    np.testing.assert_allclose(np.asarray(out), ref, atol=3e-4, rtol=3e-4)


def test_hf_mixtral_greedy_decode_parity():
    """Serving-path cross-check: our KV-cache greedy decode on imported
    Mixtral weights produces the same tokens as HF's generate — pins
    MoE routing under decode mode (per-step T=B tokens) plus the cache
    plumbing, not just the teacher-forced forward. Prompt tokens avoid
    id 0: HF infers attention_mask from pad_token_id and would mask
    real 0-tokens."""
    from tpucfn.models.generate import generate
    from tpucfn.models.hf_convert import from_hf_mixtral

    hf = _tiny_hf_mixtral()
    cfg, params = from_hf_mixtral(hf, dtype=jnp.float32, remat=False)
    prompt = np.random.RandomState(3).randint(1, 256, (2, 8)).astype(np.int32)
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(prompt).long(), max_new_tokens=8,
                          do_sample=False, pad_token_id=0).numpy()
    out = generate(cfg, jax.tree.map(jnp.asarray, params),
                   jnp.asarray(prompt), max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out)[:, 8:], ref[:, 8:])


def test_hf_mixtral_refuses_sliding_window():
    from tpucfn.models.hf_convert import config_from_hf_mixtral

    hf_cfg = transformers.MixtralConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, num_key_value_heads=2, intermediate_size=64,
        num_local_experts=2, num_experts_per_tok=1, sliding_window=1024)
    with pytest.raises(NotImplementedError, match="sliding_window"):
        config_from_hf_mixtral(hf_cfg)


def test_hf_convert_refuses_unsupported_features():
    from tpucfn.models.hf_convert import config_from_hf, from_hf_llama

    hf_cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, num_key_value_heads=2, intermediate_size=64,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 8192})
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        config_from_hf(hf_cfg)

    biased = transformers.LlamaForCausalLM(transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, num_key_value_heads=2, intermediate_size=64,
        attention_bias=True)).eval()
    with pytest.raises(NotImplementedError, match="unmapped"):
        from_hf_llama(biased)
