"""ctypes binding for the native tpurecord reader (native/tpurecord.cc).

The C++ library owns the hot read path (offset indexing, CRC validation,
batched contiguous copies, GIL released during calls); this module loads
it, auto-building with g++ on first use, and degrades to the pure-Python
reader in :mod:`tpucfn.data.records` when no toolchain is available —
same format, same errors, ~10× slower.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Iterator, Sequence

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libtpurecord.so"
_lib = None
_lib_error: str | None = None


def _load_lib():
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        if not _LIB_PATH.exists():
            subprocess.run(["sh", str(_NATIVE_DIR / "build.sh")], check=True,
                           capture_output=True, text=True, timeout=120)
        lib = ctypes.CDLL(str(_LIB_PATH))
        if not hasattr(lib, "tpurec_validate"):
            # Stale .so from before the zero-copy entry points: rebuild,
            # then load under a DIFFERENT path — dlopen caches by
            # original path and re-CDLL'ing _LIB_PATH would return the
            # old image even after the file on disk changed. The copy
            # path is deterministic (keyed by mtime+size) so concurrent
            # or repeated upgrades reuse one file instead of leaking a
            # temp dir per process.
            import shutil
            import tempfile

            subprocess.run(["sh", str(_NATIVE_DIR / "build.sh")], check=True,
                           capture_output=True, text=True, timeout=120)
            st = _LIB_PATH.stat()
            # Per-uid 0700 cache dir (a world-writable /tmp path could be
            # pre-planted by another local user); unique-name + rename so
            # a concurrent upgrader never dlopens a half-written copy.
            cache_dir = Path(tempfile.gettempdir()) / f"tpurec-{os.getuid()}"
            try:
                cache_dir.mkdir(mode=0o700, exist_ok=True)
                dstat = cache_dir.stat()
                if dstat.st_uid != os.getuid() or (dstat.st_mode & 0o077):
                    raise OSError("cache dir not exclusively ours")
            except OSError:
                cache_dir = Path(tempfile.mkdtemp(prefix="tpurec-"))
            fresh = cache_dir / f"{st.st_mtime_ns}-{st.st_size}.so"
            if not fresh.exists():
                tmp_fd, tmp_name = tempfile.mkstemp(dir=cache_dir,
                                                    suffix=".so.part")
                os.close(tmp_fd)
                shutil.copyfile(_LIB_PATH, tmp_name)
                os.replace(tmp_name, fresh)  # atomic publish
            lib = ctypes.CDLL(str(fresh))
        lib.tpurec_open.restype = ctypes.c_void_p
        lib.tpurec_open.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        lib.tpurec_count.restype = ctypes.c_long
        lib.tpurec_count.argtypes = [ctypes.c_void_p]
        # (tpurec_length / tpurec_read / tpurec_read_batch are the
        # copy-out C embedding API — unused by this zero-copy binding.)
        lib.tpurec_index.restype = None
        lib.tpurec_index.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.tpurec_validate.restype = ctypes.c_long
        lib.tpurec_validate.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_long), ctypes.c_long,
        ]
        lib.tpurec_close.restype = None
        lib.tpurec_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception as e:  # no g++ / build failure → Python fallback
        _lib_error = str(e)
    return _lib


def native_available() -> bool:
    return _load_lib() is not None


class NativeShardReader:
    """CRC-validated reader over one tpurecord shard, backed by C++."""

    def __init__(self, path: str | Path):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError(f"native reader unavailable: {_lib_error}")
        err = ctypes.create_string_buffer(256)
        self._lib = lib
        self._h = lib.tpurec_open(str(path).encode(), err, len(err))
        if not self._h:
            raise ValueError(f"{path}: {err.value.decode()}")
        self.path = str(path)
        # Zero-copy read path: C++ owns the validated index and the CRC
        # scan (GIL released); payload bytes are served as memoryviews
        # over this mapping — no per-record copy anywhere.
        n = int(lib.tpurec_count(self._h))
        self._offs = np.zeros(n, np.int64)
        self._lens = np.zeros(n, np.int64)
        if n:
            lib.tpurec_index(
                self._h,
                self._offs.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
                self._lens.ctypes.data_as(ctypes.POINTER(ctypes.c_long)))
        if n == 0:
            self._mm = None
        else:
            try:
                self._mm = memoryview(np.memmap(self.path, np.uint8, mode="r"))
            except (OSError, ValueError):
                # Filesystems without mmap (some FUSE/network mounts):
                # one read()-copy at open, views served over it — the
                # same behavior the C++ side falls back to.
                self._mm = memoryview(np.fromfile(self.path, np.uint8))

    def __len__(self) -> int:
        return int(self._lib.tpurec_count(self._h))

    def read(self, idx: int) -> memoryview:
        if idx < 0 or idx >= len(self._offs):
            raise IndexError(f"record {idx} out of range in {self.path}")
        return self.read_batch([idx])[0]

    def read_batch(self, indices: Sequence[int]) -> list[memoryview]:
        """Zero-copy batch read: ONE FFI call CRC-validates the records
        in place (C++, GIL released), then payloads are returned as
        memoryviews straight over the file mapping — no data copy on
        either side of the boundary. (The earlier copy-out design lost
        to the pure-Python reader on large records: its crc+memcpy was
        two memory passes against Python's one — data_bench history.)
        Views are bytes-compatible for every consumer (decode_example
        wraps them in BytesIO); they keep the mapping alive."""
        n = len(indices)
        if n == 0:
            return []
        idx_arr = (ctypes.c_long * n)(*indices)
        bad = int(self._lib.tpurec_validate(self._h, idx_arr, n))
        if bad == -3:
            raise IndexError(f"batch indices out of range in {self.path}")
        if bad >= 0:
            raise ValueError(f"{self.path}: CRC mismatch at record {bad}")
        mm, offs, lens = self._mm, self._offs, self._lens
        return [mm[offs[i]:offs[i] + lens[i]] for i in indices]

    _ITER_CHUNK = 1024  # validate-call granularity (no buffers involved)

    def __iter__(self) -> Iterator[memoryview]:
        n = len(self)
        for start in range(0, n, self._ITER_CHUNK):
            yield from self.read_batch(range(start, min(start + self._ITER_CHUNK, n)))

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.tpurec_close(self._h)
            self._h = None
            self._mm = None  # outstanding views keep the mapping alive

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def read_record_shard_native(path: str | Path) -> Iterator[bytes]:
    """Drop-in for :func:`tpucfn.data.records.read_record_shard`."""
    r = NativeShardReader(path)
    try:
        yield from r
    finally:
        r.close()


def decode_batch(reader: NativeShardReader, indices: Sequence[int]) -> list[dict[str, np.ndarray]]:
    from tpucfn.data.records import decode_example

    return [decode_example(p) for p in reader.read_batch(indices)]
