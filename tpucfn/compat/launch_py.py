"""``launch.py``-shaped compat entry point.

The reference's documented launch line (SURVEY.md §3.2) was dmlc's

    ../../tools/launch.py -n $DEEPLEARNING_WORKERS_COUNT \
        -H $DEEPLEARNING_WORKERS_PATH python train.py …

This module accepts that exact argv shape:

    python -m tpucfn.compat.launch_py -n $TPUCFN_WORKERS_COUNT \
        -H $TPUCFN_WORKERS_PATH python train.py …

and fans out through the tpucfn Launcher (ssh transport by default, like
the dmlc tracker; ``--local`` for single-machine/test runs). The legacy
env names still resolve, so a reference-era shell line works after
s/launch.py/python -m tpucfn.compat.launch_py/.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tpucfn.bootstrap import COORDINATOR_PORT, EnvContract
from tpucfn.launch import Launcher, LocalTransport, SSHTransport, run_with_restarts


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="launch.py", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("-n", "--num-workers", type=int, required=True,
                   help="number of worker hosts (≈ dmlc launch.py -n)")
    p.add_argument("-H", "--hostfile", required=True,
                   help="hostfile path (≈ dmlc launch.py -H)")
    p.add_argument("--local", action="store_true",
                   help="spawn locally instead of over ssh (tests/single box)")
    p.add_argument("--restarts", type=int, default=0)
    p.add_argument("cmd", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        print("error: no command given", file=sys.stderr)
        return 2

    hosts = Path(args.hostfile).read_text().split()
    if len(hosts) < args.num_workers:
        print(f"error: hostfile has {len(hosts)} hosts, -n asked for "
              f"{args.num_workers}", file=sys.stderr)
        return 2
    hosts = hosts[: args.num_workers]

    coord_host = hosts[0].rsplit(":", 1)[0]
    contract = EnvContract(
        workers_path=str(Path(args.hostfile).absolute()),
        workers_count=args.num_workers,
        worker_chip_count=0,  # unknown at this surface; runtime discovers
        coordinator=f"{coord_host}:{COORDINATOR_PORT}",
        host_id=0,
        storage="",
        generation=0,
    )
    transport = LocalTransport() if args.local else SSHTransport()
    rc = run_with_restarts(Launcher(contract, transport), cmd,
                           max_restarts=args.restarts)
    return rc


if __name__ == "__main__":
    sys.exit(main())
