"""Deterministic toy trainer for the end-to-end ft chaos drill.

Behaves like a real tpucfn job from the recovery plane's point of view:
heartbeats via HeartbeatWriter (TPUCFN_FT_DIR fan-out), checkpoints via
CheckpointManager every FT_E2E_CKPT_EVERY steps (host 0 saves, everyone
restores), resume-from-latest on startup, and a per-step loss trajectory
appended to a JSONL so the test can compare an interrupted run against
an uninterrupted one step by step.  The math is pure numpy and exactly
deterministic: w ← 0.9·w + 0.1, loss = (w − 1)², so any two runs agree
bit-for-bit wherever their step ranges overlap.
"""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from tpucfn.ckpt import CheckpointManager  # noqa: E402  (imports jax/orbax)
from tpucfn.ft import (  # noqa: E402
    RESTORE_FAILED_RC,
    HeartbeatWriter,
    drain_requested,
)
from tpucfn.obs.goodput import GoodputLedger  # noqa: E402


def _latest_finalized_step(ckpt_dir: Path) -> int:
    """Latest finalized checkpoint step by scanning the directory.

    Orbax's ``CheckpointManager.latest_step()`` serves a step list cached
    at init and updated only by that manager's own saves, so host 1
    polling its manager would never see host 0's new checkpoints.
    Finalized step dirs are bare numbers; in-flight saves carry an
    ``.orbax-checkpoint-tmp-*`` suffix and are excluded.
    """
    try:
        return max((int(p.name) for p in ckpt_dir.iterdir()
                    if p.is_dir() and p.name.isdigit()), default=0)
    except OSError:
        return 0


def main() -> int:
    host = int(os.environ.get("TPUCFN_HOST_ID", "0"))
    run_dir = Path(os.environ["FT_E2E_RUN_DIR"])
    total = int(os.environ["FT_E2E_TOTAL_STEPS"])
    ckpt_every = int(os.environ.get("FT_E2E_CKPT_EVERY", "10"))
    step_sleep = float(os.environ.get("FT_E2E_STEP_SLEEP", "0.05"))
    ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()
    hb_s = float(os.environ.get("TPUCFN_FT_HEARTBEAT_S", "0.2") or 0.2)

    hb = None
    if ft_dir:
        hb = HeartbeatWriter(ft_dir, host_id=host, interval_s=hb_s,
                             role="e2e").start()
    # Forensics plane (ISSUE 6): when the launcher assigned an obs port,
    # run the full per-host surface a real trainer runs — flight ring
    # (dumped on SIGTERM/atexit, served on /flightrecorder for the
    # coordinator's at-detect capture), step trace spans (the postmortem
    # timeline), and /metrics.
    obs_srv = flight = tracer = None
    from tpucfn.obs import obs_port_from_env

    if obs_port_from_env() is not None:
        from tpucfn.obs import (FlightRecorder, MetricRegistry, Tracer,
                                start_obs_server)

        flight = FlightRecorder(capacity=1024, host_id=host, role="e2e")
        flight.install_dump_handlers(run_dir / "flight")
        tracer = Tracer(run_dir / "trace", host_id=host, role="e2e")
        obs_srv = start_obs_server(
            MetricRegistry(labels={"host": str(host), "role": "e2e"}),
            role="e2e", host_id=host, flight=flight)
    # Goodput ledger (ISSUE 5): every incarnation appends a new window
    # to the same per-host file; a SIGKILLed incarnation leaves no close
    # record, and the gap to the relaunch's window marker is what the
    # merge reports as restart_downtime.  Re-run steps after the rewind
    # are detected by their repeated step numbers (lost_work).
    ledger = GoodputLedger(run_dir / "goodput", host_id=host, role="e2e")
    template = {"step": np.zeros((), np.int64),
                "w": np.asarray(10.0, np.float64)}
    losses = run_dir / f"losses-host{host:03d}.jsonl"
    try:
        with CheckpointManager(run_dir / "ckpt", async_save=False,
                               save_interval_steps=ckpt_every) as ckpt:
            latest = ckpt.latest_step()
            if latest is not None:
                try:
                    state = ckpt.restore(template)
                except Exception as e:  # noqa: BLE001 — corrupt artifact
                    # Distinguishable rc (ISSUE 7): the coordinator
                    # blacklists the bad step and retries from the
                    # previous finalized one.
                    print(f"restore of step {latest} failed: {e}",
                          flush=True)
                    sys.exit(RESTORE_FAILED_RC)
                print(f"resumed from step {int(state['step'])}", flush=True)
            else:
                state = {k: v.copy() for k, v in template.items()}
            step = int(state["step"])
            w = float(state["w"])
            sync_deadline = time.monotonic() + 120.0
            with open(losses, "a") as f:
                while step < total:
                    if host != 0:
                        # Bound drift to one checkpoint interval, the way a
                        # real SPMD gang's collectives would: host 0 pays
                        # every orbax save, and an unbounded-drift host 1
                        # can drag the fleet max step (the chaos at_step
                        # trigger) past the kill point before host 0 has
                        # written the checkpoint the drill resumes from.
                        t0_wait = time.monotonic()
                        while (step + 1 - _latest_finalized_step(
                                   run_dir / "ckpt") > ckpt_every
                               and time.monotonic() < sync_deadline):
                            time.sleep(0.01)
                        t_wait = time.monotonic() - t0_wait
                        if t_wait >= 0.001:
                            ledger.account("data_wait", t_wait, step=step + 1)
                    t0_step = time.monotonic()
                    w = 0.9 * w + 0.1
                    step += 1
                    f.write(json.dumps({
                        "step": step, "w": w, "loss": (w - 1.0) ** 2,
                        "pid": os.getpid()}) + "\n")
                    f.flush()
                    if hb is not None:
                        hb.update_step(step)
                    time.sleep(step_sleep)
                    dur = time.monotonic() - t0_step
                    ledger.account("step", dur, step=step)
                    if flight is not None:
                        flight.record("step", step=step, dur_s=dur)
                    if tracer is not None:
                        tracer.record("step", start=t0_step, dur_s=dur,
                                      trace_id=step)
                    if host == 0:
                        t0_ckpt = time.monotonic()
                        if ckpt.save(step,
                                     {"step": np.asarray(step, np.int64),
                                      "w": np.asarray(w, np.float64)}):
                            ledger.account(
                                "ckpt", time.monotonic() - t0_ckpt,
                                step=step)
                    # Preemption drain (ISSUE 7): every host runs UP TO
                    # the drain file's target step and stops; the
                    # force-save below lands exactly there, so the
                    # relaunch re-executes nothing (lost_work == 0).
                    if ft_dir and drain_requested(ft_dir, step):
                        print(f"drained at step {step}", flush=True)
                        break
            if host == 0:
                t0_ckpt = time.monotonic()
                if ckpt.save(step, {"step": np.asarray(step, np.int64),
                                    "w": np.asarray(w, np.float64)},
                             force=True):
                    ledger.account("ckpt", time.monotonic() - t0_ckpt,
                                   step=step)
    finally:
        if hb is not None:
            hb.stop()
        ledger.close()
        if tracer is not None:
            tracer.close()
        if obs_srv is not None:
            obs_srv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
