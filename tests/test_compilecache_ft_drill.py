"""ISSUE 13 acceptance drill: a gang-restarted rank's relaunch charges
``compile_fetched`` (not ``compile``) in the goodput ledger, and the
trajectory stays bit-identical with the cache enabled.

Real processes end to end: a GangCoordinator supervises one rank whose
first incarnation compiles, publishes its executable to a live
ArtifactServer, and crashes; the relaunched incarnation (fresh local
store, so only the FLEET can serve it) fetches instead of recompiling.
"""

import json
import sys
from pathlib import Path

from tpucfn.bootstrap import EnvContract
from tpucfn.compilecache.service import ArtifactServer
from tpucfn.ft import GangCoordinator, GangRestart, RestartBudget
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.obs.goodput import host_goodput, read_goodput_dir

WORKER = str(Path(__file__).with_name("compilecache_ft_worker.py"))


def test_gang_restart_relaunch_fetches_instead_of_recompiling(tmp_path):
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("127.0.0.1:0\n")
    contract = EnvContract(
        workers_path=str(hostfile), workers_count=1, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)
    with ArtifactServer(tmp_path / "server-store",
                        host="127.0.0.1") as srv:
        launcher = Launcher(
            contract, LocalTransport(), ft_dir=str(tmp_path / "ft"),
            compile_cache_addrs=[srv.address],
            extra_env={"CC_DRILL_DIR": str(tmp_path),
                       "JAX_PLATFORMS": "cpu"})
        coord = GangCoordinator(
            launcher, [sys.executable, WORKER],
            policy=GangRestart(RestartBudget(1, backoff_s=0.0)),
            ft_dir=tmp_path / "ft", poll_interval=0.05, term_grace_s=2.0)
        rc = coord.run()
    assert rc == 0

    results = [json.loads(s) for s in
               (tmp_path / "results-host0.jsonl").read_text().splitlines()]
    assert len(results) == 2
    first, second = results
    assert first["outcome"] == "compile"
    assert second["outcome"] == "fetch"
    # bit-identical trajectory across compile vs fetched executable
    assert first["value"] == second["value"]

    by_host, _ = read_goodput_dir(tmp_path / "goodput")
    rep = host_goodput(by_host[0])
    assert rep["windows"] == 2
    buckets = rep["buckets"]
    # incarnation 1 compiled; incarnation 2 charged the fetch bucket
    # and NOT a second real compile
    assert buckets["compile"] > 0
    assert buckets["compile_fetched"] > 0
