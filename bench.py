#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training images/sec/chip.

This is BASELINE.md's primary metric. The reference repo published no
numbers (BASELINE.json `"published": {}`); the denominator for
``vs_baseline`` is the era-appropriate per-accelerator throughput of the
reference's target fleet — ResNet-50 mixed-precision training on the
p3.16xlarge V100s its README benchmarked on, ~400 images/sec/GPU — so
``vs_baseline`` reads as "times faster per chip than the reference stack's
per-GPU number".

Prints ONE JSON line:
    {"metric": "...", "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Runs on whatever jax.devices() provides (the driver gives one real TPU
chip). ``TPUCFN_BENCH_PRESET=tiny`` shrinks the model/batch for CI smoke
on CPU.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


REFERENCE_IMAGES_PER_SEC_PER_ACCEL = 400.0  # V100 ResNet-50 fp16, reference-era


def _tpu_reachable(timeout_s: float = 150.0) -> bool:
    """Probe TPU liveness in a subprocess. The axon tunnel can wedge in a
    way that hangs PJRT client creation forever (see memory note: killed
    clients leave the grant unreleased); a hung probe must not hang the
    benchmark, so the probe is killable."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "print(float((jnp.ones((8,8)) @ jnp.ones((8,8))).sum()))"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _ensure_backend() -> str:
    """Return 'tpu' if the chip answers, else force the CPU fallback (the
    driver always gets its one JSON line)."""
    if os.environ.get("PALLAS_AXON_POOL_IPS") and _tpu_reachable():
        return "tpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )
    os.environ.setdefault("TPUCFN_BENCH_PRESET", "tiny")
    return "cpu-fallback"


def main() -> int:
    mode = _ensure_backend()
    import jax

    if mode == "cpu-fallback":
        # sitecustomize already registered the axon plugin at interpreter
        # start; pinning platforms post-import is the reliable override.
        jax.config.update("jax_platforms", "cpu")

    # Persistent XLA compilation cache: the second "create-stack → first
    # step" on the same pod skips recompilation (SURVEY.md §7.4 item 6 —
    # keep the time-to-first-step metric from being compile-dominated).
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("TPUCFN_XLA_CACHE", "/tmp/tpucfn_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpucfn.bootstrap import converge
    from tpucfn.mesh import MeshSpec, build_mesh
    from tpucfn.models import ResNet, ResNetConfig
    from tpucfn.parallel import dense_rules, shard_batch
    from tpucfn.provision import FakeControlPlane, Provisioner
    from tpucfn.spec import ClusterSpec
    from tpucfn.train import Trainer

    tiny = os.environ.get("TPUCFN_BENCH_PRESET") == "tiny"
    n_dev = jax.device_count()

    # --- "create-stack" leg of time-to-first-step (BASELINE metric 2).
    # The control plane here is the in-process fake (this environment has
    # no cloud API); what it measures is the framework's own overhead:
    # provisioning state machine + bootstrap convergence + contract load.
    t_stack0 = time.perf_counter()
    prov = Provisioner(FakeControlPlane(steps_to_provision=1))
    rec = prov.create(ClusterSpec(name="bench", accelerator="cpu-1"))
    converge(rec, "/tmp/tpucfn-bench-run")
    provision_s = time.perf_counter() - t_stack0

    if tiny:
        cfg = ResNetConfig(stage_sizes=(1, 1, 1), num_classes=10, bottleneck=False,
                           width=8, cifar_stem=True, dtype=jnp.float32)
        image_hw, per_chip_batch, classes = 32, 8, 10
        steps, warmup = 8, 2
    else:
        cfg = ResNetConfig.resnet50()
        image_hw, per_chip_batch, classes = 224, 128, 1000
        steps, warmup = 30, 5

    global_batch = per_chip_batch * n_dev
    mesh = build_mesh(MeshSpec.for_devices(n_dev))
    model = ResNet(cfg)
    sample = jnp.zeros((1, image_hw, image_hw, 3))

    def init_fn(rng):
        v = model.init(rng, sample, train=True)
        return v["params"], {"batch_stats": v["batch_stats"]}

    def loss_fn(params, mstate, batch, rng):
        logits, upd = model.apply(
            {"params": params, **mstate}, batch["image"], train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        return loss, ({}, dict(upd))

    trainer = Trainer(
        mesh, dense_rules(fsdp=False), loss_fn,
        optax.sgd(0.1, momentum=0.9), init_fn,
    )

    t0 = time.perf_counter()
    state = trainer.init(jax.random.key(0))
    jax.block_until_ready(state.params)
    init_s = time.perf_counter() - t0

    rs = np.random.RandomState(0)
    batch = shard_batch(mesh, {
        "image": rs.randn(global_batch, image_hw, image_hw, 3).astype(np.float32),
        "label": rs.randint(0, classes, (global_batch,)).astype(np.int32),
    })

    t0 = time.perf_counter()
    state, metrics = trainer.step(state, batch)
    float(metrics["loss"])  # value fetch forces a true device sync
    compile_s = time.perf_counter() - t0

    # Warmup steps (post-compile jitter), fully synced.
    for _ in range(warmup):
        state, metrics = trainer.step(state, batch)
    float(metrics["loss"])

    # Timed region: enqueue `steps` steps and sync once at the end. The
    # chain of state dependencies forces serial device execution; a single
    # final value fetch avoids paying host↔device round-trip latency per
    # step (which on the tunneled dev chip dominates and on a real pod
    # would not exist — the input pipeline keeps the queue full).
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.step(state, batch)
    final_loss = float(metrics["loss"])
    mean_step = (time.perf_counter() - t0) / steps

    ips_chip = global_batch / mean_step / n_dev
    print(json.dumps({
        "metric": "resnet50_imagenet_train_images_per_sec_per_chip"
        if not tiny else "tiny_resnet_train_images_per_sec_per_chip",
        "value": round(ips_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_chip / REFERENCE_IMAGES_PER_SEC_PER_ACCEL, 3),
        "detail": {
            "devices": n_dev,
            "platform": jax.devices()[0].platform,
            "backend_mode": mode,
            "global_batch": global_batch,
            "mean_step_s": round(mean_step, 5),
            "compile_s": round(compile_s, 2),
            "init_s": round(init_s, 2),
            "time_to_first_step_s": round(provision_s + init_s + compile_s, 2),
            "final_loss": round(final_loss, 4),
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
