from tpucfn.compat.kvstore import create as kvstore_create  # noqa: F401
from tpucfn.compat import horovod  # noqa: F401
