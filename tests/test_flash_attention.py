"""Flash kernel vs the dense reference — forward and gradients, causal and
not, GQA, offsets. Runs in Pallas interpret mode on CPU (same kernel code
path the TPU compiles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpucfn.kernels import flash_attention
from tpucfn.ops.attention import dot_product_attention


def _qkv(b=2, sq=64, sk=64, hq=4, hkv=4, d=32, seed=0):
    rng = jax.random.key(seed)
    q = jax.random.normal(jax.random.fold_in(rng, 0), (b, sq, hq, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, sk, hkv, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, sk, hkv, d))
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_gqa():
    q, k, v = _qkv(hq=8, hkv=2)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_forward_offsets():
    q, k, v = _qkv(sq=32, sk=64)
    out = flash_attention(q, k, v, causal=True, q_offset=32, interpret=True)
    ref = dot_product_attention(q, k, v, causal=True, q_offset=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_fully_masked_is_zero():
    q, k, v = _qkv(sq=32, sk=32)
    out = flash_attention(q, k, v, causal=True, k_offset=1000, interpret=True)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_non_128_blocks():
    # S=48 forces _pick_block to a non-power block that still tiles S
    q, k, v = _qkv(sq=48, sk=48, d=16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_dense(causal):
    q, k, v = _qkv(sq=32, sk=32, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4,
                                   err_msg=f"d{name} mismatch")


def test_gradients_gqa():
    q, k, v = _qkv(sq=32, sk=32, hq=4, hkv=2, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4,
                                   err_msg=f"d{name} mismatch")


def test_bf16_forward_close():
    q, k, v = _qkv()
    out = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                          v.astype(jnp.bfloat16), causal=True, interpret=True)
    ref = dot_product_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=3e-2)
