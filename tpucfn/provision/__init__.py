from tpucfn.provision.control_plane import (  # noqa: F401
    ClusterState,
    ControlPlane,
    FakeControlPlane,
    HostRecord,
    ClusterRecord,
)
from tpucfn.provision.policy import (  # noqa: F401
    PROVISION_DECISION_TABLE,
    FleetObservation,
    GoodputSignal,
    PolicyAction,
    PolicyConfig,
    PolicyDecision,
    ProvisionPolicy,
    provision_policy_from_name,
)
from tpucfn.provision.provisioner import Provisioner  # noqa: F401
from tpucfn.provision.gcp import (  # noqa: F401
    AuthError,
    GcpQueuedResourceControlPlane,
    QuotaError,
)
