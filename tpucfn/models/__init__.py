from tpucfn.models.resnet import ResNet, ResNetConfig  # noqa: F401
