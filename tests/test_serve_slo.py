"""SLOTracker (ISSUE 5): TTFT/TPOT objectives with rolling-window burn
rate, wired into the Server request lifecycle and exported as
``serve_slo_*`` on the registry the /metrics endpoint scrapes."""

import time

import pytest

from tpucfn.obs import MetricRegistry
from tpucfn.serve import AdmissionError, Server
from tpucfn.serve.frontend import SLOTracker


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeEngine:
    def __init__(self, max_batch=4, cache_len=64, prefill_delay=0.002,
                 decode_delay=0.001):
        self.max_batch = max_batch
        self.cache_len = cache_len
        self.prefill_delay = prefill_delay
        self.decode_delay = decode_delay

    def prefill(self, slot, prefix, bucket, temperature=0.0):
        time.sleep(self.prefill_delay)
        return 11

    def decode(self, tokens_by_slot):
        time.sleep(self.decode_delay)
        return {s: 12 for s in tokens_by_slot}


# ---- the tracker alone (fake clock) --------------------------------------

def test_burn_rate_is_window_violation_rate_over_budget():
    clk = FakeClock()
    t = SLOTracker(MetricRegistry(), ttft_slo_s=0.2, tpot_slo_s=0.05,
                   objective=0.9, window_s=10.0, clock=clk)
    t.record(0.1, 0.01)  # both ok
    t.record(0.5, 0.01)  # ttft violation
    t.record(0.1, 0.10)  # tpot violation
    snap = t.snapshot()
    assert snap["requests"] == 3 and snap["window_requests"] == 3
    # 1/3 violations over a 0.1 error budget
    assert snap["ttft"]["burn_rate"] == pytest.approx((1 / 3) / 0.1)
    assert snap["tpot"]["burn_rate"] == pytest.approx((1 / 3) / 0.1)
    assert snap["ttft"]["violations_total"] == 1
    assert snap["tpot"]["violations_total"] == 1


def test_window_evicts_and_burn_rate_recovers():
    clk = FakeClock()
    t = SLOTracker(MetricRegistry(), ttft_slo_s=0.2, objective=0.99,
                   window_s=10.0, clock=clk)
    t.record(9.9, 0.0)  # violation at t=0
    assert t.snapshot()["ttft"]["burn_rate"] == pytest.approx(100.0)
    clk.t = 5.0
    t.record(0.1, 0.0)  # ok at t=5: violation still in window
    assert t.snapshot()["ttft"]["burn_rate"] == pytest.approx(50.0)
    clk.t = 11.0  # t=0 sample ages out; only the ok one remains
    snap = t.snapshot()
    assert snap["window_requests"] == 1
    assert snap["ttft"]["burn_rate"] == 0.0
    # totals are monotonic — the window forgets, the counters do not
    assert snap["ttft"]["violations_total"] == 1


def test_none_scores_as_violation_and_objective_validated():
    t = SLOTracker(MetricRegistry(), ttft_slo_s=1.0, tpot_slo_s=1.0)
    t.record(None, None)  # expired request: no usable answer
    snap = t.snapshot()
    assert snap["ttft"]["violations_total"] == 1
    assert snap["tpot"]["violations_total"] == 1
    with pytest.raises(ValueError):
        SLOTracker(MetricRegistry(), objective=1.0)


def test_cli_rejects_out_of_range_objective_as_usage_error():
    """`tpucfn serve --slo-objective 1` must be an argparse usage error
    (exit 2, no traceback) — not SLOTracker's ValueError escaping after
    the obs port is already bound."""
    from tpucfn.cli.main import main

    for bad in ("1", "0", "1.5", "nan"):
        with pytest.raises(SystemExit) as ei:
            main(["serve", "--preset", "tiny", "--synthetic", "1",
                  "--slo-objective", bad])
        assert ei.value.code == 2


def test_burn_rate_on_metrics_scrape_decays_without_traffic():
    """A /metrics scrape reads the gauges directly (never snapshot());
    the window gauges must be computed AS OF the scrape, or an alert on
    sustained burn keeps firing on dead traffic forever."""
    clk = FakeClock()
    reg = MetricRegistry()
    t = SLOTracker(reg, ttft_slo_s=0.2, objective=0.99, window_s=10.0,
                   clock=clk)
    t.record(9.9, 0.0)  # violation
    m = reg.varz()["metrics"]
    assert m["serve_slo_ttft_burn_rate"] == pytest.approx(100.0)
    assert m["serve_slo_window_requests"] == 1
    clk.t = 60.0  # no further requests; the window is logically empty
    m = reg.varz()["metrics"]
    assert m["serve_slo_ttft_burn_rate"] == 0.0
    assert m["serve_slo_window_requests"] == 0
    assert m["serve_slo_ttft_violations_total"] == 1  # counters keep history
    # the text exposition path reads the same computed values
    assert "serve_slo_ttft_burn_rate 0.0" in reg.to_prometheus()


def test_slo_metrics_exported_with_targets():
    reg = MetricRegistry()
    SLOTracker(reg, ttft_slo_s=0.25, tpot_slo_s=0.04, objective=0.95)
    m = reg.varz()["metrics"]
    assert m["serve_slo_ttft_target_s"] == 0.25
    assert m["serve_slo_tpot_target_s"] == 0.04
    assert m["serve_slo_objective"] == 0.95
    text = reg.to_prometheus()
    for name in ("serve_slo_ttft_burn_rate", "serve_slo_tpot_burn_rate",
                 "serve_slo_requests_total",
                 "serve_slo_ttft_violations_total"):
        assert f"\n{name} " in "\n" + text, name


def test_second_tracker_on_shared_registry_rebinds_not_raises():
    """A process that rebuilds a Server against the shared
    default_registry() constructs a second SLOTracker on the same
    registry: like every other instrument this must get-or-create, with
    the LIVE tracker's window backing the computed gauges (counters
    stay shared and cumulative)."""
    reg = MetricRegistry()
    clock = FakeClock()
    a = SLOTracker(reg, ttft_slo_s=0.1, tpot_slo_s=0.1, objective=0.9,
                   clock=clock)
    a.record(ttft_s=1.0, tpot_s=1.0)  # violation in A's window
    b = SLOTracker(reg, ttft_slo_s=0.1, tpot_slo_s=0.1, objective=0.9,
                   clock=clock)  # must not raise
    m = reg.varz()["metrics"]
    # computed gauges now read B's (empty) window, not A's
    assert m["serve_slo_ttft_burn_rate"] == 0.0
    assert m["serve_slo_window_requests"] == 0.0
    b.record(ttft_s=1.0, tpot_s=1.0)
    m = reg.varz()["metrics"]
    assert m["serve_slo_ttft_burn_rate"] == pytest.approx(10.0)
    # the violation/request counters were shared all along: A's one
    # request plus B's one request
    assert m["serve_slo_requests_total"] == 2.0
    assert m["serve_slo_ttft_violations_total"] == 2.0


# ---- wired into the Server lifecycle -------------------------------------

def test_server_scores_completed_requests():
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    ttft_slo_s=10.0, tpot_slo_s=10.0)
    reqs = [server.submit([1] * n, max_new_tokens=3) for n in (3, 5)]
    server.run_until_idle()
    assert all(r.error is None for r in reqs)
    snap = server.slo.snapshot()
    assert snap["requests"] == 2
    assert snap["ttft"]["violations_total"] == 0
    assert snap["tpot"]["violations_total"] == 0
    assert snap["ttft"]["burn_rate"] == 0.0


def test_server_tight_targets_burn_and_expired_counts_both():
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    ttft_slo_s=1e-6, tpot_slo_s=1e-6)
    ok = server.submit([1, 2, 3], max_new_tokens=2)
    dead = server.submit([4, 5, 6], max_new_tokens=2, deadline_s=-1.0)
    server.run_until_idle()
    assert ok.error is None and dead.error is not None
    snap = server.slo.snapshot()
    assert snap["requests"] == 2  # completed + expired; rejected excluded
    assert snap["ttft"]["violations_total"] == 2
    assert snap["tpot"]["violations_total"] == 2
    assert snap["ttft"]["burn_rate"] == pytest.approx(100.0)  # 0.99 objective


# ---- SLO-aware early shedding (ISSUE 6 satellite) -------------------------

def test_should_shed_needs_min_window_then_fires_on_burn():
    clk = FakeClock()
    t = SLOTracker(MetricRegistry(), ttft_slo_s=0.2, tpot_slo_s=10.0,
                   objective=0.9, window_s=60.0, clock=clk)
    for _ in range(7):
        t.record(9.9, 0.0)  # every request violates TTFT
    # burn is 10x, but 7 < min_window: one bad burst over a thin window
    # must not shed
    assert not t.should_shed(min_window=8)
    t.record(9.9, 0.0)
    assert t.should_shed(min_window=8)
    # the window aging out re-admits traffic
    clk.t = 61.0
    assert not t.should_shed(min_window=8)


def test_should_shed_false_while_burn_under_one():
    t = SLOTracker(MetricRegistry(), ttft_slo_s=0.2, tpot_slo_s=10.0,
                   objective=0.5, window_s=60.0)
    # 10 requests, 3 TTFT violations: burn = 0.3 / 0.5 = 0.6 < 1
    for i in range(10):
        t.record(9.9 if i < 3 else 0.1, 0.0)
    assert not t.should_shed(min_window=8)


def test_server_slo_shed_rejects_429_and_counts():
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    ttft_slo_s=1e-6, tpot_slo_s=1e-6,
                    slo_shed=True, shed_min_window=2)
    # burn the budget: two completed requests, both violating
    reqs = [server.submit([1, 2, 3], max_new_tokens=2) for _ in range(2)]
    server.run_until_idle()
    assert all(r.error is None for r in reqs)
    assert server.slo.should_shed(2)
    with pytest.raises(AdmissionError) as e:
        server.submit([4, 5, 6], max_new_tokens=2)
    assert e.value.status == 429
    assert "shedding" in str(e.value)
    assert server.metrics.slo_shed.value == 1
    assert server.metrics.snapshot()["slo_shed"] == 1
    reg = server.metrics.registry
    assert "serve_slo_shed_total 1.0" in reg.to_prometheus()


def test_server_shed_off_by_default_under_burn():
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    ttft_slo_s=1e-6, tpot_slo_s=1e-6)
    for _ in range(10):
        server.submit([1, 2, 3], max_new_tokens=2)
    server.run_until_idle()
    assert server.slo.should_shed(8)  # burn IS high...
    server.submit([4, 5, 6], max_new_tokens=2)  # ...but nothing sheds
    server.run_until_idle()
    assert server.metrics.slo_shed.value == 0


def test_window_counts_stay_consistent_under_eviction():
    # the incremental window counters (O(evictions) _window_stats) must
    # agree with a brute-force recount across append/evict churn
    clk = FakeClock()
    t = SLOTracker(MetricRegistry(), ttft_slo_s=0.2, tpot_slo_s=0.05,
                   objective=0.9, window_s=10.0, clock=clk)
    for i in range(50):
        clk.t = i * 0.7
        t.record(9.9 if i % 3 == 0 else 0.1,
                 9.9 if i % 4 == 0 else 0.01)
        n, ttft_bad, tpot_bad = t._window_stats()
        assert n == len(t._window)
        assert ttft_bad == sum(1 for _, ok, _x in t._window if not ok)
        assert tpot_bad == sum(1 for _, _x, ok in t._window if not ok)
    clk.t = 1000.0  # everything ages out
    assert t._window_stats() == (0, 0, 0)


def test_shed_admits_probe_requests_for_recovery_feedback():
    # shed requests are never scored, so a frozen window would 429
    # everything until the violations age out — every Nth arrival is
    # admitted as a probe whose completion re-scores the window
    server = Server(FakeEngine(), num_blocks=64, block_size=8,
                    ttft_slo_s=1e-6, tpot_slo_s=1e-6,
                    slo_shed=True, shed_min_window=2, shed_probe_every=3)
    for _ in range(2):
        server.submit([1, 2, 3], max_new_tokens=2)
    server.run_until_idle()
    assert server.slo.should_shed(2)
    outcomes = []
    for _ in range(6):
        try:
            server.submit([4, 5, 6], max_new_tokens=2)
            outcomes.append("admit")
        except AdmissionError:
            outcomes.append("shed")
    assert outcomes == ["shed", "shed", "admit", "shed", "shed", "admit"]
    # probes really flow through to scoring
    before = server.slo.requests.value
    server.run_until_idle()
    assert server.slo.requests.value == before + 2
    assert server.metrics.slo_shed.value == 4
