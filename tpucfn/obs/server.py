"""Per-host observability HTTP endpoint.

Every role in the fan-out — trainer rank, serving frontend, restart
supervisor — binds a tiny stdlib HTTP server so the whole fleet is
scrapeable (ISSUE 2: the reference's answer was ssh + tail over
``/var/log``; per-host JSONL fixed durability but not visibility):

* ``GET /metrics`` — Prometheus text exposition of the host's registry.
* ``GET /healthz`` — liveness: 200 ``{"status":"ok",...}`` while the
  role's health callback agrees, 503 otherwise (the shape load
  balancers and the restart supervisor probe).
* ``GET /varz``    — the registry's full JSON snapshot (counters plus
  summary/histogram decompositions), for humans and ``tpucfn obs``.
* ``GET /flightrecorder`` — the attached
  :class:`~tpucfn.obs.flight.FlightRecorder`'s ring as JSON (ISSUE 6):
  the last-N-seconds snapshot the gang coordinator pulls from surviving
  hosts at detect time, and operators pull ad hoc.  404 when no
  recorder is attached.
* ``POST /profile?seconds=S`` — on-demand ``jax.profiler`` capture via
  the attached :class:`~tpucfn.obs.profiler.ProfileCapture`: blocks for
  S seconds, returns the artifact directory as JSON (409 while another
  capture runs, 404 when none is attached).
* ``GET /clock`` — this host's wall + monotonic clocks in one reply
  (ISSUE 20): the sample the coordinator's NTP-style probe brackets
  between two of ITS monotonic reads to estimate this host's wall
  offset with an RTT/2 uncertainty bound (``obs.timeline.probe_clock``).
* ``GET /tracetail?lines=N`` — the last N complete lines of the
  attached tracer's span JSONL as JSON (ISSUE 20): what the gang
  coordinator pulls from survivors at incident detect time, span
  siblings to the flight ring.  404 when no tracer (or an unwritten
  one) is attached.

Port convention: ``TPUCFN_OBS_PORT`` carries each process's assigned
port (the launcher assigns ``base + 1 + host_id`` per host, keeping
``base`` for its own supervisor endpoint — see launch/launcher.py).
Port 0 binds an ephemeral port (tests; single-host ad hoc runs) — the
bound port is on :attr:`ObsServer.port`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from tpucfn.obs.profiler import ProfilerBusy
from tpucfn.obs.registry import MetricRegistry, default_registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# health_fn() -> (healthy, detail_dict); detail is merged into the body.
HealthFn = Callable[[], tuple[bool, dict]]


class ObsServer:
    """One registry behind /metrics, /healthz, /varz on a daemon thread."""

    def __init__(self, registry: MetricRegistry | None = None, *,
                 port: int = 0, host: str = "0.0.0.0", role: str = "",
                 host_id: int | None = None, health_fn: HealthFn | None = None,
                 flight=None, profiler=None, tracer=None):
        """``flight`` is a :class:`~tpucfn.obs.flight.FlightRecorder`
        (or anything with ``snapshot() -> dict``) behind
        ``/flightrecorder``; ``profiler`` is a callable
        ``(seconds) -> dict`` (normally
        :class:`~tpucfn.obs.profiler.ProfileCapture`) behind
        ``POST /profile``; ``tracer`` is this process's
        :class:`~tpucfn.obs.trace.Tracer` behind ``/tracetail``.
        Any None leaves its route 404."""
        self.registry = registry if registry is not None else default_registry()
        self.role = role
        self.host_id = host_id
        self.health_fn = health_fn
        self.flight = flight
        self.profiler = profiler
        self.tracer = tracer
        self._t0 = time.monotonic()
        obs = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no per-scrape stderr spam
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    body = obs.registry.to_prometheus().encode()
                    self._send(200, body, PROMETHEUS_CONTENT_TYPE)
                elif path == "/healthz":
                    code, payload = obs._health()
                    self._send(code, json.dumps(payload).encode(),
                               "application/json")
                elif path == "/varz":
                    self._send(200, json.dumps(obs.registry.varz()).encode(),
                               "application/json")
                elif path == "/flightrecorder":
                    if obs.flight is None:
                        self._send(404, b"no flight recorder attached\n",
                                   "text/plain")
                    else:
                        self._send(200,
                                   json.dumps(obs.flight.snapshot()).encode(),
                                   "application/json")
                elif path == "/clock":
                    # Both clocks read back to back: the probe's
                    # offset math needs this host's wall time; mono is
                    # returned for symmetry/debugging.  Kept tiny so
                    # serve time stays well inside the RTT bound.
                    self._send(200, json.dumps({
                        "wall": time.time(),
                        "mono": time.monotonic(),
                        "host_id": obs.host_id,
                        "role": obs.role,
                    }).encode(), "application/json")
                elif path == "/tracetail":
                    body, code = obs._tracetail(self.path)
                    self._send(code, body, "application/json"
                               if code == 200 else "text/plain")
                elif path == "/":
                    self._send(200,
                               b"/metrics /healthz /varz /flightrecorder "
                               b"/clock /tracetail POST /profile\n",
                               "text/plain")
                else:
                    self._send(404, b"not found\n", "text/plain")

            def do_POST(self):
                path, _, query = self.path.partition("?")
                path = path.rstrip("/") or "/"
                if path != "/profile":
                    self._send(404, b"not found\n", "text/plain")
                    return
                if obs.profiler is None:
                    self._send(404, b"no profiler attached\n", "text/plain")
                    return
                from urllib.parse import parse_qs

                raw = parse_qs(query).get("seconds", ["1.0"])[0]
                try:
                    seconds = float(raw)
                except ValueError:
                    self._send(400, f"seconds={raw!r} is not a number\n"
                               .encode(), "text/plain")
                    return
                try:
                    result = obs.profiler(seconds)
                except ValueError as e:  # bad duration (<=0, non-finite...)
                    self._send(400, (str(e) + "\n").encode(), "text/plain")
                except ProfilerBusy as e:
                    self._send(409, (str(e) + "\n").encode(), "text/plain")
                except Exception as e:  # noqa: BLE001 — capture failed
                    self._send(500, (repr(e) + "\n").encode(), "text/plain")
                else:
                    self._send(200, json.dumps(result).encode(),
                               "application/json")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"tpucfn-obs:{self._httpd.server_address[1]}")
        self._thread.start()

    def _tracetail(self, raw_path: str) -> tuple[bytes, int]:
        """Last-N span lines of the attached tracer's file (ISSUE 20).
        Reads the file rather than any in-memory state so it sees
        exactly what a postmortem would; torn final lines are skipped
        the same way ``read_trace_file`` skips them."""
        tr = self.tracer
        path = getattr(tr, "path", None)
        if tr is None or path is None:
            return b"no tracer attached\n", 404
        from urllib.parse import parse_qs, urlparse

        raw = parse_qs(urlparse(raw_path).query).get("lines", ["500"])[0]
        try:
            n = max(1, int(raw))
        except ValueError:
            return f"lines={raw!r} is not an int\n".encode(), 400
        try:
            from tpucfn.obs.trace import read_trace_file

            events = read_trace_file(path)
        except OSError as e:
            return f"trace file unreadable: {e}\n".encode(), 404
        return json.dumps({
            "path": str(path),
            "host_id": self.host_id,
            "role": self.role,
            "events": events[-n:],
        }).encode(), 200

    def _health(self) -> tuple[int, dict]:
        healthy, detail = True, {}
        if self.health_fn is not None:
            try:
                healthy, detail = self.health_fn()
            except Exception as e:  # a crashing probe IS unhealthy
                healthy, detail = False, {"probe_error": repr(e)}
        detail = dict(detail)
        if self.flight is not None:
            # HBM watermark (ISSUE 12 satellite): when this role carries
            # a flight ring with device-memory samples, /healthz detail
            # predicts OOMs (sustained used/limit over the threshold)
            # instead of leaving them to the postmortem.  Detail only —
            # a prediction must not flap a load balancer.
            try:
                from tpucfn.obs.flight import hbm_watermark

                wm = hbm_watermark(
                    self.flight.snapshot().get("samples") or [])
                if wm["level"] != "no_data":
                    detail.setdefault("hbm_watermark", wm)
            except Exception:  # noqa: BLE001 — best-effort enrichment
                pass
        payload = {
            "status": "ok" if healthy else "unhealthy",
            "role": self.role,
            "host_id": self.host_id,
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            **detail,
        }
        return (200 if healthy else 503), payload

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def url(self, path: str = "/metrics") -> str:
        host = self._httpd.server_address[0]
        if host in ("0.0.0.0", ""):
            host = "127.0.0.1"
        return f"http://{host}:{self.port}{path}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def obs_port_from_env(env: dict | None = None) -> int | None:
    """The launcher-assigned port for this process, or None when the run
    opted out of the obs plane (unset / empty / unparseable)."""
    raw = (env or os.environ).get("TPUCFN_OBS_PORT", "").strip()
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


def start_obs_server(registry: MetricRegistry | None = None, *,
                     port: int | None = None, role: str = "",
                     host: str = "0.0.0.0",
                     host_id: int | None = None,
                     health_fn: HealthFn | None = None,
                     flight=None, profiler=None,
                     tracer=None) -> ObsServer | None:
    """Start the endpoint for this process; ``port=None`` consults
    ``TPUCFN_OBS_PORT`` and returns None when the env opted out — the
    one-liner every role calls unconditionally."""
    if port is None:
        port = obs_port_from_env()
        if port is None:
            return None
    return ObsServer(registry, port=port, host=host, role=role,
                     host_id=host_id, health_fn=health_fn,
                     flight=flight, profiler=profiler, tracer=tracer)
