"""On-device trajectory queue with host-side spill as the fallback.

The actor→learner hand-off in the Anakin layout is a queue of fixed-
shape trajectory slabs.  Keeping it ON DEVICE means the learner batch
never round-trips through the host (the whole point of co-location);
the device ring here is a preallocated pytree of ``[capacity, ...]``
slots with jitted write/read programs, so push and pop are dispatches,
not transfers.

**Bit-identical-sequence discipline** (the input plane's rule applied
to this plane): slabs leave the queue in exactly arrival order, and the
``pushed``/``popped`` counters are part of the queue state — which is
checkpointed with the learner state, so a chaos-killed run restores
the queue mid-stream and replays the identical batch sequence.

**Host spill** is strictly the fallback: when the device ring is full,
``push`` moves the slab to host memory (one transfer, counted) and
re-injects it FIFO as pops free device slots.  The spill deque is
transient by construction — the loop drains the queue every iteration
— and :meth:`assert_quiescent` is the checkpoint-boundary guard: saves
only happen with the spill empty, so queue state stays a fixed-shape
checkpointable pytree.
"""

from __future__ import annotations

from collections import deque
from typing import Any

import jax
import jax.numpy as jnp


class ReplayQueue:
    """FIFO queue of fixed-shape trajectory slabs, device-resident.

    ``capacity`` is the device ring size (slots are preallocated from
    the example slab's shapes).  ``spill=True`` enables the host-side
    overflow deque; with ``spill=False`` a push into a full ring
    raises — the strict on-device mode benches use.
    """

    def __init__(self, capacity: int = 4, *, spill: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.spill_enabled = spill
        self._spill: deque = deque()
        self.spilled_total = 0
        self._jit_push = None
        self._jit_pop = None

    # -- state -------------------------------------------------------------

    def init_state(self, example: Any) -> dict:
        """Fresh queue state: zeroed ``[capacity, ...]`` slots plus the
        head/tail/sequence counters (all device scalars, so the whole
        state checkpoints as one pytree)."""
        slots = jax.tree.map(
            lambda x: jnp.zeros((self.capacity,) + tuple(x.shape), x.dtype),
            example)
        return {"slots": slots,
                "head": jnp.zeros((), jnp.int32),
                "tail": jnp.zeros((), jnp.int32),
                "count": jnp.zeros((), jnp.int32),
                "pushed": jnp.zeros((), jnp.int32),
                "popped": jnp.zeros((), jnp.int32)}

    # -- device programs ---------------------------------------------------

    def _push_fn(self, state, item):
        idx = jnp.mod(state["head"], self.capacity)
        slots = jax.tree.map(
            lambda s, x: jax.lax.dynamic_update_index_in_dim(s, x, idx, 0),
            state["slots"], item)
        return {**state, "slots": slots,
                "head": state["head"] + 1,
                "count": state["count"] + 1,
                "pushed": state["pushed"] + 1}

    def _pop_fn(self, state):
        idx = jnp.mod(state["tail"], self.capacity)
        item = jax.tree.map(
            lambda s: jax.lax.dynamic_index_in_dim(s, idx, 0,
                                                   keepdims=False),
            state["slots"])
        new = {**state, "tail": state["tail"] + 1,
               "count": state["count"] - 1,
               "popped": state["popped"] + 1}
        return new, item

    # -- host API ----------------------------------------------------------

    def __len__(self) -> int:
        return len(self._spill)

    def size(self, state) -> int:
        """Slabs queued (device ring + host spill)."""
        return int(state["count"]) + len(self._spill)

    def push(self, state, item):
        """Enqueue one slab; returns the new queue state.

        Full ring + spill enabled → the slab is copied to host and
        queued there (arrival order preserved: spilled slabs re-enter
        the ring only behind everything already spilled).  Full ring
        without spill raises."""
        if self._jit_push is None:
            self._jit_push = jax.jit(self._push_fn, donate_argnums=(0,))
        if int(state["count"]) >= self.capacity or self._spill:
            if not self.spill_enabled:
                raise RuntimeError(
                    f"ReplayQueue full (capacity={self.capacity}) and "
                    "host spill is disabled")
            self._spill.append(jax.device_get(item))
            self.spilled_total += 1
            return state
        return self._jit_push(state, item)

    def pop(self, state):
        """Dequeue the oldest slab; returns ``(state, slab)``.

        Pops always come off the device ring (FIFO); a freed slot is
        immediately backfilled from the host spill so spilled slabs
        flow back in order.  Raises on an empty queue."""
        if self._jit_pop is None:
            self._jit_pop = jax.jit(self._pop_fn, donate_argnums=(0,))
        if int(state["count"]) == 0:
            if not self._spill:
                raise RuntimeError("ReplayQueue is empty")
            # ring drained while slabs sit spilled: re-inject then pop
            state = self._jit_push(state, self._spill.popleft())
        state, item = self._jit_pop(state)
        while self._spill and int(state["count"]) < self.capacity:
            state = self._jit_push(state, self._spill.popleft())
        return state, item

    def assert_quiescent(self) -> None:
        """Checkpoint-boundary guard: the host spill must be empty, or
        the fixed-shape device state under-describes the queue and a
        restore would drop slabs (sequence discipline broken)."""
        if self._spill:
            raise RuntimeError(
                f"{len(self._spill)} spilled slab(s) outstanding at a "
                "checkpoint boundary — drain the queue before saving")
