"""One rank of the RL chaos drill: the REAL Podracer loop, not a toy.

``test_rl_e2e.py`` fans this out through the Launcher/GangCoordinator
exactly like a production ``tpucfn launch -- tpucfn rl train`` gang.
Everything that matters — heartbeats, checkpoint save/restore, the
``rl_run_start``/``rl_resumed`` events, goodput ledger rows, the
per-iteration JSONL trajectory — comes from ``run_rl_loop`` itself;
this file only pins the CPU platform (each rank runs its own
8-fake-device jax runtime) and maps the drill's env vars onto RLConfig.
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Pin platform BEFORE jax initializes: the drill's ranks must ignore any
# site-installed accelerator plugin and present 8 fake CPU devices.
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from tpucfn.rl.loop import RLConfig, run_rl_loop  # noqa: E402


def main() -> int:
    run_rl_loop(RLConfig(
        run_dir=os.environ["RL_E2E_RUN_DIR"],
        env=os.environ.get("RL_E2E_ENV", "gridworld"),
        unroll=int(os.environ.get("RL_E2E_UNROLL", "8")),
        iters=int(os.environ["RL_E2E_ITERS"]),
        ckpt_every=int(os.environ.get("RL_E2E_CKPT_EVERY", "5")),
        log_every=1000,
        iter_sleep_s=float(os.environ.get("RL_E2E_ITER_SLEEP", "0.05"))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
