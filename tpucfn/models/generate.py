"""Autoregressive generation with a KV cache.

The serving-side counterpart of the training stack (net-new vs the
reference, which was a training-only harness): prefill runs the prompt
through the decode-mode model once (populating each layer's KV cache),
then a ``lax.scan`` emits one token per step attending over the cached
prefix — O(S) memory and O(S·D) work per token instead of re-running the
full forward. Greedy (temperature=0) or temperature sampling.

The decode-mode model shares the *exact* param tree with the training
model — checkpoints flow straight from `Trainer` to `generate`.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from tpucfn.models.llama import Llama, LlamaConfig


def generate(
    cfg: LlamaConfig,
    params,
    prompt: jax.Array,  # (B, T) int32
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: jax.Array | None = None,
    cache_len: int | None = None,
) -> jax.Array:
    """Returns (B, T + max_new_tokens) tokens (prompt included)."""
    b, t = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    total = t + max_new_tokens
    if cache_len is None:
        cache_len = total
    if cache_len < total:
        raise ValueError(f"cache_len {cache_len} < prompt+new {total}")
    # The cache (and RoPE tables) size from max_seq; cap to this call's
    # needs so short generations don't pay full-context attention.
    dcfg = dataclasses.replace(cfg, max_seq=cache_len)
    model = Llama(dcfg, decode=True)
    if rng is None:
        rng = jax.random.key(0)

    # Materialize zero caches with the right shapes (params are reused).
    cache = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((b, 1), jnp.int32))
    )["cache"]
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache)

    # Prefill: one pass over the prompt fills every layer's cache.
    logits, muts = model.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"]
    )
    cache = muts["cache"]

    def sample(logits_last, key):
        if temperature <= 0.0:
            return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        return jax.random.categorical(key, logits_last / temperature, axis=-1).astype(
            jnp.int32
        )

    first = sample(logits[:, -1], rng)

    def step(carry, key):
        cache, tok = carry
        logits, muts = model.apply(
            {"params": params, "cache": cache}, tok[:, None], mutable=["cache"]
        )
        nxt = sample(logits[:, -1], key)
        return (muts["cache"], nxt), nxt

    # first is generated token 1; each scan step samples one more.
    keys = jax.random.split(jax.random.fold_in(rng, 1), max_new_tokens - 1)
    _, toks = jax.lax.scan(step, (cache, first), keys)  # (max_new-1, B)
    generated = jnp.concatenate([first[:, None], toks.T], axis=1)  # (B, max_new)
    return jnp.concatenate([prompt, generated], axis=1)
