from tpucfn.models.resnet import ResNet, ResNetConfig  # noqa: F401
from tpucfn.models.llama import Llama, LlamaConfig, causal_lm_loss  # noqa: F401
from tpucfn.models.bert import Bert, BertConfig, mlm_loss  # noqa: F401
from tpucfn.models.hf_convert import (  # noqa: F401
    from_hf_llama,
    from_hf_mixtral,
)

