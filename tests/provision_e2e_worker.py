"""Deterministic resumable toy trainer for the provisioner-policy e2e
drill (ISSUE 18).

Same trajectory contract as ``input_e2e_worker`` (``w ← 0.9·w +
mean(batch.x)`` appended to a per-host JSONL, value-preserving sleep
decode on the LOCAL path only) plus the two behaviors a policy-driven
grow needs from a trainer:

* **drain-aware** — polls ``drain_requested(ft_dir, step)`` at every
  step boundary and exits rc 0 when the coordinator's provision-grow
  drain converges on it;
* **resumable** — persists ``{step, w}`` after every step and, on
  relaunch, skips the already-consumed prefix of the (deterministic)
  batch stream before continuing — so one mid-run drain→relaunch
  produces a trajectory BIT-IDENTICAL to an uninterrupted reference.

Before the grow the worker loads locally (paying the decode serially —
the data-starved shape the policy must notice); after it,
``TPUCFN_INPUT_ADDRS`` is fanned out and the same stream arrives
pre-decoded from the input host, collapsing the ``data_wait`` share.
"""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

from tpucfn.data.pipeline import ShardedDataset  # noqa: E402
from tpucfn.data.service import service_or_local_batches  # noqa: E402
from tpucfn.ft import HeartbeatWriter  # noqa: E402
from tpucfn.ft.preempt import drain_requested  # noqa: E402
from tpucfn.obs.goodput import GoodputLedger  # noqa: E402


class _SleepDecode:
    """Value-preserving synthetic decode cost (consumes no RNG, so the
    served stream — which skips it — stays bit-identical)."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def __call__(self, ex, rs):
        if self.seconds > 0:
            time.sleep(self.seconds)
        return ex


def main() -> int:
    host = int(os.environ.get("TPUCFN_HOST_ID", "0"))
    trainers = int(os.environ["TPUCFN_WORKERS_COUNT"])
    run_dir = Path(os.environ["PROV_E2E_RUN_DIR"])
    shards_dir = Path(os.environ["PROV_E2E_SHARDS"])
    batch = int(os.environ.get("PROV_E2E_BATCH", "8"))
    seed = int(os.environ.get("PROV_E2E_SEED", "0"))
    step_sleep = float(os.environ.get("PROV_E2E_STEP_SLEEP", "0.03"))
    decode_sleep = float(os.environ.get("PROV_E2E_DECODE_SLEEP", "0.008"))
    ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()

    hb = None
    if ft_dir:
        hb = HeartbeatWriter(
            ft_dir, host_id=host, role="trainer",
            interval_s=float(
                os.environ.get("TPUCFN_FT_HEARTBEAT_S", "0.2") or 0.2)
        ).start()
    ledger = GoodputLedger(run_dir / "goodput", host_id=host,
                           role="trainer")
    run_dir.mkdir(parents=True, exist_ok=True)

    # resume point: the deterministic stream is re-derived from (seed,
    # shards, batch) and the consumed prefix skipped, so the fold
    # continues exactly where the drained incarnation stopped
    state_path = run_dir / f"state-host{host:03d}.json"
    step, w = 0, 10.0
    if state_path.exists():
        st = json.loads(state_path.read_text())
        step, w = int(st["step"]), float(st["w"])

    ds = ShardedDataset(
        sorted(shards_dir.glob("*.tpurec")),
        batch_size_per_process=batch, seed=seed,
        process_index=host, process_count=trainers,
        transform=_SleepDecode(decode_sleep))
    stream = service_or_local_batches(ds, num_epochs=1)
    losses = run_dir / f"losses-host{host:03d}.jsonl"
    try:
        for _ in range(step):  # consumed prefix (cheap: pre-decoded)
            if next(stream, None) is None:
                return 0
        with open(losses, "a") as f:
            while True:
                t0_wait = time.monotonic()
                b = next(stream, None)
                t_wait = time.monotonic() - t0_wait
                if b is None:
                    break
                step += 1
                if t_wait >= 1e-4:
                    ledger.account("data_wait", t_wait, step=step)
                t0_step = time.monotonic()
                w = 0.9 * w + float(np.mean(b["x"]))
                f.write(json.dumps({"step": step, "w": w}) + "\n")
                f.flush()
                state_path.write_text(json.dumps({"step": step, "w": w}))
                if hb is not None:
                    hb.update_step(step)
                time.sleep(step_sleep)
                ledger.account("step", time.monotonic() - t0_step,
                               step=step)
                if ft_dir and drain_requested(ft_dir, step):
                    break  # clean exit at the boundary; resumed later
    finally:
        close_stream = getattr(stream, "close", None)
        if close_stream is not None:
            close_stream()
        if hb is not None:
            hb.stop()
        ledger.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
