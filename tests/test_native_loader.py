"""Native (C++) tpurecord reader vs the pure-Python reference reader:
byte-identical payloads, same corruption detection, batch reads, and the
dataset integration path."""

import numpy as np
import pytest

from tpucfn.data import RecordShardWriter, ShardedDataset, synthetic_cifar10, write_dataset_shards
from tpucfn.data import native
from tpucfn.data.records import read_record_shard

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native reader not built (no g++?)"
)


def _shard(tmp_path, payloads):
    p = tmp_path / "s.tpurec"
    with RecordShardWriter(p) as w:
        for b in payloads:
            w.write(b)
    return p


def test_native_matches_python_reader(tmp_path):
    payloads = [b"a", b"bb" * 500, b"", b"xyz" * 33]
    p = _shard(tmp_path, payloads)
    assert list(native.read_record_shard_native(p)) == payloads
    assert list(read_record_shard(p)) == payloads


def test_native_random_access_and_batch(tmp_path):
    payloads = [bytes([i]) * (i + 1) for i in range(20)]
    p = _shard(tmp_path, payloads)
    r = native.NativeShardReader(p)
    assert len(r) == 20
    assert r.read(7) == payloads[7]
    assert r.read_batch([3, 1, 19]) == [payloads[3], payloads[1], payloads[19]]
    assert r.read_batch([]) == []
    r.close()


def test_native_crc_detection(tmp_path):
    p = _shard(tmp_path, [b"payload-payload"])
    raw = bytearray(p.read_bytes())
    raw[-3] ^= 0xFF
    p.write_bytes(bytes(raw))
    r = native.NativeShardReader(p)
    with pytest.raises(ValueError, match="CRC"):
        r.read(0)


def test_native_truncation_detection(tmp_path):
    p = _shard(tmp_path, [b"x" * 100] * 10)
    p.write_bytes(p.read_bytes()[:-50])
    with pytest.raises(ValueError, match="truncated"):
        native.NativeShardReader(p)


def test_native_bad_magic(tmp_path):
    p = tmp_path / "junk.tpurec"
    p.write_bytes(b"\x00" * 64)
    with pytest.raises(ValueError, match="magic"):
        native.NativeShardReader(p)


def test_native_out_of_range(tmp_path):
    p = _shard(tmp_path, [b"one"])
    r = native.NativeShardReader(p)
    with pytest.raises(IndexError):
        r.read(5)


def test_dataset_uses_native_reader(tmp_path, monkeypatch):
    paths = write_dataset_shards(synthetic_cifar10(32), tmp_path, num_shards=2)
    calls = []
    orig = native.read_record_shard_native

    def spy(path):
        calls.append(path)
        return orig(path)

    monkeypatch.setattr(native, "read_record_shard_native", spy)
    ds = ShardedDataset(paths, batch_size_per_process=8)
    batches = list(ds.epoch(0))
    assert len(batches) == 4
    assert len(calls) == 2  # both shards went through the native reader


def test_native_and_python_agree_on_dataset(tmp_path):
    paths = write_dataset_shards(synthetic_cifar10(16), tmp_path, num_shards=1)
    a = list(native.read_record_shard_native(paths[0]))
    b = list(read_record_shard(paths[0]))
    assert a == b
    assert len(a) == 16
    from tpucfn.data.records import decode_example

    ex = decode_example(a[0])
    assert ex["image"].shape == (32, 32, 3)
    np.testing.assert_array_equal(ex["image"], decode_example(b[0])["image"])
