"""Shared transformer building blocks.

Module/param names follow the conventions the sharding-rule presets match
(tpucfn/parallel/presets.py): q_proj/k_proj/v_proj/o_proj, gate_proj/
up_proj/down_proj, embed_tokens, lm_head. bf16 compute / fp32 params
throughout (MXU-native mixed precision).
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpucfn.ops.attention import dot_product_attention


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(self.dtype)


def rope_frequencies(dim: int, max_pos: int, theta: float) -> tuple[jax.Array, jax.Array]:
    """Precompute RoPE cos/sin tables: (max_pos, dim//2), fp32."""
    inv = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    t = jnp.arange(max_pos, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array,
               positions: jax.Array) -> jax.Array:
    """x: (B, S, H, D); positions: (B, S) or (S,) global token positions."""
    c = cos[positions]  # (..., S, D/2)
    s = sin[positions]
    if c.ndim == 2:  # (S, D/2) -> broadcast batch
        c, s = c[None], s[None]
    c, s = c[:, :, None, :], s[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


# attention_fn(q, k, v, causal=..., q_offset=..., k_offset=...) -> out
AttentionFn = Callable[..., jax.Array]


class CausalSelfAttention(nn.Module):
    """GQA self-attention with RoPE; the attention inner op is pluggable so
    dense/flash/ring implementations swap without touching the module.

    ``decode=True`` turns on the autoregressive KV cache (flax ``cache``
    collection): each call appends this step's K/V at ``cache_index`` and
    attends over the whole prefix — the serving path. Cache capacity is
    ``max_seq``.
    """

    n_heads: int
    n_kv_heads: int
    head_dim: int
    rope_theta: float = 500000.0
    max_seq: int = 8192
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_fn: AttentionFn = dot_product_attention
    decode: bool = False

    @nn.compact
    def __call__(self, x, *, positions=None, q_offset=0):
        b, s, _ = x.shape
        dense = lambda feat, name: nn.DenseGeneral(  # noqa: E731
            feat, axis=-1, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name=name,
        )
        q = dense(self.n_heads * self.head_dim, "q_proj")(x)
        k = dense(self.n_kv_heads * self.head_dim, "k_proj")(x)
        v = dense(self.n_kv_heads * self.head_dim, "v_proj")(x)
        q = q.reshape(b, s, self.n_heads, self.head_dim)
        k = k.reshape(b, s, self.n_kv_heads, self.head_dim)
        v = v.reshape(b, s, self.n_kv_heads, self.head_dim)

        cos, sin = rope_frequencies(self.head_dim, self.max_seq, self.rope_theta)

        if self.decode:
            # Positions come from the cache index; a caller-supplied
            # schedule (the ring/SP path) is incompatible with decode.
            # q_offset arrives as the model's traced zero and is ignored.
            if positions is not None:
                raise ValueError(
                    "decode mode derives positions from the KV cache index; "
                    "explicit positions are not supported together with decode"
                )
            cached_k = self.variable(
                "cache", "cached_key", jnp.zeros,
                (b, self.max_seq, self.n_kv_heads, self.head_dim), self.dtype,
            )
            cached_v = self.variable(
                "cache", "cached_value", jnp.zeros,
                (b, self.max_seq, self.n_kv_heads, self.head_dim), self.dtype,
            )
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
            )
            i = cache_index.value
            positions = i + jnp.arange(s)
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            k_all = jax.lax.dynamic_update_slice(
                cached_k.value, k.astype(self.dtype), (0, i, 0, 0))
            v_all = jax.lax.dynamic_update_slice(
                cached_v.value, v.astype(self.dtype), (0, i, 0, 0))
            cached_k.value = k_all
            cached_v.value = v_all
            cache_index.value = i + s
            # q lives at global positions [i, i+s); cache slots beyond are
            # zeros and masked out by causality.
            out = self.attention_fn(q, k_all, v_all, causal=True,
                                    q_offset=i, k_offset=0)
            # Past-capacity decoding would silently clamp the RoPE gather
            # and the cache write; poison the output instead so overflow is
            # loud (NaNs) rather than quietly wrong.
            out = jnp.where(i + s <= self.max_seq, out, jnp.nan)
        else:
            if positions is None:
                positions = jnp.arange(s) + q_offset
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            out = self.attention_fn(q, k, v, causal=True,
                                    q_offset=q_offset, k_offset=q_offset)
        out = out.reshape(b, s, self.n_heads * self.head_dim)
        return dense(x.shape[-1], "o_proj")(out)


class SwiGLUMLP(nn.Module):
    ffn_dim: int
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        dense = lambda feat, name: nn.DenseGeneral(  # noqa: E731
            feat, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name=name,
        )
        gate = nn.silu(dense(self.ffn_dim, "gate_proj")(x))
        up = dense(self.ffn_dim, "up_proj")(x)
        return dense(x.shape[-1], "down_proj")(gate * up)
