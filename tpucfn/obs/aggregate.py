"""Fleet aggregation over per-host JSONL metrics and trace files.

The write side (MetricLogger, Tracer) produces one file per host; this
is the read side ``tpucfn obs`` uses to answer the three questions you
otherwise tail 64 files for:

* **merged step timeline** — for each global step, every host's wall
  time fused into min/median/max + which host was slowest;
* **per-host straggler report** — mean step/data-wait time per host
  relative to the fleet median (the Podracer-style per-actor timing
  decomposition: a 1.3x host is a hardware or input-pipeline problem,
  not a model problem);
* **request latency breakdown** — per-request queue-wait / prefill /
  decode reconstructed from serve trace spans, with fleet aggregates.

Everything here is pure functions over parsed dicts so the CLI, tests,
and notebooks share one implementation.
"""

from __future__ import annotations

import statistics
from pathlib import Path
from typing import Iterable

from tpucfn.obs.trace import read_trace_file


def read_metrics_dir(d: str | Path) -> dict[str, list[dict]]:
    """``host label -> [records]`` for every ``*.jsonl`` under ``d``
    (one file per host by MetricLogger convention; torn lines skipped —
    same still-being-appended tolerance as the trace reader)."""
    return {p.stem: read_trace_file(p)
            for p in sorted(Path(d).glob("*.jsonl"))}


def merge_step_timeline(by_host: dict[str, list[dict]],
                        key: str = "step_time",
                        last: int | None = None) -> list[dict]:
    """One row per global step seen on any host: per-step fleet spread
    of ``key`` plus the slowest host — the merged timeline view."""
    per_step: dict[int, dict[str, float]] = {}
    for host, rows in by_host.items():
        for r in rows:
            if key in r and "step" in r:
                per_step.setdefault(int(r["step"]), {})[host] = float(r[key])
    steps = sorted(per_step)
    if last is not None:
        steps = steps[-last:]
    out = []
    for s in steps:
        vals = per_step[s]
        straggler = max(vals, key=vals.get)
        out.append({
            "step": s,
            "hosts": len(vals),
            "min": min(vals.values()),
            "median": statistics.median(vals.values()),
            "max": vals[straggler],
            "straggler": straggler,
        })
    return out


def host_straggler_report(by_host: dict[str, list[dict]],
                          keys: tuple[str, ...] = ("step_time",),
                          slow_factor: float = 1.2) -> list[dict]:
    """Per-host means of ``keys`` with each host's ratio to the fleet
    median of the first key; ``slow`` flags ratios above
    ``slow_factor`` (the "go look at that host" bit)."""
    rows = []
    for host, recs in sorted(by_host.items()):
        row: dict = {"host": host, "records": len(recs)}
        for k in keys:
            vals = [float(r[k]) for r in recs if k in r]
            row[f"mean_{k}"] = statistics.fmean(vals) if vals else None
            row[f"n_{k}"] = len(vals)
        rows.append(row)
    primary = f"mean_{keys[0]}"
    meds = [r[primary] for r in rows if r[primary] is not None]
    fleet_median = statistics.median(meds) if meds else None
    for r in rows:
        if fleet_median and r[primary] is not None:
            r["vs_fleet_median"] = r[primary] / fleet_median
            r["slow"] = r["vs_fleet_median"] > slow_factor
        else:
            r["vs_fleet_median"], r["slow"] = None, False
    return rows


def request_breakdown(events: Iterable[dict]) -> tuple[list[dict], dict]:
    """Per-request latency decomposition from serve trace events.

    Returns ``(rows, aggregate)``: one row per request with queue_wait /
    prefill (first, non-resumed) / decode (sum of the decode rounds
    whose batch contained this sequence) / ttft / total and the
    outcome; aggregate carries fleet percentiles of each part.

    Requests are keyed by ``(host, trace_id)``: each server process
    numbers its requests from 0, so in a multi-host serve gang the same
    trace_id appears once per host and keying on it alone would fuse
    different hosts' requests into one wrong row.
    """
    per_req: dict = {}
    decode_rounds: list[dict] = []

    def req(host, tid):
        return per_req.setdefault((host, tid), {
            "host": host, "request": tid,
            "queue_wait_s": None, "prefill_s": None,
            "re_prefill_s": 0.0, "decode_s": 0.0, "decode_rounds": 0,
            "ttft_s": None, "total_s": None, "generated": None,
            "outcome": None})

    for e in events:
        name, tid, host = e.get("name"), e.get("trace_id"), e.get("host")
        attrs = e.get("attrs", {})
        if name == "queue_wait" and tid is not None:
            req(host, tid)["queue_wait_s"] = e["dur_s"]
        elif name == "prefill" and tid is not None:
            if attrs.get("resumed"):
                req(host, tid)["re_prefill_s"] += e["dur_s"]
            else:
                req(host, tid)["prefill_s"] = e["dur_s"]
        elif name == "decode_round":
            decode_rounds.append(e)
        elif name == "request_done" and tid is not None:
            r = req(host, tid)
            r["outcome"] = attrs.get("outcome")
            r["total_s"] = attrs.get("latency_s")
            r["ttft_s"] = attrs.get("ttft_s")
            r["generated"] = attrs.get("generated")
    for e in decode_rounds:
        for sid in e.get("attrs", {}).get("seqs", ()):
            key = (e.get("host"), sid)
            if key in per_req:
                per_req[key]["decode_s"] += e["dur_s"]
                per_req[key]["decode_rounds"] += 1
    rows = [per_req[k] for k in sorted(per_req,
                                       key=lambda k: (str(k[0]), str(k[1])))]

    from tpucfn.obs.metrics import nearest_rank

    agg: dict = {"requests": len(rows),
                 "completed": sum(1 for r in rows if r["outcome"] == "ok")}
    for part in ("queue_wait_s", "prefill_s", "decode_s", "ttft_s", "total_s"):
        xs = sorted(r[part] for r in rows if r[part] is not None)
        agg[part] = {"p50": nearest_rank(xs, 50), "p95": nearest_rank(xs, 95),
                     "max": xs[-1] if xs else None}
    return rows, agg


def step_spans_by_host(events: Iterable[dict]) -> dict[str, list[dict]]:
    """Regroup trainer trace spans into the by-host record shape the
    timeline/straggler views consume (span name -> ``<name>_time``
    column, trace_id -> step) — so traces alone, without the metrics
    JSONL, still feed the fleet views."""
    by_host: dict[str, list[dict]] = {}
    for e in events:
        if e.get("kind") != "span" or e.get("name") not in (
                "data_wait", "step", "ckpt"):
            continue
        host = f"host{e.get('host')}" if e.get("host") is not None else "host?"
        rec: dict = {f"{e['name']}_time": e["dur_s"]}
        if e.get("trace_id") is not None:
            rec["step"] = e["trace_id"]
        by_host.setdefault(host, []).append(rec)
    return by_host


def render_table(rows: list[dict], columns: list[str],
                 float_fmt: str = "{:.4f}") -> str:
    """Minimal fixed-width table (no external deps on the hosts)."""
    def cell(v):
        if isinstance(v, bool):
            return "YES" if v else ""
        if isinstance(v, float):
            return float_fmt.format(v)
        return "" if v is None else str(v)

    grid = [columns] + [[cell(r.get(c)) for c in columns] for r in rows]
    widths = [max(len(row[i]) for row in grid) for i in range(len(columns))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
             for row in grid]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return "\n".join(lines)
