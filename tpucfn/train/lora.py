"""LoRA adapters as a functional param-tree transform.

Low-rank finetuning for any model in the zoo without touching module
code: pick the target kernels by path regex, create per-target (A, B)
factors, and materialize ``W + scale * A @ B`` on the way into the
ordinary ``apply``.  Because the merge happens inside the jitted step,
XLA fuses the rank-r update into the surrounding program; the base tree
rides along as a frozen constant (no optimizer state, no gradients), so
optimizer memory scales with the adapter (~rank/min(fan) of full
finetuning — the reason LoRA exists).

Works with every sharding preset: A inherits the row sharding of its
kernel's first dim and B the column sharding of its last dim via
:func:`lora_sharding_rules`, so TP/FSDP shard the factors the same way
they shard the kernel.

Net-new vs the reference (a training-only harness with no finetune
story); the SD-1.5/Llama finetune configs (BASELINE 4/5) are where it
pays.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpucfn.parallel.sharding import ShardingRules, _path_str

# The attention/MLP projection kernels across the model zoo.
DEFAULT_TARGETS = r"(q_proj|k_proj|v_proj|o_proj|up_proj|down_proj|gate_proj)/kernel$"


def _targets(tree: Any, pattern: str) -> list[tuple]:
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if re.search(pattern, _path_str(path)) and getattr(leaf, "ndim", 0) >= 2:
            out.append((path, leaf))
    return out


def lora_init(
    base_params: Any,
    rng: jax.Array,
    *,
    rank: int = 8,
    pattern: str = DEFAULT_TARGETS,
    dtype=None,
) -> dict:
    """Create the adapter tree: {joined_path: {"a": (..., in, r), "b":
    (..., r, out)}}.  A is Gaussian/sqrt(in), B zeros — the adapted
    model starts exactly at the base model.  Kernels with leading
    stacked dims (scanned layers: (L, in, out)) get per-slice factors
    (L, in, r)/(L, r, out)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    found = _targets(base_params, pattern)
    if not found:
        raise ValueError(f"no params match LoRA pattern {pattern!r}")
    adapters = {}
    for path, leaf in found:
        key = _path_str(path)
        fan_in, fan_out = leaf.shape[-2], leaf.shape[-1]
        lead = leaf.shape[:-2]
        rng, k = jax.random.split(rng)
        a = (jax.random.normal(k, (*lead, fan_in, rank),
                               dtype or leaf.dtype)
             / jnp.sqrt(jnp.asarray(fan_in, jnp.float32)).astype(
                 dtype or leaf.dtype))
        b = jnp.zeros((*lead, rank, fan_out), dtype or leaf.dtype)
        adapters[key] = {"a": a, "b": b}
    return adapters


def lora_materialize(base_params: Any, adapters: dict, *,
                     scale: float = 1.0) -> Any:
    """base W -> W + scale * A@B for every adapted kernel; other leaves
    pass through BY REFERENCE (no copy).  The base tree is wrapped in
    ``stop_gradient`` so differentiating a loss w.r.t. ``adapters``
    through the merged tree touches only the factors."""
    frozen = jax.tree.map(jax.lax.stop_gradient, base_params)

    def merge(path, leaf):
        ad = adapters.get(_path_str(path))
        if ad is None:
            return leaf
        delta = jnp.einsum("...ir,...ro->...io", ad["a"], ad["b"])
        return leaf + scale * delta.astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(merge, frozen)


def lora_sharding_rules() -> ShardingRules:
    """Adapter factors replicate by default: they are rank-r slivers
    (a 4096x8 factor is 128 KB — sharding them buys nothing and costs a
    rule-surgery tier).  Use ``.extended(...)`` on the result if a
    deployment ever needs sharded factors."""
    return ShardingRules(((r".*", P()),))
