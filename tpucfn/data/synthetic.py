"""Synthetic dataset generators.

The reference staged real CIFAR-10/ImageNet from S3 (SURVEY.md §2.1); this
zero-egress build environment cannot download them, so convergence smoke
tests and benchmarks run on deterministic synthetic data with the same
shapes/dtypes/label cardinality. The staging path (``write_dataset_shards``
→ ``ShardedDataset``) is identical to what a real dataset would use — only
the bytes differ; point ``write_dataset_shards`` at a real decoder to stage
the real thing.

The synthetic task is *learnable* (class-conditional means) so loss curves
actually discriminate working training from broken training.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def _class_conditional_images(
    n: int, hw: int, classes: int, seed: int
) -> Iterator[dict[str, np.ndarray]]:
    rs = np.random.RandomState(seed)
    # Fixed per-class mean patterns; examples are mean + noise.
    protos = rs.randn(classes, hw, hw, 3).astype(np.float32)
    for _ in range(n):
        y = int(rs.randint(classes))
        x = protos[y] * 0.5 + rs.randn(hw, hw, 3).astype(np.float32) * 0.5
        yield {"image": x.astype(np.float32), "label": np.int32(y)}


def synthetic_cifar10(n: int = 1024, seed: int = 0) -> Iterator[dict[str, np.ndarray]]:
    """CIFAR-10-shaped (32×32×3, 10 classes) learnable synthetic stream."""
    return _class_conditional_images(n, 32, 10, seed)


def synthetic_imagenet(
    n: int = 256, seed: int = 0, image_size: int = 224, classes: int = 1000
) -> Iterator[dict[str, np.ndarray]]:
    """ImageNet-shaped (224×224×3, 1000 classes) synthetic stream."""
    return _class_conditional_images(n, image_size, classes, seed)


def synthetic_tokens(
    n: int = 512, seq_len: int = 128, vocab: int = 32000, seed: int = 0
) -> Iterator[dict[str, np.ndarray]]:
    """Learnable token sequences: affine next-token recurrence
    t[i+1] = (a·t[i] + b) mod vocab, so a causal LM can actually drive
    next-token loss toward zero (distinguishes learning from plumbing)."""
    rs = np.random.RandomState(seed)
    a, b = 31, 17
    for _ in range(n):
        t0 = int(rs.randint(vocab))
        seq = np.empty(seq_len, np.int32)
        seq[0] = t0
        for i in range(1, seq_len):
            seq[i] = (a * int(seq[i - 1]) + b) % vocab
        yield {"tokens": seq}


def synthetic_latents(
    n: int = 256, hw: int = 32, ctx_len: int = 77, ctx_dim: int = 768, seed: int = 0
) -> Iterator[dict[str, np.ndarray]]:
    """SD-shaped latent/text-context pairs for diffusion finetune smoke."""
    rs = np.random.RandomState(seed)
    for _ in range(n):
        yield {
            "latents": rs.randn(hw, hw, 4).astype(np.float32),
            "context": rs.randn(ctx_len, ctx_dim).astype(np.float32),
        }
