"""CLI workflow tests — the reference's README walkthrough as automation:
create-stack → status → env → launch → kill-host → heal → resize → delete.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tpucfn.cli.main import main

REPO = Path(__file__).resolve().parent.parent


def _cli(tmp_path, *argv):
    return main(["--state-dir", str(tmp_path / "state"), *argv])


def test_full_walkthrough(tmp_path, capsys):
    assert _cli(tmp_path, "create-stack", "--name", "demo", "--accelerator", "v4-32") == 0
    out = capsys.readouterr().out
    assert "CREATE_COMPLETE demo" in out
    assert "4 hosts" in out

    assert _cli(tmp_path, "status", "--name", "demo") == 0
    out = capsys.readouterr().out
    assert "ACTIVE" in out and "host3" in out

    assert _cli(tmp_path, "env", "--name", "demo") == 0
    out = capsys.readouterr().out
    assert "export TPUCFN_WORKERS_COUNT='4'" in out
    assert "export DEEPLEARNING_WORKERS_COUNT='4'" in out  # legacy alias

    # launch: each host writes its id into a file
    marker = tmp_path / "marker"
    marker.mkdir()
    rc = _cli(
        tmp_path, "launch", "--name", "demo", "--",
        sys.executable, "-c",
        f"import os,pathlib;pathlib.Path(r'{marker}').joinpath("
        "os.environ['TPUCFN_HOST_ID']).write_text('ok')",
    )
    assert rc == 0
    assert sorted(p.name for p in marker.iterdir()) == ["0", "1", "2", "3"]

    assert _cli(tmp_path, "resize", "--name", "demo", "--accelerator", "v4-64") == 0
    assert "RESIZE_COMPLETE" in capsys.readouterr().out
    _cli(tmp_path, "status", "--name", "demo")
    assert "host7" in capsys.readouterr().out

    assert _cli(tmp_path, "delete", "--name", "demo") == 0
    assert "DELETE_COMPLETE" in capsys.readouterr().out


def test_fault_injection_and_heal(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "ft", "--accelerator", "v4-16")
    capsys.readouterr()
    _cli(tmp_path, "kill-host", "--name", "ft", "--host", "1")
    capsys.readouterr()
    _cli(tmp_path, "status", "--name", "ft")
    assert "DEAD" in capsys.readouterr().out
    assert _cli(tmp_path, "heal", "--name", "ft") == 0
    assert "gen=2" in capsys.readouterr().out
    _cli(tmp_path, "status", "--name", "ft")
    assert "DEAD" not in capsys.readouterr().out


def test_launch_requires_active(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "gone", "--accelerator", "cpu-8")
    _cli(tmp_path, "delete", "--name", "gone")
    capsys.readouterr()
    rc = _cli(tmp_path, "launch", "--name", "gone", "--", "true")
    assert rc == 1
    assert "not ACTIVE" in capsys.readouterr().err


def test_spec_file_create(tmp_path, capsys):
    spec = {"name": "from-file", "accelerator": "v5p-64", "storage_path": "gs://b/x"}
    f = tmp_path / "cluster.json"
    f.write_text(json.dumps(spec))
    assert _cli(tmp_path, "create-stack", "--spec", str(f)) == 0
    out = capsys.readouterr().out
    assert "8 hosts" in out


def test_cli_subprocess_entry(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "tpucfn.cli", "--state-dir", str(tmp_path),
         "create-stack", "--name", "subp", "--accelerator", "cpu-8"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert r.returncode == 0, r.stderr
    assert "CREATE_COMPLETE subp" in r.stdout


def test_state_persists_across_invocations(tmp_path, capsys):
    _cli(tmp_path, "create-stack", "--name", "persist", "--accelerator", "v4-16")
    capsys.readouterr()
    # fresh control-plane object (new invocation) still sees the cluster
    assert _cli(tmp_path, "status", "--name", "persist") == 0
    assert "ACTIVE" in capsys.readouterr().out
    state_file = tmp_path / "state" / "control_plane.json"
    assert state_file.exists()


def test_unknown_cluster_errors(tmp_path):
    with pytest.raises(KeyError):
        _cli(tmp_path, "status", "--name", "nope")


# -- tpucfn check (ISSUE 10) ------------------------------------------------
# rc/JSON contract pinned so tooling (the builder loop, CI wrappers) can
# consume it: rc 0 clean, rc 1 findings, rc 2 usage error; --json emits
# exactly one JSON object per finding with file/line/rule/fingerprint/
# message keys.

CHECK_BUG_SRC = '''
import threading


class Router:
    def __init__(self):
        self._lock = threading.Lock()

    def relaunch(self, timeout=10.0):
        with self._lock:
            self._thread.join(timeout)
'''


def _check_pkg(tmp_path, src=CHECK_BUG_SRC):
    pkg = tmp_path / "repo" / "pkg"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "router.py").write_text(src)
    return pkg


def test_check_json_one_line_per_finding_rc1(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    rc = _cli(tmp_path, "check", "--json", str(pkg))
    assert rc == 1
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    assert len(lines) == 1
    rec = json.loads(lines[0])
    assert set(rec) == {"file", "line", "rule", "fingerprint", "message"}
    assert rec["rule"] == "blocking-under-lock"
    assert rec["file"].endswith("pkg/router.py")
    assert isinstance(rec["line"], int) and rec["line"] > 0
    assert isinstance(rec["fingerprint"], str) and len(rec["fingerprint"]) == 16


def test_check_clean_package_rc0(tmp_path, capsys):
    pkg = _check_pkg(tmp_path, "X = 1\n")
    rc = _cli(tmp_path, "check", "--json", str(pkg))
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_check_usage_errors_rc2(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    assert _cli(tmp_path, "check", "--rules", "nosuch", str(pkg)) == 2
    assert "unknown rule" in capsys.readouterr().err
    assert _cli(tmp_path, "check", str(pkg / "missing")) == 2
    capsys.readouterr()
    assert _cli(tmp_path, "check", "--baseline",
                str(tmp_path / "nope.json"), str(pkg)) == 2


def test_check_baseline_suppresses_to_rc0(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    bp = tmp_path / "baseline.json"
    assert _cli(tmp_path, "check", "--baseline", str(bp)) == 2  # missing
    capsys.readouterr()
    # --update-baseline writes it; justify; then the run is clean
    assert _cli(tmp_path, "check", "--update-baseline",
                "--baseline", str(bp), str(pkg)) == 0
    capsys.readouterr()
    body = bp.read_text().replace(
        "TODO: one line on why this finding is deliberately kept",
        "bounded join by design")
    bp.write_text(body)
    rc = _cli(tmp_path, "check", "--json", "--baseline", str(bp), str(pkg))
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_check_rules_filter(tmp_path, capsys):
    pkg = _check_pkg(tmp_path)
    rc = _cli(tmp_path, "check", "--json", "--rules", "signal-safety",
              str(pkg))
    assert rc == 0  # the join bug is not a signal-safety finding
    assert capsys.readouterr().out.strip() == ""


def test_check_update_baseline_refuses_partial_views(tmp_path, capsys):
    # review fix: rewriting the baseline from a --rules or --diff
    # subset would silently drop every other rule's suppressions
    pkg = _check_pkg(tmp_path)
    bp = tmp_path / "baseline.json"
    rc = _cli(tmp_path, "check", "--update-baseline", "--baseline", str(bp),
              "--rules", "signal-safety", str(pkg))
    assert rc == 2
    assert "--rules" in capsys.readouterr().err
    assert not bp.exists()
