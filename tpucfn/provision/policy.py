"""Goodput-driven provisioner policy (ISSUE 18).

The goodput ledger (tpucfn/obs/goodput.py) has named the thief per run
since ISSUE 10 — ``data_wait`` share for input-bound fleets, ``compile``
share for cold starts — but nothing ever *acted* on it.  This module is
the decision layer that closes the loop: observe the fleet's bucket
shares, classify the run, and pick an actuation through primitives that
already exist:

* **grow the input plane** (activate deferred input hosts, ISSUE 11)
  when the ``data_wait`` share says trainers are starved and the
  projected savings over the policy horizon beat the actuation cost;
* **shrink the input plane** when served batches are no longer the
  bottleneck (PR 11's resilient streams degrade trainers back to local
  loading at the exact batch cursor, so a shrink is trajectory-safe);
* **flag chronic starvation** — accelerator hosts that stay starved
  across consecutive windows even with the input plane up are burning
  reserved capacity; the fleet operator (or a queued-resource resize)
  is the actuator, so the policy only raises the flag.

The actuation-latency model is fetch-warm spin-up (ISSUE 13): a grown
input host costs ``spinup_s`` to fan out plus the trainers' warm
time-to-first-step after the drain-relaunch — ``warm_ttfs_frac *
cold_ttfs_s``, the measured 0.35x bound from compile_bench — not a full
cold compile.  That is what makes growing *worth it* mid-run at all.

Same discipline as :mod:`tpucfn.ft.policy`, which this mirrors: pure
and jax-free (the coordinator imports it; so does the analyzer), no
wall-clock reads outside the injectable ``clock``, and a module-level
decision table the ``decision-totality`` rule audits — every
:class:`GoodputSignal` earns a row, every row's action has an actor.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Callable, Mapping


class GoodputSignal(enum.Enum):
    """Classification of one fleet observation window."""

    HEALTHY = "healthy"          # nothing dominates; leave the fleet alone
    DATA_STARVED = "data_starved"    # data_wait share over threshold
    DATA_RICH = "data_rich"          # input plane up, data_wait ~ zero
    CHRONIC_STARVATION = "chronic_starvation"  # starved across N windows
    COMPILE_BOUND = "compile_bound"  # compile share dominates (warm-start
    #                                  plane's job, not a topology change)


class PolicyAction(enum.Enum):
    HOLD = "hold"
    GROW_INPUT_HOSTS = "grow_input_hosts"
    SHRINK_INPUT_HOSTS = "shrink_input_hosts"
    FLAG_STARVED = "flag_starved"


# signal → action, audited by the decision-totality rule: every signal
# has a row, every action is actuated (or deliberately held) somewhere
# in the coordinator.  COMPILE_BOUND holds on purpose — the compile
# cache (ISSUE 13) already amortizes compiles fleet-wide; resizing the
# input plane would not move that share.
PROVISION_DECISION_TABLE: dict[GoodputSignal, PolicyAction] = {
    GoodputSignal.HEALTHY: PolicyAction.HOLD,
    GoodputSignal.DATA_STARVED: PolicyAction.GROW_INPUT_HOSTS,
    GoodputSignal.DATA_RICH: PolicyAction.SHRINK_INPUT_HOSTS,
    GoodputSignal.CHRONIC_STARVATION: PolicyAction.FLAG_STARVED,
    GoodputSignal.COMPILE_BOUND: PolicyAction.HOLD,
}


@dataclasses.dataclass(frozen=True)
class PolicyConfig:
    """Thresholds + the actuation-latency model, all explicit so a test
    pins every branch with a fake ledger and a fake clock."""

    # data_wait share above which trainers count as starved (the bench's
    # input-bound verdict uses the same order of magnitude).
    grow_threshold: float = 0.25
    # data_wait share below which a grown input plane is idle freight.
    shrink_threshold: float = 0.02
    # Observation windows shorter than this are noise, not signal.
    min_window_s: float = 1.0
    # No two actuations closer than this (a drain-relaunch mid-cooldown
    # would measure its own downtime as starvation and oscillate).
    cooldown_s: float = 30.0
    # Topology ceiling: never grow past what the launcher reserved.
    max_input_hosts: int = 1
    # Consecutive starved windows WITH the input plane already at its
    # ceiling before the fleet is flagged chronically starved.
    chronic_windows: int = 3
    # -- actuation-latency model (fetch-warm spin-up, ISSUE 13) --------
    # Fan-out + serve-ready cost of activating one input host.
    spinup_s: float = 5.0
    # Cold time-to-first-step the relaunched trainers would pay bare...
    cold_ttfs_s: float = 60.0
    # ...discounted to the fetch-warm fraction (compile_bench's 0.35x
    # acceptance bound) because the artifact cache serves the relaunch.
    warm_ttfs_frac: float = 0.35
    # Horizon the projected data_wait savings must amortize the
    # actuation latency over.
    horizon_s: float = 600.0

    def actuation_latency_s(self) -> float:
        """What one grow costs the fleet in wall seconds: input-host
        spin-up plus the trainers' fetch-warm relaunch TTFS."""
        return self.spinup_s + self.warm_ttfs_frac * self.cold_ttfs_s


@dataclasses.dataclass(frozen=True)
class FleetObservation:
    """One merged goodput window (obs.goodput.fleet_window_observation)."""

    wall_s: float
    goodput_ratio: float
    shares: Mapping[str, float]  # bucket → share of wall, averaged
    num_hosts: int = 1

    @property
    def data_wait_share(self) -> float:
        return float(self.shares.get("data_wait", 0.0))

    @property
    def compile_share(self) -> float:
        return float(sum(self.shares.get(b, 0.0) for b in
                         ("compile", "compile_cached", "compile_fetched")))


@dataclasses.dataclass(frozen=True)
class PolicyDecision:
    action: PolicyAction
    signal: GoodputSignal
    reason: str
    data_wait_share: float = 0.0
    goodput_ratio: float = 0.0
    # Filled for GROW decisions: the cost model that justified it.
    actuation_latency_s: float = 0.0
    projected_savings_s: float = 0.0


class ProvisionPolicy:
    """Deterministic decide() over fleet goodput windows.

    All state is explicit (consecutive starved-window count, last
    actuation time) and all timing flows through the injectable
    ``clock``, so the full decision surface pins under a fake clock —
    the same testability contract :class:`~tpucfn.ft.policy.StragglerGuard`
    set.
    """

    name = "goodput"

    def __init__(self, config: PolicyConfig | None = None, *,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or PolicyConfig()
        self.clock = clock
        self._last_actuation_t: float | None = None
        self._starved_windows = 0

    # -- classification ----------------------------------------------------

    def classify(self, obs: FleetObservation, *,
                 input_hosts: int) -> GoodputSignal:
        cfg = self.config
        starved = obs.data_wait_share > cfg.grow_threshold
        if starved and input_hosts >= cfg.max_input_hosts:
            # Input plane already at ceiling and still starved: count
            # the window toward the chronic verdict.
            if self._starved_windows + 1 >= cfg.chronic_windows:
                return GoodputSignal.CHRONIC_STARVATION
            return GoodputSignal.HEALTHY  # still accumulating evidence
        if starved:
            return GoodputSignal.DATA_STARVED
        if input_hosts > 0 and obs.data_wait_share < cfg.shrink_threshold:
            return GoodputSignal.DATA_RICH
        if obs.compile_share > max(cfg.grow_threshold, obs.data_wait_share):
            return GoodputSignal.COMPILE_BOUND
        return GoodputSignal.HEALTHY

    # -- decision ----------------------------------------------------------

    def decide(self, obs: FleetObservation | None, *, input_hosts: int,
               now: float | None = None) -> PolicyDecision:
        now = self.clock() if now is None else now
        cfg = self.config
        if obs is None or obs.wall_s < cfg.min_window_s:
            return PolicyDecision(
                PolicyAction.HOLD, GoodputSignal.HEALTHY,
                reason="window too short to classify"
                       if obs is not None else "no goodput window yet")
        signal = self.classify(obs, input_hosts=input_hosts)
        # Track consecutive at-ceiling starvation for the chronic verdict
        # (grow-eligible starvation resets on actuation, not here).
        at_ceiling = input_hosts >= cfg.max_input_hosts
        if obs.data_wait_share > cfg.grow_threshold and at_ceiling:
            self._starved_windows += 1
        elif obs.data_wait_share <= cfg.grow_threshold:
            self._starved_windows = 0
        action = PROVISION_DECISION_TABLE[signal]
        base = dataclasses.replace(
            PolicyDecision(action, signal, reason=""),
            data_wait_share=obs.data_wait_share,
            goodput_ratio=obs.goodput_ratio)
        if action is PolicyAction.HOLD:
            return dataclasses.replace(
                base, reason=f"{signal.value}: no actuation warranted")
        if self._last_actuation_t is not None \
                and now - self._last_actuation_t < cfg.cooldown_s:
            return dataclasses.replace(
                base, action=PolicyAction.HOLD,
                reason=f"{signal.value} but cooling down "
                       f"({now - self._last_actuation_t:.1f}s of "
                       f"{cfg.cooldown_s:.1f}s)")
        if action is PolicyAction.GROW_INPUT_HOSTS:
            latency = cfg.actuation_latency_s()
            # Project the starved share forward over the horizon; the
            # grow pays off when the reclaimed wall beats the drain-
            # relaunch cost.  data_wait rarely reaches zero post-grow, so
            # credit only the share above the shrink floor.
            reclaimable = max(
                0.0, obs.data_wait_share - cfg.shrink_threshold)
            savings = reclaimable * cfg.horizon_s
            if savings <= latency:
                return dataclasses.replace(
                    base, action=PolicyAction.HOLD,
                    reason=f"data_starved but projected savings "
                           f"{savings:.1f}s over {cfg.horizon_s:.0f}s "
                           f"horizon does not amortize "
                           f"{latency:.1f}s actuation",
                    actuation_latency_s=latency,
                    projected_savings_s=savings)
            self._last_actuation_t = now
            self._starved_windows = 0
            return dataclasses.replace(
                base,
                reason=f"data_wait share {obs.data_wait_share:.2f} > "
                       f"{cfg.grow_threshold:.2f}: grow input plane "
                       f"(savings {savings:.1f}s > actuation "
                       f"{latency:.1f}s)",
                actuation_latency_s=latency,
                projected_savings_s=savings)
        if action is PolicyAction.SHRINK_INPUT_HOSTS:
            self._last_actuation_t = now
            return dataclasses.replace(
                base,
                reason=f"data_wait share {obs.data_wait_share:.2f} < "
                       f"{cfg.shrink_threshold:.2f}: input plane is idle "
                       "freight; trainers degrade to local at the exact "
                       "batch cursor")
        # PolicyAction.FLAG_STARVED — observation-only: the operator (or
        # a queued-resource resize) owns the accelerator topology.
        return dataclasses.replace(
            base,
            reason=f"starved {self._starved_windows} consecutive windows "
                   f"with input plane at ceiling ({input_hosts}): "
                   "accelerator hosts are burning reserved capacity")


PROVISION_POLICIES = {ProvisionPolicy.name: ProvisionPolicy}


def provision_policy_from_name(
        name: str, config: PolicyConfig | None = None, *,
        clock: Callable[[], float] = time.monotonic) -> ProvisionPolicy:
    try:
        cls = PROVISION_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown provision policy {name!r}; choose from "
            f"{sorted(PROVISION_POLICIES)}") from None
    return cls(config, clock=clock)
