"""Obs primitives + MetricRegistry/Histogram/exposition (ISSUE 2).

Covers the satellite checklist — Summary reservoir truncation and
percentile edge cases, StepTimer warmup exclusion, Counter.add under
thread contention, Histogram bucket boundaries — plus the registry's
get-or-create semantics and the Prometheus text format it renders.
"""

import json
import math
import re
import threading

import pytest

from tpucfn.obs import Counter, Gauge, Histogram, MetricRegistry, StepTimer, Summary
from tpucfn.obs.registry import sanitize_metric_name


# ---- Summary ------------------------------------------------------------

def test_summary_empty_percentiles_are_none():
    s = Summary("x")
    assert s.percentile(50) is None
    snap = s.snapshot()
    assert snap["count"] == 0 and snap["mean"] is None and snap["p99"] is None


def test_summary_single_sample_every_percentile():
    s = Summary("x")
    s.observe(7.0)
    assert s.percentile(0) == 7.0
    assert s.percentile(50) == 7.0
    assert s.percentile(100) == 7.0
    assert s.snapshot()["p95"] == 7.0


def test_summary_p0_p100_are_min_max():
    s = Summary("x")
    for v in (5.0, 1.0, 3.0, 9.0, 2.0):
        s.observe(v)
    assert s.percentile(0) == 1.0
    assert s.percentile(100) == 9.0


def test_summary_reservoir_truncates_to_recent_keep():
    s = Summary("x", keep=10)
    for v in range(100):
        s.observe(float(v))
    # exact aggregates survive truncation...
    assert s.count == 100 and s.sum == sum(range(100))
    # ...percentiles cover only the most recent `keep` samples (90..99)
    assert len(s._recent) == 10
    assert s.percentile(0) == 90.0 and s.percentile(100) == 99.0


def test_summary_percentiles_one_pass_matches_individual():
    s = Summary("x")
    for v in (0.4, 0.1, 0.9, 0.2, 0.6):
        s.observe(v)
    pcts = s.percentiles((0.0, 50.0, 95.0, 100.0))
    assert pcts == {0.0: s.percentile(0), 50.0: s.percentile(50),
                    95.0: s.percentile(95), 100.0: s.percentile(100)}


# ---- StepTimer ----------------------------------------------------------

def test_step_timer_warmup_ticks_excluded_from_mean(monkeypatch):
    clock = {"t": 0.0}

    def fake_clock():
        clock["t"] += 1.0  # one second per observation, deterministic
        return clock["t"]

    monkeypatch.setattr("tpucfn.obs.metrics.time.perf_counter", fake_clock)
    t = StepTimer(warmup=2)
    for _ in range(6):
        t.tick()
    # 6 ticks -> 5 measured deltas of 1.0; the first 2 are warmup
    assert t._count == 5
    assert t.mean_step_time == pytest.approx(1.0)
    assert t._total == pytest.approx(3.0)  # only steady-state summed


# ---- Counter under contention ------------------------------------------

def test_counter_thread_contention_exact():
    c = Counter("hits")
    n_threads, n_adds = 8, 2000

    def work():
        for _ in range(n_adds):
            c.add()

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_adds


# ---- Histogram ----------------------------------------------------------

def test_histogram_bucket_boundaries_le_semantics():
    h = Histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 100.0):
        h.observe(v)
    cum = dict(h.cumulative())
    # le is INCLUSIVE: 1.0 lands in the le=1.0 bucket, 2.0 in le=2.0...
    assert cum[1.0] == 2          # 0.5, 1.0
    assert cum[2.0] == 4          # + 1.5, 2.0
    assert cum[4.0] == 6          # + 3.0, 4.0
    assert cum[math.inf] == 7     # + 100.0 overflow
    assert h.count == 7
    assert h.sum == pytest.approx(0.5 + 1.0 + 1.5 + 2.0 + 3.0 + 4.0 + 100.0)


def test_histogram_cumulative_monotone_and_inf_equals_count():
    h = Histogram("h")
    import random
    rng = random.Random(0)
    for _ in range(500):
        h.observe(rng.expovariate(10.0))
    cum = h.cumulative()
    counts = [c for _, c in cum]
    assert counts == sorted(counts)
    assert cum[-1][0] == math.inf and cum[-1][1] == 500


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram("h", buckets=())
    with pytest.raises(ValueError):
        Histogram("h", buckets=(1.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("h", buckets=(2.0, 1.0))


def test_histogram_snapshot_json_roundtrips():
    h = Histogram("h", buckets=(0.1, 1.0))
    h.observe(0.05)
    snap = json.loads(json.dumps(h.snapshot()))
    assert snap["count"] == 1 and snap["buckets"]["+Inf"] == 1


# ---- MetricRegistry -----------------------------------------------------

def test_registry_get_or_create_returns_same_instrument():
    r = MetricRegistry()
    assert r.counter("a_total") is r.counter("a_total")
    with pytest.raises(ValueError):
        r.gauge("a_total")  # same name, different type


def test_registry_rejects_conflicting_histogram_buckets():
    r = MetricRegistry()
    h = r.histogram("lat_seconds", buckets=(0.1, 1.0))
    assert r.histogram("lat_seconds", buckets=(0.1, 1.0)) is h  # same config
    with pytest.raises(ValueError):
        r.histogram("lat_seconds", buckets=(0.5, 5.0))  # silently-wrong SLOs


def test_registry_rejects_conflicting_summary_keep():
    r = MetricRegistry()
    s = r.summary("ttft_seconds", keep=128)
    assert r.summary("ttft_seconds", keep=128) is s
    with pytest.raises(ValueError):
        r.summary("ttft_seconds", keep=4096)


def test_registry_register_conflicting_object_raises():
    r = MetricRegistry()
    s = Summary("ttft")
    assert r.register("ttft_seconds", s) is s
    assert r.register("ttft_seconds", s) is s  # idempotent for same object
    with pytest.raises(ValueError):
        r.register("ttft_seconds", Summary("other"))


def test_sanitize_metric_name():
    assert sanitize_metric_name("ok_name:x") == "ok_name:x"
    assert sanitize_metric_name("bad-name.1") == "bad_name_1"
    assert sanitize_metric_name("9leading") == "_9leading"


# ---- Prometheus exposition ---------------------------------------------

LINE_RE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? (?:[+-]?(?:\d+\.?\d*(?:e[+-]?\d+)?|Inf)|NaN))$"
)


def _valid_exposition(text: str) -> None:
    """Line-by-line structural validation of the text format."""
    assert text.endswith("\n")
    for line in text.rstrip("\n").splitlines():
        assert LINE_RE.match(line), f"invalid exposition line: {line!r}"


def test_prometheus_exposition_all_types():
    r = MetricRegistry(labels={"host": "3", "role": "trainer"})
    r.counter("reqs_total", "requests").add(5)
    r.gauge("depth").set(2)
    s = r.summary("lat_seconds")
    for v in (0.1, 0.2, 0.4):
        s.observe(v)
    h = r.histogram("step_seconds", buckets=(0.5, 1.0))
    h.observe(0.25)
    h.observe(3.0)
    text = r.to_prometheus()
    _valid_exposition(text)
    assert '# TYPE reqs_total counter' in text
    assert 'reqs_total{host="3",role="trainer"} 5.0' in text
    assert '# HELP reqs_total requests' in text
    assert 'lat_seconds{host="3",role="trainer",quantile="0.5"} 0.2' in text
    assert 'lat_seconds_count{host="3",role="trainer"} 3.0' in text
    assert 'step_seconds_bucket{host="3",role="trainer",le="+Inf"} 2.0' in text
    assert 'step_seconds_bucket{host="3",role="trainer",le="0.5"} 1.0' in text


def test_empty_summary_emits_no_quantiles_but_keeps_count():
    r = MetricRegistry()
    r.summary("empty_seconds")
    text = r.to_prometheus()
    _valid_exposition(text)
    assert "quantile" not in text
    assert "empty_seconds_count 0.0" in text


def test_varz_snapshot_shape():
    r = MetricRegistry(labels={"host": "0"})
    r.counter("c_total").add(2)
    r.histogram("h_seconds", buckets=(1.0,)).observe(0.5)
    v = json.loads(json.dumps(r.varz()))
    assert v["labels"] == {"host": "0"}
    assert v["metrics"]["c_total"] == 2.0
    assert v["metrics"]["h_seconds"]["count"] == 1


def test_gauge_still_lock_free_assignment():
    g = Gauge("g")
    g.set(4)
    assert g.value == 4.0
