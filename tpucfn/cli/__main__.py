import sys

from tpucfn.cli.main import main

sys.exit(main())
