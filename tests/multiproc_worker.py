"""Worker for the multi-process rendezvous test (not a pytest module).

Launched by tests/test_multiprocess.py via the Launcher: joins the
jax.distributed rendezvous from the env contract, builds a global mesh
over both processes' CPU devices, and runs a cross-process reduction.
"""

import os
import sys

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> int:
    from tpucfn.launch import initialize_runtime
    from tpucfn.mesh import MeshSpec, build_mesh
    from tpucfn.parallel import shard_batch

    contract = initialize_runtime()
    assert contract is not None, "no cluster env"
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4  # 2 procs x 2 fake devices

    mesh = build_mesh(MeshSpec(data=4))
    # each process contributes rows of value (process_index + 1)
    local = np.full((2, 3), jax.process_index() + 1.0, np.float32)
    batch = shard_batch(mesh, {"x": local})
    total = jax.jit(lambda b: jnp.sum(b["x"]))(batch)
    expect = (1 + 2) * 2 * 3
    assert float(total) == expect, (float(total), expect)
    print(f"RENDEZVOUS_OK rank={jax.process_index()} total={float(total)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
