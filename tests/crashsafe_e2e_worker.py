"""Stdlib-only deterministic toy trainer for the coordinator
crash-safety drill (ISSUE 12).

Mirrors ft_e2e_worker.py's recovery-plane behavior without the
jax/orbax import cost (the drill kills the COORDINATOR, not jax):
heartbeats via HeartbeatWriter (jax-free), a JSON checkpoint host 0
atomically rewrites every CRASHSAFE_CKPT_EVERY steps, resume-from-
checkpoint on startup, and a per-step loss trajectory appended to
JSONL.  The math is exactly deterministic — w ← 0.9·w + 0.1 — so any
two runs agree bit-for-bit wherever their step ranges overlap, which
is what lets the drill compare a twice-supervised run against an
uninterrupted reference."""

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tpucfn.ft import HeartbeatWriter  # noqa: E402  (jax-free)


def main() -> int:
    host = int(os.environ.get("TPUCFN_HOST_ID", "0"))
    run_dir = Path(os.environ["CRASHSAFE_RUN_DIR"])
    total = int(os.environ.get("CRASHSAFE_TOTAL_STEPS", "40"))
    ckpt_every = int(os.environ.get("CRASHSAFE_CKPT_EVERY", "10"))
    step_sleep = float(os.environ.get("CRASHSAFE_STEP_SLEEP", "0.05"))
    ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()
    hb_s = float(os.environ.get("TPUCFN_FT_HEARTBEAT_S", "0.05") or 0.05)
    hb = None
    if ft_dir:
        hb = HeartbeatWriter(ft_dir, host_id=host, interval_s=hb_s,
                             role="trainer").start()
    ckpt = run_dir / "ckpt.json"
    step, w = 0, 10.0
    if ckpt.exists():
        rec = json.loads(ckpt.read_text())
        step, w = int(rec["step"]), float(rec["w"])
    losses = run_dir / f"losses-host{host:03d}.jsonl"
    try:
        with open(losses, "a") as f:
            while step < total:
                w = 0.9 * w + 0.1
                step += 1
                f.write(json.dumps({"step": step, "w": w,
                                    "pid": os.getpid()}) + "\n")
                f.flush()
                if hb is not None:
                    hb.update_step(step)
                time.sleep(step_sleep)
                if host == 0 and step % ckpt_every == 0:
                    tmp = ckpt.with_suffix(".tmp")
                    tmp.write_text(json.dumps({"step": step, "w": w}))
                    tmp.replace(ckpt)  # atomic: a kill never tears it
    finally:
        if hb is not None:
            hb.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
