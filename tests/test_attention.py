import jax
import jax.numpy as jnp
import numpy as np

from tpucfn.ops.attention import dot_product_attention


def _naive(q, k, v, causal, q_off=0, k_off=0):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    out = np.zeros_like(np.asarray(q, np.float32))
    for bi in range(b):
        for hi in range(h):
            logits = (np.asarray(q[bi, :, hi]) @ np.asarray(k[bi, :, hi]).T) / np.sqrt(d)
            if causal:
                for i in range(sq):
                    for j in range(sk):
                        if i + q_off < j + k_off:
                            logits[i, j] = -np.inf
            m = logits.max(-1, keepdims=True)
            m = np.where(np.isfinite(m), m, 0.0)
            p = np.exp(logits - m)
            denom = p.sum(-1, keepdims=True)
            p = np.where(denom > 0, p / np.maximum(denom, 1e-30), 0.0)
            out[bi, :, hi] = p @ np.asarray(v[bi, :, hi])
    return out


def test_matches_naive_causal():
    rng = jax.random.key(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 8, 4, 16))
               for i in range(3))
    out = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), _naive(q, k, v, True), atol=1e-5)


def test_matches_naive_bidirectional():
    rng = jax.random.key(1)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 8, 4, 16))
               for i in range(3))
    out = dot_product_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), _naive(q, k, v, False), atol=1e-5)


def test_offsets_reproduce_block_of_full_attention():
    """A (q block, k block) pair with offsets must equal the corresponding
    slice of full attention when the block is self-contained — the property
    ring attention is built on."""
    rng = jax.random.key(2)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (1, 16, 2, 8))
               for i in range(3))
    full = dot_product_attention(q, k, v, causal=True)
    # second half queries against full prefix: split ks
    out = dot_product_attention(q[:, 8:], k, v, causal=True, q_offset=8, k_offset=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full[:, 8:]), atol=1e-5)


def test_fully_masked_rows_are_zero():
    """Ring blocks where every key is in the future must output zeros."""
    rng = jax.random.key(3)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (1, 4, 2, 8))
               for i in range(3))
    out = dot_product_attention(q, k, v, causal=True, q_offset=0, k_offset=100)
    np.testing.assert_allclose(np.asarray(out), np.zeros_like(out), atol=1e-6)


def test_gqa_equals_repeated_kv():
    rng = jax.random.key(4)
    q = jax.random.normal(jax.random.fold_in(rng, 0), (2, 8, 8, 16))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (2, 8, 2, 16))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (2, 8, 2, 16))
    out_gqa = dot_product_attention(q, k, v, causal=True)
    k_rep = jnp.repeat(k, 4, axis=2)
    v_rep = jnp.repeat(v, 4, axis=2)
    out_full = dot_product_attention(q, k_rep, v_rep, causal=True)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_full), atol=1e-6)


def test_bf16_inputs_fp32_softmax_stable():
    q = (jnp.ones((1, 4, 1, 8)) * 30).astype(jnp.bfloat16)
    k = (jnp.ones((1, 4, 1, 8)) * 30).astype(jnp.bfloat16)
    v = jnp.arange(4, dtype=jnp.bfloat16).reshape(1, 4, 1, 1) * jnp.ones((1, 4, 1, 8), jnp.bfloat16)
    out = dot_product_attention(q, k, v, causal=False)
    assert out.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())
