from tpucfn.ckpt.manager import CheckpointManager  # noqa: F401
