"""metric-hygiene: the /metrics surface stays coherent by construction.

Incident encoded (CHANGES.md, PR 8): ``router_request_latency_seconds``
was built as a bare ``Summary(...)`` and never registered, so /metrics
silently lost request latency exactly when ``--replicas`` turned on —
found only because a review pass went looking.  Plus the general
hygiene the registry cannot check across modules: the same name
registered as two different instrument types (or with two different
help strings) splits or silently shadows one series, and names outside
the fleet prefix convention don't group in dashboards.

Checks:

1. **prefix convention** — every literally-named registration must
   match ``^(serve|train|ft|router|obs|device|jit|supervisor)_``.
2. **type conflict** — one name, two instrument types, anywhere in the
   package.
3. **help conflict** — one name, two different non-empty help strings
   (the registry keeps the first and silently drops the second).
4. **unregistered instrument** — a direct ``Summary(...)`` /
   ``Counter(...)`` / ... construction whose literal name claims a
   fleet prefix but is never registered in any registry: it looks like
   a /metrics series and is invisible there (the lost-Summary bug).
   Deliberately-private instruments use a non-fleet name (as
   ``request_latency_s`` does) and stay silent.
5. **dangling references** — metric-shaped literals (``<prefix>_*_
   total|seconds|bytes|rate|ratio``) in the repo's tests or README that
   no registration produces: the test or doc pins a series that does
   not exist.

Dynamic names are handled conservatively: f-string registrations
become wildcard patterns; a module that registers through a variable
(device-gauge tables) contributes its module-level string tables to the
known-name set.
"""

from __future__ import annotations

import ast
import re

from tpucfn.analysis.core import Analysis, Finding

RULE_ID = "metric-hygiene"

PREFIXES = ("serve", "train", "ft", "router", "obs", "device", "jit",
            "supervisor", "input", "coordinator", "compilecache", "net",
            "provision", "rl")
PREFIX_RE = re.compile(r"^(%s)_" % "|".join(PREFIXES))
REF_RE = re.compile(
    r"^(%s)_[a-z0-9_]*_(total|seconds|bytes|rate|ratio)$" % "|".join(PREFIXES))
_README_TOKEN = re.compile(
    r"\b(%s)_[a-z0-9_]*_(?:total|seconds|bytes|rate|ratio)\b"
    % "|".join(PREFIXES))

REG_METHODS = ("counter", "gauge", "summary", "histogram", "computed_gauge")
INSTRUMENT_CLASSES = {"Counter": "counter", "Gauge": "gauge",
                      "Summary": "summary", "Histogram": "histogram",
                      "ComputedGauge": "computed_gauge"}


def _literal_help(call: ast.Call, type_: str) -> str | None:
    """The literal help string of a registration, if statically
    visible.  computed_gauge takes (name, fn, help); the others take
    (name, help)."""
    pos = 2 if type_ == "computed_gauge" else 1
    if len(call.args) > pos and isinstance(call.args[pos], ast.Constant) \
            and isinstance(call.args[pos].value, str):
        return call.args[pos].value
    for kw in call.keywords:
        if kw.arg == "help" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _joinedstr_pattern(node: ast.JoinedStr) -> str | None:
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(re.escape(str(v.value)))
        else:
            parts.append(r"[A-Za-z0-9_]+")
    return "^" + "".join(parts) + "$"


def check(analysis: Analysis):
    registrations: list[tuple] = []  # (name, type, mod, line, help)
    patterns: list[re.Pattern] = []
    constructions: list[tuple] = []  # (name, type, mod, line)
    registered_names: set[str] = set()

    for mod in analysis.modules:
        dynamic_reg = False
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in REG_METHODS \
                    and node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) \
                        and isinstance(arg0.value, str):
                    registrations.append(
                        (arg0.value, f.attr, mod, node.lineno,
                         _literal_help(node, f.attr)))
                    registered_names.add(arg0.value)
                elif isinstance(arg0, ast.JoinedStr):
                    pat = _joinedstr_pattern(arg0)
                    if pat:
                        patterns.append(re.compile(pat))
                else:
                    dynamic_reg = True
            elif isinstance(f, ast.Attribute) and f.attr == "register" \
                    and node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) \
                        and isinstance(arg0.value, str):
                    registered_names.add(arg0.value)
                else:
                    dynamic_reg = True
            elif isinstance(f, ast.Name) and f.id in INSTRUMENT_CLASSES \
                    and node.args:
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) \
                        and isinstance(arg0.value, str):
                    constructions.append(
                        (arg0.value, INSTRUMENT_CLASSES[f.id], mod,
                         node.lineno))
        if dynamic_reg:
            # variable-named registrations: trust the module's own
            # string tables (the _HBM_GAUGES pattern) as the name source
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Constant) \
                                and isinstance(sub.value, str) \
                                and PREFIX_RE.match(sub.value):
                            registered_names.add(sub.value)

    findings: list[Finding] = []

    # 1. prefix convention
    for name, type_, mod, line, _ in registrations:
        if not PREFIX_RE.match(name):
            findings.append(Finding(
                RULE_ID, mod.rel, line,
                f"metric {name!r} violates the fleet naming convention "
                f"^({'|'.join(PREFIXES)})_ — out-of-family names do not "
                "group in dashboards and bypass fleet-wide checks",
                key=f"prefix:{name}"))

    # 2./3. same name, different type / help
    by_name: dict[str, list[tuple]] = {}
    for reg in registrations:
        by_name.setdefault(reg[0], []).append(reg)
    for name, regs in by_name.items():
        types = {r[1] for r in regs}
        if len(types) > 1:
            first_type = regs[0][1]
            for r in regs:
                if r[1] != first_type:
                    findings.append(Finding(
                        RULE_ID, r[2].rel, r[3],
                        f"metric {name!r} registered as {r[1]} here but "
                        f"as {first_type} in {regs[0][2].rel} — the "
                        "registry raises at runtime, and only on the "
                        "code path that loses the race",
                        key=f"type:{name}:{r[1]}"))
        helps = [r for r in regs if r[4]]
        distinct = {r[4] for r in helps}
        if len(distinct) > 1:
            first_help = helps[0][4]
            for r in helps:
                if r[4] != first_help:
                    findings.append(Finding(
                        RULE_ID, r[2].rel, r[3],
                        f"metric {name!r} registered with a different "
                        f"help string than in {helps[0][2].rel} — the "
                        "registry keeps the first and silently drops "
                        "this one",
                        key=f"help:{name}"))

    # 4. fleet-named instrument never registered (the lost-Summary bug)
    for name, type_, mod, line in constructions:
        if PREFIX_RE.match(name) and name not in registered_names \
                and not any(p.match(name) for p in patterns):
            findings.append(Finding(
                RULE_ID, mod.rel, line,
                f"{type_} {name!r} is constructed directly but never "
                "registered in any MetricRegistry — it claims a fleet "
                "metric name yet /metrics will not expose it (register "
                "it, or use a non-fleet name for a private instrument)",
                key=f"unregistered:{name}"))

    # 5. dangling references in tests / README
    def _known(name: str) -> bool:
        return name in registered_names or \
            any(p.match(name) for p in patterns)

    if analysis.tests_dir is not None:
        for p in sorted(analysis.tests_dir.glob("*.py")):
            try:
                tree = ast.parse(p.read_text(encoding="utf-8",
                                             errors="replace"))
            except SyntaxError:
                continue
            rel = p.relative_to(analysis.repo_root).as_posix()
            seen: set[str] = set()
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and REF_RE.match(node.value) \
                        and not _known(node.value) \
                        and node.value not in seen:
                    seen.add(node.value)
                    findings.append(Finding(
                        RULE_ID, rel, node.lineno,
                        f"test references metric {node.value!r} which no "
                        "registration in the package produces — the "
                        "series this pins does not exist",
                        key=f"ref:{node.value}"))
    if analysis.readme is not None:
        rel = analysis.readme.relative_to(analysis.repo_root).as_posix()
        seen = set()
        for i, line_text in enumerate(
                analysis.readme.read_text(errors="replace").splitlines(), 1):
            for m in _README_TOKEN.finditer(line_text):
                name = m.group(0)
                if not _known(name) and name not in seen:
                    seen.add(name)
                    findings.append(Finding(
                        RULE_ID, rel, i,
                        f"README documents metric {name!r} which no "
                        "registration in the package produces",
                        key=f"ref:{name}"))
    return findings
