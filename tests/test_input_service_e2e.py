"""ISSUE 11 acceptance drill: 1 input host + 2 trainer hosts under the
real launch fan-out.

Three runs over the same shards, same seeds:

* **reference** — trainers load locally (no input plane); also the
  bit-identical ground truth and the input-bound goodput baseline
  (every batch pays the synthetic decode serially with compute).
* **served** — `tpucfn launch`-shaped fan-out with one input host
  running the real `tpucfn data serve` CLI; trajectory must equal the
  reference bit-for-bit and the fleet ``data_wait`` share must be
  STRICTLY lower (with buckets still summing to wall time) — the
  goodput half of the acceptance criteria.
* **chaos** — same fan-out, input host chaos-killed mid-run: the
  coordinator records ``input_degraded`` (no detect/decide incident, no
  gang restart, budget untouched), trainers degrade to local loading at
  the exact batch cursor, the run completes rc 0, and the trajectory is
  STILL bit-identical to the reference.
"""

import json
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.data import write_dataset_shards
from tpucfn.ft import (
    ChaosEvent,
    ChaosSpec,
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
)
from tpucfn.launch import Launcher, LocalTransport

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
WORKER = Path(__file__).resolve().parent / "input_e2e_worker.py"

TRAINERS = 2
BATCH = 8
SEED = 5
EPOCHS = 1
# 480 examples over 8 shards -> 4 shards (240 examples, 30 batches) per
# trainer per epoch
EXAMPLES, SHARDS = 480, 8
STEPS_PER_TRAINER = 30


def _write_shards(tmp_path) -> Path:
    d = tmp_path / "shards"
    d.mkdir()
    rs = np.random.RandomState(1)
    # 16 KB/example -> 128 KB/batch: bigger than the socket buffers, so
    # a killed input host is NOTICED mid-stream (tiny batches would let
    # the whole epoch hide in TCP buffering and the drill would pass
    # vacuously without ever degrading)
    write_dataset_shards(
        ({"x": rs.randn(4096).astype(np.float32)} for _ in range(EXAMPLES)),
        d, num_shards=SHARDS)
    return d


def _contract(tmp_path, n) -> EnvContract:
    hostfile = tmp_path / f"hostfile{n}"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def _worker_env(run_dir: Path, shards: Path) -> dict[str, str]:
    return {
        "INPUT_E2E_RUN_DIR": str(run_dir),
        "INPUT_E2E_SHARDS": str(shards),
        "INPUT_E2E_BATCH": str(BATCH),
        "INPUT_E2E_SEED": str(SEED),
        "INPUT_E2E_EPOCHS": str(EPOCHS),
        "INPUT_E2E_STEP_SLEEP": "0.05",
        "INPUT_E2E_DECODE_SLEEP": "0.004",
        "TPUCFN_INPUT_RCVBUF": str(64 * 1024),
    }


def _serve_argv(shards: Path) -> list[str]:
    # tight socket buffers: in-flight batches must not hide the chaos
    # kill (auto-tuned loopback windows would buffer the whole epoch)
    return [sys.executable, "-m", "tpucfn.cli", "data", "serve",
            "--shards", str(shards), "--batch-size", str(BATCH),
            "--seed", str(SEED), "--num-epochs", str(EPOCHS),
            "--host", "127.0.0.1", "--idle-exit", "2.0",
            "--queue-batches", "2", "--sndbuf-kb", "64"]


def _run(tmp_path, shards, run_dir, *, input_plane: bool,
         chaos: ChaosSpec | None = None, input_port: int) -> GangCoordinator:
    run_dir.mkdir(parents=True, exist_ok=True)
    n = TRAINERS + (1 if input_plane else 0)
    ft_dir = run_dir / "ft"
    launcher = Launcher(
        _contract(tmp_path, n), LocalTransport(),
        ft_dir=str(ft_dir), ft_heartbeat_s=0.2,
        input_hosts=1 if input_plane else 0,
        input_port=input_port,
        input_argv=_serve_argv(shards) if input_plane else None,
        extra_env=_worker_env(run_dir, shards))
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=n,
        config=MonitorConfig(interval_s=0.2, startup_grace_s=60.0))
    coord = GangCoordinator(
        launcher, [sys.executable, str(WORKER)],
        policy=GangRestart(RestartBudget(0)), monitor=monitor,
        ft_dir=ft_dir, poll_interval=0.02, term_grace_s=2.0,
        chaos=chaos)
    assert coord.run() == 0
    return coord


def _trajectories(run_dir: Path) -> dict[int, list[str]]:
    out = {}
    for h in range(TRAINERS):
        p = run_dir / f"losses-host{h:03d}.jsonl"
        out[h] = [ln for ln in p.read_text().splitlines() if ln.strip()]
        assert len(out[h]) == STEPS_PER_TRAINER * EPOCHS, (h, len(out[h]))
    return out


def _mode(run_dir: Path, h: int) -> dict:
    return json.loads((run_dir / f"mode-host{h:03d}.json").read_text())


def _events(run_dir: Path) -> list[dict]:
    p = run_dir / "ft" / "events.jsonl"
    return [json.loads(s) for s in p.read_text().splitlines() if s.strip()]


def _goodput(run_dir: Path) -> dict:
    from tpucfn.obs.goodput import goodput_report

    rep = goodput_report(run_dir / "goodput",
                         run_dir / "ft" / "events.jsonl")
    assert rep["num_hosts"] == TRAINERS
    # the acceptance invariant: buckets (derived fillers included) sum
    # to wall time — residual is float noise
    assert abs(rep["unaccounted_s"]) <= 0.05 * max(rep["wall_s"], 1e-9)
    return rep


def test_input_plane_e2e_degradation_and_goodput(tmp_path):
    shards = _write_shards(tmp_path)

    # -- reference: local loading, also the goodput baseline -------------
    ref_dir = tmp_path / "ref"
    _run(tmp_path, shards, ref_dir, input_plane=False, input_port=9310)
    ref = _trajectories(ref_dir)
    assert not _mode(ref_dir, 0)["used_service"]
    ref_rep = _goodput(ref_dir)
    ref_share = ref_rep["buckets"]["data_wait"] / ref_rep["wall_s"]
    assert ref_share > 0.2, ref_share  # the workload IS input-bound

    # -- served: full fan-out, no chaos ----------------------------------
    served_dir = tmp_path / "served"
    _run(tmp_path, shards, served_dir, input_plane=True, input_port=9320)
    served = _trajectories(served_dir)
    assert served == ref  # bit-identical trajectory, service-fed
    for h in range(TRAINERS):
        m = _mode(served_dir, h)
        assert m["used_service"] and not m["degraded"], m
    served_rep = _goodput(served_dir)
    served_share = (served_rep["buckets"]["data_wait"]
                    / served_rep["wall_s"])
    # the goodput acceptance: data_wait share STRICTLY lower with the
    # service enabled (decode left the trainers' critical path)
    assert served_share < ref_share, (served_share, ref_share)
    kinds = [e["kind"] for e in _events(served_dir)]
    assert "input_degraded" not in kinds
    assert "detect" not in kinds

    # -- chaos: kill the input host mid-run ------------------------------
    chaos_dir = tmp_path / "chaos"
    chaos = ChaosSpec(seed=0, events=(
        ChaosEvent(action="kill", at_step=10, host=TRAINERS),))
    coord = _run(tmp_path, shards, chaos_dir, input_plane=True,
                 chaos=chaos, input_port=9330)
    got = _trajectories(chaos_dir)
    assert got == ref  # the whole point: degradation changed NOTHING
    degraded = [h for h in range(TRAINERS)
                if _mode(chaos_dir, h)["degraded"]]
    assert degraded, "the kill landed mid-run; someone must have degraded"
    kinds = [e["kind"] for e in _events(chaos_dir)]
    assert "input_degraded" in kinds
    # no gang incident, no restart, budget untouched
    assert "detect" not in kinds and "recovered" not in kinds
    assert coord.policy.budget.used == 0
    v = coord.registry.varz()["metrics"]
    assert v["ft_input_degradations_total"] == 1
    assert v["supervisor_restarts_total"] == 0
    _goodput(chaos_dir)  # invariant still holds through the degradation
