import numpy as np
import pytest

from tpucfn.data import (
    RecordShardWriter,
    ShardedDataset,
    prefetch_to_mesh,
    read_record_shard,
    synthetic_cifar10,
    write_dataset_shards,
)
from tpucfn.data.records import decode_example


def test_record_roundtrip(tmp_path):
    p = tmp_path / "a.tpurec"
    with RecordShardWriter(p) as w:
        w.write(b"hello")
        w.write(b"world" * 100)
    assert list(read_record_shard(p)) == [b"hello", b"world" * 100]


def test_record_crc_detects_corruption(tmp_path):
    p = tmp_path / "a.tpurec"
    with RecordShardWriter(p) as w:
        w.write(b"payload-payload")
    raw = bytearray(p.read_bytes())
    raw[-3] ^= 0xFF  # flip a payload byte
    p.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="CRC"):
        list(read_record_shard(p))


def test_record_truncation_detected(tmp_path):
    p = tmp_path / "a.tpurec"
    with RecordShardWriter(p) as w:
        for i in range(10):
            w.write(b"x" * 100)
    p.write_bytes(p.read_bytes()[:-50])
    with pytest.raises(ValueError):
        list(read_record_shard(p))


def test_bad_magic(tmp_path):
    p = tmp_path / "junk.tpurec"
    p.write_bytes(b"\x00" * 64)
    with pytest.raises(ValueError, match="magic"):
        list(read_record_shard(p))


def test_write_dataset_shards_roundtrip(tmp_path):
    paths = write_dataset_shards(synthetic_cifar10(32), tmp_path, num_shards=4)
    assert len(paths) == 4
    examples = [decode_example(b) for p in paths for b in read_record_shard(p)]
    assert len(examples) == 32
    assert examples[0]["image"].shape == (32, 32, 3)
    assert examples[0]["label"].shape == ()


def test_sharded_dataset_process_ownership(tmp_path):
    paths = write_dataset_shards(synthetic_cifar10(64), tmp_path, num_shards=4)
    d0 = ShardedDataset(paths, batch_size_per_process=8, process_index=0, process_count=2)
    d1 = ShardedDataset(paths, batch_size_per_process=8, process_index=1, process_count=2)
    assert set(d0.local_shards) | set(d1.local_shards) == {str(p) for p in paths}
    assert not set(d0.local_shards) & set(d1.local_shards)


def test_more_processes_than_shards_raises(tmp_path):
    paths = write_dataset_shards(synthetic_cifar10(8), tmp_path, num_shards=2)
    with pytest.raises(ValueError, match="owns no shards"):
        ShardedDataset(paths, batch_size_per_process=2, process_index=2, process_count=4)


def test_epoch_determinism_and_reshuffle(tmp_path):
    paths = write_dataset_shards(synthetic_cifar10(64), tmp_path, num_shards=2)
    ds = ShardedDataset(paths, batch_size_per_process=16, seed=7)
    e0a = [b["label"] for b in ds.epoch(0)]
    e0b = [b["label"] for b in ds.epoch(0)]
    e1 = [b["label"] for b in ds.epoch(1)]
    np.testing.assert_array_equal(np.concatenate(e0a), np.concatenate(e0b))
    assert not np.array_equal(np.concatenate(e0a), np.concatenate(e1))


def test_batch_shapes_and_len(tmp_path):
    paths = write_dataset_shards(synthetic_cifar10(70), tmp_path, num_shards=2)
    ds = ShardedDataset(paths, batch_size_per_process=16)
    assert len(ds) == 4  # 70 // 16, drop remainder
    batches = list(ds.epoch(0))
    assert len(batches) == 4
    assert batches[0]["image"].shape == (16, 32, 32, 3)


def test_prefetch_to_mesh_yields_sharded(tmp_path, mesh_dp8):
    from jax.sharding import PartitionSpec as P

    paths = write_dataset_shards(synthetic_cifar10(64), tmp_path, num_shards=2)
    ds = ShardedDataset(paths, batch_size_per_process=16)
    out = list(prefetch_to_mesh(ds.epoch(0), mesh_dp8))
    assert len(out) == 4
    assert out[0]["image"].sharding.spec == P(("data", "fsdp", "expert"))
    assert out[0]["image"].addressable_shards[0].data.shape[0] == 2


def test_prefetch_propagates_errors(mesh_dp8):
    def bad_iter():
        yield {"x": np.ones((8, 2), np.float32)}
        raise RuntimeError("decode exploded")

    it = prefetch_to_mesh(bad_iter(), mesh_dp8)
    next(it)
    with pytest.raises(RuntimeError, match="decode exploded"):
        list(it)


def test_sharded_dataset_num_workers_parallel_decode(tmp_path):
    """num_workers>0 runs the transform in a thread pool: batches are
    identical across worker counts (per-example seeds are drawn
    sequentially; map preserves order) and reproducible run-to-run."""
    import numpy as np

    from tpucfn.data import write_dataset_shards
    from tpucfn.data.pipeline import ShardedDataset

    rs = np.random.RandomState(0)
    examples = [{"x": rs.randn(4).astype(np.float32),
                 "label": np.int32(i % 3)} for i in range(64)]
    shards = write_dataset_shards(iter(examples), tmp_path, num_shards=4)

    def noisy(ex, aug_rs):
        return {"x": ex["x"] + aug_rs.randn(4).astype(np.float32),
                "label": ex["label"]}

    def batches(workers):
        ds = ShardedDataset(shards, batch_size_per_process=16, seed=7,
                            process_index=0, process_count=1,
                            transform=noisy, num_workers=workers)
        return list(ds.epoch(0))

    b4 = batches(4)
    b1 = batches(1)
    b4_again = batches(4)
    assert len(b4) == 4
    for a, b, c in zip(b4, b1, b4_again):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["x"], c["x"])
        np.testing.assert_array_equal(a["label"], b["label"])


def _mp_shards(tmp_path, n=48, num_shards=6):
    import numpy as np

    from tpucfn.data import write_dataset_shards

    rs = np.random.RandomState(0)
    examples = [{"x": rs.randn(3).astype(np.float32),
                 "uid": np.int32(i)} for i in range(n)]
    return write_dataset_shards(iter(examples), tmp_path, num_shards=num_shards)


def test_multiprocess_loader_one_worker_matches_sharded_dataset(tmp_path):
    import numpy as np

    from tpucfn.data.pipeline import MultiProcessLoader, ShardedDataset
    from tpucfn.data.transforms import normalize

    shards = _mp_shards(tmp_path)
    kw = dict(batch_size_per_process=8, seed=3,
              transform=normalize((0.5,), (2.0,), key="x"))
    ds = ShardedDataset(shards, process_index=0, process_count=1, **kw)
    ref = list(ds.batches(2))
    with MultiProcessLoader(shards, num_workers=1, process_index=0,
                            process_count=1, **kw) as loader:
        got = list(loader.batches(2))
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a["x"], b["x"])
        np.testing.assert_array_equal(a["uid"], b["uid"])


def test_multiprocess_loader_deterministic_and_covers_epoch(tmp_path):
    import numpy as np

    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path)

    def run():
        with MultiProcessLoader(shards, num_workers=3, process_index=0,
                                process_count=1, batch_size_per_process=4,
                                seed=1) as loader:
            return list(loader.batches(1))

    a, b = run(), run()
    assert len(a) == 12  # 48 examples / batch 4, all workers drained
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x["uid"], y["uid"])
    seen = sorted(int(u) for batch in a for u in batch["uid"])
    assert seen == list(range(48))  # every example exactly once per epoch


def test_multiprocess_loader_propagates_worker_errors(tmp_path):
    import pytest

    from tpucfn.data.pipeline import MultiProcessLoader
    from tpucfn.data.transforms import RandomCrop

    shards = _mp_shards(tmp_path)
    # RandomCrop on a rank-1 "x" raises inside the worker
    loader = MultiProcessLoader(shards, num_workers=2, process_index=0,
                                process_count=1, batch_size_per_process=4,
                                transform=RandomCrop(2, key="x"))
    with pytest.raises(RuntimeError, match="loader worker"):
        list(loader.batches(1))


def test_multiprocess_loader_requires_enough_shards(tmp_path):
    import pytest

    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path, num_shards=2)
    with pytest.raises(ValueError, match="num_workers"):
        MultiProcessLoader(shards, num_workers=4, process_index=0,
                           process_count=1, batch_size_per_process=4)


def test_multiprocess_loader_len_matches_stream(tmp_path):
    # ADVICE r3 (medium): epoch-driven loops compute
    # len(ds) * num_epochs; MultiProcessLoader must agree with what its
    # stream actually yields.
    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path)  # 48 examples over 6 shards
    with MultiProcessLoader(shards, num_workers=3, process_index=0,
                            process_count=1, batch_size_per_process=4,
                            seed=1) as loader:
        n = len(loader)
        got = list(loader.batches(1))
    assert n == len(got) == 12
    # Remainder rounding is per-worker: 5 shards / 2 workers with an
    # odd split still matches the stream.
    shards5 = _mp_shards(tmp_path / "odd", n=44, num_shards=5)
    with MultiProcessLoader(shards5, num_workers=2, process_index=0,
                            process_count=1, batch_size_per_process=8,
                            seed=1) as loader:
        assert len(loader) == len(list(loader.batches(1)))


def test_multiprocess_loader_detects_killed_worker(tmp_path):
    # ADVICE r3: a worker killed without posting (OOM SIGKILL) must
    # surface as an error, not hang the parent on Queue.get forever.
    import pytest

    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path)
    loader = MultiProcessLoader(shards, num_workers=2, process_index=0,
                                process_count=1, batch_size_per_process=4,
                                prefetch=1)
    it = loader.batches(None)
    next(it)  # workers are up and producing
    for p in loader._procs:
        p.kill()  # simulate the OOM killer: no "error" message posted
    with pytest.raises(RuntimeError, match="died"):
        # Drain: queues may hold a few already-produced batches; the
        # dead-worker check fires once they empty. _get polls fast.
        while True:
            loader._get(0, timeout_s=0.2)
            loader._get(1, timeout_s=0.2)


# -- MultiProcessLoader shutdown / torn-queue edges (ISSUE 11 satellite) ----
# The disaggregated input service reuses these exact paths per trainer
# stream, so they are pinned here rather than rediscovered over a socket.


class _SlowTransform:
    """Module-level so spawn can pickle it by reference."""

    def __init__(self, seconds):
        self.seconds = seconds

    def __call__(self, ex, rs):
        import time

        time.sleep(self.seconds)
        return ex


@pytest.mark.slow
def test_multiprocess_loader_worker_death_surfaces_via_batches(tmp_path):
    """The public batches() path (not just _get) must raise the clean
    dead-worker error when a worker is killed mid-batch without posting
    — the stream must never hang the consumer."""
    import pytest

    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path, n=96, num_shards=6)
    loader = MultiProcessLoader(shards, num_workers=2, process_index=0,
                                process_count=1, batch_size_per_process=4,
                                prefetch=1,
                                transform=_SlowTransform(0.02))
    it = loader.batches(None)
    next(it)  # workers up and producing
    for p in loader._procs:
        p.kill()  # OOM-killer shape: no "error" message posted
    with pytest.raises(RuntimeError, match="died"):
        for _ in range(10_000):
            next(it)


@pytest.mark.slow
def test_multiprocess_loader_close_during_iteration(tmp_path):
    """close() from another thread mid-iteration (the input service's
    stream teardown) ends the iteration with a clean RuntimeError, not
    an IndexError on the torn queue list — and close is idempotent."""
    import threading

    import pytest

    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path, n=96, num_shards=6)
    loader = MultiProcessLoader(shards, num_workers=2, process_index=0,
                                process_count=1, batch_size_per_process=4,
                                prefetch=1,
                                transform=_SlowTransform(0.01))
    it = loader.batches(None)
    next(it)
    t = threading.Thread(target=loader.close)
    t.start()
    with pytest.raises(RuntimeError, match="closed|died"):
        for _ in range(10_000):
            next(it)
    t.join(timeout=10)
    assert not t.is_alive()
    loader.close()  # double close is a no-op
    assert loader._procs == [] and loader._queues == []


@pytest.mark.slow
def test_multiprocess_loader_get_timeout_polls_until_batch(tmp_path):
    """_get with a timeout shorter than the batch build time polls
    through queue.Empty cycles while the worker is ALIVE and returns
    the batch — a slow worker is slow, not dead."""
    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path, n=16, num_shards=2)
    loader = MultiProcessLoader(shards, num_workers=2, process_index=0,
                                process_count=1, batch_size_per_process=8,
                                prefetch=1,
                                transform=_SlowTransform(0.05))
    try:
        loader._start(1)
        tag, payload = loader._get(0, timeout_s=0.05)
        assert tag == "batch"
        assert payload["uid"].shape == (8,)
    finally:
        loader.close()


@pytest.mark.slow
def test_multiprocess_loader_get_after_close_raises_cleanly(tmp_path):
    import pytest

    from tpucfn.data.pipeline import MultiProcessLoader

    shards = _mp_shards(tmp_path)
    loader = MultiProcessLoader(shards, num_workers=2, process_index=0,
                                process_count=1, batch_size_per_process=4)
    loader._start(1)
    loader.close()
    with pytest.raises(RuntimeError, match="closed"):
        loader._get(0, timeout_s=0.05)
