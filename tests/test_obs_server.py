"""Per-host obs endpoint smoke test (ISSUE 2 satellite): start the
HTTP server on an ephemeral port, scrape it, and validate the
Prometheus exposition line-by-line — plus /healthz semantics (200/503)
and the /varz JSON snapshot.  Tier-1-safe: loopback only, port 0."""

import json
import re
import urllib.error
import urllib.request

import pytest

from tpucfn.obs import MetricRegistry, ObsServer, obs_port_from_env, start_obs_server

LINE_RE = re.compile(
    r"^(?:# (?:HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(?:\{[^{}]*\})? (?:[+-]?(?:\d+\.?\d*(?:e[+-]?\d+)?|Inf)|NaN))$"
)


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


@pytest.fixture()
def obs():
    reg = MetricRegistry(labels={"host": "0", "role": "test"})
    reg.counter("scrapes_total", "how many").add(1)
    reg.gauge("depth").set(3)
    s = reg.summary("lat_seconds")
    for v in (0.01, 0.02):
        s.observe(v)
    reg.histogram("step_seconds", buckets=(0.1, 1.0)).observe(0.05)
    srv = ObsServer(reg, port=0, host="127.0.0.1", role="test", host_id=0)
    yield srv
    srv.close()


def test_metrics_scrape_is_valid_prometheus_exposition(obs):
    status, ctype, body = _get(obs.url("/metrics"))
    assert status == 200
    assert ctype.startswith("text/plain")
    assert body.endswith("\n")
    lines = body.rstrip("\n").splitlines()
    assert lines, "empty exposition"
    for line in lines:  # the line-by-line validation the satellite asks for
        assert LINE_RE.match(line), f"invalid exposition line: {line!r}"
    assert 'scrapes_total{host="0",role="test"} 1.0' in lines
    assert '# TYPE step_seconds histogram' in lines
    assert 'step_seconds_bucket{host="0",role="test",le="+Inf"} 1.0' in lines
    # every histogram series carries cumulative counts ending at _count
    count = [ln for ln in lines if ln.startswith("step_seconds_count")]
    assert count and count[0].endswith(" 1.0")


def test_healthz_ok_and_unhealthy_503():
    reg = MetricRegistry()
    state = {"ok": True}
    srv = ObsServer(reg, port=0, host="127.0.0.1", role="trainer", host_id=2,
                    health_fn=lambda: (state["ok"], {"step": 17}))
    try:
        status, _, body = _get(srv.url("/healthz"))
        payload = json.loads(body)
        assert status == 200
        assert payload["status"] == "ok"
        assert payload["role"] == "trainer" and payload["host_id"] == 2
        assert payload["step"] == 17 and payload["uptime_s"] >= 0
        state["ok"] = False
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url("/healthz"))
        assert ei.value.code == 503
        assert json.loads(ei.value.read().decode())["status"] == "unhealthy"
    finally:
        srv.close()


def test_crashing_health_probe_is_unhealthy():
    def boom():
        raise RuntimeError("probe died")

    srv = ObsServer(MetricRegistry(), port=0, host="127.0.0.1",
                    health_fn=boom)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.url("/healthz"))
        assert ei.value.code == 503
        assert "probe_error" in json.loads(ei.value.read().decode())
    finally:
        srv.close()


def test_varz_json_snapshot(obs):
    status, ctype, body = _get(obs.url("/varz"))
    assert status == 200 and ctype.startswith("application/json")
    v = json.loads(body)
    assert v["labels"]["role"] == "test"
    assert v["metrics"]["scrapes_total"] == 1.0
    assert v["metrics"]["lat_seconds"]["count"] == 2


def test_unknown_path_404_and_index(obs):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(obs.url("/nope"))
    assert ei.value.code == 404
    status, _, body = _get(obs.url("/"))
    assert status == 200 and "/metrics" in body


def test_start_obs_server_env_gating(monkeypatch):
    monkeypatch.delenv("TPUCFN_OBS_PORT", raising=False)
    assert obs_port_from_env() is None
    assert start_obs_server(MetricRegistry(), role="trainer") is None
    monkeypatch.setenv("TPUCFN_OBS_PORT", "not-a-port")
    assert obs_port_from_env() is None
    monkeypatch.setenv("TPUCFN_OBS_PORT", "0")
    srv = start_obs_server(MetricRegistry(), role="trainer",
                           host="127.0.0.1")
    try:
        assert srv is not None and srv.port > 0
        status, _, _ = _get(srv.url("/metrics"))
        assert status == 200
    finally:
        srv.close()


def test_healthz_carries_hbm_watermark_from_flight_ring():
    """ISSUE 12 satellite: a role with a flight ring gets an OOM
    prediction in /healthz detail — sustained used/limit over the
    threshold alerts; detail only, the HTTP status never flips."""
    from tpucfn.obs import FlightRecorder

    flight = FlightRecorder(capacity=64, host_id=0, role="test",
                            clock=lambda: 0.0)

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = Clock()
    flight.clock = clock
    for i in range(40):
        clock.t = float(i)
        flight.record("hbm", used=95, peak=96, limit=100)
    srv = ObsServer(MetricRegistry(), port=0, host="127.0.0.1",
                    role="test", flight=flight)
    try:
        status, _, body = _get(srv.url("/healthz"))
        assert status == 200  # an alert is a prediction, not a 503
        wm = json.loads(body)["hbm_watermark"]
        assert wm["level"] == "alert"
        assert wm["ratio"] == 0.95
        assert wm["sustained_s"] >= 30.0
    finally:
        srv.close()


def test_healthz_watermark_absent_without_hbm_samples():
    from tpucfn.obs import FlightRecorder

    flight = FlightRecorder(capacity=8, host_id=0)
    flight.record("step", step=1, dur_s=0.1)  # no hbm samples on CPU
    srv = ObsServer(MetricRegistry(), port=0, host="127.0.0.1",
                    flight=flight)
    try:
        _, _, body = _get(srv.url("/healthz"))
        assert "hbm_watermark" not in json.loads(body)
    finally:
        srv.close()
