#!/usr/bin/env python
"""Stable Diffusion 1.5 UNet finetune (BASELINE config 5: "S3→HBM image
streaming path").

Latent-diffusion ε-prediction finetuning of the SD-1.5-class UNet. The
point of this config is the input path: latents/context records stream
from sharded storage through the CRC-checked tpurecord reader (C++ when
built) and the background device-prefetch queue straight onto the mesh —
the tpucfn version of the reference's S3 staging hooks (SURVEY.md §2.1).

``--tiny`` runs the CI-sized config; the full sd15 config is the real
~0.9B-param UNet shape.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import (  # noqa: E402
    add_cluster_args,
    build_example_mesh,
    per_process_batch,
    run_train_loop,
    stage_synthetic,
)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_cluster_args(p)
    p.add_argument("--tiny", action="store_true", help="tiny config (CI)")
    p.add_argument("--latent-size", type=int, default=0,
                   help="latent H=W (default 64 full / 16 tiny)")
    p.add_argument("--num-examples", type=int, default=256)
    p.add_argument("--ema-decay", type=float, default=0.9999,
                   help="EMA of the UNet params (the diffusion-finetune "
                        "standard; tracked in model_state, checkpointed); "
                        "0 disables")
    args = p.parse_args()

    from tpucfn.launch import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp
    import optax

    from tpucfn.data import ShardedDataset
    from tpucfn.models.unet import UNet, UNetConfig, ddpm_loss
    from tpucfn.parallel import transformer_rules
    from tpucfn.train import Trainer

    cfg = UNetConfig.tiny() if args.tiny else UNetConfig.sd15()
    hw = args.latent_size or (16 if args.tiny else 64)
    ctx_len = 8 if args.tiny else 77

    run_dir = Path(args.run_dir)
    shards = stage_synthetic(
        "latents", run_dir / "data", n=args.num_examples,
        num_shards=max(8, jax.process_count()), seed=args.seed,
        hw=hw, ctx_len=ctx_len, ctx_dim=cfg.context_dim,
    )

    mesh = build_example_mesh(args)
    model = UNet(cfg)

    def init_fn(rng):
        return model.init(
            rng, jnp.zeros((1, hw, hw, cfg.in_channels)),
            jnp.zeros((1,), jnp.int32), jnp.zeros((1, ctx_len, cfg.context_dim)),
        )["params"], {}

    def loss_fn(params, mstate, batch, rng):
        loss = ddpm_loss(model, params, batch, rng)
        return loss, ({}, mstate)

    tx = optax.adamw(args.lr if args.lr != 0.1 else 1e-5)  # finetune-scale default
    from tpucfn.train import TrainerConfig

    trainer = Trainer(
        mesh, transformer_rules(tensor=args.tensor > 1), loss_fn, tx, init_fn,
        config=TrainerConfig(ema_decay=args.ema_decay),
    )
    ds = ShardedDataset(shards, batch_size_per_process=per_process_batch(args),
                        seed=args.seed)
    run_train_loop(trainer, ds, mesh, args, items_per_step=args.batch_size)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
