"""Self-supervision: the tiny re-exec loop above the coordinator.

``tpucfn launch --supervise`` (ISSUE 12) wraps the gang coordinator in
one more — deliberately boring — layer: a jax-free, lock-free loop that

* spawns the coordinator as a child process,
* makes itself a **child subreaper** (``prctl(PR_SET_CHILD_SUBREAPER)``)
  so that when the coordinator dies, its orphaned ranks reparent to
  *this* process instead of init,
* reaps every child with ``waitpid(-1)``: the coordinator's status
  drives the restart decision, and every *grandchild* status is written
  to ``<ft_dir>/rc/rc-<pid>.json`` — the only way an adopting
  coordinator (not the parent of the fleet it adopts) can ever tell a
  rank's clean exit from a crash,
* relaunches a crashed coordinator up to ``max_restarts`` times; the
  relaunched incarnation finds the unfinished write-ahead journal and
  adopts the running fleet (see :mod:`tpucfn.ft.journal`).

The loop never restarts a coordinator whose journal says the run ended
(``done`` record): a give-up rc must propagate, not crash-loop.  A
SIGTERM to the supervisor is forwarded to the coordinator (which runs
its normal drain/stop path) and disables further restarts — the
handler is two plain stores and an ``os.kill``, nothing a signal can
deadlock (the PR 8 ``drain(wait=False)`` lesson).

Why re-exec rather than fork-and-retry in process: the coordinator may
die *because of* its own process state (a poisoned import, a leaked
fd, a wedged thread); a fresh interpreter is the only restart that
resets everything, and the journal makes the fresh interpreter cheap.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Sequence

from tpucfn.ft.events import append_event
from tpucfn.ft.journal import (
    journal_path,
    replay_journal,
    rotate_journal,
    write_rc,
)

PR_SET_CHILD_SUBREAPER = 36


def set_child_subreaper() -> bool:
    """Linux-only best effort: orphaned grandchildren reparent to us so
    our ``waitpid(-1)`` sees their real exit statuses.  Elsewhere (or
    under a restricted sandbox) adoption still works — unknown deaths
    just degrade to CRASH-with-unknown-rc."""
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        return libc.prctl(PR_SET_CHILD_SUBREAPER, 1, 0, 0, 0) == 0
    except Exception:  # noqa: BLE001 — non-Linux / no libc access
        return False


def _status_rc(status: int) -> int:
    if os.WIFSIGNALED(status):
        return -os.WTERMSIG(status)
    return os.WEXITSTATUS(status)


def run_supervised(child_argv: Sequence[str], *, ft_dir: str | Path,
                   max_restarts: int = 3, backoff_s: float = 0.5,
                   env: dict | None = None,
                   sleep=time.sleep) -> int:
    """Run ``child_argv`` (a coordinator invocation) under supervision;
    returns the run's final exit code.

    Restart rule: relaunch only while the journal says the run has NOT
    ended — a coordinator that returned its run's rc (clean finish or
    give_up) propagates it; one that *died* (signal, crash) is
    relaunched with the same argv, and its adoption of the journal is
    what makes the relaunch safe.  ``max_restarts`` bounds the loop so
    a coordinator that crashes on arrival cannot flap forever.
    """
    ft_dir = Path(ft_dir)
    ft_dir.mkdir(parents=True, exist_ok=True)
    try:
        st0, _, _ = replay_journal(journal_path(ft_dir))
        if st0.started and st0.done_rc is not None:
            # A FINISHED previous run's journal must not masquerade as
            # this run's.  The coordinator rotates it on a fresh start —
            # but a child that crashes on arrival never gets there, and
            # the post-exit replay below would then read the OLD run's
            # done rc as this run's result and report a coordinator
            # that trained nothing as a completed run.
            rotate_journal(journal_path(ft_dir))
    except Exception:  # noqa: BLE001 — corrupt journal: let the child's
        pass           # adoption refuse it loudly
    subreaper = set_child_subreaper()
    restarts = 0
    state = {"child_pid": None, "stop_sig": None}

    import signal as _signal

    def _forward(signum, frame):
        # Signal-handler discipline: plain stores + os.kill only.
        state["stop_sig"] = signum
        pid = state["child_pid"]
        if pid is not None:
            try:
                os.kill(pid, signum)
            except ProcessLookupError:
                pass

    prev_term = _signal.getsignal(_signal.SIGTERM)
    try:
        _signal.signal(_signal.SIGTERM, _forward)
    except ValueError:
        prev_term = None  # not the main thread (tests): no forwarding
    try:
        while True:
            proc = subprocess.Popen(
                list(child_argv),
                env=env if env is not None else None)
            state["child_pid"] = proc.pid
            rc: int | None = None
            while rc is None:
                try:
                    pid, status = os.waitpid(-1, 0)
                except ChildProcessError:
                    # no children left at all: the coordinator is gone
                    # and something else reaped it (shouldn't happen —
                    # degrade to its poll)
                    rc = proc.poll()
                    rc = 1 if rc is None else rc
                    break
                if pid == proc.pid:
                    rc = _status_rc(status)
                    # keep the Popen object's bookkeeping honest: we
                    # reaped its child behind its back
                    proc.returncode = rc
                else:
                    # an orphaned grandchild (a rank whose coordinator
                    # died): land its real rc where an adopting
                    # coordinator can find it
                    write_rc(ft_dir, pid, _status_rc(status))
            state["child_pid"] = None
            done_rc = None
            try:
                st, _, _ = replay_journal(journal_path(ft_dir))
                done_rc = st.done_rc if st.started else None
            except Exception:  # noqa: BLE001 — corrupt journal
                # adoption would refuse it loudly too: restarting is
                # futile, propagate the crash
                return rc
            if done_rc is not None:
                return done_rc if rc != 0 else rc
            if rc == 0 or state["stop_sig"] is not None:
                return rc
            if restarts >= max_restarts:
                append_event(ft_dir, "coordinator_give_up",
                             restarts=restarts, rc=rc)
                return rc
            restarts += 1
            append_event(ft_dir, "coordinator_restarted",
                         restarts=restarts, rc=rc,
                         subreaper=subreaper)
            sleep(backoff_s)
    finally:
        if prev_term is not None:
            try:
                _signal.signal(_signal.SIGTERM, prev_term)
            except ValueError:
                pass


def supervised_cli_argv(argv: Sequence[str]) -> list[str]:
    """The child command for ``tpucfn launch --supervise``: the same
    CLI invocation minus the supervise flags (the child must run the
    coordinator, not another supervisor).  Adoption needs no flag —
    the relaunched coordinator finds the unfinished journal."""
    out: list[str] = [sys.executable, "-m", "tpucfn.cli"]
    skip_next = False
    passthrough = False  # past the first bare "--": the USER JOB's argv
    for a in argv:
        if passthrough:
            out.append(a)
            continue
        if skip_next:
            skip_next = False
            continue
        if a == "--":
            passthrough = True
            out.append(a)
            continue
        if a == "--supervise":
            continue
        if a == "--supervise-restarts":
            skip_next = True
            continue
        if a.startswith("--supervise-restarts="):
            continue
        out.append(a)
    return out
