"""tpucfn.net.deadline (ISSUE 15): the end-to-end Deadline composed
over per-chunk socket timeouts, the shared RetryPolicy, and the
deadline-aware framing in tpucfn.data.service — including the headline
gray-failure pin: a TRICKLING peer (one byte per chunk timeout, which
resets a naive per-chunk clock forever) now times out inside the
end-to-end bound."""

import socket
import threading
import time

import pytest

from tpucfn.data.service import (
    FRAME_BATCH,
    ServiceError,
    _recv_exact,
    recv_frame,
    send_frame,
)
from tpucfn.net.deadline import (
    Deadline,
    DeadlineExceeded,
    NetMetrics,
    RetryPolicy,
    sendall_deadline,
)
from tpucfn.obs.registry import MetricRegistry


# -- Deadline ---------------------------------------------------------------


def test_deadline_remaining_and_expiry_on_fake_clock():
    t = [100.0]
    d = Deadline(5.0, clock=lambda: t[0])
    assert d.remaining() == pytest.approx(5.0)
    assert not d.expired()
    t[0] = 104.9
    assert d.timeout() == pytest.approx(0.1)
    t[0] = 105.1
    assert d.expired()
    with pytest.raises(DeadlineExceeded):
        d.timeout(what="recv")
    with pytest.raises(DeadlineExceeded):
        d.check()


def test_deadline_timeout_cap_and_floor():
    t = [0.0]
    d = Deadline(100.0, clock=lambda: t[0])
    assert d.timeout(cap=5.0) == pytest.approx(5.0)
    t[0] = 100.0 - 1e-5  # nearly spent, but not expired
    assert d.timeout(floor=0.01) == pytest.approx(0.01)


def test_deadline_at_anchors_an_absolute_endpoint():
    t = [50.0]
    d = Deadline.at(60.0, clock=lambda: t[0])
    assert d.remaining() == pytest.approx(10.0)


def test_deadline_exceeded_is_oserror():
    # the planes' existing `except OSError` transport handling must
    # catch an expired deadline — degradation, not a new crash class
    assert issubclass(DeadlineExceeded, OSError)


# -- RetryPolicy ------------------------------------------------------------


def test_retry_backoff_is_capped_exponential_with_seeded_jitter():
    rp = RetryPolicy(base_s=0.1, multiplier=2.0, max_s=0.5, jitter=0.25,
                     seed=7)
    seq = [rp.backoff_s(i) for i in range(6)]
    for i, d in enumerate(seq):
        nominal = min(0.5, 0.1 * 2.0 ** i)
        assert nominal * 0.75 <= d <= nominal * 1.25
    # seeded: same seed, same delays
    rp2 = RetryPolicy(base_s=0.1, multiplier=2.0, max_s=0.5, jitter=0.25,
                      seed=7)
    assert [rp2.backoff_s(i) for i in range(6)] == seq


def test_retry_attempts_respect_max_and_sleep_between():
    slept = []
    rp = RetryPolicy(max_attempts=4, base_s=0.1, multiplier=2.0,
                     max_s=10.0, jitter=0.0, sleep=slept.append)
    assert list(rp.attempts()) == [0, 1, 2, 3]
    assert slept == pytest.approx([0.1, 0.2, 0.4])


def test_retry_attempts_stop_at_deadline_and_bound_the_last_sleep():
    t = [0.0]

    def sleep(s):
        t[0] += s

    rp = RetryPolicy(base_s=1.0, multiplier=1.0, max_s=1.0, jitter=0.0,
                     clock=lambda: t[0], sleep=sleep)
    d = Deadline(2.5, clock=lambda: t[0])
    out = list(rp.attempts(deadline=d))
    # attempt 0 free, then 1.0s sleeps; the deadline at 2.5 admits two
    # more rounds (the final partial sleep is clamped and then expires)
    assert out[0] == 0 and len(out) <= 3
    assert t[0] <= 2.5 + 1e-9


def test_retry_attempts_metrics_count_retries_and_backoff():
    reg = MetricRegistry()
    m = NetMetrics(reg, "input")
    rp = RetryPolicy(max_attempts=3, base_s=0.01, multiplier=1.0,
                     max_s=0.01, jitter=0.0, sleep=lambda s: None)
    list(rp.attempts(metrics=m))
    v = reg.varz()["metrics"]
    assert v["net_input_retries_total"] == 2
    assert v["net_input_backoff_seconds_total"] == pytest.approx(0.02)


# -- deadline-aware framing -------------------------------------------------


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_recv_exact_trickle_times_out_within_the_deadline():
    """THE gray-failure pin: a peer delivering one byte per 50 ms
    forever used to reset a per-chunk timeout on every byte; with the
    end-to-end deadline the read fails inside the bound."""
    a, b = _pair()
    stop = threading.Event()

    def trickle():
        while not stop.is_set():
            try:
                b.sendall(b"x")
            except OSError:
                return
            time.sleep(0.05)

    t = threading.Thread(target=trickle, daemon=True)
    t.start()
    t0 = time.monotonic()
    try:
        with pytest.raises(DeadlineExceeded):
            _recv_exact(a, 1 << 20, Deadline(0.5))
        dt = time.monotonic() - t0
        assert dt < 2.0, f"trickle read took {dt:.2f}s against a 0.5s deadline"
    finally:
        stop.set()
        a.close()
        b.close()
        t.join(timeout=2)


def test_recv_frame_stall_times_out_within_the_deadline():
    a, b = _pair()
    try:
        b.sendall(b"TPIB")  # a header's worth of nothing more: stall
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            recv_frame(a, deadline=Deadline(0.3))
        assert time.monotonic() - t0 < 1.5
    finally:
        a.close()
        b.close()


def test_recv_frame_without_deadline_keeps_socket_timeout_semantics():
    a, b = _pair()
    a.settimeout(0.2)
    try:
        with pytest.raises(OSError):  # socket.timeout is an OSError
            recv_frame(a)
    finally:
        a.close()
        b.close()


def test_frame_roundtrip_under_deadline_is_byte_identical():
    a, b = _pair()
    try:
        payload = bytes(range(256)) * 64
        send_frame(b, FRAME_BATCH, payload, deadline=Deadline(5.0))
        kind, got = recv_frame(a, deadline=Deadline(5.0))
        assert kind == FRAME_BATCH and bytes(got) == payload
    finally:
        a.close()
        b.close()


def test_sendall_deadline_expires_on_a_stalled_receiver():
    a, b = _pair()
    try:
        # tiny buffers so the kernel cannot swallow the whole payload
        b.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 16 * 1024)
        a.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16 * 1024)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            # nobody ever reads from `a`: the send must fail inside the
            # bound instead of blocking forever
            sendall_deadline(b, b"z" * (8 << 20), Deadline(0.4))
        assert time.monotonic() - t0 < 2.0
    finally:
        a.close()
        b.close()


def test_closed_peer_still_raises_service_error_shape():
    a, b = _pair()
    b.close()
    try:
        with pytest.raises(ServiceError):
            recv_frame(a, deadline=Deadline(1.0))
    finally:
        a.close()
