from tpucfn.launch.launcher import (  # noqa: F401
    Launcher,
    LocalTransport,
    SSHTransport,
    initialize_runtime,
    run_with_restarts,
)
