#!/usr/bin/env python
"""Fault-tolerance plane benchmark: scripted recoveries on the local
transport, ONE JSON line out in the standard BENCH row schema.

Two scenarios (both deterministic, seeded), reported as the
planned-vs-unplanned MTTR split (ISSUE 7):

* **unplanned** (the headline): a ChaosSpec SIGKILLs host 0 at
  ``--kill-after`` seconds; the GangCoordinator detects the crash,
  gang-restarts under a budget of 1, and the relaunched workers finish
  clean.  Reports ``ft_mttr_seconds`` (detect → relaunch-complete) and
  ``detection_latency_s`` (kill firing → detect event; bounded by the
  supervision ``--poll-interval``, not the heartbeat interval).
* **planned**: a ``preempt_notice`` chaos event at the same instant;
  the coordinator drains the gang cleanly and relaunches with a budget
  of ZERO — proving a drained preemption needs no restart budget — and
  reports ``ft_planned_mttr_seconds`` in ``detail.planned``.

Workers are pure stdlib (no jax import) so the run measures the
recovery plane, not interpreter+XLA startup.  ``vs_baseline`` is 0.0:
the reference harness's recovery story was "the training job dies and
is re-run by hand" — there is no automated-recovery number to compare
against.

Usage: python benches/ft_bench.py [--hosts 2 --kill-after 1.0 ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Stdlib-only worker: beat every BENCH_HB_S; first attempt runs until
# killed or drained (30s safety cap), post-restart attempts finish clean
# after a few beats.  Per-host attempt flags — no cross-host races.  The
# drain check mirrors the trainer protocol: stop clean once the drain
# file exists and this host reached its target step.
WORKER = """
import json, os, pathlib, sys, time
d = os.environ['TPUCFN_FT_DIR']; h = int(os.environ['TPUCFN_HOST_ID'])
hb_s = float(os.environ.get('TPUCFN_FT_HEARTBEAT_S', '0.05'))
os.makedirs(d, exist_ok=True)
flag = pathlib.Path(os.environ['FT_BENCH_FLAG_DIR']) / f'attempt2_{h}'
second = flag.exists()
flag.write_text('x')
drain = pathlib.Path(d) / 'drain.json'
seq = 0
t_end = time.time() + (3 * hb_s if second else 30.0)
while time.time() < t_end:
    seq += 1
    with open(f'{d}/hb-host{h:03d}.jsonl', 'a') as f:
        f.write(json.dumps({'host_id': h, 'pid': os.getpid(), 'step': seq,
                            't': time.time(), 'seq': seq}) + '\\n')
    if drain.exists():
        try:
            tgt = json.loads(drain.read_text()).get('step')
        except Exception:
            tgt = None
        if tgt is None or seq >= tgt:
            sys.exit(0)
    time.sleep(hb_s)
sys.exit(0 if second else 1)
"""


def _run_scenario(args, work: Path, *, planned: bool):
    """One coordinator run; returns (rc, wall_s, metrics, events,
    kill_wall_t).  Unplanned = scripted SIGKILL under budget 1; planned
    = preemption notice drained under budget ZERO (a drain must not
    need a restart slot)."""
    from tpucfn.bootstrap import EnvContract
    from tpucfn.ft import (ChaosEvent, ChaosSpec, GangCoordinator,
                           GangRestart, HeartbeatMonitor, MonitorConfig,
                           RestartBudget)
    from tpucfn.launch import Launcher, LocalTransport
    from tpucfn.obs import MetricRegistry

    work.mkdir(parents=True, exist_ok=True)
    ft_dir = work / "ft"
    flag_dir = work / "flags"
    flag_dir.mkdir(exist_ok=True)
    os.environ["FT_BENCH_FLAG_DIR"] = str(flag_dir)

    hostfile = work / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(args.hosts)))
    contract = EnvContract(
        workers_path=str(hostfile), workers_count=args.hosts,
        worker_chip_count=1, coordinator="127.0.0.1:1234", host_id=0,
        storage=str(work), generation=1)
    launcher = Launcher(contract, LocalTransport(), ft_dir=str(ft_dir),
                        ft_heartbeat_s=args.heartbeat_interval)
    registry = MetricRegistry(labels={"role": "ft-bench"})
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=args.hosts,
        config=MonitorConfig(interval_s=args.heartbeat_interval,
                             startup_grace_s=30.0))
    action = "preempt_notice" if planned else "kill"
    chaos = ChaosSpec(events=(
        ChaosEvent(action=action, at_s=args.kill_after, host=0,
                   duration_s=10.0 if planned else 0.0),),
        seed=args.seed)
    coord = GangCoordinator(
        launcher, [sys.executable, "-c", WORKER],
        policy=GangRestart(RestartBudget(0 if planned else 1)),
        monitor=monitor, registry=registry, ft_dir=ft_dir,
        poll_interval=args.poll_interval, term_grace_s=1.0, chaos=chaos)

    # Clock instrumentation: wall time of the kill actually firing vs the
    # coordinator's detect event (events.jsonl stamps wall time).
    kill_wall: dict[str, float] = {}
    orig_kill = coord.kill_host

    def kill_spy(host_id):
        kill_wall["t"] = time.time()
        orig_kill(host_id)

    coord.kill_host = kill_spy

    t0 = time.perf_counter()
    rc = coord.run()
    wall = time.perf_counter() - t0
    events = [json.loads(s) for s in
              (ft_dir / "events.jsonl").read_text().splitlines()]
    return rc, wall, registry.varz()["metrics"], events, kill_wall.get("t")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hosts", type=int, default=2)
    p.add_argument("--kill-after", type=float, default=1.0,
                   help="chaos kill (or preempt notice), seconds after "
                        "launch")
    p.add_argument("--heartbeat-interval", type=float, default=0.05)
    p.add_argument("--poll-interval", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out-dir", default=None,
                   help="scratch dir (default: a fresh temp dir)")
    args = p.parse_args()

    import tempfile

    root = Path(args.out_dir or tempfile.mkdtemp(prefix="ft-bench-"))

    rc, wall, m, events, kill_t = _run_scenario(
        args, root / "unplanned", planned=False)
    detect = next((e for e in events if e["kind"] == "detect"), None)
    recovered = next((e for e in events if e["kind"] == "recovered"), None)
    mttr = (m["ft_mttr_seconds"].get("mean") or 0.0) if isinstance(
        m.get("ft_mttr_seconds"), dict) else 0.0
    detection = (detect["ts"] - kill_t
                 if detect and kill_t is not None else None)

    prc, pwall, pm, pevents, _ = _run_scenario(
        args, root / "planned", planned=True)
    pmttr = (pm["ft_planned_mttr_seconds"].get("mean") or 0.0) if isinstance(
        pm.get("ft_planned_mttr_seconds"), dict) else 0.0
    planned_ok = (prc == 0
                  and pm.get("ft_preempt_drains_total") == 1
                  and pm.get("ft_restarts_total", 0) == 0
                  and any(e["kind"] == "recovered" and e.get("planned")
                          for e in pevents))

    ok = (rc == 0 and detect is not None and recovered is not None
          and m.get("ft_restarts_total") == 1 and planned_ok)
    print(f"# ft_bench rc={rc} wall={wall:.2f}s detect={detection} "
          f"mttr={mttr} planned_mttr={pmttr} planned_ok={planned_ok}",
          file=sys.stderr)
    row = {
        "metric": "ft_mttr_seconds",
        "value": round(mttr, 4),
        "unit": "seconds",
        "vs_baseline": 0.0,
        "detail": {
            "baseline_note": "reference harness recovery was a manual "
                             "re-run; no automated-recovery number exists",
            "ok": ok,
            "rc": rc,
            "wall_s": round(wall, 3),
            "scenario": f"kill host 0 at t={args.kill_after}s, gang "
                        "restart under budget 1, relaunched gang "
                        "finishes clean",
            "hosts": args.hosts,
            "policy": "gang",
            "poll_interval_s": args.poll_interval,
            "heartbeat_interval_s": args.heartbeat_interval,
            "detection_latency_s": (None if detection is None
                                    else round(detection, 4)),
            "mttr_s": round(mttr, 4),
            "failures_detected": m.get("ft_failures_detected_total"),
            "restarts": m.get("ft_restarts_total"),
            "gang_restarts": m.get("ft_gang_restarts_total"),
            "events": [e["kind"] for e in events],
            # planned-vs-unplanned MTTR split (ISSUE 7): the same
            # interruption handled via advance notice — drained clean,
            # zero restart budget consumed.
            "planned": {
                "ok": planned_ok,
                "rc": prc,
                "wall_s": round(pwall, 3),
                "mttr_s": round(pmttr, 4),
                "drains": pm.get("ft_preempt_drains_total"),
                "restart_budget_used": pm.get("ft_restarts_total", 0),
                "events": [e["kind"] for e in pevents],
            },
        },
    }
    print(json.dumps(row))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
