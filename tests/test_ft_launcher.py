"""Launcher paths under the ft plane (ISSUE 4 satellite): SIGTERM→SIGKILL
escalation in stop_all, launch_host env identity for solo restarts, and
the ft env fan-out."""

import signal
import sys
import time

import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.launch import Launcher, LocalTransport


def _contract(tmp_path, n=2) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def test_stop_all_graceful_sigterm(tmp_path):
    """A cooperative process dies on SIGTERM inside the grace window —
    no escalation."""
    launcher = Launcher(_contract(tmp_path, n=2), LocalTransport())
    procs = launcher.launch(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    time.sleep(0.3)  # let the interpreters install default handlers
    escalated = launcher.stop_all(procs, grace_s=5.0, poll_interval=0.02)
    assert escalated == 0
    assert [p.poll() for p in procs] == [-signal.SIGTERM, -signal.SIGTERM]


def test_stop_all_escalates_to_sigkill(tmp_path):
    """A process that ignores SIGTERM (wedged in a collective, or
    SIGSTOP'd by chaos) is SIGKILLed after the grace window."""
    launcher = Launcher(_contract(tmp_path, n=1), LocalTransport())
    ready = tmp_path / "ready"
    stubborn = (
        "import pathlib, signal, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        f"pathlib.Path(r'{ready}').write_text('x')\n"
        "time.sleep(60)\n")
    procs = launcher.launch([sys.executable, "-c", stubborn])
    deadline = time.monotonic() + 10
    while not ready.exists():  # handler must be installed before TERM
        assert time.monotonic() < deadline
        time.sleep(0.01)
    t0 = time.monotonic()
    escalated = launcher.stop_all(procs, grace_s=0.3, poll_interval=0.02)
    assert escalated == 1
    assert procs[0].poll() == -signal.SIGKILL
    assert time.monotonic() - t0 < 5.0  # grace + kill, not the full sleep


def test_stop_all_reaps_already_dead(tmp_path):
    launcher = Launcher(_contract(tmp_path, n=1), LocalTransport())
    procs = launcher.launch([sys.executable, "-c", "pass"])
    deadline = time.monotonic() + 10
    while procs[0].poll() is None:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    assert launcher.stop_all(procs, grace_s=0.1) == 0
    assert procs[0].returncode == 0


def test_launch_host_reuses_exact_host_env(tmp_path):
    """The solo-restart contract: a relaunched host gets byte-identical
    env (host_id, obs port, ft dir/interval) to the rank it replaces."""
    launcher = Launcher(_contract(tmp_path, n=3), LocalTransport(),
                        obs_base_port=9300, ft_dir=str(tmp_path / "ft"),
                        ft_heartbeat_s=0.25)
    out = tmp_path / "envs"
    out.mkdir()
    code = (
        "import os, pathlib, time\n"
        "keys = ['TPUCFN_HOST_ID', 'TPUCFN_OBS_PORT', 'TPUCFN_FT_DIR',"
        " 'TPUCFN_FT_HEARTBEAT_S']\n"
        f"d = pathlib.Path(r'{out}')\n"
        "h = os.environ['TPUCFN_HOST_ID']\n"
        "with open(d / f'env-{h}.log', 'a') as f:\n"
        "    f.write(','.join(os.environ[k] for k in keys) + '\\n')\n")
    procs = launcher.launch([sys.executable, "-c", code])
    assert launcher.wait(procs) == 0
    solo = launcher.launch_host([sys.executable, "-c", code], 1)
    assert solo.wait(timeout=30) == 0
    lines1 = (out / "env-1.log").read_text().splitlines()
    assert len(lines1) == 2 and lines1[0] == lines1[1]
    assert lines1[0] == f"1,9302,{tmp_path / 'ft'},0.25"
    # the other hosts ran exactly once, with their own ports
    assert (out / "env-0.log").read_text().splitlines() == [
        f"0,9301,{tmp_path / 'ft'},0.25"]


def test_launch_host_validates_range(tmp_path):
    launcher = Launcher(_contract(tmp_path, n=2), LocalTransport())
    with pytest.raises(ValueError):
        launcher.launch_host([sys.executable, "-c", "pass"], 5)


def test_host_env_without_ft_has_no_ft_vars(tmp_path):
    launcher = Launcher(_contract(tmp_path), LocalTransport())
    env = launcher.host_env(0)
    assert "TPUCFN_FT_DIR" not in env
    assert "TPUCFN_FT_HEARTBEAT_S" not in env


def test_extra_env_reaches_every_launch_shape(tmp_path):
    """The coordinator's degradation state (ckpt blacklist) rides
    extra_env into both gang launches and solo relaunches, and wins
    over contract-derived vars."""
    launcher = Launcher(_contract(tmp_path, n=2), LocalTransport())
    launcher.extra_env["TPUCFN_CKPT_BLACKLIST"] = "20,30"
    env = launcher.host_env(1)
    assert env["TPUCFN_CKPT_BLACKLIST"] == "20,30"
    out = tmp_path / "out"
    out.mkdir()
    code = ("import os, pathlib\n"
            "h = os.environ['TPUCFN_HOST_ID']\n"
            f"pathlib.Path(r'{out}', f'bl-{{h}}').write_text("
            "os.environ.get('TPUCFN_CKPT_BLACKLIST', 'MISSING'))\n")
    procs = launcher.launch([sys.executable, "-c", code])
    assert launcher.wait(procs) == 0
    solo = launcher.launch_host([sys.executable, "-c", code], 0)
    assert solo.wait(timeout=30) == 0
    assert (out / "bl-0").read_text() == "20,30"
    assert (out / "bl-1").read_text() == "20,30"


def test_shrink_contract_bumps_generation_and_renumbers(tmp_path):
    """Elastic shrink (ISSUE 7): dropping a lost host re-converges at
    N-1 with a NEW contract generation, a new hostfile next to the old
    one, and the coordinator address following the new host 0."""
    from tpucfn.bootstrap import shrink_contract

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("10.0.0.1:8471\n10.0.0.2:8471\n10.0.0.3:8471\n")
    c = EnvContract(
        workers_path=str(hostfile), workers_count=3, worker_chip_count=4,
        coordinator="10.0.0.1:8476", host_id=0, storage="/shared",
        generation=5)
    s = shrink_contract(c, [0])  # host 0 (the coordinator host!) lost
    assert s.generation == 6
    assert s.workers_count == 2
    assert s.hosts() == ["10.0.0.2:8471", "10.0.0.3:8471"]
    assert s.coordinator == "10.0.0.2:8476"  # follows new host 0
    assert s.worker_chip_count == 4 and s.storage == "/shared"
    # the new hostfile is a sibling; the old generation's is untouched
    assert s.workers_path != c.workers_path
    assert hostfile.read_text().count("\n") == 3
    # env fan-out carries the new generation
    assert s.to_env()["TPUCFN_GENERATION"] == "6"
    # per-host re-converge: each survivor's own id shifts down by the
    # lost ids below it — distinct slots, no collisions
    c1 = EnvContract(**{**c.__dict__, "host_id": 1})
    assert shrink_contract(c1, [0]).host_id == 0
    c2 = EnvContract(**{**c.__dict__, "host_id": 2})
    assert shrink_contract(c2, [0]).host_id == 1
    assert shrink_contract(c2, [1]).host_id == 1
    assert shrink_contract(c2, [0, 1]).host_id == 0
    # shrinking away everything is a give-up, not a shrink
    with pytest.raises(ValueError):
        shrink_contract(s, [0, 1])
    with pytest.raises(ValueError):
        shrink_contract(s, [7])  # out of range


# -- input-plane role fan-out (ISSUE 11) ------------------------------------

def test_input_hosts_role_env_fanout(tmp_path):
    """The last N hosts are input-role: TPUCFN_ROLE, a per-host input
    port, TPUCFN_INPUT_ADDRS everywhere, and the trainer ranks' jax
    rendezvous shrunk to the TRAINER count."""
    launcher = Launcher(_contract(tmp_path, n=4), LocalTransport(),
                        input_hosts=2, input_port=9100)
    assert launcher.trainer_host_ids == [0, 1]
    assert launcher.input_host_ids == [2, 3]
    t_env = launcher.host_env(0)
    assert t_env["TPUCFN_ROLE"] == "trainer"
    assert t_env["TPUCFN_WORKERS_COUNT"] == "2"
    assert t_env["TPUCFN_INPUT_ADDRS"] == "127.0.0.1:9102,127.0.0.1:9103"
    assert "TPUCFN_INPUT_PORT" not in t_env
    i_env = launcher.host_env(3)
    assert i_env["TPUCFN_ROLE"] == "input"
    assert i_env["TPUCFN_INPUT_PORT"] == "9103"
    assert i_env["TPUCFN_WORKERS_COUNT"] == "2"


def test_input_advertise_host_overrides_hostfile(tmp_path):
    """A LocalTransport fleet's hostfile may carry the control plane's
    synthetic addresses (10.0.0.x) — undialable on loopback, so the
    advertised input endpoints must be overridable (ISSUE 18; same
    failure class as --compile-cache-advertise)."""
    hostfile = tmp_path / "hostfile"
    hostfile.write_text(
        "".join(f"10.0.0.{i + 1}:8471\n" for i in range(4)))
    contract = EnvContract(
        workers_path=str(hostfile), workers_count=4, worker_chip_count=1,
        coordinator="10.0.0.1:8476", host_id=0, storage=str(tmp_path),
        generation=1)
    plain = Launcher(contract, LocalTransport(),
                     input_hosts=2, input_port=9100)
    assert plain.host_env(0)["TPUCFN_INPUT_ADDRS"] == \
        "10.0.0.3:9102,10.0.0.4:9103"
    launcher = Launcher(contract, LocalTransport(),
                        input_hosts=2, input_port=9100,
                        input_advertise_host="127.0.0.1")
    env = launcher.host_env(0)
    assert env["TPUCFN_INPUT_ADDRS"] == "127.0.0.1:9102,127.0.0.1:9103"
    # the input host still binds its own per-host port, unaffected
    assert launcher.host_env(3)["TPUCFN_INPUT_PORT"] == "9103"


def test_input_hosts_zero_keeps_env_byte_identical(tmp_path):
    """input_hosts=0 (every existing caller) must not grow the env —
    the role vars appear only when the input plane is on."""
    plain = Launcher(_contract(tmp_path, n=2), LocalTransport())
    env = plain.host_env(1)
    assert "TPUCFN_ROLE" not in env
    assert "TPUCFN_INPUT_ADDRS" not in env
    assert env["TPUCFN_WORKERS_COUNT"] == "2"


def test_input_hosts_run_input_argv(tmp_path):
    """Input hosts run --input-cmd's argv; trainers run the job's."""
    import subprocess

    class Recording(LocalTransport):
        def __init__(self):
            self.calls = []

        def run(self, host, argv, env):
            self.calls.append((env.get("TPUCFN_ROLE"), list(argv)))
            return subprocess.Popen([sys.executable, "-c", "pass"])

    tr = Recording()
    launcher = Launcher(_contract(tmp_path, n=3), LocalTransport(),
                        input_hosts=1,
                        input_argv=["serve-input"])
    launcher.transport = tr
    procs = launcher.launch(["train"])
    launcher.stop_all(procs)
    assert tr.calls == [("trainer", ["train"]), ("trainer", ["train"]),
                       ("input", ["serve-input"])]
    # solo relaunch of the input host keeps its argv too
    tr.calls.clear()
    launcher.launch_host(["train"], 2).wait()
    assert tr.calls == [("input", ["serve-input"])]


def test_input_hosts_must_leave_a_trainer(tmp_path):
    launcher = Launcher(_contract(tmp_path, n=2), LocalTransport(),
                        input_hosts=2)
    with pytest.raises(ValueError, match="no trainer"):
        launcher.host_env(0)
