from tpucfn.utils.tree import param_count, param_bytes, tree_paths, describe_params  # noqa: F401
