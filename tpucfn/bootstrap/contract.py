"""The cluster contract — what every layer above the provisioner consumes.

This is the exact analogue of the reference's bootstrap output (SURVEY.md
§2.1 "Cluster contract"): a hostfile of worker addresses plus exported env
vars, converged per-host at boot. Reference names → tpucfn names:

    $DEEPLEARNING_WORKERS_PATH      → $TPUCFN_WORKERS_PATH  (hostfile)
    $DEEPLEARNING_WORKERS_COUNT     → $TPUCFN_WORKERS_COUNT
    $DEEPLEARNING_WORKER_GPU_COUNT  → $TPUCFN_WORKER_CHIP_COUNT
    (implicit master)               → $TPUCFN_COORDINATOR   (host0:port —
                                      jax.distributed rendezvous, which
                                      replaces both MPI and the dmlc
                                      scheduler)
    (implicit EFS mount)            → $TPUCFN_STORAGE       (GCS/shared dir)

The legacy ``DEEPLEARNING_*`` names are also exported so reference-era
launch commands (``launch.py -n $DEEPLEARNING_WORKERS_COUNT -H
$DEEPLEARNING_WORKERS_PATH …``) keep working verbatim — the "examples run
unmodified from the user's side" requirement (BASELINE.json north star).
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path

from tpucfn.provision.control_plane import ClusterRecord

COORDINATOR_PORT = 8476


@dataclasses.dataclass(frozen=True)
class EnvContract:
    workers_path: str  # hostfile location
    workers_count: int
    worker_chip_count: int
    coordinator: str  # "host0_addr:port"
    host_id: int
    storage: str
    generation: int

    def to_env(self) -> dict[str, str]:
        env = {
            "TPUCFN_WORKERS_PATH": self.workers_path,
            "TPUCFN_WORKERS_COUNT": str(self.workers_count),
            "TPUCFN_WORKER_CHIP_COUNT": str(self.worker_chip_count),
            "TPUCFN_COORDINATOR": self.coordinator,
            "TPUCFN_HOST_ID": str(self.host_id),
            "TPUCFN_STORAGE": self.storage,
            "TPUCFN_GENERATION": str(self.generation),
            # Legacy aliases for reference-era commands.
            "DEEPLEARNING_WORKERS_PATH": self.workers_path,
            "DEEPLEARNING_WORKERS_COUNT": str(self.workers_count),
            "DEEPLEARNING_WORKER_GPU_COUNT": str(self.worker_chip_count),
        }
        return env

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None) -> "EnvContract":
        e = os.environ if env is None else env
        try:
            return cls(
                workers_path=e["TPUCFN_WORKERS_PATH"],
                workers_count=int(e["TPUCFN_WORKERS_COUNT"]),
                worker_chip_count=int(e["TPUCFN_WORKER_CHIP_COUNT"]),
                coordinator=e["TPUCFN_COORDINATOR"],
                host_id=int(e["TPUCFN_HOST_ID"]),
                storage=e.get("TPUCFN_STORAGE", ""),
                generation=int(e.get("TPUCFN_GENERATION", "0")),
            )
        except KeyError as k:
            raise EnvironmentError(
                f"missing {k.args[0]} — this process is not inside a converged "
                "tpucfn cluster (run via `tpucfn launch` or source the env file)"
            ) from None

    def hosts(self) -> list[str]:
        return Path(self.workers_path).read_text().split()


def converge(record: ClusterRecord, run_dir: str | Path, host_id: int = 0) -> EnvContract:
    """Per-host bootstrap: write the hostfile + env file under ``run_dir``
    (≈ what cfn-init did with EC2 metadata), return the contract.

    Idempotent — re-running after a re-acquire overwrites with the new
    generation, exactly like the reference's bootstrap regenerating the
    hostfile after an ASG resize (SURVEY.md §3.5).
    """
    d = Path(run_dir)
    d.mkdir(parents=True, exist_ok=True)
    hostfile = d / "hostfile"
    hostfile.write_text("".join(f"{h.address}\n" for h in record.hosts))
    coord_host = record.hosts[0].address.rsplit(":", 1)[0]
    contract = EnvContract(
        workers_path=str(hostfile),
        workers_count=len(record.hosts),
        worker_chip_count=record.spec.sku.chips_per_host,
        coordinator=f"{coord_host}:{COORDINATOR_PORT}",
        host_id=host_id,
        storage=record.spec.storage_path or str(d / "storage"),
        generation=record.generation,
    )
    envfile = d / "env.sh"
    envfile.write_text(
        "".join(f"export {k}={v!r}\n" for k, v in sorted(contract.to_env().items()))
    )
    return contract


def shrink_contract(contract: EnvContract,
                    lost_host_ids: list[int] | set[int],
                    hostfile_path: str | Path | None = None) -> EnvContract:
    """Re-converge at N-k hosts (elastic shrink, ISSUE 7): drop the lost
    hosts from the launched slice, renumber the survivors 0..N-k-1, bump
    the contract generation, and write the new hostfile next to the old
    one (``<hostfile>.gen<G>`` — the previous generation's file is left
    untouched for forensics).  The coordinator address follows the new
    host 0 (on the original coordinator port) in case host 0 itself was
    the one lost.

    Raises ``ValueError`` when nothing would remain — a gang of zero is
    not a shrink, it is a give-up, and the caller must decide that."""
    hosts = contract.hosts()[: contract.workers_count]
    lost = {int(h) for h in lost_host_ids}
    bad = lost - set(range(len(hosts)))
    if bad:
        raise ValueError(
            f"lost host id(s) {sorted(bad)} out of range for "
            f"{len(hosts)} launched hosts")
    keep = [h for i, h in enumerate(hosts) if i not in lost]
    if not keep:
        raise ValueError(
            f"shrink would remove all {len(hosts)} hosts — nothing left "
            "to re-converge")
    generation = contract.generation + 1
    old = Path(contract.workers_path)
    path = (Path(hostfile_path) if hostfile_path is not None
            else old.with_name(f"{old.name}.gen{generation}"))
    path.write_text("".join(f"{h}\n" for h in keep))
    coord_port = contract.coordinator.rsplit(":", 1)[1]
    # This host's own new id: old id minus the lost ids below it — the
    # same renumbering every survivor applies, so a per-host
    # re-converge lands each machine in a distinct slot.  A caller
    # whose own host was lost (shouldn't happen — the lost host has no
    # business re-converging) clamps to 0.
    if contract.host_id in lost:
        new_host_id = 0
    else:
        new_host_id = contract.host_id - sum(
            1 for i in lost if i < contract.host_id)
    return EnvContract(
        workers_path=str(path),
        workers_count=len(keep),
        worker_chip_count=contract.worker_chip_count,
        coordinator=f"{keep[0].rsplit(':', 1)[0]}:{coord_port}",
        host_id=new_host_id,
        storage=contract.storage,
        generation=generation,
    )
