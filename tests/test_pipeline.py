"""GPipe pipeline schedule: composition correctness, gradients, and a
pipelined transformer-block stack on a pipeline=4 mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpucfn.mesh import MeshSpec, build_mesh
from tpucfn.parallel.pipeline import gpipe, microbatch, unmicrobatch


@pytest.fixture()
def mesh_pp4():
    return build_mesh(MeshSpec(pipeline=4, data=2))


def _stack_params(n_layers, d, seed=0):
    rng = jax.random.key(seed)
    w = jax.random.normal(rng, (n_layers, d, d)) * (1.0 / np.sqrt(d))
    b = jnp.zeros((n_layers, d))
    return {"w": w, "b": b}


def _stage_fn(stage_params, x):
    """Apply this stage's layer slice sequentially (scan over local layers)."""

    def layer(h, wb):
        w, b = wb
        return jnp.tanh(h @ w + b), None

    out, _ = jax.lax.scan(layer, x, (stage_params["w"], stage_params["b"]))
    return out


def _sequential(params, x):
    def layer(h, wb):
        w, b = wb
        return jnp.tanh(h @ w + b), None

    out, _ = jax.lax.scan(layer, x, (params["w"], params["b"]))
    return out


def _run_gpipe(mesh, params, x, m):
    mb = microbatch(x, m)

    fn = jax.jit(
        jax.shard_map(
            lambda p, xs: gpipe(_stage_fn, p, xs),
            mesh=mesh,
            in_specs=({"w": P("pipeline"), "b": P("pipeline")}, P()),
            out_specs=P(),
            check_vma=False,
        )
    )
    return unmicrobatch(fn(params, mb))


def test_gpipe_matches_sequential(mesh_pp4):
    params = _stack_params(8, 16)  # 8 layers over 4 stages = 2/stage
    x = jax.random.normal(jax.random.key(1), (16, 16))
    out = _run_gpipe(mesh_pp4, params, x, m=4)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_gpipe_single_microbatch(mesh_pp4):
    params = _stack_params(4, 8)
    x = jax.random.normal(jax.random.key(2), (4, 8))
    out = _run_gpipe(mesh_pp4, params, x, m=1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_sequential(params, x)),
                               atol=1e-5)


def test_gpipe_more_microbatches_than_stages(mesh_pp4):
    params = _stack_params(4, 8)
    x = jax.random.normal(jax.random.key(3), (32, 8))
    out = _run_gpipe(mesh_pp4, params, x, m=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_sequential(params, x)),
                               atol=1e-5)


def test_gpipe_gradients_match_sequential(mesh_pp4):
    params = _stack_params(8, 8)
    x = jax.random.normal(jax.random.key(4), (8, 8))
    y = jax.random.normal(jax.random.key(5), (8, 8))

    def loss_pp(params):
        mb = microbatch(x, 4)
        fn = jax.shard_map(
            lambda p, xs: gpipe(_stage_fn, p, xs),
            mesh=mesh_pp4,
            in_specs=({"w": P("pipeline"), "b": P("pipeline")}, P()),
            out_specs=P(),
            check_vma=False,
        )
        return jnp.mean((unmicrobatch(fn(params, mb)) - y) ** 2)

    def loss_seq(params):
        return jnp.mean((_sequential(params, x) - y) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_seq = jax.jit(jax.grad(loss_seq))(params)
    np.testing.assert_allclose(np.asarray(g_pp["w"]), np.asarray(g_seq["w"]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(g_pp["b"]), np.asarray(g_seq["b"]),
                               atol=1e-5)


def test_microbatch_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    mb = microbatch(x, 4)
    assert mb.shape == (4, 3, 2)
    np.testing.assert_array_equal(np.asarray(unmicrobatch(mb)), np.asarray(x))
    with pytest.raises(ValueError, match="divisible"):
        microbatch(x, 5)
