"""Incident postmortem assembly: one bundle per incident (ISSUE 6).

The write side of every observability plane already lands per-host
JSONL under the run dir — trace spans, goodput ledgers, heartbeats,
ft events, flight-recorder dumps.  This module is the read side that
turns them into a *diagnosis*: given an incident id (or ``--latest``),
it assembles

* the **incident** itself — the enriched ``goodput_incident`` row plus
  the raw detect/decide/recovered events and the failure verdicts;
* the **skew-corrected merged timeline** windowed around detection
  (every event carries ``ts_adj``, ordered on the fleet's median
  clock — the same correction ``tpucfn obs`` applies);
* the **goodput buckets for the affected span** (the window's phase
  records only, decomposed by the normal merge);
* the **per-host flight-recorder tails** — the coordinator's at-detect
  captures (``<ft_dir>/flight/incident{N}-host*.jsonl``) preferred,
  each process's signal/atexit dump (``<run_dir>/flight/``) as
  fallback — with coverage relative to the detection instant;
* each host's **last heartbeat** before detection.

Everything is pure functions over parsed dicts (the ``tpucfn obs
postmortem`` CLI, tests, and notebooks share one implementation), and
every input is optional-but-reported: a missing trace dir yields an
empty timeline plus a note, not a crash — the postmortem of a broken
run must survive the brokenness it is diagnosing.
"""

from __future__ import annotations

import json
import shutil
from pathlib import Path
from typing import Iterable

from tpucfn.ft.heartbeat import HB_GLOB
from tpucfn.obs.aggregate import (apply_clock_skew, render_table,
                                  window_events)
from tpucfn.obs.flight import FLIGHT_GLOB, read_flight_dir
from tpucfn.obs.goodput import (_incidents_from_events, host_id_from_path,
                                merge_goodput, read_ft_events,
                                read_goodput_dir, read_jsonl_counting)
from tpucfn.obs.timeline import fleet_skew, read_clock_offsets
from tpucfn.obs.trace import read_trace_dir, read_trace_file

DEFAULT_WINDOW_S = 15.0


def select_incident(events: Iterable[dict],
                    incident_id: int | None = None) -> dict:
    """The incident row to postmortem: the enriched/fallback row from
    :func:`~tpucfn.obs.goodput._incidents_from_events`, newest when
    ``incident_id`` is None (``--latest``).  Raises ``ValueError`` with
    the known ids when the run has no incidents or the id is unknown —
    the CLI's usage-error text."""
    incidents = _incidents_from_events(events)
    if not incidents:
        raise ValueError(
            "no incidents in the ft events log (nothing failed, or the "
            "run was not launched with --ft)")
    if incident_id is None:
        return incidents[-1]
    for inc in incidents:
        if inc["incident"] == incident_id:
            return inc
    raise ValueError(
        f"unknown incident {incident_id}; this run has "
        f"{[i['incident'] for i in incidents]}")


def _read_heartbeats_full(ft_dir: Path) -> dict[int, list[dict]]:
    out: dict[int, list[dict]] = {}
    if not ft_dir.is_dir():
        return out
    for p in sorted(ft_dir.glob(HB_GLOB)):
        host = host_id_from_path(p)
        if host is None:
            continue
        recs, _ = read_jsonl_counting(p)
        if recs:
            out[host] = recs
    return out


def _flight_rows(sources: dict[str, dict[int, dict]],
                 t_detect: float | None,
                 skew: dict[str, float] | None = None) -> list[dict]:
    """One row per (host, source): sample count, time span, and how far
    short of the detection instant the tail stops — the acceptance
    question is "do the survivors' rings cover the seconds up to
    detection", so answer it as a number, not a feeling.  Sample times
    are placed on the fleet clock via ``skew`` (the same correction the
    timeline gets) before comparing against the detect instant."""
    rows = []
    skew = skew or {}
    for source, by_host in sources.items():
        for host in sorted(by_host):
            d = by_host[host]
            off = skew.get(f"host{host}", 0.0)
            ts = [s["t"] - off for s in d["samples"]
                  if isinstance(s.get("t"), (int, float))]
            header = d.get("header") or {}
            row = {"host": host, "source": source,
                   "samples": len(d["samples"]),
                   "dropped": header.get("dropped"),
                   "t_first": min(ts) if ts else None,
                   "t_last": max(ts) if ts else None,
                   "path": d["path"]}
            if t_detect is not None and ts:
                row["gap_to_detect_s"] = round(t_detect - max(ts), 3)
            else:
                row["gap_to_detect_s"] = None
            rows.append(row)
    return rows


def build_postmortem(run_dir: str | Path, *,
                     incident_id: int | None = None,
                     window_s: float = DEFAULT_WINDOW_S,
                     ft_dir: str | Path | None = None) -> dict:
    """Assemble the postmortem report dict for one incident (see module
    doc for the sections).  Raises ``ValueError`` when there is no ft
    events log or the incident id is unknown; every other missing input
    degrades to an empty section plus a line in ``notes``."""
    run_dir = Path(run_dir)
    ft_dir = Path(ft_dir) if ft_dir is not None else run_dir / "ft"
    notes: list[str] = []

    events_path = ft_dir / "events.jsonl"
    events, ev_skipped = read_ft_events(events_path)
    if not events:
        raise ValueError(f"no ft events at {events_path} — a postmortem "
                         "needs the incident log (launch with --ft)")
    incident = select_incident(events, incident_id)
    inc_id = incident["incident"]
    raw_events = [e for e in events if e.get("incident") == inc_id]
    detect = next((e for e in raw_events if e.get("kind") == "detect"), None)
    recovered = next((e for e in raw_events if e.get("kind") == "recovered"),
                     None)
    t_detect = (detect or {}).get("ts") or incident.get("ts")
    t_end = (recovered or {}).get("ts") or t_detect
    window = (None, None)
    if t_detect is not None:
        window = (t_detect - window_s, (t_end or t_detect) + window_s)
    else:
        notes.append("incident has no usable timestamp; timeline and "
                     "goodput windows are empty")

    # -- skew-corrected timeline around detection -------------------------
    trace_dir = run_dir / "trace"
    trace_events = read_trace_dir(trace_dir) if trace_dir.is_dir() else []
    if not trace_events:
        notes.append(f"no trace spans under {trace_dir}")
    # Span tails (ISSUE 20): the coordinator's at-detect /tracetail
    # captures — the survivors' last spans, pulled before the restart
    # erased nothing (files are durable) but the postmortem may run on
    # a machine that only has ft_dir.  They back-fill the timeline when
    # the run dir's trace files are absent.
    span_tail_rows = []
    tail_events: list[dict] = []
    for p in sorted((ft_dir / "spans").glob(
            f"incident{inc_id:03d}-host*.jsonl")):
        evts = read_trace_file(p)
        tail_events.extend(evts)
        host = host_id_from_path(p)
        profile = p.with_name(p.stem + "-profile.json")
        span_tail_rows.append({
            "host": host, "events": len(evts),
            "profile": str(profile) if profile.is_file() else None,
            "path": str(p)})
    if not trace_events and tail_events:
        trace_events = sorted(tail_events,
                              key=lambda e: (e.get("ts", 0.0),
                                             e.get("mono", 0.0)))
        notes.append("timeline built from the coordinator's at-detect "
                     "span tails (no run-dir trace files)")
    hb_full = _read_heartbeats_full(ft_dir)
    # Measured clock offsets (coordinator /clock probes) win over the
    # step-anchored estimate wherever a probe exists.
    offsets = read_clock_offsets(ft_dir / "clock-offsets.jsonl")
    skew = fleet_skew(trace_events, offsets, hb_full or None)
    corrected = apply_clock_skew(trace_events, skew)
    timeline = (window_events(corrected, window[0], window[1])
                if window[0] is not None else [])

    # -- goodput buckets for the affected span ----------------------------
    # Ledger record times are host wall clocks: window them on the
    # corrected fleet clock (same skew the timeline gets), and hand the
    # merge only THIS incident's events — the full run's event list
    # would make goodput.json's incidents/downtime describe the whole
    # run under a section labeled "the affected span".
    by_host, gp_skipped = read_goodput_dir(run_dir / "goodput")
    if not by_host:
        notes.append(f"no goodput ledgers under {run_dir / 'goodput'}")
    if window[0] is not None:
        windowed = {
            h: [r for r in recs
                if isinstance(r.get("t"), (int, float))
                and window[0] <= r["t"] - skew.get(f"host{h}", 0.0)
                <= window[1]]
            for h, recs in by_host.items()}
    else:
        windowed = {}
    goodput = merge_goodput({h: r for h, r in windowed.items() if r},
                            raw_events, skipped_lines=gp_skipped)

    # -- flight-recorder tails --------------------------------------------
    # Captures first, dumps strictly as FALLBACK: the at-detect capture
    # is incident-scoped by its file name, but run_dir/flight dumps are
    # truncate-overwritten by every incarnation's exit — for a host the
    # coordinator already captured, the dump is a LATER incarnation's
    # ring, and for an earlier-than-latest incident a dump may postdate
    # detection entirely.  Only a dump with samples at or before the
    # detect instant can speak for this incident.
    sources: dict[str, dict[int, dict]] = {}
    captures = read_flight_dir(ft_dir / "flight",
                               glob=f"incident{inc_id:03d}-host*.jsonl")
    if captures:
        sources["incident-capture"] = captures
    dumps = read_flight_dir(run_dir / "flight", glob=FLIGHT_GLOB)
    fallback: dict[int, dict] = {}
    for host, d in dumps.items():
        if host in captures:
            continue
        off = skew.get(f"host{host}", 0.0)
        ts = [s["t"] - off for s in d["samples"]
              if isinstance(s.get("t"), (int, float))]
        if t_detect is not None and (not ts or min(ts) > t_detect):
            notes.append(
                f"host {host}'s process dump starts after detection "
                "(a later incarnation's ring) — excluded from this "
                "incident's coverage")
            continue
        fallback[host] = d
    if fallback:
        sources["process-dump"] = fallback
    if not sources:
        notes.append("no flight-recorder dumps (neither the "
                     "coordinator's at-detect captures nor per-process "
                     "exit dumps) — was the job wired with a "
                     "FlightRecorder and an obs port?")
    flight_rows = _flight_rows(sources, t_detect, skew)

    # -- last heartbeat per host before detection -------------------------
    heartbeats = []
    for host in sorted(hb_full):
        beats = hb_full[host]
        # beat times are this host's wall clock: compare on the fleet
        # clock, or a fast host's perfectly healthy beats would all
        # read as "after detection" and falsely vanish from the table
        off = skew.get(f"host{host}", 0.0)
        before = [b for b in beats
                  if isinstance(b.get("t"), (int, float))
                  and (t_detect is None or b["t"] - off <= t_detect)]
        if not before:
            # every parseable beat postdates detection (host launched
            # after this incident, or torn early lines): listing its
            # later beat under "last heartbeat BEFORE detection" would
            # assert the host was beating before an incident it never
            # saw — say so instead.
            notes.append(f"host {host} has no heartbeat at or before "
                         "detection — omitted from the heartbeat table")
            continue
        last = before[-1]
        heartbeats.append({
            "host": host, "t": last.get("t"), "step": last.get("step"),
            "pid": last.get("pid"), "role": last.get("role"),
            "age_at_detect_s": (round(t_detect - (last["t"] - off), 3)
                                if t_detect is not None else None)})
    if not hb_full:
        notes.append(f"no heartbeat files under {ft_dir}")

    return {
        "run_dir": str(run_dir),
        "ft_dir": str(ft_dir),
        "incident": incident,
        "events": raw_events,
        "detect_ts": t_detect,
        "window": {"start": window[0], "end": window[1],
                   "window_s": window_s},
        "clock_skew_s": skew,
        "clock_offsets": offsets,
        "timeline": timeline,
        "goodput": goodput,
        "flight": flight_rows,
        "span_tails": span_tail_rows,
        "heartbeats": heartbeats,
        "skipped_event_lines": ev_skipped,
        "notes": notes,
    }


def write_bundle(report: dict, out_dir: str | Path) -> Path:
    """Materialize one postmortem bundle directory:

    ``incident.json`` / ``heartbeats.json`` / ``goodput.json`` (the
    report sections), ``timeline.jsonl`` (one skew-corrected event per
    line), ``flight/`` (the source dump files copied in, so the bundle
    stays readable after the run dir is cleaned), and ``report.md``
    (the rendered human summary).  Returns the bundle path."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    (out / "incident.json").write_text(json.dumps(
        {"incident": report["incident"], "events": report["events"],
         "detect_ts": report["detect_ts"], "window": report["window"],
         "clock_skew_s": report["clock_skew_s"],
         "clock_offsets": report.get("clock_offsets") or {},
         "notes": report["notes"]}, indent=2))
    (out / "goodput.json").write_text(json.dumps(report["goodput"],
                                                 indent=2))
    (out / "heartbeats.json").write_text(json.dumps(report["heartbeats"],
                                                    indent=2))
    with open(out / "timeline.jsonl", "w") as f:
        for e in report["timeline"]:
            f.write(json.dumps(e) + "\n")
    flight_dir = out / "flight"
    for row in report["flight"]:
        src = Path(row["path"])
        if src.is_file():
            flight_dir.mkdir(parents=True, exist_ok=True)
            shutil.copy(src, flight_dir / f"{row['source']}-{src.name}")
    # Span tails + their optional profile artifacts (ISSUE 20) ride
    # along the same way the flight dumps do: the bundle must stay
    # readable after ft_dir is cleaned.
    spans_dir = out / "spans"
    for row in report.get("span_tails") or []:
        for src in (row.get("path"), row.get("profile")):
            if src and Path(src).is_file():
                spans_dir.mkdir(parents=True, exist_ok=True)
                shutil.copy(src, spans_dir / Path(src).name)
    (out / "report.md").write_text(render_postmortem(report) + "\n")
    return out


def render_postmortem(report: dict) -> str:
    """The human summary (``report.md`` and the CLI's default output)."""
    inc = report["incident"]
    label = inc.get("action") or "unresolved"
    if inc.get("planned"):
        label += ", planned"
    lines = [f"# postmortem — incident {inc['incident']} ({label})",
             "",
             f"run dir: {report['run_dir']}",
             f"detected at: {report['detect_ts']}",
             f"downtime_s: {inc.get('downtime_s')}  "
             f"detection_s: {inc.get('detection_s')}  "
             f"fleet_step: {inc.get('fleet_step')}  "
             f"lost_steps: {inc.get('lost_steps')}"]
    if inc.get("planned"):
        lines.append("planned restart: preemption notice drained into a "
                     "clean stop — this downtime was chosen, not suffered")
    shrink = inc.get("shrink")
    if shrink:
        lines.append(
            f"elastic shrink: {shrink.get('from_hosts')} -> "
            f"{shrink.get('to_hosts')} hosts "
            f"(lost {shrink.get('lost')}, contract generation "
            f"{shrink.get('generation')})")
    ckpt = inc.get("ckpt")
    if ckpt:
        lines.append(
            f"checkpoint retry: step {ckpt.get('bad_step')} failed to "
            f"restore and was blacklisted; resumed from "
            f"{ckpt.get('retry_from')}")
    detect = next((e for e in report["events"]
                   if e.get("kind") == "detect"), None)
    if detect and detect.get("failures"):
        lines += ["", "## failures"]
        lines.append(render_table(detect["failures"],
                                  ["host", "kind", "rc", "step", "detail"]))
    if report["heartbeats"]:
        lines += ["", "## last heartbeat before detection"]
        lines.append(render_table(
            report["heartbeats"],
            ["host", "step", "age_at_detect_s", "pid", "role"]))
    if report["flight"]:
        lines += ["", "## flight-recorder coverage"]
        lines.append(render_table(
            report["flight"],
            ["host", "source", "samples", "dropped", "gap_to_detect_s"]))
    if report.get("span_tails"):
        lines += ["", "## span tails captured at detect"]
        lines.append(render_table(
            [{**r, "profiled": bool(r.get("profile"))}
             for r in report["span_tails"]],
            ["host", "events", "profiled"]))
    gp = report["goodput"]
    if gp["num_hosts"]:
        lines += ["", f"## goodput over the window "
                      f"({report['window']['window_s']:g}s around the "
                      "incident)"]
        rows = [{"bucket": b, "seconds": v}
                for b, v in gp["buckets"].items() if v]
        lines.append(render_table(rows, ["bucket", "seconds"]))
    n = len(report["timeline"])
    skewed = sum(1 for s in report["clock_skew_s"].values() if s)
    probed = len(report.get("clock_offsets") or {})
    lines += ["", f"## timeline: {n} events in window "
                  f"(skew-corrected; {skewed} host(s) adjusted, "
                  f"{probed} from measured /clock probes) — "
                  "timeline.jsonl"]
    for note in report["notes"]:
        lines.append(f"NOTE: {note}")
    return "\n".join(lines)


# -- bundle diffing (ISSUE 20 satellite) ------------------------------------

def _read_bundle(d: str | Path) -> dict:
    """One :func:`write_bundle` directory parsed back (missing pieces
    degrade to empty, same contract as assembly — a diff of two bundles
    must survive either being partial)."""
    d = Path(d)

    def _json(name: str, default):
        p = d / name
        if not p.is_file():
            return default
        try:
            return json.loads(p.read_text())
        except json.JSONDecodeError:
            return default

    timeline = []
    p = d / "timeline.jsonl"
    if p.is_file():
        for line in p.read_text().splitlines():
            try:
                timeline.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return {"path": str(d),
            "incident": _json("incident.json", {}),
            "goodput": _json("goodput.json", {}),
            "heartbeats": _json("heartbeats.json", []),
            "timeline": timeline}


def diff_bundles(a_dir: str | Path, b_dir: str | Path) -> dict:
    """Two postmortem bundles of the SAME incident class diffed like
    goodput ledger rows (``b - a``): what did the second incident do
    differently?  Sections:

    * ``incident`` — action/planned/downtime/detection/lost-step deltas,
      with ``class_match`` False (and a note) when the two incidents
      are different classes (action+planned differ) — the deltas are
      still printed, the caller just can't read them as a regression;
    * ``buckets`` — the windows' goodput bucket seconds, normalized to
      shares of each bundle's own window so different window widths
      still compare;
    * ``hosts`` — per-host heartbeat-age-at-detect and timeline span
      count deltas (union of hosts, either side may miss one).
    """
    a, b = _read_bundle(a_dir), _read_bundle(b_dir)
    ia = (a["incident"].get("incident") or {})
    ib = (b["incident"].get("incident") or {})
    cls_a = (ia.get("action"), bool(ia.get("planned")))
    cls_b = (ib.get("action"), bool(ib.get("planned")))
    notes = []
    if cls_a != cls_b:
        notes.append(
            f"incident classes differ ({cls_a[0]}/planned={cls_a[1]} vs "
            f"{cls_b[0]}/planned={cls_b[1]}) — deltas below compare "
            "unlike incidents")

    def _delta(key):
        x, y = ia.get(key), ib.get(key)
        return {"a": x, "b": y,
                "delta": (round(y - x, 6)
                          if isinstance(x, (int, float))
                          and isinstance(y, (int, float)) else None)}

    incident = {
        "a_incident": ia.get("incident"), "b_incident": ib.get("incident"),
        "class_match": cls_a == cls_b,
        "action": {"a": cls_a[0], "b": cls_b[0]},
        "downtime_s": _delta("downtime_s"),
        "detection_s": _delta("detection_s"),
        "lost_steps": _delta("lost_steps"),
    }

    def _shares(g):
        buckets = g.get("buckets") or {}
        total = sum(v for v in buckets.values()
                    if isinstance(v, (int, float))) or None
        return {k: (v / total if total else None)
                for k, v in buckets.items()
                if isinstance(v, (int, float))}

    sa, sb = _shares(a["goodput"]), _shares(b["goodput"])
    buckets = []
    for name in sorted(set(sa) | set(sb)):
        x, y = sa.get(name), sb.get(name)
        buckets.append({"bucket": name, "a_share": x, "b_share": y,
                        "delta": (round(y - x, 6)
                                  if x is not None and y is not None
                                  else None)})

    def _hb_age(bundle):
        return {h.get("host"): h.get("age_at_detect_s")
                for h in bundle["heartbeats"] if h.get("host") is not None}

    def _span_counts(bundle):
        out: dict[int, int] = {}
        for e in bundle["timeline"]:
            h = e.get("host")
            if h is not None:
                out[h] = out.get(h, 0) + 1
        return out

    ha, hb = _hb_age(a), _hb_age(b)
    ca, cb = _span_counts(a), _span_counts(b)
    hosts = []
    for h in sorted(set(ha) | set(hb) | set(ca) | set(cb)):
        ax, bx = ha.get(h), hb.get(h)
        hosts.append({
            "host": h,
            "a_hb_age_s": ax, "b_hb_age_s": bx,
            "hb_age_delta_s": (round(bx - ax, 3)
                               if isinstance(ax, (int, float))
                               and isinstance(bx, (int, float)) else None),
            "a_spans": ca.get(h, 0), "b_spans": cb.get(h, 0),
            "span_delta": cb.get(h, 0) - ca.get(h, 0)})

    return {"a": a["path"], "b": b["path"], "incident": incident,
            "buckets": buckets, "hosts": hosts, "notes": notes,
            "window_s": {"a": (a["incident"].get("window") or {})
                         .get("window_s"),
                         "b": (b["incident"].get("window") or {})
                         .get("window_s")}}


def render_bundle_diff(diff: dict) -> str:
    """Human rendering of :func:`diff_bundles` (``tpucfn forensics
    diff``)."""
    inc = diff["incident"]
    lines = [f"# forensics diff — incident {inc['a_incident']} "
             f"({Path(diff['a']).name}) vs incident {inc['b_incident']} "
             f"({Path(diff['b']).name})"]
    if not inc["class_match"]:
        lines.append("WARNING: different incident classes — read deltas "
                     "as context, not regression")
    lines.append(
        f"action: {inc['action']['a']} vs {inc['action']['b']}")
    for key in ("downtime_s", "detection_s", "lost_steps"):
        d = inc[key]
        lines.append(f"{key}: {d['a']} vs {d['b']}"
                     + (f"  (delta {d['delta']:+g})"
                        if d["delta"] is not None else ""))
    if diff["buckets"]:
        lines += ["", "## goodput bucket shares over each bundle's window"]
        lines.append(render_table(
            diff["buckets"], ["bucket", "a_share", "b_share", "delta"]))
    if diff["hosts"]:
        lines += ["", "## per-host deltas (heartbeat age at detect, "
                      "timeline events)"]
        lines.append(render_table(
            diff["hosts"],
            ["host", "a_hb_age_s", "b_hb_age_s", "hb_age_delta_s",
             "a_spans", "b_spans", "span_delta"]))
    for note in diff["notes"]:
        lines.append(f"NOTE: {note}")
    return "\n".join(lines)
