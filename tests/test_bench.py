"""bench.py is a driver-scored artifact: the orchestrator must always
print exactly one parseable JSON line with the contract fields, even
with no TPU anywhere in sight."""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _run_bench(extra_env=None, timeout=1200):
    # Outer timeout must exceed bench.py's internal CPU-worker budget
    # (TPUCFN_BENCH_CPU_TIMEOUT_S=900) so a slow worker surfaces as the
    # orchestrator's bench_failed record, not an opaque harness kill.
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # forces the CPU-fallback path
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.run([sys.executable, str(REPO / "bench.py")],
                          env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_bench_emits_contract_json_line():
    r = _run_bench()
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    line = r.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    for key in ("metric", "value", "unit", "vs_baseline", "detail"):
        assert key in rec, rec
    assert rec["value"] > 0
    d = rec["detail"]
    assert d["backend_mode"] == "cpu-fallback"
    assert "probes" in d and "mean_step_s" in d and "time_to_first_step_s" in d
    # MFU machinery ran (flops measured; mfu itself is None off-TPU)
    assert d["flops_per_dev_step_g"] is not None
    assert d["mfu"] is None


def test_bench_llama_preset():
    r = _run_bench({"TPUCFN_BENCH_MODEL": "llama"})
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "tiny_llama_train_tokens_per_sec_per_chip"
    assert rec["unit"] == "tokens/sec/chip"
    assert rec["value"] > 0


def test_bench_replays_recorded_onchip_result(tmp_path):
    """When a TPU is configured but unreachable (or the single-client
    megabench holds the tunnel), the orchestrator replays the newest
    recorded on-chip headline result instead of degrading to CPU."""
    recorded = {
        "phase": "resnet_full", "ts": 1.0, "utc": "2026-07-29T00:00:00Z",
        "result": {
            "metric": "resnet50_imagenet_train_images_per_sec_per_chip",
            "value": 3210.5, "unit": "images/sec/chip", "vs_baseline": 8.03,
            "detail": {"platform": "tpu", "device_kind": "TPU v5 lite",
                       "mfu": 0.31, "mean_step_s": 0.0638}}}
    path = tmp_path / "recorded.jsonl"
    lines = [
        json.dumps({"phase": "connect", "ts": 0.5, "result": {}}),
        # CPU-fallback rows must never be replayed as on-chip evidence.
        json.dumps({"phase": "resnet_full", "ts": 9.0,
                    "result": {"metric": "x", "value": 1.0,
                               "detail": {"platform": "cpu"}}}),
        json.dumps(recorded),
    ]
    path.write_text("\n".join(lines) + "\n")
    r = _run_bench({
        "PALLAS_AXON_POOL_IPS": "203.0.113.1",  # unreachable by design
        "TPUCFN_BENCH_RECORDED_PATH": str(path),
        "TPUCFN_BENCH_PROBE_BUDGET_S": "1",
        "TPUCFN_BENCH_PROBE_TIMEOUT_S": "5",
        "TPUCFN_BENCH_PROBE_INTERVAL_S": "1",
    })
    assert r.returncode == 0, f"stderr:\n{r.stderr[-2000:]}"
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["value"] == 3210.5
    d = rec["detail"]
    assert d["backend_mode"] == "tpu-recorded"
    assert d["platform"] == "tpu" and d["mfu"] == 0.31
    assert d["recorded"]["phase"] == "resnet_full"
    assert d["recorded"]["utc"] == "2026-07-29T00:00:00Z"
