"""Disaggregated input plane (ISSUE 11): wire protocol, service/client
parity with the local loaders, failover + degrade-to-local at the exact
cursor, backpressure bounds, and the data_wait-driven prefetch
controller.  Everything here is numpy + sockets on localhost — no jax,
sub-second per test."""

import itertools
import threading
import time

import numpy as np
import pytest

from tpucfn.data import write_dataset_shards
from tpucfn.data.pipeline import MultiProcessLoader, ShardedDataset
from tpucfn.data.service import (
    AdaptivePrefetcher,
    InputService,
    PrefetchController,
    ResilientBatchStream,
    ServiceBatchStream,
    ServiceError,
    decode_batch,
    encode_batch,
    input_addrs_from_env,
)


def _shards(tmp_path, n=48, num_shards=6, dim=3):
    rs = np.random.RandomState(0)
    examples = [{"x": rs.randn(dim).astype(np.float32),
                 "uid": np.int32(i)} for i in range(n)]
    return write_dataset_shards(iter(examples), tmp_path,
                                num_shards=num_shards)


def _local(shards, trainer=0, pc=1, batch=4, seed=3, **kw):
    return ShardedDataset(shards, batch_size_per_process=batch, seed=seed,
                          process_index=trainer, process_count=pc, **kw)


def _assert_streams_equal(got, ref):
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        assert sorted(a) == sorted(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


# -- wire protocol ----------------------------------------------------------

def test_encode_decode_roundtrip_dtypes_shapes_and_writability():
    b = {"img": np.arange(24, dtype=np.uint8).reshape(2, 3, 4),
         "x": np.linspace(0, 1, 6, dtype=np.float32).reshape(3, 2),
         "label": np.int32(7),             # 0-d must stay 0-d
         "mask": np.array([True, False])}
    d = decode_batch(encode_batch(b))
    assert sorted(d) == sorted(b)
    for k in b:
        assert d[k].dtype == np.asarray(b[k]).dtype
        assert d[k].shape == np.asarray(b[k]).shape
        np.testing.assert_array_equal(d[k], b[k])
    d["x"][0, 0] = 42.0  # decoded arrays are writable, like local batches


def test_encode_handles_noncontiguous_input():
    b = {"x": np.arange(24, dtype=np.float64).reshape(4, 6).T}
    np.testing.assert_array_equal(decode_batch(encode_batch(b))["x"],
                                  b["x"])


def test_decode_rejects_torn_payloads():
    payload = encode_batch({"x": np.ones(8, np.float32)})
    with pytest.raises(ServiceError, match="torn|truncated"):
        decode_batch(payload[: len(payload) - 5])
    with pytest.raises(ServiceError):
        decode_batch(b"\x01")


def test_input_addrs_from_env():
    assert input_addrs_from_env({}) == []
    assert input_addrs_from_env(
        {"TPUCFN_INPUT_ADDRS": "h1:7641, h2:7642 ,"}) == \
        ["h1:7641", "h2:7642"]


# -- service <-> local parity ----------------------------------------------

def test_served_stream_matches_local_sharded_dataset(tmp_path):
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=2, batch_size_per_process=4,
                      seed=3, host="127.0.0.1") as svc:
        for trainer in (0, 1):
            got = list(ServiceBatchStream(
                svc.address, trainer, process_count=2, batch_size=4,
                seed=3, num_epochs=2))
            ref = list(_local(shards, trainer, pc=2).batches(2))
            _assert_streams_equal(got, ref)
    m = svc.registry.varz()["metrics"]
    assert m["input_batches_streamed_total"] == len(got) * 2
    assert m["input_bytes_streamed_total"] > 0
    assert m["input_connections_total"] == 2


def test_served_stream_matches_multiprocess_loader(tmp_path):
    """mp_workers>0 runs the stream through MultiProcessLoader — the
    stage an input host exists to scale — and the sequence must equal
    the local MultiProcessLoader's for the same identity."""
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=1, mp_workers=2, host="127.0.0.1") as svc:
        got = list(ServiceBatchStream(svc.address, 0, process_count=1,
                                      batch_size=4, seed=1, num_epochs=1))
    with MultiProcessLoader(shards, num_workers=2, process_index=0,
                            process_count=1, batch_size_per_process=4,
                            seed=1) as loader:
        ref = list(loader.batches(1))
    _assert_streams_equal(got, ref)


def test_start_batch_skips_but_preserves_the_stream(tmp_path):
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=3, host="127.0.0.1") as svc:
        ref = list(_local(shards).batches(1))
        got = list(ServiceBatchStream(svc.address, 0, process_count=1,
                                      batch_size=4, seed=3, num_epochs=1,
                                      start_batch=5))
    _assert_streams_equal(got, ref[5:])


def test_handshake_refuses_mismatched_identity(tmp_path):
    """The determinism contract's loud half: a trainer whose fleet
    size / batch / seed disagrees must be refused (it would otherwise
    train on a different sequence than its local fallback)."""
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=2, batch_size_per_process=4,
                      seed=3, host="127.0.0.1") as svc:
        for kw, pat in (
                (dict(process_count=3), "fleet size"),
                (dict(process_count=2, batch_size=8), "batch_size"),
                (dict(process_count=2, seed=9), "seed"),
        ):
            with pytest.raises(ServiceError, match=pat):
                next(iter(ServiceBatchStream(svc.address, 0,
                                             num_epochs=1, **kw)))
        with pytest.raises(ServiceError, match="out of range"):
            next(iter(ServiceBatchStream(svc.address, 7, process_count=2,
                                         num_epochs=1)))
    assert svc.registry.varz()["metrics"]["input_stream_errors_total"] >= 4


def test_queue_depth_stays_bounded_under_slow_consumer(tmp_path):
    """Backpressure: a trainer that never reads must not grow the
    service's memory past queue_batches (+ the socket buffers)."""
    shards = _shards(tmp_path, n=96, num_shards=6)
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=0, queue_batches=2, host="127.0.0.1") as svc:
        stream = ServiceBatchStream(svc.address, 0, process_count=1,
                                    batch_size=4, seed=0, num_epochs=4)
        next(stream)  # handshake done, producer running
        time.sleep(0.25)  # consumer stalls; producer must block, not grow
        depth = svc.registry.varz()["metrics"]["input_queue_depth"]
        assert depth <= 2, depth
        stream.close()


# -- failover + degradation -------------------------------------------------

def test_resilient_stream_fails_over_to_second_input_host(tmp_path):
    shards = _shards(tmp_path)
    ref = list(_local(shards).batches(1))
    svc_a = InputService(shards, num_trainers=1, batch_size_per_process=4,
                         seed=3, host="127.0.0.1").start()
    svc_b = InputService(shards, num_trainers=1, batch_size_per_process=4,
                         seed=3, host="127.0.0.1").start()
    try:
        stream = ResilientBatchStream(
            [svc_a.address, svc_b.address], 0,
            local_factory=lambda skip: itertools.islice(
                _local(shards).batches(1), skip, None),
            process_count=1, batch_size=4, seed=3, num_epochs=1)
        got = [next(stream) for _ in range(3)]
        svc_a.close()  # the primary dies mid-stream
        got += list(stream)
    finally:
        svc_a.close()
        svc_b.close()
    _assert_streams_equal(got, ref)
    assert not stream.degraded  # host B absorbed it


def test_resilient_stream_degrades_to_local_bit_identically(tmp_path):
    """The acceptance property in miniature: kill the only input host
    mid-stream — the continuation comes from the LOCAL loader at the
    exact cursor and the full sequence equals the uninterrupted one."""
    shards = _shards(tmp_path)
    ref = list(_local(shards).batches(2))
    svc = InputService(shards, num_trainers=1, batch_size_per_process=4,
                       seed=3, host="127.0.0.1").start()
    reasons = []
    stream = ResilientBatchStream(
        [svc.address], 0,
        local_factory=lambda skip: itertools.islice(
            _local(shards).batches(2), skip, None),
        process_count=1, batch_size=4, seed=3, num_epochs=2,
        on_degrade=reasons.append)
    got = [next(stream) for _ in range(4)]
    svc.close()
    got += list(stream)
    _assert_streams_equal(got, ref)
    assert stream.degraded and len(reasons) == 1


def test_resilient_stream_pop_link_pairs_fifo_with_batches(tmp_path):
    """Span causality (ISSUE 20): one pop_link() per consumed batch, in
    FIFO order, surviving the degrade-to-local seam — remote batches
    yield the server's (cursor, span_id, origin) context, local ones
    yield None, and cursors stay strictly sequential."""
    from tpucfn.obs.trace import Tracer, origin_id, read_trace_file

    shards = _shards(tmp_path)
    tracer = Tracer(tmp_path / "trace", host_id=9, role="input")
    svc = InputService(shards, num_trainers=1, batch_size_per_process=4,
                       seed=3, host="127.0.0.1", tracer=tracer).start()
    stream = ResilientBatchStream(
        [svc.address], 0,
        local_factory=lambda skip: itertools.islice(
            _local(shards).batches(2), skip, None),
        process_count=1, batch_size=4, seed=3, num_epochs=2)
    links = []
    for _ in range(4):  # remote half
        next(stream)
        links.append(stream.pop_link())
    svc.close()
    tracer.close()
    for _ in stream:  # local continuation
        links.append(stream.pop_link())
    assert stream.degraded
    remote = [l for l in links if l is not None]
    assert len(remote) >= 4 and links[:len(remote)] == remote
    # server cursors are 1-based and strictly sequential in FIFO order
    assert [c for c, _sid, _org in remote] == list(range(1, len(remote) + 1))
    assert all(org == origin_id("input", 9) for _c, _sid, org in remote)
    assert all(l is None for l in links[len(remote):])
    # every handed-out link names a real input_serve span on the server
    served = {e["span_id"] for e in read_trace_file(
        tmp_path / "trace" / "trace-input-host009.jsonl")
        if e.get("name") == "input_serve"}
    assert {sid for _c, sid, _org in remote} <= served


def test_resilient_stream_with_no_reachable_host_goes_local(tmp_path):
    shards = _shards(tmp_path)
    ref = list(_local(shards).batches(1))
    stream = ResilientBatchStream(
        ["127.0.0.1:1"], 0,  # nothing listens on port 1
        local_factory=lambda skip: itertools.islice(
            _local(shards).batches(1), skip, None),
        process_count=1, batch_size=4, seed=3, num_epochs=1,
        connect_timeout_s=0.5, connect_retry_s=0.0)
    got = list(stream)
    _assert_streams_equal(got, ref)
    assert stream.degraded


def test_trainers_spread_across_input_hosts():
    """Trainer i's PRIMARY is addrs[i % n] (load spreads), with the
    remaining hosts as its failover order."""
    addrs = ["a:1", "b:2", "c:3"]
    for trainer in range(6):
        s = ResilientBatchStream(addrs, trainer,
                                 local_factory=lambda skip: iter(()))
        assert s._addrs[0] == addrs[trainer % 3]
        assert sorted(s._addrs) == sorted(addrs)


# -- adaptive prefetch ------------------------------------------------------

def test_controller_deepens_while_input_bound_and_decays_idle():
    c = PrefetchController(min_depth=1, max_depth=16, deepen_share=0.05,
                           shrink_share=0.01, window=4)
    # input-bound: waits dominate -> depth climbs toward max
    for _ in range(10):
        c.observe(wait_s=0.05, busy_s=0.05)
    assert c.depth == 16
    # healthy: zero waits over full windows -> decay to min, one per window
    for _ in range(16 * 4):
        c.observe(wait_s=0.0, busy_s=0.1)
    assert c.depth == 1


def test_controller_holds_depth_in_the_dead_band():
    c = PrefetchController(min_depth=2, max_depth=8, deepen_share=0.5,
                           shrink_share=0.0, window=4)
    c.depth = 4
    for _ in range(20):
        c.observe(wait_s=0.01, busy_s=0.09)  # 10% share: inside the band
    assert c.depth == 4


def test_controller_validates_bounds():
    with pytest.raises(ValueError):
        PrefetchController(min_depth=0)
    with pytest.raises(ValueError):
        PrefetchController(deepen_share=0.01, shrink_share=0.5)


def test_adaptive_prefetcher_yields_everything_in_order():
    src = [{"x": np.full(4, i, np.float32)} for i in range(20)]
    got = list(AdaptivePrefetcher(iter(src)))
    _assert_streams_equal(got, src)


def test_adaptive_prefetcher_propagates_source_errors():
    def bad():
        yield {"x": np.ones(2, np.float32)}
        raise RuntimeError("input host exploded")

    it = AdaptivePrefetcher(bad())
    next(it)
    with pytest.raises(RuntimeError, match="exploded"):
        list(it)


def test_adaptive_prefetcher_respects_byte_bound():
    produced = []

    def src():
        for i in range(100):
            produced.append(i)
            yield {"x": np.zeros(1024, np.float32)}  # 4 KiB each

    ctl = PrefetchController(min_depth=8, max_depth=8)
    it = AdaptivePrefetcher(src(), controller=ctl, max_bytes=3 * 4096)
    next(it)
    time.sleep(0.2)  # producer runs ahead as far as the bound allows
    # depth allows 8 buffered, the byte bound allows ~3 (+1 in flight)
    assert len(produced) <= 6, produced
    it.close()


def test_adaptive_prefetcher_exports_depth_gauge():
    from tpucfn.obs.registry import MetricRegistry

    r = MetricRegistry()
    it = AdaptivePrefetcher(iter([{"x": np.ones(2, np.float32)}]),
                            registry=r)
    list(it)
    assert r.varz()["metrics"]["input_prefetch_depth"] >= 1.0


def test_service_close_is_idempotent_and_unblocks_clients(tmp_path):
    shards = _shards(tmp_path)
    svc = InputService(shards, num_trainers=1, batch_size_per_process=4,
                       seed=0, host="127.0.0.1").start()
    stream = ServiceBatchStream(svc.address, 0, process_count=1,
                                batch_size=4, seed=0)  # unbounded epochs
    next(stream)

    t = threading.Thread(target=svc.close)
    t.start()
    with pytest.raises((ServiceError, StopIteration)):
        for _ in range(10_000):
            next(stream)
    t.join(timeout=10)
    assert not t.is_alive()
    svc.close()  # second close is a no-op


def test_request_close_is_noticed_by_wait_idle(tmp_path):
    shards = _shards(tmp_path)
    svc = InputService(shards, num_trainers=1, batch_size_per_process=4,
                       seed=0, host="127.0.0.1").start()
    t = threading.Thread(target=svc.wait_idle)
    t.start()
    time.sleep(0.1)
    svc.request_close()  # the SIGTERM-handler form: one plain store
    t.join(timeout=5)
    assert not t.is_alive()
    svc.close()


def test_mp_workers_and_thread_workers_are_exclusive(tmp_path):
    """The CLI always forwards num_workers (default 0): mp_workers must
    tolerate the 0 and REFUSE a real double-configuration (caught by the
    jax-blocked `tpucfn data serve --mp-workers` verify drive)."""
    shards = _shards(tmp_path)
    # the CLI shape: num_workers=0 alongside mp_workers is fine
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=1, mp_workers=2, num_workers=0,
                      host="127.0.0.1") as svc:
        got = list(ServiceBatchStream(svc.address, 0, process_count=1,
                                      batch_size=4, seed=1, num_epochs=1))
    assert got  # the stream actually ran through MultiProcessLoader
    with pytest.raises(ValueError, match="mutually exclusive"):
        InputService(shards, num_trainers=1, batch_size_per_process=4,
                     mp_workers=2, num_workers=4)


def test_server_num_epochs_bound_applies_when_client_defers(tmp_path):
    """Every shipped client sends num_epochs=None ('no opinion'): the
    service's --num-epochs bound must still apply, or the configured
    epoch cap is dead config and streams never end."""
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=3, num_epochs=1, host="127.0.0.1") as svc:
        got = list(ServiceBatchStream(svc.address, 0, process_count=1,
                                      batch_size=4, seed=3,
                                      num_epochs=None))
    _assert_streams_equal(got, list(_local(shards).batches(1)))


def test_adaptive_prefetcher_repeated_next_keeps_raising():
    it = AdaptivePrefetcher(iter([{"x": np.ones(2, np.float32)}]))
    assert len(list(it)) == 1
    for _ in range(3):  # iterator protocol: exhausted stays exhausted
        with pytest.raises(StopIteration):
            next(it)


def test_service_prunes_finished_streams(tmp_path):
    """Dead _Stream objects must not accumulate per connection ever
    accepted — a week of reconnect churn is a memory leak otherwise."""
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=3, host="127.0.0.1") as svc:
        for _ in range(5):
            list(ServiceBatchStream(svc.address, 0, process_count=1,
                                    batch_size=4, seed=3, num_epochs=1))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with svc._lock:
                if all(s.done.is_set() for s in svc._streams):
                    break
            time.sleep(0.05)
        # one more accept prunes everything the churn left behind
        list(ServiceBatchStream(svc.address, 0, process_count=1,
                                batch_size=4, seed=3, num_epochs=1))
        with svc._lock:
            assert len(svc._streams) <= 2, len(svc._streams)


def test_loader_shape_mismatch_is_refused(tmp_path):
    """A service running MultiProcessLoader streams (merge order depends
    on worker count) must refuse a client whose declared FALLBACK is the
    plain loader — the degrade handoff would swap permutations."""
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=1, mp_workers=2, host="127.0.0.1") as svc:
        with pytest.raises(ServiceError, match="loader shape"):
            next(iter(ServiceBatchStream(svc.address, 0, process_count=1,
                                         batch_size=4, seed=1,
                                         num_epochs=1, mp_workers=0)))
        # a matching declaration streams fine
        got = list(ServiceBatchStream(svc.address, 0, process_count=1,
                                      batch_size=4, seed=1, num_epochs=1,
                                      mp_workers=2))
    assert got


def test_adaptive_prefetcher_close_with_empty_buffer_unblocks_next():
    """close() racing an empty buffer must end the iteration, not leave
    next() waiting forever on an END sentinel that will never come."""
    def slow():
        while True:
            time.sleep(0.05)
            yield {"x": np.ones(2, np.float32)}

    it = AdaptivePrefetcher(slow())
    next(it)
    done = threading.Event()

    def consume():
        try:
            for _ in it:
                pass
        except Exception:
            pass
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.1)
    it.close()
    assert done.wait(timeout=5), "consumer still blocked after close()"


def test_clean_disconnect_is_not_a_stream_error(tmp_path):
    """The shipped integration ends an UNBOUNDED stream by just
    disconnecting — input_stream_errors_total must stay 0 or every
    healthy run trips the alerting metric."""
    shards = _shards(tmp_path)
    with InputService(shards, num_trainers=1, batch_size_per_process=4,
                      seed=3, host="127.0.0.1") as svc:
        stream = ServiceBatchStream(svc.address, 0, process_count=1,
                                    batch_size=4, seed=3)  # unbounded
        next(stream)
        stream.close()  # the trainer reached its step target and left
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if not svc._live_streams():
                break
            time.sleep(0.05)
        assert svc.registry.varz()["metrics"][
            "input_stream_errors_total"] == 0
