"""obs.aggregate on adversarial input (ISSUE 5 satellite): torn/partial
JSONL lines, empty dirs, hosts that never emitted a terminal event —
the views must skip-and-count, never raise.  Plus the two new
aggregation primitives: per-host clock-skew estimation and the
incremental JSONL tailer behind ``tpucfn obs --watch``."""

import json

import pytest

from tpucfn.obs.aggregate import (
    JsonlTailer,
    apply_clock_skew,
    estimate_clock_skew,
    read_metrics_dir,
    request_breakdown,
)


# ---- adversarial input ---------------------------------------------------

def test_read_metrics_dir_tolerates_torn_and_empty(tmp_path):
    (tmp_path / "train-host000.jsonl").write_text(
        json.dumps({"step": 1, "step_time": 0.1}) + "\n"
        + '{"step": 2, "step_ti')  # torn mid-append
    (tmp_path / "train-host001.jsonl").write_text("")  # host died at boot
    by_host = read_metrics_dir(tmp_path)
    assert by_host["train-host000"] == [{"step": 1, "step_time": 0.1}]
    assert by_host["train-host001"] == []


def test_read_metrics_dir_missing_dir_is_empty(tmp_path):
    assert read_metrics_dir(tmp_path / "never-created") == {}


def test_request_breakdown_host_without_request_done():
    """A host that crashed before any request finished must still yield
    rows for what it saw — and the aggregate counts completion, it does
    not raise on the absent terminal events."""
    events = [
        # host 0: complete lifecycle
        {"kind": "span", "name": "queue_wait", "trace_id": 0, "host": 0,
         "dur_s": 0.1},
        {"kind": "span", "name": "prefill", "trace_id": 0, "host": 0,
         "dur_s": 0.2, "attrs": {}},
        {"kind": "event", "name": "request_done", "trace_id": 0, "host": 0,
         "attrs": {"outcome": "ok", "latency_s": 0.5, "ttft_s": 0.3,
                   "generated": 4}},
        # host 1: prefill observed, process died before request_done
        {"kind": "span", "name": "queue_wait", "trace_id": 0, "host": 1,
         "dur_s": 0.4},
        {"kind": "span", "name": "prefill", "trace_id": 0, "host": 1,
         "dur_s": 0.2, "attrs": {}},
    ]
    rows, agg = request_breakdown(events)
    assert agg["requests"] == 2 and agg["completed"] == 1
    orphan = next(r for r in rows if r["host"] == 1)
    assert orphan["outcome"] is None and orphan["total_s"] is None
    assert orphan["queue_wait_s"] == 0.4
    # percentile aggregates skip the Nones instead of raising
    assert agg["total_s"]["p50"] == 0.5


def test_request_breakdown_empty_and_garbage_events():
    rows, agg = request_breakdown([])
    assert rows == [] and agg["requests"] == 0
    rows, agg = request_breakdown([{"unrelated": True}, {"name": "decode_round"}])
    assert rows == []


# ---- clock skew ----------------------------------------------------------

def test_skew_from_heartbeats_and_apply(tmp_path):
    # host 1's wall clock runs 2 s ahead: same-step beats, +2 s stamps
    hbs = {0: [{"seq": k, "step": k, "t": 100.0 + k} for k in range(1, 6)],
           1: [{"seq": k, "step": k, "t": 102.0 + k} for k in range(1, 6)]}
    skew = estimate_clock_skew([], hbs)
    assert skew["host0"] == pytest.approx(-1.0)
    assert skew["host1"] == pytest.approx(1.0)  # offsets vs pairwise median
    assert skew["host1"] - skew["host0"] == pytest.approx(2.0)
    # ordering after correction: host1's event at ts=103.4 actually
    # happened BEFORE host0's at ts=102.6 once skew is removed
    events = [{"name": "a", "host": 0, "ts": 102.6},
              {"name": "b", "host": 1, "ts": 103.4}]
    adj = apply_clock_skew(events, skew)
    assert [e["name"] for e in adj] == ["b", "a"]
    assert adj[0]["ts_adj"] == pytest.approx(102.4)


def test_skew_from_lockstep_step_spans():
    events = []
    for step in range(1, 5):
        events.append({"kind": "span", "name": "step", "trace_id": step,
                       "host": 0, "ts": 10.0 + step})
        events.append({"kind": "span", "name": "step", "trace_id": step,
                       "host": 1, "ts": 10.5 + step})
    skew = estimate_clock_skew(events)
    assert skew["host1"] - skew["host0"] == pytest.approx(0.5)


def test_skew_survives_heartbeat_seq_restart():
    """HeartbeatWriter restarts seq from 1 per incarnation while
    appending to the same file, and a restarted trainer REWINDS its
    step: post-restart re-runs of the same steps must not overwrite
    the launch-time reference points (they would read as tens of
    seconds of phantom skew on the restarted host)."""
    base = {0: [{"seq": k, "step": k, "t": 100.0 + k}
                for k in range(1, 6)],
            1: [{"seq": k, "step": k, "t": 100.5 + k}
                for k in range(1, 6)]}
    # host 1 solo-restarts 30 s later, rewound to step 1: seqs 1..3
    # again, steps 1..3 re-run, +30 s stamps
    base[1] = base[1] + [{"seq": k, "step": k, "t": 130.0 + k}
                         for k in range(1, 4)]
    skew = estimate_clock_skew([], base)
    # true skew is 0.5 s, not ~30: incarnation-2 points match no peer
    # and are dropped instead of overwriting incarnation 1's
    assert skew["host1"] - skew["host0"] == pytest.approx(0.5)


def test_skew_ignores_writer_start_stagger():
    """Perfectly synced clocks, but host 1's writer started 3 s later
    (slower jax import): pairing beats by seq would read the stagger as
    ±1.5 s of phantom skew and actively MIS-order correct timestamps.
    Step-keyed pairing is start-invariant — skew must come out ~0."""
    hbs = {0: [{"seq": k, "step": k, "t": 100.0 + k}
               for k in range(1, 8)],
           # same true beat times for the same steps, but seq shifted:
           # host 1 booted 3 s late, its seq k is host 0's seq k+3
           1: [{"seq": k - 3, "step": k, "t": 100.0 + k}
               for k in range(4, 8)]}
    skew = estimate_clock_skew([], hbs)
    assert skew["host1"] - skew["host0"] == pytest.approx(0.0)


def test_skew_heartbeats_without_steps_fall_back_to_spans():
    """Beats with no step (a serve host, or a loop that never called
    update_step) carry no fleet-simultaneous anchor — seq pairing would
    measure start stagger, so they contribute nothing and the lockstep
    step spans decide."""
    hbs = {0: [{"seq": k, "t": 100.0 + k} for k in range(1, 6)],
           1: [{"seq": k, "t": 103.0 + k} for k in range(1, 6)]}
    events = []
    for step in (1, 2, 3):
        events.append({"kind": "span", "name": "step", "trace_id": step,
                       "host": 0, "ts": 10.0 + step})
        events.append({"kind": "span", "name": "step", "trace_id": step,
                       "host": 1, "ts": 10.5 + step})
    skew = estimate_clock_skew(events, hbs)
    assert skew["host1"] - skew["host0"] == pytest.approx(0.5)


def test_skew_single_host_heartbeats_falls_back_to_spans():
    # one usable hb file is NOT a cross-host reference (the peer's file
    # is missing/torn); lockstep step spans must still give an estimate
    events = []
    for step in (1, 2, 3):
        events.append({"kind": "span", "name": "step", "trace_id": step,
                       "host": 0, "ts": 10.0 + step})
        events.append({"kind": "span", "name": "step", "trace_id": step,
                       "host": 1, "ts": 10.5 + step})
    hb = {0: [{"seq": k, "t": 100.0 + k} for k in range(1, 4)]}
    skew = estimate_clock_skew(events, hb)
    assert skew["host1"] - skew["host0"] == pytest.approx(0.5)


def test_skew_single_host_and_no_data():
    assert estimate_clock_skew([]) == {}
    one = estimate_clock_skew([{"kind": "span", "name": "step",
                               "trace_id": 1, "host": 0, "ts": 5.0}])
    assert one == {"host0": 0.0}


# ---- the incremental tailer ---------------------------------------------

def test_tailer_reads_incrementally_and_tolerates_torn_tail(tmp_path):
    p = tmp_path / "a.jsonl"
    p.write_text(json.dumps({"i": 1}) + "\n")
    t = JsonlTailer()
    assert t.poll([p]) == {p: [{"i": 1}]}
    assert t.poll([p]) == {}  # nothing new -> no re-read from byte 0

    # a torn tail is NOT consumed...
    with open(p, "a") as f:
        f.write(json.dumps({"i": 2}) + "\n" + '{"i": 3')
    assert t.poll([p]) == {p: [{"i": 2}]}
    # ...and is delivered whole once the writer finishes the line
    with open(p, "a") as f:
        f.write("}\n")
    assert t.poll([p]) == {p: [{"i": 3}]}


def test_tailer_counts_garbage_and_resets_on_truncation(tmp_path):
    p = tmp_path / "a.jsonl"
    p.write_text("not json\n" + json.dumps({"i": 1}) + "\n")
    t = JsonlTailer()
    assert t.poll([p]) == {p: [{"i": 1}]}
    assert t.skipped == 1
    assert t.truncated == set()
    # rotation: file restarts smaller than the old offset — re-delivered
    # from byte 0 AND flagged, so accumulating callers drop stale state
    p.write_text(json.dumps({"i": 9}) + "\n")
    assert t.poll([p]) == {p: [{"i": 9}]}
    assert t.truncated == {p}
    # the flag is per-poll, not sticky
    assert t.poll([p]) == {} and t.truncated == set()
    # missing files are skipped silently
    assert t.poll([tmp_path / "gone.jsonl"]) == {}


def test_tailer_truncation_offset_persists_without_complete_line(tmp_path):
    """A truncation observed on a poll that consumes NO complete line
    (file emptied, or regrown tail still torn) must still reset the
    stored offset: if the stale offset survived, a file that later
    regrows PAST it would resume mid-stream and silently drop the new
    file's head."""
    p = tmp_path / "a.jsonl"
    p.write_text(json.dumps({"i": 1}) + "\n" + json.dumps({"i": 2}) + "\n")
    t = JsonlTailer()
    assert t.poll([p]) == {p: [{"i": 1}, {"i": 2}]}
    old_size = p.stat().st_size

    p.write_text("")  # rotation step 1: truncate to empty
    assert t.poll([p]) == {}  # nothing to deliver...
    assert t.truncated == {p}  # ...but the restart IS flagged

    # rotation step 2: regrow past the old offset before the next poll
    rows = [{"i": k} for k in range(10, 20)]
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    assert p.stat().st_size > old_size
    assert t.poll([p]) == {p: rows}  # the whole new file, not a mid-cut


def test_tailer_detects_regrow_past_offset_in_one_tick(tmp_path):
    """Truncate-then-regrow PAST the stored offset between two polls:
    the size never dips below the offset, so only the head-bytes
    signature betrays the swap.  Without it the tailer resumes
    mid-stream inside the NEW file and fuses two runs' records."""
    p = tmp_path / "a.jsonl"
    old = [{"run": 1, "i": k} for k in range(3)]
    p.write_text("".join(json.dumps(r) + "\n" for r in old))
    t = JsonlTailer()
    assert t.poll([p]) == {p: old}
    off = p.stat().st_size

    # restart: truncate + regrow past the old offset before any poll
    new = [{"run": 2, "ts": 999.125, "i": k} for k in range(5)]
    p.write_text("".join(json.dumps(r) + "\n" for r in new))
    assert p.stat().st_size > off
    assert t.poll([p]) == {p: new}  # whole new file, not a mid-cut
    assert t.truncated == {p}  # accumulating callers drop run-1 state
    # steady state afterwards: appends tail normally
    with open(p, "a") as f:
        f.write(json.dumps({"run": 2, "i": 99}) + "\n")
    assert t.poll([p]) == {p: [{"run": 2, "i": 99}]}
    assert t.truncated == set()


def test_select_skew_reference_beats_shared_rule():
    """The compaction rule is the estimator's selection rule (one
    shared function) and is idempotent: re-running it over an already
    selected stream must keep every beat, or watch-mode compaction
    would starve estimate_clock_skew."""
    from tpucfn.obs.aggregate import select_skew_reference_beats

    beats = ([{"seq": s, "t": 100.0 + s, "step": (s // 3) * 3}
              for s in range(1, 10)]
             + [{"seq": 1, "t": 130.0, "step": 6}]  # restart incarnation
             + [{"seq": 2, "t": 130.5, "step": 6},
                {"seq": 3, "t": 131.0, "step": 9},
                {"seq": 4, "t": 131.5},  # no step: never a reference
                {"seq": "x", "t": 132.0}, {"seq": 5}])  # malformed
    kept, state = select_skew_reference_beats(beats)
    assert [(r["seq"], r.get("step")) for r in kept] == [
        (1, 0), (3, 3), (6, 6), (9, 9), (1, 6), (3, 9)]
    again, _ = select_skew_reference_beats(kept)
    assert again == kept  # idempotent
    # incremental threading matches the one-shot result
    inc, st = [], (None, None)
    for i in range(0, len(beats), 2):
        k, st = select_skew_reference_beats(beats[i:i + 2], st)
        inc.extend(k)
    assert inc == kept and st == state


def test_apply_clock_skew_mono_breaks_same_instant_ties():
    # two same-host writes with colliding reconstructed wall times:
    # mono (strictly ordered within a process) decides, however the
    # input was ordered; events without mono sort after their tie.
    events = [{"name": "late", "host": 0, "ts": 50.0, "mono": 7.2},
              {"name": "early", "host": 0, "ts": 50.0, "mono": 7.1},
              {"name": "nomono", "host": 0, "ts": 50.0}]
    adj = apply_clock_skew(events, {"host0": 0.0})
    assert [e["name"] for e in adj] == ["early", "late", "nomono"]


def test_obs_watch_state_drops_rotated_file_records(tmp_path):
    """cmd_obs accumulates per-file records across --watch ticks; a
    rotated (truncated) file must REPLACE its accumulated records, not
    double-count them (the tailer re-delivers from byte 0).  --watch
    loops forever, so the accumulate-with-reset contract is exercised
    exactly as cmd_obs wires it."""
    f = tmp_path / "train-host000.jsonl"
    f.write_text(json.dumps({"step": 1, "step_time": 0.1}) + "\n"
                 + json.dumps({"step": 2, "step_time": 0.1}) + "\n")
    t = JsonlTailer()
    by_host = {}
    new = t.poll([f])
    for p in t.truncated:
        by_host.pop(p.stem, None)
    for p, recs in new.items():
        by_host.setdefault(p.stem, []).extend(recs)
    assert len(by_host["train-host000"]) == 2
    f.write_text(json.dumps({"step": 1, "step_time": 0.2}) + "\n")  # rotated
    new = t.poll([f])
    for p in t.truncated:
        by_host.pop(p.stem, None)
    for p, recs in new.items():
        by_host.setdefault(p.stem, []).extend(recs)
    assert by_host["train-host000"] == [{"step": 1, "step_time": 0.2}]


def test_obs_cli_watch_path_uses_incremental_state(tmp_path, capsys):
    """The --watch plumbing through cmd_obs: a second pass over an
    APPENDED log must include the new rows (accumulated incrementally,
    not re-read) — exercised via two sequential main() calls sharing
    one process-level tailer is impossible, so drive one_pass twice via
    --watch=0 by appending between two direct invocations."""
    from tpucfn.cli.main import main

    logs = tmp_path / "logs"
    logs.mkdir()
    (logs / "train-host000.jsonl").write_text(
        json.dumps({"step": 1, "step_time": 0.1}) + "\n")
    rc = main(["obs", "--run-dir", str(tmp_path), "--json"])
    assert rc == 0
    rep = json.loads(capsys.readouterr().out)
    assert len(rep["timeline"]) == 1


def test_window_events_filters_on_ts_adj():
    from tpucfn.obs.aggregate import window_events

    events = [
        {"name": "a", "ts_adj": 9.0},
        {"name": "b", "ts_adj": 10.0},   # boundary: included
        {"name": "c", "ts_adj": 15.0},
        {"name": "d", "ts_adj": 20.0},   # boundary: included
        {"name": "e", "ts_adj": 20.1},
        {"name": "f", "ts_adj": None},   # unplaceable: excluded
        {"name": "g"},                   # no annotation at all
    ]
    out = window_events(events, 10.0, 20.0)
    assert [e["name"] for e in out] == ["b", "c", "d"]
    assert window_events([], 0.0, 1.0) == []


# -- control-plane timeline (ISSUE 13) ---------------------------------------

def test_control_timeline_selects_and_orders_control_spans():
    from tpucfn.obs.aggregate import CONTROL_SPAN_NAMES, control_timeline

    assert "compile_fetch" in CONTROL_SPAN_NAMES
    events = [
        {"kind": "span", "name": "step", "ts": 1.0, "dur_s": 0.1,
         "host": 0, "attrs": {}},
        {"kind": "span", "name": "compile_fetch", "ts": 3.0, "dur_s": 0.4,
         "host": 1, "role": "trainer",
         "attrs": {"key": "ab12", "addr": "h0:7741", "bytes": 123}},
        {"kind": "span", "name": "ft_recover", "ts": 2.0, "dur_s": 1.5,
         "host": None, "role": "", "trace_id": 1,
         "attrs": {"action": "gang_restart", "hosts": [1]}},
        {"kind": "event", "name": "compile_fetch", "ts": 9.0,
         "attrs": {}},  # not a span: excluded
    ]
    rows = control_timeline(events)
    assert [r["span"] for r in rows] == ["ft_recover", "compile_fetch"]
    assert "compile_fetch" in rows[1]["span"]
    assert "h0:7741" in rows[1]["detail"]
    # skew-corrected timestamps win when present
    rows2 = control_timeline([{**events[1], "ts_adj": 0.5},
                              {**events[2]}])
    assert [r["span"] for r in rows2] == ["compile_fetch", "ft_recover"]
