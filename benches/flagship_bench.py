#!/usr/bin/env python
"""Flagship perf drill (ISSUE 18 acceptance): the input-host AND
warm-start planes under one real launch fan-out, rc-gated, ONE JSON
line out in the standard BENCH row schema.

The claim being cashed: the two PR 11/13 planes compose on the
flagship path.  One `tpucfn launch`-shaped fleet — 1 input host
running the real ``tpucfn data serve`` CLI + 1 trainer + the jax-free
compile-artifact server — runs a synthetic INPUT-BOUND workload twice:

* **cold** — the trainer compiles a residual-MLP grad program (the
  compile_bench program: a real multi-second XLA:CPU compile) and
  publishes its serialized executable to the artifact server; its data
  legs measure ``prestaged_step_s`` (every batch in RAM — the floor),
  ``loader_step_s`` (local decode serializes with compute — the
  recorded stall in miniature) and ``served_step_s`` (fed by the input
  host through ``service_or_local_batches``).
* **warm** — a second fleet incarnation with a FRESH local store: its
  time-to-first-step must come from a fleet **fetch**, not a compile.

Gates (all must hold, three consecutive runs green by construction —
``--repeat N`` reruns the whole drill):

* ``served_step_s  <= 1.5 x prestaged_step_s`` (the PR 11 bound, now
  on the flagship path),
* ``warm ttfs      <= 0.35 x cold ttfs`` (the PR 13 bound, through a
  real launch fan-out),
* goodput bucket shares present in the emitted row, each in [0, 1],
  with ``data_wait`` strictly lower served than local.

Trainer children are this same file (``TPUCFN_FLAGSHIP_CHILD=1``), so
every measured number crosses real process boundaries: separate
interpreters, batches over TCP, artifacts through the server.

Usage: JAX_PLATFORMS=cpu python benches/flagship_bench.py [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


# -- the trainer child ------------------------------------------------------

class _SleepDecode:
    """Value-preserving synthetic decode cost: the local path pays it
    per example, the served stream skips it (the input host streams
    ready batches) — so the two paths yield bit-identical values while
    only the LOCAL one is input-bound."""

    def __init__(self, seconds: float):
        self.seconds = seconds

    def __call__(self, ex, rs):
        if self.seconds > 0:
            time.sleep(self.seconds)
        return ex


def child() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from tpucfn.compilecache import configure_from_env
    from tpucfn.compilecache.jit import maybe_warm
    from tpucfn.data.pipeline import ShardedDataset
    from tpucfn.data.service import service_or_local_batches
    from tpucfn.ft import HeartbeatWriter
    from tpucfn.obs.goodput import GoodputLedger

    host = int(os.environ.get("TPUCFN_HOST_ID", "0"))
    run_dir = Path(os.environ["TPUCFN_FLAGSHIP_RUN_DIR"])
    shards_dir = Path(os.environ["TPUCFN_FLAGSHIP_SHARDS"])
    layers = int(os.environ["TPUCFN_FLAGSHIP_LAYERS"])
    width = int(os.environ["TPUCFN_FLAGSHIP_WIDTH"])
    batch = int(os.environ["TPUCFN_FLAGSHIP_BATCH"])
    batches = int(os.environ["TPUCFN_FLAGSHIP_BATCHES"])
    compute_s = float(os.environ["TPUCFN_FLAGSHIP_COMPUTE_S"])
    decode_s = float(os.environ["TPUCFN_FLAGSHIP_DECODE_S"])

    hb = None
    ft_dir = os.environ.get("TPUCFN_FT_DIR", "").strip()
    if ft_dir:
        hb = HeartbeatWriter(
            ft_dir, host_id=host, role="trainer",
            interval_s=float(
                os.environ.get("TPUCFN_FT_HEARTBEAT_S", "0.2") or 0.2)
        ).start()
    ledger = GoodputLedger(run_dir / "goodput", host_id=host, role="bench")

    try:
        # -- warm-start leg: the compile_bench program through the ----
        # -- launcher-fanned artifact plane ---------------------------
        client = configure_from_env()

        def loss(params, x):
            h = x
            for w, b in params:
                h = jnp.tanh(h @ w + b) + 0.1 * h
            return (h ** 2).mean()

        rs = np.random.RandomState(0)
        params = [(rs.randn(width, width).astype(np.float32) * 0.1,
                   np.zeros(width, np.float32)) for _ in range(layers)]
        x = rs.randn(8, width).astype(np.float32)

        t0 = time.perf_counter()  # jax imported, program built: the clock
        step_fn = maybe_warm(jax.jit(jax.grad(loss)), label="flagship")
        out = step_fn(params, x)
        jax.block_until_ready(out)
        ttfs_s = time.perf_counter() - t0
        outcome = client.last_outcome if client is not None else None
        # "store" published a FRESH compile; only "fetch" skipped one
        ledger.account(
            "compile_fetched" if outcome == "fetch" else "compile", ttfs_s)
        digest = float(sum(float(jnp.sum(w)) for w, _ in out))

        # -- data legs: prestaged floor, local loader, served ---------
        shards = sorted(shards_dir.glob("*.tpurec"))
        tf = _SleepDecode(decode_s)
        warmup = min(3, max(0, batches - 1))

        def ds():
            return ShardedDataset(
                shards, batch_size_per_process=batch, seed=0,
                cache_in_memory=False, process_index=0, process_count=1,
                transform=tf)

        def drive(it, account: bool) -> float:
            steps = []
            for i in range(batches):
                t0 = time.perf_counter()
                b = next(it)
                t_wait = time.perf_counter() - t0
                time.sleep(compute_s)
                steps.append(time.perf_counter() - t0)
                if account and i >= warmup:
                    ledger.account("data_wait", t_wait)
                    ledger.account("step", steps[-1] - t_wait)
                if hb is not None:
                    hb.update_step(i)
            s = steps[warmup:]
            return sum(s) / len(s)

        staged = list(ds().epoch(0))[:batches]
        t0 = time.perf_counter()
        for _ in staged:
            time.sleep(compute_s)
        prestaged_step_s = (time.perf_counter() - t0) / len(staged)

        loader_step_s = drive(iter(ds().batches(None)), account=False)

        served = service_or_local_batches(ds(), num_epochs=1)
        try:
            served_step_s = drive(iter(served), account=True)
        finally:
            close = getattr(served, "close", None)
            if close is not None:
                close()

        (run_dir / f"result-host{host:03d}.json").write_text(json.dumps({
            "ttfs_s": round(ttfs_s, 4),
            "outcome": outcome,
            "digest": digest,
            "prestaged_step_s": round(prestaged_step_s, 5),
            "loader_step_s": round(loader_step_s, 5),
            "served_step_s": round(served_step_s, 5),
            "used_service": bool(
                (os.environ.get("TPUCFN_INPUT_ADDRS") or "").strip()),
        }))
    finally:
        if hb is not None:
            hb.stop()
        ledger.close()
    return 0


# -- the orchestrator -------------------------------------------------------

def _write_shards(tmp: Path, n: int) -> Path:
    import numpy as np

    from tpucfn.data import write_dataset_shards

    rs = np.random.RandomState(1)
    d = tmp / "shards"
    d.mkdir()
    write_dataset_shards(
        ({"x": rs.randn(64).astype(np.float32)} for _ in range(n)),
        d, num_shards=4)
    return d


def _launch(tmp: Path, run_dir: Path, shards: Path, args,
            *, cc_addrs: str, cc_dir: Path, input_port: int) -> dict:
    """One fleet incarnation: 1 trainer + 1 input host under the real
    Launcher/GangCoordinator, compile-cache address fanned out.
    Returns the trainer's result row."""
    from tpucfn.bootstrap import EnvContract
    from tpucfn.ft import (GangCoordinator, GangRestart, HeartbeatMonitor,
                           MonitorConfig, RestartBudget)
    from tpucfn.launch import Launcher, LocalTransport

    run_dir.mkdir(parents=True, exist_ok=True)
    n = 2  # 1 trainer + 1 input host
    hostfile = run_dir / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    contract = EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(run_dir),
        generation=1)
    ft_dir = run_dir / "ft"
    serve_argv = [sys.executable, "-m", "tpucfn.cli", "data", "serve",
                  "--shards", str(shards), "--batch-size", str(args.batch),
                  "--seed", "0", "--num-epochs", "1",
                  "--host", "127.0.0.1", "--idle-exit", "2.0"]
    launcher = Launcher(
        contract, LocalTransport(),
        ft_dir=str(ft_dir), ft_heartbeat_s=0.2,
        input_hosts=1, input_port=input_port, input_argv=serve_argv,
        compile_cache_addrs=[cc_addrs],
        extra_env={
            "TPUCFN_FLAGSHIP_CHILD": "1",
            "TPUCFN_FLAGSHIP_RUN_DIR": str(run_dir),
            "TPUCFN_FLAGSHIP_SHARDS": str(shards),
            "TPUCFN_FLAGSHIP_LAYERS": str(args.layers),
            "TPUCFN_FLAGSHIP_WIDTH": str(args.width),
            "TPUCFN_FLAGSHIP_BATCH": str(args.batch),
            "TPUCFN_FLAGSHIP_BATCHES": str(args.batches),
            "TPUCFN_FLAGSHIP_COMPUTE_S": str(args.compute_ms / 1e3),
            "TPUCFN_FLAGSHIP_DECODE_S": str(args.decode_ms / 1e3),
            "TPUCFN_COMPILE_CACHE_DIR": str(cc_dir),
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        })
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=n,
        config=MonitorConfig(interval_s=0.2, startup_grace_s=120.0))
    coord = GangCoordinator(
        launcher, [sys.executable, str(Path(__file__).resolve())],
        policy=GangRestart(RestartBudget(0)), monitor=monitor,
        ft_dir=ft_dir, poll_interval=0.05, term_grace_s=5.0)
    rc = coord.run()
    if rc != 0:
        raise RuntimeError(f"fleet incarnation failed rc={rc} "
                           f"(see {ft_dir}/events.jsonl)")
    return json.loads((run_dir / "result-host000.json").read_text())


def _drill(args, round_idx: int) -> dict:
    from tpucfn.compilecache.service import ArtifactServer
    from tpucfn.obs.goodput import fleet_window_observation

    tmp = Path(tempfile.mkdtemp(prefix=f"tpucfn-flagship-r{round_idx}-"))
    try:
        shards = _write_shards(tmp, args.batches * args.batch)
        srv = ArtifactServer(tmp / "server-store", host="127.0.0.1").start()
        try:
            cold = _launch(tmp, tmp / "cold", shards, args,
                           cc_addrs=srv.address, cc_dir=tmp / "store-cold",
                           input_port=args.input_port)
            warm = _launch(tmp, tmp / "warm", shards, args,
                           cc_addrs=srv.address, cc_dir=tmp / "store-warm",
                           input_port=args.input_port + 10)
        finally:
            srv.close()

        ratio_ttfs = (warm["ttfs_s"] / cold["ttfs_s"]
                      if cold["ttfs_s"] else 1.0)
        ratio_served = (cold["served_step_s"] / cold["prestaged_step_s"]
                        if cold["prestaged_step_s"] else 0.0)
        gp = fleet_window_observation(tmp / "cold" / "goodput")
        shares = ({k: round(float(v), 4)
                   for k, v in sorted(gp["shares"].items())}
                  if gp else None)
        ok_shares = bool(
            shares is not None
            and all(0.0 <= v <= 1.0 for v in shares.values())
            and "data_wait" in shares and "idle" in shares)
        ok = (cold["used_service"] and warm["used_service"]
              and ratio_served <= args.served_ratio
              and cold["loader_step_s"]
              > cold["prestaged_step_s"] * 1.15  # the workload IS bound
              and warm["outcome"] == "fetch"  # fleet plane, not a recompile
              and warm["digest"] == cold["digest"]
              and ratio_ttfs <= args.warm_ratio
              and ok_shares)
        return {
            "ok": ok,
            "cold_time_to_first_step_s": cold["ttfs_s"],
            "warm_time_to_first_step_s": warm["ttfs_s"],
            "warm_cold_ttfs_ratio": round(ratio_ttfs, 4),
            "cold_outcome": cold["outcome"],
            "warm_outcome": warm["outcome"],
            "digest_bit_identical": warm["digest"] == cold["digest"],
            "prestaged_step_s": cold["prestaged_step_s"],
            "loader_step_s": cold["loader_step_s"],
            "served_step_s": cold["served_step_s"],
            "served_prestaged_ratio": round(ratio_served, 4),
            "goodput_shares": shares,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    if os.environ.get("TPUCFN_FLAGSHIP_CHILD") == "1":
        return child()

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--layers", type=int, default=48,
                   help="grad-program depth — sizes the cold compile")
    p.add_argument("--width", type=int, default=128)
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--batches", type=int, default=24)
    p.add_argument("--compute-ms", type=float, default=50.0)
    p.add_argument("--decode-ms", type=float, default=6.0,
                   help="synthetic per-example decode cost (local path "
                        "only — the input host streams ready batches)")
    p.add_argument("--served-ratio", type=float, default=1.5,
                   help="gate: served step <= this x prestaged")
    p.add_argument("--warm-ratio", type=float, default=0.35,
                   help="gate: warm ttfs <= this x cold ttfs")
    p.add_argument("--input-port", type=int, default=9350)
    p.add_argument("--repeat", type=int, default=1,
                   help="run the whole drill N times; every round must "
                        "gate green (the 3x-consecutive acceptance)")
    p.add_argument("--quick", action="store_true",
                   help="smaller program + fewer batches (make "
                        "bench-smoke): same gates, faster wall")
    args = p.parse_args()
    if args.quick:
        args.layers, args.batches = 24, 12

    rounds = []
    for i in range(args.repeat):
        r = _drill(args, i)
        print(f"# flagship round {i}: ok={r['ok']} "
              f"ttfs {r['cold_time_to_first_step_s']}s -> "
              f"{r['warm_time_to_first_step_s']}s "
              f"(ratio {r['warm_cold_ttfs_ratio']}, gate {args.warm_ratio}) "
              f"served/prestaged {r['served_prestaged_ratio']} "
              f"(gate {args.served_ratio})", file=sys.stderr)
        rounds.append(r)
    ok = all(r["ok"] for r in rounds)
    row = {
        "metric": "flagship_served_step_vs_prestaged",
        "value": rounds[-1]["served_prestaged_ratio"],
        "unit": "served/prestaged step time",
        "vs_baseline": 0.0,
        "detail": {
            "baseline_note": "no composed input+warm-start path existed "
                             "before ISSUE 18; the gates are the bound",
            "ok": ok,
            "rounds": len(rounds),
            **rounds[-1],
        },
    }
    print(json.dumps(row))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
