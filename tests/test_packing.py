"""Packed-sequence training: packing, cross-segment isolation, loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpucfn.data.packing import (
    pack_sequences,
    packed_attention_fn,
    packed_causal_lm_loss,
)
from tpucfn.models.llama import Llama, LlamaConfig


def test_pack_sequences_first_fit():
    seqs = [np.arange(1, 5), np.arange(10, 13), np.arange(20, 22),
            np.arange(30, 37)]
    tokens, segments = pack_sequences(seqs, seq_len=8)
    # row 0: [1..4] + [10..12] (fits, seg 2), 1 pad
    np.testing.assert_array_equal(tokens[0], [1, 2, 3, 4, 10, 11, 12, 0])
    np.testing.assert_array_equal(segments[0], [1, 1, 1, 1, 2, 2, 2, 0])
    # [20,21] doesn't fit row 0 (7 used) -> row 1; [30..36] (7 tokens)
    # fits neither row 0 nor row 1 (2 used, needs 7 -> 9 > 8) -> row 2
    assert tokens.shape == (3, 8)
    np.testing.assert_array_equal(tokens[1, :2], [20, 21])
    np.testing.assert_array_equal(segments[1], [1, 1, 0, 0, 0, 0, 0, 0])
    np.testing.assert_array_equal(tokens[2, :7], np.arange(30, 37))
    np.testing.assert_array_equal(segments[2, 7:], [0])


def test_pack_sequences_accepts_one_pass_iterator():
    """A generator input must survive the min-length pre-scan (which
    iterates twice) — the pre-scan materializes first (ADVICE r4)."""
    seqs = [np.arange(1, 5), np.arange(10, 13)]
    tokens_gen, segs_gen = pack_sequences((s for s in seqs), seq_len=8)
    tokens_list, segs_list = pack_sequences(seqs, seq_len=8)
    np.testing.assert_array_equal(tokens_gen, tokens_list)
    np.testing.assert_array_equal(segs_gen, segs_list)


def test_pack_sequences_rejects_overlong_and_empty():
    with pytest.raises(ValueError, match="exceeds"):
        pack_sequences([np.arange(9)], seq_len=8)
    with pytest.raises(ValueError, match="non-empty"):
        pack_sequences([np.array([], np.int32)], seq_len=8)


def test_packed_model_isolates_documents():
    """Perturbing document A's tokens must not change document B's
    logits (attention masked) — and pad rows change nothing."""
    cfg = LlamaConfig.tiny()
    rs = np.random.RandomState(0)
    doc_a = rs.randint(1, cfg.vocab_size, 6)
    doc_b = rs.randint(1, cfg.vocab_size, 7)
    tokens, segments = pack_sequences([doc_a, doc_b], seq_len=16)
    assert tokens.shape == (1, 16)
    toks = jnp.asarray(tokens)
    segs = jnp.asarray(segments)

    model = Llama(cfg, attention_fn=packed_attention_fn(segs))
    params = model.init(jax.random.key(0), toks)["params"]
    base = model.apply({"params": params}, toks)

    # perturb doc A (positions 0..5); doc B occupies 6..12
    toks2 = toks.at[0, 2].set((int(toks[0, 2]) + 1) % cfg.vocab_size)
    out2 = model.apply({"params": params}, toks2)
    np.testing.assert_allclose(np.asarray(out2[0, 6:13]),
                               np.asarray(base[0, 6:13]), atol=1e-6)
    # and doc A's own logits DID change (the perturbation is visible)
    assert np.abs(np.asarray(out2[0, 2:6]) -
                  np.asarray(base[0, 2:6])).max() > 1e-3

    # pad content is inert
    toks3 = toks.at[0, 14].set(42)
    out3 = model.apply({"params": params}, toks3)
    np.testing.assert_allclose(np.asarray(out3[0, :13]),
                               np.asarray(base[0, :13]), atol=1e-6)


def test_packed_causal_lm_loss_masks_boundaries():
    rs = np.random.RandomState(1)
    v = 32
    tokens = jnp.asarray(rs.randint(0, v, (1, 8)), jnp.int32)
    segments = jnp.asarray([[1, 1, 1, 2, 2, 2, 0, 0]])
    logits = jnp.asarray(rs.randn(1, 8, v), jnp.float32)

    loss, acc = packed_causal_lm_loss(logits, tokens, segments)

    import optax

    per = optax.softmax_cross_entropy_with_integer_labels(
        logits[:, :-1], tokens[:, 1:])
    # valid targets: positions 1,2 (seg1) and 4,5 (seg2) — not 3 (cross
    # boundary) and not 6,7 (pad)
    want = (per[0, 0] + per[0, 1] + per[0, 3] + per[0, 4]) / 4
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-6)
    assert 0.0 <= float(acc) <= 1.0


def test_convert_token_jsonl_cli_roundtrip(tmp_path):
    """jsonl corpus -> packed shards via the CLI -> ShardedDataset rows
    carry aligned tokens/segments."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    REPO = Path(__file__).resolve().parent.parent
    rs = np.random.RandomState(0)
    src = tmp_path / "corpus.jsonl"
    with src.open("w") as f:
        for n in (5, 9, 3, 12, 7):
            f.write(json.dumps({"tokens": rs.randint(1, 100, n).tolist()})
                    + "\n")
    out = tmp_path / "shards"
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "tpucfn.cli", "convert-dataset",
         "--kind", "token-jsonl", "--src", str(src), "--out", str(out),
         "--seq-len", "16", "--num-shards", "2"],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr

    from tpucfn.data.pipeline import ShardedDataset

    ds = ShardedDataset(sorted(out.glob("*.tpurec")),
                        batch_size_per_process=1, shuffle=False,
                        process_index=0, process_count=1)
    rows = list(ds.epoch(0))
    assert rows and set(rows[0]) == {"tokens", "segments"}
    for b in rows:
        toks, segs = b["tokens"][0], b["segments"][0]
        assert toks.shape == (16,) and segs.shape == (16,)
        # padding aligns: segment 0 exactly where tokens are pad
        assert ((segs == 0) == (toks == 0)).all() or (segs > 0).all()


def test_llama_segment_ids_kwarg_isolates_documents():
    """Model-level packed API: Llama(...).apply(..., segment_ids=segs)."""
    cfg = LlamaConfig.tiny()
    rs = np.random.RandomState(2)
    tokens, segments = pack_sequences(
        [rs.randint(1, cfg.vocab_size, 5), rs.randint(1, cfg.vocab_size, 6)],
        seq_len=12)
    toks, segs = jnp.asarray(tokens), jnp.asarray(segments)
    model = Llama(cfg)
    params = model.init(jax.random.key(0), toks)["params"]
    base = model.apply({"params": params}, toks, segment_ids=segs)
    toks2 = toks.at[0, 1].set((int(toks[0, 1]) + 1) % cfg.vocab_size)
    out2 = model.apply({"params": params}, toks2, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out2[0, 5:11]),
                               np.asarray(base[0, 5:11]), atol=1e-6)
    with pytest.raises(ValueError, match="decode"):
        Llama(cfg, decode=True).apply({"params": params}, toks,
                                      segment_ids=segs)


def test_pack_sequences_open_row_pruning_preserves_first_fit():
    # ADVICE r3: packing went O(docs x rows). The fix prunes rows whose
    # remaining capacity is below the corpus-wide min doc length; the
    # result must stay bit-identical to naive first-fit.
    import numpy as np

    from tpucfn.data.packing import pack_sequences

    rs = np.random.RandomState(0)
    seqs = [np.arange(rs.randint(3, 60), dtype=np.int32) + i
            for i in range(400)]
    tokens, segments = pack_sequences(seqs, 64)

    def naive(sequences, seq_len):
        rows, segs, counts = [], [], []
        for seq in sequences:
            for i, row in enumerate(rows):
                if len(row) + len(seq) <= seq_len:
                    counts[i] += 1
                    row.extend(int(t) for t in seq)
                    segs[i].extend([counts[i]] * len(seq))
                    break
            else:
                rows.append([int(t) for t in seq])
                segs.append([1] * len(seq))
                counts.append(1)
        tok = np.zeros((len(rows), seq_len), np.int32)
        sg = np.zeros((len(rows), seq_len), np.int32)
        for i, (row, seg) in enumerate(zip(rows, segs)):
            tok[i, :len(row)] = row
            sg[i, :len(seg)] = seg
        return tok, sg

    ref_tok, ref_seg = naive(seqs, 64)
    np.testing.assert_array_equal(tokens, ref_tok)
    np.testing.assert_array_equal(segments, ref_seg)
