"""Procedurally generated shape-classification dataset ("procgen-shapes").

The reference's de-facto integration test was "the stack comes up and
CIFAR-10 converges" (SURVEY.md §4); its README staged the real dataset
from S3. This build environment has zero egress, so no public dataset can
be downloaded — this module is the documented substitution: a procedural
10-class image-classification task that is **honestly hard**, unlike the
class-conditional-mean streams in ``synthetic.py``:

* the class signal is GEOMETRY ONLY — ten shape families rendered with
  random position, scale, rotation, foreground/background colors, a
  random background gradient, and pixel noise;
* a linear probe on raw pixels sits near chance (no fixed template, no
  color shortcut — verified in ``tests/test_shapes.py``), while a small
  CNN (ResNet-20) can reach high-90s accuracy;
* generation is deterministic in (seed, n) and runs anywhere (numpy +
  PIL), so the end-to-end accuracy run is reproducible in CI.

Two surfaces:

* :func:`synthetic_shapes` — decoded ``{"image": uint8 HWC, "label"}``
  stream for direct staging via ``write_dataset_shards``.
* :func:`write_shapes_image_tree` — a ``root/class_name/img.png`` tree,
  the torchvision/ImageNet layout, so the END-TO-END path exercises the
  real ``tpucfn convert-dataset --kind image-tree`` → encoded shards →
  host-side decode pipeline, exactly as a user's real dataset would
  (SURVEY.md §2.1 S3-staging row).
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator

import numpy as np

SHAPE_CLASSES = (
    "disk", "ring", "triangle", "square", "pentagon",
    "star5", "star6", "cross", "crescent", "twodisks",
)


def _poly_points(cx: float, cy: float, r: float, n: int, rot: float):
    ang = rot + np.arange(n) * 2.0 * np.pi / n
    return [(cx + r * np.cos(a), cy + r * np.sin(a)) for a in ang]


def _star_points(cx: float, cy: float, r: float, points: int, rot: float,
                 inner: float):
    ang = rot + np.arange(2 * points) * np.pi / points
    rad = np.where(np.arange(2 * points) % 2 == 0, r, r * inner)
    return [(cx + rr * np.cos(a), cy + rr * np.sin(a))
            for rr, a in zip(rad, ang)]


def _shape_mask(label: int, rs: np.random.RandomState, size: int,
                ss: int) -> np.ndarray:
    """Anti-aliased occupancy mask in [0, 1]: rendered at ``ss``×
    supersampling, box-downscaled. Geometry is the ONLY class signal."""
    from PIL import Image, ImageDraw

    big = size * ss
    # Scale and position jitter: the shape always fits, never centered.
    r = rs.uniform(0.26, 0.42) * big  # radius in supersampled px
    pad = r + 2 * ss
    cx = rs.uniform(pad, big - pad)
    cy = rs.uniform(pad, big - pad)
    rot = rs.uniform(0, 2 * np.pi)

    img = Image.new("L", (big, big), 0)
    d = ImageDraw.Draw(img)
    name = SHAPE_CLASSES[label]
    if name == "disk":
        d.ellipse([cx - r, cy - r, cx + r, cy + r], fill=255)
    elif name == "ring":
        d.ellipse([cx - r, cy - r, cx + r, cy + r], fill=255)
        ri = r * rs.uniform(0.45, 0.6)
        d.ellipse([cx - ri, cy - ri, cx + ri, cy + ri], fill=0)
    elif name == "triangle":
        d.polygon(_poly_points(cx, cy, r, 3, rot), fill=255)
    elif name == "square":
        d.polygon(_poly_points(cx, cy, r, 4, rot), fill=255)
    elif name == "pentagon":
        d.polygon(_poly_points(cx, cy, r, 5, rot), fill=255)
    elif name == "star5":
        d.polygon(_star_points(cx, cy, r, 5, rot, 0.42), fill=255)
    elif name == "star6":
        d.polygon(_star_points(cx, cy, r, 6, rot, 0.5), fill=255)
    elif name == "cross":
        w = r * rs.uniform(0.28, 0.38)
        c, s = np.cos(rot), np.sin(rot)

        def bar(hx, hy):
            pts = [(-hx, -hy), (hx, -hy), (hx, hy), (-hx, hy)]
            return [(cx + x * c - y * s, cy + x * s + y * c) for x, y in pts]

        d.polygon(bar(r, w), fill=255)
        d.polygon(bar(w, r), fill=255)
    elif name == "crescent":
        d.ellipse([cx - r, cy - r, cx + r, cy + r], fill=255)
        off = r * rs.uniform(0.35, 0.55)
        ox = cx + off * np.cos(rot)
        oy = cy + off * np.sin(rot)
        rc = r * rs.uniform(0.75, 0.95)
        d.ellipse([ox - rc, oy - rc, ox + rc, oy + rc], fill=0)
    elif name == "twodisks":
        rd = r * rs.uniform(0.38, 0.5)
        off = r - rd
        for sign in (1.0, -1.0):
            ox = cx + sign * off * np.cos(rot)
            oy = cy + sign * off * np.sin(rot)
            d.ellipse([ox - rd, oy - rd, ox + rd, oy + rd], fill=255)
    else:  # pragma: no cover — SHAPE_CLASSES is the closed set
        raise ValueError(f"unknown shape label {label}")
    small = img.resize((size, size), Image.BOX)
    return np.asarray(small, np.float32) / 255.0


def render_shape(label: int, rs: np.random.RandomState,
                 size: int = 32, ss: int = 4) -> np.ndarray:
    """One uint8 HWC image: random-gradient background + random-color
    shape + noise. Colors/brightness carry NO class information."""
    mask = _shape_mask(label, rs, size, ss)[..., None]
    bg_a = rs.randint(0, 256, 3).astype(np.float32)
    bg_b = rs.randint(0, 256, 3).astype(np.float32)
    while True:
        fg = rs.randint(0, 256, 3).astype(np.float32)
        # Contrast floor against BOTH gradient ends, or the shape can
        # vanish into one side of the background.
        if (np.abs(fg - bg_a).sum() >= 200
                and np.abs(fg - bg_b).sum() >= 200):
            break
    # Linear gradient along a random direction.
    theta = rs.uniform(0, 2 * np.pi)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / (size - 1)
    t = (xx * np.cos(theta) + yy * np.sin(theta) + 1.0) / 2.0  # ~[0,1]
    bg = bg_a[None, None, :] * (1 - t[..., None]) + bg_b[None, None, :] * t[..., None]
    img = bg * (1 - mask) + fg[None, None, :] * mask
    img = img + rs.randn(size, size, 3).astype(np.float32) * rs.uniform(2, 10)
    return np.clip(img, 0, 255).astype(np.uint8)


def synthetic_shapes(
    n: int = 1024, seed: int = 0, size: int = 32,
) -> Iterator[dict[str, np.ndarray]]:
    """Decoded stream of ``{"image": uint8 (size,size,3), "label"}`` with
    a balanced round-robin label sequence (shuffling is the loader's
    job)."""
    rs = np.random.RandomState(seed)
    for i in range(n):
        y = i % len(SHAPE_CLASSES)
        yield {"image": render_shape(y, rs, size), "label": np.int32(y)}


def write_shapes_image_tree(
    root: str | Path, n: int, *, seed: int = 0, size: int = 32,
) -> Path:
    """Materialize the dataset as a ``root/<class>/NNNNN.png`` tree — the
    input format of ``tpucfn convert-dataset --kind image-tree``, so the
    accuracy run's data path starts where a real user's would: image
    files on disk."""
    from PIL import Image

    root = Path(root)
    for cls in SHAPE_CLASSES:
        (root / cls).mkdir(parents=True, exist_ok=True)
    rs = np.random.RandomState(seed)
    for i in range(n):
        y = i % len(SHAPE_CLASSES)
        img = render_shape(y, rs, size)
        Image.fromarray(img).save(root / SHAPE_CLASSES[y] / f"{i:06d}.png")
    return root
