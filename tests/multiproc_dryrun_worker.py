"""Worker for the multi-process x multi-device dryrun leg (not a pytest
module).

Spawned by ``__graft_entry__._dryrun_multiprocess`` (and runnable by
hand): N processes x K fake CPU devices each join one
``jax.distributed`` rendezvous and train over global meshes that SPAN
the process boundary — the actual multihost TPU execution model
(SURVEY.md §4 "Multi-process without a cluster"). Two legs:

* ``MPLEG`` — (data:2, fsdp:4) MLP; loss must match the single-process
  control bit-for-bit.
* ``MPLEG2`` — (expert:4, tensor:2) MoE: the expert axis (and its
  all-to-all dispatch) stretches across processes; loss must match the
  control to a small fp tolerance (the two layouts compile different
  executables, so reduce orders differ — ~5e-7 observed).

The same file run with ``TPUCFN_MP_NPROC=1`` and 8 local devices is the
single-process control; the parent does the comparisons.
"""

import os
import sys

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    _n = int(os.environ.get("TPUCFN_MP_LOCAL_DEVICES", "4"))
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_n}"
    )

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402


def _init(rng):
    k1, k2 = jax.random.split(rng)
    params = {
        "fc1": {"kernel": jax.random.normal(k1, (4, 32)) * 0.1,
                "bias": jnp.zeros(32)},
        "fc2": {"kernel": jax.random.normal(k2, (32, 1)) * 0.1,
                "bias": jnp.zeros(1)},
    }
    return params, {}


def _loss(params, model_state, batch, rng):
    h = jnp.tanh(batch["x"] @ params["fc1"]["kernel"] + params["fc1"]["bias"])
    pred = h @ params["fc2"]["kernel"] + params["fc2"]["bias"]
    loss = jnp.mean((pred[:, 0] - batch["y"]) ** 2)
    return loss, ({}, model_state)


def main() -> int:
    rank = int(os.environ.get("TPUCFN_MP_RANK", "0"))
    nproc = int(os.environ.get("TPUCFN_MP_NPROC", "1"))
    if nproc > 1:
        jax.distributed.initialize(os.environ["TPUCFN_MP_COORD"],
                                   num_processes=nproc, process_id=rank)

    from tpucfn.mesh import MeshSpec, build_mesh
    from tpucfn.parallel import ShardingRules, shard_batch
    from tpucfn.train import Trainer

    assert jax.process_count() == nproc, (jax.process_count(), nproc)
    assert jax.device_count() == 8, jax.device_count()

    mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    rules = ShardingRules(((r"(fc1|fc2)/kernel$", P("fsdp")), (r".*", P())))
    trainer = Trainer(mesh, rules, _loss, optax.sgd(0.1), _init)
    state = trainer.init(jax.random.key(0))

    # The fsdp-sharded kernel is one GLOBAL array; this process addresses
    # only the shards on its local devices.
    k = state.params["fc1"]["kernel"]
    assert k.sharding.spec == P("fsdp"), k.sharding.spec
    assert len(k.addressable_shards) == 8 // nproc, len(k.addressable_shards)

    # Deterministic global batch; each process feeds its contiguous rows
    # (data index p = process p's devices under row-major mesh layout).
    rs = np.random.RandomState(0)
    x = rs.randn(64, 4).astype(np.float32)
    y = (x @ np.array([1.0, -2.0, 0.5, 0.0], np.float32)).astype(np.float32)
    lo, hi = rank * 64 // nproc, (rank + 1) * 64 // nproc
    batch = shard_batch(mesh, {"x": x[lo:hi], "y": y[lo:hi]})

    metrics = {}
    for _ in range(3):
        state, metrics = trainer.step(state, batch)
    print(f"MPLEG rank={rank} nproc={nproc} loss={float(metrics['loss']):.12f}",
          flush=True)

    # Leg 2 (round 5): expert parallelism SPANNING the process boundary.
    # Axis order puts data/fsdp outer, so a (expert:4, tensor:2) mesh
    # stretches the expert axis across the 2-process layout (experts
    # 0-1 on process 0, 2-3 on process 1): the MoE dispatch's
    # lax.all_to_all is a genuine cross-process collective, and the
    # parent asserts the loss equals the single-process layout's.
    import dataclasses

    from tpucfn.models.llama import (Llama, LlamaConfig, causal_lm_loss,
                                     sharding_rules)
    from tpucfn.models.moe import MoEConfig, collect_moe_aux

    mesh2 = build_mesh(MeshSpec(expert=4, tensor=2))
    # tiny()'s 4 heads / 2 kv-heads already divide the tensor axis.
    cfg = dataclasses.replace(
        LlamaConfig.tiny(),
        moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=2.0))
    model = Llama(cfg, ep_mesh=mesh2)
    sample = jnp.zeros((4, 16), jnp.int32)

    def init2(rng):
        return model.init(rng, sample)["params"], {}

    def loss2(params, mstate, batch, rng):
        logits, muts = model.apply({"params": params}, batch["tokens"],
                                   mutable=["losses", "metrics"])
        loss, acc = causal_lm_loss(logits, batch["tokens"])
        return loss + collect_moe_aux(muts), ({"accuracy": acc}, mstate)

    trainer2 = Trainer(mesh2, sharding_rules(cfg), loss2, optax.sgd(0.05),
                      init2)
    state2 = trainer2.init(jax.random.key(1))
    toks = np.random.RandomState(1).randint(
        0, cfg.vocab_size, (8, 16)).astype(np.int32)
    lo2, hi2 = rank * 8 // nproc, (rank + 1) * 8 // nproc
    batch2 = shard_batch(mesh2, {"tokens": toks[lo2:hi2]})
    m2 = {}
    for _ in range(2):
        state2, m2 = trainer2.step(state2, batch2)
    print(f"MPLEG2 rank={rank} nproc={nproc} loss={float(m2['loss']):.12f}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
