"""MoE expert-parallel layer: routing math, capacity, aux losses, Llama
integration with the expert mesh axis."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpucfn.mesh import MeshSpec, build_mesh
from tpucfn.models.llama import Llama, LlamaConfig, causal_lm_loss, sharding_rules
from tpucfn.models.moe import MoEConfig, MoEMLP, collect_moe_aux
from tpucfn.parallel import shard_batch
from tpucfn.train import Trainer


def _apply(model, x, seed=0):
    variables = model.init(jax.random.key(seed), x)
    out, muts = model.apply(variables, x, mutable=["losses", "metrics"])
    return out, muts


def test_moe_forward_shape():
    model = MoEMLP(ffn_dim=32, moe=MoEConfig(n_experts=4, top_k=2), dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    out, muts = _apply(model, x)
    assert out.shape == x.shape
    assert "losses" in muts


def test_moe_generous_capacity_drops_nothing():
    model = MoEMLP(ffn_dim=32,
                   moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=4.0),
                   dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    _, muts = _apply(model, x)
    dropped = float(jax.tree.leaves(muts["metrics"])[0])
    assert dropped == 0.0


def test_moe_tiny_capacity_drops_tokens():
    model = MoEMLP(ffn_dim=32,
                   moe=MoEConfig(n_experts=8, top_k=1, capacity_factor=0.25),
                   dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 16, 16))
    out, muts = _apply(model, x)
    dropped = float(jax.tree.leaves(muts["metrics"])[0])
    assert dropped > 0.0
    assert bool(jnp.isfinite(out).all())


def test_moe_aux_loss_finite_and_positive():
    model = MoEMLP(ffn_dim=32, moe=MoEConfig(n_experts=4, top_k=2), dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (2, 8, 16))
    _, muts = _apply(model, x)
    aux = collect_moe_aux(muts)
    assert float(aux) > 0.0


def test_collect_moe_aux_empty_is_zero():
    assert float(collect_moe_aux({})) == 0.0


def test_dropless_capacity_factor_exact():
    """capacity_factor = E/k must be EXACTLY dropless even when k does
    not divide E: capacity = round(cf*T*k/E) — truncation would let
    float dust shave one slot (cap = T-1) and silently drop a token.
    The Mixtral import's parity guarantee relies on this."""
    for e_, k_, t_ in ((3, 2, 7), (8, 3, 7), (6, 4, 10)):
        cfg = MoEConfig(n_experts=e_, top_k=k_, capacity_factor=e_ / k_)
        model = MoEMLP(16, cfg, dtype=jnp.float32)
        x = jax.random.normal(jax.random.key(0), (1, t_, 8))
        _, muts = _apply(model, x)
        dropped = float(jax.tree.leaves(muts["metrics"])[0])
        assert dropped == 0.0, (e_, k_, t_)


@pytest.fixture()
def mesh_ep():
    return build_mesh(MeshSpec(data=2, expert=4))


def test_dense_dispatch_with_expert_axis_raises(mesh_ep):
    """dispatch='dense' is the single-device reference checker; combined
    with an active expert axis the layer must refuse instead of silently
    running the ragged all-to-all path (ADVICE r5)."""
    cfg = MoEConfig(n_experts=4, top_k=2, dispatch="dense")
    x = jax.random.normal(jax.random.key(0), (2, 8, 16), jnp.float32)
    model = MoEMLP(32, cfg, dtype=jnp.float32, ep_mesh=mesh_ep)
    with pytest.raises(ValueError, match="ragged all-to-all"):
        model.init(jax.random.key(0), x)
    # Inert expert axis (size 1): the dense checker still works.
    mesh1 = build_mesh(MeshSpec(data=8))
    ok = MoEMLP(32, cfg, dtype=jnp.float32, ep_mesh=mesh1)
    out, _ = _apply(ok, x)
    assert out.shape == x.shape


def _moe_llama_cfg():
    return dataclasses.replace(
        LlamaConfig.tiny(),
        moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=2.0),
    )


def test_moe_llama_trains(mesh_ep):
    cfg = _moe_llama_cfg()
    model = Llama(cfg, ep_mesh=mesh_ep)  # explicit EP all-to-all dispatch
    sample = jnp.zeros((2, 16), jnp.int32)

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    def loss_fn(params, mstate, batch, rng):
        logits, muts = model.apply({"params": params}, batch["tokens"],
                                   mutable=["losses", "metrics"])
        loss, acc = causal_lm_loss(logits, batch["tokens"])
        loss = loss + collect_moe_aux(muts)
        return loss, ({"accuracy": acc}, mstate)

    trainer = Trainer(mesh_ep, sharding_rules(cfg, tensor=False), loss_fn,
                      optax.adamw(3e-3), init_fn)
    state = trainer.init(jax.random.key(0))

    # expert dim sharded over the expert axis (scan lead dim first)
    wk = state.params["layers"]["mlp"]["experts/gate_proj/kernel"]
    assert wk.sharding.spec == P(None, "expert", "fsdp")
    assert wk.addressable_shards[0].data.shape[1] == 1  # 4 experts / 4-way axis

    rs = np.random.RandomState(0)
    batch = shard_batch(mesh_ep, {"tokens": rs.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)})
    first = None
    for _ in range(10):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first


def test_ep_dispatch_lowers_to_all_to_all(mesh_ep):
    """VERDICT r4 #6: the expert-sharded step's compiled HLO must contain
    the EP all-to-all pair, no all-gather, and no collective carrying the
    FULL (E*C, D) dispatch buffer (the partitioner's default lowering of
    a sharded scatter is local-scatter + full-buffer all-reduce — exactly
    what the explicit shard_map dispatch exists to prevent)."""
    cfg = _moe_llama_cfg()
    model = Llama(cfg, ep_mesh=mesh_ep)
    sample = jnp.zeros((2, 16), jnp.int32)

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    def loss_fn(params, mstate, batch, rng):
        logits, muts = model.apply({"params": params}, batch["tokens"],
                                   mutable=["losses", "metrics"])
        loss, acc = causal_lm_loss(logits, batch["tokens"])
        return loss + collect_moe_aux(muts), ({"accuracy": acc}, mstate)

    trainer = Trainer(mesh_ep, sharding_rules(cfg, tensor=False), loss_fn,
                      optax.adamw(3e-3), init_fn)
    state = trainer.init(jax.random.key(0))
    batch = shard_batch(mesh_ep, {"tokens": np.zeros((8, 16), np.int32)})
    state, _ = trainer.step(state, batch)  # builds + caches the jit
    txt = trainer._jit_step.lower(state, batch).compile().as_text()

    collective_lines = [l for l in txt.splitlines()
                        if "all-to-all(" in l or "all-gather(" in l
                        or "all-reduce(" in l]
    assert any("all-to-all(" in l for l in collective_lines), \
        "no all-to-all in the expert-sharded step"
    assert not any("all-gather(" in l for l in collective_lines), \
        "EP dispatch must not all-gather"
    # Global dispatch buffer at this config: T=8*16=128 tokens, k=2,
    # cf=2.0, E=4 -> C=128, buffer (E*C, D) = (512, 64). No collective
    # may carry it (weight grads are (1, 128, 64)/(1, 64, 128); loss
    # scalars are f32[]).
    full_buffer = "512,64"
    offenders = [l.strip()[:120] for l in collective_lines if full_buffer in l]
    assert not offenders, offenders


def test_ep_dispatch_matches_single_device(mesh_ep):
    """With capacity generous enough that nothing drops, the explicit EP
    dispatch computes the same function as the single-device ragged path:
    outputs and parameter gradients match."""
    cfg = MoEConfig(n_experts=4, top_k=2, capacity_factor=4.0)
    x = jax.random.normal(jax.random.key(1), (2, 64, 16), jnp.float32)

    ref = MoEMLP(32, cfg, dtype=jnp.float32)
    variables = ref.init(jax.random.key(0), x)
    ep = MoEMLP(32, cfg, dtype=jnp.float32, ep_mesh=mesh_ep)

    def fwd(module):
        def f(params):
            out, _ = module.apply({"params": params}, x,
                                  mutable=["losses", "metrics"])
            return out.sum(), out
        # jit: the partial-manual shard_map (auto fsdp/tensor axes) is a
        # jit-context feature — same as every real call site (Trainer).
        return jax.jit(jax.value_and_grad(f, has_aux=True))

    (s_ref, o_ref), g_ref = fwd(ref)(variables["params"])
    (s_ep, o_ep), g_ep = fwd(ep)(variables["params"])
    np.testing.assert_allclose(np.asarray(o_ep), np.asarray(o_ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(s_ep), float(s_ref), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), g_ep, g_ref)


def test_ep_dispatch_composes_with_ring_attention():
    """EP x SP in the non-PP path: ep_mesh dispatch (tokens manual over
    batch axes) under ring attention (sequence manual over context in
    its own shard_map). Generous capacity => logits match the plain
    model."""
    import dataclasses

    from tpucfn.kernels import make_ring_attention

    mesh = build_mesh(MeshSpec(data=2, expert=2, context=2))
    cfg = dataclasses.replace(
        LlamaConfig.tiny(),
        moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=4.0))
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 32)),
        jnp.int32)
    plain = Llama(cfg)
    params = plain.init(jax.random.key(0), toks)["params"]
    ref, _ = plain.apply({"params": params}, toks,
                         mutable=["losses", "metrics"])

    model = Llama(cfg, attention_fn=make_ring_attention(mesh), ep_mesh=mesh)
    out, _ = jax.jit(lambda p, t: model.apply(
        {"params": p}, t, mutable=["losses", "metrics"]))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4)


def test_ep_dispatch_composes_with_ulysses():
    """EP x Ulysses SP: the head all-to-all (context axis) and the
    expert all-to-all (expert axis) in one step; logits match the plain
    model in the no-drop regime."""
    import dataclasses

    from tpucfn.kernels import make_ulysses_attention

    mesh = build_mesh(MeshSpec(data=2, expert=2, context=2))
    cfg = dataclasses.replace(
        LlamaConfig.tiny(),
        moe=MoEConfig(n_experts=4, top_k=2, capacity_factor=4.0))
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (4, 32)),
        jnp.int32)
    plain = Llama(cfg)
    params = plain.init(jax.random.key(0), toks)["params"]
    ref, _ = plain.apply({"params": params}, toks,
                         mutable=["losses", "metrics"])

    model = Llama(cfg, attention_fn=make_ulysses_attention(mesh),
                  ep_mesh=mesh)
    out, _ = jax.jit(lambda p, t: model.apply(
        {"params": p}, t, mutable=["losses", "metrics"]))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def _moe_apply(dispatch, x, capacity_factor=1.25):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tpucfn.models.moe import MoEConfig, MoEMLP

    cfg = MoEConfig(n_experts=4, top_k=2, capacity_factor=capacity_factor,
                    dispatch=dispatch)
    m = MoEMLP(32, cfg, dtype=jnp.float32)
    # Params are dispatch-independent (same names/shapes): init once via
    # the dense config and reuse.
    variables = MoEMLP(32, dataclasses.replace(cfg, dispatch="dense"),
                       dtype=jnp.float32).init(jax.random.key(0), x)

    def fwd(params):
        out, aux = m.apply({"params": params}, x, mutable=["losses", "metrics"])
        from tpucfn.models.moe import collect_moe_aux

        return out.sum() + collect_moe_aux(aux), (out, aux)

    (loss, (out, aux)), grads = jax.value_and_grad(
        fwd, has_aux=True)(variables["params"])
    return loss, out, aux, grads


def test_ragged_matches_dense_dispatch():
    # VERDICT r3 missing #3: the ragged scatter/gather dispatch must be
    # bit-equivalent to the dense one-hot reference — outputs, aux
    # losses, AND gradients — both with generous capacity and in the
    # overflow/drop regime.
    import jax
    import numpy as np

    x = jax.random.normal(jax.random.key(1), (2, 24, 16), jnp.float32)
    for cap in (2.0, 0.4):  # no drops / heavy drops
        l_r, o_r, a_r, g_r = _moe_apply("ragged", x, capacity_factor=cap)
        l_d, o_d, a_d, g_d = _moe_apply("dense", x, capacity_factor=cap)
        np.testing.assert_allclose(np.asarray(o_r), np.asarray(o_d),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(float(l_r), float(l_d), rtol=1e-5)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), g_r, g_d)


def test_ragged_memory_beats_dense_at_scale():
    # The point of the ragged path: no (T, E, C) dispatch/combine
    # temporaries. At T=8k tokens, E=16 experts the dense einsum form
    # materializes ~T*E*C*4B*2 = 5.4 GB of one-hots; the ragged form
    # scatters into one (E*C, D) buffer. Compare XLA's own accounting
    # of the compiled forward's temp allocations.
    import dataclasses

    import jax
    import jax.numpy as jnp
    import pytest

    from tpucfn.models.moe import MoEConfig, MoEMLP

    cfg = MoEConfig(n_experts=16, top_k=2, capacity_factor=1.0)
    x = jnp.zeros((8, 1024, 64), jnp.float32)  # T = 8192
    m = MoEMLP(128, cfg, dtype=jnp.float32)
    variables = jax.eval_shape(lambda: m.init(jax.random.key(0), x))

    def temp_bytes(dispatch):
        mm = MoEMLP(128, dataclasses.replace(cfg, dispatch=dispatch),
                    dtype=jnp.float32)
        fn = jax.jit(lambda p, x: mm.apply(
            {"params": p}, x, mutable=["losses", "metrics"])[0])
        compiled = fn.lower(variables["params"], x).compile()
        ma = compiled.memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("backend exposes no memory analysis")
        return ma.temp_size_in_bytes

    dense = temp_bytes("dense")
    ragged = temp_bytes("ragged")
    # T*E*C fp32 is 512 MB per one-hot at this size; demand at least an
    # order of magnitude between the two forms.
    assert ragged * 10 < dense, (ragged, dense)
