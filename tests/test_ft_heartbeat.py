"""Heartbeat writing + fleet classification (tpucfn.ft.heartbeat) —
every timing input is a fake clock, so the classifier thresholds are
pinned exactly with zero sleeps."""

import json
import urllib.request

import pytest

from tpucfn.ft import (
    HeartbeatMonitor,
    HeartbeatWriter,
    HostState,
    MonitorConfig,
    heartbeat_path,
    read_heartbeats,
)


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _writer(tmp_path, host_id, clock, **kw):
    return HeartbeatWriter(tmp_path / "ft", host_id, clock=clock, **kw)


def test_writer_appends_schema_lines(tmp_path):
    clock = Clock()
    w = _writer(tmp_path, 3, clock, role="trainer", pid=42)
    w.beat(step=7)
    clock.advance(1.0)
    w.beat(step=9)
    w.stop()
    lines = [json.loads(s) for s in
             heartbeat_path(tmp_path / "ft", 3).read_text().splitlines()]
    assert [r["seq"] for r in lines] == [1, 2]
    assert lines[0] == {"host_id": 3, "pid": 42, "step": 7, "t": 1000.0,
                        "seq": 1, "role": "trainer"}
    assert lines[1]["step"] == 9 and lines[1]["t"] == 1001.0


def test_update_step_rides_next_beat_and_beat_after_stop_is_noop(tmp_path):
    w = _writer(tmp_path, 0, Clock())
    w.update_step(123)
    w.beat()
    w.stop()
    w.beat()  # post-stop: must not raise or write
    lines = heartbeat_path(tmp_path / "ft", 0).read_text().splitlines()
    assert len(lines) == 1
    assert json.loads(lines[0])["step"] == 123


def test_read_heartbeats_latest_per_host_and_torn_tail(tmp_path):
    clock = Clock()
    d = tmp_path / "ft"
    _writer(tmp_path, 0, clock).beat(step=1)
    w1 = _writer(tmp_path, 1, clock)
    w1.beat(step=5)
    clock.advance(2.0)
    w1.beat(step=6)
    # a crash mid-append leaves a torn final line; the reader must fall
    # back to the last complete record
    with open(heartbeat_path(d, 1), "a") as f:
        f.write('{"host_id": 1, "t": 99')
    recs = read_heartbeats(d)
    assert sorted(recs) == [0, 1]
    assert recs[1]["step"] == 6 and recs[1]["t"] == 1002.0


def test_monitor_live_suspect_dead_progression(tmp_path):
    clock = Clock()
    w = _writer(tmp_path, 0, clock)
    w.beat(step=10)
    mon = HeartbeatMonitor(tmp_path / "ft", expected_hosts=1,
                           config=MonitorConfig(interval_s=1.0), clock=clock)
    assert mon.observe().hosts[0].state is HostState.LIVE
    clock.advance(3.5)  # > suspect (3x), <= dead (6x)
    v = mon.observe().hosts[0]
    assert v.state is HostState.SUSPECT and v.age_s == pytest.approx(3.5)
    clock.advance(3.0)  # now 6.5s old > dead
    v = mon.observe().hosts[0]
    assert v.state is HostState.DEAD
    assert v.step == 10 and v.pid == w.pid
    # a fresh beat resurrects the host
    w.beat(step=11)
    assert mon.observe().hosts[0].state is HostState.LIVE


def test_monitor_missing_host_grace_then_dead(tmp_path):
    clock = Clock()
    (tmp_path / "ft").mkdir()
    mon = HeartbeatMonitor(tmp_path / "ft", expected_hosts=[0, 1],
                           config=MonitorConfig(interval_s=1.0), clock=clock)
    _writer(tmp_path, 0, clock).beat()
    view = mon.observe()
    by = view.by_host()
    assert by[0].state is HostState.LIVE
    assert by[1].state is HostState.SUSPECT  # startup grace (10x interval)
    assert "grace" in by[1].reason
    clock.advance(10.5)
    by = mon.observe().by_host()
    assert by[1].state is HostState.DEAD and by[1].age_s is None
    # restart_grace re-arms the window (what the coordinator does after
    # every relaunch)
    mon.restart_grace()
    assert mon.observe().by_host()[1].state is HostState.SUSPECT


def test_straggler_needs_fleet_context_and_lag(tmp_path):
    clock = Clock()
    w0, w1 = _writer(tmp_path, 0, clock), _writer(tmp_path, 1, clock)
    cfg = MonitorConfig(interval_s=1.0, straggler_step_lag=50)
    mon = HeartbeatMonitor(tmp_path / "ft", config=cfg, clock=clock)
    w0.beat(step=1000)
    w1.beat(step=960)  # within lag
    states = [v.state for v in mon.observe().hosts]
    assert states == [HostState.LIVE, HostState.LIVE]
    w1.beat(step=940)  # still fresh, but > 50 behind
    view = mon.observe()
    assert view.by_host()[1].state is HostState.STRAGGLER
    assert view.by_host()[0].state is HostState.LIVE
    assert view.max_step() == 1000
    # straggling degrades detail, not /healthz status
    healthy, detail = view.healthy()
    assert healthy and detail["fleet"]["STRAGGLER"] == 1


def test_injected_heartbeat_delay_expires(tmp_path):
    clock = Clock()
    w = _writer(tmp_path, 0, clock)
    w.beat()
    mon = HeartbeatMonitor(tmp_path / "ft",
                           config=MonitorConfig(interval_s=1.0), clock=clock)
    mon.inject_heartbeat_delay(0, extra_age_s=10.0, duration_s=5.0)
    assert mon.observe().hosts[0].state is HostState.DEAD  # age 0 + 10 > 6
    clock.advance(5.5)  # injection expired; real age 5.5 -> SUSPECT
    w.beat()  # fresh beat after the chaos window
    assert mon.observe().hosts[0].state is HostState.LIVE


def test_retired_host_not_judged_and_healthz_stays_green(tmp_path):
    """A rank that exits cleanly stops beating; without retirement its
    aging last beat would flip the supervisor /healthz to 503 for the
    rest of an otherwise healthy run.  The coordinator retires clean
    exits; a relaunch re-activates the slot."""
    clock = Clock()
    for h in (0, 1):
        w = _writer(tmp_path, h, clock)
        w.beat(step=10)
        w.stop()
    mon = HeartbeatMonitor(tmp_path / "ft", expected_hosts=2,
                           config=MonitorConfig(interval_s=1.0), clock=clock)
    clock.advance(7.0)  # both beats are now past dead_s (6x interval)
    w1 = _writer(tmp_path, 1, clock)
    w1.beat(step=11)  # host 1 alive; host 0 finished and stopped
    w1.stop()
    assert mon.observe().by_host()[0].state is HostState.DEAD
    assert mon.health()[0] is False

    mon.retire_host(0)
    view = mon.observe()
    assert set(view.by_host()) == {1}, "retired host must not be judged"
    healthy, detail = view.healthy()
    assert healthy and detail["fleet"]["DEAD"] == 0

    mon.activate_host(0)  # the slot relaunched: judged again
    assert mon.observe().by_host()[0].state is HostState.DEAD


def test_set_expected_hosts_rescopes_after_shrink(tmp_path):
    """Elastic shrink (ISSUE 7): after the gang re-converges at N-1 the
    old highest id's heartbeat file is still on disk — re-scoping the
    monitor (plus retiring the dropped id) must stop it being judged,
    or its aging beat reads as a phantom hang of a host the contract no
    longer has."""
    clock = Clock()
    for h in (0, 1, 2):
        w = _writer(tmp_path, h, clock)
        w.beat(step=10)
        w.stop()
    mon = HeartbeatMonitor(tmp_path / "ft", expected_hosts=3,
                           config=MonitorConfig(interval_s=1.0), clock=clock)
    assert set(mon.observe().by_host()) == {0, 1, 2}
    # shrink 3 -> 2: host 2's slot is gone from the contract
    mon.set_expected_hosts(2)
    mon.retire_host(2)
    clock.advance(7.0)  # all original beats now past dead_s
    for h in (0, 1):  # survivors keep beating
        w = _writer(tmp_path, h, clock)
        w.beat(step=11)
        w.stop()
    view = mon.observe()
    assert set(view.by_host()) == {0, 1}
    healthy, detail = view.healthy()
    assert healthy, "a dropped host's stale file must not 503 the fleet"
    assert detail["fleet"]["DEAD"] == 0


def test_monitor_feeds_obs_healthz(tmp_path):
    """The monitor's health() IS an obs-server health_fn: /healthz flips
    200 → 503 when a host goes DEAD (ISSUE 4 tentpole wiring)."""
    from tpucfn.obs import MetricRegistry, ObsServer

    clock = Clock()
    w = _writer(tmp_path, 0, clock)
    w.beat(step=4)
    mon = HeartbeatMonitor(tmp_path / "ft", expected_hosts=1,
                           config=MonitorConfig(interval_s=1.0), clock=clock)
    srv = ObsServer(MetricRegistry(), port=0, host="127.0.0.1",
                    role="supervisor", health_fn=mon.health)
    try:
        body = json.load(urllib.request.urlopen(srv.url("/healthz"),
                                                timeout=5))
        assert body["status"] == "ok" and body["fleet"]["LIVE"] == 1
        clock.advance(7.0)  # past dead threshold
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url("/healthz"), timeout=5)
        assert ei.value.code == 503
        assert json.load(ei.value)["fleet"]["DEAD"] == 1
    finally:
        srv.close()


def test_writer_daemon_thread_beats_without_loop_calls(tmp_path):
    """start() keeps liveness flowing while the 'train loop' is stuck —
    the one wall-clock test here, bounded at tenths of a second."""
    w = HeartbeatWriter(tmp_path / "ft", 0, interval_s=0.02)
    with w:
        import time

        deadline = time.monotonic() + 2.0
        path = heartbeat_path(tmp_path / "ft", 0)
        while time.monotonic() < deadline:
            recs = path.read_text().splitlines()
            if len(recs) >= 3:
                break
            time.sleep(0.01)
    assert len(path.read_text().splitlines()) >= 3
