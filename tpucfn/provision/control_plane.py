"""TPU control-plane abstraction + fake implementation.

The reference trusted the CloudFormation service as an untestable black
box (SURVEY.md §4: "multi-node was only ever tested on real EC2"). Here
the control plane is an interface so the whole provisioning state machine
is exercised in CI against :class:`FakeControlPlane` — a deterministic,
optionally-failing in-process implementation of the TPU queued-resource
lifecycle:

    QUEUED → PROVISIONING → ACTIVE → (DELETING → DELETED | FAILED)

A real GCP/AWS-trn backend implements the same five methods against the
cloud API; nothing above this module changes (SURVEY.md §5 failure-
detection row and §7.2 step 4).
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import itertools
import threading
from typing import Callable

from tpucfn.spec import ClusterSpec


class ClusterState(enum.Enum):
    QUEUED = "QUEUED"
    PROVISIONING = "PROVISIONING"
    ACTIVE = "ACTIVE"
    DELETING = "DELETING"
    DELETED = "DELETED"
    FAILED = "FAILED"


@dataclasses.dataclass
class HostRecord:
    host_id: int
    address: str  # ip:port the launcher reaches this host at
    healthy: bool = True


@dataclasses.dataclass
class ClusterRecord:
    spec: ClusterSpec
    state: ClusterState
    hosts: list[HostRecord]
    generation: int = 0  # bumped on every (re)acquire — resume fencing
    message: str = ""


class ControlPlane:
    """Interface; see FakeControlPlane for semantics."""

    def create(self, spec: ClusterSpec) -> ClusterRecord:
        raise NotImplementedError

    def describe(self, name: str) -> ClusterRecord:
        raise NotImplementedError

    def delete(self, name: str) -> None:
        raise NotImplementedError

    def tick(self) -> None:
        """Advance async state transitions (real backends poll instead)."""

    def kill_host(self, name: str, host_id: int) -> None:
        """Fault injection: mark a host dead (test-only on real backends)."""
        raise NotImplementedError


class FakeControlPlane(ControlPlane):
    """Deterministic fake with scriptable latency and failures.

    ``steps_to_provision`` QUEUED→ACTIVE ticks model queued-resource wait;
    ``fail_after`` makes creation land in FAILED (capacity error);
    ``kill_host`` flips a host unhealthy, which the Provisioner's monitor
    must notice (SURVEY.md §5: ASG auto-replacement analogue — except a
    TPU slice is atomic, so replacement = re-acquire the whole slice).
    """

    def __init__(self, *, steps_to_provision: int = 2, fail_creation: bool = False,
                 state_file: str | None = None):
        """``state_file`` persists cluster records to disk so separate CLI
        invocations (create-stack, then launch, then delete) share state —
        the role the CFN service's own database played for the reference."""
        self.steps_to_provision = steps_to_provision
        self.fail_creation = fail_creation
        self._clusters: dict[str, ClusterRecord] = {}
        self._pending: dict[str, int] = {}
        self._gen = itertools.count(1)
        self.events: list[tuple[str, str]] = []  # (cluster, event) audit log
        self._state_file = state_file
        self._in_txn = False
        # Guards _in_txn/_clusters for threads sharing one instance; the
        # flock serializes across processes, this across threads.  RLock
        # so describe() inside a same-thread transaction doesn't deadlock.
        self._ilock = threading.RLock()
        if state_file:
            self._load()

    # -- persistence -----------------------------------------------------
    #
    # Concurrent CLI invocations (e.g. a health-monitor loop racing a user
    # resize) serialize on an flock'd sidecar: every mutation is a full
    # read-modify-write transaction under the lock (reload state, apply,
    # write), so no invocation can lose another's update.  Writes are
    # atomic (tmp + rename) so lock-free readers never observe a torn
    # JSON — the control-plane-race concern from SURVEY.md §5.

    @contextlib.contextmanager
    def _locked(self):
        import fcntl
        from pathlib import Path

        lock_path = Path(self._state_file).with_suffix(".lock")
        lock_path.parent.mkdir(parents=True, exist_ok=True)
        with open(lock_path, "w") as lk:
            fcntl.flock(lk, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lk, fcntl.LOCK_UN)

    @contextlib.contextmanager
    def _transaction(self):
        """Critical section for mutations: reload → mutate → persist,
        all under one flock, so concurrent processes (kill-host racing
        heal, monitor racing resize) serialize instead of last-writer-
        wins over a stale in-memory copy."""
        if not self._state_file:
            with self._ilock:  # memory-only instances still serialize
                yield
            return
        with self._ilock, self._locked():
            self._load_unlocked()
            self._in_txn = True
            try:
                yield
            finally:
                self._in_txn = False
            self._save_unlocked()

    def _load_unlocked(self) -> None:
        import json
        from pathlib import Path

        p = Path(self._state_file)
        if not p.exists():
            return
        raw = json.loads(p.read_text())
        self._clusters = {}
        for name, rec in raw.get("clusters", {}).items():
            self._clusters[name] = ClusterRecord(
                spec=ClusterSpec.from_json(rec["spec"]),
                state=ClusterState(rec["state"]),
                hosts=[HostRecord(**h) for h in rec["hosts"]],
                generation=rec["generation"],
                message=rec.get("message", ""),
            )
        self._pending = dict(raw.get("pending", {}))
        self._gen = itertools.count(raw.get("next_gen", 1))

    def _load(self) -> None:
        with self._locked():
            self._load_unlocked()

    def _save_unlocked(self) -> None:
        import dataclasses as dc
        import json
        from pathlib import Path

        next_gen = next(self._gen)  # peek (consumes; re-prime below)
        self._gen = itertools.count(next_gen)
        data = {
            "clusters": {
                name: {
                    "spec": rec.spec.to_json(),
                    "state": rec.state.value,
                    "hosts": [dc.asdict(h) for h in rec.hosts],
                    "generation": rec.generation,
                    "message": rec.message,
                }
                for name, rec in self._clusters.items()
            },
            "pending": self._pending,
            "next_gen": next_gen,
        }
        p = Path(self._state_file)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(".tmp")
        tmp.write_text(json.dumps(data, indent=2))
        tmp.replace(p)

    # -- ControlPlane ----------------------------------------------------

    def create(self, spec: ClusterSpec) -> ClusterRecord:
        with self._transaction():
            existing = self._clusters.get(spec.name)
            if existing is not None and existing.state not in (
                ClusterState.DELETED,
                ClusterState.FAILED,
            ):
                raise ValueError(f"cluster {spec.name!r} already exists ({existing.state.value})")
            rec = ClusterRecord(spec=spec, state=ClusterState.QUEUED, hosts=[],
                                generation=next(self._gen))
            self._clusters[spec.name] = rec
            self._pending[spec.name] = self.steps_to_provision
            self.events.append((spec.name, "create"))
        return rec

    def describe(self, name: str) -> ClusterRecord:
        # Long-lived readers (health monitors) must see other processes'
        # writes; inside a transaction the state was just reloaded.
        with self._ilock:
            if self._state_file and not self._in_txn:
                self._load()
            if name not in self._clusters:
                raise KeyError(f"no cluster named {name!r}")
            return self._clusters[name]

    def delete(self, name: str) -> None:
        with self._transaction():
            rec = self.describe(name)
            rec.state = ClusterState.DELETED
            rec.hosts = []
            self._pending.pop(name, None)
            self.events.append((name, "delete"))

    def tick(self) -> None:
        with self._transaction():
            for name, rec in self._clusters.items():
                if rec.state in (ClusterState.QUEUED, ClusterState.PROVISIONING):
                    left = self._pending.get(name, 0) - 1
                    self._pending[name] = left
                    if left > 0:
                        rec.state = ClusterState.PROVISIONING
                    elif self.fail_creation:
                        rec.state = ClusterState.FAILED
                        rec.message = "no capacity for requested topology"
                        self.events.append((name, "failed"))
                    else:
                        rec.state = ClusterState.ACTIVE
                        rec.hosts = [
                            HostRecord(host_id=i, address=f"10.0.0.{i + 1}:8471")
                            for i in range(rec.spec.num_hosts)
                        ]
                        self.events.append((name, "active"))

    def kill_host(self, name: str, host_id: int) -> None:
        with self._transaction():
            rec = self.describe(name)
            rec.hosts[host_id].healthy = False
            self.events.append((name, f"host{host_id}-died"))


WaitCallback = Callable[[ClusterRecord], None]
