"""Device mesh construction with named parallelism axes.

The reference's only "mesh" was a flat hostfile consumed by ``mpirun`` /
``launch.py`` (SURVEY.md §1 L3/L4: ``$DEEPLEARNING_WORKERS_PATH`` +
``$DEEPLEARNING_WORKERS_COUNT``); all parallelism was 1-D data parallelism
over that list. On TPU the mesh is the first-class object: every parallelism
strategy is an axis of one ``jax.sharding.Mesh``, and XLA emits the
collectives (SURVEY.md §2.3, §2.4).

Axis order encodes the fabric hierarchy: axes that move the most bytes per
step sit innermost so they map to ICI neighbors; axes that communicate
rarely (pipeline bubbles, DP gradient reduction once per step) sit outermost
and may ride DCN in multi-slice deployments.

    (pipeline, data, fsdp, expert, context, tensor)
     outermost / DCN-tolerant  ......  innermost / ICI-hungry
"""

from __future__ import annotations

import dataclasses
import math
from typing import TYPE_CHECKING, Sequence

# jax (and numpy, which jax drags in anyway) are imported lazily inside
# the mesh-building functions: MeshSpec itself is pure arithmetic, and
# the CLI's jax-free paths (`tpucfn check`, provisioning) import this
# module for the spec only.
if TYPE_CHECKING:  # pragma: no cover - annotations only
    import jax
    from jax.sharding import Mesh

AXIS_PIPELINE = "pipeline"
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_EXPERT = "expert"
AXIS_CONTEXT = "context"
AXIS_TENSOR = "tensor"

# Outermost→innermost. Tensor parallelism is the most latency/bandwidth
# sensitive (collectives inside every layer), so it gets the innermost —
# physically closest — ICI neighbors. Pipeline only ppermutes activations at
# stage boundaries, so it tolerates the outermost placement (DCN between
# slices in a multislice job).
ALL_AXES = (
    AXIS_PIPELINE,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_CONTEXT,
    AXIS_TENSOR,
)

# Axes over which the global batch is split. FSDP is "data parallelism with
# sharded state", so the batch dimension shards over both.  The expert axis
# is a batch axis too (the standard expert-parallel layout): outside MoE
# layers its devices do ordinary data-parallel work instead of replicating
# it, and inside MoE the per-device token shard is what the explicit
# all-to-all dispatch exchanges over ``expert`` (tpucfn/models/moe.py).
BATCH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_EXPERT)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape — the analogue of the reference's
    ``WorkerCount`` CFN parameter, generalized to six named axes.

    Any axis left at 1 is still present in the mesh so sharding rules can
    mention it unconditionally; XLA elides collectives over size-1 axes.
    """

    pipeline: int = 1
    data: int = 1
    fsdp: int = 1
    expert: int = 1
    context: int = 1
    tensor: int = 1

    def __post_init__(self):
        for name in ALL_AXES:
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"mesh axis {name!r} must be a positive int, got {v!r}")

    @property
    def axis_sizes(self) -> tuple[int, ...]:
        return tuple(getattr(self, name) for name in ALL_AXES)

    @property
    def num_devices(self) -> int:
        return math.prod(self.axis_sizes)

    @property
    def dp_size(self) -> int:
        """Total data-parallel degree (batch shards)."""
        return self.data * self.fsdp

    @classmethod
    def for_devices(cls, n: int, **overrides: int) -> "MeshSpec":
        """Fill the ``data`` axis with whatever devices the explicit axes
        leave over — the common "just do DP over everything" default that
        matches the reference's behavior of using every GPU in the fleet.
        """
        if "data" in overrides:
            raise ValueError("pass data= via the constructor, not for_devices")
        fixed = math.prod(overrides.values()) if overrides else 1
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by explicit axes product {fixed}")
        return cls(data=n // fixed, **overrides)

    def validate(self, n_devices: int) -> None:
        if self.num_devices != n_devices:
            raise ValueError(
                f"MeshSpec wants {self.num_devices} devices "
                f"({dict(zip(ALL_AXES, self.axis_sizes))}) but {n_devices} are available"
            )


def build_mesh(
    spec: MeshSpec | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the 6-axis :class:`jax.sharding.Mesh` for ``spec``.

    Devices are laid out so that the innermost spec axes stride over
    adjacent device ids — on a real slice, adjacent ids are ICI neighbors,
    which is exactly where the tensor/context axes belong.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec.for_devices(len(devices))
    spec.validate(len(devices))
    dev_array = np.asarray(devices).reshape(spec.axis_sizes)
    return Mesh(dev_array, ALL_AXES)


def local_mesh_devices(mesh: Mesh) -> list[jax.Device]:
    """Devices of ``mesh`` attached to this process (host-local shard of the
    fleet — the analogue of one row of the reference's hostfile)."""
    import jax

    return [d for d in mesh.devices.flat if d.process_index == jax.process_index()]


# Axes whose collectives are once-per-step and bandwidth-light enough to
# ride DCN between slices; everything else must stay inside a slice (ICI).
DCN_FRIENDLY_AXES = (AXIS_PIPELINE, AXIS_DATA)


def build_multislice_mesh(
    spec: MeshSpec,
    *,
    num_slices: int,
    dcn_axis: str = AXIS_DATA,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Mesh for a multislice fleet: ``dcn_axis`` spans the slices (DCN),
    every other axis stays inside one slice (ICI).

    The two-tier fabric decision from SURVEY.md §2.4: gradient reduction
    (data) or stage hand-off (pipeline) per step is the only traffic that
    crosses DCN; TP/SP/FSDP collectives never leave a slice. Devices are
    grouped by ``slice_index`` when the platform reports it (real
    multislice TPU); otherwise (CPU tests, single slice) contiguous
    device-id blocks stand in for slices — same layout math either way.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if dcn_axis not in DCN_FRIENDLY_AXES:
        raise ValueError(
            f"dcn_axis {dcn_axis!r} is latency/bandwidth-bound; only "
            f"{DCN_FRIENDLY_AXES} may span slices"
        )
    if devices is None:
        devices = jax.devices()
    if getattr(spec, dcn_axis) != num_slices:
        raise ValueError(
            f"spec.{dcn_axis}={getattr(spec, dcn_axis)} must equal "
            f"num_slices={num_slices} (one shard per slice)"
        )
    spec.validate(len(devices))
    if len(devices) % num_slices:
        raise ValueError(f"{len(devices)} devices not divisible by {num_slices} slices")

    per_slice = len(devices) // num_slices
    if all(getattr(d, "slice_index", None) is not None for d in devices):
        groups: dict[int, list[jax.Device]] = {}
        for d in devices:
            groups.setdefault(d.slice_index, []).append(d)
        if len(groups) != num_slices or any(len(g) != per_slice for g in groups.values()):
            raise ValueError(
                f"device slice topology {[len(g) for g in groups.values()]} "
                f"!= {num_slices}x{per_slice}"
            )
        slices = [sorted(groups[i], key=lambda d: d.id) for i in sorted(groups)]
    else:
        devs = list(devices)
        slices = [devs[i * per_slice:(i + 1) * per_slice] for i in range(num_slices)]

    # Lay out: dcn axis strides across slices; intra-slice axes tile the
    # devices of one slice exactly as build_mesh would.
    intra_sizes = tuple(
        1 if name == dcn_axis else getattr(spec, name) for name in ALL_AXES
    )
    arr = np.empty(spec.axis_sizes, dtype=object)
    dcn_pos = ALL_AXES.index(dcn_axis)
    for si, sdevs in enumerate(slices):
        block = np.asarray(sdevs).reshape(intra_sizes)
        index = [slice(None)] * len(ALL_AXES)
        index[dcn_pos] = si
        arr[tuple(index)] = block.squeeze(axis=dcn_pos)
    return Mesh(arr, ALL_AXES)
