"""Shared fleet-transport hardening (ISSUE 15).

Every fleet TCP plane — the input service, the compile-artifact
service — speaks through this package's two halves:

* :mod:`tpucfn.net.deadline` — an end-to-end :class:`Deadline`
  composed over per-chunk socket timeouts (a trickling peer can no
  longer reset the clock one byte at a time), one jittered-backoff
  :class:`RetryPolicy` shared by every plane's retry loop, and the
  ``net_*`` metric family.
* :mod:`tpucfn.net.proxy` — a deterministic fault-injection TCP proxy
  (:class:`ChaosProxy`, ``tpucfn chaos proxy``) that sits in front of
  any plane's port and injects gray failures from a seeded schedule:
  latency, throttle/trickle, mid-stream stall with the connection held
  open, one-way partition, torn-frame-then-close, RST.

jax-free on purpose: input hosts, the coordinator, the supervise loop,
and the analyzer all sit on top of it.
"""

from tpucfn.net.deadline import (  # noqa: F401
    Deadline,
    DeadlineExceeded,
    NetMetrics,
    RetryPolicy,
    sendall_deadline,
)
from tpucfn.net.proxy import (  # noqa: F401
    NET_FAULT_KINDS,
    ChaosProxy,
    NetFault,
    NetFaultSchedule,
)
