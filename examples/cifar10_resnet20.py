#!/usr/bin/env python
"""Distributed CIFAR-10 ResNet training — the minimum end-to-end slice.

Capability parity with the reference's flagship walkthrough (SURVEY.md
§3.2; BASELINE config 1):

    reference:  ../../tools/launch.py -n $DEEPLEARNING_WORKERS_COUNT \
                   -H $DEEPLEARNING_WORKERS_PATH \
                   python train_cifar10.py --network resnet --kv-store dist_sync
    tpucfn:     tpucfn launch examples/cifar10_resnet20.py -- \
                   --network resnet20 --kv-store dist_sync

Same UX; under the hood the per-batch kvstore.push/pull against parameter
servers is replaced by one jit-compiled SPMD step whose gradient psum XLA
emits over ICI. ``--kv-store dist_sync`` is accepted (and means what it
meant: synchronous data parallelism); there is simply no server process to
run anymore.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import (  # noqa: E402
    add_cluster_args,
    build_example_mesh,
    per_process_batch,
    run_train_loop,
    stage_synthetic,
)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_cluster_args(p)
    p.add_argument("--network", default="resnet20", choices=["resnet20", "resnet32"])
    p.add_argument("--num-examples", type=int, default=2048,
                   help="synthetic dataset size to stage (ignored with "
                        "--data-url)")
    p.add_argument("--augment", action="store_true",
                   help="pad-crop + mirror augmentation (the CIFAR recipe)")
    p.add_argument("--data-url", default="",
                   help="real dataset: tpurecord shards of ENCODED images "
                        "(tpucfn convert-dataset --kind image-tree) at a "
                        "gs://, s3://, file:// URL or local dir — decoded "
                        "on the host input path, 10-class 32x32 expected")
    p.add_argument("--eval-url", default="",
                   help="held-out split shards (encoded images) for "
                        "--eval-every; with neither, eval uses a "
                        "synthetic split")
    p.add_argument("--loader-workers", type=int, default=0,
                   help="decode/augment parallelism: N>0 threads, N<0 "
                        "spawn processes (|N| MultiProcessLoader workers)")
    p.add_argument("--cosine", action="store_true",
                   help="warmup-cosine LR over the step budget (the "
                        "train-to-accuracy recipe; default is constant "
                        "--lr)")
    args = p.parse_args()

    from tpucfn.launch import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp
    import optax

    from tpucfn.data import ShardedDataset
    from tpucfn.models import ResNet, ResNetConfig
    from tpucfn.parallel import dense_rules
    from tpucfn.train import Trainer

    run_dir = Path(args.run_dir)
    if args.data_url:
        # The reference's "aws s3 sync" staging step (SURVEY.md §2.1 S3
        # row): sync encoded shards down once, decode on the host.
        from tpucfn.data import stage_url

        shards = stage_url(args.data_url, run_dir / "data-cache",
                           owner_slice=(jax.process_index(),
                                        jax.process_count()))
    else:
        shards = stage_synthetic(
            "cifar10", run_dir / "data", n=args.num_examples,
            num_shards=max(8, jax.process_count()), seed=args.seed,
        )

    mesh = build_example_mesh(args)
    cfg = {
        "resnet20": ResNetConfig.resnet20_cifar,
        "resnet32": ResNetConfig.resnet32_cifar,
    }[args.network]()
    model = ResNet(cfg)
    sample = jnp.zeros((1, 32, 32, 3))

    def init_fn(rng):
        v = model.init(rng, sample, train=True)
        return v["params"], {"batch_stats": v["batch_stats"]}

    def loss_fn(params, mstate, batch, rng):
        logits, upd = model.apply(
            {"params": params, **mstate}, batch["image"], train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, ({"accuracy": acc}, dict(upd))

    def eval_loss_fn(params, mstate, batch, rng):
        logits = model.apply({"params": params, **mstate}, batch["image"], train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, ({"accuracy": acc}, mstate)

    from tpucfn.data.transforms import CIFAR_TRAIN, Compose, normalize

    if args.data_url:
        # Encoded shards: decode, optional CIFAR pad-crop/mirror, then
        # map 0-255 pixels to [-1, 1] (shape/color stats are not
        # CIFAR's, so channel-neutral normalization).
        from tpucfn.data import decode_transform

        steps_t = [decode_transform()]
        if args.augment:
            steps_t.append(CIFAR_TRAIN)
        steps_t.append(normalize((127.5,) * 3, (127.5,) * 3))
        transform = Compose(steps_t)
    elif args.augment:
        transform = CIFAR_TRAIN
    else:
        transform = None
    loader_kw = dict(batch_size_per_process=per_process_batch(args),
                     seed=args.seed, transform=transform,
                     cache_in_memory=not args.data_url)
    if args.loader_workers < 0:
        from tpucfn.data import MultiProcessLoader

        ds = MultiProcessLoader(shards, num_workers=-args.loader_workers,
                                **loader_kw)
    else:
        ds = ShardedDataset(shards, num_workers=args.loader_workers,
                            **loader_kw)

    eval_ds = None
    if args.eval_every:
        if args.eval_url:
            from tpucfn.data import decode_transform, stage_url

            eval_shards = stage_url(args.eval_url, run_dir / "eval-cache",
                                    owner_slice=(jax.process_index(),
                                                 jax.process_count()))
            eval_ds = ShardedDataset(
                eval_shards, shuffle=False, cache_in_memory=False,
                batch_size_per_process=per_process_batch(args),
                transform=Compose([decode_transform(),
                                   normalize((127.5,) * 3, (127.5,) * 3)]))
        else:
            eval_shards = stage_synthetic(
                "cifar10", run_dir / "eval", n=max(64, args.num_examples // 4),
                num_shards=max(8, jax.process_count()), seed=args.seed + 1,
            )
            eval_ds = ShardedDataset(
                eval_shards, shuffle=False,
                batch_size_per_process=per_process_batch(args))

    if args.cosine:
        # The train-to-accuracy recipe (mirrors the ImageNet example):
        # linear warmup into cosine decay over the full step budget.
        steps_total = args.steps or len(ds) * args.num_epochs
        tx = optax.chain(
            optax.add_decayed_weights(1e-4),
            optax.sgd(
                optax.warmup_cosine_decay_schedule(
                    0.0, args.lr, min(200, max(1, steps_total // 10)),
                    steps_total),
                momentum=0.9, nesterov=True,
            ),
        )
    else:
        tx = optax.sgd(args.lr, momentum=0.9, nesterov=True)
    trainer = Trainer(mesh, dense_rules(fsdp=args.fsdp > 1), loss_fn, tx, init_fn,
                      eval_loss_fn=eval_loss_fn)

    run_train_loop(trainer, ds, mesh, args, items_per_step=args.batch_size,
                   eval_ds=eval_ds)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
