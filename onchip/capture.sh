#!/bin/bash
# Sequential on-chip benchmark capture (VERDICT r2 item 1).
# Runs each bench as its own bounded step so partial results survive a
# tunnel wedge; never runs two JAX clients concurrently.
set -u
cd /root/repo
mkdir -p onchip
log=onchip/capture.log
echo "=== capture start $(date -u +%FT%TZ) ===" >> "$log"

run() {
  name=$1; shift
  echo "--- $name start $(date -u +%FT%TZ)" >> "$log"
  "$@" > "onchip/$name.out" 2> "onchip/$name.err"
  echo "--- $name rc=$? end $(date -u +%FT%TZ)" >> "$log"
}

run bench_resnet_full timeout 3600 python bench.py
run bench_llama      timeout 3600 env TPUCFN_BENCH_MODEL=llama python bench.py
run flash_s2k        timeout 1800 python benches/flash_bench.py --seqs 2048
run flash_s8k        timeout 1800 python benches/flash_bench.py --seqs 8192
run flash_s32k       timeout 2400 python benches/flash_bench.py --seqs 32768
echo "=== capture done $(date -u +%FT%TZ) ===" >> "$log"
