#!/usr/bin/env python
"""Distributed CIFAR-10 ResNet training — the minimum end-to-end slice.

Capability parity with the reference's flagship walkthrough (SURVEY.md
§3.2; BASELINE config 1):

    reference:  ../../tools/launch.py -n $DEEPLEARNING_WORKERS_COUNT \
                   -H $DEEPLEARNING_WORKERS_PATH \
                   python train_cifar10.py --network resnet --kv-store dist_sync
    tpucfn:     tpucfn launch examples/cifar10_resnet20.py -- \
                   --network resnet20 --kv-store dist_sync

Same UX; under the hood the per-batch kvstore.push/pull against parameter
servers is replaced by one jit-compiled SPMD step whose gradient psum XLA
emits over ICI. ``--kv-store dist_sync`` is accepted (and means what it
meant: synchronous data parallelism); there is simply no server process to
run anymore.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent))
from common import (  # noqa: E402
    add_cluster_args,
    build_example_mesh,
    per_process_batch,
    run_train_loop,
    stage_synthetic,
)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_cluster_args(p)
    p.add_argument("--network", default="resnet20", choices=["resnet20", "resnet32"])
    p.add_argument("--num-examples", type=int, default=2048,
                   help="synthetic dataset size to stage")
    p.add_argument("--augment", action="store_true",
                   help="pad-crop + mirror augmentation (the CIFAR recipe)")
    args = p.parse_args()

    from tpucfn.launch import initialize_runtime

    initialize_runtime()

    import jax
    import jax.numpy as jnp
    import optax

    from tpucfn.data import ShardedDataset
    from tpucfn.models import ResNet, ResNetConfig
    from tpucfn.parallel import dense_rules
    from tpucfn.train import Trainer

    run_dir = Path(args.run_dir)
    shards = stage_synthetic(
        "cifar10", run_dir / "data", n=args.num_examples,
        num_shards=max(8, jax.process_count()), seed=args.seed,
    )

    mesh = build_example_mesh(args)
    cfg = {
        "resnet20": ResNetConfig.resnet20_cifar,
        "resnet32": ResNetConfig.resnet32_cifar,
    }[args.network]()
    model = ResNet(cfg)
    sample = jnp.zeros((1, 32, 32, 3))

    def init_fn(rng):
        v = model.init(rng, sample, train=True)
        return v["params"], {"batch_stats": v["batch_stats"]}

    def loss_fn(params, mstate, batch, rng):
        logits, upd = model.apply(
            {"params": params, **mstate}, batch["image"], train=True,
            mutable=["batch_stats"],
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, ({"accuracy": acc}, dict(upd))

    def eval_loss_fn(params, mstate, batch, rng):
        logits = model.apply({"params": params, **mstate}, batch["image"], train=False)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, ({"accuracy": acc}, mstate)

    tx = optax.sgd(args.lr, momentum=0.9, nesterov=True)
    trainer = Trainer(mesh, dense_rules(fsdp=args.fsdp > 1), loss_fn, tx, init_fn,
                      eval_loss_fn=eval_loss_fn)

    transform = None
    if args.augment:
        from tpucfn.data.transforms import CIFAR_TRAIN

        transform = CIFAR_TRAIN
    ds = ShardedDataset(shards, batch_size_per_process=per_process_batch(args),
                        seed=args.seed, transform=transform)
    eval_ds = None
    if args.eval_every:
        eval_shards = stage_synthetic(
            "cifar10", run_dir / "eval", n=max(64, args.num_examples // 4),
            num_shards=max(8, jax.process_count()), seed=args.seed + 1,
        )
        eval_ds = ShardedDataset(eval_shards, shuffle=False,
                                 batch_size_per_process=per_process_batch(args))
    run_train_loop(trainer, ds, mesh, args, items_per_step=args.batch_size,
                   eval_ds=eval_ds)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
