"""Goodput chaos-drill acceptance (ISSUE 5): a scripted mid-run kill →
gang restart → resume drill must produce a `tpucfn obs goodput --json`
report whose buckets sum to within 5% of the wall time it measured,
with nonzero restart_downtime_s and lost_work_s attributed to the
injected incident.

Multi-second by construction (each worker pays a jax+orbax import) —
``slow``-marked, excluded from tier-1 like the ft e2e drill.
"""

import json
import os
import sys
import time
from pathlib import Path

import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.ft import (
    ChaosEvent,
    ChaosSpec,
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.obs import MetricRegistry

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
WORKER = str(REPO / "tests" / "ft_e2e_worker.py")

TOTAL_STEPS = 40
CKPT_EVERY = 10
# Kill off a checkpoint boundary so the rewind DEFINITELY re-runs work:
# resume is from step <= 21, the kill landed at >= 25, so steps 21..24
# are paid twice whatever the detection jitter does.
KILL_AT_STEP = 25


def _contract(tmp_path, n) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def test_chaos_drill_goodput_report_sums_to_wall(tmp_path):
    run_dir = tmp_path / "drill"
    ft_dir = run_dir / "ft"
    run_dir.mkdir()
    env = {"FT_E2E_RUN_DIR": str(run_dir),
           "FT_E2E_TOTAL_STEPS": str(TOTAL_STEPS),
           "FT_E2E_CKPT_EVERY": str(CKPT_EVERY),
           "FT_E2E_STEP_SLEEP": "0.05",
           "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get(
               "PYTHONPATH", "")}
    os.environ.update(env)
    launcher = Launcher(_contract(run_dir, 2), LocalTransport(),
                        ft_dir=str(ft_dir), ft_heartbeat_s=0.2)
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=2,
        config=MonitorConfig(interval_s=0.2, startup_grace_s=120.0))
    chaos = ChaosSpec(events=(
        ChaosEvent(action="kill", at_step=KILL_AT_STEP, host=0),))
    coord = GangCoordinator(
        launcher, [sys.executable, WORKER],
        policy=GangRestart(RestartBudget(1)), monitor=monitor,
        registry=MetricRegistry(), ft_dir=ft_dir, ckpt_dir=run_dir / "ckpt",
        poll_interval=0.02, term_grace_s=1.0, chaos=chaos)
    t0 = time.monotonic()
    rc = coord.run()
    measured_wall = time.monotonic() - t0
    assert rc == 0, "gang must finish cleanly after one recovery"
    assert coord.chaos.done(), "the scripted kill must have fired"

    # -- the acceptance report, through the real CLI ---------------------
    from tpucfn.cli.main import main

    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["obs", "goodput", "--run-dir", str(run_dir), "--json"])
    assert rc == 0
    rep = json.loads(buf.getvalue())

    assert rep["num_hosts"] == 2
    # buckets sum to within 5% of the wall the ledger measured (by
    # construction the residual is float noise; 5% is the acceptance
    # ceiling) and the ledger wall cannot exceed what the test measured.
    assert rep["wall_s"] > 0
    assert abs(rep["accounted_s"] - rep["wall_s"]) <= 0.05 * rep["wall_s"]
    assert rep["wall_s"] <= measured_wall + 0.5
    for host_rep in rep["hosts"].values():
        assert (abs(host_rep["accounted_s"] - host_rep["wall_s"])
                <= 0.05 * host_rep["wall_s"])

    # -- the injected incident shows up as downtime + lost work ----------
    assert rep["restart_downtime_s"] > 0
    assert rep["lost_work_s"] > 0
    assert rep["lost_steps"] >= 4  # 21..24 at minimum, per host >= ...
    # every host restarted once: two ledger windows each
    assert all(h["windows"] == 2 for h in rep["hosts"].values())
    # the coordinator attributed it: one enriched incident row
    [inc] = rep["incidents"]
    assert inc["action"] == "gang_restart"
    assert inc["downtime_s"] > 0
    assert inc["detection_s"] is not None
    assert inc["fleet_step"] is not None and inc["fleet_step"] >= KILL_AT_STEP
    # the merge attributes the ledger's re-run steps to this incident
    assert inc["lost_steps"] == rep["lost_steps"]
    # productive work dominates a 2-host drill with one restart
    assert 0 < rep["goodput_ratio"] <= 1
    assert rep["productive_steps"] >= 2 * TOTAL_STEPS  # both hosts finish
