"""Pipeline-parallel Llama: same params, same numbers as the scanned
model, trains under the Trainer with stage-sharded params."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpucfn.mesh import MeshSpec, build_mesh
from tpucfn.models.llama import Llama, LlamaConfig, causal_lm_loss
from tpucfn.models.llama_pp import pipelined_llama_apply, pp_sharding_rules
from tpucfn.parallel import shard_batch
from tpucfn.train import Trainer


@pytest.fixture()
def mesh_pp4d2():
    return build_mesh(MeshSpec(pipeline=4, data=2))


def _cfg(n_layers=4):
    return dataclasses.replace(LlamaConfig.tiny(), n_layers=n_layers)


def _tokens(b=8, s=16, vocab=256, seed=0):
    rs = np.random.RandomState(seed)
    return rs.randint(0, vocab, (b, s)).astype(np.int32)


def test_pp_forward_matches_scanned(mesh_pp4d2):
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens())
    params = model.init(jax.random.key(0), toks)["params"]
    ref = model.apply({"params": params}, toks)
    out = jax.jit(
        lambda p, t: pipelined_llama_apply(cfg, mesh_pp4d2, p, t, num_microbatches=4)
    )(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_pp_requires_scanned_params(mesh_pp4d2):
    cfg = dataclasses.replace(_cfg(), scan_layers=False)
    with pytest.raises(ValueError, match="scan_layers"):
        pp_sharding_rules(cfg)


def test_pp_training_learns_with_stage_sharded_params(mesh_pp4d2):
    cfg = _cfg()
    model = Llama(cfg)
    sample = jnp.zeros((8, 16), jnp.int32)

    def init_fn(rng):
        return model.init(rng, sample)["params"], {}

    def loss_fn(params, mstate, batch, rng):
        logits = pipelined_llama_apply(cfg, mesh_pp4d2, params, batch["tokens"],
                                       num_microbatches=4)
        loss, acc = causal_lm_loss(logits, batch["tokens"])
        return loss, ({"accuracy": acc}, mstate)

    trainer = Trainer(mesh_pp4d2, pp_sharding_rules(cfg), loss_fn,
                      optax.adamw(3e-3), init_fn)
    state = trainer.init(jax.random.key(0))

    # block params live stage-sharded: 4 layers / pipeline=4 -> 1 per stage
    qk = state.params["layers"]["attn"]["q_proj"]["kernel"]
    assert qk.sharding.spec == P("pipeline")
    assert qk.addressable_shards[0].data.shape[0] == 1

    batch = shard_batch(mesh_pp4d2, {"tokens": _tokens()})
    first = None
    for _ in range(15):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first * 0.9


def test_pp_gradients_match_scanned(mesh_pp4d2):
    cfg = _cfg()
    model = Llama(cfg)
    toks = jnp.asarray(_tokens(b=4))
    params = model.init(jax.random.key(1), toks)["params"]

    def loss_pp(p):
        logits = pipelined_llama_apply(cfg, mesh_pp4d2, p, toks, num_microbatches=2)
        return causal_lm_loss(logits, toks)[0]

    def loss_ref(p):
        return causal_lm_loss(model.apply({"params": p}, toks), toks)[0]

    g_pp = jax.jit(jax.grad(loss_pp))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    qk_pp = np.asarray(g_pp["layers"]["attn"]["q_proj"]["kernel"])
    qk_ref = np.asarray(g_ref["layers"]["attn"]["q_proj"]["kernel"])
    np.testing.assert_allclose(qk_pp, qk_ref, atol=5e-4)
    emb_pp = np.asarray(g_pp["embed_tokens"]["embedding"])
    emb_ref = np.asarray(g_ref["embed_tokens"]["embedding"])
    np.testing.assert_allclose(emb_pp, emb_ref, atol=5e-4)
