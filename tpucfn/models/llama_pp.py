"""Pipeline-parallel execution of the Llama stack.

Same params, different schedule: the scanned Llama param tree (leading
``layers`` axis) is sharded over the ``pipeline`` mesh axis — stage p
holds layers [p·L/P, (p+1)·L/P) — and the forward runs the GPipe
microbatch schedule from :mod:`tpucfn.parallel.pipeline` inside a
``shard_map``. Embedding, final norm, and LM head compute replicated on
every stage (cheap relative to the block stack; revisit for huge vocab).

Composition in this version: pipeline × data (batch shards ride along as
unsharded-per-stage slices; the only cross-shard traffic is the
stage-boundary ppermute). TP/FSDP × PP composition is a known gap tracked
in PARITY.md.

Checkpoints interchange with the plain :class:`tpucfn.models.llama.Llama`
— the param tree is identical; only placement and schedule differ.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import flax.linen as nn

from tpucfn.mesh import AXIS_PIPELINE, BATCH_AXES
from tpucfn.models.layers import RMSNorm
from tpucfn.models.llama import LlamaBlock, LlamaConfig
from tpucfn.ops.attention import dot_product_attention
from tpucfn.parallel.pipeline import gpipe, microbatch, unmicrobatch
from tpucfn.parallel.sharding import ShardingRules


def pp_sharding_rules(cfg: LlamaConfig) -> ShardingRules:
    """Stage-sharded layout: every scanned block param shards its leading
    (layer) dim over ``pipeline``; embed/norm/head replicate."""
    if not cfg.scan_layers:
        raise ValueError("pipeline execution needs scan_layers=True (stacked params)")
    return ShardingRules((
        (r"(^|/)layers/", P(AXIS_PIPELINE)),
        (r".*", P()),
    ))


def pipelined_llama_apply(
    cfg: LlamaConfig,
    mesh: Mesh,
    params,
    tokens: jax.Array,
    *,
    num_microbatches: int = 4,
) -> jax.Array:
    """tokens (B, S) → logits (B, S, vocab), numerically equal to
    ``Llama(cfg).apply`` with the same params (tests assert it)."""
    if not cfg.scan_layers:
        raise ValueError("pipeline execution needs scan_layers=True")

    embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
    x = embed.apply({"params": params["embed_tokens"]}, tokens)

    def stage_fn(stage_params, h):
        """Apply this stage's layer slice (lax.scan over local layers)."""

        def body(carry, layer_params):
            if cfg.remat:
                apply = jax.checkpoint(
                    lambda p, c: LlamaBlock(cfg, dot_product_attention).apply(
                        {"params": p}, c
                    )[0],
                    prevent_cse=False,
                )
                carry = apply(layer_params, carry)
            else:
                carry, _ = LlamaBlock(cfg, dot_product_attention).apply(
                    {"params": layer_params}, carry
                )
            return carry, None

        (h_out, _), _ = lax.scan(body, (h, jnp.zeros((), jnp.int32)), stage_params)
        return h_out

    mb = microbatch(x, num_microbatches)  # (M, B/M, S, D)
    layer_specs = jax.tree.map(lambda _: P(AXIS_PIPELINE), params["layers"])
    mb_spec = P(None, BATCH_AXES)

    run = jax.shard_map(
        lambda p, xs: gpipe(stage_fn, p, xs),
        mesh=mesh,
        in_specs=(layer_specs, mb_spec),
        out_specs=mb_spec,
        check_vma=False,
    )
    x = unmicrobatch(run(params["layers"], mb))

    x = RMSNorm(cfg.norm_eps, cfg.dtype).apply({"params": params["final_norm"]}, x)
    logits = nn.DenseGeneral(cfg.vocab_size, use_bias=False, dtype=jnp.float32,
                             param_dtype=cfg.param_dtype).apply(
        {"params": params["lm_head"]}, x.astype(jnp.float32)
    )
    return logits
