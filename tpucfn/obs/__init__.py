from tpucfn.obs.metrics import MetricLogger, StepTimer  # noqa: F401
from tpucfn.obs.profiler import (  # noqa: F401
    enable_compile_cache,
    profile_steps,
    start_profiler_server,
)
