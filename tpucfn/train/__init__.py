from tpucfn.train.state import TrainState  # noqa: F401
from tpucfn.train.trainer import Trainer, TrainerConfig  # noqa: F401
from tpucfn.train.lora import (  # noqa: F401
    lora_init,
    lora_materialize,
    lora_sharding_rules,
)
