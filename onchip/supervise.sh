#!/bin/bash
# Retry megabench until it completes (rc 0). Every failure — rc 42
# (client creation failed), rc 43 (watchdog; may have killed a
# half-created client on a wedged tunnel), rc 44 (phase raised; tunnel
# likely dropped mid-bench), or an unexpected crash — sleeps on the
# tunnel-recovery timescale before retrying, because almost every
# failure mode here ends with a dead/wedged client and an immediate
# retry just burns another connection. Completed phases are
# checkpointed in megabench_state.json, so retries resume. The attempt
# cap bounds deterministic failures. Never kills a running attempt.
cd /root/repo
log=onchip/megabench.log
# Single-instance guard: two megabench clients racing for the one
# tunnel slot is worse than none (each wedges the other). flock on a
# lockfile held for the supervisor's lifetime.
exec 9>/tmp/tpucfn-supervise.lock
if ! flock -n 9; then
  echo "=== another supervisor holds the lock; exiting $(date -u +%FT%TZ) ===" >> "$log"
  exit 0
fi
# Run until the session deadline (default ~11h) rather than a fixed
# attempt count: fast client-creation failures would otherwise exhaust
# the cap in under 2h of a 12h session.
deadline=$(( $(date +%s) + ${SUPERVISE_BUDGET_S:-39600} ))
attempt=0
while [ "$(date +%s)" -lt "$deadline" ]; do
  attempt=$((attempt + 1))
  if pgrep -f "python[^ ]* .*onchip/megabench\.py" > /dev/null; then
    # A client from another lineage is alive; never race it for the
    # single tunnel slot.
    echo "=== attempt $attempt skipped: foreign megabench client alive $(date -u +%FT%TZ) ===" >> "$log"
    sleep 420
    continue
  fi
  echo "=== attempt $attempt $(date -u +%FT%TZ) ===" >> "$log"
  python onchip/megabench.py >> "$log" 2>&1
  rc=$?
  echo "=== attempt $attempt rc=$rc $(date -u +%FT%TZ) ===" >> "$log"
  if [ "$rc" -eq 0 ]; then exit 0; fi
  sleep 420
done
echo "=== supervisor deadline reached $(date -u +%FT%TZ) ===" >> "$log"
exit 1
