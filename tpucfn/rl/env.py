"""Built-in vectorized pure-jax environments for the Podracer RL plane.

Anakin's whole premise (PAPERS.md, arXiv:2104.06272) is that the env
step is a jitted function living on the SAME mesh as policy decode and
the learner update — no host round-trip anywhere in the acting loop.
That only works if the env itself is a pure jax function, so the plane
ships two: a K-armed contextual bandit (the observation IS the arm-mean
vector, so the optimal policy is learnable in a handful of updates —
the smoke/bench workload) and a small gridworld (multi-step credit
assignment for the A2C path).

Contract (both envs, and anything user-supplied to the actor):

* ``reset(key) -> (state, obs)`` — ``state`` is a pytree of arrays with
  leading dim ``num_envs``; ``obs`` is ``[num_envs, obs_dim]`` float32.
* ``step(state, action, key) -> (state, obs, reward, done)`` — pure,
  shape-static, **auto-resetting**: a done env is reseeded from ``key``
  inside the same call (the lax.scan rollout never branches on done).
* Everything is a deterministic function of ``(state, action, key)``,
  which is what makes episode trajectories bit-identical across runs
  and across a chaos-kill resume (the recovery drill's pin).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class BanditEnv:
    """Vectorized K-armed bandit with observable arm means.

    Every episode is one step: the observation is the per-arm mean
    vector (drawn uniform [0,1) at reset), reward is the chosen arm's
    mean, and the episode ends immediately — auto-reset redraws the
    means.  The optimal policy ("pick the argmax of the obs") is
    learnable by a linear layer, so return curves move within tens of
    updates: the canonical smoke/bench workload.
    """

    num_envs: int = 8
    num_arms: int = 4

    @property
    def obs_dim(self) -> int:
        return self.num_arms

    @property
    def num_actions(self) -> int:
        return self.num_arms

    def _draw(self, key: jax.Array) -> jax.Array:
        return jax.random.uniform(key, (self.num_envs, self.num_arms),
                                  jnp.float32)

    def reset(self, key: jax.Array):
        means = self._draw(key)
        return {"means": means}, means

    def step(self, state, action: jax.Array, key: jax.Array):
        means = state["means"]
        reward = jnp.take_along_axis(means, action[:, None], axis=1)[:, 0]
        done = jnp.ones((self.num_envs,), jnp.bool_)
        # one-step episodes: auto-reset IS the transition
        new_means = self._draw(key)
        return {"means": new_means}, new_means, reward, done


@dataclasses.dataclass(frozen=True)
class GridWorldEnv:
    """Vectorized ``size``×``size`` gridworld: reach the goal cell.

    Observation is ``[row, col, goal_row, goal_col] / (size-1)`` (4
    floats); actions are up/down/left/right with wall clamping; reward
    is +1 on reaching the goal (episode done) and a -0.05 living cost
    otherwise; episodes also time out at ``horizon`` steps.  Done envs
    auto-reset to a fresh random start/goal drawn from the step key.
    """

    num_envs: int = 8
    size: int = 5
    horizon: int = 20

    @property
    def obs_dim(self) -> int:
        return 4

    @property
    def num_actions(self) -> int:
        return 4

    def _spawn(self, key: jax.Array):
        kp, kg = jax.random.split(key)
        pos = jax.random.randint(kp, (self.num_envs, 2), 0, self.size)
        goal = jax.random.randint(kg, (self.num_envs, 2), 0, self.size)
        # a spawn on the goal would be a zero-length episode; shift one
        # column (wrapping) so start != goal always holds
        clash = jnp.all(pos == goal, axis=1, keepdims=True)
        pos = jnp.where(clash, (pos + jnp.array([0, 1])) % self.size, pos)
        return pos, goal

    def _obs(self, state):
        denom = jnp.float32(max(self.size - 1, 1))
        return jnp.concatenate(
            [state["pos"].astype(jnp.float32) / denom,
             state["goal"].astype(jnp.float32) / denom], axis=1)

    def reset(self, key: jax.Array):
        pos, goal = self._spawn(key)
        state = {"pos": pos, "goal": goal,
                 "t": jnp.zeros((self.num_envs,), jnp.int32)}
        return state, self._obs(state)

    def step(self, state, action: jax.Array, key: jax.Array):
        moves = jnp.array([[-1, 0], [1, 0], [0, -1], [0, 1]], jnp.int32)
        pos = jnp.clip(state["pos"] + moves[action], 0, self.size - 1)
        at_goal = jnp.all(pos == state["goal"], axis=1)
        t = state["t"] + 1
        done = at_goal | (t >= self.horizon)
        reward = jnp.where(at_goal, 1.0, -0.05).astype(jnp.float32)
        # auto-reset: done lanes get a fresh spawn and a zeroed clock
        new_pos, new_goal = self._spawn(key)
        d2 = done[:, None]
        state = {
            "pos": jnp.where(d2, new_pos, pos),
            "goal": jnp.where(d2, new_goal, state["goal"]),
            "t": jnp.where(done, 0, t),
        }
        return state, self._obs(state), reward, done


ENVS = {"bandit": BanditEnv, "gridworld": GridWorldEnv}


def make_env(name: str, num_envs: int):
    """Build one of the built-in envs by registry name."""
    try:
        cls = ENVS[name]
    except KeyError:
        raise ValueError(
            f"unknown rl env {name!r}; built-ins: {sorted(ENVS)}") from None
    return cls(num_envs=num_envs)
