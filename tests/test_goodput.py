"""Goodput accounting (ISSUE 5 tentpole): the per-host ledger
decomposes wall clock into buckets that SUM to wall time, re-run steps
land in lost_work, inter-window gaps in restart_downtime — and the
trainer's live efficiency gauges (train_mfu / train_step_time_s /
train_goodput_ratio) are pinned with a fake clock, no TPU involved."""

import json
import urllib.request

import pytest

from tpucfn.obs import MetricRegistry
from tpucfn.obs.goodput import (
    GoodputLedger,
    cost_analysis_flops,
    device_peak_flops,
    goodput_report,
    host_goodput,
    host_id_from_path,
    merge_goodput,
    read_goodput_dir,
    read_jsonl_counting,
    render_goodput,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _phase(led, clk, bucket, dur, step=None):
    """Real-writer convention: the phase runs, THEN the record is
    stamped — so a record's t is the phase's end."""
    clk.advance(dur)
    led.account(bucket, dur, step=step)


# ---- write side ----------------------------------------------------------

def test_ledger_writes_window_phase_close(tmp_path):
    clk = FakeClock()
    with GoodputLedger(tmp_path, 3, clock=clk, role="trainer") as led:
        assert led.enabled
        _phase(led, clk, "step", 0.5, step=1)
    lines = [json.loads(s) for s in
             (tmp_path / "goodput-host003.jsonl").read_text().splitlines()]
    assert [r["kind"] for r in lines] == ["window", "phase", "close"]
    assert lines[0]["role"] == "trainer" and lines[0]["host"] == 3
    assert lines[1] == {"kind": "phase", "bucket": "step", "dur_s": 0.5,
                        "host": 3, "step": 1, "t": 1000.5}


def test_noop_ledger_never_writes(tmp_path):
    led = GoodputLedger(None)
    assert not led.enabled
    led.account("step", 1.0, step=1)
    led.close()


# ---- decomposition -------------------------------------------------------

@pytest.fixture()
def interrupted_ledger(tmp_path):
    """One host: steps 1-5 (first is compile), ckpt, SIGKILL (no close),
    3 s gap, relaunch re-running steps 4-5 then finishing 6-8."""
    clk = FakeClock()
    led = GoodputLedger(tmp_path, 0, clock=clk)
    _phase(led, clk, "compile", 1.0, step=1)
    for s in range(2, 6):
        _phase(led, clk, "data_wait", 0.1, step=s)
        _phase(led, clk, "step", 0.4, step=s)
    _phase(led, clk, "ckpt", 0.3, step=5)
    led._f.close()  # SIGKILL: no close record
    led._f = None
    clk.advance(3.0)
    led2 = GoodputLedger(tmp_path, 0, clock=clk)
    _phase(led2, clk, "compile", 0.2, step=4)
    for s in range(5, 9):
        _phase(led2, clk, "step", 0.4, step=s)
    led2.close()
    return tmp_path


def test_buckets_sum_to_wall_and_rewind_is_lost_work(interrupted_ledger):
    by_host, skipped = read_goodput_dir(interrupted_ledger)
    assert skipped == 0
    rep = host_goodput(by_host[0])
    b = rep["buckets"]
    # THE invariant: every second of the host's span is in some bucket.
    assert rep["accounted_s"] == pytest.approx(rep["wall_s"])
    assert rep["unaccounted_s"] == pytest.approx(0.0)
    assert rep["windows"] == 2
    assert b["restart_downtime"] == pytest.approx(3.0)
    # step 5 was executed before the kill and re-run after the rewind;
    # the re-run (and only the re-run) is lost work.
    assert rep["lost_steps"] == 1
    assert b["lost_work"] == pytest.approx(0.4)
    assert rep["productive_steps"] == 7  # 2,3,4,5 then 6,7,8
    assert b["productive_step"] == pytest.approx(7 * 0.4)
    assert b["compile"] == pytest.approx(1.2)
    assert b["data_wait"] == pytest.approx(0.4)
    assert b["ckpt"] == pytest.approx(0.3)
    assert 0 < rep["goodput_ratio"] < 1


def test_merge_averages_hosts_and_keeps_invariant(interrupted_ledger):
    # add a second, uninterrupted host with a different span
    clk = FakeClock(2000.0)
    led = GoodputLedger(interrupted_ledger, 1, clock=clk)
    for s in range(1, 4):
        _phase(led, clk, "step", 0.5, step=s)
    led.close()
    by_host, skipped = read_goodput_dir(interrupted_ledger)
    rep = merge_goodput(by_host, skipped_lines=skipped)
    assert rep["num_hosts"] == 2
    assert rep["accounted_s"] == pytest.approx(rep["wall_s"])
    assert rep["wall_s"] == pytest.approx(
        (rep["hosts"]["0"]["wall_s"] + rep["hosts"]["1"]["wall_s"]) / 2)
    assert rep["lost_work_s"] > 0 and rep["restart_downtime_s"] > 0
    assert rep["lost_steps"] == 1
    text = render_goodput(rep)
    assert "restart_downtime" in text and "lost_work" in text


def test_incidents_merge_from_ft_events(interrupted_ledger, tmp_path):
    events = [
        {"ts": 1.0, "kind": "detect", "incident": 1,
         "failures": [{"host": 0, "kind": "crash", "rc": -9}]},
        {"ts": 1.5, "kind": "recovered", "incident": 1, "action": "gang",
         "mttr_s": 0.5},
        {"ts": 1.5, "kind": "goodput_incident", "incident": 1,
         "action": "gang", "downtime_s": 0.5, "detection_s": 0.05,
         "fleet_step": 5},
    ]
    by_host, _ = read_goodput_dir(interrupted_ledger)
    rep = merge_goodput(by_host, events)
    [inc] = rep["incidents"]
    # lost_steps is attributed from the ledger (step 5's re-run), not
    # from the event — the coordinator can't know it at recovery time.
    assert inc == {"incident": 1, "action": "gang", "ts": 1.5,
                   "downtime_s": 0.5, "detection_s": 0.05,
                   "fleet_step": 5, "lost_steps": 1,
                   "planned": False, "shrink": None, "ckpt": None,
                   "journal_replay_ms": None}
    assert rep["incident_downtime_s"] == pytest.approx(0.5)
    # older event files without the enriched record fall back to mttr_s
    rep2 = merge_goodput(by_host, events[:2])
    assert rep2["incidents"][0]["downtime_s"] == 0.5


def test_planned_incidents_are_flagged_and_split(interrupted_ledger):
    """Graceful-degradation fields (ISSUE 7): a drained preemption's
    incident row carries planned=true, shrink/ckpt detail passes
    through, and unplanned_downtime_s excludes the planned rows — a
    chosen restart must not read as a downtime regression."""
    events = [
        {"ts": 1.0, "kind": "detect", "incident": 1,
         "failures": [{"host": 1, "kind": "preempt", "lead_s": 30.0}]},
        {"ts": 1.4, "kind": "goodput_incident", "incident": 1,
         "action": "drain_restart", "planned": True, "downtime_s": 0.4,
         "detection_s": 0.01, "fleet_step": 5},
        {"ts": 2.0, "kind": "detect", "incident": 2,
         "failures": [{"host": 0, "kind": "crash", "rc": -9}]},
        {"ts": 2.6, "kind": "goodput_incident", "incident": 2,
         "action": "gang_restart", "planned": False, "downtime_s": 0.6,
         "detection_s": 0.02, "fleet_step": 7,
         "shrink": {"from_hosts": 2, "to_hosts": 1, "lost": [0],
                    "generation": 3}},
    ]
    by_host, _ = read_goodput_dir(interrupted_ledger)
    rep = merge_goodput(by_host, events)
    planned, unplanned = rep["incidents"]
    assert planned["planned"] is True and planned["action"] == "drain_restart"
    assert unplanned["planned"] is False
    assert unplanned["shrink"]["to_hosts"] == 1
    assert rep["incident_downtime_s"] == pytest.approx(1.0)
    assert rep["unplanned_downtime_s"] == pytest.approx(0.6)
    text = render_goodput(rep)
    assert "planned" in text  # the incident table names the split


def test_give_up_incident_still_gets_a_row(interrupted_ledger):
    """A budget-exhausted incident never writes recovered/
    goodput_incident — only detect/decide/give_up.  It must still appear
    in the report (it is the incident that ended the run), with unknown
    downtime rather than no row at all."""
    events = [
        {"ts": 1.0, "kind": "detect", "incident": 1,
         "failures": [{"host": 0, "kind": "crash", "rc": -9}]},
        {"ts": 1.5, "kind": "recovered", "incident": 1, "action": "gang",
         "mttr_s": 0.5},
        {"ts": 2.0, "kind": "detect", "incident": 2,
         "failures": [{"host": 0, "kind": "crash", "rc": -9}]},
        {"ts": 2.1, "kind": "decide", "incident": 2, "action": "give_up",
         "reason": "restart budget exhausted"},
        {"ts": 2.2, "kind": "give_up", "incident": 2, "rc": 137,
         "reason": "restart budget exhausted"},
    ]
    by_host, _ = read_goodput_dir(interrupted_ledger)
    rep = merge_goodput(by_host, events)
    assert [i["incident"] for i in rep["incidents"]] == [1, 2]
    final = rep["incidents"][1]
    assert final["action"] == "give_up"
    assert final["ts"] == 2.2
    assert final["downtime_s"] is None
    # unknown downtime must not poison the sum
    assert rep["incident_downtime_s"] == pytest.approx(0.5)
    # detect-only with no give_up/decide (observe-only incident) also rows
    rep2 = merge_goodput(by_host, events[:3])
    detect_only = rep2["incidents"][1]
    assert detect_only["incident"] == 2
    assert detect_only["action"] is None
    assert detect_only["ts"] == 2.0
    assert detect_only["downtime_s"] is None


def test_lost_steps_binned_by_time_not_step_number(tmp_path):
    # incident 1 (solo, no rewind) then incident 2 rewinding BELOW
    # incident 1's fleet_step: every re-run executes after incident 2's
    # recovery, so step-number binning would miscredit steps 4-5 to
    # incident 1 — time binning must give incident 2 all of them.
    clk = FakeClock(0.0)
    led = GoodputLedger(tmp_path, 0, clock=clk)
    for s in range(1, 11):
        _phase(led, clk, "step", 1.0, step=s)  # t=1..10
    led._f.close()  # killed
    led._f = None
    clk.advance(2.0)
    led2 = GoodputLedger(tmp_path, 0, clock=clk)
    for s in range(4, 11):
        _phase(led2, clk, "step", 1.0, step=s)  # re-runs at t=13..19
    led2.close()
    events = [
        {"ts": 5.5, "kind": "goodput_incident", "incident": 1,
         "action": "solo_restart", "downtime_s": 0.1,
         "detection_s": 0.05, "fleet_step": 5},
        {"ts": 11.5, "kind": "goodput_incident", "incident": 2,
         "action": "gang_restart", "downtime_s": 0.5,
         "detection_s": 0.05, "fleet_step": 10},
    ]
    by_host, _ = read_goodput_dir(tmp_path)
    rep = merge_goodput(by_host, events)
    assert [i["lost_steps"] for i in rep["incidents"]] == [0, 7]
    assert rep["lost_steps"] == 7


def test_adversarial_ledger_skips_and_counts(tmp_path):
    p = tmp_path / "goodput-host000.jsonl"
    p.write_text(
        json.dumps({"kind": "window", "host": 0, "t": 1.0}) + "\n"
        + json.dumps({"kind": "phase", "bucket": "step", "dur_s": 0.5,
                      "step": 1, "t": 1.5}) + "\n"
        + "{\"kind\": \"phase\", \"bucket\": \"st"  # torn tail
    )
    (tmp_path / "goodput-host001.jsonl").write_text("")  # empty host
    (tmp_path / "goodput-host002.jsonl").write_text(
        json.dumps({"kind": "phase", "bucket": "nonsense", "dur_s": 1.0,
                    "t": 2.0}) + "\n")  # malformed-only host
    by_host, skipped = read_goodput_dir(tmp_path)
    assert skipped == 1  # the torn line, counted not raised
    rep = merge_goodput(by_host, skipped_lines=skipped)
    assert rep["skipped_lines"] == 1
    assert rep["num_hosts"] >= 1
    assert rep["hosts"]["0"]["buckets"]["productive_step"] == 0.5
    assert rep["hosts"]["2"]["malformed_records"] == 1


def test_nonfinite_durations_are_malformed_not_poison(tmp_path):
    """json.loads accepts bare NaN/Infinity — one accumulated NaN would
    poison every downstream sum AND make --json output unparseable by
    strict readers, so non-finite dur_s/t must be skip-and-counted."""
    import math

    p = tmp_path / "goodput-host000.jsonl"
    p.write_text(
        json.dumps({"kind": "window", "host": 0, "t": 1.0}) + "\n"
        + '{"kind": "phase", "bucket": "step", "dur_s": NaN, '
        '"step": 1, "t": 1.2}\n'
        + '{"kind": "phase", "bucket": "ckpt", "dur_s": Infinity, '
        '"t": 1.3}\n'
        + '{"kind": "phase", "bucket": "step", "dur_s": 0.1, '
        '"step": 2, "t": NaN}\n'
        + json.dumps({"kind": "phase", "bucket": "step", "dur_s": 0.5,
                      "step": 3, "t": 1.5}) + "\n")
    by_host, skipped = read_goodput_dir(tmp_path)
    rep = merge_goodput(by_host, skipped_lines=skipped)
    host = rep["hosts"]["0"]
    assert host["malformed_records"] == 3
    assert host["buckets"]["productive_step"] == 0.5
    assert all(math.isfinite(v) for v in host["buckets"].values())
    assert math.isfinite(rep["wall_s"]) and math.isfinite(rep["accounted_s"])
    # the report must serialize under STRICT json (what jq/JS parse);
    # allow_nan=False raises on any NaN/inf that leaked through
    json.dumps(rep, allow_nan=False)


def test_goodput_report_on_missing_dirs(tmp_path):
    rep = goodput_report(tmp_path / "nope", tmp_path / "also-nope.jsonl")
    assert rep["num_hosts"] == 0 and rep["wall_s"] == 0.0


def test_read_jsonl_counting_tolerates_non_utf8(tmp_path):
    # disk corruption / binary garbage appended: skip-and-count, never
    # raise — one invalid byte must not take down the whole report.
    p = tmp_path / "goodput-host000.jsonl"
    p.write_bytes(
        json.dumps({"kind": "window", "host": 0, "t": 1.0}).encode()
        + b"\n" + b"\xff\xfe{garbage\n"
        + json.dumps({"kind": "close", "t": 2.0}).encode() + b"\n")
    recs, skipped = read_jsonl_counting(p)
    assert [r["kind"] for r in recs] == ["window", "close"]
    assert skipped == 1


def test_host_id_from_path():
    from pathlib import Path
    assert host_id_from_path(Path("/x/goodput-host007.jsonl")) == 7
    assert host_id_from_path(Path("/x/hb-host012.jsonl")) == 12
    assert host_id_from_path(Path("/x/notes.jsonl")) is None


# ---- live efficiency gauges (acceptance: fake clock, no TPU) -------------

def test_trainer_obs_exports_live_mfu_on_metrics_endpoint(tmp_path):
    from tpucfn.obs.server import ObsServer
    from tpucfn.train.trainer import TrainerObs

    clk = FakeClock(0.0)
    reg = MetricRegistry(labels={"host": "0", "role": "trainer"})
    led = GoodputLedger(tmp_path, 0, clock=clk)
    obs = TrainerObs(reg, ledger=led, clock=clk)
    # 2 TFLOP per device-step at 200 TFLOP/s peak, 0.1 s steps -> MFU 0.1
    obs.set_model_flops(2.0e12, 200e12)
    for i in range(1, 4):
        with obs.data_wait(i):
            clk.advance(0.05)
        with obs.step(i):
            clk.advance(0.1)
    m = reg.varz()["metrics"]
    assert m["train_mfu"] == pytest.approx(2.0e12 / 0.1 / 200e12)
    assert m["train_step_time_s"] == pytest.approx(0.1)
    # productive 0.2 (first step is compile) over 0.45 wall
    assert m["train_goodput_ratio"] == pytest.approx(0.2 / 0.45)
    srv = ObsServer(reg, port=0, host="127.0.0.1", role="trainer")
    try:
        body = urllib.request.urlopen(srv.url("/metrics"),
                                      timeout=5).read().decode()
    finally:
        srv.close()
    for name in ("train_mfu", "train_step_time_s", "train_goodput_ratio"):
        assert any(line.startswith(name + "{") for line
                   in body.splitlines()), name
    led.close()
    # and the same phases landed in the goodput ledger
    rep = goodput_report(tmp_path)
    b = rep["hosts"]["0"]["buckets"]
    assert b["compile"] == pytest.approx(0.1)
    assert b["productive_step"] == pytest.approx(0.2)
    assert b["data_wait"] == pytest.approx(0.15)
    assert rep["accounted_s"] == pytest.approx(rep["wall_s"])


def test_mfu_gauge_stays_unset_without_flops_or_peak():
    from tpucfn.train.trainer import TrainerObs

    clk = FakeClock()
    reg = MetricRegistry()
    obs = TrainerObs(reg, clock=clk)
    for i in (1, 2):
        with obs.step(i):
            clk.advance(0.1)
    assert reg.varz()["metrics"]["train_mfu"] == 0.0  # never armed


# ---- cost-analysis helpers ----------------------------------------------

def test_cost_analysis_flops_unwraps_list_and_dict():
    assert cost_analysis_flops([{"flops": 3.0}]) == 3.0  # jax <= 0.4.x
    assert cost_analysis_flops({"flops": 5.0}) == 5.0    # jax >= 0.5
    assert cost_analysis_flops([]) is None
    assert cost_analysis_flops(None) is None
    assert cost_analysis_flops([{"bytes accessed": 1.0}]) is None
    assert cost_analysis_flops("garbage") is None


def test_device_peak_flops_table():
    assert device_peak_flops("TPU v5e") == pytest.approx(197e12)
    assert device_peak_flops("TPU v4") == pytest.approx(275e12)
    assert device_peak_flops("cpu") is None


def test_trainer_step_cost_flops_is_none_before_compile():
    # no _jit_step yet -> None, no raise (the best-effort contract)
    from tpucfn.train.trainer import Trainer

    t = Trainer.__new__(Trainer)
    t._jit_step = None
    assert Trainer.step_cost_flops(t, batch=None) is None


# ---- compile-bucket refinement (ISSUE 6 satellite) ------------------------

def test_compile_cached_is_its_own_bucket_and_advances_max_step(tmp_path):
    # warm restart: the second incarnation's first step was served from
    # the persistent cache — it must land in compile_cached, still
    # advance the re-run horizon, and keep the sum-to-wall invariant
    clk = FakeClock()
    led = GoodputLedger(tmp_path, 0, clock=clk)
    _phase(led, clk, "compile", 1.0, step=1)
    _phase(led, clk, "step", 0.4, step=2)
    led._f.close()  # SIGKILL
    led._f = None
    clk.advance(2.0)
    led2 = GoodputLedger(tmp_path, 0, clock=clk)
    _phase(led2, clk, "compile_cached", 0.1, step=1)
    _phase(led2, clk, "step", 0.4, step=2)  # re-run: lost_work
    _phase(led2, clk, "step", 0.4, step=3)
    led2.close()
    rep = host_goodput(read_goodput_dir(tmp_path)[0][0])
    assert rep["buckets"]["compile"] == pytest.approx(1.0)
    assert rep["buckets"]["compile_cached"] == pytest.approx(0.1)
    assert rep["buckets"]["lost_work"] == pytest.approx(0.4)
    assert rep["lost_steps"] == 1
    assert rep["malformed_records"] == 0
    assert abs(rep["unaccounted_s"]) < 1e-9


def test_compile_cache_probe_decides_the_bucket(tmp_path):
    from tpucfn.obs import CompileCacheProbe
    from tpucfn.train.trainer import TrainerObs

    cache = tmp_path / "xla_cache"

    def run_first_step(probe, ledger_dir, during_step=None):
        clk = FakeClock(0.0)
        led = GoodputLedger(ledger_dir, 0, clock=clk)
        obs = TrainerObs(MetricRegistry(), ledger=led, clock=clk,
                         compile_probe=probe)
        with obs.step(1):
            if during_step is not None:
                during_step()
            clk.advance(1.0)
        led.close()
        recs, _ = read_jsonl_counting(
            ledger_dir / "goodput-host000.jsonl")
        return [r["bucket"] for r in recs if r.get("kind") == "phase"]

    # cold: XLA persists a new entry DURING the first step -> compile
    cache.mkdir()
    (cache / "step-atime").write_bytes(b"\0" * 8)  # pre-existing pair
    (cache / "step-cache").write_bytes(b"x")
    probe = CompileCacheProbe(cache)
    assert run_first_step(
        probe, tmp_path / "cold",
        during_step=lambda: (cache / "new-cache").write_text("x"),
    ) == ["compile"]
    # warm: jax's cache get() rewrites the *-atime sidecar on every
    # read — a served-from-cache first step leaves exactly that trace
    probe2 = CompileCacheProbe(cache)
    assert run_first_step(
        probe2, tmp_path / "warm",
        during_step=lambda: (cache / "step-atime").write_bytes(b"\1" * 8),
    ) == ["compile_cached"]
    # a SHARED non-empty cache holding none of this run's programs:
    # nothing read, nothing written -> unknown -> plain compile (a
    # sub-threshold cold compile must NOT read as a phantom hit)
    probe3 = CompileCacheProbe(cache)
    assert run_first_step(probe3, tmp_path / "shared") == ["compile"]
    # resumed run: the restore path writes/reads entries BEFORE step 1;
    # the rearm at step entry discounts them, and step 1's own cache
    # read still lands the hit
    probe4 = CompileCacheProbe(cache)
    (cache / "restore-cache").write_text("x")   # restore's own program
    (cache / "step-atime").write_bytes(b"\2" * 8)  # restore-path read
    assert run_first_step(
        probe4, tmp_path / "resumed",
        during_step=lambda: (cache / "step-atime").write_bytes(b"\3" * 8),
    ) == ["compile_cached"]
    # unknown: empty cache, nothing written -> plain compile
    empty = tmp_path / "empty_cache"
    probe5 = CompileCacheProbe(empty)
    assert probe5.hit() is None
    assert run_first_step(probe5, tmp_path / "unk") == ["compile"]
    # no probe at all keeps the historical charge
    assert run_first_step(None, tmp_path / "noprobe") == ["compile"]


# -- fleet warm start (ISSUE 13) ---------------------------------------------

def test_compile_fetched_bucket_merges_and_sums_to_wall():
    """The fetch-hit first step gets its own column; the sums-to-wall
    invariant holds with it."""
    recs = [
        {"kind": "window", "host": 0, "t": 100.0},
        {"kind": "phase", "bucket": "compile_fetched", "dur_s": 2.0,
         "step": 1, "t": 103.0, "host": 0},
        {"kind": "phase", "bucket": "step", "dur_s": 0.5, "step": 2,
         "t": 104.0, "host": 0},
        {"kind": "close", "host": 0, "t": 104.0},
    ]
    rep = host_goodput(recs)
    assert rep["buckets"]["compile_fetched"] == 2.0
    assert rep["buckets"]["compile"] == 0.0
    assert abs(rep["unaccounted_s"]) < 1e-9
    # a fetched first step still advances the re-run horizon
    assert rep["productive_steps"] == 1


def test_incident_rows_carry_journal_replay_ms():
    """ISSUE 13 satellite: the adopted coordinator's replay time rides
    the goodput_incident row into the merged report and its total."""
    by_host = {0: [
        {"kind": "window", "host": 0, "t": 10.0},
        {"kind": "phase", "bucket": "step", "dur_s": 1.0, "step": 1,
         "t": 12.0, "host": 0},
        {"kind": "close", "host": 0, "t": 12.0},
    ]}
    events = [
        {"kind": "goodput_incident", "incident": 1, "ts": 11.0,
         "action": "gang_restart", "downtime_s": 3.0,
         "detection_s": 0.05, "fleet_step": 1,
         "journal_replay_ms": 12.5},
    ]
    rep = merge_goodput(by_host, events)
    assert rep["incidents"][0]["journal_replay_ms"] == 12.5
    assert rep["journal_replay_ms"] == 12.5


def test_incident_without_replay_detail_stays_none():
    rep = merge_goodput({}, [
        {"kind": "goodput_incident", "incident": 2, "ts": 1.0,
         "action": "solo_restart", "downtime_s": 1.0}])
    assert rep["incidents"][0]["journal_replay_ms"] is None
    assert rep["journal_replay_ms"] == 0.0
