"""LoRA adapters: functional delta-param finetuning over any model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpucfn.mesh import MeshSpec, build_mesh
from tpucfn.models.llama import Llama, LlamaConfig, causal_lm_loss
from tpucfn.parallel import shard_batch
from tpucfn.train import Trainer, lora_init, lora_materialize, lora_sharding_rules


def _setup():
    cfg = LlamaConfig.tiny()
    toks = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (4, 16)), jnp.int32)
    params = Llama(cfg).init(jax.random.key(0), toks)["params"]
    return cfg, toks, params


def test_lora_init_shapes_and_identity_start():
    cfg, toks, params = _setup()
    adapters = lora_init(params, jax.random.key(1), rank=4)
    # scanned llama kernels carry a leading layer dim -> per-layer factors
    qk = adapters["layers/attn/q_proj/kernel"]
    assert qk["a"].shape == (cfg.n_layers, cfg.dim, 4)
    assert qk["b"].shape == (cfg.n_layers, 4, cfg.dim)
    # B starts at zero: the adapted model IS the base model
    merged = lora_materialize(params, adapters)
    ref = Llama(cfg).apply({"params": params}, toks)
    out = Llama(cfg).apply({"params": merged}, toks)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_lora_materialize_applies_delta():
    _, _, params = _setup()
    adapters = lora_init(params, jax.random.key(1), rank=2,
                         pattern=r"q_proj/kernel$")
    adapters["layers/attn/q_proj/kernel"]["b"] = jnp.ones_like(
        adapters["layers/attn/q_proj/kernel"]["b"])
    merged = lora_materialize(params, adapters, scale=0.5)
    a = adapters["layers/attn/q_proj/kernel"]["a"]
    b = adapters["layers/attn/q_proj/kernel"]["b"]
    want = params["layers"]["attn"]["q_proj"]["kernel"] + 0.5 * jnp.einsum(
        "lir,lro->lio", a, b)
    np.testing.assert_allclose(
        np.asarray(merged["layers"]["attn"]["q_proj"]["kernel"]),
        np.asarray(want), rtol=1e-6)
    # untargeted leaves pass through untouched
    np.testing.assert_array_equal(
        np.asarray(merged["embed_tokens"]["embedding"]),
        np.asarray(params["embed_tokens"]["embedding"]))


def test_lora_grads_flow_only_to_adapters():
    cfg, toks, params = _setup()
    adapters = lora_init(params, jax.random.key(1), rank=4)

    def loss_fn(ad):
        merged = lora_materialize(params, ad)
        return causal_lm_loss(Llama(cfg).apply({"params": merged}, toks),
                              toks)[0]

    grads = jax.jit(jax.grad(loss_fn))(adapters)
    # At init B=0, so dL/dA (∝ B) is zero — B is where gradient lands.
    gb = np.asarray(grads["layers/attn/q_proj/kernel"]["b"])
    assert np.abs(gb).max() > 0  # adapters get gradient
    # and the base stays untouched by construction (stop_gradient) —
    # differentiating w.r.t. base through the merged tree yields zeros
    gbase = jax.jit(jax.grad(lambda p: causal_lm_loss(
        Llama(cfg).apply({"params": lora_materialize(p, adapters)}, toks),
        toks)[0]))(params)
    assert float(np.abs(np.asarray(
        gbase["layers"]["attn"]["q_proj"]["kernel"])).max()) == 0.0


def test_lora_training_learns_under_trainer():
    cfg, toks, params = _setup()
    mesh = build_mesh(MeshSpec(data=8))

    def init_fn(rng):
        return lora_init(params, rng, rank=8), {}

    def loss_fn(ad, mstate, batch, rng):
        merged = lora_materialize(params, ad)
        loss, acc = causal_lm_loss(
            Llama(cfg).apply({"params": merged}, batch["tokens"]),
            batch["tokens"])
        return loss, ({"accuracy": acc}, mstate)

    trainer = Trainer(mesh, lora_sharding_rules(), loss_fn,
                      optax.adamw(5e-3), init_fn)
    state = trainer.init(jax.random.key(0))
    batch = shard_batch(mesh, {"tokens": np.asarray(
        jnp.tile(toks, (2, 1)))})
    first = None
    for _ in range(20):
        state, m = trainer.step(state, batch)
        first = first if first is not None else float(m["loss"])
    assert float(m["loss"]) < first * 0.9


def test_lora_rejects_bad_inputs():
    _, _, params = _setup()
    with pytest.raises(ValueError, match="rank"):
        lora_init(params, jax.random.key(0), rank=0)
    with pytest.raises(ValueError, match="pattern"):
        lora_init(params, jax.random.key(0), pattern=r"nonexistent_xyz$")
