"""Pipeline-parallel execution of the Llama stack.

Same params, different schedule: the scanned Llama param tree (leading
``layers`` axis) is sharded over the ``pipeline`` mesh axis — stage p
holds layers [p·L/P, (p+1)·L/P) — and the forward runs the GPipe
microbatch schedule from :mod:`tpucfn.parallel.pipeline` inside a
``shard_map`` that is **manual over the pipeline axis only**
(``axis_names={"pipeline"}``).  Every other mesh axis stays on XLA's
auto-sharding inside the stage body, which is what makes PP compose:

* **PP × FSDP**: stage params carry their fsdp-axis sharding into the
  stage body; XLA inserts the all-gather on use and the reduce-scatter
  on the grad transpose — gather-on-use ZeRO-3, compiler-scheduled.
* **PP × TP**: the Megatron column/row specs on qkv/o/up/down propagate
  through the block's einsums exactly as in the non-PP path.
* **PP × SP**: pass ``context_parallel=True`` — the shard_map goes
  manual over {pipeline, context} together and the stage body runs the
  ring-attention body directly (RoPE offsets ride the block carry,
  derived from ``lax.axis_index("context")``).  One flat manual region,
  deliberately NOT a nested shard_map: transposing an outer partial-
  manual shard_map through a nested one re-binds the outer axis and
  Shardy rejects the backward program (observed on jax 0.9).

Embedding, final norm, and LM head compute outside the pipeline body
under plain auto-sharding (cheap relative to the block stack).

Checkpoints interchange with the plain :class:`tpucfn.models.llama.Llama`
— the param tree is identical; only placement and schedule differ.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import flax.linen as nn

from tpucfn.kernels.ring_attention import ring_attention
from tpucfn.mesh import AXIS_CONTEXT, AXIS_EXPERT, AXIS_PIPELINE
from tpucfn.models.layers import RMSNorm
from tpucfn.models.llama import (LlamaBlock, LlamaConfig, remat_policy,
                                 sharding_rules)
from tpucfn.models.moe import collect_moe_aux
from tpucfn.ops.attention import dot_product_attention
from tpucfn.parallel.pipeline import (
    deinterleave_chunks,
    gpipe,
    interleave_chunks,
    microbatch,
    pipeline_1f1b,
    unmicrobatch,
)
from tpucfn.parallel.sharding import ShardingRules

def pp_sharding_rules(cfg: LlamaConfig, *, fsdp: bool = True,
                      tensor: bool = True) -> ShardingRules:
    """Stage-sharded layout composed with FSDP/TP: every scanned block
    param shards its leading (layer) dim over ``pipeline`` and keeps the
    Megatron/FSDP specs from :func:`llama.sharding_rules` on its other
    dims; embed/head keep their vocab-sharded specs (they run outside
    the pipeline body)."""
    if not cfg.scan_layers:
        raise ValueError("pipeline execution needs scan_layers=True (stacked params)")
    return sharding_rules(cfg, fsdp=fsdp, tensor=tensor,
                          layer_lead_axis=AXIS_PIPELINE)


# MoE under context parallelism routes BLOCK-LOCALLY: each context shard
# routes its own (mb, S/C) tokens with capacity ∝ S/C.  That is the
# standard SP×EP trade (static shapes, no cross-shard dispatch); per-token
# top-k is unchanged, so in the no-drop regime the layer OUTPUT equals
# full-sequence routing and only the aux statistics are shard-local.  The
# aux convention is the mean over context shards of each shard's aux
# (stage_fn pre-divides by the context axis size so the schedules' psum
# over `context` forms that mean); tests pin it against an explicit
# blockwise-routing reference.


def _attention_for(context_parallel: bool, hop_attention: str = "auto"):
    if not context_parallel:
        # The non-CP stage body's q_offset is statically zero
        # (_make_stage_fn), so the flash-eligible auto dispatcher is
        # sound here: flash on TPU above the S threshold, dense below.
        from tpucfn.kernels.auto import auto_attention_static_zero

        return auto_attention_static_zero

    def att(q, k, v, *, causal=True, mask=None, q_offset=0, k_offset=0):
        if mask is not None:
            raise NotImplementedError("ring attention is causal-only")
        return ring_attention(q, k, v, axis=AXIS_CONTEXT, causal=causal,
                              hop_attention=hop_attention)

    return att


def _is_expert_leaf(path) -> bool:
    return any("experts" in str(getattr(k, "key", k)) for k in path)


def _ep_layer_specs(layers, *, expert_parallel: bool, chunked: bool = False):
    """Per-leaf manual specs for the stage shard_map: every leaf splits
    its leading (layer) dim over ``pipeline``; with ``expert_parallel``
    the per-expert kernels (path contains ``experts``) additionally
    split their expert dim manually — stage bodies then see their E/ep
    local slice, matching MoEMLP's ``ep_manual`` contract.  ``chunked``:
    interleaved layout (PV, L/PV, ...) puts the expert dim one deeper."""
    if not expert_parallel:
        return jax.tree.map(lambda _: P(AXIS_PIPELINE), layers)

    def spec(path, _):
        if _is_expert_leaf(path):
            return (P(AXIS_PIPELINE, None, AXIS_EXPERT) if chunked
                    else P(AXIS_PIPELINE, AXIS_EXPERT))
        return P(AXIS_PIPELINE)

    return jax.tree_util.tree_map_with_path(spec, layers)


def _make_stage_fn(cfg: LlamaConfig, att, context_parallel: bool,
                   with_aux: bool = False, expert_parallel: bool = False):
    def stage_fn(stage_params, h):
        """Apply this stage's layer slice (lax.scan over local layers).

        ``with_aux``: returns ``(h_out, aux)`` where aux sums the MoE
        losses sown by this stage's layers — the ``sow`` collection
        cannot cross the shard_map boundary, so it is collected here per
        block apply and threaded through the pipeline schedules' aux
        plumbing instead.
        """
        if context_parallel:
            # h is the local (mb, S/C, D) shard: RoPE needs the global
            # position of this shard's first token.
            q_off = lax.axis_index(AXIS_CONTEXT) * h.shape[-2]
        else:
            q_off = jnp.zeros((), jnp.int32)

        do_remat, policy = remat_policy(cfg.remat)

        def make_block():
            return LlamaBlock(cfg, att, ep_manual=expert_parallel)

        def body(carry, layer_params):
            if with_aux:
                def apply_fn(p, c):
                    out, lcl = make_block().apply(
                        {"params": p}, c, mutable=["losses"])
                    return out[0], collect_moe_aux(lcl)

                if do_remat:
                    apply_fn = jax.checkpoint(apply_fn, prevent_cse=False,
                                              policy=policy)
                carry, aux = apply_fn(layer_params, carry)
                return carry, aux
            if do_remat:
                apply = jax.checkpoint(
                    lambda p, c: make_block().apply(
                        {"params": p}, c
                    )[0],
                    prevent_cse=False,
                    policy=policy,
                )
                carry = apply(layer_params, carry)
            else:
                carry, _ = make_block().apply(
                    {"params": layer_params}, carry
                )
            return carry, None

        (h_out, _), auxs = lax.scan(body, (h, q_off), stage_params)
        if with_aux:
            aux = jnp.sum(auxs)
            if context_parallel:
                # Shard-local aux / C: the schedules psum over `context`
                # (gpipe wrapper / 1f1b reduce_axes), yielding the mean
                # over context shards per the blockwise-routing contract.
                aux = aux / lax.axis_size(AXIS_CONTEXT)
            return h_out, aux
        return h_out

    return stage_fn


def _apply_head(cfg: LlamaConfig, head_params, h) -> jax.Array:
    """final_norm + fp32 lm_head — the one definition both PP schedules
    share (and must keep matching llama.Llama's tail)."""
    h = RMSNorm(cfg.norm_eps, cfg.dtype).apply(
        {"params": head_params["final_norm"]}, h)
    return nn.DenseGeneral(
        cfg.vocab_size, use_bias=False, dtype=jnp.float32,
        param_dtype=cfg.param_dtype).apply(
        {"params": head_params["lm_head"]}, h.astype(jnp.float32))


def pipelined_llama_apply(
    cfg: LlamaConfig,
    mesh: Mesh,
    params,
    tokens: jax.Array,
    *,
    num_microbatches: int = 4,
    context_parallel: bool = False,
    hop_attention: str = "auto",
    with_aux: bool = False,
    expert_parallel: bool = False,
):
    """tokens (B, S) → logits (B, S, vocab), numerically equal to
    ``Llama(cfg).apply`` with the same params (tests assert it).

    ``context_parallel=True`` additionally shards the sequence over the
    ``context`` axis with ring attention inside the stage body
    (``hop_attention="flash"`` for Pallas-kernel hops).

    ``with_aux=True`` (MoE training through the GPipe schedule) returns
    ``(logits, aux)`` where aux is the microbatch-mean of the sown MoE
    losses summed over all layers — differentiable, so
    ``loss = ce + aux`` trains the router. Per-microbatch routing means
    aux is defined per microbatch (matching per-micro sequential
    application, not one full-batch apply); under ``context_parallel``
    routing is additionally block-local per context shard and aux is the
    mean over shards (see the module-level MoE×CP note).

    ``expert_parallel=True`` (MoE with the mesh's ``expert`` axis >1):
    the stage shard_map goes manual over {pipeline, expert} together,
    each microbatch's rows split over ``expert``, and the MoE layers run
    the explicit all-to-all dispatch inline (``MoEMLP.ep_manual`` — one
    flat manual region, no nesting). Routing/capacity become local per
    expert shard (E/ep experts' weights per device), and aux follows the
    shard-mean convention. In the no-drop regime the layer OUTPUT equals
    single-device routing, so logits still match the plain model."""
    if not cfg.scan_layers:
        raise ValueError("pipeline execution needs scan_layers=True")
    if expert_parallel and cfg.moe is None:
        raise ValueError("expert_parallel requires a MoE config")

    att = _attention_for(context_parallel, hop_attention)

    embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
    x = embed.apply({"params": params["embed_tokens"]}, tokens)

    stage_fn = _make_stage_fn(cfg, att, context_parallel, with_aux=with_aux,
                              expert_parallel=expert_parallel)

    mb = microbatch(x, num_microbatches)  # (M, B/M, S, D)
    if expert_parallel and mb.shape[1] % mesh.shape[AXIS_EXPERT]:
        raise ValueError(
            f"microbatch rows {mb.shape[1]} not divisible by expert axis "
            f"{mesh.shape[AXIS_EXPERT]}")
    # Manual over pipeline (and context/expert when enabled): specs name
    # just the manual axes; fsdp/tensor/data shardings flow through as
    # auto axes.
    manual = ({AXIS_PIPELINE}
              | ({AXIS_CONTEXT} if context_parallel else set())
              | ({AXIS_EXPERT} if expert_parallel else set()))
    layer_specs = _ep_layer_specs(params["layers"],
                                  expert_parallel=expert_parallel)
    mb_spec = P(None, AXIS_EXPERT if expert_parallel else None,
                AXIS_CONTEXT if context_parallel else None)

    def run_body(p, xs):
        res = gpipe(stage_fn, p, xs, with_aux=with_aux)
        if with_aux and (context_parallel or expert_parallel):
            # Stage aux is shard-local, pre-divided by the shard count
            # (context in _make_stage_fn, expert in MoEMLP.ep_manual):
            # summing completes the mean over shards.
            ys, aux = res
            axes = (((AXIS_CONTEXT,) if context_parallel else ())
                    + ((AXIS_EXPERT,) if expert_parallel else ()))
            return ys, lax.psum(aux, axes)
        return res

    run = jax.shard_map(
        run_body,
        mesh=mesh,
        in_specs=(layer_specs, mb_spec),
        out_specs=(mb_spec, P()) if with_aux else mb_spec,
        axis_names=manual,
        check_vma=False,
    )
    out = run(params["layers"], mb)
    x, aux = out if with_aux else (out, None)
    logits = _apply_head(
        cfg, {"final_norm": params["final_norm"], "lm_head": params["lm_head"]},
        unmicrobatch(x))
    return (logits, aux) if with_aux else logits


def pipelined_llama_value_and_grad(
    cfg: LlamaConfig,
    mesh: Mesh,
    params,
    tokens: jax.Array,
    *,
    num_microbatches: int = 4,
    context_parallel: bool = False,
    hop_attention: str = "auto",
    z_loss: float = 0.0,
    with_metrics: bool = False,
    num_virtual: int = 1,
    expert_parallel: bool = False,
):
    """1F1B-scheduled causal-LM loss and gradients.

    ``num_virtual=V > 1`` selects the interleaved schedule: the layer
    stack splits into P·V chunks of L/(P·V) layers, chunk c on device
    c mod P, shrinking the pipeline bubble for small microbatch counts
    (see :func:`tpucfn.parallel.pipeline._pipeline_1f1b_interleaved`).
    The params tree is unchanged — the chunk reshape/permutation happens
    here (and is inverted on the grads), so checkpoints stay
    interchangeable with the plain model.

    Returns ``(loss, grads)`` — or ``(loss, metrics, grads)`` with
    ``with_metrics=True``, where ``metrics["accuracy"]`` is next-token
    accuracy over valid tokens — ``grads`` matches the ``params`` tree
    and ``loss`` is next-token cross entropy averaged over (B, S-1)
    tokens plus the optional z-loss regularizer, the same quantity as
    :func:`llama.causal_lm_loss`. MoE configs (``cfg.moe``) additionally
    include the per-microbatch-mean MoE aux losses in ``loss`` with
    exact gradients (threaded through the schedule's aux plumbing — the
    ``sow`` collection cannot cross the shard_map boundary); under
    ``context_parallel`` routing is block-local per context shard and
    aux is the mean over shards (module-level MoE×CP note).

    Unlike :func:`pipelined_llama_apply`, this is not meant to be
    differentiated through — it IS the backward pass, scheduled 1F1B so
    the per-stage activation stash is O(P) instead of O(M) (see
    :func:`tpucfn.parallel.pipeline.pipeline_1f1b`).  Wrap it in a
    ``jax.custom_vjp`` to feed optimizers that call ``value_and_grad``
    (the llama example does exactly this for ``--pp-schedule 1f1b``).
    """
    if not cfg.scan_layers:
        raise ValueError("pipeline execution needs scan_layers=True")
    with_aux = cfg.moe is not None
    if expert_parallel and cfg.moe is None:
        raise ValueError("expert_parallel requires a MoE config")
    att = _attention_for(context_parallel, hop_attention)
    b, s = tokens.shape
    mb_size = b // num_microbatches
    if expert_parallel and mb_size % mesh.shape[AXIS_EXPERT]:
        raise ValueError(
            f"microbatch rows {mb_size} not divisible by expert axis "
            f"{mesh.shape[AXIS_EXPERT]}")

    embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
    x, embed_vjp = jax.vjp(
        lambda ep: embed.apply({"params": ep}, tokens), params["embed_tokens"])

    # Shifted targets with -1 at the (global) last position, computed
    # BEFORE any context sharding so the shard-boundary next-token is
    # still each position's target.
    labels = jnp.concatenate(
        [tokens[:, 1:], jnp.full((b, 1), -1, tokens.dtype)], axis=1)
    denom = mb_size * (s - 1)  # per-micro global valid-token count

    head_params = {"final_norm": params["final_norm"],
                   "lm_head": params["lm_head"]}

    def head_fn(hp, y, lbl):
        """Local-shard loss sum / global per-micro token count (the
        pipeline_1f1b HeadFn contract: contributions psum to the mean).
        Matches causal_lm_loss's per-token loss incl. z-loss; the
        metrics dict carries next-token accuracy on the same per-micro
        mean convention."""
        import optax

        logits = _apply_head(cfg, hp, y)
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.maximum(lbl, 0))
        if z_loss:
            per_tok = per_tok + z_loss * jax.nn.logsumexp(logits, axis=-1) ** 2
        valid = lbl >= 0
        loss = jnp.sum(jnp.where(valid, per_tok, 0.0)) / denom
        correct = jnp.where(valid, jnp.argmax(logits, -1) == lbl, False)
        return loss, {"accuracy": jnp.sum(correct.astype(jnp.float32)) / denom}

    stage_fn = _make_stage_fn(cfg, att, context_parallel, with_aux=with_aux,
                              expert_parallel=expert_parallel)
    mb = microbatch(x, num_microbatches)
    lbl_mb = microbatch(labels, num_microbatches)

    layers_in = params["layers"]
    if num_virtual > 1:
        # (L, ...) -> (P·V, L/(P·V), ...) execution-order chunks, then
        # device-major so P(pipeline) hands device i its V chunks local.
        n_stages = mesh.shape[AXIS_PIPELINE]
        n_chunks = n_stages * num_virtual
        if cfg.n_layers % n_chunks:
            raise ValueError(
                f"n_layers={cfg.n_layers} not divisible by "
                f"pipeline×virtual={n_chunks}")
        lc = cfg.n_layers // n_chunks
        layers_in = interleave_chunks(
            jax.tree.map(lambda l: l.reshape((n_chunks, lc) + l.shape[1:]),
                         layers_in),
            n_stages, num_virtual)

    manual = ({AXIS_PIPELINE}
              | ({AXIS_CONTEXT} if context_parallel else set())
              | ({AXIS_EXPERT} if expert_parallel else set()))
    layer_specs = _ep_layer_specs(layers_in, expert_parallel=expert_parallel,
                                  chunked=num_virtual > 1)
    head_specs = jax.tree.map(lambda _: P(), head_params)
    mb_spec = P(None, AXIS_EXPERT if expert_parallel else None,
                AXIS_CONTEXT if context_parallel else None)

    def run_fn(lp, hp, xs, lb):
        loss, dstage, dhead, dmicro, metrics = pipeline_1f1b(
            stage_fn, head_fn, lp, hp, xs, lb,
            # `expert` is deliberately NOT a blanket reduce axis: the
            # expert-SPLIT stage leaves hold grads for DIFFERENT experts
            # per shard — a uniform psum would mix them. Selective
            # reduction below.
            reduce_axes=(AXIS_CONTEXT,) if context_parallel else (),
            stage_aux=with_aux,
            head_metrics=True,
            num_virtual=num_virtual,
        )
        if expert_parallel:
            # Each expert shard saw only its token rows: loss, head
            # grads, metrics, and grads of expert-REPLICATED stage
            # leaves (attn/norms/router) sum over the expert axis;
            # expert-split leaves keep their own-expert local grads.
            dstage = jax.tree_util.tree_map_with_path(
                lambda path, g: g if _is_expert_leaf(path)
                else lax.psum(g, AXIS_EXPERT), dstage)
            dhead = jax.tree.map(lambda g: lax.psum(g, AXIS_EXPERT), dhead)
            loss = lax.psum(loss, AXIS_EXPERT)
            metrics = jax.tree.map(
                lambda g: lax.psum(g, AXIS_EXPERT), metrics)
        return loss, dstage, dhead, dmicro, metrics

    run = jax.shard_map(
        run_fn,
        mesh=mesh,
        in_specs=(layer_specs, head_specs, mb_spec, mb_spec),
        out_specs=(P(), layer_specs, head_specs, mb_spec, {"accuracy": P()}),
        axis_names=manual,
        check_vma=False,
    )
    loss, dlayers, dhead, dmicro, metrics = run(
        layers_in, head_params, mb, lbl_mb)
    if num_virtual > 1:
        dlayers = jax.tree.map(
            lambda l: l.reshape((cfg.n_layers,) + l.shape[2:]),
            deinterleave_chunks(dlayers, mesh.shape[AXIS_PIPELINE],
                                num_virtual))
    (d_embed,) = embed_vjp(unmicrobatch(dmicro).astype(x.dtype))
    grads = dict(params)
    grads["layers"] = dlayers
    grads["embed_tokens"] = d_embed
    grads["final_norm"] = dhead["final_norm"]
    grads["lm_head"] = dhead["lm_head"]
    if with_metrics:
        return loss, metrics, grads
    return loss, grads
