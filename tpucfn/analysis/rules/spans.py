"""span-balance: every emitted trace-span family is balanced and read.

The trace plane's analogue of the lost-Summary rule (ROADMAP
correctness follow-on, landed with ISSUE 13 — which adds the
``compile_fetch`` span and is exactly the kind of change that could
ship a write-only span).  Two rots, both silent at runtime:

* **unbalanced span** — a ``tracer.record(name, start=...)`` call that
  passes neither ``end=`` nor ``dur_s=`` writes a zero-duration span:
  the start was observed, the end never was, and every downstream
  percentile over that family reads 0.  (``queue_wait``'s retroactive
  record is the sanctioned *pattern* — start observed on another
  thread — and it is balanced: it passes ``end=``.  Point events go
  through ``.event()`` / ``kind="event"`` and are exempt: zero
  duration is their contract.)
* **write-only span** — a literal span name emitted somewhere but
  consumed by no reader in the package (``obs.aggregate``'s views, the
  postmortem, anything matching on the record's ``name``): the span
  costs a JSONL line per occurrence and tells nobody anything.

Emitters are ``X.record("lit", ..., start=...)`` and ``X.span("lit",
...)`` call sites (the ``start=`` keyword is what distinguishes a
trace-span record from the flight ring's same-named method).
Consumers are string literals compared (``==``/``in``/...) against a
``name`` field lookup — ``e.get("name")``, ``e["name"]``, a variable
bound from one — including comparisons against a module-level string
tuple (``CONTROL_SPAN_NAMES``), whose elements then all count as
consumed.  A package emitting no literal spans gets no findings.
"""

from __future__ import annotations

import ast

from tpucfn.analysis.core import Analysis, Finding
from tpucfn.analysis.rules.vocab import (
    _compared_literals,
    _is_field_lookup,
    _lookup_bound_names,
    _scope_walk,
)

RULE_ID = "span-balance"


def _literal_str(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _kw(call: ast.Call, name: str) -> ast.expr | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _span_emissions(analysis: Analysis):
    """``(mod, call, name, balanced, is_event)`` for every literal-named
    trace-span emission in the package."""
    for mod in analysis.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute) \
                    or not node.args:
                continue
            name = _literal_str(node.args[0])
            if name is None:
                continue
            if node.func.attr == "record":
                if _kw(node, "start") is None:
                    continue  # flight-ring / SLO record, not a trace span
                kind = _kw(node, "kind")
                is_event = (_literal_str(kind) == "event"
                            if kind is not None else False)
                balanced = (_kw(node, "end") is not None
                            or _kw(node, "dur_s") is not None)
                yield mod, node, name, balanced, is_event
            elif node.func.attr == "span":
                # context-managed spans time their own end
                yield mod, node, name, True, False


def _module_str_tuples(analysis: Analysis) -> dict[str, list[str]]:
    """Module-level ``NAME = ("a", "b", ...)`` string tuples,
    package-wide — comparison sides naming one consume its elements."""
    out: dict[str, list[str]] = {}
    for mod in analysis.modules:
        for stmt in mod.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            if not isinstance(stmt.value, (ast.Tuple, ast.List)):
                continue
            vals = []
            ok = True
            for e in stmt.value.elts:
                s = _literal_str(e)
                if s is None:
                    ok = False
                    break
                vals.append(s)
            if not ok or not vals:
                continue
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = vals
    return out


def _consumed_names(analysis: Analysis) -> set[str]:
    """Every span name some reader in the package matches on."""
    tuples = _module_str_tuples(analysis)
    consumed: set[str] = set()
    for mod in analysis.modules:
        scopes = [mod.tree.body]
        for _qual, info in analysis.functions(mod).items():
            if not isinstance(info.node, ast.Lambda):
                scopes.append(info.node.body)
        for body in scopes:
            name_vars = _lookup_bound_names(body, "name")

            def is_name(e: ast.expr) -> bool:
                if _is_field_lookup(e, "name"):
                    return True
                return isinstance(e, ast.Name) and e.id in name_vars

            for node in _scope_walk(body):
                if not isinstance(node, ast.Compare):
                    continue
                sides = [node.left, *node.comparators]
                if not any(is_name(s) for s in sides):
                    continue
                consumed.update(_compared_literals(node, is_name))
                for s in sides:
                    if isinstance(s, ast.Name) and s.id in tuples:
                        consumed.update(tuples[s.id])
    return consumed


def check(analysis: Analysis):
    findings: list[Finding] = []
    emissions = list(_span_emissions(analysis))
    if not emissions:
        return findings
    consumed = _consumed_names(analysis)
    flagged_unconsumed: set[str] = set()
    for mod, call, name, balanced, is_event in emissions:
        if not is_event and not balanced:
            findings.append(Finding(
                RULE_ID, mod.rel, call.lineno,
                f"span {name!r} records a start but neither end= nor "
                "dur_s= — the end path was never observed, so every "
                "duration percentile over this family reads 0 (pass the "
                "measured end/duration, or make it an explicit "
                "kind=\"event\" point marker)",
                key=f"unbalanced:{name}"))
        if is_event:
            continue  # point events are an open vocabulary by contract
        if name not in consumed and name not in flagged_unconsumed:
            flagged_unconsumed.add(name)
            findings.append(Finding(
                RULE_ID, mod.rel, call.lineno,
                f"span {name!r} is emitted here but no reader in the "
                "package ever matches on it — a write-only span costs a "
                "JSONL line per occurrence and tells nobody anything "
                "(consume it in an obs.aggregate view, or stop emitting "
                "it)",
                key=f"unconsumed:{name}"))
    return findings
