"""tpucfn.ft — the fleet fault-tolerance plane (ISSUE 4).

Heartbeat failure detection (``heartbeat``), recovery policies with
budgets and backoff (``policy``), the gang coordinator that executes
them over the launcher's process table (``coordinator``), and the
deterministic chaos harness that proves the whole loop works
(``chaos``).
"""

from tpucfn.ft.chaos import (  # noqa: F401
    ChaosEngine,
    ChaosEvent,
    ChaosSpec,
    ChaosTarget,
    ControlPlaneChaosTarget,
    corrupt_latest_checkpoint,
)
from tpucfn.ft.coordinator import GangCoordinator  # noqa: F401
from tpucfn.ft.heartbeat import (  # noqa: F401
    FleetView,
    HeartbeatMonitor,
    HeartbeatWriter,
    HostState,
    HostVerdict,
    MonitorConfig,
    heartbeat_path,
    read_heartbeats,
)
from tpucfn.ft.policy import (  # noqa: F401
    Action,
    Decision,
    Failure,
    FailureKind,
    GangRestart,
    RecoveryPolicy,
    RestartBudget,
    SoloRestart,
    policy_from_name,
)
