"""Continuous-batching scheduler behavior (tpucfn.serve.scheduler),
driven with a simulated engine (the scheduler is pure host logic): FCFS
admission into buckets, in-place retirement, preempt-on-full with
recompute re-queue, deadline expiry, and the zero-leak invariant."""

import pytest

from tpucfn.serve.kvcache import KVCacheManager
from tpucfn.serve.scheduler import (
    ContinuousBatchingScheduler,
    DecodeWork,
    PrefillWork,
    Sequence,
    SequenceState,
    prefill_bucket,
)


def _seq(i, prompt_len=4, max_new=4, **kw):
    return Sequence(seq_id=i, prompt=list(range(1, prompt_len + 1)),
                    max_new_tokens=max_new, arrival=float(i), **kw)


def _sched(num_blocks=16, block_size=4, max_batch=2, cache_len=64, **kw):
    return ContinuousBatchingScheduler(
        KVCacheManager(num_blocks, block_size), max_batch=max_batch,
        cache_len=cache_len, **kw)


def _drive(s, token=7):
    """Run the scheduler to empty with a fake engine that always emits
    ``token``; returns the finished sequences in completion order."""
    done = []
    for _ in range(10_000):
        work = s.next_work()
        if work is None:
            break
        if isinstance(work, PrefillWork):
            for it in work.items:
                fin = s.record_prefill(it.slot, token)
                done += [fin] if fin else []
        else:
            for slot in list(work.slots):
                fin = s.record_decode(slot, token)
                done += [fin] if fin else []
    else:
        pytest.fail("scheduler did not drain")
    return done


def test_prefill_bucket_pow2_and_cap():
    assert prefill_bucket(1, 512) == 16
    assert prefill_bucket(16, 512) == 16
    assert prefill_bucket(17, 512) == 32
    assert prefill_bucket(100, 512) == 128
    assert prefill_bucket(100, 100) == 100  # capped at cache_len
    with pytest.raises(ValueError, match="exceeds cache_len"):
        prefill_bucket(101, 100)


def test_add_rejects_infeasible_requests():
    s = _sched(num_blocks=2, block_size=4, cache_len=16)
    with pytest.raises(ValueError, match="KV blocks"):
        s.add(_seq(0, prompt_len=6, max_new=4))  # 9 tokens > 8 slots
    with pytest.raises(ValueError, match="cache_len"):
        s.add(_seq(0, prompt_len=10, max_new=10))
    with pytest.raises(ValueError, match="max_new_tokens"):
        s.add(_seq(0, max_new=0))


def test_prefill_priority_then_decode_then_retire():
    s = _sched(max_batch=2)
    s.add(_seq(0, max_new=2))
    s.add(_seq(1, max_new=3))
    w0 = s.next_work()
    assert isinstance(w0, PrefillWork) and w0.seq.seq_id == 0
    s.record_prefill(w0.slot, 5)
    # A waiting sequence + a free slot: prefill wins over decode.
    w1 = s.next_work()
    assert isinstance(w1, PrefillWork) and w1.seq.seq_id == 1
    s.record_prefill(w1.slot, 5)
    # Both running: decode covers both slots.
    w2 = s.next_work()
    assert isinstance(w2, DecodeWork) and len(w2.slots) == 2
    fin0 = s.record_decode(w0.slot, 6)  # seq 0 reaches max_new=2
    assert fin0 is not None and fin0.state is SequenceState.FINISHED
    assert s.record_decode(w1.slot, 6) is None
    # Retirement was in place: slot freed while seq 1 keeps running.
    assert s.num_running == 1
    done = _drive(s)
    assert [q.seq_id for q in done] == [1]
    assert s.kv.allocator.num_free == s.kv.allocator.num_blocks


def test_eos_retires_early():
    s = _sched(eos_id=99)
    s.add(_seq(0, max_new=50, prompt_len=4))
    w = s.next_work()
    fin = s.record_prefill(w.slot, 99)  # instant EOS
    assert fin is not None and fin.generated == [99]
    assert s.kv.allocator.num_free == s.kv.allocator.num_blocks


def test_admission_waits_for_blocks_then_admits():
    # Pool of 4 blocks x 4 = 16 token slots; seq 0 occupies most of it.
    s = _sched(num_blocks=4, block_size=4, max_batch=2, cache_len=16)
    s.add(_seq(0, prompt_len=9, max_new=4))   # 3 blocks at admit
    s.add(_seq(1, prompt_len=8, max_new=2))   # needs 2 — must wait
    w = s.next_work()
    s.record_prefill(w.slot, 5)
    # Free slot exists but blocks don't: decode, not prefill.
    assert isinstance(s.next_work(), DecodeWork)
    s.record_decode(w.slot, 5)
    done = _drive(s)
    assert {q.seq_id for q in done} == {0, 1}
    assert s.kv.allocator.num_free == 4


def test_preempt_on_full_requeues_youngest_and_recovers():
    # 4 blocks x 2 = 8 slots. Two prompts of 4 (2 blocks each) fill the
    # pool at admit; the first decode reservation must preempt the
    # YOUNGER sequence, which then recomputes and finishes.
    s = _sched(num_blocks=4, block_size=2, max_batch=2, cache_len=8)
    s.add(_seq(0, prompt_len=4, max_new=4))
    s.add(_seq(1, prompt_len=4, max_new=4))
    s.record_prefill(s.next_work().slot, 5)
    s.record_prefill(s.next_work().slot, 5)
    w = s.next_work()
    assert isinstance(w, DecodeWork)
    assert [q.seq_id for q in w.slots.values()] == [0]  # 1 evicted
    assert s.kv.evictions == 1
    assert s.waiting and s.waiting[0].seq_id == 1
    assert s.waiting[0].preemptions == 1
    assert s.waiting[0].generated == [5]  # kept for the recompute prefix
    done = _drive(s)
    assert {q.seq_id for q in done} == {0, 1}
    # Preempted seq re-prefilled with prompt+generated, finished fully.
    assert len([q for q in done if q.seq_id == 1][0].generated) == 4
    assert s.kv.allocator.num_free == 4
    assert s.kv.allocator.num_used == 0


def test_expire_waiting_and_running():
    s = _sched(max_batch=2)
    s.add(_seq(0, max_new=8, deadline=10.0))
    s.add(_seq(1, max_new=8, deadline=100.0))
    s.record_prefill(s.next_work().slot, 5)  # seq 0 running
    dead = s.expire(now=50.0)
    assert [q.seq_id for q in dead] == [0]
    assert dead[0].state is SequenceState.EXPIRED
    assert s.num_running == 0 and s.num_waiting == 1
    assert s.kv.allocator.num_used == 0  # running casualty freed its blocks
    done = _drive(s)
    assert [q.seq_id for q in done] == [1]


def test_mixed_workload_zero_leaks():
    """The acceptance invariant: >= 8 concurrent synthetic requests with
    interleaved prefills/decodes/preemptions; afterwards the allocator
    free count is exactly the initial pool."""
    s = _sched(num_blocks=24, block_size=4, max_batch=8, cache_len=64)
    for i in range(12):
        s.add(_seq(i, prompt_len=3 + (i * 5) % 17, max_new=1 + (i * 3) % 7))
    done = _drive(s)
    assert len(done) == 12
    assert all(q.state is SequenceState.FINISHED for q in done)
    assert all(len(q.generated) == q.max_new_tokens for q in done)
    assert s.kv.allocator.num_free == 24
    assert s.kv.allocator.num_used == 0
    assert not s.has_work()


# ---- batched prefill + prefix-hit planning (ISSUE 3) --------------------

def _psched(num_blocks=32, block_size=4, max_batch=4, cache_len=64, **kw):
    return ContinuousBatchingScheduler(
        KVCacheManager(num_blocks, block_size, prefix_cache=True),
        max_batch=max_batch, cache_len=cache_len, **kw)


def test_batched_prefill_admits_same_bucket_only():
    """One PrefillWork carries every same-bucket waiter up to K; a
    different-bucket sequence stays queued (and runs next)."""
    s = _psched(max_prefill_batch=3)
    s.add(_seq(0, prompt_len=4))
    # Disjoint tokens: no shared first block, so no prefix hit can
    # shrink this one into the 16 bucket.
    s.add(Sequence(seq_id=1, prompt=list(range(100, 120)),
                   max_new_tokens=4, arrival=1.0))  # bucket 32, not 16
    s.add(_seq(2, prompt_len=5))
    s.add(_seq(3, prompt_len=6))
    w = s.next_work()
    assert isinstance(w, PrefillWork) and w.bucket == 16
    assert [it.seq.seq_id for it in w.items] == [0, 2, 3]
    assert len({it.slot for it in w.items}) == 3
    for it in w.items:
        s.record_prefill(it.slot, 5)
    w2 = s.next_work()
    assert isinstance(w2, PrefillWork) and w2.bucket == 32
    assert w2.seq.seq_id == 1
    s.record_prefill(w2.slot, 5)
    done = _drive(s)
    assert {q.seq_id for q in done} == {0, 1, 2, 3}
    assert s.kv.allocator.num_used == 0


def test_batched_prefill_respects_slot_and_block_limits():
    # 2 slots, K=4: the batch stops at the slot budget.
    s = _psched(max_batch=2, max_prefill_batch=4)
    for i in range(4):
        s.add(_seq(i))
    w = s.next_work()
    assert len(w.items) == 2
    assert s.num_waiting == 2


def test_prefix_hit_plans_copy_from_prefilled_backer():
    """Sequence B sharing A's first full blocks prefills only its
    suffix: cached_len set, src_slot = A's slot, bucket from the
    suffix."""
    s = _psched(max_prefill_batch=1)
    base = list(range(1, 17))        # 4 full blocks of 4
    s.add(Sequence(seq_id=0, prompt=base + [77], max_new_tokens=2,
                   arrival=0.0))
    w0 = s.next_work()
    assert w0.items[0].cached_len == 0
    s.record_prefill(w0.slot, 5)     # A is now a valid backer
    s.add(Sequence(seq_id=1, prompt=base + [88, 89], max_new_tokens=2,
                   arrival=1.0))
    w1 = s.next_work()
    it = w1.items[0]
    assert it.cached_len == 16 and it.src_slot == w0.slot
    assert w1.bucket == 16           # suffix of 2, not the full 32 bucket
    s.record_prefill(it.slot, 5)
    done = _drive(s)
    assert {q.seq_id for q in done} == {0, 1}
    assert s.kv.allocator.num_used == 0


def test_no_hit_from_unprefilled_backer():
    """An admitted-but-not-yet-prefilled holder has no device bytes to
    copy: the second identical prompt in the SAME wave must plan a full
    prefill."""
    s = _psched(max_prefill_batch=1)
    base = list(range(1, 9))
    s.add(Sequence(seq_id=0, prompt=base + [1], max_new_tokens=2,
                   arrival=0.0))
    s.add(Sequence(seq_id=1, prompt=base + [2], max_new_tokens=2,
                   arrival=1.0))
    w0 = s.next_work()               # admits 0; NOT prefilled yet
    w1_plan = s._plan(s.waiting[0])
    assert w1_plan.cached_len == 0
    s.record_prefill(w0.slot, 5)
    assert s._plan(s.waiting[0]).cached_len == 8


def test_retired_slot_backs_hits_until_reassigned():
    """After every sharer finishes, the retired slot's residue still
    backs a hit (zero-copy: the new sequence lands ON the slot)."""
    s = _psched(max_prefill_batch=1)
    base = list(range(1, 9))         # 2 full blocks
    s.add(Sequence(seq_id=0, prompt=base + [7], max_new_tokens=2,
                   arrival=0.0))
    done = _drive(s)                 # seq 0 fully finished, slot free
    assert done and s.num_running == 0
    s.add(Sequence(seq_id=1, prompt=base + [8, 9], max_new_tokens=2,
                   arrival=1.0))
    w = s.next_work()
    it = w.items[0]
    assert it.cached_len == 8
    assert it.src_slot == it.slot    # zero-copy reuse of the residue
    s.record_prefill(it.slot, 5)
    _drive(s)
    assert s.kv.allocator.num_used == 0


def test_expire_rebuilds_deep_queue_in_order():
    """Deadline storm on a deep queue: every expired waiter drops, the
    survivors keep FCFS order (the O(n) rebuild satellite)."""
    s = _sched(max_batch=1)
    for i in range(200):
        s.add(_seq(i, deadline=(10.0 if i % 2 else 1000.0)))
    dead = s.expire(now=50.0)
    assert len(dead) == 100
    assert all(q.state is SequenceState.EXPIRED for q in dead)
    assert [q.seq_id for q in s.waiting] == [i for i in range(200)
                                             if i % 2 == 0]


def test_mixed_workload_with_prefix_cache_zero_leaks():
    """Hits, misses, shared evictions, batched prefills interleaved
    through a tight pool: the zero-leak invariant with sharing on."""
    s = _psched(num_blocks=20, block_size=4, max_batch=4, cache_len=64,
                max_prefill_batch=3)
    base = list(range(1, 13))
    for i in range(12):
        tail = [100 + i, 200 + i, 300 + i][: 1 + i % 3]
        s.add(Sequence(seq_id=i, prompt=base + tail,
                       max_new_tokens=1 + (i * 3) % 5, arrival=float(i)))
    done = _drive(s)
    assert len(done) == 12
    assert all(q.state is SequenceState.FINISHED for q in done)
    assert s.kv.allocator.num_used == 0
    assert s.kv.allocator.num_free == 20


# ---- multi-token decode recording (ISSUE 14) ----------------------------

def test_record_decode_tokens_multi_and_eos_mid_run():
    """An accepted run retires on the FIRST stop condition: tokens past
    an EOS (or past max_new) are dropped, the slot vacates, and the
    recorded count tells the caller where to roll the caches back to."""
    s = _sched(num_blocks=16, block_size=4, max_batch=2, eos_id=99)
    s.add(_seq(0, max_new=10))
    w = s.next_work()
    s.record_prefill(w.slot, 5)
    s.next_work()  # reserve the round's first token
    fin, n = s.record_decode_tokens(w.slot, [6, 7, 99, 8, 9])
    assert fin is not None and fin.state is SequenceState.FINISHED
    assert n == 3
    assert fin.generated == [5, 6, 7, 99]  # nothing after the EOS
    assert s.kv.allocator.num_used == 0


def test_record_decode_tokens_max_new_mid_run():
    s = _sched(num_blocks=16, block_size=4, max_batch=2)
    s.add(_seq(0, max_new=3))
    w = s.next_work()
    s.record_prefill(w.slot, 5)
    s.next_work()
    fin, n = s.record_decode_tokens(w.slot, [6, 7, 8, 9])
    assert fin is not None and n == 2  # 5 counted already: stop at 3
    assert fin.generated == [5, 6, 7]
    assert s.kv.allocator.num_used == 0


def test_record_decode_tokens_truncates_when_pool_dry():
    """Tokens past the up-front reservation are best-effort: a dry pool
    truncates the acceptance instead of preempting mid-commit, and the
    sequence finishes later once capacity returns."""
    s = _sched(num_blocks=4, block_size=2, max_batch=1, cache_len=8)
    s.add(_seq(0, prompt_len=4, max_new=4))
    w = s.next_work()
    s.record_prefill(w.slot, 5)
    s.next_work()  # reserves the round's first token (3rd block)
    s.kv.admit("dummy", prompt_len=2)  # drains the last free block
    fin, n = s.record_decode_tokens(w.slot, [6, 7, 8])
    assert fin is None
    assert n == 2  # first token reserved up front, second fit the
    #                reserved block, third found the pool dry
    seq = s.running[w.slot]
    assert seq.generated == [5, 6, 7]
    s.kv.release("dummy")
    done = _drive(s)
    assert [q.seq_id for q in done] == [0]
    assert len(done[0].generated) == 4
    assert s.kv.allocator.num_used == 0


def test_record_decode_single_token_delegates():
    """record_decode(slot, tok) == record_decode_tokens(slot, [tok]) —
    the plain path is the K=1 case of the multi-token one."""
    s = _sched(max_batch=1)
    s.add(_seq(0, max_new=1))
    w = s.next_work()
    fin = s.record_prefill(w.slot, 5)
    assert fin is not None  # max_new=1 retires at the prefill token
    assert s.kv.allocator.num_used == 0


def test_decode_work_carries_proposed_runs():
    s = _sched(max_batch=1)
    s.add(_seq(0))
    s.record_prefill(s.next_work().slot, 5)
    w = s.next_work()
    assert isinstance(w, DecodeWork) and w.proposed is None
    w.proposed = {0: [1, 2]}  # the serve loop stashes the round here
    assert w.proposed == {0: [1, 2]}
