from tpucfn.data.records import RecordShardWriter, read_record_shard, write_dataset_shards  # noqa: F401
from tpucfn.data.pipeline import (  # noqa: F401
    MultiProcessLoader,
    ShardedDataset,
    prefetch_to_mesh,
)
from tpucfn.data.store import (  # noqa: F401
    CliObjectStore,
    LocalStore,
    Store,
    stage,
    stage_url,
    store_for_url,
)
from tpucfn.data.images import (  # noqa: F401
    center_crop_resize,
    decode_image,
    decode_transform,
    encode_jpeg,
)
from tpucfn.data.convert import (  # noqa: F401
    convert_cifar_binary,
    convert_image_tree,
    upload_shards,
)
from tpucfn.data.recordio import (  # noqa: F401
    convert_recordio,
    read_recordio,
    write_recordio,
)
from tpucfn.data.synthetic import (  # noqa: F401
    synthetic_cifar10,
    synthetic_imagenet,
    synthetic_latents,
    synthetic_tokens,
)
from tpucfn.data.packing import (  # noqa: F401
    pack_sequences,
    packed_attention_fn,
    packed_causal_lm_loss,
)
from tpucfn.data.service import (  # noqa: F401
    AdaptivePrefetcher,
    InputService,
    PrefetchController,
    ResilientBatchStream,
    ServiceBatchStream,
    input_addrs_from_env,
    service_or_local_batches,
)
