"""Fleet-wide warm start: content-addressed XLA artifact cache +
distribution plane (ISSUE 13 tentpole).

Time-to-first-step is compile-dominated (45.8 s of 64 s on the bench
row), and every ft relaunch, adopted-coordinator recovery, and serve
replica spin-up repays the same compile.  PR 6 proved the single-host
half (jax's persistent compile cache); this package is the fleet half:

* :mod:`~tpucfn.compilecache.store` — a jax-free content-addressed
  local store of serialized compiled executables, keyed by a digest
  computed *before* compiling (StableHLO hash + avals + shardings +
  mesh + device_kind + jax version), with checksummed payloads that are
  refused loudly and quarantined on corruption (the PR 7
  ckpt-quarantine lesson — never silently recompiled into a wrong-key
  slot).
* :mod:`~tpucfn.compilecache.service` — a jax-free artifact server
  (host 0, an input-role host, or the launch coordinator) speaking the
  PR 11 length-prefixed framing, with a handshake that refuses
  device_kind/jax-version mismatches and a single-flight claim
  protocol so a cold fleet compiles each program exactly once.
* :mod:`~tpucfn.compilecache.jit` — the jax glue: ``maybe_warm`` wraps
  a ``jax.jit`` callable so its first call per avals-signature goes
  lower → key → local-store / fleet-fetch / compile+publish, returning
  the AOT ``deserialize_and_load``-ed executable on a hit.  With no
  client configured (``TPUCFN_COMPILE_CACHE_ADDRS`` and
  ``TPUCFN_COMPILE_CACHE_DIR`` unset) it returns the jitted callable
  itself — byte-identical behavior, pinned by test.

The goodput ledger splits the first step's charge three ways —
``compile`` (a real XLA compile ran), ``compile_cached`` (jax's
persistent cache or the local artifact store served it), and
``compile_fetched`` (a fleet peer's artifact was fetched) — via the
extended :class:`~tpucfn.obs.profiler.CompileCacheProbe`.
"""

from tpucfn.compilecache.store import (  # noqa: F401
    ArtifactStore,
    CacheCorrupt,
    CacheMismatch,
    cache_key,
    default_store_dir,
)
from tpucfn.compilecache.service import (  # noqa: F401
    ArtifactClient,
    ArtifactServer,
    CompileCacheClient,
    cache_addrs_from_env,
    COMPILE_CACHE_ADDRS_ENV,
    COMPILE_CACHE_DIR_ENV,
)


def configure_from_env(*, tracer=None, registry=None, probe=None, env=None):
    """Build and install the process-default compile-cache client from
    the launcher's env fan-out.  Returns the client, or None when
    neither ``TPUCFN_COMPILE_CACHE_ADDRS`` nor
    ``TPUCFN_COMPILE_CACHE_DIR`` is set (the pinned byte-identical
    default) — that no-op path never touches jax.  When a cache IS
    configured, the runtime-identity probe (device_kind, versions —
    two key components and the handshake identity) imports jax HERE:
    only call this from processes that run jitted programs, never from
    the jax-free planes (input hosts, the artifact server, the
    coordinator)."""
    from tpucfn.compilecache.jit import configure_client_from_env

    return configure_client_from_env(tracer=tracer, registry=registry,
                                     probe=probe, env=env)
