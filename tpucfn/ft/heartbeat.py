"""Per-host heartbeat writing + fleet liveness classification.

The failure-detection layer of the fault-tolerance plane (ISSUE 4): every
rank appends one JSON line per interval to its own heartbeat file under a
shared directory (the same shippable-file transport the metrics and trace
JSONL already use — no new wire protocol), and a :class:`HeartbeatMonitor`
anywhere with filesystem visibility (the gang coordinator, ``tpucfn ft
status``, a ``/healthz`` probe) classifies each host:

    LIVE      fresh heartbeat, step keeping up with the fleet
    STRAGGLER fresh heartbeat, but ``straggler_step_lag`` steps behind
              the fleet max (alive ≠ making progress)
    SUSPECT   heartbeat older than ``suspect_after_s`` (or none yet,
              within the startup grace window)
    DEAD      heartbeat older than ``dead_after_s``, or still absent
              after the grace window

Heartbeat line schema (one JSON object per line, append-only)::

    {"host_id": 1, "pid": 4242, "step": 1200, "t": <time.time()>,
     "seq": 17, "role": "trainer"}

``t`` is wall-clock on purpose: writer and monitor are different
processes (often after a restart), so monotonic clocks do not compare.
Every timing input is injectable (``clock``) so the classifier is tested
against a fake clock with zero sleeps.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import re
import threading
import time
from pathlib import Path
from typing import Callable

# The heartbeat file-naming convention in its three forms — writer
# (heartbeat_path), reader regex, and directory glob (used by the
# `tpucfn obs` skew-reference ingestion).  They MUST agree; renaming
# one without the others silently degrades skew estimation to its
# span fallback.
_HB_FILE = re.compile(r"^hb-host(\d+)\.jsonl$")
HB_GLOB = "hb-host*.jsonl"

# Read at most this much of a heartbeat file's tail per observe() — the
# monitor only needs the last line, and the files grow for the whole run.
_TAIL_BYTES = 8192


def heartbeat_path(ft_dir: str | Path, host_id: int) -> Path:
    return Path(ft_dir) / f"hb-host{host_id:03d}.jsonl"


class HeartbeatWriter:
    """Appends one heartbeat line per interval for this process.

    ``beat()`` writes immediately; ``start()`` runs beats on a daemon
    thread so liveness keeps flowing while the train loop is inside a
    long step or compile (the loop only has to call
    :meth:`update_step` — cheap, lock-free attribute store — for the
    step-lag signal to stay current).
    """

    def __init__(self, ft_dir: str | Path, host_id: int, *,
                 interval_s: float = 1.0, role: str = "",
                 clock: Callable[[], float] = time.time,
                 pid: int | None = None):
        self.host_id = host_id
        self.interval_s = float(interval_s)
        self.role = role
        self.clock = clock
        self.pid = os.getpid() if pid is None else pid
        self.step: int | None = None
        self._seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        d = Path(ft_dir)
        d.mkdir(parents=True, exist_ok=True)
        self.path = heartbeat_path(d, host_id)
        # Line-buffered append: each beat is one write() of one line, so
        # a reader never sees a torn line except at a crash boundary
        # (which read_heartbeats tolerates).
        self._f = open(self.path, "a", buffering=1)

    def update_step(self, step: int) -> None:
        self.step = int(step)

    def beat(self, step: int | None = None) -> dict:
        if step is not None:
            self.update_step(step)
        with self._lock:
            if self._f is None:
                return {}
            self._seq += 1
            rec = {"host_id": self.host_id, "pid": self.pid,
                   "step": self.step, "t": self.clock(), "seq": self._seq,
                   "role": self.role}
            self._f.write(json.dumps(rec) + "\n")
            return rec

    def start(self) -> "HeartbeatWriter":
        if self._thread is None:
            self.beat()  # first beat before the interval elapses
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"tpucfn-hb:host{self.host_id}")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def read_heartbeat_file(path: str | Path) -> dict | None:
    """Last valid heartbeat record of one host file (None when the file
    is missing/empty).  Reads only the tail and skips a torn final line —
    the writer may be mid-append, or may have died mid-write."""
    p = Path(path)
    try:
        with open(p, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - _TAIL_BYTES))
            tail = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn write at the crash/read boundary
        if isinstance(rec, dict) and "t" in rec:
            return rec
    return None


def read_heartbeats(ft_dir: str | Path) -> dict[int, dict]:
    """host_id → latest record for every ``hb-host*.jsonl`` under
    ``ft_dir`` (the file name wins over the record's host_id field — a
    copied file must not impersonate another host)."""
    out: dict[int, dict] = {}
    d = Path(ft_dir)
    if not d.is_dir():
        return out
    for p in sorted(d.iterdir()):
        m = _HB_FILE.match(p.name)
        if not m:
            continue
        rec = read_heartbeat_file(p)
        if rec is not None:
            out[int(m.group(1))] = rec
    return out


class HostState(enum.Enum):
    LIVE = "LIVE"
    STRAGGLER = "STRAGGLER"
    SUSPECT = "SUSPECT"
    DEAD = "DEAD"


@dataclasses.dataclass(frozen=True)
class MonitorConfig:
    """Classification thresholds, all in seconds/steps.

    ``suspect_after_s``/``dead_after_s`` default to 3x/6x the heartbeat
    interval: one missed beat is scheduling noise, three is a problem,
    six is a verdict.  ``startup_grace_s`` covers interpreter + runtime
    start before the first beat (a freshly launched gang must not be
    declared dead while jax imports)."""

    interval_s: float = 1.0
    suspect_after_s: float | None = None
    dead_after_s: float | None = None
    straggler_step_lag: int = 100
    startup_grace_s: float | None = None

    @property
    def suspect_s(self) -> float:
        return (self.suspect_after_s if self.suspect_after_s is not None
                else 3.0 * self.interval_s)

    @property
    def dead_s(self) -> float:
        return (self.dead_after_s if self.dead_after_s is not None
                else 6.0 * self.interval_s)

    @property
    def grace_s(self) -> float:
        return (self.startup_grace_s if self.startup_grace_s is not None
                else 10.0 * self.interval_s)


@dataclasses.dataclass(frozen=True)
class HostVerdict:
    host_id: int
    state: HostState
    age_s: float | None  # None: no heartbeat seen yet
    step: int | None
    pid: int | None
    reason: str = ""


@dataclasses.dataclass(frozen=True)
class FleetView:
    t: float  # monitor clock at observation
    hosts: tuple[HostVerdict, ...]

    def by_host(self) -> dict[int, HostVerdict]:
        return {v.host_id: v for v in self.hosts}

    def counts(self) -> dict[str, int]:
        c = {s.value: 0 for s in HostState}
        for v in self.hosts:
            c[v.state.value] += 1
        return c

    def max_step(self) -> int | None:
        steps = [v.step for v in self.hosts if v.step is not None]
        return max(steps) if steps else None

    def in_state(self, *states: HostState) -> list[HostVerdict]:
        return [v for v in self.hosts if v.state in states]

    def healthy(self) -> tuple[bool, dict]:
        """The ``/healthz`` tuple: healthy while no host is DEAD (a
        STRAGGLER or a transient SUSPECT degrades detail, not status —
        the restart supervisor decides on those, probes should not flap
        a load balancer over one missed beat)."""
        counts = self.counts()
        detail = {"hosts": len(self.hosts), "fleet": counts,
                  "max_step": self.max_step()}
        return counts[HostState.DEAD.value] == 0, detail


class HeartbeatMonitor:
    """Classifies every host under one heartbeat dir (see module doc).

    ``expected_hosts`` adds absent-file detection: a host that never
    produced a heartbeat file is SUSPECT within the startup grace window
    and DEAD after it.  Without it, only hosts that have written at
    least once are judged.
    """

    def __init__(self, ft_dir: str | Path,
                 expected_hosts: int | list[int] | None = None, *,
                 config: MonitorConfig = MonitorConfig(),
                 clock: Callable[[], float] = time.time):
        self.ft_dir = Path(ft_dir)
        if isinstance(expected_hosts, int):
            expected_hosts = list(range(expected_hosts))
        self.expected_hosts = (None if expected_hosts is None
                               else sorted(expected_hosts))
        self.config = config
        self.clock = clock
        self._t0 = clock()
        # chaos-injected heartbeat delay: host → (extra_age_s, until_t)
        self._injected_delay: dict[int, tuple[float, float]] = {}
        # hosts that exited cleanly (the coordinator retires them): no
        # longer judged, or their aging last beat would flip /healthz to
        # 503 for the rest of an otherwise healthy run
        self._retired: set[int] = set()

    def retire_host(self, host_id: int) -> None:
        """Stop judging ``host_id`` — its rank finished cleanly, so its
        heartbeat going stale is retirement, not death."""
        self._retired.add(host_id)

    def set_expected_hosts(self, expected: int | list[int] | None) -> None:
        """Re-scope the judged fleet (elastic shrink, ISSUE 7): after the
        gang re-converges at N-1 the old highest id's heartbeat file
        still exists on disk, and without re-scoping its aging last beat
        would read as a phantom hang of a host the contract no longer
        has."""
        if isinstance(expected, int):
            expected = list(range(expected))
        self.expected_hosts = (None if expected is None
                               else sorted(expected))

    def activate_host(self, host_id: int) -> None:
        """Re-judge ``host_id`` (a retired slot was relaunched)."""
        self._retired.discard(host_id)

    def restart_grace(self, now: float | None = None) -> None:
        """Re-arm the startup grace window (the coordinator calls this
        right after a (re)launch: stale heartbeats from the previous
        incarnation must not instantly re-condemn the fresh gang)."""
        self._t0 = self.clock() if now is None else now

    def inject_heartbeat_delay(self, host_id: int, extra_age_s: float,
                               *, until: float | None = None,
                               duration_s: float | None = None) -> None:
        """Chaos hook (ft/chaos.py ``delay_heartbeats``): make ``host_id``'s
        heartbeats look ``extra_age_s`` older than they are until
        ``until`` (absolute monitor-clock time) or for ``duration_s``."""
        if until is None:
            until = self.clock() + (duration_s if duration_s is not None
                                    else float("inf"))
        self._injected_delay[host_id] = (float(extra_age_s), until)

    def _verdict(self, host_id: int, rec: dict | None,
                 now: float, fleet_max_step: int | None) -> HostVerdict:
        cfg = self.config
        if rec is None:
            age_from_start = now - self._t0
            if age_from_start <= cfg.grace_s:
                return HostVerdict(host_id, HostState.SUSPECT, None, None,
                                   None, "no heartbeat yet (startup grace)")
            return HostVerdict(host_id, HostState.DEAD, None, None, None,
                               f"no heartbeat after {cfg.grace_s:.1f}s grace")
        age = now - float(rec["t"])
        delay = self._injected_delay.get(host_id)
        if delay is not None:
            extra, until = delay
            if now < until:
                age += extra
            else:
                # pop, not del: observe() runs concurrently from the
                # coordinator loop AND /healthz scrape threads — two
                # callers may both see the entry expired.
                self._injected_delay.pop(host_id, None)
        step = rec.get("step")
        pid = rec.get("pid")
        if age > cfg.dead_s:
            return HostVerdict(host_id, HostState.DEAD, age, step, pid,
                               f"heartbeat {age:.1f}s old > {cfg.dead_s:.1f}s")
        if age > cfg.suspect_s:
            return HostVerdict(
                host_id, HostState.SUSPECT, age, step, pid,
                f"heartbeat {age:.1f}s old > {cfg.suspect_s:.1f}s")
        if (step is not None and fleet_max_step is not None
                and fleet_max_step - step > cfg.straggler_step_lag):
            return HostVerdict(
                host_id, HostState.STRAGGLER, age, step, pid,
                f"step {step} lags fleet max {fleet_max_step} by > "
                f"{cfg.straggler_step_lag}")
        return HostVerdict(host_id, HostState.LIVE, age, step, pid)

    def observe(self, now: float | None = None) -> FleetView:
        now = self.clock() if now is None else now
        recs = read_heartbeats(self.ft_dir)
        hosts = set(recs)
        if self.expected_hosts is not None:
            hosts |= set(self.expected_hosts)
        # copy: retire/activate run on the coordinator thread while
        # /healthz scrape threads observe concurrently
        hosts -= set(self._retired)
        steps = [r.get("step") for r in recs.values()
                 if r.get("step") is not None]
        fleet_max = max(steps) if steps else None
        verdicts = tuple(self._verdict(h, recs.get(h), now, fleet_max)
                         for h in sorted(hosts))
        return FleetView(t=now, hosts=verdicts)

    def health(self) -> tuple[bool, dict]:
        """Directly usable as ``obs.server`` ``health_fn`` — the monitor
        feeding the existing ``/healthz`` probe (ISSUE 4 tentpole)."""
        return self.observe().healthy()
