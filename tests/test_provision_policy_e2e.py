"""ISSUE 18 acceptance drill: the goodput-driven provisioner policy
loop, end to end under the real launch fan-out.

Two runs over the same shards, same seed:

* **reference** — no policy, no input plane: trainer loads locally,
  paying the synthetic decode serially with compute (the data-starved
  shape).  Also the bit-identical ground truth.
* **policy** — `tpucfn launch --provision-policy goodput`-shaped fleet:
  one input host RESERVED but deferred, the coordinator running the
  policy tick against the live goodput ledger.  The policy must observe
  the ``data_wait`` share over threshold, emit a grow decision
  (journaled + metered), drain the trainer to a step boundary, activate
  the input plane, and relaunch — after which the measured ``data_wait``
  share STRICTLY drops and the trajectory still equals the reference
  bit for bit (the drain→resume consumed every batch exactly once).
"""

import json
import sys
from pathlib import Path

import numpy as np
import pytest

from tpucfn.bootstrap import EnvContract
from tpucfn.data import write_dataset_shards
from tpucfn.ft import (
    GangCoordinator,
    GangRestart,
    HeartbeatMonitor,
    MonitorConfig,
    RestartBudget,
)
from tpucfn.launch import Launcher, LocalTransport
from tpucfn.provision import PolicyConfig, ProvisionPolicy

pytestmark = pytest.mark.slow

WORKER = Path(__file__).resolve().parent / "provision_e2e_worker.py"

BATCH = 8
SEED = 7
EXAMPLES, SHARDS = 480, 4
STEPS = EXAMPLES // BATCH  # 60


def _write_shards(tmp_path) -> Path:
    d = tmp_path / "shards"
    d.mkdir()
    rs = np.random.RandomState(2)
    write_dataset_shards(
        ({"x": rs.randn(512).astype(np.float32)} for _ in range(EXAMPLES)),
        d, num_shards=SHARDS)
    return d


def _contract(tmp_path, n) -> EnvContract:
    hostfile = tmp_path / f"hostfile{n}"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def _worker_env(run_dir: Path, shards: Path) -> dict[str, str]:
    return {
        "PROV_E2E_RUN_DIR": str(run_dir),
        "PROV_E2E_SHARDS": str(shards),
        "PROV_E2E_BATCH": str(BATCH),
        "PROV_E2E_SEED": str(SEED),
        "PROV_E2E_STEP_SLEEP": "0.03",
        "PROV_E2E_DECODE_SLEEP": "0.008",
    }


def _serve_argv(shards: Path) -> list[str]:
    return [sys.executable, "-m", "tpucfn.cli", "data", "serve",
            "--shards", str(shards), "--batch-size", str(BATCH),
            "--seed", str(SEED), "--num-epochs", "1",
            "--host", "127.0.0.1", "--idle-exit", "2.0"]


def _run(tmp_path, shards, run_dir, *, policy: bool,
         input_port: int) -> GangCoordinator:
    run_dir.mkdir(parents=True, exist_ok=True)
    n = 2 if policy else 1  # trainer (+ reserved input host)
    ft_dir = run_dir / "ft"
    launcher = Launcher(
        _contract(tmp_path, n), LocalTransport(),
        ft_dir=str(ft_dir), ft_heartbeat_s=0.2,
        input_hosts=1 if policy else 0,
        input_port=input_port,
        input_argv=_serve_argv(shards) if policy else None,
        defer_input_plane=policy,
        extra_env=_worker_env(run_dir, shards))
    monitor = HeartbeatMonitor(
        ft_dir, expected_hosts=n,
        config=MonitorConfig(interval_s=0.2, startup_grace_s=120.0))
    provision_policy = None
    if policy:
        # Small windows + short actuation model so the loop closes in
        # test time; LONG cooldown so the one grow is the only actuation
        # (no post-grow shrink oscillation inside the run).
        provision_policy = ProvisionPolicy(PolicyConfig(
            grow_threshold=0.25, shrink_threshold=0.02,
            min_window_s=0.4, cooldown_s=300.0,
            spinup_s=0.1, cold_ttfs_s=1.0, horizon_s=600.0))
    coord = GangCoordinator(
        launcher, [sys.executable, str(WORKER)],
        policy=GangRestart(RestartBudget(0)), monitor=monitor,
        ft_dir=ft_dir, poll_interval=0.02, term_grace_s=2.0,
        provision_policy=provision_policy,
        goodput_dir=run_dir / "goodput" if policy else None,
        provision_interval_s=0.4)
    assert coord.run() == 0
    return coord


def _trajectory(run_dir: Path) -> list[str]:
    p = run_dir / "losses-host000.jsonl"
    lines = [ln for ln in p.read_text().splitlines() if ln.strip()]
    assert len(lines) == STEPS, len(lines)
    return lines


def _events(run_dir: Path) -> list[dict]:
    p = run_dir / "ft" / "events.jsonl"
    return [json.loads(s) for s in p.read_text().splitlines() if s.strip()]


def _phase_records(run_dir: Path) -> list[dict]:
    recs = []
    for p in sorted((run_dir / "goodput").glob("goodput-host*.jsonl")):
        for ln in p.read_text().splitlines():
            if not ln.strip():
                continue
            r = json.loads(ln)
            if r.get("kind") == "phase":
                recs.append(r)
    return recs


def _data_wait_share(recs: list[dict]) -> float:
    tot = sum(r["dur_s"] for r in recs)
    assert tot > 0
    return sum(r["dur_s"] for r in recs
               if r["bucket"] == "data_wait") / tot


def test_provision_policy_grow_e2e(tmp_path):
    shards = _write_shards(tmp_path)

    # -- reference: no policy, local loading, the ground truth -----------
    ref_dir = tmp_path / "ref"
    _run(tmp_path, shards, ref_dir, policy=False, input_port=9370)
    ref = _trajectory(ref_dir)
    ref_share = _data_wait_share(_phase_records(ref_dir))
    assert ref_share > 0.25, ref_share  # the workload IS starved

    # -- policy: deferred input plane, goodput-driven grow ---------------
    pol_dir = tmp_path / "policy"
    coord = _run(tmp_path, shards, pol_dir, policy=True, input_port=9380)

    # the decision was journaled and metered
    events = _events(pol_dir)
    decisions = [e for e in events if e["kind"] == "provision_decision"]
    assert decisions and decisions[0]["action"] == "grow_input_hosts", \
        decisions
    assert decisions[0]["data_wait_share"] > 0.25, decisions[0]
    actuated = [e for e in events if e["kind"] == "provision_actuated"]
    assert actuated and actuated[0]["action"] == "grow_input_hosts", \
        actuated
    v = coord.registry.varz()["metrics"]
    assert v["provision_grow_total"] == 1
    assert v["provision_decisions_total"] == 1
    # a PLANNED restart: the gang-restart budget is untouched
    assert coord.policy.budget.used == 0

    # after actuation the measured data_wait share strictly drops
    t_grow = actuated[0]["ts"]
    recs = _phase_records(pol_dir)
    pre = [r for r in recs if r["t"] < t_grow]
    post = [r for r in recs if r["t"] >= t_grow]
    assert pre and post, (len(pre), len(post))
    pre_share = _data_wait_share(pre)
    post_share = _data_wait_share(post)
    assert pre_share > 0.25, pre_share
    assert post_share < pre_share, (post_share, pre_share)

    # and the trajectory is bit-identical to the no-policy reference:
    # the drain→resume consumed every batch exactly once
    assert _trajectory(pol_dir) == ref
