"""Durable coordinator run journal — the supervisor's write-ahead log.

The :class:`~tpucfn.ft.coordinator.GangCoordinator` is the component
that makes every other plane of the harness survive its failures — and
until ISSUE 12 it was itself the last single point of failure: its
restart budget, incident counter, host incarnations, and drain state
lived only in memory, so a coordinator crash orphaned a healthy fleet
and lost all failure-handling state.  This module is the durable half
of the fix:

* **Write-ahead journal** — :class:`JournalWriter` appends one
  checksummed, fsync'd record per coordinator state transition to
  ``<ft_dir>/journal/journal.jsonl`` *before* the transition's action
  runs.  Records carry a contiguous ``seq`` so replay can tell a torn
  tail (tolerated — the crash boundary) from a corrupt middle
  (refused loudly — that journal is lying).
* **Replay** — :func:`replay_journal` folds any prefix of the record
  stream into a consistent :class:`CoordinatorState`: budget used,
  incident counter, live host→pid incarnations, finished hosts, any
  restart intent that never saw its commit (the mid-flight incident a
  restarted coordinator must finish exactly once), shrinks, ckpt
  blacklist, input-host restart counts.
* **Adoption plumbing** — :class:`AdoptedProcess` wraps a re-discovered
  child pid in the ``Popen`` duck-type the coordinator and
  ``Launcher.stop_all`` already speak (a restarted coordinator is not
  the parent of the fleet it adopts, so ``waitpid`` is unavailable;
  liveness comes from ``kill(pid, 0)`` and exit codes from the rc
  files the ``--supervise`` reaper writes — see
  :mod:`tpucfn.launch.supervise`).
* **Crash points** — :func:`crash_point` is the deterministic
  crash-injection hook the crash-safety tests use: set
  ``TPUCFN_CRASH_AT=<label>`` and the process SIGKILLs itself the
  first time it passes that label (a marker file makes it once-ever
  per ft_dir, so the relaunched incarnation survives the same label).

jax-free on purpose: the coordinator, the supervise loop, and the
analyzer all import it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
import zlib
from pathlib import Path
from typing import Callable

# Canonical vocabulary of journal record kinds (the ``*_KINDS`` naming
# opts into the vocab-drift rule of ``tpucfn check``, like EVENT_KINDS).
JOURNAL_KINDS = (
    "run_start",        # fresh run: argv, hosts, policy, budget max
    "launching",        # spawn imminent for these hosts (pids unknown yet)
    "gang_launched",    # whole-gang (re)launch committed: host→pid map
    "solo_launched",    # one host relaunched: host, pid
    "host_exit",        # a supervised rank left the process table: host, rc
    "incident_open",    # detect: incident number + failure set
    "restart_intent",   # decide committed to act: action, hosts, budget_used
    "restart_commit",   # the act finished; the incident is closed
    "incident_closed",  # observe-only incident closed without an act
    "drain_armed",      # drain file written for a drain_restart intent
    "give_up",          # incident ended the run: rc
    "shrink",           # contract re-converged at N-k: lost, to_hosts
    "ckpt_retry",       # corruption retry: bad_step, blacklist
    "input_degraded",   # input host left the table (no incident)
    "input_restarted",  # input host solo-relaunched: host, restarts
    "provision_decision",  # policy verdict on a goodput window (ISSUE 18)
    "provision_shrink",    # input hosts stopped back to reserved
    "straggler_probation",  # guard fired for a host (eviction inbound)
    "chaos_fired",      # a scripted chaos event fired: index into the spec
    "adopted",          # a restarted coordinator attached to this journal
    "snapshot",         # compaction: a full CoordinatorState, journal's head
    "done",             # the run ended: rc
)

# Compaction threshold (ISSUE 15 satellite): at adoption, a journal
# longer than this folds its replayed state into one checksummed
# `snapshot` record, so week-long runs replay O(recent) instead of
# O(run lifetime).
JOURNAL_COMPACT_RECORDS = 4096

CRASH_AT_ENV = "TPUCFN_CRASH_AT"


class JournalError(RuntimeError):
    """A non-final journal record is torn, checksum-corrupt, or out of
    sequence — the journal cannot be trusted and adoption must refuse
    loudly instead of reconstructing a plausible-but-wrong state."""


def journal_path(ft_dir: str | Path) -> Path:
    return Path(ft_dir) / "journal" / "journal.jsonl"


def repair_torn_tail(path: str | Path) -> bool:
    """Truncate a torn FINAL record (the tolerated crash boundary)
    before appending to an adopted journal: ``JournalWriter`` opens in
    append mode, and writing after a partial line would glue the new
    record onto the torn bytes — one garbled line that is no longer
    final, which the NEXT replay would refuse as corruption.  Returns
    True when bytes were dropped.  A bad record that is not final
    raises :class:`JournalError`, same as replay."""
    p = Path(path)
    try:
        data = p.read_bytes()
    except OSError:
        return False
    lines = data.split(b"\n")
    offsets = []  # (start, end-incl-newline) per line
    off = 0
    for raw in lines:
        offsets.append((off, min(off + len(raw) + 1, len(data))))
        off += len(raw) + 1
    content = [i for i, raw in enumerate(lines) if raw.strip()]
    end = 0  # byte offset just past the last valid record line
    for i in content:
        if decode_record(lines[i].decode("utf-8", "replace")) is None:
            if i == content[-1]:  # torn final record: the crash boundary
                break
            raise JournalError(
                f"journal record at byte {offsets[i][0]} of {p} fails "
                "its checksum but is not the final record — refusing to "
                "repair a corrupt journal")
        end = offsets[i][1]
    if end == len(data):
        return False
    with open(p, "r+b") as f:
        f.truncate(end)
    return True


def rotate_journal(path: str | Path) -> Path | None:
    """Move an existing journal aside (``journal-prev.jsonl``) so a
    fresh run starts a fresh log — the previous run's history stays on
    disk for forensics, but can never be adopted by accident."""
    p = Path(path)
    if not p.exists():
        return None
    dst = p.with_name("journal-prev.jsonl")
    p.replace(dst)
    return dst


# -- record encoding --------------------------------------------------------
#
# One line per record: ``<crc32 hex8> <payload json>``.  The checksum
# covers the payload bytes, so a torn tail (partial final line) and a
# flipped bit both fail validation — position in the file decides
# whether that is tolerated (final record: the crash boundary) or fatal
# (anywhere else: corruption).


def encode_record(rec: dict) -> str:
    payload = json.dumps(rec, separators=(",", ":"), sort_keys=True)
    return f"{zlib.crc32(payload.encode()):08x} {payload}\n"


def decode_record(line: str) -> dict | None:
    """The record, or None when the line fails framing/checksum/json —
    the caller decides whether None is a torn tail or corruption."""
    line = line.rstrip("\n")
    if len(line) < 10 or line[8] != " ":
        return None
    crc_hex, payload = line[:8], line[9:]
    try:
        if int(crc_hex, 16) != zlib.crc32(payload.encode()):
            return None
        rec = json.loads(payload)
    except (ValueError, json.JSONDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


class JournalWriter:
    """Appends checksummed records, fsync'd before :meth:`append`
    returns — the write-ahead property: by the time the coordinator
    acts on a transition, the transition survives the coordinator."""

    def __init__(self, path: str | Path, *, start_seq: int = 0,
                 fsync: bool = True):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.seq = int(start_seq)
        self.fsync = fsync
        # An existing file can end WITHOUT a newline (a crash can
        # truncate at any byte — including exactly at the final
        # record's newline, leaving a VALID record that repair_torn_tail
        # rightly keeps).  Appending straight after it would glue the
        # next record onto that line; terminate it first.
        needs_nl = False
        try:
            with open(self.path, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                needs_nl = rf.read(1) != b"\n"
        except OSError:
            pass  # missing or empty: nothing to terminate
        self._f = open(self.path, "a")
        if needs_nl:
            self._f.write("\n")
            self._f.flush()

    def append(self, kind: str, **fields) -> dict:
        if kind not in JOURNAL_KINDS:
            raise ValueError(
                f"journal kind {kind!r} is not in JOURNAL_KINDS — add it to "
                "the canonical tuple (and replay) or fix the typo")
        if self._f is None:
            raise JournalError("journal writer is closed")
        self.seq += 1
        rec = {"seq": self.seq, "ts": time.time(), "kind": kind, **fields}
        self._f.write(encode_record(rec))
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        return rec

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- replay -----------------------------------------------------------------


@dataclasses.dataclass
class PendingIntent:
    """A journaled ``restart_intent`` whose ``restart_commit`` never
    landed: the coordinator crashed mid-act.  ``launched`` tells the
    adopter whether the relaunch half already happened (launch records
    after the intent) — redo the act when False, only write the commit
    when True; either way the restart happens exactly once."""

    incident: int
    action: str
    hosts: tuple[int, ...]
    seq: int
    planned: bool = False
    launched: bool = False
    _solo_done: set[int] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class CoordinatorState:
    """What a journal prefix reconstructs.  Every field has a safe
    zero value, so replaying an empty (or torn-to-empty) journal is a
    valid no-history state rather than an error."""

    seq: int = 0
    started: bool = False
    argv: list[str] | None = None
    max_restarts: int | None = None
    budget_used: int = 0
    incident: int = 0
    # Hosts with a ``launching`` record but no pid record yet: the
    # coordinator died inside the spawn window (ISSUE 13 satellite —
    # the PR 12 hazard).  Their processes may exist without any journal
    # trace, so adoption must give their first heartbeat a grace period
    # before relaunching over them.
    launching: set[int] = dataclasses.field(default_factory=set)
    procs: dict[int, int] = dataclasses.field(default_factory=dict)
    # Per-host kernel start time of the journaled pid (ISSUE 15
    # satellite, closing the PR 12 cross-reboot hazard): a (pid,
    # starttime) pair survives pid recycling — an adopter that finds
    # the pid alive but with a DIFFERENT start time is looking at an
    # unrelated process, and the rank must read as dead-unwatched.
    proc_starts: dict[int, int] = dataclasses.field(default_factory=dict)
    finished: dict[int, int] = dataclasses.field(default_factory=dict)
    pending: PendingIntent | None = None
    done_rc: int | None = None
    shrinks: list[list[int]] = dataclasses.field(default_factory=list)
    input_restarts: dict[int, int] = dataclasses.field(default_factory=dict)
    ckpt_blacklist: set[int] = dataclasses.field(default_factory=set)
    ckpt_retries: int = 0
    probation: set[int] = dataclasses.field(default_factory=set)
    chaos_fired: set[int] = dataclasses.field(default_factory=set)
    adoptions: int = 0

    def apply(self, rec: dict) -> None:
        seq = int(rec.get("seq", 0))
        if rec.get("kind") == "snapshot":
            # Compaction head (ISSUE 15 satellite): the folded state of
            # every record it replaced.  Only valid as the FIRST record
            # — a snapshot mid-stream means someone spliced journals.
            if self.seq != 0 or self.started:
                raise JournalError(
                    "journal snapshot record is not the first record — "
                    "refusing a spliced journal")
            self.restore(rec.get("state") or {})
            self.seq = seq
            return
        if seq != self.seq + 1:
            raise JournalError(
                f"journal sequence gap: record seq {seq} after {self.seq} — "
                "a middle record is missing or corrupt")
        self.seq = seq
        k = rec.get("kind")
        if k == "run_start":
            self.started = True
            self.argv = rec.get("argv")
            self.max_restarts = rec.get("max_restarts")
        elif k == "launching":
            self.launching.update(int(h) for h in rec.get("hosts") or ())
        elif k == "gang_launched":
            self.procs = {int(h): int(p)
                          for h, p in (rec.get("pids") or {}).items()}
            self.proc_starts = {
                int(h): int(s)
                for h, s in (rec.get("starts") or {}).items()
                if s is not None}
            self.launching.clear()
            if self.pending is not None:
                # A whole-gang launch completes ANY pending act — even a
                # solo intent: the only solo intent a gang launch follows
                # is one the elastic-shrink path upgraded to a gang
                # relaunch (the lost host left the contract), and redoing
                # it solo would double-restart fresh ranks at host_ids
                # the re-converged contract no longer has.
                self.pending.launched = True
        elif k == "solo_launched":
            self.procs[int(rec["host"])] = int(rec["pid"])
            if rec.get("start") is not None:
                self.proc_starts[int(rec["host"])] = int(rec["start"])
            else:
                self.proc_starts.pop(int(rec["host"]), None)
            self.launching.discard(int(rec["host"]))
            self.finished.pop(int(rec["host"]), None)
            if self.pending is not None \
                    and self.pending.action == "solo_restart":
                self.pending._solo_done.add(int(rec["host"]))
                if self.pending._solo_done >= set(self.pending.hosts):
                    self.pending.launched = True
        elif k == "host_exit":
            h = int(rec["host"])
            self.procs.pop(h, None)
            self.proc_starts.pop(h, None)
            self.launching.discard(h)
            self.finished[h] = int(rec.get("rc") or 0)
        elif k == "incident_open":
            self.incident = max(self.incident, int(rec.get("incident", 0)))
        elif k == "restart_intent":
            self.pending = PendingIntent(
                incident=int(rec.get("incident", self.incident)),
                action=str(rec.get("action", "")),
                hosts=tuple(int(h) for h in rec.get("hosts") or ()),
                seq=seq, planned=bool(rec.get("planned", False)))
            self.budget_used = max(self.budget_used,
                                   int(rec.get("budget_used", 0)))
        elif k in ("restart_commit", "incident_closed", "give_up"):
            self.pending = None
        elif k == "shrink":
            self.shrinks.append([int(h) for h in rec.get("lost") or ()])
        elif k == "ckpt_retry":
            self.ckpt_retries += 1
            self.ckpt_blacklist.update(
                int(s) for s in rec.get("blacklist") or ())
        elif k == "input_degraded":
            h = int(rec["host"])
            self.procs.pop(h, None)
            self.proc_starts.pop(h, None)
            self.finished.setdefault(h, 0)
        elif k == "input_restarted":
            self.input_restarts[int(rec["host"])] = int(
                rec.get("restarts", 1))
        elif k == "straggler_probation":
            self.probation.add(int(rec["host"]))
        elif k == "chaos_fired":
            self.chaos_fired.add(int(rec["index"]))
        elif k == "adopted":
            self.adoptions += 1
        elif k == "done":
            self.done_rc = int(rec.get("rc") or 0)
        # "drain_armed" mutates nothing replayable: the drain file on
        # disk is the durable artifact, and the pending intent already
        # carries the drain_restart action.

    # -- snapshot (de)serialization (ISSUE 15 compaction satellite) --------

    def to_json(self) -> dict:
        p = self.pending
        return {
            "seq": self.seq,
            "started": self.started,
            "argv": self.argv,
            "max_restarts": self.max_restarts,
            "budget_used": self.budget_used,
            "incident": self.incident,
            "launching": sorted(self.launching),
            "procs": {str(h): p_ for h, p_ in self.procs.items()},
            "proc_starts": {str(h): s for h, s in self.proc_starts.items()},
            "finished": {str(h): rc for h, rc in self.finished.items()},
            "pending": None if p is None else {
                "incident": p.incident, "action": p.action,
                "hosts": list(p.hosts), "seq": p.seq,
                "planned": p.planned, "launched": p.launched,
                "solo_done": sorted(p._solo_done)},
            "done_rc": self.done_rc,
            "shrinks": [list(s) for s in self.shrinks],
            "input_restarts": {str(h): n
                               for h, n in self.input_restarts.items()},
            "ckpt_blacklist": sorted(self.ckpt_blacklist),
            "ckpt_retries": self.ckpt_retries,
            "probation": sorted(self.probation),
            "chaos_fired": sorted(self.chaos_fired),
            "adoptions": self.adoptions,
        }

    def restore(self, state: dict) -> None:
        self.started = bool(state.get("started", False))
        self.argv = state.get("argv")
        self.max_restarts = state.get("max_restarts")
        self.budget_used = int(state.get("budget_used", 0))
        self.incident = int(state.get("incident", 0))
        self.launching = {int(h) for h in state.get("launching") or ()}
        self.procs = {int(h): int(p)
                      for h, p in (state.get("procs") or {}).items()}
        self.proc_starts = {
            int(h): int(s)
            for h, s in (state.get("proc_starts") or {}).items()}
        self.finished = {int(h): int(rc)
                         for h, rc in (state.get("finished") or {}).items()}
        p = state.get("pending")
        self.pending = None if p is None else PendingIntent(
            incident=int(p.get("incident", 0)),
            action=str(p.get("action", "")),
            hosts=tuple(int(h) for h in p.get("hosts") or ()),
            seq=int(p.get("seq", 0)),
            planned=bool(p.get("planned", False)),
            launched=bool(p.get("launched", False)),
            _solo_done={int(h) for h in p.get("solo_done") or ()})
        self.done_rc = state.get("done_rc")
        self.shrinks = [[int(h) for h in s]
                        for s in state.get("shrinks") or ()]
        self.input_restarts = {
            int(h): int(n)
            for h, n in (state.get("input_restarts") or {}).items()}
        self.ckpt_blacklist = {int(s)
                               for s in state.get("ckpt_blacklist") or ()}
        self.ckpt_retries = int(state.get("ckpt_retries", 0))
        self.probation = {int(h) for h in state.get("probation") or ()}
        self.chaos_fired = {int(i) for i in state.get("chaos_fired") or ()}
        self.adoptions = int(state.get("adoptions", 0))


def compact_journal(path: str | Path, *,
                    max_records: int = JOURNAL_COMPACT_RECORDS,
                    replayed: tuple[CoordinatorState, int] | None = None
                    ) -> bool:
    """Fold a long journal into one checksummed ``snapshot`` record so
    replay stays O(recent) on week-long runs (ISSUE 15 satellite).

    Run at adoption (after :func:`repair_torn_tail`) or at any quiet
    moment: when the record count exceeds ``max_records``, the replayed
    :class:`CoordinatorState` is written as a single ``snapshot``
    record (same seq — appends continue contiguously) via
    tmp-fsync-rename, so a crash mid-compaction leaves either the old
    or the new journal, never neither.  The pre-compaction bytes move
    to ``journal-compacted.jsonl`` for forensics (one generation kept).
    A finished (``done``) journal is rotation's business, not ours; a
    corrupt journal raises exactly like replay.  ``replayed`` is the
    caller's already-built ``(state, record_count)`` — adoption just
    replayed the whole journal, and re-parsing it here would double
    the O(N) cost exactly when the journal is at its largest.  Returns
    True when bytes were folded."""
    p = Path(path)
    if not p.exists():
        return False
    if replayed is not None:
        st, n_records = replayed
    else:
        st, records, _torn = replay_journal(p)
        n_records = len(records)
    if n_records <= max_records or not st.started \
            or st.done_rc is not None:
        return False
    rec = {"seq": st.seq, "ts": time.time(), "kind": "snapshot",
           "state": st.to_json()}
    tmp = p.with_name("journal.compact.tmp")
    with open(tmp, "w") as f:
        f.write(encode_record(rec))
        f.flush()
        os.fsync(f.fileno())
    try:
        # forensics first (best-effort copy — losing it costs history,
        # not correctness), then the atomic swap
        p.with_name("journal-compacted.jsonl").write_bytes(p.read_bytes())
    except OSError:
        pass
    tmp.replace(p)
    return True


def replay_journal(path: str | Path
                   ) -> tuple[CoordinatorState, list[dict], bool]:
    """``(state, records, torn)`` for one journal file.  A torn/corrupt
    FINAL record is dropped (``torn=True``) — that is the crash
    boundary the format is designed around.  A bad record anywhere
    else raises :class:`JournalError`: the journal is corrupt and a
    plausible partial replay would be worse than a loud refusal."""
    state = CoordinatorState()
    records: list[dict] = []
    try:
        lines = Path(path).read_text().splitlines()
    except OSError:
        return state, records, False
    torn = False
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        rec = decode_record(line)
        if rec is None:
            if i == len(lines) - 1:
                torn = True
                break
            raise JournalError(
                f"journal record at line {i + 1} of {path} fails its "
                "checksum but is not the final record — the journal is "
                "corrupt; refusing to reconstruct state from it")
        state.apply(rec)
        records.append(rec)
    return state, records, torn


# -- crash injection --------------------------------------------------------


def crash_point(label: str, marker_dir: str | Path | None = None) -> None:
    """Deterministic crash injection for crash-safety tests: when
    ``TPUCFN_CRASH_AT`` names this label, SIGKILL ourselves — but only
    once per ``marker_dir`` (the marker file is fsync'd *before* the
    kill, so the relaunched incarnation sees it and survives the same
    label).  A no-op in production (env unset)."""
    if os.environ.get(CRASH_AT_ENV, "") != label:
        return
    if marker_dir is not None:
        marker = Path(marker_dir) / f"crashed-{label}"
        if marker.exists():
            return
        marker.parent.mkdir(parents=True, exist_ok=True)
        with open(marker, "w") as f:
            f.write(str(os.getpid()))
            f.flush()
            os.fsync(f.fileno())
    os.kill(os.getpid(), signal.SIGKILL)


# -- adopted children -------------------------------------------------------


def pid_alive(pid: int) -> bool:
    """Best-effort liveness for a process we are not the parent of.
    A recycled pid can alias a dead child to alive — pair with
    :func:`pid_start_time` (the journaled identity) where a false
    positive would be adopted-and-later-killed, not merely observed."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def pid_start_time(pid: int) -> int | None:
    """The kernel start time of ``pid`` (clock ticks since boot,
    ``/proc/<pid>/stat`` field 22).  The (pid, starttime) pair is a
    process identity pid recycling cannot forge: across a machine
    reboot — or just a long downtime — the same pid number names a
    DIFFERENT process, and an adopter trusting the pid alone would
    attach to (and later SIGKILL) an unrelated victim.  ``None`` when
    unreadable (no /proc, process gone): identity checking degrades to
    the plain pid, never blocks adoption on a platform quirk."""
    try:
        data = Path(f"/proc/{pid}/stat").read_bytes()
    except OSError:
        return None
    # comm (field 2) is parenthesized and may itself contain spaces or
    # parens — parse from the LAST ')'; starttime is field 22, i.e.
    # index 19 of the post-comm tail (which starts at field 3).
    tail = data.rsplit(b")", 1)[-1].split()
    try:
        return int(tail[19])
    except (IndexError, ValueError):
        return None


def rc_dir(ft_dir: str | Path) -> Path:
    return Path(ft_dir) / "rc"


def rc_path(ft_dir: str | Path, pid: int) -> Path:
    return rc_dir(ft_dir) / f"rc-{pid}.json"


def write_rc(ft_dir: str | Path, pid: int, rc: int) -> Path:
    """The ``--supervise`` reaper's half of the adoption contract: when
    an orphaned grandchild (a rank whose coordinator died) is reaped,
    its real exit status lands here so the adopting coordinator can
    tell a clean exit from a crash (``waitpid`` is the parent's
    privilege, and the adopter is not the parent)."""
    d = rc_dir(ft_dir)
    d.mkdir(parents=True, exist_ok=True)
    p = rc_path(ft_dir, pid)
    tmp = p.with_suffix(".tmp")
    tmp.write_text(json.dumps({"pid": int(pid), "rc": int(rc),
                               "ts": time.time()}))
    tmp.replace(p)
    return p


def read_rc(ft_dir: str | Path, pid: int) -> int | None:
    try:
        rec = json.loads(rc_path(ft_dir, pid).read_text())
    except (OSError, json.JSONDecodeError):
        return None
    rc = rec.get("rc")
    return int(rc) if isinstance(rc, int) else None


def clear_rc_dir(ft_dir: str | Path) -> None:
    d = rc_dir(ft_dir)
    if not d.is_dir():
        return
    for p in d.glob("rc-*.json"):
        try:
            p.unlink()
        except OSError:
            pass


class AdoptedProcess:
    """``Popen`` duck-type over a re-discovered child pid.

    The adopting coordinator is not the parent of the fleet it adopts,
    so there is no ``waitpid``: liveness is ``kill(pid, 0)`` and the
    exit code comes from the supervise reaper's rc file.  When the
    process is gone and no rc file appears within ``rc_grace_s`` (bare
    ``--adopt`` without a supervisor, or the reaper lost the race),
    the exit degrades to the signal we sent it — or to rc 1 (an
    unexplained death is a failure, never silently clean)."""

    def __init__(self, pid: int, *, host_id: int | None = None,
                 ft_dir: str | Path | None = None, rc_grace_s: float = 2.0,
                 start_time: int | None = None,
                 clock: Callable[[], float] = time.monotonic):
        # ``start_time`` is the JOURNALED (pid, starttime) identity
        # (ISSUE 15 satellite): when given, a live pid whose current
        # start time disagrees is a RECYCLED pid — an unrelated process
        # this handle must treat as the dead rank it replaced, and must
        # never signal.
        self.pid = int(pid)
        self.host_id = host_id
        self.ft_dir = ft_dir
        self.rc_grace_s = float(rc_grace_s)
        self.start_time = start_time
        self.clock = clock
        self.returncode: int | None = None
        self._sent: int | None = None  # last signal we delivered
        self._dead_at: float | None = None

    def _alive(self) -> bool:
        if not pid_alive(self.pid):
            return False
        if self.start_time is not None:
            cur = pid_start_time(self.pid)
            if cur is not None and cur != self.start_time:
                return False  # recycled pid: an unrelated live process
        return True

    def _signal(self, sig: int) -> None:
        if self.start_time is not None and not self._alive():
            # never signal a recycled pid — the number now names an
            # innocent process that is not ours to kill
            return
        try:
            os.kill(self.pid, sig)
            self._sent = sig
        except ProcessLookupError:
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def poll(self) -> int | None:
        if self.returncode is not None:
            return self.returncode
        if self._alive():
            return None
        rc = None if self.ft_dir is None else read_rc(self.ft_dir, self.pid)
        if rc is None:
            now = self.clock()
            if self._dead_at is None:
                self._dead_at = now
            if now - self._dead_at < self.rc_grace_s \
                    and self._sent is None and self.ft_dir is not None:
                return None  # give the reaper a beat to land the rc file
            rc = -self._sent if self._sent is not None else 1
        self.returncode = rc
        return rc

    def wait(self, timeout: float | None = None) -> int:
        deadline = None if timeout is None else self.clock() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if deadline is not None and self.clock() >= deadline:
                raise TimeoutError(
                    f"adopted pid {self.pid} still alive after {timeout}s")
            time.sleep(0.02)
