"""Observability-plane wiring through the launch path (ISSUE 2): the
launcher assigns every host a TPUCFN_OBS_PORT, the restart supervisor
publishes its own metrics, and `tpucfn launch --obs-port` serves the
supervisor endpoint while the gang runs."""

import json
import socket
import sys
import urllib.request
from pathlib import Path

from tpucfn.bootstrap import EnvContract
from tpucfn.launch import Launcher, LocalTransport, run_with_restarts
from tpucfn.obs import MetricRegistry

REPO = Path(__file__).resolve().parent.parent


def _contract(tmp_path, n=3) -> EnvContract:
    hostfile = tmp_path / "hostfile"
    hostfile.write_text("".join("127.0.0.1:0\n" for _ in range(n)))
    return EnvContract(
        workers_path=str(hostfile), workers_count=n, worker_chip_count=1,
        coordinator="127.0.0.1:1234", host_id=0, storage=str(tmp_path),
        generation=1)


def test_host_env_obs_port_fanout(tmp_path):
    launcher = Launcher(_contract(tmp_path), LocalTransport(),
                        obs_base_port=9100)
    # supervisor keeps 9100; hosts get base+1+host_id
    assert launcher.host_env(0)["TPUCFN_OBS_PORT"] == "9101"
    assert launcher.host_env(2)["TPUCFN_OBS_PORT"] == "9103"
    plain = Launcher(_contract(tmp_path), LocalTransport())
    assert "TPUCFN_OBS_PORT" not in plain.host_env(0)


def test_children_receive_their_obs_port(tmp_path):
    launcher = Launcher(_contract(tmp_path, n=2), LocalTransport(),
                        obs_base_port=9200)
    marker = tmp_path / "markers"
    marker.mkdir()
    code = (f"import os,pathlib;pathlib.Path(r'{marker}').joinpath("
            "os.environ['TPUCFN_HOST_ID']).write_text("
            "os.environ['TPUCFN_OBS_PORT'])")
    procs = launcher.launch([sys.executable, "-c", code])
    assert launcher.wait(procs) == 0
    assert (marker / "0").read_text() == "9201"
    assert (marker / "1").read_text() == "9202"


def test_run_with_restarts_publishes_supervisor_metrics(tmp_path):
    """Fail once, succeed on relaunch: attempts=2, restarts=1, rc=0."""
    launcher = Launcher(_contract(tmp_path, n=1), LocalTransport())
    flag = tmp_path / "ran_once"
    code = (f"import pathlib,sys; p = pathlib.Path(r'{flag}');\n"
            "sys.exit(0) if p.exists() else (p.write_text('x'), sys.exit(3))")
    registry = MetricRegistry()
    rc = run_with_restarts(launcher, [sys.executable, "-c", code],
                           max_restarts=2, registry=registry)
    assert rc == 0
    v = registry.varz()["metrics"]
    assert v["supervisor_launch_attempts_total"] == 2
    assert v["supervisor_restarts_total"] == 1
    assert v["supervisor_gang_hosts"] == 1
    assert v["supervisor_last_exit_code"] == 0


def test_cli_launch_obs_port_serves_supervisor_and_hands_out_ports(
        tmp_path, capsys):
    """The full CLI path: `tpucfn launch --obs-port` binds the
    supervisor /metrics on the base port and each rank sees its own
    TPUCFN_OBS_PORT — every role in the job scrapeable."""
    from tpucfn.cli.main import main

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        base = probe.getsockname()[1]
    state = str(tmp_path / "state")
    assert main(["--state-dir", state, "create-stack", "--name", "obs",
                 "--accelerator", "cpu-8"]) == 0
    marker = tmp_path / "markers"
    marker.mkdir()
    # While the gang runs, scrape the supervisor endpoint from inside a
    # rank (the supervisor closes it when launch returns).
    code = (
        "import os, pathlib, urllib.request\n"
        f"body = urllib.request.urlopen('http://127.0.0.1:{base}/metrics',"
        " timeout=5).read().decode()\n"
        f"pathlib.Path(r'{marker}').joinpath(os.environ['TPUCFN_HOST_ID'])"
        ".write_text(os.environ['TPUCFN_OBS_PORT'] + '\\n' + body)\n")
    rc = main(["--state-dir", state, "launch", "--name", "obs",
               "--obs-port", str(base), "--", sys.executable, "-c", code])
    assert rc == 0
    got = (marker / "0").read_text().splitlines()
    assert got[0] == str(base + 1)
    assert any(line.startswith("supervisor_launch_attempts_total")
               for line in got[1:])
