"""Multihost launcher — the ``launch.py`` / ``mpirun`` replacement.

Reference launch path (SURVEY.md §3.2/§3.3): a tracker process ssh-fans
out per-host commands with role env vars, then MPI/ps-lite bootstrap their
own rendezvous. tpucfn keeps the one-command UX but collapses the stack:

    tpucfn launch train.py -- --flags        (CLI, any host)
      → Launcher: per-host env (contract + process_id) + Transport fan-out
        → per host: initialize_runtime() → jax.distributed.initialize
          → user main runs as ONE SPMD program over all chips

There is no scheduler process, no per-GPU ranks (one process per host
drives all local chips), and no wire protocol owned by this code —
``jax.distributed`` does rendezvous (gRPC) and XLA does the data path.

Transports: LocalTransport spawns subprocesses (single-host multi-chip,
and the N-process CPU test rig from SURVEY.md §4); SSHTransport runs the
same argv over ssh for real multi-host fleets, relying on the bootstrap
layer's key setup exactly as the reference did.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
import sys
from typing import Sequence

from tpucfn.bootstrap import EnvContract


class Transport:
    def run(self, host: str, argv: Sequence[str], env: dict[str, str]) -> subprocess.Popen:
        raise NotImplementedError


class LocalTransport(Transport):
    """Spawn on this machine (ignores ``host``)."""

    def run(self, host: str, argv: Sequence[str], env: dict[str, str]) -> subprocess.Popen:
        full_env = {**os.environ, **env}
        return subprocess.Popen(list(argv), env=full_env)

    def argv_for(self, host: str, argv: Sequence[str], env: dict[str, str]) -> list[str]:
        return list(argv)


class SSHTransport(Transport):
    """Fan out over passwordless SSH (the bootstrap layer's key contract).

    Mirrors the reference's dmlc ssh tracker / `mpirun -hostfile` hop:
    env is passed inline because ssh does not forward arbitrary vars.
    """

    def __init__(self, ssh_args: Sequence[str] = ("-o", "StrictHostKeyChecking=no")):
        self.ssh_args = tuple(ssh_args)

    def argv_for(self, host: str, argv: Sequence[str], env: dict[str, str]) -> list[str]:
        hostname = host.rsplit(":", 1)[0]
        env_prefix = " ".join(f"{k}={shlex.quote(v)}" for k, v in sorted(env.items()))
        remote_cmd = f"{env_prefix} {' '.join(shlex.quote(a) for a in argv)}"
        return ["ssh", *self.ssh_args, hostname, remote_cmd]

    def run(self, host: str, argv: Sequence[str], env: dict[str, str]) -> subprocess.Popen:
        return subprocess.Popen(self.argv_for(host, argv, env))


@dataclasses.dataclass
class Launcher:
    contract: EnvContract
    transport: Transport
    # Observability-plane port fan-out: when set, the supervisor keeps
    # ``obs_base_port`` for its own /metrics endpoint and each host's
    # process gets ``base + 1 + host_id`` via TPUCFN_OBS_PORT — every
    # role in the job becomes scrapeable at a predictable address
    # (tpucfn/obs/server.py documents the endpoint surface).
    obs_base_port: int | None = None
    # Fault-tolerance-plane fan-out (tpucfn/ft): when set, every host
    # writes heartbeats into this shared directory (TPUCFN_FT_DIR) at
    # TPUCFN_FT_HEARTBEAT_S intervals, and the gang coordinator's
    # HeartbeatMonitor reads the same dir.  Part of host_env so a solo
    # restart reuses the identical env — the replacement rank appends to
    # the same heartbeat file the dead one owned.
    ft_dir: str | None = None
    ft_heartbeat_s: float | None = None
    # Supervisor-injected vars applied to every subsequent (re)launch —
    # how the coordinator's graceful-degradation state (e.g. the ckpt
    # step blacklist on a corruption retry, ISSUE 7) reaches the ranks
    # without the contract changing.  Applied last, so it can override.
    extra_env: dict[str, str] = dataclasses.field(default_factory=dict)
    # Disaggregated input plane (ISSUE 11): the LAST ``input_hosts`` of
    # the launched slice serve batches instead of training.  They run
    # ``input_argv`` (default: the same argv — role-switching jobs read
    # TPUCFN_ROLE), bind ``input_port + host_id``, and every host gets
    # TPUCFN_INPUT_ADDRS so trainers know where the batches are.
    # Trainer ranks see TPUCFN_WORKERS_COUNT = the TRAINER count — the
    # jax.distributed rendezvous is over accelerator hosts only; input
    # hosts never join it (they never import jax at all).
    input_hosts: int = 0
    input_port: int | None = None
    input_argv: list[str] | None = dataclasses.field(default=None)
    # TPUCFN_INPUT_ADDRS advertises the hostfile addresses by default.
    # Those are only dialable when the fleet really runs on them — a
    # LocalTransport fleet runs every "host" on loopback while the fake
    # control plane hands out synthetic 10.0.0.x addresses, so trainers
    # would burn the full connect-retry window and silently degrade to
    # local loading (same failure class as --compile-cache-advertise,
    # ISSUE 13).  Set to the host trainers should dial instead.
    input_advertise_host: str | None = None
    # Provisioner policy loop (ISSUE 18): reserve the input hosts in the
    # topology but do NOT spawn or advertise them yet.  Trainers still
    # see TPUCFN_ROLE/TPUCFN_WORKERS_COUNT (the per-trainer shard split
    # must be identical before and after activation — that is what keeps
    # the trajectory bit-identical across a policy-driven grow), but
    # TPUCFN_INPUT_ADDRS stays absent so service_or_local_batches keeps
    # loading locally.  activate_input_plane() flips the switch; the
    # next (re)launch spawns the input hosts with the full served env.
    defer_input_plane: bool = False
    # Fleet warm start (ISSUE 13): every host learns where the compiled-
    # artifact servers are (TPUCFN_COMPILE_CACHE_ADDRS, same pattern as
    # TPUCFN_INPUT_ADDRS) — trainers/serve replicas consult them before
    # compiling, so host 0 compiles once and N-1 peers fetch.  A RELAUNCH
    # through launch_host / a gang restart re-derives the same env, which
    # is what makes restart MTTR stop repaying the compile.  None/empty ⇒
    # the env key is absent and behavior is byte-identical (pinned).
    compile_cache_addrs: list[str] | None = dataclasses.field(default=None)

    @property
    def trainer_count(self) -> int:
        return self.contract.workers_count - self.input_hosts

    @property
    def trainer_host_ids(self) -> list[int]:
        return list(range(self.trainer_count))

    @property
    def input_host_ids(self) -> list[int]:
        return list(range(self.trainer_count, self.contract.workers_count))

    @property
    def deferred_input_host_ids(self) -> list[int]:
        """Input hosts reserved but not yet activated (ISSUE 18)."""
        return self.input_host_ids if self.defer_input_plane else []

    def activate_input_plane(self) -> None:
        """Provisioner actuation: the next (re)launch spawns the
        reserved input hosts and fans TPUCFN_INPUT_ADDRS out to the
        trainers.  Idempotent; a no-op when nothing was deferred."""
        self.defer_input_plane = False

    def _input_base_port(self) -> int:
        if self.input_port is not None:
            return self.input_port
        from tpucfn.data.service import DEFAULT_INPUT_PORT

        return DEFAULT_INPUT_PORT

    def host_env(self, host_id: int) -> dict[str, str]:
        env = self.contract.to_env()
        env["TPUCFN_HOST_ID"] = str(host_id)
        if self.obs_base_port is not None:
            env["TPUCFN_OBS_PORT"] = str(self.obs_base_port + 1 + host_id)
        if self.ft_dir is not None:
            env["TPUCFN_FT_DIR"] = self.ft_dir
            if self.ft_heartbeat_s is not None:
                env["TPUCFN_FT_HEARTBEAT_S"] = repr(float(self.ft_heartbeat_s))
        if self.input_hosts > 0:
            if self.trainer_count < 1:
                raise ValueError(
                    f"input_hosts={self.input_hosts} leaves no trainer in "
                    f"a {self.contract.workers_count}-host slice")
            base = self._input_base_port()
            hosts = self.contract.hosts()[: self.contract.workers_count]
            env["TPUCFN_ROLE"] = ("input" if host_id in self.input_host_ids
                                  else "trainer")
            # the rendezvous (and every per-trainer shard split) is over
            # trainer ranks only
            env["TPUCFN_WORKERS_COUNT"] = str(self.trainer_count)
            if not self.defer_input_plane:
                env["TPUCFN_INPUT_ADDRS"] = ",".join(
                    f"{self.input_advertise_host or hosts[h].rsplit(':', 1)[0]}"
                    f":{base + h}"
                    for h in self.input_host_ids)
                if host_id in self.input_host_ids:
                    env["TPUCFN_INPUT_PORT"] = str(base + host_id)
        if self.compile_cache_addrs:
            from tpucfn.compilecache.service import COMPILE_CACHE_ADDRS_ENV

            env[COMPILE_CACHE_ADDRS_ENV] = ",".join(self.compile_cache_addrs)
        env.update(self.extra_env)
        return env

    def _argv_for_host(self, argv: Sequence[str], host_id: int) -> list[str]:
        if self.input_hosts > 0 and host_id in self.input_host_ids \
                and self.input_argv is not None:
            return list(self.input_argv)
        return list(argv)

    def launch(
        self,
        argv: Sequence[str],
        *,
        kill_host_after: tuple[int, float] | None = None,
    ) -> list[subprocess.Popen]:
        """Start ``argv`` on every host; returns the Popen handles (the
        local handle for LocalTransport, the ssh client handles for SSH).

        ``kill_host_after=(host_id, seconds)`` is the fault-injection hook
        (SURVEY.md §5): a timer SIGKILLs that host's process mid-run so
        recovery paths (fail-fast wait, --restarts resume) can be
        exercised deterministically in tests and drills.
        """
        # The contract's count wins over the hostfile's line count (the
        # reference's launch.py -n had the same precedence over -H).
        hosts = self.contract.hosts()[: self.contract.workers_count]
        if kill_host_after is not None and not (
            0 <= kill_host_after[0] < len(hosts)
        ):
            # Validate before spawning: an out-of-range victim must not
            # leak an already-launched gang.  (The CLI validates against
            # the full hostfile, which may be longer than workers_count.)
            raise ValueError(
                f"kill_host_after host_id {kill_host_after[0]} out of range "
                f"for {len(hosts)} launched hosts"
            )
        deferred = set(self.deferred_input_host_ids)
        procs = []
        for host_id, host in enumerate(hosts):
            if host_id in deferred:
                continue  # reserved for the provisioner; not spawned yet
            procs.append(self.transport.run(
                host, self._argv_for_host(argv, host_id),
                self.host_env(host_id)))
        if kill_host_after is not None:
            import threading

            victim, delay = kill_host_after

            def _kill(p=procs[victim]):
                if p.poll() is None:
                    p.kill()

            t = threading.Timer(delay, _kill)
            t.daemon = True
            t.start()
        return procs

    def launch_host(self, argv: Sequence[str], host_id: int) -> subprocess.Popen:
        """(Re)start ``argv`` on one host with that host's exact env —
        the solo-restart path: the replacement rank gets the same
        host_id, obs port, and heartbeat file as the rank it replaces,
        so the rest of the gang cannot tell the difference."""
        hosts = self.contract.hosts()[: self.contract.workers_count]
        if not 0 <= host_id < len(hosts):
            raise ValueError(
                f"host_id {host_id} out of range for {len(hosts)} hosts")
        return self.transport.run(hosts[host_id],
                                  self._argv_for_host(argv, host_id),
                                  self.host_env(host_id))

    def stop_all(self, procs: Sequence[subprocess.Popen], *,
                 grace_s: float = 5.0, poll_interval: float = 0.05) -> int:
        """Stop every live process: SIGTERM first, then SIGKILL whatever
        is still alive after ``grace_s`` (a rank wedged in a collective,
        or SIGSTOP'd by the chaos harness, ignores SIGTERM forever).
        All processes are reaped before returning.  Returns how many
        needed the SIGKILL escalation.

        ``procs`` is the ``poll/terminate/kill/wait`` duck-type, not
        necessarily ``Popen``: an adopting coordinator (ISSUE 12) hands
        this :class:`~tpucfn.ft.journal.AdoptedProcess` handles for
        ranks it re-attached to but did not spawn."""
        import time

        live = [p for p in procs if p.poll() is None]
        for p in live:
            p.terminate()
        deadline = time.monotonic() + grace_s
        while any(p.poll() is None for p in live):
            if time.monotonic() >= deadline:
                break
            time.sleep(poll_interval)
        escalated = 0
        for p in live:
            if p.poll() is None:
                escalated += 1
                p.kill()
        for p in live:
            p.wait()
        return escalated

    def wait(self, procs: list[subprocess.Popen], poll_interval: float = 0.05) -> int:
        """Wait for all ranks; first nonzero exit wins and the rest are
        terminated, so one dead host fails the job fast instead of hanging
        the collective (SURVEY.md §5 failure-detection row). Polls rather
        than waiting in rank order — rank 0 being alive must not mask a
        crashed rank 3."""
        import time

        rc = 0
        remaining = set(range(len(procs)))
        try:
            while remaining:
                for i in sorted(remaining):
                    r = procs[i].poll()
                    if r is None:
                        continue
                    remaining.discard(i)
                    if r != 0 and rc == 0:
                        rc = r
                        for q in procs:
                            if q.poll() is None:
                                q.terminate()
                if remaining:
                    time.sleep(poll_interval)
        finally:
            for q in procs:
                if q.poll() is None:
                    q.kill()
        return rc


def run_with_restarts(
    launcher: "Launcher",
    argv: Sequence[str],
    *,
    max_restarts: int = 0,
    backoff_s: float = 0.0,
    kill_host_after: tuple[int, float] | None = None,
    registry=None,
) -> int:
    """Supervise a job: relaunch the whole gang after a failure.

    The recovery contract from SURVEY.md §5 (failure detection row): a TPU
    slice is not elastic, so recovery is re-launch + resume-from-
    checkpoint — jobs written with tpucfn's CheckpointManager pick up at
    their latest step (the examples' ``--resume`` path). The reference's
    answer here was "the training job dies and is re-run by hand"; this
    automates the re-run.

    ``registry`` (a ``tpucfn.obs.MetricRegistry``) makes the supervisor
    itself a scrapeable role: attempts, restarts, failures, gang size,
    and the last exit code are published so a dashboard can tell
    "training is slow" apart from "training is crash-looping".

    Exit-cause accounting (ISSUE 4 satellite): only actual failures
    consume the restart budget and bump ``supervisor_failures_total`` /
    ``supervisor_restarts_total`` — a clean rc=0 gang after a prior
    failure ends the run successfully without burning a slot.
    ``supervisor_launch_attempts_total`` still counts every gang launch
    including the first (it is a launch counter, not a failure counter).
    """
    from tpucfn.ft import GangCoordinator, GangRestart, RestartBudget

    # multiplier=1/jitter=0/uncapped preserves this entry point's
    # historical constant-backoff contract (the replaced loop slept
    # exactly backoff_s); the full exponential+jitter surface is
    # GangCoordinator with an explicitly built RestartBudget.
    budget = RestartBudget(max_restarts, backoff_s=backoff_s,
                           multiplier=1.0, jitter=0.0,
                           max_backoff_s=float("inf"))
    coordinator = GangCoordinator(
        launcher, argv, policy=GangRestart(budget), registry=registry,
        kill_host_after=kill_host_after)
    return coordinator.run()


def initialize_runtime(contract: EnvContract | None = None) -> EnvContract | None:
    """Per-process entry: join the cluster rendezvous.

    Replaces both `hvd.init()`/MPI_Init and the dmlc scheduler handshake
    (SURVEY.md §3.2/§3.3) with `jax.distributed.initialize`. No-op for
    single-host jobs so the same user script runs anywhere.
    """
    if contract is None:
        try:
            contract = EnvContract.from_env()
        except EnvironmentError:
            return None  # plain single-host run, no cluster env
    if contract.workers_count > 1:
        import jax

        jax.distributed.initialize(
            coordinator_address=contract.coordinator,
            num_processes=contract.workers_count,
            process_id=contract.host_id,
        )
    # Every clustered process gets the persistent XLA compile cache — the
    # relaunch-and-resume recovery path must not pay full recompilation
    # (SURVEY.md §7.4 item 6).
    from tpucfn.obs import enable_compile_cache

    enable_compile_cache()
    return contract


def main_argv_for_script(script: str, args: Sequence[str]) -> list[str]:
    return [sys.executable, script, *args]
