"""Encoded-image handling on the host staging path.

The reference's RecordIO shards stored JPEG bytes and decoded on the
worker (MXNet DataIter's decode threads — SURVEY.md §3.2); tpurecord
does the same: :func:`tpucfn.data.convert.convert_image_tree` packs the
original encoded files, and :func:`decode_transform` turns them back
into HWC uint8 arrays inside the ShardedDataset transform chain, before
augmentation.  Decoding on the host keeps the TPU step pure MXU work;
the C++ reader + prefetch thread hide the decode latency.

Encoded images travel through tpurecord as 1-D uint8 arrays (the raw
file bytes); decoded images are HWC.  ``ndim`` is the discriminator.
"""

from __future__ import annotations

import io

import numpy as np


def _pil():
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover - PIL is in the image
        raise ImportError(
            "Pillow is required for JPEG/PNG decode; install pillow or "
            "stage pre-decoded arrays instead") from e
    return Image


def decode_image(data: bytes | np.ndarray) -> np.ndarray:
    """JPEG/PNG bytes → HWC uint8 RGB array."""
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    img = _pil().open(io.BytesIO(data)).convert("RGB")
    return np.asarray(img, dtype=np.uint8)


def encode_jpeg(arr: np.ndarray, quality: int = 90) -> bytes:
    """HWC uint8 array → JPEG bytes (used by tests and re-encoding
    converters; the image-tree converter passes original bytes through)."""
    buf = io.BytesIO()
    _pil().fromarray(np.asarray(arr, dtype=np.uint8)).save(
        buf, format="JPEG", quality=quality)
    return buf.getvalue()


class DecodeTransform:
    """Transform: decode ``ex[key]`` if it holds encoded bytes (1-D uint8);
    pass decoded (HWC) examples through untouched, so the same pipeline
    runs on encoded and pre-decoded datasets.  A class (not a closure) so
    it pickles into MultiProcessLoader workers."""

    def __init__(self, key: str = "image"):
        self.key = key

    def __call__(self, ex: dict, rs) -> dict:
        img = ex[self.key]
        if getattr(img, "ndim", None) == 1:
            ex = {**ex, self.key: decode_image(img)}
        return ex


def decode_transform(key: str = "image"):
    return DecodeTransform(key)


class CenterCropResize:
    """Eval-path geometry (the standard ImageNet recipe): resize shorter
    side to ``1.14 * out_hw`` then center-crop ``out_hw``.  Nearest-
    neighbor indexing, matching random_resized_crop's host-side-cheap
    stance.  A class so it pickles into MultiProcessLoader workers."""

    def __init__(self, out_hw: int, key: str = "image"):
        self.out_hw = out_hw
        self.key = key

    def __call__(self, ex: dict, rs) -> dict:
        img = ex[self.key]
        out_hw = self.out_hw
        h, w = img.shape[:2]
        short = int(round(out_hw * 1.14))
        if h < w:
            nh, nw = short, max(out_hw, int(round(w * short / h)))
        else:
            nh, nw = max(out_hw, int(round(h * short / w))), short
        yy = (np.arange(nh) * h / nh).astype(np.int64)
        xx = (np.arange(nw) * w / nw).astype(np.int64)
        img = img[yy][:, xx]
        y0 = (nh - out_hw) // 2
        x0 = (nw - out_hw) // 2
        return {**ex, self.key: img[y0:y0 + out_hw, x0:x0 + out_hw]}


def center_crop_resize(out_hw: int, key: str = "image"):
    return CenterCropResize(out_hw, key)
