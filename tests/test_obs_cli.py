"""`tpucfn obs` — the fleet aggregation view (ISSUE 2 tentpole): merged
step timeline, per-host straggler report, request latency breakdown,
as tables and as one JSON report."""

import json

import pytest

from tpucfn.cli.main import main
from tpucfn.obs.aggregate import (
    host_straggler_report,
    merge_step_timeline,
    read_metrics_dir,
    render_table,
    step_spans_by_host,
)


def _write_host_logs(d, host, rows):
    d.mkdir(parents=True, exist_ok=True)
    p = d / f"train-host{host:03d}.jsonl"
    with open(p, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return p


@pytest.fixture()
def fleet_run(tmp_path):
    """Two-host run where host 1 is a 2x straggler, plus a traced serve
    workload — the obs CLI's full diet."""
    logs = tmp_path / "logs"
    for host, base in ((0, 0.10), (1, 0.20)):
        _write_host_logs(logs, host, [
            {"step": s, "time": 1000.0 + s, "loss": 2.0 - s * 0.1,
             "step_time": base + s * 0.001, "data_wait_time": 0.01}
            for s in range(1, 6)])
    # serve trace via the real instrumented frontend
    from test_obs_trace import FakeEngine  # tests dir is on sys.path

    from tpucfn.obs import Tracer
    from tpucfn.serve import Server

    tracer = Tracer(tmp_path / "trace", host_id=0, role="server")
    server = Server(FakeEngine(), num_blocks=64, block_size=8, tracer=tracer)
    reqs = [server.submit([1] * n, max_new_tokens=2) for n in (3, 6)]
    server.run_until_idle()
    tracer.close()
    assert all(r.error is None for r in reqs)
    return tmp_path


# ---- pure aggregation ---------------------------------------------------

def test_merge_step_timeline_names_straggler(fleet_run):
    by_host = read_metrics_dir(fleet_run / "logs")
    timeline = merge_step_timeline(by_host, key="step_time")
    assert [r["step"] for r in timeline] == [1, 2, 3, 4, 5]
    for row in timeline:
        assert row["hosts"] == 2
        assert row["straggler"] == "train-host001"
        assert row["max"] > row["min"]
    assert merge_step_timeline(by_host, key="step_time", last=2)[0]["step"] == 4


def test_host_straggler_report_flags_slow_host(fleet_run):
    by_host = read_metrics_dir(fleet_run / "logs")
    rows = host_straggler_report(by_host,
                                 keys=("step_time", "data_wait_time"))
    by_name = {r["host"]: r for r in rows}
    slow = by_name["train-host001"]
    fast = by_name["train-host000"]
    assert slow["slow"] and not fast["slow"]
    assert slow["vs_fleet_median"] > 1.2
    assert slow["mean_data_wait_time"] == pytest.approx(0.01)


def test_step_spans_feed_the_same_views(tmp_path):
    from tpucfn.obs import Tracer, read_trace_dir

    tr = Tracer(tmp_path / "trace", host_id=4, role="trainer")
    for step in (1, 2):
        tr.record("data_wait", start=0.0, dur_s=0.02, trace_id=step)
        tr.record("step", start=0.0, dur_s=0.5, trace_id=step)
    tr.record("ckpt", start=0.0, dur_s=0.1, trace_id=2)
    tr.close()
    by_host = step_spans_by_host(read_trace_dir(tmp_path / "trace"))
    assert set(by_host) == {"host4"}
    timeline = merge_step_timeline(by_host, key="step_time")
    assert [r["step"] for r in timeline] == [1, 2]
    assert timeline[0]["median"] == pytest.approx(0.5)


def test_render_table_alignment_and_none():
    text = render_table([{"a": 1.5, "b": None, "c": True},
                         {"a": 10.25, "b": "x", "c": False}],
                        ["a", "b", "c"])
    lines = text.splitlines()
    assert lines[0].split() == ["a", "b", "c"]
    assert "1.5000" in lines[2] and "YES" in lines[2]
    assert "10.2500" in lines[3]


# ---- the CLI ------------------------------------------------------------

def test_obs_cli_tables(fleet_run, capsys):
    rc = main(["obs", "--run-dir", str(fleet_run)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "merged step timeline" in out
    assert "train-host001" in out          # straggler named
    assert "per-host stragglers" in out
    assert "request latency breakdown" in out
    assert "2/2 completed" in out


def test_obs_cli_json_report(fleet_run, capsys):
    rc = main(["obs", "--run-dir", str(fleet_run), "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["hosts"] == ["train-host000", "train-host001"]
    assert len(report["timeline"]) == 5
    assert report["request_aggregate"]["completed"] == 2
    assert {r["outcome"] for r in report["requests"]} == {"ok"}
    # every request decomposes: queue + prefill + decode present
    for r in report["requests"]:
        assert r["queue_wait_s"] is not None
        assert r["prefill_s"] is not None
        assert r["ttft_s"] == pytest.approx(
            r["queue_wait_s"] + r["prefill_s"], abs=0.005)


def test_request_breakdown_keys_by_host_and_trace_id(tmp_path):
    """Each server process numbers requests from 0 — a two-host gang's
    traces must yield one row per (host, request), not fuse them."""
    from tpucfn.obs import Tracer, read_trace_dir
    from tpucfn.obs.aggregate import request_breakdown

    for host, (lat, outcome) in ((0, (1.0, "ok")), (1, (9.0, "expired"))):
        tr = Tracer(tmp_path / "trace", host_id=host, role="server")
        tr.record("queue_wait", start=0.0, dur_s=0.1, trace_id=0)
        tr.record("prefill", start=0.1, dur_s=0.2, trace_id=0)
        tr.event("request_done", trace_id=0, outcome=outcome,
                 latency_s=lat, ttft_s=0.3, generated=4)
        tr.close()
    rows, agg = request_breakdown(read_trace_dir(tmp_path / "trace"))
    assert agg["requests"] == 2 and agg["completed"] == 1
    assert [(r["host"], r["outcome"]) for r in rows] == \
        [(0, "ok"), (1, "expired")]
    assert rows[1]["total_s"] == 9.0


def test_obs_cli_empty_run_dir(tmp_path, capsys):
    rc = main(["obs", "--run-dir", str(tmp_path)])
    assert rc == 0
    assert "no metrics or trace JSONL found" in capsys.readouterr().out


def test_obs_goodput_flags_work_on_either_side_of_subcommand(tmp_path,
                                                            capsys):
    """Subparser defaults must not clobber parent-position flags:
    `tpucfn obs --json --run-dir X goodput` and `tpucfn obs goodput
    --run-dir X --json` are the same invocation."""
    from tpucfn.obs.goodput import GoodputLedger

    led = GoodputLedger(tmp_path / "goodput", 0)
    led.account("step", 0.5, step=1)
    led.close()
    for argv in (["obs", "--json", "--run-dir", str(tmp_path), "goodput"],
                 ["obs", "goodput", "--run-dir", str(tmp_path), "--json"]):
        rc = main(argv)
        assert rc == 0, argv
        report = json.loads(capsys.readouterr().out)
        assert report["num_hosts"] == 1, argv
        assert report["buckets"]["productive_step"] == 0.5, argv
    # missing --run-dir is a clean usage error on both commands
    assert main(["obs", "goodput"]) == 2
    assert main(["obs"]) == 2
    capsys.readouterr()
    # ...but an explicit --goodput-dir stands on its own (--run-dir only
    # derives the defaults): relocated/copied ledgers need no dummy dir
    rc = main(["obs", "goodput", "--goodput-dir",
               str(tmp_path / "goodput"), "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["num_hosts"] == 1
    assert report["incidents"] == []  # no run dir -> no ft events default


def test_obs_cli_explicit_dirs(fleet_run, tmp_path, capsys):
    rc = main(["obs", "--run-dir", str(tmp_path),
               "--logs-dir", str(fleet_run / "logs"),
               "--trace-dir", str(fleet_run / "trace"), "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["timeline"] and report["requests"]
