"""Profiler hooks produce real artifacts (SURVEY.md §5 tracing row).

VERDICT r3 weak #7: the profiler was the only §5 subsystem with no test
asserting its output exists. These pin the two user-facing entry points:
``profile_steps`` (the ``--profile`` flag's engine) must leave an XPlane
trace on disk, and ``enable_compile_cache`` must point XLA's persistent
cache somewhere real.
"""

import jax
import jax.numpy as jnp


def test_profile_steps_writes_trace_artifact(tmp_path):
    from tpucfn.obs import profile_steps

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64))
    with profile_steps(tmp_path / "trace"):
        for _ in range(3):
            f(x).block_until_ready()

    files = [p for p in (tmp_path / "trace").rglob("*") if p.is_file()]
    assert files, "profile_steps produced no trace files"
    # jax's profiler writes the XPlane protobuf under plugins/profile/<ts>/
    assert any(p.suffix == ".pb" and p.stat().st_size > 0 for p in files), (
        f"no non-empty .pb trace among {[p.name for p in files]}")


def test_profile_steps_disabled_writes_nothing(tmp_path):
    from tpucfn.obs import profile_steps

    with profile_steps(tmp_path / "trace", enabled=False):
        jnp.ones(4).sum().block_until_ready()
    assert not (tmp_path / "trace").exists()


def test_enable_compile_cache_configures_jax(tmp_path, monkeypatch):
    from tpucfn.obs import enable_compile_cache

    d = str(tmp_path / "xla-cache")
    got = enable_compile_cache(d)
    assert got == d
    assert jax.config.jax_compilation_cache_dir == d
