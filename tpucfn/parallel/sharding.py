"""Regex-path → PartitionSpec sharding-rule engine.

The reference had exactly one placement decision — which hosts appear in the
hostfile (SURVEY.md §1 L3) — because every parameter lived replicated on
every GPU (PS) or all-reduced (Horovod). Here placement is per-parameter:
a rule list maps parameter tree paths (``"blocks_3/attn/qkv/kernel"``) to
:class:`jax.sharding.PartitionSpec` over the named mesh axes. This is the
single mechanism behind DP (trivial specs), FSDP, TP, and EP; the presets in
:mod:`tpucfn.parallel.presets` are just rule lists.

First matching rule wins; a catch-all ``(".*", P())`` should terminate every
rule list so unmatched params are explicitly replicated.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Iterable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpucfn.mesh import BATCH_AXES

Rule = tuple[str, P]


def _path_str(path) -> str:
    parts = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(str(k.name))
        elif isinstance(k, jax.tree_util.FlattenedIndexKey):
            parts.append(str(k.key))
        else:
            parts.append(str(k))
    return "/".join(parts)


@dataclasses.dataclass(frozen=True)
class ShardingRules:
    """An ordered rule list, applied first-match-wins to tree paths."""

    rules: tuple[Rule, ...]

    def __post_init__(self):
        for pat, spec in self.rules:
            re.compile(pat)
            if not isinstance(spec, P):
                raise TypeError(f"rule {pat!r} maps to {spec!r}, want PartitionSpec")

    def spec_for(self, path: str, ndim: int,
                 on_rank_mismatch: Any = None) -> P:
        """First matching rule's spec, rank-checked.  ``on_rank_mismatch``
        (path, spec, ndim) -> P handles leaves of lower rank than the
        matched spec — e.g. factored optimizer state (Adafactor v_row/
        v_col mirror the param path at rank n-1); default is to raise."""
        for pat, spec in self.rules:
            if re.search(pat, path):
                if len(spec) > ndim and on_rank_mismatch is not None:
                    return on_rank_mismatch(path, spec, ndim)
                return _fit_spec(spec, ndim, path)
        return P()

    def extended(self, head: Iterable[Rule]) -> "ShardingRules":
        """New rules with ``head`` prepended (higher precedence)."""
        return ShardingRules(tuple(head) + self.rules)


def _fit_spec(spec: P, ndim: int, path: str) -> P:
    """Reject over-long specs loudly instead of letting jit produce an
    inscrutable error later. Short specs are fine — NamedSharding treats
    missing trailing entries as None."""
    if len(spec) > ndim:
        raise ValueError(
            f"rule spec {spec} has {len(spec)} entries but {path!r} has rank {ndim}"
        )
    return spec


def make_partition_spec(rules: ShardingRules, tree: Any,
                        on_rank_mismatch: Any = None) -> Any:
    """Map a pytree of arrays/ShapeDtypeStructs to a pytree of PartitionSpec."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x: rules.spec_for(
            _path_str(path),
            getattr(x, "ndim", len(getattr(x, "shape", ()))),
            on_rank_mismatch),
        tree,
    )


partition_spec_tree = make_partition_spec  # alias


def named_sharding_tree(mesh: Mesh, rules: ShardingRules, tree: Any,
                        on_rank_mismatch: Any = None) -> Any:
    """PartitionSpecs bound to a concrete mesh, ready for jit in_shardings."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        make_partition_spec(rules, tree, on_rank_mismatch),
        is_leaf=lambda x: isinstance(x, P),
    )


def batch_spec(extra_axes: tuple[str | None, ...] = ()) -> P:
    """PartitionSpec for a batch: leading dim over (data, fsdp), optional
    trailing axes (e.g. ``("context",)`` for sequence-parallel inputs)."""
    return P(BATCH_AXES, *extra_axes)


def shard_batch(mesh: Mesh, batch: Any, extra_axes: tuple[str | None, ...] = ()) -> Any:
    """Place a host-local batch onto the mesh, sharded over the batch axes.

    The analogue of the reference's per-worker DataIter partitioning
    (SURVEY.md §3.2: each worker reads its own RecordIO shard), expressed
    as an explicit device placement. Each process passes only its local
    rows; ``make_array_from_process_local_data`` assembles the global
    array, so the same call works single-process (tests, one chip) and
    multi-host (each host feeds its slice of the fleet).
    """
    sharding = NamedSharding(mesh, batch_spec(extra_axes))
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), batch
    )


def shard_batch_device_layout(
    mesh: Mesh, batch: Any, extra_axes: tuple[str | None, ...] = ()
) -> Any:
    """Device-layout placement of a served host batch (ISSUE 18
    satellite): slice the batch into each device's rows with numpy
    basic indexing (views — no staging copy) and assemble the global
    array from the per-device buffers directly, skipping the
    process-local repack ``make_array_from_process_local_data``
    performs.  The input-host stream delivers rows already in draw
    order, so the contiguous row slices ARE the device layout.

    Same sharding, bit-identical values as :func:`shard_batch` (pinned
    by test_data) — only the host-side copy disappears.  Multi-process
    fleets fall back to :func:`shard_batch`: the global-assembly path
    there is what stitches cross-host rows, and the zero-copy win is a
    local-process property.
    """
    if jax.process_count() > 1:
        return shard_batch(mesh, batch, extra_axes)
    sharding = NamedSharding(mesh, batch_spec(extra_axes))

    def place(x):
        imap = sharding.addressable_devices_indices_map(x.shape)
        leaves = [jax.device_put(x[idx], d) for d, idx in imap.items()]
        return jax.make_array_from_single_device_arrays(
            x.shape, sharding, leaves)

    return jax.tree.map(place, batch)
