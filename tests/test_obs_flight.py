"""FlightRecorder (ISSUE 6 tentpole): ring overwrite semantics, dump on
signal/atexit, torn-dump tolerance on the read side, and the obs
server's /flightrecorder + POST /profile routes."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

from tpucfn.obs import (FlightRecorder, MetricRegistry, ObsServer,
                        ProfileCapture, ProfilerBusy, read_flight_dir,
                        read_flight_file)
from tpucfn.obs.flight import flight_path, incident_flight_path, \
    write_flight_dump

REPO = Path(__file__).resolve().parent.parent


# ---- ring semantics ------------------------------------------------------

def test_ring_overwrites_oldest_and_counts_drops():
    fr = FlightRecorder(capacity=3, host_id=0)
    for i in range(5):
        fr.record("step", step=i)
    snap = fr.snapshot()
    assert [s["step"] for s in snap["samples"]] == [2, 3, 4]
    assert snap["recorded"] == 5 and snap["dropped"] == 2
    assert snap["capacity"] == 3
    # seq is monotonic across overwrites: a reader can tell how much
    # history the ring ate
    assert [s["seq"] for s in snap["samples"]] == [3, 4, 5]


def test_capacity_must_be_positive():
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_record_is_thread_safe_under_contention():
    fr = FlightRecorder(capacity=128)
    n, workers = 500, 4

    def spin(k):
        for i in range(n):
            fr.record("x", k=k, i=i)

    ts = [threading.Thread(target=spin, args=(k,)) for k in range(workers)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    snap = fr.snapshot()
    assert snap["recorded"] == n * workers
    assert len(snap["samples"]) == 128
    assert snap["dropped"] == n * workers - 128


def test_sample_device_is_none_safe_on_cpu():
    # CPU backends report no memory_stats: no sample, no crash, and the
    # probe result is memoized (second call returns fast).
    fr = FlightRecorder()
    assert fr.sample_device() is None
    assert fr.sample_device() is None
    assert fr.snapshot()["samples"] == []


def test_sample_device_records_hbm_fields_from_fake_device():
    class FakeDev:
        def memory_stats(self):
            return {"bytes_in_use": 100, "peak_bytes_in_use": 200,
                    "bytes_limit": 300}

    fr = FlightRecorder()
    rec = fr.sample_device(FakeDev())
    assert rec["kind"] == "hbm"
    assert (rec["used"], rec["peak"], rec["limit"]) == (100, 200, 300)


# ---- dump + read side ----------------------------------------------------

def test_dump_writes_header_plus_samples_and_truncates(tmp_path):
    fr = FlightRecorder(capacity=8, host_id=2, role="trainer")
    for i in range(3):
        fr.record("step", step=i)
    p = fr.dump(tmp_path)  # dir form derives the per-host name
    assert p == flight_path(tmp_path, 2)
    header, samples, skipped = read_flight_file(p)
    assert header["kind"] == "flight_dump" and header["samples"] == 3
    assert header["host"] == 2 and header["role"] == "trainer"
    assert [s["step"] for s in samples] == [0, 1, 2] and skipped == 0
    # a second dump REPLACES the first (latest ring wins) — repeated
    # dumps (signal then atexit) must not fuse two rings
    fr.record("step", step=3)
    fr.dump(tmp_path)
    header2, samples2, _ = read_flight_file(p)
    assert header2["samples"] == 4 and len(samples2) == 4


def test_torn_dump_read_side_skips_and_counts(tmp_path):
    p = tmp_path / "flight-host000.jsonl"
    fr = FlightRecorder(host_id=0)
    fr.record("step", step=1)
    fr.record("step", step=2)
    fr.dump(p)
    # SIGKILL mid-write: chop the file mid-line
    raw = p.read_bytes()
    p.write_bytes(raw[: len(raw) - 7])
    header, samples, skipped = read_flight_file(p)
    assert header is not None
    assert [s["step"] for s in samples] == [1]
    assert skipped == 1
    # torn HEAD (no header line at all) still yields the samples
    lines = [json.dumps({"kind": "step", "t": 1.0, "seq": 1, "step": 9})]
    p2 = tmp_path / "flight-host001.jsonl"
    p2.write_text("\n".join(lines) + "\n")
    header2, samples2, skipped2 = read_flight_file(p2)
    assert header2 is None and len(samples2) == 1 and skipped2 == 0


def test_read_flight_dir_keys_by_host_and_skips_unparseable(tmp_path):
    for host in (0, 3):
        fr = FlightRecorder(host_id=host)
        fr.record("step", step=host)
        fr.dump(tmp_path)
    (tmp_path / "flight-hostXYZ.jsonl").write_text("{}\n")  # bad host id
    out = read_flight_dir(tmp_path)
    assert sorted(out) == [0, 3]
    assert out[3]["samples"][0]["step"] == 3
    assert read_flight_dir(tmp_path / "missing") == {}


def test_incident_capture_file_shares_the_reader(tmp_path):
    # the coordinator's HTTP capture goes through write_flight_dump with
    # the snapshot body — same header+samples layout, same reader, and
    # host_id_from_path parses the incident naming
    fr = FlightRecorder(host_id=1)
    fr.record("serve", queue=4)
    p = incident_flight_path(tmp_path, 7, 1)
    write_flight_dump(p, fr.snapshot())
    out = read_flight_dir(tmp_path, glob="incident007-host*.jsonl")
    assert list(out) == [1]
    assert out[1]["header"]["samples"] == 1


DUMP_ON_SIGTERM = """
import os, signal, sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from tpucfn.obs import FlightRecorder
fr = FlightRecorder(capacity=64, host_id=5, role="drill")
fr.install_dump_handlers({out!r})
for i in range(10):
    fr.record("step", step=i)
print("READY", flush=True)
time.sleep(60)
"""


@pytest.mark.slow
def test_dump_on_sigterm_lands_ring_on_disk(tmp_path):
    out = tmp_path / "flight"
    code = DUMP_ON_SIGTERM.format(repo=str(REPO), out=str(out))
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE, text=True)
    try:
        assert p.stdout.readline().strip() == "READY"
        p.terminate()
        rc = p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    # the handler re-raises SIGTERM's default disposition after dumping
    assert rc != 0
    header, samples, _ = read_flight_file(flight_path(out, 5))
    assert header["host"] == 5 and len(samples) == 10
    assert [s["step"] for s in samples] == list(range(10))


# ---- server routes -------------------------------------------------------

@pytest.fixture()
def srv_with_flight():
    fr = FlightRecorder(capacity=16, host_id=0, role="t")
    fr.record("step", step=1, dur_s=0.1)
    calls = []
    pc = ProfileCapture("/tmp", capture_fn=lambda d, s: calls.append(s))
    srv = ObsServer(MetricRegistry(), port=0, host="127.0.0.1",
                    flight=fr, profiler=pc)
    yield srv, fr, calls
    srv.close()


def test_flightrecorder_route_serves_the_ring(srv_with_flight):
    srv, fr, _ = srv_with_flight
    with urllib.request.urlopen(srv.url("/flightrecorder")) as r:
        assert r.status == 200
        body = json.loads(r.read())
    assert body["host"] == 0 and body["role"] == "t"
    assert body["samples"][0]["step"] == 1


def test_flightrecorder_route_404_without_recorder():
    srv = ObsServer(MetricRegistry(), port=0, host="127.0.0.1")
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url("/flightrecorder"))
        assert e.value.code == 404
    finally:
        srv.close()


def _post(url, timeout=10):
    req = urllib.request.Request(url, data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_profile_route_runs_capture_and_validates(srv_with_flight):
    srv, _, calls = srv_with_flight
    status, body = _post(srv.url("/profile?seconds=0.25"))
    assert status == 200 and calls == [0.25]
    assert "artifact" in body and body["seconds"] == 0.25
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv.url("/profile?seconds=nope"))
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(srv.url("/profile?seconds=-1"))
    assert e.value.code == 400


def test_profile_route_404_without_profiler():
    srv = ObsServer(MetricRegistry(), port=0, host="127.0.0.1")
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url("/profile?seconds=1"))
        assert e.value.code == 404
    finally:
        srv.close()


def test_profile_capture_serializes_concurrent_requests(tmp_path):
    # jax owns one global trace: the second concurrent capture must be
    # refused (ProfilerBusy -> 409 at the HTTP layer), not interleaved.
    started = threading.Event()

    def slow_capture(d, s):
        started.set()
        time.sleep(0.3)

    pc = ProfileCapture(tmp_path, capture_fn=slow_capture)
    results = {}

    def first():
        results["first"] = pc(1.0)

    t = threading.Thread(target=first)
    t.start()
    assert started.wait(5)
    with pytest.raises(ProfilerBusy):
        pc(1.0)
    t.join()
    assert "artifact" in results["first"]
    with pytest.raises(ValueError):
        pc(0.0)
    with pytest.raises(ValueError):
        pc(ProfileCapture.MAX_SECONDS + 1)


def test_obs_profile_cli_client(srv_with_flight, capsys):
    srv, _, calls = srv_with_flight
    from tpucfn.cli.main import main

    host, port = "127.0.0.1", srv.port
    assert main(["obs", "profile", "--host", f"{host}:{port}",
                 "--seconds", "0.5"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["seconds"] == 0.5 and calls == [0.5]
    # connection refused -> rc 1, not a traceback
    assert main(["obs", "profile", "--host", "127.0.0.1:1",
                 "--seconds", "0.1"]) == 1


# ---- instrumentation wiring ----------------------------------------------

def test_trainer_obs_lands_phases_in_the_ring():
    from tpucfn.train.trainer import TrainerObs

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clock()
    fr = FlightRecorder(capacity=64)
    obs = TrainerObs(MetricRegistry(), clock=clk, flight=fr)
    with obs.data_wait(1):
        clk.t += 0.05
    with obs.step(1):  # first step: compile-bucketed, still sampled
        clk.t += 1.0
    with obs.step(2):
        clk.t += 0.2
    obs.record_ckpt(2, 1.2, 0.3)
    kinds = [s["kind"] for s in fr.snapshot()["samples"]]
    assert kinds == ["data_wait", "step", "step", "ckpt"]
    steps = [s for s in fr.snapshot()["samples"] if s["kind"] == "step"]
    assert steps[0]["dur_s"] == 1.0 and steps[1]["step"] == 2


def test_serve_frontend_lands_sched_and_queue_samples():
    from test_serve_slo import FakeEngine

    from tpucfn.serve import Server

    fr = FlightRecorder(capacity=256)
    server = Server(FakeEngine(), num_blocks=64, block_size=8, flight=fr)
    reqs = [server.submit([1, 2, 3], max_new_tokens=2) for _ in range(2)]
    server.run_until_idle()
    assert all(r.error is None for r in reqs)
    samples = fr.snapshot()["samples"]
    kinds = {s["kind"] for s in samples}
    assert {"sched", "serve", "admit"} <= kinds
    scheds = [s for s in samples if s["kind"] == "sched"]
    assert any(s["work"] == "prefill" for s in scheds)
    assert any(s["work"] == "decode" for s in scheds)
    serves = [s for s in samples if s["kind"] == "serve"]
    assert all({"queue", "running", "occupancy"} <= set(s) for s in serves)


def test_snapshot_reentrant_from_a_signal_frame():
    # The SIGTERM dump handler runs ON the main thread and may
    # interrupt a record() that already holds the recorder's lock; the
    # lock is reentrant so the dump proceeds instead of self-
    # deadlocking until the coordinator's SIGKILL escalation.
    fr = FlightRecorder(capacity=8, host_id=0)
    fr.record("step", step=1)
    with fr._lock:  # simulate the signal landing inside record()
        snap = fr.snapshot()
    assert len(snap["samples"]) == 1


def test_cmd_serve_wires_flight_and_profiler(tmp_path, monkeypatch, capsys):
    # the REAL serve CLI path must expose the forensics surface: the
    # ring behind /flightrecorder (what the coordinator captures at
    # detect time) fed by the live workload, and --trace-dir arming the
    # exit dump + on-demand profiler next to the trace dir
    import tpucfn.cli.main as climain

    seen = {}

    def capture_start(*a, **kw):
        seen.update(kw)
        return None  # no port bound in the test

    # cmd_serve resolves start_obs_server from the tpucfn.obs package
    # namespace at call time (function-local import)
    monkeypatch.setattr("tpucfn.obs.start_obs_server", capture_start)
    trace_dir = tmp_path / "run" / "trace"
    assert climain.main([
        "serve", "--preset", "tiny", "--synthetic", "3",
        "--max-new", "4", "--max-batch", "2", "--cache-len", "64",
        "--num-blocks", "32", "--block-size", "8",
        "--trace-dir", str(trace_dir)]) == 0
    assert seen.get("flight") is not None
    assert seen.get("profiler") is not None
    # the workload's scheduler decisions landed in the SAME ring the
    # endpoint would have served
    kinds = {s["kind"] for s in seen["flight"].snapshot()["samples"]}
    assert {"sched", "serve", "admit"} <= kinds
    # profiler artifacts are rooted next to the trace dir, where
    # `obs postmortem` and the launch layout expect them
    assert seen["profiler"].log_dir == trace_dir.parent / "profile"
    capsys.readouterr()


@pytest.mark.slow
def test_sigterm_dump_preserves_sig_ign(tmp_path):
    # a worker configured to survive SIGTERM (inherited SIG_IGN) must
    # STILL survive it after dump handlers are installed — the dump
    # happens, the process keeps living
    code = """
import os, signal, sys, time
signal.signal(signal.SIGTERM, signal.SIG_IGN)
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from tpucfn.obs import FlightRecorder
fr = FlightRecorder(capacity=16, host_id=7)
fr.install_dump_handlers({out!r})
fr.record("step", step=1)
print("READY", flush=True)
os.kill(os.getpid(), signal.SIGTERM)
print("SURVIVED", flush=True)
""".format(repo=str(REPO), out=str(tmp_path / "flight"))
    p = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stderr
    assert "SURVIVED" in p.stdout
    header, samples, _ = read_flight_file(
        flight_path(tmp_path / "flight", 7))
    assert header["host"] == 7 and len(samples) == 1


# -- HBM watermark (ISSUE 12 satellite) -------------------------------------

def test_hbm_watermark_levels():
    from tpucfn.obs.flight import hbm_watermark

    def hbm(t, used, limit=100):
        return {"kind": "hbm", "t": t, "used": used, "peak": used,
                "limit": limit}

    # no samples / no hbm samples → no_data
    assert hbm_watermark([])["level"] == "no_data"
    assert hbm_watermark([{"kind": "step", "t": 0}])["level"] == "no_data"
    # below threshold → ok with the live ratio
    wm = hbm_watermark([hbm(0, 50), hbm(1, 60)])
    assert wm["level"] == "ok" and wm["ratio"] == 0.6
    assert wm["peak_ratio"] == 0.6 and wm["sustained_s"] == 0.0
    # over threshold but not sustained → ok (a spike is not an alert)
    wm = hbm_watermark([hbm(0, 50), hbm(10, 95)], sustain_s=30)
    assert wm["level"] == "ok" and wm["sustained_s"] == 0.0
    # sustained over threshold → alert, sustained span measured
    samples = [hbm(float(t), 95) for t in range(0, 40, 2)]
    wm = hbm_watermark(samples, sustain_s=30)
    assert wm["level"] == "alert" and wm["sustained_s"] >= 30.0
    # a dip below threshold RESETS the sustain window
    samples = [hbm(0, 95), hbm(20, 80), hbm(21, 95), hbm(40, 95)]
    wm = hbm_watermark(samples, sustain_s=30)
    assert wm["level"] == "ok" and wm["sustained_s"] == 19.0
    # `now` extends the tail (the last sample is still the live level)
    wm = hbm_watermark([hbm(0, 95)], sustain_s=30, now=45.0)
    assert wm["level"] == "alert" and wm["sustained_s"] == 45.0
    # limit<=0 or malformed samples are skipped, not crashed on
    wm = hbm_watermark([hbm(0, 95, limit=0), {"kind": "hbm", "t": 1},
                        hbm(2, 10)])
    assert wm["ratio"] == 0.1


def test_hbm_watermark_threshold_is_configurable():
    from tpucfn.obs.flight import hbm_watermark

    samples = [{"kind": "hbm", "t": float(t), "used": 80, "peak": 80,
                "limit": 100} for t in range(0, 40, 5)]
    assert hbm_watermark(samples, threshold=0.9)["level"] == "ok"
    wm = hbm_watermark(samples, threshold=0.75, sustain_s=30)
    assert wm["level"] == "alert"
