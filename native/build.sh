#!/bin/sh
# Build the native tpurecord reader. Invoked automatically by
# tpucfn/data/native.py on first use; safe to run by hand.
#
#   sh build.sh          optimized build
#   sh build.sh --tsan   ThreadSanitizer build (race-detection CI lane for
#                        the concurrent-reader contract; SURVEY.md §5)
set -e
cd "$(dirname "$0")"
if [ "$1" = "--tsan" ]; then
  g++ -O1 -g -fsanitize=thread -fPIC -shared -std=c++17 -Wall \
      -o libtpurecord_tsan.so tpurecord.cc -lz
  echo "built $(pwd)/libtpurecord_tsan.so (ThreadSanitizer)"
else
  g++ -O3 -fPIC -shared -std=c++17 -Wall -o libtpurecord.so tpurecord.cc -lz
  echo "built $(pwd)/libtpurecord.so"
fi
