"""ResNet family — CIFAR (ResNet-20/32/56) and ImageNet (ResNet-50) variants.

Capability parity: the reference's two bundled examples were MXNet
``train_cifar10.py --network resnet`` and ImageNet ResNet-50 (SURVEY.md
§2.1 "Example" rows; BASELINE.md configs 1-2). Those scripts lived on the
AMI and ran on cuDNN; this is a from-scratch flax implementation designed
for the MXU instead:

* NHWC layout (TPU-native; cuDNN preferred NCHW) so XLA lowers convs to
  MXU matmuls without transposes.
* bf16 activations / fp32 params + fp32 batch-norm statistics: the MXU's
  native mixed precision.
* Static shapes everywhere; stride-2 projection shortcuts (post-activation
  "v1.5" ResNet, the variant the 76%-top-1 target assumes).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int]
    num_classes: int
    bottleneck: bool = True
    width: int = 64
    cifar_stem: bool = False  # 3x3 stem, no maxpool (CIFAR) vs 7x7/s2 + pool
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def resnet20_cifar(cls, num_classes: int = 10) -> "ResNetConfig":
        # The reference CIFAR example's default network (SURVEY.md §3.2).
        return cls(stage_sizes=(3, 3, 3), num_classes=num_classes,
                   bottleneck=False, width=16, cifar_stem=True)

    @classmethod
    def resnet32_cifar(cls, num_classes: int = 10) -> "ResNetConfig":
        return cls(stage_sizes=(5, 5, 5), num_classes=num_classes,
                   bottleneck=False, width=16, cifar_stem=True)

    @classmethod
    def resnet50(cls, num_classes: int = 1000) -> "ResNetConfig":
        # The north-star model: 76% top-1 target (BASELINE.md).
        return cls(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                   bottleneck=True, width=64)

    @classmethod
    def resnet18(cls, num_classes: int = 1000) -> "ResNetConfig":
        return cls(stage_sizes=(2, 2, 2, 2), num_classes=num_classes,
                   bottleneck=False, width=64)


class ResNetBlock(nn.Module):
    filters: int
    strides: int
    bottleneck: bool
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype
        )
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, param_dtype=self.param_dtype,
        )
        residual = x
        if self.bottleneck:
            y = conv(self.filters, (1, 1), name="conv1")(x)
            y = nn.relu(norm(name="bn1")(y))
            y = conv(self.filters, (3, 3), strides=(self.strides,) * 2, name="conv2")(y)
            y = nn.relu(norm(name="bn2")(y))
            y = conv(self.filters * 4, (1, 1), name="conv3")(y)
            # Zero-init the last BN scale so each block starts as identity —
            # standard for the 76%-top-1 recipe.
            y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
            out_filters = self.filters * 4
        else:
            y = conv(self.filters, (3, 3), strides=(self.strides,) * 2, name="conv1")(x)
            y = nn.relu(norm(name="bn1")(y))
            y = conv(self.filters, (3, 3), name="conv2")(y)
            y = norm(name="bn2", scale_init=nn.initializers.zeros)(y)
            out_filters = self.filters
        if residual.shape[-1] != out_filters or self.strides != 1:
            residual = conv(out_filters, (1, 1), strides=(self.strides,) * 2,
                            name="conv_proj")(residual)
            residual = norm(name="bn_proj")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        cfg = self.config
        x = images.astype(cfg.dtype)
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype
        )
        if cfg.cifar_stem:
            x = conv(cfg.width, (3, 3), name="conv_stem")(x)
        else:
            x = conv(cfg.width, (7, 7), strides=(2, 2), name="conv_stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5,
                         dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="bn_stem")(x)
        x = nn.relu(x)
        if not cfg.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, num_blocks in enumerate(cfg.stage_sizes):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = ResNetBlock(
                    filters=cfg.width * (2 ** stage),
                    strides=strides,
                    bottleneck=cfg.bottleneck,
                    dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    name=f"stage{stage}_block{block}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        # Classifier head in fp32 for a stable softmax.
        x = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                     param_dtype=cfg.param_dtype, name="head")(x)
        return x
