"""Fast per-plane gray-failure tests (ISSUE 15): trickle / stall /
partition injected by a real ChaosProxy in front of a real InputService
or ArtifactServer socket — the client-side deadlines must notice within
their bound and degrade (failover → local) with the sequence unchanged.
Everything is numpy + localhost sockets, seconds per test; the slow
launch-fan-out drills live in test_net_gray_e2e.py."""

import itertools
import time

import numpy as np
import pytest

from tpucfn.compilecache.service import (
    ArtifactClient,
    ArtifactServer,
    CompileCacheClient,
)
from tpucfn.compilecache.store import ArtifactStore, cache_key
from tpucfn.data import write_dataset_shards
from tpucfn.data.pipeline import ShardedDataset
from tpucfn.data.service import (
    InputService,
    ResilientBatchStream,
    ServiceBatchStream,
    ServiceError,
)
from tpucfn.net.proxy import ChaosProxy
from tpucfn.obs.registry import MetricRegistry


def _shards(tmp_path, n=48, num_shards=6, dim=64):
    rs = np.random.RandomState(0)
    examples = [{"x": rs.randn(dim).astype(np.float32),
                 "uid": np.int32(i)} for i in range(n)]
    return write_dataset_shards(iter(examples), tmp_path,
                                num_shards=num_shards)


def _local(shards, trainer=0, pc=1, batch=4, seed=3, **kw):
    return ShardedDataset(shards, batch_size_per_process=batch, seed=seed,
                          process_index=trainer, process_count=pc, **kw)


def _assert_streams_equal(got, ref):
    assert len(got) == len(ref)
    for a, b in zip(got, ref):
        assert sorted(a) == sorted(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


@pytest.fixture
def plane(tmp_path):
    """A real InputService with a ChaosProxy in front of it."""
    shards = _shards(tmp_path)
    svc = InputService(shards, num_trainers=1, batch_size_per_process=4,
                       seed=3, host="127.0.0.1",
                       send_deadline_s=5.0).start()
    proxy = ChaosProxy(svc.address).start()
    yield shards, svc, proxy
    proxy.close()
    svc.close()


def _resilient(shards, proxy, *, registry=None, op_deadline_s=1.0):
    ds = _local(shards)
    return ResilientBatchStream(
        [proxy.address], 0,
        local_factory=lambda skip: itertools.islice(
            _local(shards).batches(1), skip, None),
        process_count=1, batch_size=4, seed=3, num_epochs=1,
        connect_retry_s=0.5, op_deadline_s=op_deadline_s,
        registry=registry), ds


def test_input_trickle_degrades_within_the_deadline(plane):
    """The headline gray failure: mid-stream the input plane starts
    TRICKLING (bytes keep flowing, so per-chunk timeouts never fire) —
    the end-to-end frame deadline must notice within its bound and the
    stream degrade to local at the exact cursor, bit-identical."""
    shards, svc, proxy = plane
    registry = MetricRegistry()
    stream, ds = _resilient(shards, proxy, registry=registry)
    ref = list(_local(shards).batches(1))
    got = [next(stream)]  # healthy first batch through the proxy
    proxy.inject("throttle", rate_bps=64.0, duration_s=120.0)
    t0 = time.monotonic()
    got.extend(stream)
    detect = time.monotonic() - t0
    assert stream.degraded
    # detection latency: the 1 s frame deadline + slack, never the
    # multi-minute per-chunk worst case this PR retires
    assert detect < 5.0, f"degradation took {detect:.1f}s"
    _assert_streams_equal(got, ref)
    v = registry.varz()["metrics"]
    assert v["net_input_deadline_exceeded_total"] >= 1


def test_input_stall_degrades_within_the_deadline(plane):
    shards, svc, proxy = plane
    stream, ds = _resilient(shards, proxy)
    ref = list(_local(shards).batches(1))
    got = [next(stream)]
    proxy.inject("stall", duration_s=120.0)
    t0 = time.monotonic()
    got.extend(stream)
    assert time.monotonic() - t0 < 5.0
    assert stream.degraded
    _assert_streams_equal(got, ref)


def test_input_partition_down_degrades_within_the_deadline(plane):
    """One-way partition: the trainer's requests reach the host, the
    host's bytes never arrive — asymmetric reachability, the half-open
    class."""
    shards, svc, proxy = plane
    stream, ds = _resilient(shards, proxy)
    ref = list(_local(shards).batches(1))
    got = [next(stream)]
    proxy.inject("partition", direction="down", duration_s=120.0)
    t0 = time.monotonic()
    got.extend(stream)
    assert time.monotonic() - t0 < 5.0
    assert stream.degraded
    _assert_streams_equal(got, ref)


def test_input_torn_frame_degrades_bit_identical(plane):
    shards, svc, proxy = plane
    stream, ds = _resilient(shards, proxy)
    ref = list(_local(shards).batches(1))
    got = [next(stream)]
    proxy.inject("tear", after_bytes=100, direction="down")
    got.extend(stream)
    assert stream.degraded
    _assert_streams_equal(got, ref)


def test_input_server_drops_stalled_trainer_and_frees_the_stream(tmp_path):
    """Satellite: the server side of the same coin — a trainer that
    connects, reads a little, then blackholes must not pin its producer
    (and queue_batches of encoded batches) for the old 5-minute window;
    the per-frame send deadline drops it and counts the stall."""
    shards = _shards(tmp_path, n=400, num_shards=4, dim=4096)
    registry = MetricRegistry()
    svc = InputService(shards, num_trainers=1, batch_size_per_process=8,
                       seed=3, host="127.0.0.1", queue_batches=2,
                       sndbuf_bytes=32 * 1024,
                       send_deadline_s=0.8, registry=registry).start()
    try:
        stream = ServiceBatchStream(svc.address, 0, process_count=1,
                                    batch_size=8, seed=3, num_epochs=1,
                                    rcvbuf_bytes=32 * 1024)
        next(stream)  # one healthy batch, then the trainer goes silent
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            v = registry.varz()["metrics"]
            if v.get("input_send_stalls_total", 0) >= 1:
                break
            time.sleep(0.05)
        v = registry.varz()["metrics"]
        assert v["input_send_stalls_total"] == 1
        # the stream is torn down like a disconnect: producer released
        deadline = time.monotonic() + 5.0
        while svc._live_streams() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not svc._live_streams()
        stream.close()
    finally:
        svc.close()


# -- compile-artifact plane -------------------------------------------------


def _publish_entry(store_dir, payload_kb=512):
    store = ArtifactStore(store_dir)
    key = cache_key({"program": "gray-drill"})
    payload = bytes(range(256)) * (payload_kb * 4)  # payload_kb KiB
    store.put(key, payload, {"key": key, "label": "gray"})
    return key, payload


def test_artifact_stall_mid_payload_times_out_within_op_deadline(tmp_path):
    """A GET whose multi-hundred-KB payload stalls mid-stream (the
    connection held open) must fail the op inside op_deadline_s — the
    per-chunk shape waited recv_timeout_s per chunk, forever."""
    key, payload = _publish_entry(tmp_path / "store")
    srv = ArtifactServer(tmp_path / "store", host="127.0.0.1").start()
    proxy = ChaosProxy(srv.address).start()
    try:
        # stall the DOWN direction mid-payload: handshake passes, the
        # artifact tears off at 64 KiB and then nothing, forever
        proxy.inject("stall", duration_s=300.0, direction="down",
                     after_bytes=64 * 1024)
        client = ArtifactClient(proxy.address, op_deadline_s=1.0)
        t0 = time.monotonic()
        with pytest.raises(ServiceError, match="deadline"):
            client.get(key)
        assert time.monotonic() - t0 < 5.0
    finally:
        proxy.close()
        srv.close()


def test_stalled_artifact_server_degrades_to_local_compile(tmp_path):
    """The acceptance shape, fast form: get_or_compile against a
    stalled artifact server compiles locally within the op deadline —
    latency cost, never a hang, and the result is the same program."""
    key, payload = _publish_entry(tmp_path / "srvstore")
    srv = ArtifactServer(tmp_path / "srvstore", host="127.0.0.1").start()
    proxy = ChaosProxy(srv.address).start()
    registry = MetricRegistry()
    compiled = []
    try:
        proxy.inject("stall", duration_s=300.0, direction="down",
                     after_bytes=16 * 1024)
        client = CompileCacheClient(
            ArtifactStore(tmp_path / "localstore"), [proxy.address],
            registry=registry, op_deadline_s=1.0, wait_s=2.0)
        t0 = time.monotonic()
        result, outcome = client.get_or_compile(
            key, lambda: compiled.append(1) or b"the-program")
        wall = time.monotonic() - t0
        assert (result, outcome) == (b"the-program", "compile")
        assert compiled == [1]
        assert wall < 10.0, f"degrade-to-compile took {wall:.1f}s"
        v = registry.varz()["metrics"]
        assert v["net_compilecache_deadline_exceeded_total"] >= 1
        assert v["compilecache_fetch_failures_total"] >= 1
    finally:
        proxy.close()
        srv.close()


def test_artifact_rst_degrades_to_local_compile_fast(tmp_path):
    key, payload = _publish_entry(tmp_path / "srvstore", payload_kb=64)
    srv = ArtifactServer(tmp_path / "srvstore", host="127.0.0.1").start()
    proxy = ChaosProxy(srv.address).start()
    try:
        proxy.inject("partition", direction="down", duration_s=300.0)
        client = CompileCacheClient(None, [proxy.address],
                                    op_deadline_s=0.5, wait_s=1.0)
        t0 = time.monotonic()
        result, outcome = client.get_or_compile(key, lambda: b"prog")
        assert (result, outcome) == (b"prog", "compile")
        assert time.monotonic() - t0 < 8.0
    finally:
        proxy.close()
        srv.close()


def test_healthy_proxy_passthrough_fetch_is_bit_identical(tmp_path):
    """Control: through a fault-free proxy the plane behaves exactly as
    without it — the fetch hits and verifies."""
    key, payload = _publish_entry(tmp_path / "srvstore", payload_kb=128)
    srv = ArtifactServer(tmp_path / "srvstore", host="127.0.0.1").start()
    proxy = ChaosProxy(srv.address).start()
    try:
        client = CompileCacheClient(None, [proxy.address], op_deadline_s=5.0)
        result, outcome = client.get_or_compile(
            key, lambda: (_ for _ in ()).throw(AssertionError("no compile")))
        assert outcome == "fetch" and result == payload
    finally:
        proxy.close()
        srv.close()


def test_send_deadline_zero_disables_the_bound(tmp_path):
    """Review fix: 0 means DISABLED (the sibling-knob convention:
    --serve-for 0, duration_s 0) — not an already-expired deadline that
    drops every stream at frame 1."""
    shards = _shards(tmp_path)
    svc = InputService(shards, num_trainers=1, batch_size_per_process=4,
                       seed=3, host="127.0.0.1",
                       send_deadline_s=0.0).start()
    try:
        stream = ServiceBatchStream(svc.address, 0, process_count=1,
                                    batch_size=4, seed=3, num_epochs=1)
        got = list(stream)
        ref = list(_local(shards).batches(1))
        _assert_streams_equal(got, ref)
    finally:
        svc.close()
