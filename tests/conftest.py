"""Test harness: 8 fake CPU devices, per SURVEY.md §4.

The reference had no test suite at all (its only "integration test" was a
CloudFormation stack reaching CREATE_COMPLETE); we test every parallelism
path on a virtual 8-device CPU mesh so multi-chip behavior is exercised in
CI without TPU hardware.

Env must be adjusted before the first JAX backend initialization. The image
ships an `axon` TPU plugin that force-registers itself via sitecustomize
when PALLAS_AXON_POOL_IPS is set, so we both scrub the env and pin
jax_platforms to cpu explicitly.
"""

import importlib.util
import os
from pathlib import Path

# One shared scrub rule (tpucfn/utils/env.py), loaded by file path so no
# package (and no jax) import happens before the environment is fixed.
_spec = importlib.util.spec_from_file_location(
    "_tpucfn_env",
    Path(__file__).resolve().parent.parent / "tpucfn" / "utils" / "env.py")
_envmod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_envmod)
_clean = _envmod.scrub_accelerator_env(os.environ, n_devices=8)
os.environ.clear()
os.environ.update(_clean)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_fake_devices():
    assert jax.devices()[0].platform == "cpu"
    assert len(jax.devices()) == 8, (
        "tests need 8 fake CPU devices; got "
        f"{len(jax.devices())} — check XLA_FLAGS handling in conftest"
    )
    yield


@pytest.fixture()
def mesh8():
    """A full 6-axis mesh over the 8 fake devices: 2 data × 2 fsdp × 2 tensor."""
    from tpucfn.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=2, fsdp=2, tensor=2))


@pytest.fixture()
def mesh_dp8():
    """Pure-DP mesh (data=8) — the reference-equivalent topology."""
    from tpucfn.mesh import MeshSpec, build_mesh

    return build_mesh(MeshSpec(data=8))
